"""Global Failure Knowledge Base — the framework's center of gravity.

Capability parity with the reference GFKB service
(reference: services/gfkb/app.py:23-198): append-only JSONL persistence with
versioning-by-append, ``F-%04d``/``FP-%04d`` id minting, top-k similarity
match, and pattern upsert with identity-by-name. Re-designed TPU-first:

  * every canonical failure's ``signature_text`` is embedded once at upsert
    time (hashed n-grams, kakveda_tpu.ops.featurizer) and lives in an
    HBM-resident [capacity, dim] matrix sharded over the mesh's ``data``
    axis — instead of the reference's read-the-whole-file + TF-IDF-refit per
    match request (reference: services/gfkb/app.py:54-56,81-89);
  * a match is one compiled matmul + sharded top-k (kakveda_tpu.ops.knn),
    batched across concurrent queries;
  * the index is fully replayable from ``failures.jsonl`` (checkpoint =
    the append log, mirroring the reference's durability-by-append design).

Deliberate deviations from the reference, both documented here:
  * id minting counts *canonical* failures, not JSONL rows — the reference
    mints ``F-{len(rows)+1}`` so version appends create id gaps
    (reference: services/gfkb/app.py:117); here ids are dense.
  * the reference applies the ``failure_type`` filter *after* truncating to
    top-5 so a type-filtered query can return fewer (or zero) matches even
    when matching failures exist (reference: services/gfkb/app.py:89-91).
    ``type_filter="post"`` (default) preserves that observable behavior;
    a device-side pre-selection mask is planned as a follow-up.
"""

from __future__ import annotations

import json
import os
import threading
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh

from kakveda_tpu import native
from kakveda_tpu.core import profiling
from kakveda_tpu.core.schemas import (
    CanonicalFailureRecord,
    FailureMatch,
    PatternEntity,
    Severity,
    utcnow,
)
from kakveda_tpu.ops.featurizer import HashedNGramFeaturizer
from kakveda_tpu.ops.knn import ShardedKnn, batch_bucket
from kakveda_tpu.parallel.mesh import create_mesh


class SnapshotError(RuntimeError):
    """Snapshot unavailable or aborted (persist=False, concurrent reload) —
    a caller-side condition, distinct from device/runtime failures."""


def _record_from_snapshot(obj: dict) -> dict:
    """Snapshot rows are our own model_dump_json output: re-hydrate the two
    non-JSON-native field types for model_construct (which skips the
    validators that would otherwise do this)."""
    from datetime import datetime

    obj["created_at"] = datetime.fromisoformat(obj["created_at"])
    obj["updated_at"] = datetime.fromisoformat(obj["updated_at"])
    obj["impact_severity"] = Severity(obj["impact_severity"])
    return obj


class GFKB:
    """Failure + pattern store with a device-resident similarity index."""

    def __init__(
        self,
        data_dir: str | Path = "data",
        mesh: Optional[Mesh] = None,
        capacity: int = 1 << 14,
        dim: int = 2048,
        top_k: int = 5,
        featurizer: Optional[HashedNGramFeaturizer] = None,
        persist: bool = True,
    ):
        self.data_dir = Path(data_dir)
        self.persist = persist
        if persist:
            self.data_dir.mkdir(parents=True, exist_ok=True)
        self.failures_path = self.data_dir / "failures.jsonl"
        self.patterns_path = self.data_dir / "patterns.jsonl"

        self.mesh = mesh if mesh is not None else create_mesh("data:-1")
        self.featurizer = featurizer or HashedNGramFeaturizer(dim=dim)
        self.top_k = top_k
        self._knn = ShardedKnn(self.mesh, capacity, dim, k=top_k)
        self._emb, self._valid = self._knn.alloc()

        # Host-side metadata: one entry per canonical failure, slot-aligned.
        self._records: List[CanonicalFailureRecord] = []
        self._slot_by_key: Dict[Tuple[str, str], int] = {}
        self._patterns: Dict[str, PatternEntity] = {}  # name -> latest
        self._snapshot_write_lock = threading.Lock()
        # Bumped by reload(); snapshot() aborts if it changed mid-write so a
        # purge (external log rewrite + reload) can't race a snapshot into
        # resurrecting pre-purge records.
        self._generation = 0
        # Per-type aggregates maintained incrementally at upsert so pattern
        # detection reads them O(1) instead of rescanning every record per
        # batch (O(N²) over a failure stream).
        self._ids_by_type: Dict[str, List[str]] = {}
        self._apps_by_type: Dict[str, set] = {}
        self._lock = threading.Lock()
        # Group-commit append logs (C++ writer when available): records are
        # buffered and flushed after each upsert batch instead of paying an
        # open+write+close per record (the reference's pattern,
        # services/gfkb/app.py:49-51).
        self._logs: Dict[Path, "native.AppendLog"] = {}

        if persist:
            self._replay()

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------

    def _append_jsonl(self, path: Path, obj: dict) -> None:
        """Buffer one record; callers group-commit with :meth:`_flush_logs`
        at the end of each public mutation (read-your-writes for external
        readers of the JSONL files, one syscall per batch instead of an
        open+write+close per record)."""
        self._append_line(path, json.dumps(obj, ensure_ascii=False))

    def _append_line(self, path: Path, line: str) -> None:
        """Raw pre-serialized variant: the streaming path serializes with
        pydantic's C serializer (model_dump_json) and skips the Python json
        encoder entirely."""
        if not self.persist:
            return
        log = self._logs.get(path)
        if log is None:
            log = self._logs[path] = native.AppendLog(path)
        log.append((line + "\n").encode("utf-8"))

    def _flush_logs(self) -> None:
        for log in self._logs.values():
            log.flush()

    def close(self) -> None:
        """Flush and close the append logs (safe to call repeatedly)."""
        for log in self._logs.values():
            log.close()
        self._logs.clear()

    def _replay(self) -> None:
        """Rebuild host metadata + device index from the append logs,
        fast-forwarding through a snapshot when one is valid (startup at
        1M rows is dominated by re-embedding + re-parsing otherwise)."""
        if self.failures_path.exists():
            tail_offset = self._restore_snapshot()
            latest: Dict[Tuple[str, str], CanonicalFailureRecord] = {}
            order: List[Tuple[str, str]] = []
            with self.failures_path.open("r", encoding="utf-8") as f:
                if tail_offset:
                    f.seek(tail_offset)
                for line in f:
                    if not line.strip():
                        continue
                    rec = CanonicalFailureRecord.model_validate(json.loads(line))
                    key = (rec.failure_type, rec.signature_text)
                    if key in self._slot_by_key:  # snapshot row updated in tail
                        self._records[self._slot_by_key[key]] = rec
                        self._apps_by_type.setdefault(rec.failure_type, set()).update(
                            rec.affected_apps
                        )
                        continue
                    if key not in latest:
                        order.append(key)
                    latest[key] = rec
            if order:
                base = len(self._records)
                self._records.extend(latest[k] for k in order)
                for i, k in enumerate(order):
                    self._slot_by_key[k] = base + i
                for k in order:
                    rec = latest[k]
                    self._ids_by_type.setdefault(rec.failure_type, []).append(rec.failure_id)
                    self._apps_by_type.setdefault(rec.failure_type, set()).update(
                        rec.affected_apps
                    )
                vecs = self.featurizer.encode_batch([latest[k].signature_text for k in order])
                self._ensure_capacity(len(self._records))
                slots = np.arange(base, base + len(order), dtype=np.int32)
                self._emb, self._valid = self._knn.insert(self._emb, self._valid, vecs, slots)

        if self.patterns_path.exists():
            for line in self.patterns_path.read_text(encoding="utf-8").splitlines():
                if not line.strip():
                    continue
                p = PatternEntity.model_validate(json.loads(line))
                self._patterns[p.name] = p

    # --- snapshot / restore --------------------------------------------

    _SNAPSHOT_VERSION = 1
    _TAIL_HASH_BYTES = 4096

    def _snapshot_dir(self) -> Path:
        return self.data_dir / "snapshot"

    def _log_prefix_hash(self, offset: int) -> str:
        """sha256 of the last ≤4KB of failures.jsonl before ``offset`` —
        cheap integrity check that the log the snapshot covered is still
        the same log (purge-demo rewrites it, for instance)."""
        import hashlib

        start = max(0, offset - self._TAIL_HASH_BYTES)
        with self.failures_path.open("rb") as f:
            f.seek(start)
            return hashlib.sha256(f.read(offset - start)).hexdigest()

    def snapshot(self) -> Path:
        """Write an atomic point-in-time snapshot: slot-ordered embedding
        rows (no re-embed on restore) + pre-serialized records (no pydantic
        re-validate) + a manifest pinning the covered failures.jsonl byte
        range. Restore replays only the log tail written after it."""
        import shutil
        import tempfile

        # Capture a consistent view under the data lock: records list copy
        # (records are replaced, never mutated) + a device-side HBM copy of
        # the embedding buffer (fast). The slow parts — the multi-GB host
        # transfer and the disk write — run WITHOUT the data lock so a live
        # service's warn/ingest path doesn't stall. A separate snapshot lock
        # serializes concurrent snapshot() calls (endpoint + shutdown).
        if not self.persist:
            raise SnapshotError("snapshot requires a persistent GFKB (persist=True)")
        with self._snapshot_write_lock:
            with self._lock:
                self._flush_logs()
                records = list(self._records)
                n = len(records)
                offset = self.failures_path.stat().st_size if self.failures_path.exists() else 0
                # Capture the knn alongside the buffer: a concurrent growth
                # re-shard swaps self._knn and would decode emb_copy's
                # layout with the wrong rows_per_shard.
                knn = self._knn
                emb_copy = knn.device_copy(self._emb)
                log_hash = self._log_prefix_hash(offset) if offset else ""
                generation = self._generation

            vecs = knn.gather_slots(emb_copy, np.arange(n, dtype=np.int32))
            del emb_copy
            sd = self._snapshot_dir()
            tmp = Path(tempfile.mkdtemp(dir=self.data_dir, prefix=".snapshot-"))
            old = self.data_dir / f".snapshot-old-{os.getpid()}-{id(tmp)}"
            try:
                np.save(tmp / "vectors.npy", vecs)
                with (tmp / "records.jsonl").open("w", encoding="utf-8") as f:
                    f.writelines(r.model_dump_json() + "\n" for r in records)
                (tmp / "manifest.json").write_text(
                    json.dumps(
                        {
                            "version": self._SNAPSHOT_VERSION,
                            "n": n,
                            "dim": knn.dim,
                            "log_offset": offset,
                            "log_hash": log_hash,
                        }
                    )
                )
                # Swap via renames under the data lock: serialized with
                # reload(), and a crash mid-swap leaves at worst no snapshot
                # (full replay fallback), never a half-written one.
                with self._lock:
                    if self._generation != generation:
                        raise SnapshotError(
                            "GFKB was reloaded during snapshot; snapshot aborted — retry"
                        )
                    if sd.exists():
                        sd.rename(old)
                    tmp.rename(sd)
                shutil.rmtree(old, ignore_errors=True)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                if old.exists() and not sd.exists():
                    old.rename(sd)  # restore the previous snapshot
                raise
            return sd

    def _restore_snapshot(self) -> int:
        """Load a valid snapshot; returns the failures.jsonl byte offset to
        replay from (0 = no usable snapshot, full replay)."""
        sd = self._snapshot_dir()
        manifest_path = sd / "manifest.json"
        if not manifest_path.exists():
            return 0
        try:
            manifest = json.loads(manifest_path.read_text())
            if manifest.get("version") != self._SNAPSHOT_VERSION:
                return 0
            if manifest.get("dim") != self._knn.dim:
                return 0
            offset = int(manifest.get("log_offset", 0))
            size = self.failures_path.stat().st_size if self.failures_path.exists() else 0
            if size < offset:
                return 0  # log truncated/rewritten since the snapshot
            if offset and self._log_prefix_hash(offset) != manifest.get("log_hash"):
                return 0  # log rewritten in place (e.g. purge) — full replay
            n = int(manifest["n"])
            records = []
            with (sd / "records.jsonl").open("r", encoding="utf-8") as f:
                for line in f:
                    if line.strip():
                        # our own snapshot — construct without re-validation
                        records.append(
                            CanonicalFailureRecord.model_construct(
                                **_record_from_snapshot(json.loads(line))
                            )
                        )
            if len(records) != n:
                return 0
            vecs = np.load(sd / "vectors.npy")
            if vecs.shape != (n, self._knn.dim):
                return 0
        except Exception:  # noqa: BLE001 — any corruption ⇒ full replay
            return 0
        # Grow the index BEFORE installing the records: _ensure_capacity
        # re-embeds from self._records on growth, which would re-do exactly
        # the work the snapshot vectors exist to skip.
        self._ensure_capacity(n)
        self._records = records
        self._slot_by_key = {
            (r.failure_type, r.signature_text): i for i, r in enumerate(records)
        }
        for r in records:
            self._ids_by_type.setdefault(r.failure_type, []).append(r.failure_id)
            self._apps_by_type.setdefault(r.failure_type, set()).update(r.affected_apps)
        if n:
            self._emb, self._valid = self._knn.insert(
                self._emb, self._valid, vecs, np.arange(n, dtype=np.int32)
            )
        return offset

    def reload(self) -> None:
        """Drop all in-memory/device state and replay the append logs.

        Required after any external rewrite of the JSONL files (e.g. the
        dashboard's purge-demo flow) so the device index, id minting and
        host metadata stay consistent with the log. Any existing snapshot
        describes the pre-rewrite state and is deleted; an in-flight
        snapshot sees the generation bump at its swap step and aborts
        (reload deliberately does NOT take the snapshot-write lock — a
        purge must not stall behind a multi-GB snapshot disk write).
        """
        import shutil

        with self._lock:
            self._generation += 1
            shutil.rmtree(self._snapshot_dir(), ignore_errors=True)
            # Reopen the append logs: an external rewrite may have replaced
            # the files (new inode), and a held fd would append to the old one.
            self.close()
            self._emb, self._valid = self._knn.alloc()
            self._records = []
            self._slot_by_key = {}
            self._patterns = {}
            self._ids_by_type = {}
            self._apps_by_type = {}
            if self.persist:
                self._replay()

    # ------------------------------------------------------------------
    # failures
    # ------------------------------------------------------------------

    @property
    def count(self) -> int:
        return len(self._records)

    def list_failures(self) -> List[CanonicalFailureRecord]:
        with self._lock:
            return list(self._records)

    def records_and_embeddings(self) -> Tuple[List[CanonicalFailureRecord], np.ndarray]:
        """Consistent (records, slot-aligned embedding rows) pair — captured
        atomically so a concurrent reload() (purge) can't misalign row i
        with records[i]. The slow host transfer happens after the lock via a
        device-side buffer copy."""
        with self._lock:
            records = list(self._records)
            knn = self._knn  # growth re-shard swaps the knn; pair it with the buffer
            emb_copy = knn.device_copy(self._emb)
        vecs = knn.gather_slots(emb_copy, np.arange(len(records), dtype=np.int32))
        return records, vecs

    def type_aggregate(self, failure_type: str) -> Tuple[List[str], List[str]]:
        """(failure_ids in insertion order, sorted affected apps) for a type
        — maintained incrementally so per-batch pattern detection never
        rescans the record list."""
        with self._lock:
            return (
                list(self._ids_by_type.get(failure_type, [])),
                sorted(self._apps_by_type.get(failure_type, set())),
            )

    def _ensure_capacity(self, needed: int) -> None:
        if needed <= self._knn.capacity:
            return
        new_cap = self._knn.capacity
        while new_cap < needed:
            new_cap *= 2
        # Growth is an explicit re-shard event: allocate a doubled index and
        # re-embed from host metadata (rare; amortized O(1) per insert).
        knn = ShardedKnn(self.mesh, new_cap, self._knn.dim, k=self.top_k)
        emb, valid = knn.alloc()
        if self._records:
            vecs = self.featurizer.encode_batch([r.signature_text for r in self._records])
            slots = np.arange(len(self._records), dtype=np.int32)
            emb, valid = knn.insert(emb, valid, vecs, slots)
        self._knn, self._emb, self._valid = knn, emb, valid

    def upsert_failure(
        self,
        *,
        failure_type: str,
        signature_text: str,
        app_id: str,
        impact_severity: Severity,
        context_signature: Optional[dict] = None,
        root_cause: Optional[str] = None,
        resolution: Optional[str] = None,
    ) -> Tuple[CanonicalFailureRecord, bool]:
        """Versioned upsert; returns (record, created).

        Identity is (failure_type, signature_text) — same as the reference's
        reverse scan (reference: services/gfkb/app.py:108-113). Updates bump
        version/occurrences, merge affected apps, and let root cause /
        resolution evolve; every write re-appends to the JSONL log.
        """
        with self._lock:
            key = (failure_type, signature_text)
            slot = self._slot_by_key.get(key)
            now = utcnow()
            if slot is None:
                created = True
                rec = CanonicalFailureRecord(
                    failure_id=f"F-{len(self._records) + 1:04d}",
                    version=1,
                    created_at=now,
                    updated_at=now,
                    failure_type=failure_type,
                    root_cause=root_cause,
                    context_signature=context_signature or {},
                    impact_severity=impact_severity,
                    resolution=resolution,
                    occurrences=1,
                    affected_apps=[app_id],
                    signature_text=signature_text,
                )
                slot = len(self._records)
                self._ensure_capacity(slot + 1)
                self._records.append(rec)
                self._slot_by_key[key] = slot
                self._ids_by_type.setdefault(failure_type, []).append(rec.failure_id)
                self._apps_by_type.setdefault(failure_type, set()).add(app_id)
                vec = self.featurizer.encode_batch([signature_text])
                self._emb, self._valid = self._knn.insert(
                    self._emb, self._valid, vec, np.asarray([slot], dtype=np.int32)
                )
            else:
                created = False
                old = self._records[slot]
                rec = old.model_copy(deep=True)
                rec.version += 1
                rec.updated_at = now
                rec.occurrences += 1
                if app_id not in rec.affected_apps:
                    rec.affected_apps.append(app_id)
                self._apps_by_type.setdefault(failure_type, set()).add(app_id)
                rec.root_cause = root_cause or rec.root_cause
                rec.resolution = resolution or rec.resolution
                rec.context_signature = context_signature or rec.context_signature
                self._records[slot] = rec
                # Same signature text => identical embedding; no device write.
            self._append_jsonl(self.failures_path, rec.model_dump(mode="json"))
            self._flush_logs()
            return rec, created

    def upsert_failures_batch(self, items: Sequence[dict]) -> List[Tuple[CanonicalFailureRecord, bool]]:
        """Batched upsert for the streaming-ingest path.

        New signatures are embedded in one ``encode_batch`` and written to the
        device in one scatter — the 10k traces/sec path.
        """
        out: List[Tuple[CanonicalFailureRecord, bool]] = []
        new_slots: List[int] = []
        new_texts: List[str] = []
        with self._lock:
            now = utcnow()
            for item in items:
                key = (item["failure_type"], item["signature_text"])
                slot = self._slot_by_key.get(key)
                if slot is None:
                    # model_construct: inputs are classifier-built and typed;
                    # skipping validation keeps batch inserts off the pydantic
                    # hot loop (single-record upsert_failure keeps validating).
                    rec = CanonicalFailureRecord.model_construct(
                        failure_id=f"F-{len(self._records) + 1:04d}",
                        version=1,
                        created_at=now,
                        updated_at=now,
                        failure_type=item["failure_type"],
                        root_cause=item.get("root_cause"),
                        context_signature=item.get("context_signature") or {},
                        impact_severity=Severity(item["impact_severity"]),
                        resolution=item.get("resolution"),
                        occurrences=1,
                        affected_apps=[item["app_id"]],
                        signature_text=item["signature_text"],
                    )
                    slot = len(self._records)
                    self._records.append(rec)
                    self._slot_by_key[key] = slot
                    self._ids_by_type.setdefault(rec.failure_type, []).append(rec.failure_id)
                    self._apps_by_type.setdefault(rec.failure_type, set()).add(item["app_id"])
                    new_slots.append(slot)
                    new_texts.append(rec.signature_text)
                    out.append((rec, True))
                else:
                    old = self._records[slot]
                    rec = old.model_copy(deep=True)
                    rec.version += 1
                    rec.updated_at = now
                    rec.occurrences += 1
                    if item["app_id"] not in rec.affected_apps:
                        rec.affected_apps.append(item["app_id"])
                    self._apps_by_type.setdefault(rec.failure_type, set()).add(item["app_id"])
                    rec.root_cause = item.get("root_cause") or rec.root_cause
                    rec.resolution = item.get("resolution") or rec.resolution
                    rec.context_signature = item.get("context_signature") or rec.context_signature
                    self._records[slot] = rec
                    out.append((rec, False))
                self._append_line(self.failures_path, rec.model_dump_json())
            self._flush_logs()
            if new_slots:
                self._ensure_capacity(len(self._records))
                vecs = self.featurizer.encode_batch(new_texts)
                with profiling.annotate("gfkb.insert"):
                    self._emb, self._valid = self._knn.insert(
                        self._emb, self._valid, vecs, np.asarray(new_slots, dtype=np.int32)
                    )
        return out

    # ------------------------------------------------------------------
    # match
    # ------------------------------------------------------------------

    def match(
        self,
        signature_text: str,
        failure_type: Optional[str] = None,
        type_filter: str = "post",
    ) -> List[FailureMatch]:
        return self.match_batch([signature_text], failure_type, type_filter)[0]

    def match_batch(
        self,
        signature_texts: Sequence[str],
        failure_type: Optional[str] = None,
        type_filter: str = "post",
    ) -> List[List[FailureMatch]]:
        """Top-k similarity matches for a batch of queries (one device call)."""
        q = self.featurizer.encode_batch(list(signature_texts))
        b = q.shape[0]
        bb = batch_bucket(b)
        if bb != b:
            q = np.concatenate([q, np.zeros((bb - b, q.shape[1]), dtype=q.dtype)])

        # The device call runs under the lock: inserts donate the (emb, valid)
        # buffers, so a concurrent upsert would invalidate a lock-free
        # snapshot (and a capacity growth would change the slot mapping).
        with self._lock:
            if not self._records:
                return [[] for _ in signature_texts]
            records = list(self._records)
            with profiling.annotate("gfkb.match.topk"):
                scores, slots = self._knn.topk(self._emb, self._valid, q)

        out: List[List[FailureMatch]] = []
        for i in range(b):
            row: List[FailureMatch] = []
            for s, slot in zip(scores[i], slots[i]):
                if s <= -1.0 or slot >= len(records):
                    continue  # padding / invalid rows
                rec = records[int(slot)]
                if failure_type and rec.failure_type != failure_type:
                    continue
                row.append(
                    FailureMatch(
                        failure_id=rec.failure_id,
                        version=rec.version,
                        # f32 accumulation can nudge an exact self-match a hair
                        # past 1.0; cosine is bounded, so clamp.
                        score=min(1.0, max(-1.0, float(s))),
                        failure_type=rec.failure_type,
                        suggested_mitigation=rec.resolution,
                    )
                )
            out.append(row)
        return out

    # ------------------------------------------------------------------
    # patterns
    # ------------------------------------------------------------------

    def list_patterns(self) -> List[PatternEntity]:
        """Latest record per pattern (dedup-for-presentation, like the
        reference's GET /patterns, services/gfkb/app.py:150-157)."""
        with self._lock:
            return list(self._patterns.values())

    def upsert_pattern(
        self,
        *,
        name: str,
        failure_ids: Sequence[str],
        affected_apps: Sequence[str],
        description: Optional[str] = None,
    ) -> Tuple[PatternEntity, bool]:
        """Identity-by-name pattern upsert with set-union merge
        (reference: services/gfkb/app.py:168-198)."""
        with self._lock:
            existing = self._patterns.get(name)
            if existing is None:
                p = PatternEntity(
                    pattern_id=f"FP-{len(self._patterns) + 1:04d}",
                    name=name,
                    created_at=utcnow(),
                    failure_ids=sorted(set(failure_ids)),
                    affected_apps=sorted(set(affected_apps)),
                    description=description,
                )
                created = True
            else:
                p = existing.model_copy(deep=True)
                p.failure_ids = sorted(set(list(p.failure_ids) + list(failure_ids)))
                p.affected_apps = sorted(set(list(p.affected_apps) + list(affected_apps)))
                p.description = description or p.description
                created = False
            self._patterns[name] = p
            self._append_jsonl(self.patterns_path, p.model_dump(mode="json"))
            self._flush_logs()
            return p, created
