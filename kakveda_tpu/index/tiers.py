"""Tiered GFKB storage hierarchy — device-hot / host-warm / disk-cold.

The warn path's exact device scan is O(N) per query and capped by HBM:
past the hot-row budget nothing was even representable, and the PR-5
degraded-mode host mirror (``GFKB.match_batch_host``) lived as a parallel
code path rather than an architecture. This module turns those pieces
into ONE storage hierarchy behind a single match/insert abstraction:

* **device-hot** — the existing sharded device index (``ops/knn.py``),
  exact top-k, capped at ``KAKVEDA_GFKB_HOT_ROWS`` logical slots. The
  GFKB keeps owning those buffers; this module only knows the boundary.
* **host-warm** (:class:`WarmTier`) — slot-indexed fixed-width sparse
  (idx, val) row arrays in host RAM plus the lazily-built inverted index
  the degraded mode has always used. Degraded mode, overflow matching,
  snapshot restore and the exact oracle all read the SAME rows through
  the same scorer — the PR-5 mirror, promoted from a bespoke fallback to
  the middle tier.
* **disk-cold** (:class:`ColdTier`) — append-only ``np.memmap`` sparse
  row shards under ``KAKVEDA_GFKB_COLD_DIR``. Rows past the warm budget
  land here; candidate lists page them in on demand (mmap reads touch
  only the candidate rows), and recently paged rows are promoted into a
  bounded LRU so repeat hits stay in RAM.

Tier membership is a pure function of the append slot — ``[0, hot)`` on
device (and mirrored warm for degraded mode), ``[hot, warm_budget)``
warm, ``[warm_budget, N)`` cold — so there is no migration bookkeeping
to snapshot or to desynchronize; the promote-LRU supplies recency
adaptivity on top of the static ranges.

Routing is IVF-style (:class:`CoarseRouter`): maintain coarse centroids
over the corpus (online spawn + running-mean delta update, ONE
vectorized update per ingest batch — the same one-dispatch-per-batch
contract as the device insert), split oversized lists with a 2-means
pass, optionally re-seed the partition from the incremental mining
state's labels (``ops/incremental.py`` already maintains exactly the
per-row cluster structure a coarse quantizer needs), then at query time
route to ``nprobe`` lists, gather their candidate slots, and run EXACT
top-k only over the candidates — O(C·nnz + cand·K) per query instead of
O(N).

Failure contract (chaos sites ``gfkb.tier_spill`` / ``gfkb.tier_route``,
docs/robustness.md): a spill fault keeps the row warm (over budget —
memory pressure, never data loss, never a failed ingest); a routing
fault degrades that query to the exact full scan (slower, never a
wrong-but-confident verdict). ``KAKVEDA_GFKB_TIERED=0`` disables the
hot cap, the router and the cold tier entirely — bit-for-bit the
pre-tiered exact behavior — while the warm mirror keeps serving
degraded mode through this same class.

Thread-safety: one RLock per :class:`TieredIndex`; the GFKB additionally
serializes mutations under its own data lock, standalone users (bench)
get correctness from ours.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
from collections import OrderedDict
from pathlib import Path
from time import perf_counter
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from kakveda_tpu import native as _native
from kakveda_tpu.core import faults as _faults
from kakveda_tpu.core import metrics as _metrics
from kakveda_tpu.core import sanitize

log = logging.getLogger("kakveda.tiers")

__all__ = [
    "TierConfig",
    "NativeScorer",
    "WarmTier",
    "ColdTier",
    "CoarseRouter",
    "TieredIndex",
    "TierSpillError",
]

# Below this corpus size a routed match gains nothing over the exact
# inverted-index walk — route only past it (and always past the hot cap,
# where exactness over the overflow requires candidates anyway).
_ROUTE_MIN_ROWS = 4096
# Cosine floor under which a new row spawns its own centroid instead of
# joining its best match — keeps lists coherent without a knob.
_SPAWN_SIM = 0.30
_SPLIT_ITERS = 6
_COLD_SHARD_ROWS = 1 << 18


class TierSpillError(RuntimeError):
    """A cold-tier write failed (disk full, injected fault). Internal —
    the spill path catches it and keeps the row warm; it must never
    surface to an ingest caller."""


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _topk_desc(scores: np.ndarray, k: int) -> np.ndarray:
    """Top-k indices by descending score: O(m) partition + O(k log k)
    sort of the survivors, vs the full argsort's O(m log m). Tie order
    among exactly-equal scores can differ from a full argsort — native
    paths only; the numpy fallback keeps the historical argsort."""
    if k >= len(scores):
        return np.argsort(-scores)
    part = np.argpartition(-scores, k)[:k]
    return part[np.argsort(-scores[part])]


class TierConfig:
    """Resolved-once knob bundle (docs/observability.md registry)."""

    def __init__(
        self,
        *,
        tiered: Optional[bool] = None,
        hot_rows: Optional[int] = None,
        warm_rows: Optional[int] = None,
        nprobe: Optional[int] = None,
        cold_dir: Optional[Path] = None,
        max_list: Optional[int] = None,
        promote_cache: Optional[int] = None,
    ):
        self.tiered = (
            os.environ.get("KAKVEDA_GFKB_TIERED", "1") != "0"
            if tiered is None else tiered
        )
        self.hot_rows = _env_int("KAKVEDA_GFKB_HOT_ROWS", 1 << 20) if hot_rows is None else hot_rows
        self.warm_rows = _env_int("KAKVEDA_GFKB_WARM_ROWS", 1 << 22) if warm_rows is None else warm_rows
        self.nprobe = _env_int("KAKVEDA_GFKB_NPROBE", 8) if nprobe is None else nprobe
        self.max_list = _env_int("KAKVEDA_GFKB_MAX_LIST", 4096) if max_list is None else max_list
        self.promote_cache = (
            _env_int("KAKVEDA_GFKB_PROMOTE_CACHE", 4096)
            if promote_cache is None else promote_cache
        )
        if cold_dir is not None:
            self.cold_dir: Optional[Path] = Path(cold_dir)
        else:
            env = os.environ.get("KAKVEDA_GFKB_COLD_DIR", "")
            self.cold_dir = Path(env) if env else None
        if not self.tiered:
            # Pre-tiered semantics: no hot cap (device grows), no cold
            # spill, no routing. The warm mirror still exists for
            # degraded mode — that part predates tiering.
            self.hot_rows = 1 << 62
            self.warm_rows = 1 << 62
            self.cold_dir = None


# ---------------------------------------------------------------------------
# native scoring seam
# ---------------------------------------------------------------------------


class NativeScorer:
    """The one gate between host-tier scoring and the C++ library.

    Every method returns scores or ``None`` — None means "run the numpy
    path", and the numpy paths are byte-identical to the pre-native code,
    so ``KAKVEDA_NATIVE=0``, a missing library, a failed call and an armed
    ``native.score`` fault all reproduce today's results bit-for-bit. A
    scoring problem is NEVER a failed warn: the worst outcome is the
    pre-native latency. Fault site and metric children resolve once here
    (construction), per the fault-site / hot-path invariants."""

    def __init__(self) -> None:
        try:
            self.enabled = _native.load() is not None
        except RuntimeError:
            # KAKVEDA_NATIVE=require propagates from consumers' own load()
            # calls (featurizer, tests); the scorer itself just degrades.
            self.enabled = False
        self.min_rows = _native.score_min_rows()
        self._fault = _faults.site("native.score")
        reg = _metrics.get_registry()
        h = reg.histogram(
            "kakveda_native_score_seconds",
            "Native host-tier scoring call duration by path (warm = warm "
            "exact scan, cold = cold-shard exact scan, ivf = routed "
            "candidate scoring)", ("path",),
        )
        self._h = {p: h.labels(path=p) for p in ("warm", "cold", "ivf")}
        c = reg.counter(
            "kakveda_native_fallback_total",
            "Host-tier scoring calls served by the numpy fallback by reason "
            "(unavailable = library off/absent, fault = chaos site "
            "native.score, error = native call failed)", ("reason",),
        )
        self._c_fb = {r: c.labels(reason=r) for r in ("unavailable", "fault", "error")}

    def _admit(self, total_rows: int) -> bool:
        """Common gate: tiny scans stay numpy (no fallback counted — a
        policy choice, not a degradation); disabled/armed/failed calls
        count their reason."""
        if total_rows < self.min_rows:
            return False
        if not self.enabled:
            self._c_fb["unavailable"].inc()
            return False
        try:
            self._fault.fire()
        except Exception:  # noqa: BLE001 — FaultInjected → numpy, never a failed warn
            self._c_fb["fault"].inc()
            return False
        return True

    def score_block(
        self, qdense: np.ndarray, idx: np.ndarray, val: np.ndarray,
        dim: int, path: str,
    ) -> Optional[np.ndarray]:
        b = qdense.shape[0] if qdense.ndim == 2 else 1
        if not self._admit(b * idx.shape[0]):
            return None
        t0 = perf_counter()
        out = _native.score_block(qdense, idx, val, dim)
        if out is None:
            self._c_fb["error"].inc()
            return None
        self._h[path].observe(perf_counter() - t0)
        return out

    def score_candidates(
        self, qdense: np.ndarray, idx: np.ndarray, val: np.ndarray,
        offsets: np.ndarray, dim: int,
    ) -> Optional[np.ndarray]:
        if not self._admit(int(offsets[-1])):
            return None
        t0 = perf_counter()
        out = _native.score_candidates(qdense, idx, val, offsets, dim)
        if out is None:
            self._c_fb["error"].inc()
            return None
        self._h["ivf"].observe(perf_counter() - t0)
        return out

    def score_gather_segments(
        self, qdense: np.ndarray,
        segments: List[Tuple[np.ndarray, np.ndarray, np.ndarray]],
        dim: int,
    ) -> Optional[List[np.ndarray]]:
        """One query against row ids gathered in place from several base
        arrays (warm arrays + one per cold shard) — the zero-copy routed
        scoring plan. Admission once over the total row count; any
        segment failing falls the whole query back to the materialized
        path (never a partial result)."""
        if not self._admit(sum(len(s[2]) for s in segments)):
            return None
        t0 = perf_counter()
        outs: List[np.ndarray] = []
        for idx, val, rows in segments:
            res = _native.score_gather(qdense, idx, val, rows, dim)
            if res is None:
                self._c_fb["error"].inc()
                return None
            outs.append(res)
        self._h["ivf"].observe(perf_counter() - t0)
        return outs


# ---------------------------------------------------------------------------
# host-warm tier
# ---------------------------------------------------------------------------


class WarmTier:
    """Slot-indexed sparse rows in host RAM + the degraded-mode inverted
    index.

    Rows live in fixed-width ``idx [cap, K] int32`` / ``val [cap, K] f32``
    arrays (pad idx == ``dim``, the same drop sentinel the device scatter
    uses) so candidate gathers are one fancy-index read, not a dict walk.
    ``K`` grows to the widest row seen (power of two) — rows are stored
    EXACTLY, never truncated, because the degraded mode's top-1 parity
    contract depends on it. The inverted index (feature → slot/val
    postings) is built lazily on the first exact scan and extended by
    watermark, exactly as the PR-5 mirror did."""

    _GROW = 1 << 12

    def __init__(self, dim: int, scorer: Optional[NativeScorer] = None):
        self.dim = dim
        self.k = 64  # matches the sparse encoders' starting width
        self.scorer = scorer
        self._idx = np.full((0, self.k), dim, np.int32)
        self._val = np.zeros((0, self.k), np.float32)
        # rows [0, n) are present except slots the owner never stored
        # (pure-cold rows); absent rows keep the all-pad sentinel.
        self.n = 0
        self._inv: Optional[dict] = None
        self._inv_n = 0

    def _grow(self, n: int, k: int) -> None:
        if n <= len(self._idx) and k <= self.k:
            return
        new_k = self.k
        while new_k < k:
            new_k <<= 1
        cap = len(self._idx)
        if n > cap:
            cap = max(n, cap + self._GROW, 2 * cap)
        idx = np.full((cap, new_k), self.dim, np.int32)
        val = np.zeros((cap, new_k), np.float32)
        idx[: len(self._idx), : self.k] = self._idx
        val[: len(self._val), : self.k] = self._val
        self._idx, self._val, self.k = idx, val, new_k

    def store(self, slots: np.ndarray, sp_idx: np.ndarray, sp_val: np.ndarray) -> None:
        slots = np.asarray(slots, np.int64)
        if len(slots) == 0:
            return
        self._grow(int(slots.max()) + 1, sp_idx.shape[1])
        k = sp_idx.shape[1]
        self._idx[slots, :k] = sp_idx
        self._idx[slots, k:] = self.dim
        self._val[slots, :k] = sp_val
        self._val[slots, k:] = 0.0
        self.n = max(self.n, int(slots.max()) + 1)

    def row(self, slot: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """(idx, val) trimmed of padding, or None when not resident."""
        if slot >= len(self._idx):
            return None
        keep = self._idx[slot] < self.dim
        if not keep.any():
            return None
        return self._idx[slot][keep].copy(), self._val[slot][keep].copy()

    def rows_block(self, slots: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Fixed-width gather for candidate scoring ([B, K] idx/val).
        Slots never stored (or past the arrays) gather as all-pad rows
        that score 0 — same semantics as an embed still pending."""
        in_range = slots < len(self._idx)
        if in_range.all():
            return self._idx[slots], self._val[slots]
        idx = np.full((len(slots), self.k), self.dim, np.int32)
        val = np.zeros((len(slots), self.k), np.float32)
        if in_range.any():
            idx[in_range] = self._idx[slots[in_range]]
            val[in_range] = self._val[slots[in_range]]
        return idx, val

    # -- exact scoring ----------------------------------------------------

    def _extend_inv(self, upto: int) -> dict:
        if self._inv is None:
            self._inv = {}
            self._inv_n = 0
        inv = self._inv
        s = self._inv_n
        upto = min(upto, self.n)
        while s < upto:
            keep = self._idx[s] < self.dim
            if not keep.any():
                s += 1
                continue
            for f, v in zip(self._idx[s][keep].tolist(), self._val[s][keep].tolist()):
                ent = inv.get(f)
                if ent is None:
                    ent = inv[f] = ([], [])
                ent[0].append(s)
                ent[1].append(v)
            s += 1
        self._inv_n = s
        return inv

    def score_all(self, q_idx: np.ndarray, q_val: np.ndarray, n: int) -> np.ndarray:
        """Exact scores [n] for one sparse query over every resident row.

        Native path: one SIMD sparse-dot sweep over the fixed-width row
        arrays (O(n·K), the degraded-window warn cost). Fallback (and the
        ``KAKVEDA_NATIVE=0`` bit-for-bit contract): the inverted-index
        walk (O(query nnz · postings)), the degraded mode scorer since
        PR 5. Slots past the stored range (pure-cold rows) score 0 on
        both paths."""
        sc = self.scorer
        if sc is not None and n > 0:
            m = min(n, self.n, len(self._idx))
            if m > 0:
                qd = np.zeros(self.dim + 1, np.float32)
                np.add.at(qd, np.minimum(q_idx, self.dim), q_val)
                qd[self.dim] = 0.0
                out = sc.score_block(qd, self._idx[:m], self._val[:m], self.dim, "warm")
                if out is not None:
                    if m < n:
                        out = np.concatenate([out, np.zeros(n - m, np.float32)])
                    return out
        inv = self._extend_inv(n)
        scores = np.zeros(n, np.float32)
        keep = q_idx < self.dim
        for f, v in zip(q_idx[keep].tolist(), q_val[keep].tolist()):
            ent = inv.get(f)
            if ent is not None:
                sl = np.asarray(ent[0])
                m = sl < n
                # add.at, not fancy +=: a row holding the same feature
                # twice posts two entries for the same slot, and buffered
                # fancy indexing would drop all but one — silently
                # undercounting vs the dense-gather semantics every other
                # scoring path (hot scan, routed candidates, native) uses.
                np.add.at(scores, sl[m], v * np.asarray(ent[1], np.float32)[m])
        return scores


# ---------------------------------------------------------------------------
# disk-cold tier
# ---------------------------------------------------------------------------


class ColdTier:
    """Append-only sparse row shards on disk, paged in on demand.

    Each shard is a pair of raw memmaps (``idx-…`` int32 / ``val-…`` f32,
    ``[_COLD_SHARD_ROWS, K]``) plus a tiny JSON meta; ``K`` is fixed per
    shard, so a wider row simply seals the current shard and opens the
    next at the wider width. Row address = (slot - base) → shard, row.
    Reads touch only the candidate rows (mmap pages fault in on demand);
    a bounded LRU (:attr:`promoted`) keeps recently paged rows hot."""

    def __init__(self, root: Path, dim: int, base_slot: int, promote_cache: int,
                 scorer: Optional[NativeScorer] = None):
        self.root = Path(root)
        self.dim = dim
        self.base = base_slot
        self.scorer = scorer
        self.n = 0  # rows appended (slot s ↔ cold row s - base)
        self._shards: List[dict] = []  # {k, rows, idx(memmap), val(memmap)}
        self._promote_max = promote_cache
        self.promoted: "OrderedDict[int, Tuple[np.ndarray, np.ndarray]]" = OrderedDict()
        self.root.mkdir(parents=True, exist_ok=True)
        self._load_meta()

    # -- persistence ------------------------------------------------------

    def _meta_path(self) -> Path:
        return self.root / "cold.json"

    def _load_meta(self) -> None:
        mp = self._meta_path()
        if not mp.exists():
            return
        try:
            meta = json.loads(mp.read_text())
            if meta.get("dim") != self.dim or meta.get("base") != self.base:
                raise ValueError("cold meta does not match this index")
            for s in meta["shards"]:
                self._open_shard(int(s["k"]), int(s["rows"]), s["name"])
            self.n = int(meta["n"])
        except Exception as e:  # noqa: BLE001 — cold is derived, rebuildable
            log.warning(
                "cold tier meta unreadable (%s: %s); discarding cold shards "
                "(owner re-spills from the log)", type(e).__name__, e,
            )
            self._shards = []
            self.n = 0
            for p in self.root.iterdir():
                if p.name != "cold.json":
                    p.unlink(missing_ok=True)
            mp.unlink(missing_ok=True)

    def _flush_meta(self) -> None:
        meta = {
            "dim": self.dim,
            "base": self.base,
            "n": self.n,
            "shards": [
                {"k": s["k"], "rows": s["rows"], "name": s["name"]}
                for s in self._shards
            ],
        }
        tmp = self._meta_path().with_suffix(".tmp")
        tmp.write_text(json.dumps(meta))
        os.replace(tmp, self._meta_path())

    def _open_shard(self, k: int, rows: int, name: str) -> dict:
        ip = self.root / f"idx-{name}.mm"
        vp = self.root / f"val-{name}.mm"
        mode = "r+" if ip.exists() else "w+"
        shard = {
            "k": k,
            "rows": rows,
            "name": name,
            "idx": np.memmap(ip, np.int32, mode, shape=(_COLD_SHARD_ROWS, k)),
            "val": np.memmap(vp, np.float32, mode, shape=(_COLD_SHARD_ROWS, k)),
        }
        if mode == "w+":
            shard["idx"][:] = self.dim  # pad sentinel everywhere
        self._shards.append(shard)
        return shard

    # -- append / read ----------------------------------------------------

    def append(self, sp_idx: np.ndarray, sp_val: np.ndarray) -> None:
        """Append a batch of rows at the current tail (slots are assigned
        by the caller in order — cold row r holds slot base + r). Raises
        :class:`TierSpillError` on any IO failure; the caller keeps the
        rows warm instead."""
        try:
            b, k = sp_idx.shape
            done = 0
            while done < b:
                if not self._shards or self._shards[-1]["rows"] >= _COLD_SHARD_ROWS \
                        or self._shards[-1]["k"] < k:
                    if self._shards:
                        self._shards[-1]["idx"].flush()
                        self._shards[-1]["val"].flush()
                    self._open_shard(max(k, 64), 0, f"{len(self._shards):05d}")
                sh = self._shards[-1]
                room = _COLD_SHARD_ROWS - sh["rows"]
                take = min(room, b - done)
                r0 = sh["rows"]
                sh["idx"][r0 : r0 + take, :k] = sp_idx[done : done + take]
                sh["val"][r0 : r0 + take, :k] = sp_val[done : done + take]
                sh["rows"] += take
                done += take
            self.n += b
            self._flush_meta()
        except (OSError, ValueError) as e:
            raise TierSpillError(f"cold append failed: {e}") from e

    def _locate(self, slot: int) -> Tuple[dict, int]:
        r = slot - self.base
        off = 0
        for sh in self._shards:
            if r < off + sh["rows"]:
                return sh, r - off
            off += sh["rows"]
        raise KeyError(slot)

    def row(self, slot: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        hit = self.promoted.get(slot)
        if hit is not None:
            self.promoted.move_to_end(slot)
            return hit
        try:
            sh, r = self._locate(slot)
        except KeyError:
            return None
        keep = sh["idx"][r] < self.dim
        row = (np.asarray(sh["idx"][r][keep]), np.asarray(sh["val"][r][keep]))
        if self._promote_max > 0:
            self.promoted[slot] = row
            while len(self.promoted) > self._promote_max:
                self.promoted.popitem(last=False)
        return row

    def rows_block(self, slots: np.ndarray, k_out: int) -> Tuple[np.ndarray, np.ndarray]:
        """Fixed-width gather of cold rows via a coalesced read plan.

        Candidates are grouped per shard, sorted, and split into
        contiguous runs; each run is ONE basic-slice memmap read (a single
        large pread through the page cache) scattered back into position.
        IVF lists extend in slot-append order, so routed candidate lists
        are dominated by long runs — the pathological per-row fancy-index
        paging this replaces only survives as the fallback for genuinely
        scattered gathers (mean run < 4 rows), where run reads degenerate
        to the same row-by-row cost plus Python loop overhead."""
        idx = np.full((len(slots), k_out), self.dim, np.int32)
        val = np.zeros((len(slots), k_out), np.float32)
        r = slots - self.base
        off = 0
        for sh in self._shards:
            rows = sh["rows"]
            sel = (r >= off) & (r < off + rows)
            if sel.any():
                pos = np.flatnonzero(sel)
                rr = (r[pos] - off).astype(np.int64)
                k = min(sh["k"], k_out)
                order = np.argsort(rr, kind="stable")
                rs, ps = rr[order], pos[order]
                cut = np.flatnonzero(np.r_[True, np.diff(rs) != 1])
                if len(rs) >= 4 * len(cut):
                    bounds = np.r_[cut, len(rs)]
                    for a, z in zip(bounds[:-1], bounds[1:]):
                        r0 = int(rs[a])
                        blk_i = np.asarray(sh["idx"][r0 : r0 + (z - a), :k])
                        blk_v = np.asarray(sh["val"][r0 : r0 + (z - a), :k])
                        idx[ps[a:z], :k] = blk_i
                        val[ps[a:z], :k] = blk_v
                else:
                    idx[pos, :k] = np.asarray(sh["idx"][rr][:, :k])
                    val[pos, :k] = np.asarray(sh["val"][rr][:, :k])
            off += rows
        return idx, val

    def score_all(self, qdense: np.ndarray) -> np.ndarray:
        """Exact scores [n] over EVERY cold row (the oracle /
        degraded-exact path; routed queries never pay this). Native path:
        one threaded sweep per shard reading straight through the memmap
        (no RAM copy — the shard slice is already contiguous); fallback
        chunk-streams through numpy exactly as before."""
        out = np.zeros(self.n, np.float32)
        off = 0
        for sh in self._shards:
            rows = sh["rows"]
            if self.scorer is not None and rows:
                res = self.scorer.score_block(
                    qdense, sh["idx"][:rows], sh["val"][:rows], self.dim, "cold"
                )
                if res is not None:
                    out[off : off + rows] = res
                    off += rows
                    continue
            for c0 in range(0, rows, 1 << 14):
                c1 = min(rows, c0 + (1 << 14))
                idx = np.asarray(sh["idx"][c0:c1])
                val = np.asarray(sh["val"][c0:c1])
                out[off + c0 : off + c1] = (qdense[idx] * val).sum(axis=1)
            off += rows
        return out


# ---------------------------------------------------------------------------
# IVF coarse router
# ---------------------------------------------------------------------------


class CoarseRouter:
    """Coarse quantizer over the corpus: centroids + per-centroid slot
    lists + per-slot assignment.

    Maintenance is streaming: each ingest batch gets ONE vectorized
    assignment (O(B·C·nnz) host work), new rows below :data:`_SPAWN_SIM`
    spawn their own centroid, running sums keep centroids the mean of
    their members, and a list past ``max_list`` is split by a short
    2-means pass. :meth:`seed_from_labels` rebuilds the partition from
    the incremental mining state's labels (``ClusterState.labels()``) —
    the coarse structure mining already maintains."""

    def __init__(self, dim: int, max_list: int):
        self.dim = dim
        self.max_list = max_list
        self.c = 0
        self._cent = np.zeros((0, dim), np.float32)   # L2-normalized
        self._sums = np.zeros((0, dim), np.float32)   # running member sums
        self._counts = np.zeros(0, np.int64)
        self._lists: List[List[int]] = []
        self._assign = np.full(0, -1, np.int32)       # slot -> centroid
        self._n = 0          # 1 + highest slot seen
        self._assigned = 0   # rows actually assigned (no holes ⟺ == _n)
        self.splits = 0

    @property
    def n_rows(self) -> int:
        return self._n

    def covers(self, n: int) -> bool:
        """Does the partition cover every slot in [0, n)? A faulted
        delta update leaves holes — a router with holes must NEVER serve
        a routed match (silent misses are wrong-but-confident verdicts);
        callers fall back to the exact scan until a reseed/rebuild."""
        return self._n >= n and self._assigned >= n

    def _grow_c(self, c: int) -> None:
        if c <= len(self._cent):
            return
        cap = max(c, 2 * len(self._cent), 64)
        cent = np.zeros((cap, self.dim), np.float32)
        sums = np.zeros((cap, self.dim), np.float32)
        counts = np.zeros(cap, np.int64)
        cent[: self.c] = self._cent[: self.c]
        sums[: self.c] = self._sums[: self.c]
        counts[: self.c] = self._counts[: self.c]
        self._cent, self._sums, self._counts = cent, sums, counts

    def _grow_assign(self, n: int) -> None:
        if n <= len(self._assign):
            return
        a = np.full(max(n, 2 * len(self._assign), 1024), -1, np.int32)
        a[: len(self._assign)] = self._assign
        self._assign = a

    def _scores(self, sp_idx: np.ndarray, sp_val: np.ndarray) -> np.ndarray:
        """[B, C] centroid similarities for sparse rows — O(B·C·nnz),
        never a dense [B, dim]. Batches take the scipy CSR × dense path
        (a compiled sparse gemm — the per-ingest-batch assignment cost)
        when scipy is present; single queries and the fallback use a
        column gather over the centroid matrix."""
        b, k = sp_idx.shape
        cent = self._cent[: self.c]
        # pad entries point at col dim-1 with val 0 — they contribute 0
        idx_safe = np.minimum(sp_idx, self.dim - 1)
        if b == 1:
            g = cent[:, idx_safe[0]]                 # [C, K]
            return (g * sp_val[0][None, :]).sum(axis=1)[None, :]
        try:
            from scipy import sparse as _sp

            csr = _sp.csr_matrix(
                (
                    sp_val.ravel(),
                    idx_safe.ravel().astype(np.int64),
                    np.arange(0, (b + 1) * k, k, dtype=np.int64),
                ),
                shape=(b, self.dim),
            )
            return np.asarray(csr @ cent.T, dtype=np.float32)
        except ImportError:
            out = np.empty((b, self.c), np.float32)
            centT = np.ascontiguousarray(cent.T)     # [dim, C]
            step = max(1, (1 << 24) // max(1, self.c * k))
            for s in range(0, b, step):
                e = min(b, s + step)
                g = centT[idx_safe[s:e]]             # [Bc, K, C]
                out[s:e] = np.matmul(sp_val[s:e, None, :], g)[:, 0, :]
            return out

    def _renorm(self, cids: Sequence[int]) -> None:
        for c in set(int(c) for c in cids):
            nrm = float(np.linalg.norm(self._sums[c]))
            self._cent[c] = self._sums[c] / nrm if nrm > 0 else 0.0

    def _spawn(self, sp_i: np.ndarray, sp_v: np.ndarray) -> int:
        self._grow_c(self.c + 1)
        c = self.c
        self.c += 1
        self._lists.append([])
        keep = sp_i < self.dim
        self._sums[c] = 0.0
        np.add.at(self._sums[c], sp_i[keep], sp_v[keep])
        # the spawning row is folded in here (sums AND count) — batch
        # commit skips spawned rows.
        self._counts[c] = 1
        self._renorm([c])
        return c

    def add_batch(
        self,
        slots: Sequence[int],
        sp_idx: np.ndarray,
        sp_val: np.ndarray,
        rows_fn: Optional[Callable[[np.ndarray], Tuple[np.ndarray, np.ndarray]]] = None,
    ) -> None:
        """Assign one ingest batch (the per-batch delta update). One
        vectorized similarity pass assigns the whole batch; per-row work
        happens only at centroid spawns (a new failure shape), where the
        not-yet-assigned tail is re-scored against the one new centroid
        so same-batch siblings join it. ``rows_fn`` supplies member rows
        for an oversized-list split; splits are skipped without it."""
        slots_arr = np.asarray(slots, np.int64)
        if len(slots_arr) == 0:
            return
        self._grow_assign(int(slots_arr.max()) + 1)
        new = self._assign[slots_arr] < 0  # idempotent re-add (replay overlap)
        if not new.all():
            if not new.any():
                return
            slots_arr = slots_arr[new]
            sp_idx, sp_val = sp_idx[new], sp_val[new]
        b = len(slots_arr)
        if self.c:
            sims = self._scores(sp_idx, sp_val)
            best = sims.argmax(axis=1).astype(np.int64)
            best_sim = sims[np.arange(b), best]
        else:
            best = np.zeros(b, np.int64)
            best_sim = np.full(b, -np.inf, np.float32)
        labels = np.empty(b, np.int64)
        spawned = np.zeros(b, np.bool_)
        touched: set = set()
        idx_safe = np.minimum(sp_idx, self.dim - 1)
        keep_all = sp_idx < self.dim
        start = 0
        while start < b:
            low = np.flatnonzero(best_sim[start:] < _SPAWN_SIM)
            stop = start + (int(low[0]) if len(low) else b - start)
            if stop > start:
                labels[start:stop] = best[start:stop]
                touched.update(np.unique(best[start:stop]).tolist())
            if stop < b:
                c_new = self._spawn(sp_idx[stop], sp_val[stop])
                labels[stop] = c_new
                spawned[stop] = True
                touched.add(c_new)
                if stop + 1 < b:
                    # the tail may join the freshly spawned centroid
                    rest = slice(stop + 1, b)
                    cvec = self._cent[c_new]
                    s_new = np.where(
                        keep_all[rest], cvec[idx_safe[rest]] * sp_val[rest], 0.0
                    ).sum(axis=1)
                    upd = s_new > best_sim[rest]
                    best_sim[rest] = np.where(upd, s_new, best_sim[rest])
                    best[rest] = np.where(upd, c_new, best[rest])
                start = stop + 1
            else:
                start = b
        # bulk commit: spawned rows were folded into their centroid by
        # _spawn; everything else lands in one grouped scatter-add.
        ns = ~spawned
        if ns.any():
            lab_b = np.broadcast_to(labels[:, None], sp_idx.shape)
            sel = keep_all & ns[:, None]
            np.add.at(self._sums, (lab_b[sel], sp_idx[sel]), sp_val[sel])
            self._counts[: self.c] += np.bincount(
                labels[ns], minlength=self.c
            )[: self.c]
        order = np.argsort(labels, kind="stable")
        sl_sorted, lab_sorted = slots_arr[order], labels[order]
        bounds = np.flatnonzero(np.r_[True, lab_sorted[1:] != lab_sorted[:-1], True])
        for a, z in zip(bounds[:-1], bounds[1:]):
            self._lists[int(lab_sorted[a])].extend(sl_sorted[a:z].tolist())
        self._assign[slots_arr] = labels
        self._n = max(self._n, int(slots_arr.max()) + 1)
        self._assigned += b
        self._renorm(touched)
        if rows_fn is not None:
            for c in touched:
                if len(self._lists[c]) > self.max_list:
                    self._split(c, rows_fn)

    def _split(self, c: int, rows_fn) -> None:
        """2-means split of one oversized list (short, host-side)."""
        members = np.asarray(self._lists[c], np.int64)
        m_idx, m_val = rows_fn(members)
        if len(members) < 4:
            return
        # seeds: first member + the member least similar to it
        q = np.zeros(self.dim + 1, np.float32)
        np.add.at(q, m_idx[0], m_val[0])
        sims0 = (q[np.minimum(m_idx, self.dim)] * m_val).sum(axis=1)
        seeds = [0, int(np.argmin(sims0))]
        cents = np.zeros((2, self.dim), np.float32)
        for j, s in enumerate(seeds):
            keep = m_idx[s] < self.dim
            np.add.at(cents[j], m_idx[s][keep], m_val[s][keep])
            n = np.linalg.norm(cents[j]) or 1.0
            cents[j] /= n
        lab = np.zeros(len(members), np.int64)
        for _ in range(_SPLIT_ITERS):
            g = cents[:, np.minimum(m_idx, self.dim - 1)]       # [2, M, K]
            sims = np.einsum("cmk,mk->mc", g, m_val)
            new_lab = np.argmax(sims, axis=1)
            if np.array_equal(new_lab, lab):
                break
            lab = new_lab
            for j in (0, 1):
                sel = lab == j
                cents[j] = 0.0
                if sel.any():
                    np.add.at(cents[j], m_idx[sel].ravel()[m_idx[sel].ravel() < self.dim],
                              m_val[sel].ravel()[m_idx[sel].ravel() < self.dim])
                    n = np.linalg.norm(cents[j]) or 1.0
                    cents[j] /= n
        if not lab.any() or lab.all():
            return  # degenerate split — keep the list as-is
        new_c = self._spawn(np.full(1, self.dim, np.int32), np.zeros(1, np.float32))
        moved = members[lab == 1]
        stay = members[lab == 0]
        self._lists[c] = stay.tolist()
        self._lists[new_c] = moved.tolist()
        self._assign[moved] = new_c
        # rebuild sums for both halves from member rows (exact means)
        for cid, sel in ((c, lab == 0), (new_c, lab == 1)):
            self._sums[cid] = 0.0
            flat_i = m_idx[sel].ravel()
            flat_v = m_val[sel].ravel()
            keep = flat_i < self.dim
            np.add.at(self._sums[cid], flat_i[keep], flat_v[keep])
            self._counts[cid] = int(sel.sum())
        self._renorm([c, new_c])
        self.splits += 1

    def route(self, q_idx: np.ndarray, q_val: np.ndarray, nprobe: int) -> np.ndarray:
        """Candidate slots for one sparse query: the members of its
        ``nprobe`` nearest centroid lists."""
        if self.c == 0:
            return np.zeros(0, np.int64)
        sims = self._scores(q_idx[None, :], q_val[None, :])[0]
        order = np.argsort(-sims)[: max(1, nprobe)]
        cands: List[int] = []
        for c in order.tolist():
            cands.extend(self._lists[c])
        return np.asarray(cands, np.int64)

    def seed_from_labels(
        self,
        labels: np.ndarray,
        rows_fn: Callable[[np.ndarray], Tuple[np.ndarray, np.ndarray]],
    ) -> None:
        """Rebuild the partition from mining labels: one centroid per
        cluster, exact member means — the incremental ``ClusterState``
        (ops/incremental.py) exported as the coarse quantizer."""
        from kakveda_tpu.ops.incremental import centroids_from_sparse

        n = len(labels)
        cents, counts, lists, assign = centroids_from_sparse(
            labels, rows_fn, self.dim
        )
        self.c = len(cents)
        self._cent = cents
        self._sums = cents * counts[:, None].astype(np.float32)
        self._counts = counts
        self._lists = lists
        self._grow_assign(n)
        self._assign[:n] = assign
        self._n = max(self._n, n)
        self._assigned = int((self._assign[: self._n] >= 0).sum())

    # -- snapshot ---------------------------------------------------------

    def export_state(self) -> Tuple[np.ndarray, np.ndarray]:
        """(centroids [C, dim] f32, assignment [n] int32) — everything a
        restore needs (lists/counts/sums re-derive from the assignment)."""
        return self._cent[: self.c].copy(), self._assign[: self.n_rows].copy()

    def restore_state(self, cent: np.ndarray, assign: np.ndarray) -> None:
        n, c = len(assign), len(cent)
        if c and (cent.shape[1] != self.dim or assign.max(initial=-1) >= c):
            raise ValueError("router state shape mismatch")
        self.c = c
        self._cent = cent.astype(np.float32).copy()
        self._counts = np.bincount(assign[assign >= 0], minlength=c).astype(np.int64)
        self._sums = self._cent * np.maximum(self._counts, 1)[:, None].astype(np.float32)
        self._lists = [[] for _ in range(c)]
        for s, a in enumerate(assign.tolist()):
            if a >= 0:
                self._lists[a].append(s)
        self._assign = assign.astype(np.int32).copy()
        self._n = n
        self._assigned = int((assign >= 0).sum())


# ---------------------------------------------------------------------------
# facade
# ---------------------------------------------------------------------------


class TieredIndex:
    """The one host-side abstraction the GFKB (and bench) talk to.

    Owns the warm tier, the optional cold tier and the router; the
    device-hot tier stays in the GFKB (it owns the jax buffers) — this
    class only knows the hot boundary so routed matches can exclude the
    slots the device already answered exactly."""

    def __init__(self, dim: int, config: Optional[TierConfig] = None,
                 data_dir: Optional[Path] = None):
        self.cfg = config or TierConfig()
        self.dim = dim
        self.lock = sanitize.named_lock("TieredIndex.lock", kind="rlock")
        self.scorer = NativeScorer()
        self.warm = WarmTier(dim, self.scorer)
        self._data_dir = Path(data_dir) if data_dir is not None else None
        self.cold: Optional[ColdTier] = None
        self.router = CoarseRouter(dim, self.cfg.max_list) if self.cfg.tiered else None
        self.n = 0  # total rows stored (dense slots [0, n))
        # Spill overflow that could not reach cold stays warm past the
        # budget; tracked so info()/gauges stay honest.
        self._warm_overflow = 0
        self._fault_spill = _faults.site("gfkb.tier_spill")
        self._fault_route = _faults.site("gfkb.tier_route")
        reg = _metrics.get_registry()
        g_rows = reg.gauge(
            "kakveda_gfkb_tier_rows",
            "Rows resident per GFKB storage tier (hot = device, warm = "
            "host RAM, cold = disk shards)", ("tier",),
        )
        self._g_rows = {t: g_rows.labels(tier=t) for t in ("hot", "warm", "cold")}
        c_route = reg.counter(
            "kakveda_gfkb_tier_route_total",
            "Tiered match queries by serving mode (routed = IVF candidate "
            "lists, exact = full scan, fault_exact = routing fault degraded "
            "to the exact scan)", ("mode",),
        )
        self._c_route = {m: c_route.labels(mode=m) for m in ("routed", "exact", "fault_exact")}
        c_spill = reg.counter(
            "kakveda_gfkb_tier_spill_total",
            "Rows spilled past the warm budget by outcome (cold = landed "
            "on disk, warm_fallback = spill failed, row kept in RAM)",
            ("outcome",),
        )
        self._c_spill = {o: c_spill.labels(outcome=o) for o in ("cold", "warm_fallback")}
        self._c_promote = reg.counter(
            "kakveda_gfkb_tier_promote_total",
            "Cold rows paged in and promoted to the in-RAM LRU",
        )
        self._h_cands = reg.histogram(
            "kakveda_gfkb_route_candidates",
            "Candidate slots gathered per routed tiered query",
        )

    # -- tier boundaries --------------------------------------------------

    @property
    def hot_n(self) -> int:
        """Slots the device tier covers (the GFKB inserts [0, hot_rows))."""
        return min(self.n, self.cfg.hot_rows)

    def _cold_enabled(self) -> bool:
        return self.cfg.tiered and (
            self.cfg.cold_dir is not None or self._data_dir is not None
        )

    def _cold_root(self) -> Path:
        return self.cfg.cold_dir if self.cfg.cold_dir is not None \
            else self._data_dir / "cold"

    def _ensure_cold(self) -> Optional[ColdTier]:
        if self.cold is None and self._cold_enabled():
            self.cold = ColdTier(
                self._cold_root(), self.dim, self.cfg.warm_rows,
                self.cfg.promote_cache, self.scorer,
            )
        return self.cold

    # -- insert -----------------------------------------------------------

    def insert(
        self,
        slots: Sequence[int],
        sp_idx: np.ndarray,
        sp_val: np.ndarray,
        route: bool = True,
    ) -> None:
        """Store one ingest batch: warm (or cold past the warm budget) +
        one router delta update (``route=False`` skips it — snapshot
        restore installs the persisted router state instead). Never
        raises for spill/route trouble — ingest must not fail from the
        storage hierarchy's own paths."""
        with self.lock:
            slots_arr = np.asarray(slots, np.int64)
            if len(slots_arr) == 0:
                return
            W = self.cfg.warm_rows
            warm_sel = slots_arr < W
            cold_sel = ~warm_sel
            if warm_sel.any():
                self.warm.store(slots_arr[warm_sel], sp_idx[warm_sel], sp_val[warm_sel])
            if cold_sel.any():
                self._spill(slots_arr[cold_sel], sp_idx[cold_sel], sp_val[cold_sel])
            self.n = max(self.n, int(slots_arr.max()) + 1)
            if route and self.router is not None:
                try:
                    self._fault_route.fire()
                    self.router.add_batch(slots_arr, sp_idx, sp_val, self._rows_block)
                except Exception as e:  # noqa: BLE001 — routing is derived state
                    log.warning(
                        "router delta update failed (%s: %s); affected rows "
                        "route via the exact scan until reseeded",
                        type(e).__name__, e,
                    )
            self._set_gauges()

    def _spill(self, slots: np.ndarray, sp_idx: np.ndarray, sp_val: np.ndarray) -> None:
        """Cold-append overflow rows; on ANY failure keep them warm (over
        budget beats lost) and count the fallback."""
        cold = self._ensure_cold()
        try:
            self._fault_spill.fire()
            if cold is None:
                raise TierSpillError("no cold tier configured")
            # cold rows must land in slot order with no gaps; slots the
            # shards already hold (snapshot restore / log replay walking
            # over an existing cold store) are skipped idempotently.
            expected = cold.base + cold.n
            done = slots < expected
            if done.any():
                slots = slots[~done]
                sp_idx, sp_val = sp_idx[~done], sp_val[~done]
            if len(slots) == 0:
                return
            if int(slots[0]) != expected or not np.array_equal(
                slots, np.arange(slots[0], slots[0] + len(slots))
            ):
                raise TierSpillError(
                    f"non-contiguous cold append (slot {int(slots[0])}, "
                    f"expected {expected})"
                )
            cold.append(sp_idx, sp_val)
            self._c_spill["cold"].inc(len(slots))
        except Exception as e:  # noqa: BLE001 — never fail the ingest
            log.warning(
                "cold spill failed (%s: %s); keeping %d rows warm over "
                "budget", type(e).__name__, e, len(slots),
            )
            self.warm.store(slots, sp_idx, sp_val)
            self._warm_overflow += len(slots)
            self._c_spill["warm_fallback"].inc(len(slots))

    def _set_gauges(self) -> None:
        # warm = rows resident in host RAM (the hot tier's degraded-mode
        # mirror included — it IS the degraded serving capacity).
        cold_n = self.cold.n if self.cold is not None else 0
        self._g_rows["hot"].set(self.hot_n)
        self._g_rows["warm"].set(min(self.n, self.cfg.warm_rows) + self._warm_overflow)
        self._g_rows["cold"].set(cold_n)

    # -- row access -------------------------------------------------------

    def row(self, slot: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        r = self.warm.row(slot)
        if r is not None:
            return r
        if self.cold is not None and slot >= self.cold.base:
            r = self.cold.row(slot)
            if r is not None:
                self._c_promote.inc()
            return r
        return None

    def _rows_block(self, slots: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """[B, K] fixed-width rows for arbitrary slots (router splits,
        candidate scoring). Warm rows gather in one fancy-index read;
        cold rows gather per shard through the memmap (pages fault in
        only for the candidate rows)."""
        warm_sel = slots < self.cfg.warm_rows
        if warm_sel.all():
            return self.warm.rows_block(slots)
        cold_k = max(
            (sh["k"] for sh in self.cold._shards), default=0
        ) if self.cold is not None else 0
        k = max(self.warm.k, cold_k)
        idx = np.full((len(slots), k), self.dim, np.int32)
        val = np.zeros((len(slots), k), np.float32)
        if warm_sel.any():
            wi, wv = self.warm.rows_block(slots[warm_sel])
            idx[warm_sel, : wi.shape[1]] = wi
            val[warm_sel, : wv.shape[1]] = wv
        rest = ~warm_sel
        if rest.any() and self.cold is not None:
            ci, cv = self.cold.rows_block(slots[rest], k)
            idx[rest] = ci
            val[rest] = cv
            # spill-fallback rows live warm ABOVE the budget; the cold
            # gather returned pads for them — patch from warm storage.
            if self._warm_overflow:
                miss = rest.copy()
                miss[rest] = (ci >= self.dim).all(axis=1)
                if miss.any():
                    wi, wv = self.warm.rows_block(slots[miss])
                    idx[miss, : wi.shape[1]] = wi
                    idx[miss, wi.shape[1] :] = self.dim
                    val[miss, : wv.shape[1]] = wv
                    val[miss, wv.shape[1] :] = 0.0
        return idx, val

    # -- match ------------------------------------------------------------

    def densify_query(self, q_idx: np.ndarray, q_val: np.ndarray) -> np.ndarray:
        """[dim + 1] dense query with a zero at the pad sentinel, so sparse
        gathers score pads as 0."""
        q = np.zeros(self.dim + 1, np.float32)
        np.add.at(q, q_idx, q_val)
        q[self.dim] = 0.0
        return q

    def match_host(
        self,
        q_idx: np.ndarray,
        q_val: np.ndarray,
        k: int,
        *,
        min_slot: int = 0,
        exact: Optional[bool] = None,
    ) -> Tuple[np.ndarray, np.ndarray, str]:
        """Host-tier top-k for ONE sparse query over slots ``[min_slot, n)``.

        Returns ``(scores, slots, mode)`` sorted best-first; ``mode`` is
        ``routed`` / ``exact`` / ``fault_exact`` (what actually served —
        the warn verdict's ``tier`` provenance). ``exact=None`` lets the
        policy decide: routed once the corpus is past :data:`_ROUTE_MIN_ROWS`
        and the router covers it, exact otherwise. A routing failure
        (chaos site ``gfkb.tier_route`` or a real fault) DEGRADES to the
        exact scan — slower, never wrong-but-confident."""
        with self.lock:
            n = self.n
            if n <= min_slot:
                return np.zeros(0, np.float32), np.zeros(0, np.int64), "exact"
            want_routed = (
                exact is False
                or (
                    exact is None
                    and self.router is not None
                    and n - min_slot > _ROUTE_MIN_ROWS
                    and self.router.covers(n)
                )
            )
            if want_routed and self.router is not None:
                try:
                    self._fault_route.fire()
                    cands = self.router.route(q_idx, q_val, self.cfg.nprobe)
                    cands = cands[cands >= min_slot]
                    self._h_cands.observe(float(len(cands)))
                    if len(cands):
                        scores, native = self._score_candidates(q_idx, q_val, cands)
                        order = (
                            _topk_desc(scores, k) if native
                            else np.argsort(-scores)[:k]
                        )
                        self._c_route["routed"].inc()
                        return scores[order], cands[order], "routed"
                    # empty candidate set: fall through to exact (a
                    # confident empty answer would be a silent miss)
                except Exception as e:  # noqa: BLE001 — degrade, never lie
                    log.warning(
                        "tier routing failed (%s: %s); serving this query "
                        "from the exact scan", type(e).__name__, e,
                    )
                    scores, slots = self._exact_topk(q_idx, q_val, k, min_slot)
                    self._c_route["fault_exact"].inc()
                    return scores, slots, "fault_exact"
            scores, slots = self._exact_topk(q_idx, q_val, k, min_slot)
            self._c_route["exact"].inc()
            return scores, slots, "exact"

    def _gather_scores_native(self, qd: np.ndarray, cands: np.ndarray) -> Optional[np.ndarray]:
        """Native zero-copy candidate scoring: split candidate slots into
        (warm arrays, per-cold-shard) segments of in-range row ids and
        score them IN PLACE — no [B, K] materialization, cold pages fault
        in during the C scan. Candidates are sorted ONCE up front: tier/
        shard segmentation becomes O(shards) searchsorted cuts instead of
        per-shard boolean masks over the whole list, and the kernel walks
        each mapping monotonically (measurably faster than a random-order
        gather on a latency-bound sweep). None (→ the materialized path)
        when the scorer is off, a segment fails, or warm-overflow rows
        exist (they need the rows_block patch logic — a degraded/chaos
        condition where the routed hot path no longer matters)."""
        sc = self.scorer
        if sc is None or not sc.enabled or self._warm_overflow:
            return None
        m = len(cands)
        order = np.argsort(cands)
        srt = cands[order]
        out_sorted = np.zeros(m, np.float32)
        segments: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        spans: List[Tuple[int, int]] = []
        # warm segment: rows below the warm boundary AND present in the
        # store (not-yet-grown slots stay 0, like the materialized gather)
        n_warm = int(np.searchsorted(
            srt, min(self.cfg.warm_rows, len(self.warm._idx))
        ))
        if n_warm:
            segments.append((self.warm._idx, self.warm._val, srt[:n_warm]))
            spans.append((0, n_warm))
        if int(np.searchsorted(srt, self.cfg.warm_rows)) < m:
            if self.cold is None:
                return None  # cold-region slots with no cold tier: let rows_block decide
            base = self.cold.base
            off = 0
            for sh in self.cold._shards:
                a = int(np.searchsorted(srt, base + off))
                z = int(np.searchsorted(srt, base + off + sh["rows"]))
                if z > a:
                    segments.append(
                        (sh["idx"], sh["val"],
                         (srt[a:z] - (base + off)).astype(np.int64))
                    )
                    spans.append((a, z))
                off += sh["rows"]
            # slots past every shard (not yet spilled) stay 0 — the same
            # all-pad score the materialized gather returns for them
        outs = sc.score_gather_segments(qd, segments, self.dim)
        if outs is None:
            return None
        for (a, z), res in zip(spans, outs):
            out_sorted[a:z] = res
        out = np.empty(m, np.float32)
        out[order] = out_sorted
        return out

    def _score_candidates(self, q_idx, q_val, cands: np.ndarray) -> Tuple[np.ndarray, bool]:
        """Score routed candidates; returns ``(scores, native)``.

        ``native`` tells the caller whether a native path served — those
        callers may then take the cheaper partition top-k, while the
        numpy fallback keeps the historical full argsort so
        ``KAKVEDA_NATIVE=0`` ordering stays bit-for-bit."""
        qd = self.densify_query(q_idx, q_val)
        out = self._gather_scores_native(qd, cands)
        if out is not None:
            return out, True
        idx, val = self._rows_block(cands)
        out = self.scorer.score_block(qd, idx, val, self.dim, "ivf")
        if out is not None:
            return out, True
        return (
            (qd[np.minimum(idx, self.dim)] * val).sum(axis=1).astype(np.float32),
            False,
        )

    def match_host_batch(
        self,
        q_idx: np.ndarray,
        q_val: np.ndarray,
        k: int,
        *,
        min_slot: int = 0,
        exact: Optional[bool] = None,
    ) -> List[Tuple[np.ndarray, np.ndarray, str]]:
        """Batched :meth:`match_host`: one ``(scores, slots, mode)`` per
        query row, same per-query contract (mode provenance, routing
        fault degrades THAT query to the exact scan).

        The batch form exists for the shared scoring plan: all routed
        queries' candidate lists are deduplicated into ONE row gather
        (the cold tier's coalesced read plan runs once per batch, not per
        query) and ONE thread-pooled native scoring call over the
        concatenated candidates. The numpy fallback scores per query over
        the same gathered rows — identical values to the per-query path,
        so ``KAKVEDA_NATIVE=0`` keeps bit-for-bit parity."""
        b = q_idx.shape[0]
        with self.lock:
            n = self.n
            if n <= min_slot:
                return [
                    (np.zeros(0, np.float32), np.zeros(0, np.int64), "exact")
                ] * b
            want_routed = (
                exact is False
                or (
                    exact is None
                    and self.router is not None
                    and n - min_slot > _ROUTE_MIN_ROWS
                    and self.router.covers(n)
                )
            )
            results: List[Optional[Tuple[np.ndarray, np.ndarray, str]]] = [None] * b
            routed_q: List[int] = []
            cand_lists: List[np.ndarray] = []
            if want_routed and self.router is not None:
                for i in range(b):
                    try:
                        self._fault_route.fire()
                        cands = self.router.route(q_idx[i], q_val[i], self.cfg.nprobe)
                        cands = cands[cands >= min_slot]
                        self._h_cands.observe(float(len(cands)))
                        if len(cands):
                            routed_q.append(i)
                            cand_lists.append(cands)
                        # empty candidate set falls through to exact below
                    except Exception as e:  # noqa: BLE001 — degrade, never lie
                        log.warning(
                            "tier routing failed (%s: %s); serving this query "
                            "from the exact scan", type(e).__name__, e,
                        )
                        scores, slots = self._exact_topk(q_idx[i], q_val[i], k, min_slot)
                        self._c_route["fault_exact"].inc()
                        results[i] = (scores, slots, "fault_exact")
            if routed_q:
                # Native plan: zero-copy gather-scoring per query (the
                # shared materialized gather below exists for the numpy
                # fallback, where the row copy is the dominant cost worth
                # amortizing across the batch). All-or-nothing: a failed
                # query discards the native attempt so the fallback plan
                # runs over the whole batch unchanged.
                native_res: List[Tuple[np.ndarray, np.ndarray, str]] = []
                for j, i in enumerate(routed_q):
                    qd1 = self.densify_query(q_idx[i], q_val[i])
                    scores = self._gather_scores_native(qd1, cand_lists[j])
                    if scores is None:
                        native_res = []
                        break
                    order = _topk_desc(scores, k)
                    native_res.append(
                        (scores[order], cand_lists[j][order], "routed")
                    )
                if native_res:
                    for j, i in enumerate(routed_q):
                        self._c_route["routed"].inc()
                        results[i] = native_res[j]
                    routed_q = []
            if routed_q:
                counts = np.asarray([len(c) for c in cand_lists], np.int64)
                offsets = np.zeros(len(cand_lists) + 1, np.int64)
                np.cumsum(counts, out=offsets[1:])
                flat = np.concatenate(cand_lists)
                uniq, inv = np.unique(flat, return_inverse=True)
                u_idx, u_val = self._rows_block(uniq)
                cat_idx, cat_val = u_idx[inv], u_val[inv]
                qd = np.stack(
                    [self.densify_query(q_idx[i], q_val[i]) for i in routed_q]
                )
                scores_flat = self.scorer.score_candidates(
                    qd, cat_idx, cat_val, offsets, self.dim
                )
                if scores_flat is None:
                    scores_flat = np.empty(int(offsets[-1]), np.float32)
                    for j in range(len(routed_q)):
                        sl = slice(int(offsets[j]), int(offsets[j + 1]))
                        scores_flat[sl] = (
                            qd[j][np.minimum(cat_idx[sl], self.dim)] * cat_val[sl]
                        ).sum(axis=1).astype(np.float32)
                for j, i in enumerate(routed_q):
                    sl = slice(int(offsets[j]), int(offsets[j + 1]))
                    scores = scores_flat[sl]
                    order = np.argsort(-scores)[:k]
                    self._c_route["routed"].inc()
                    results[i] = (scores[order], cand_lists[j][order], "routed")
            for i in range(b):
                if results[i] is None:
                    scores, slots = self._exact_topk(q_idx[i], q_val[i], k, min_slot)
                    self._c_route["exact"].inc()
                    results[i] = (scores, slots, "exact")
            return results  # type: ignore[return-value]

    def _exact_topk(self, q_idx, q_val, k: int, min_slot: int) -> Tuple[np.ndarray, np.ndarray]:
        n = self.n
        # Warm postings cover every warm-resident slot — including any
        # spill-fallback rows parked above the budget; cold-region slots
        # are all-pad in the warm arrays and score 0 there.
        scores = self.warm.score_all(q_idx, q_val, n)
        if self.cold is not None and self.cold.n:
            qd = self.densify_query(q_idx, q_val)
            b = self.cold.base
            scores[b : b + self.cold.n] = self.cold.score_all(qd)[: max(0, n - b)]
        if min_slot:
            scores[:min_slot] = -np.inf
        order = np.argsort(-scores)[:k]
        return scores[order].astype(np.float32), order.astype(np.int64)

    # -- mining export ----------------------------------------------------

    def reseed_router(self, labels: np.ndarray) -> bool:
        """Re-derive the coarse partition from mining labels (the
        ``ClusterState`` export). Failure leaves the old router — routing
        is derived state; it degrades, it never breaks ingest/match."""
        if self.router is None:
            return False
        with self.lock:
            if len(labels) < self.n:
                return False
            try:
                self.router.seed_from_labels(
                    np.asarray(labels[: self.n], np.int32), self._rows_block
                )
                return True
            except Exception as e:  # noqa: BLE001
                log.warning(
                    "router reseed from mining labels failed (%s: %s); "
                    "keeping the online partition", type(e).__name__, e,
                )
                return False

    # -- snapshot ---------------------------------------------------------

    def export_router_state(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        if self.router is None or not self.router.covers(self.n):
            return None
        with self.lock:
            return self.router.export_state()

    def restore_router_state(self, cent: np.ndarray, assign: np.ndarray) -> None:
        if self.router is None:
            return
        with self.lock:
            self.router.restore_state(cent, assign)

    def rebuild_router(self, chunk: int = 1 << 14) -> None:
        """Re-assign every stored row from scratch (restore-degrade path
        after a centroid checksum mismatch). O(N·C·nnz) host work."""
        if self.router is None:
            return
        with self.lock:
            self.router = CoarseRouter(self.dim, self.cfg.max_list)
            for s in range(0, self.n, chunk):
                e = min(self.n, s + chunk)
                slots = np.arange(s, e, dtype=np.int64)
                idx, val = self._rows_block(slots)
                self.router.add_batch(slots, idx, val, self._rows_block)

    # -- lifecycle --------------------------------------------------------

    def recently_promoted_slots(self) -> set:
        """Global slots currently in the cold tier's promote-LRU — rows a
        recent query paged in from disk. This is the tiers' touch evidence:
        row aging (GFKB.age_rows) exempts these slots, because a record's
        ``updated_at`` only moves on WRITES and a cold row that live queries
        keep paging in is working set, whatever its timestamp says."""
        with self.lock:
            if self.cold is None:
                return set()
            return set(self.cold.promoted.keys())

    def reset(self) -> None:
        """Drop everything (GFKB.reload — the append log was rewritten;
        cold shards describe pre-rewrite slots and must go with it)."""
        with self.lock:
            self.warm = WarmTier(self.dim, self.scorer)
            self.router = CoarseRouter(self.dim, self.cfg.max_list) if self.cfg.tiered else None
            self.n = 0
            self._warm_overflow = 0
            if self.cold is not None:
                shutil.rmtree(self.cold.root, ignore_errors=True)
                self.cold = None
            self._set_gauges()

    def info(self) -> dict:
        with self.lock:
            cold_n = self.cold.n if self.cold is not None else 0
            return {
                "tiered": self.cfg.tiered,
                "native": self.scorer.enabled,
                "rows": self.n,
                "hot": self.hot_n,
                "warm": min(self.n, self.cfg.warm_rows) + self._warm_overflow,
                "cold": cold_n,
                "warm_overflow": self._warm_overflow,
                "centroids": self.router.c if self.router is not None else 0,
                "splits": self.router.splits if self.router is not None else 0,
                "nprobe": self.cfg.nprobe,
            }
