"""Model runtimes: deterministic stub, in-tree JAX Llama, Ollama-compat client."""

from kakveda_tpu.models.runtime import GenerateResult, ModelRuntime, StubRuntime, get_runtime  # noqa: F401
