"""Model runtimes: deterministic stub, the in-tree JAX transformer core
(eight HF families — Llama/Mistral/Qwen2+3/Gemma+2/Phi-3/Mixtral — over
dp/cp/tp/ep/pp), and an Ollama-compat client.

Heavy imports stay lazy: importing this package must not initialize jax
(the stub tier and the HTTP layer run without it)."""

from kakveda_tpu.models.runtime import (  # noqa: F401
    GenerateResult,
    HBMBudgetError,
    ModelRuntime,
    MultiModelRuntime,
    StubRuntime,
    UnknownModelError,
    get_runtime,
)
