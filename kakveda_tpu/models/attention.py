"""Fused attention kernels for the Llama runtime.

The reference never runs a model forward itself (it HTTP-calls Ollama;
reference: services/dashboard/app.py:1182-1258) — this module is the
TPU-native replacement's hot path. Two tiers over one contract:

``gqa_cache_attention(q, k, v, pos0, kv_valid)``
    q            [B, S, H, D]    queries (prefill chunk or decode step)
    k, v         [B, KV, L, D]   KV cache, head-major so each head's rows
                                 are contiguous for DMA streaming
    pos0         scalar int32    cache slot of q[:, 0] (cache["pos"])
    kv_valid     [B, L] bool     optional per-slot validity (left-pad batching)
    -> [B, S, H, D]

* **XLA path** (`_gqa_xla`): grouped einsum that keeps the GQA group axis
  explicit — K/V are *never* repeated to H heads, so the cache is read once
  per step instead of ``n_rep`` (=8 for Llama-3/TinyLlama) times. At 1B
  scale, repeat-materialization was ~1.5 GB of HBM traffic per decode step
  — more than the weights.
* **Pallas flash path** (`flash_gqa_cache`): blockwise online-softmax
  attention (flash attention) — scores live only in VMEM tiles, never a
  ``[B, H, S, L]`` f32 HBM tensor. GQA-native: the group's ``R`` query
  heads are folded into the q-row axis so each (batch, kv-head) program is
  one ``[S·R, D] @ [D, L_blk]`` MXU matmul per cache tile. Dispatched for
  long-context inference shapes (see `_flash_wins`) where the XLA path's
  transient score scratch gets into the gigabytes; at short serving shapes
  the batched einsum is faster because the Pallas grid serializes over
  B·KV small programs. Training always uses the XLA path (it
  differentiates).

Both paths produce identical logits (tested to ~1e-5 in f32; see
tests/test_attention.py). One documented don't-care divergence: a query row
with NO visible slot (a left-pad position earlier than every valid cache
slot) softmaxes to a uniform average in the XLA paths but emits zeros from
the flash kernel; such rows are pad positions whose activations can't reach
any real token's logits (their K/V slots are themselves masked).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# XLA grouped path (differentiable; CPU + fallback)
# ---------------------------------------------------------------------------


def _gqa_xla(q, k, v, pos0, kv_valid, window: int = 0, softcap: float = 0.0, full_mask=None):
    b, s, h, d = q.shape
    _, kv, l, _ = k.shape
    r = h // kv
    scale = d**-0.5
    # [B,S,H,D] -> [B,KV,S,R,D]; group axis stays explicit so XLA batches
    # the matmul over KV instead of materializing repeated K/V.
    q5 = q.reshape(b, s, kv, r, d).transpose(0, 2, 1, 3, 4)
    scores = jnp.einsum("bgsrd,bgld->bgsrl", q5, k).astype(jnp.float32) * scale
    if softcap:
        # Gemma-2 attention-logit softcapping: cap·tanh(s/cap), pre-mask.
        scores = softcap * jnp.tanh(scores / softcap)
    if full_mask is not None:
        # Caller-computed [B, S, L] mask (per-slot query positions — the
        # speculative serving chunk); replaces causal/window/kv_valid.
        scores = jnp.where(full_mask[:, None, :, None, :], scores, _NEG_INF)
    else:
        q_pos = pos0 + jnp.arange(s)
        l_pos = jnp.arange(l)
        mask = q_pos[:, None] >= l_pos[None, :]  # [S, L]
        if window:
            # Sliding-window attention (Mistral): keep iff q_pos − l_pos < window.
            mask &= (q_pos[:, None] - l_pos[None, :]) < window
        if kv_valid is not None:
            full = mask[None, :, :] & kv_valid[:, None, :]  # [B, S, L]
            scores = jnp.where(full[:, None, :, None, :], scores, _NEG_INF)
        else:
            scores = jnp.where(mask[None, None, :, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bgsrl,bgld->bgsrd", probs, v)
    return out.transpose(0, 2, 1, 3, 4).reshape(b, s, h, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash kernel
# ---------------------------------------------------------------------------


def _flash_body(
    pos0_ref,
    q,  # [q_blk, D]
    k,  # [l_blk, D] — already dequantized
    v,  # [l_blk, D]
    valid_ref,
    o_ref,
    m_scr,
    l_scr,
    acc_scr,
    *,
    r: int,
    q_blk: int,
    l_blk: int,
    n_l: int,
    scale: float,
    window: int,
):
    lb = pl.program_id(2)
    qb = pl.program_id(1)

    @pl.when(lb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # [q_blk, l_blk] scores on the MXU, f32 accumulation.
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale

    # Causal + validity mask. Query rows fold (seq, group-head): row i is
    # sequence position (qb*q_blk + i) // r.
    rows = jax.lax.broadcasted_iota(jnp.int32, (q_blk, l_blk), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (q_blk, l_blk), 1)
    q_pos = pos0_ref[0, 0] + (qb * q_blk + rows) // r
    l_pos = lb * l_blk + cols
    keep = (q_pos >= l_pos) & (valid_ref[0, 0][None, :] > 0.5)
    if window:
        keep &= (q_pos - l_pos) < window
    s = jnp.where(keep, s, _NEG_INF)

    m_prev = m_scr[:, :1]  # [q_blk, 1] (all lanes equal; col 0 is truth)
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # Re-mask after exp: on an all-masked tile, s - m_new == 0 would exp to 1.
    p = jnp.where(keep, jnp.exp(s - m_new), 0.0)
    corr = jnp.exp(m_prev - m_new)  # [q_blk, 1]
    l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v.dtype),
        v,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_scr[:] = acc_scr[:] * corr + pv
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(lb == n_l - 1)
    def _emit():
        denom = jnp.maximum(l_scr[:, :1], 1e-20)
        o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)


def _flash_kernel(
    pos0_ref,  # SMEM [1, 1]
    q_ref,  # VMEM [1, q_blk, D]
    k_ref,  # VMEM [1, l_blk, D]
    v_ref,  # VMEM [1, l_blk, D]
    valid_ref,  # VMEM [1, 1, l_blk] f32
    o_ref,  # VMEM [1, q_blk, D]
    m_scr,  # VMEM [q_blk, 128] f32
    l_scr,  # VMEM [q_blk, 128] f32
    acc_scr,  # VMEM [q_blk, D] f32
    **kw,
):
    _flash_body(
        pos0_ref, q_ref[0], k_ref[0], v_ref[0], valid_ref, o_ref,
        m_scr, l_scr, acc_scr, **kw,
    )


def _flash_kernel_kv8(
    pos0_ref,  # SMEM [1, 1]
    q_ref,  # VMEM [1, q_blk, D]
    k_ref,  # VMEM [1, l_blk, D] int8
    ks_ref,  # VMEM [1, 1, l_blk] f32 per-row scales
    v_ref,  # VMEM [1, l_blk, D] int8
    vs_ref,  # VMEM [1, 1, l_blk] f32
    valid_ref,  # VMEM [1, 1, l_blk] f32
    o_ref,
    m_scr,
    l_scr,
    acc_scr,
    **kw,
):
    """int8-KV variant: the cache tiles DMA from HBM as int8 (+1 f32
    scale per head_dim row) — ~½ the bandwidth of bf16 tiles on the
    stream that binds long-context decode — and dequantize in VMEM.
    The dequant replicates `_kv_dequant`'s EXACT op order (cast scale to
    the compute dtype FIRST, multiply in that dtype): under bf16 a
    multiply-in-f32-then-round differs in the last bit from
    round-scale-then-multiply, which would make flash and XLA-fallback
    logits diverge per element."""
    dt = q_ref.dtype
    kd = k_ref[0].astype(dt) * ks_ref[0, 0].astype(dt)[:, None]
    vd = v_ref[0].astype(dt) * vs_ref[0, 0].astype(dt)[:, None]
    _flash_body(
        pos0_ref, q_ref[0], kd, vd, valid_ref, o_ref, m_scr, l_scr, acc_scr, **kw,
    )


@functools.partial(jax.jit, static_argnames=("q_blk", "l_blk", "window", "interpret"))
def flash_gqa_cache(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, KV, L, D] (cfg.dtype, or int8 with k_scale)
    v: jax.Array,  # [B, KV, L, D]
    pos0: jax.Array,
    kv_valid: jax.Array | None,
    *,
    k_scale: jax.Array | None = None,  # [B, KV, L] f32 — int8-cache rows
    v_scale: jax.Array | None = None,
    q_blk: int = 512,
    l_blk: int = 512,
    window: int = 0,
    interpret: bool = False,
) -> jax.Array:
    b, s, h, d = q.shape
    _, kv, l, _ = k.shape
    r = h // kv
    sr = s * r
    # Pad the folded q-row axis to the f32 sublane multiple: decode shapes
    # (s=1, r<8) otherwise can't tile at all. Padded rows compute
    # throwaway attention (their q_pos lands past the real rows; denom is
    # floor-guarded) and are sliced off the output.
    sr_pad = -(-sr // 8) * 8
    q_blk = min(q_blk, sr_pad)
    l_blk = min(l_blk, l)
    if sr_pad % q_blk or l % l_blk:
        raise ValueError(f"flash layout: SR={sr_pad} q_blk={q_blk} L={l} l_blk={l_blk}")
    kv8 = k_scale is not None

    # Fold (seq, group-head) into the q-row axis: [B*KV, S*R, D]. With an
    # int8 cache the q tiles keep their own dtype (casting q to int8 would
    # destroy it); the kernel dequantizes K/V tiles in VMEM.
    qf = (
        q.reshape(b, s, kv, r, d)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b * kv, sr, d)
    )
    if not kv8:
        qf = qf.astype(k.dtype)
    if sr_pad != sr:
        qf = jnp.pad(qf, ((0, 0), (0, sr_pad - sr), (0, 0)))
    kf = k.reshape(b * kv, l, d)
    vf = v.reshape(b * kv, l, d)
    valid = (
        jnp.ones((b, 1, l), jnp.float32)
        if kv_valid is None
        else kv_valid.astype(jnp.float32).reshape(b, 1, l)
    )
    pos = jnp.asarray(pos0, jnp.int32).reshape(1, 1)
    n_q = sr_pad // q_blk
    n_l = l // l_blk

    smem_spec = pl.BlockSpec((1, 1), lambda bg, qb, lb: (0, 0), memory_space=pltpu.SMEM)
    q_spec = pl.BlockSpec((1, q_blk, d), lambda bg, qb, lb: (bg, qb, 0), memory_space=pltpu.VMEM)
    l_spec = pl.BlockSpec((1, l_blk, d), lambda bg, qb, lb: (bg, lb, 0), memory_space=pltpu.VMEM)
    sc_spec = pl.BlockSpec((1, 1, l_blk), lambda bg, qb, lb: (bg, 0, lb), memory_space=pltpu.VMEM)
    valid_spec = pl.BlockSpec(
        (1, 1, l_blk), lambda bg, qb, lb, _kv=kv: (bg // _kv, 0, lb), memory_space=pltpu.VMEM
    )
    kw = dict(r=r, q_blk=q_blk, l_blk=l_blk, n_l=n_l, scale=d**-0.5, window=window)
    if kv8:
        kernel = functools.partial(_flash_kernel_kv8, **kw)
        in_specs = [smem_spec, q_spec, l_spec, sc_spec, l_spec, sc_spec, valid_spec]
        operands = (
            pos, qf, kf, k_scale.reshape(b * kv, 1, l),
            vf, v_scale.reshape(b * kv, 1, l), valid,
        )
        kv_bytes = 2 * l * (d + 4)  # int8 values + f32 scales
    else:
        kernel = functools.partial(_flash_kernel, **kw)
        in_specs = [smem_spec, q_spec, l_spec, l_spec, valid_spec]
        operands = (pos, qf, kf, vf, valid)
        kv_bytes = 2 * l * d * k.dtype.itemsize

    out = pl.pallas_call(
        kernel,
        grid=(b * kv, n_q, n_l),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, q_blk, d), lambda bg, qb, lb: (bg, qb, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((b * kv, sr_pad, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((q_blk, 128), jnp.float32),
            pltpu.VMEM((q_blk, 128), jnp.float32),
            pltpu.VMEM((q_blk, d), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * b * kv * sr * l * d,
            bytes_accessed=b * kv * (sr * d * q.dtype.itemsize + kv_bytes),
            transcendentals=b * kv * sr * l,
        ),
        interpret=interpret,
    )(*operands)

    # [B*KV, S*R(+pad), D] -> [B, S, H, D]
    if sr_pad != sr:
        out = out[:, :sr]
    return (
        out.reshape(b, kv, s, r, d).transpose(0, 2, 1, 3, 4).reshape(b, s, h, d)
    ).astype(q.dtype)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def _flash_ok(s: int, h: int, kv: int, l: int, d: int) -> bool:
    """Layout gate: the cache length must tile by the l-block and lanes
    want d % 128 == 0 or d == 64 (Mosaic pads 64-lane tiles acceptably).
    The folded q-row axis (S·R) pads itself to the sublane multiple
    inside flash_gqa_cache, so short decode shapes qualify."""
    return h % kv == 0 and l % 128 == 0 and (d % 128 == 0 or d == 64)


def _flash_wins(s: int, h: int, kv: int, l: int) -> bool:
    """Profitability gate, measured on v5e (see docs/performance.md): the
    Pallas grid serializes over B·KV programs, so at short S·R / short cache
    the batched XLA einsum is faster (its [B,KV,S,R,L] f32 scratch is small
    and transient). Flash wins where that scratch gets big — long-context
    prefill and long caches — and is mandatory where XLA's scratch would
    not fit HBM at all (S and L in the thousands)."""
    r = h // kv
    return (s * r) * l >= 1024 * 2048


def _pick_block(n: int, cap: int, step: int) -> int:
    """Largest divisor of ``n`` that is ≤ cap and a multiple of ``step``."""
    best = step
    c = step
    while c <= min(n, cap):
        if n % c == 0:
            best = c
        c += step
    return best


def gqa_cache_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    pos0: jax.Array,
    kv_valid: jax.Array | None = None,
    *,
    window: int = 0,
    softcap: float = 0.0,
    k_scale: jax.Array | None = None,  # int8 cache: [B, KV, L] per-row scales
    v_scale: jax.Array | None = None,
    use_flash: bool | None = None,
    full_mask: jax.Array | None = None,  # [B, S, L] per-query mask (spec chunks)
) -> jax.Array:
    """Cached GQA attention — dispatches to the Pallas flash kernel on TPU
    (inference shapes that fit its tiling), XLA grouped einsum otherwise.
    ``window`` > 0 applies sliding-window attention (Mistral) in both paths;
    ``softcap`` > 0 (Gemma-2 logit capping) always takes the XLA path.
    With ``k_scale``/``v_scale`` the cache is int8 (cfg.kv_quant): the
    flash path streams the int8 tiles from HBM and dequantizes in VMEM —
    the bandwidth win, on top of the capacity win — while the XLA path
    dequantizes up front (same math, materialized). ``KAKVEDA_FLASH=0``
    forces the XLA path."""
    b, s, h, d = q.shape
    _, kv, l, _ = k.shape

    def _dequant():
        from kakveda_tpu.models.llama import _kv_dequant

        return _kv_dequant(k, k_scale, q.dtype), _kv_dequant(v, v_scale, q.dtype)

    if full_mask is not None or softcap:
        # full_mask: per-slot query positions (the speculative serving
        # chunk) — inexpressible in the flash kernel's scalar-pos0 causal
        # mask, so these shapes take the XLA path. S ≤ k+1 keeps its
        # scratch tiny. softcap likewise always takes the XLA path.
        if k_scale is not None:
            kd, vd = _dequant()
            return _gqa_xla(
                q, kd, vd, pos0, kv_valid, window=window, softcap=softcap, full_mask=full_mask
            )
        return _gqa_xla(
            q, k, v, pos0, kv_valid, window=window, softcap=softcap, full_mask=full_mask
        )
    if use_flash is None:
        from kakveda_tpu.ops.device import is_tpu_backend

        env = os.environ.get("KAKVEDA_FLASH", "auto")
        use_flash = (
            env != "0"
            and is_tpu_backend()
            and _flash_ok(s, h, kv, l, d)
            # int8 caches prefer the kernel wherever the shape tiles: the
            # XLA path must materialize a full bf16 dequant copy of the
            # cache (write + re-read through HBM — MORE traffic than a
            # plain bf16 cache), while the kernel streams int8 and
            # expands in VMEM. For bf16 caches the measured profitability
            # gate applies.
            and (env == "1" or k_scale is not None or _flash_wins(s, h, kv, l))
        )
    if use_flash:
        r = h // kv
        sr = s * r
        return flash_gqa_cache(
            q, k, v, pos0, kv_valid,
            k_scale=k_scale, v_scale=v_scale,
            q_blk=_pick_block(-(-sr // 8) * 8, 512, 8),
            l_blk=_pick_block(l, 512, 128),
            window=window,
        )
    if k_scale is not None:
        kd, vd = _dequant()
        return _gqa_xla(q, kd, vd, pos0, kv_valid, window=window)
    return _gqa_xla(q, k, v, pos0, kv_valid, window=window)
