"""Sampling loop + the `tpu` model runtime over the in-tree Llama.

The decode loop drives ``decode_step`` (KV-cache incremental forward) with
fixed [B, 1] token shapes, so after the first call everything is a warm
compiled program. Greedy or temperature sampling.

``LlamaRuntime`` is the drop-in ``runtime=tpu`` backend
(kakveda_tpu.models.runtime.get_runtime): same GenerateResult meta shape as
the stub/ollama tiers. Without a checkpoint it runs a deterministic
randomly-initialized model — useful for latency/meta plumbing and tests.
Real weights load two ways: ``KAKVEDA_HF_CKPT=/path/to/hf_dir`` converts a
local HF Llama checkpoint + tokenizer in place (models/hf_convert.py, logit
parity tested), or ``KAKVEDA_LLAMA_CKPT`` restores an orbax checkpoint of
the param pytree (the in-tree training path).
"""

from __future__ import annotations

import os
import time
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from kakveda_tpu.models.llama import (
    LlamaConfig,
    Params,
    decode_step,
    init_cache,
    init_params,
    mask_pad_vocab,
)
from kakveda_tpu.models.runtime import GenerateResult
from kakveda_tpu.models.tokenizer import ByteTokenizer
from kakveda_tpu.core import sanitize


@partial(jax.jit, static_argnames=("cfg", "last_only"))
def _decode_jit(params, cfg: LlamaConfig, tokens, cache, last_only=False):
    return decode_step(params, cfg, tokens, cache, last_only=last_only)


def _last_logits(logits: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """[B, S, V] -> [B, V] of the final position, with padded-vocab columns
    masked out so sampling can never emit a token the tokenizer lacks
    (converted checkpoints pad vocab to a TP-friendly multiple)."""
    return mask_pad_vocab(logits[:, -1, :], cfg)


@jax.jit
def _sample_top_p(rng, logits, temperature, top_p):
    """Nucleus sampling: keep the smallest prefix of the probability-sorted
    vocab whose mass reaches ``top_p``, renormalize, sample. Runs entirely
    on device with fixed shapes so the decode loop stays retrace-free."""
    scaled = logits / temperature
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # A token survives if the mass *before* it is < top_p (the first token
    # always survives even when its own probability exceeds top_p).
    keep_sorted = (cum - probs) < top_p
    cutoff = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True)
    masked = jnp.where(scaled >= cutoff, scaled, -jnp.inf)
    return jax.random.categorical(rng, masked, axis=-1)



def _prefill_width(plen: int, chunk: int) -> int:
    """Prompt-window width under chunked prefill: unchanged when the prompt
    fits one chunk (prefill takes the single-shot branch — rounding would
    only widen it), else the next chunk multiple. The ONE place the
    rounding lives: DecodeSession's pack width and the runtime's cache
    sizing must agree on it."""
    if chunk <= 0 or plen <= chunk:
        return plen
    return -(-plen // chunk) * chunk


def _bucket_len(need: int, cap: int) -> int:
    """Power-of-two cache window ≥ need (capped): the window is part of the
    compiled program signature, so exact-fit lengths would recompile for
    every distinct prompt length. Thin wrapper over the ONE blessed bucket
    seam (``ops/knn.pow2_bucket``) with the decode floor/cap semantics."""
    from kakveda_tpu.ops.knn import pow2_bucket

    return pow2_bucket(need, floor=64, cap=cap)


def _pack_prompts(prompts: list[list[int]], ml: int, plen: Optional[int] = None):
    """Left-pad a ragged prompt batch into the shared convention used by
    every batched decode path: (tokens [B, plen] i32, kv_valid [B, ml]
    bool, pos_offset [B] i32, plen). Sequence i's real tokens occupy
    columns [off_i, plen); its cache rows [off_i, …) are valid and its
    RoPE positions are slot − off_i. An explicit ``plen`` (≥ the longest
    prompt) widens the left padding — chunked prefill uses it to round
    the prompt window to a chunk multiple."""
    import numpy as onp

    plen = max(plen or 0, max(len(p) for p in prompts))
    toks = onp.zeros((len(prompts), plen), onp.int32)
    valid = onp.zeros((len(prompts), ml), bool)
    offsets = onp.zeros((len(prompts),), onp.int32)
    for i, p in enumerate(prompts):
        off = plen - len(p)
        toks[i, off:] = p
        offsets[i] = off
        valid[i, off:] = True  # real prompt slots + all future decode slots
    return toks, valid, offsets, plen


def generate_tokens(
    params: Params,
    cfg: LlamaConfig,
    prompt_ids: list[int],
    *,
    max_new_tokens: int = 64,
    temperature: float = 0.0,
    top_p: float = 1.0,
    rng: Optional[jax.Array] = None,
    eos_id: Optional[int] = None,
    max_len: Optional[int] = None,
) -> list[int]:
    """Autoregressive decode; returns only the newly generated ids."""
    if max_len is None:
        ml = _bucket_len(len(prompt_ids) + max_new_tokens + 1, cfg.max_seq_len)
    else:
        ml = max_len
    cache = init_cache(cfg, batch=1, max_len=ml)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    prompt = jnp.asarray([prompt_ids], jnp.int32)
    logits, cache = _decode_jit(params, cfg, prompt, cache, last_only=True)
    last = _last_logits(logits, cfg)

    out: list[int] = []
    for _ in range(max_new_tokens):
        if temperature > 0.0:
            rng, sub = jax.random.split(rng)
            if top_p < 1.0:
                nxt = _sample_top_p(sub, last, temperature, top_p)
            else:
                nxt = jax.random.categorical(sub, last / temperature, axis=-1)
        else:
            nxt = jnp.argmax(last, axis=-1)
        tok = int(nxt[0])
        if eos_id is not None and tok == eos_id:
            break
        out.append(tok)
        if len(prompt_ids) + len(out) >= ml:
            break
        logits, cache = _decode_jit(params, cfg, nxt[:, None].astype(jnp.int32), cache)
        last = _last_logits(logits, cfg)
    return out


@partial(jax.jit, static_argnames=("cfg", "last_only"))
def _decode_batch_jit(params, cfg: LlamaConfig, tokens, cache, kv_valid, pos_offset, last_only=False):
    return decode_step(
        params, cfg, tokens, cache, kv_valid=kv_valid, pos_offset=pos_offset, last_only=last_only
    )


def generate_tokens_batch(
    params: Params,
    cfg: LlamaConfig,
    prompts: list[list[int]],
    *,
    max_new_tokens: int = 64,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_id: Optional[int] = None,
) -> list[list[int]]:
    """Batched autoregressive decode over variable-length prompts.

    Left-pads to the longest prompt; per-sequence position offsets and a
    KV-validity mask make each sequence's logits identical to what
    :func:`generate_tokens` would produce for it alone — batching is a
    throughput optimization, not an approximation. The parity caveat: all
    sequences share one cache window sized for the LONGEST prompt, so when
    ``max(len(prompt)) + max_new_tokens + 1`` exceeds ``cfg.max_seq_len``,
    shorter sequences truncate where their solo call (with its smaller
    window) would have kept generating. Used by the LLM classifier tier to
    judge a whole ingest batch in one decode stream.
    """
    import numpy as onp

    bsz = len(prompts)
    if bsz == 0:
        return []
    plen = max(len(p) for p in prompts)
    if plen + 1 > cfg.max_seq_len:
        raise ValueError(
            f"longest prompt ({plen} tokens) leaves no room in the cache window "
            f"(max_seq_len={cfg.max_seq_len}); truncate prompts before calling"
        )
    ml = _bucket_len(plen + max_new_tokens + 1, cfg.max_seq_len)
    toks, valid, offsets, _ = _pack_prompts(prompts, ml)
    cache = init_cache(cfg, batch=bsz, max_len=ml)
    kv_valid = jnp.asarray(valid)
    pos_offset = jnp.asarray(offsets)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    logits, cache = _decode_batch_jit(
        params, cfg, jnp.asarray(toks), cache, kv_valid, pos_offset, last_only=True
    )
    last = _last_logits(logits, cfg)

    outs: list[list[int]] = [[] for _ in range(bsz)]
    done = [False] * bsz
    for _ in range(max_new_tokens):
        if temperature > 0.0:
            rng, sub = jax.random.split(rng)
            nxt = jax.random.categorical(sub, last / temperature, axis=-1)
        else:
            nxt = jnp.argmax(last, axis=-1)
        # One device→host transfer for the whole step — int(t) per sequence
        # would sync B times per decoded token.
        step_toks = onp.asarray(nxt).tolist()
        for i, tok in enumerate(step_toks):
            if done[i]:
                continue
            if eos_id is not None and tok == eos_id:
                done[i] = True
                continue
            outs[i].append(tok)
        if all(done) or plen + max(len(o) for o in outs) >= ml:
            break
        logits, cache = _decode_batch_jit(
            params, cfg, nxt[:, None].astype(jnp.int32), cache, kv_valid, pos_offset
        )
        last = _last_logits(logits, cfg)
    return outs


@partial(jax.jit, static_argnames=("cfg", "n_steps", "greedy"))
def _decode_chunk_jit(
    params,
    cfg: LlamaConfig,
    last,  # [B, V] logits of the previous position (vocab-masked)
    cache,
    kv_valid,
    pos_offset,
    rng,
    temperature,
    n_steps: int,
    greedy: bool,
):
    """``n_steps`` sampled decode steps as one compiled scan, resumable:
    returns (tokens [B, n_steps], last, cache, rng) so the caller can chain
    chunks. Chunked dispatch is what lets pre-flight warn batches interleave
    with generation on the same chip — a whole-generation program is a
    multi-hundred-ms device-queue block (SURVEY §7 'interleaving generate
    steps with match batches')."""

    def body(carry, _):
        last, cache, rng = carry
        if greedy:
            nxt = jnp.argmax(last, axis=-1)
        else:
            rng, sub = jax.random.split(rng)
            nxt = jax.random.categorical(sub, last / temperature, axis=-1)
        logits, cache = decode_step(
            params, cfg, nxt[:, None].astype(jnp.int32), cache,
            kv_valid=kv_valid, pos_offset=pos_offset,
        )
        nl = mask_pad_vocab(logits[:, -1, :], cfg)
        return (nl, cache, rng), nxt

    (last, cache, rng), toks = jax.lax.scan(body, (last, cache, rng), None, length=n_steps)
    return toks.T, last, cache, rng  # toks: [B, n_steps]


@partial(jax.jit, static_argnames=("cfg",))
def _prefill_jit(params, cfg: LlamaConfig, prompt, cache, kv_valid, pos_offset, seq_total=None):
    logits, cache = decode_step(
        params, cfg, prompt, cache, kv_valid=kv_valid, pos_offset=pos_offset,
        last_only=True, seq_total=seq_total,
    )
    last = mask_pad_vocab(logits[:, -1, :], cfg)
    return last, cache


def prefill(
    params,
    cfg: LlamaConfig,
    prompt: jax.Array,  # [B, P] left-padded
    cache,
    kv_valid,
    pos_offset,
    chunk: int = 0,
):
    """Prefill the cache for a left-padded prompt batch; returns
    (last_logits [B, V] vocab-masked, cache).

    ``chunk`` > 0 processes the prompt in fixed-size pieces, each an
    incremental ``decode_step`` over the shared cache — bounding the
    per-dispatch activation footprint to O(chunk · d_ff) instead of
    O(P · d_ff). That is the long-context prefill path: a 128k-token
    prompt's single-shot [P, d_ff] transients run to gigabytes, while
    chunked prefill compiles ONE chunk-shaped program reused P/chunk
    times. The prompt width must be a chunk multiple — callers widen the
    left padding via ``_pack_prompts(..., plen=rounded)`` so the caller's
    kv_valid/pos_offset mirrors stay authoritative. Exactness: cached
    attention makes chunked and single-shot prefill mathematically
    identical; parity is tested.
    """
    if chunk <= 0 or prompt.shape[1] <= chunk:
        return _prefill_jit(params, cfg, prompt, cache, kv_valid, pos_offset)
    if prompt.shape[1] % chunk:
        raise ValueError(
            f"chunked prefill needs the prompt width ({prompt.shape[1]}) padded "
            f"to a multiple of chunk={chunk} (pack with plen=rounded)"
        )
    # Phi-3 longrope selects short/long factors from the sequence length:
    # each chunk must see the FULL per-row prompt length (width − left pad),
    # not its own max position, or early chunks of a long prompt rotate K/V
    # in the short regime while single-shot prefill uses long throughout.
    seq_total = None
    if cfg.rope_dim_factors_long:
        seq_total = jnp.asarray(prompt.shape[1], jnp.int32) - pos_offset
    last = None
    for s in range(0, prompt.shape[1], chunk):
        last, cache = _prefill_jit(
            params, cfg, prompt[:, s : s + chunk], cache, kv_valid, pos_offset, seq_total
        )
    return last, cache


def _generate_fused_jit(
    params,
    cfg: LlamaConfig,
    prompt: jax.Array,  # [B, P]
    cache,
    kv_valid,
    pos_offset,
    rng,
    temperature,
    max_new_tokens: int,
    greedy: bool,
):
    """Whole generation in two dispatches (prefill + one decode scan).
    Kept as the throughput path; the chunked path (DecodeSession) trades a
    few dispatches for device-queue preemption points."""
    last, cache = _prefill_jit(params, cfg, prompt, cache, kv_valid, pos_offset)
    toks, _, _, _ = _decode_chunk_jit(
        params, cfg, last, cache, kv_valid, pos_offset, rng, temperature,
        max_new_tokens, greedy,
    )
    return toks


def generate_tokens_fused(
    params: Params,
    cfg: LlamaConfig,
    prompts: list[list[int]],
    *,
    max_new_tokens: int = 64,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_id: Optional[int] = None,
) -> list[list[int]]:
    """Whole-generation-on-device decode: prefill + ``max_new_tokens`` decode
    steps run as ONE compiled program (`lax.scan` over decode_step), so a
    generation costs a single host→device dispatch and a single result fetch
    instead of one round-trip per token. On a remote/tunneled TPU (~70 ms
    RTT) that is the difference between wire-bound and compute-bound decode;
    on locally-attached chips it still removes per-step dispatch overhead.

    Trade-off vs :func:`generate_tokens_batch`: always runs the full
    ``max_new_tokens`` steps (no early exit when every sequence hit EOS) —
    the host truncates at the first EOS afterwards. Greedy output parity
    with the step-loop is exact; sampled output differs only in RNG
    consumption order.
    """
    import numpy as onp

    bsz = len(prompts)
    if bsz == 0:
        return []
    plen = max(len(p) for p in prompts)
    if plen + 1 > cfg.max_seq_len:
        raise ValueError(
            f"longest prompt ({plen} tokens) leaves no room in the cache window "
            f"(max_seq_len={cfg.max_seq_len}); truncate prompts before calling"
        )
    ml = _bucket_len(plen + max_new_tokens + 1, cfg.max_seq_len)
    steps = min(max_new_tokens, ml - plen - 1)
    toks, valid, offsets, _ = _pack_prompts(prompts, ml)
    cache = init_cache(cfg, batch=bsz, max_len=ml)
    out = _generate_fused_jit(
        params,
        cfg,
        jnp.asarray(toks),
        cache,
        jnp.asarray(valid),
        jnp.asarray(offsets),
        rng if rng is not None else jax.random.PRNGKey(0),
        jnp.asarray(max(temperature, 1e-6), jnp.float32),
        steps,
        temperature <= 0.0,
    )
    rows = onp.asarray(out)
    outs: list[list[int]] = []
    for row in rows:
        ids = row.tolist()
        if eos_id is not None and eos_id in ids:
            ids = ids[: ids.index(eos_id)]
        outs.append(ids)
    return outs


class DecodeSession:
    """Resumable chunked generation over one left-padded prompt batch.

    ``step_chunk()`` dispatches the next ``chunk_steps`` decode steps as one
    compiled program and fetches the sampled tokens. Bounding the per-
    dispatch slice is the serving-side scheduling mechanism for sharing the
    chip: the device queue gets a preemption point every chunk, so a
    pre-flight warn batch waits at most ~chunk_steps·(per-step time) instead
    of a whole generation (SURVEY §7 'interleaving generate steps with match
    batches'). Token parity with :func:`generate_tokens_fused` is exact for
    greedy decoding and RNG-exact for sampling (the rng threads through
    chunks in the same split order).
    """

    def __init__(
        self,
        params: Params,
        cfg: LlamaConfig,
        prompts: list[list[int]],
        *,
        chunk_steps: int = 8,
        max_len: Optional[int] = None,
        temperature: float = 0.0,
        rng: Optional[jax.Array] = None,
        prefill_chunk: int = 0,
    ):
        import numpy as onp

        if not prompts:
            raise ValueError("empty prompt batch")
        self.params, self.cfg = params, cfg
        self.chunk_steps = chunk_steps
        self.greedy = temperature <= 0.0
        self.temperature = jnp.asarray(max(temperature, 1e-6), jnp.float32)
        natural_plen = max(len(p) for p in prompts)
        # Chunked prefill widens the prompt window to a chunk multiple
        # (extra left padding) so every piece hits one compiled shape; the
        # padding can consume up to chunk−1 decode slots when the window
        # is capped at max_seq_len — the price of retrace-free prefill.
        plen = _prefill_width(natural_plen, prefill_chunk)
        ml = max_len or cfg.max_seq_len
        if plen + 1 > ml:
            raise ValueError(
                f"longest prompt ({natural_plen}"
                + (f", padded to {plen} for prefill_chunk={prefill_chunk}" if plen != natural_plen else "")
                + f") leaves no room (max_len={ml})"
            )
        bsz = len(prompts)
        toks, valid, offsets, plen = _pack_prompts(prompts, ml, plen=plen)
        self.kv_valid = jnp.asarray(valid)
        self.pos_offset = jnp.asarray(offsets)
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        cache = init_cache(cfg, batch=bsz, max_len=ml)
        self._last, self._cache = prefill(
            params, cfg, jnp.asarray(toks), cache, self.kv_valid, self.pos_offset,
            chunk=prefill_chunk,
        )
        self._pos = plen
        self._max_len = ml

    @property
    def steps_left(self) -> int:
        return max(0, self._max_len - 1 - self._pos)

    def step_chunk(self, n: Optional[int] = None):
        """Run the next min(n, steps_left) decode steps; returns the sampled
        token matrix [B, steps] as a numpy array (None when the cache
        window is exhausted)."""
        import numpy as onp

        steps = min(n or self.chunk_steps, self.steps_left)
        if steps <= 0:
            return None
        toks, self._last, self._cache, self.rng = _decode_chunk_jit(
            self.params, self.cfg, self._last, self._cache, self.kv_valid,
            self.pos_offset, self.rng, self.temperature, steps, self.greedy,
        )
        self._pos += steps
        return onp.asarray(toks)


class LlamaRuntime:
    """`runtime=tpu`: on-device Llama generation with the shared meta shape."""

    name = "tpu"

    def __init__(
        self,
        cfg: Optional[LlamaConfig] = None,
        params: Optional[Params] = None,
        seed: int = 0,
        tokenizer=None,
        model_label: Optional[str] = None,
        quant: Optional[str] = None,
    ):
        self.cfg = cfg or LlamaConfig.tiny()
        self.tokenizer = tokenizer if tokenizer is not None else ByteTokenizer()
        if self.cfg.vocab_size < self.tokenizer.vocab_size:
            raise ValueError("model vocab smaller than tokenizer vocab")
        kvq = os.environ.get("KAKVEDA_KV_QUANT", "")
        if kvq and kvq != "none":
            if kvq != "int8":
                raise ValueError(f"unknown KAKVEDA_KV_QUANT={kvq!r} (int8|none)")
            import dataclasses as _dc

            # Serving-layer cache quantization: every decode path this
            # runtime spawns (chunked, engine, speculative) inherits the
            # flag through self.cfg.
            self.cfg = _dc.replace(self.cfg, kv_quant="int8")
        if self.cfg.effective_vocab is None and self.tokenizer.vocab_size < self.cfg.vocab_size:
            # The table is padded past the tokenizer (tp-friendly multiple):
            # without effective_vocab the pad-vocab mask is a no-op and a
            # random-init/underspecified model can argmax an id the
            # tokenizer cannot decode — ByteTokenizer.decode then raises
            # mid-request (observed as stochastic playground 500s). Every
            # decode path masks via mask_pad_vocab(cfg), so clamping here
            # covers chunked, engine, speculative and batch serving alike.
            import dataclasses as _dc

            self.cfg = _dc.replace(self.cfg, effective_vocab=self.tokenizer.vocab_size)
        self.params = params if params is not None else init_params(jax.random.PRNGKey(seed), self.cfg)
        if quant == "int8":
            # Weight-only int8 serving: halves the HBM weight stream that
            # bounds decode throughput (models/quant.py).
            from kakveda_tpu.models.quant import quantize_params_int8

            self.params = quantize_params_int8(self.params)
        elif quant not in (None, "none"):
            raise ValueError(f"unknown quant mode {quant!r} (int8|none)")
        self.quant = quant
        self.model_label = model_label or f"llama-{self.cfg.n_layers}L-{self.cfg.d_model}d"
        import threading

        self._engine = None
        self._engine_lock = sanitize.named_lock("LlamaRuntime._engine_lock")
        self._retired = False

    @classmethod
    def from_env(cls) -> "LlamaRuntime":
        quant = os.environ.get("KAKVEDA_QUANT") or None
        if quant not in (None, "none", "int8"):
            raise ValueError(f"unknown KAKVEDA_QUANT={quant!r} (int8|none)")
        # KAKVEDA_HF_DIR is the documented operator-facing alias (VERDICT
        # item 8: one env var from proven real-weight parity on any
        # machine with a local HF checkpoint); KAKVEDA_HF_CKPT predates it
        # and wins when both are set.
        hf_ckpt = os.environ.get("KAKVEDA_HF_CKPT") or os.environ.get("KAKVEDA_HF_DIR")
        if hf_ckpt:
            return cls.from_hf(hf_ckpt, quant=quant)
        preset = os.environ.get("KAKVEDA_LLAMA_PRESET", "tiny").lower()
        cfg = LlamaConfig.llama3_8b() if preset in ("8b", "llama3-8b") else LlamaConfig.tiny()
        rt = cls(cfg=cfg)
        ckpt = os.environ.get("KAKVEDA_LLAMA_CKPT")
        if ckpt:
            rt.load_checkpoint(ckpt)
        if quant == "int8":
            from kakveda_tpu.models.quant import quantize_params_int8

            rt.params = quantize_params_int8(rt.params)
            rt.quant = quant
        return rt

    @classmethod
    def from_hf(cls, path: str, *, mesh=None, quant: Optional[str] = None) -> "LlamaRuntime":
        """Real-weight serving: convert a local HF Llama checkpoint directory
        (weights + tokenizer files) and serve it on the TPU runtime. With a
        ``mesh``, params are placed per the Megatron TP layout; ``quant``
        ("int8") applies weight-only quantization before placement.
        Replaces the reference's Ollama daemon hop
        (reference: services/dashboard/app.py:1182-1258)."""
        from kakveda_tpu.models.hf_convert import load_hf_checkpoint, shard_params
        from kakveda_tpu.models.tokenizer import HFTokenizer

        params, cfg = load_hf_checkpoint(path)
        if quant not in (None, "none", "int8"):
            raise ValueError(f"unknown quant mode {quant!r} (int8|none)")
        rt_quant = None
        if quant == "int8":
            from kakveda_tpu.models.quant import quantize_params_int8

            params = quantize_params_int8(params)
            rt_quant = quant
        if mesh is not None:
            params = shard_params(params, cfg, mesh)  # handles int8 leaves
        tok = HFTokenizer(path)
        label = os.path.basename(os.path.normpath(path))
        rt = cls(cfg=cfg, params=params, tokenizer=tok, model_label=label)
        rt.quant = rt_quant
        return rt

    def load_checkpoint(self, path: str) -> None:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        self.params = ckptr.restore(path, self.params)
        with self._engine_lock:
            if self._engine is not None:
                # The engine captured the old param tree at construction;
                # drop it so the next online request rebuilds on the new
                # weights instead of serving the stale ones.
                self._engine.close()
                self._engine = None

    def list_models(self) -> list:
        return [self.model_label]

    def engine(self):
        """The shared online ServingEngine (continuous batching), or None
        when disabled. KAKVEDA_SERVE_CONTINUOUS=0 opts out (falls back to
        one decode stream per call); KAKVEDA_SERVE_SLOTS / _SERVE_WINDOW /
        _SERVE_CHUNK size the pool. Lazy: offline users (training, bench
        static paths) never pay for the loop thread."""
        if os.environ.get("KAKVEDA_SERVE_CONTINUOUS", "1") == "0" or self._retired:
            return None
        if self._engine is None:
            with self._engine_lock:
                if self._retired:
                    # Evicted by MultiModelRuntime's HBM budget: never
                    # rebuild the KV pool — an in-flight generate falls
                    # back to the solo decode (params stay alive only as
                    # long as its caller holds this runtime).
                    return None
                if self._engine is None:
                    from kakveda_tpu.models.serving import ServingEngine

                    window = int(
                        os.environ.get(
                            "KAKVEDA_SERVE_WINDOW", min(512, self.cfg.max_seq_len)
                        )
                    )
                    try:
                        self._engine = ServingEngine(
                            self.params, self.cfg,
                            batch_slots=int(os.environ.get("KAKVEDA_SERVE_SLOTS", "8")),
                            max_len=min(window, self.cfg.max_seq_len),
                            chunk_steps=int(os.environ.get("KAKVEDA_SERVE_CHUNK", "8")),
                            eos_id=self.tokenizer.EOS,
                            name=self.model_label,
                        )
                    except Exception as e:  # noqa: BLE001
                        # KV-pool allocation can fail on a memory-tight
                        # chip (the co-residency case the HBM budget
                        # exists for). Serving must degrade to the solo
                        # path, not 500 — and not retry the allocation on
                        # every request.
                        import logging

                        logging.getLogger("kakveda.serving").warning(
                            "ServingEngine construction failed; online "
                            "continuous batching disabled for %s: %s",
                            self.model_label, e,
                        )
                        self._retired = True
                        return None
        return self._engine

    def register_prefix(self, prefix: str) -> bool:
        """Precompute a shared prompt prefix (system preamble, judge
        template) on the serving engine so every later request that starts
        with it prefills only its suffix. No-op (False) when the engine is
        disabled or the prefix is unsuitable (see
        ContinuousBatcher.register_prefix)."""
        eng = self.engine()
        if eng is None:
            return False
        ids = self.tokenizer.encode(prefix)
        try:
            return eng.register_prefix(ids)
        except (RuntimeError, TimeoutError):
            # A failed registration must not break serving: engine
            # closed/dead (RuntimeError family) or a saturated pool timing
            # the registration future out. Deliberately NOT a broad
            # except — OverloadError/DeviceUnavailableError must surface.
            return False

    def serving_stats(self) -> dict:
        """Ops snapshot for the admin serving panel — engine pool state
        (without constructing one: observability must not allocate a KV
        pool on a chip it is checking) plus the serving-lever flags."""
        eng = self._engine  # peek, never build
        stats = None
        if eng is not None:
            # stats() is the lock-guarded deep-copy snapshot (the loop
            # thread mutates spec_stats/k_trace concurrently with this
            # panel) — never read the live dicts here.
            stats = {
                **eng.stats(),
                "active": eng.cb.active,
                "slots": eng.cb.B,
                "window": eng.cb.max_len,
                "closed": eng._closed.is_set(),
            }
            if not eng.cb.spec_k:
                stats["spec"] = None
        return {
            "runtime": "tpu",
            "model": self.model_label,
            "quant": self.quant or "none",
            "kv_quant": self.cfg.kv_quant or "none",
            "retired": self._retired,
            "engine": stats,
        }

    def retire(self) -> None:
        """Tear down the serving engine and bar rebuilding — called by the
        HBM-budget evictor. In-flight generates finish on the solo path;
        device memory frees once the last caller drops this runtime."""
        with self._engine_lock:
            self._retired = True
            if self._engine is not None:
                self._engine.close()
                self._engine = None

    def _generate_ids_chunked(self, ids: list[list[int]], max_tokens: int) -> list[list[int]]:
        """Greedy decode via chunked dispatch (DecodeSession): ~chunk_steps
        tokens per device program instead of one (the per-token host loop
        pays a full dispatch RTT per token on remote-attached chips), with
        EOS early-exit checked between chunks and the device queue left
        preemptible for concurrent pre-flight matches."""
        import numpy as onp

        plen = max(len(p) for p in ids)
        # Long-context serving: KAKVEDA_PREFILL_CHUNK=512 (etc.) prefills
        # in fixed pieces, bounding activation memory per dispatch.
        pchunk = int(os.environ.get("KAKVEDA_PREFILL_CHUNK", "0"))
        plen = _prefill_width(plen, pchunk)
        ml = _bucket_len(plen + max_tokens + 1, self.cfg.max_seq_len)
        sess = DecodeSession(
            self.params, self.cfg, ids, chunk_steps=16, max_len=ml, prefill_chunk=pchunk
        )
        eos = self.tokenizer.EOS
        outs: list[list[int]] = [[] for _ in ids]
        done = [False] * len(ids)
        budget = min(max_tokens, sess.steps_left)
        while budget > 0 and not all(done):
            chunk = sess.step_chunk(min(16, budget))
            if chunk is None:
                break
            budget -= chunk.shape[1]
            for i, row in enumerate(onp.asarray(chunk)):
                for t in row.tolist():
                    if done[i]:
                        break
                    if t == eos:
                        done[i] = True
                    elif len(outs[i]) < max_tokens:
                        outs[i].append(t)
        return outs

    def generate_batch(
        self, prompts: list, *, model: Optional[str] = None, max_tokens: int = 64
    ) -> list:
        """Batched generation: one decode stream for the whole list, exact
        per-sequence parity with generate()."""
        started = time.perf_counter()
        # Device-loss fail-fast: while the backend is latched DEGRADED,
        # every decode path (engine AND solo) would dispatch into a wedged
        # chip and hang — raise the typed retryable error in microseconds
        # instead (shed-never-hang, docs/robustness.md).
        from kakveda_tpu.core import admission as _admission

        _admission.get_device_health().check()
        ids = [self.tokenizer.encode(p)[-self.cfg.max_seq_len // 2 :] for p in prompts]
        from kakveda_tpu.core import profiling

        eng = self.engine()
        extra = {}
        new_ids = None
        if eng is not None and all(eng.fits(len(i), max_tokens) for i in ids):
            # Online path: the whole list joins the SHARED slot pool, so a
            # judge batch and a concurrent playground chat decode together.
            try:
                if len(ids) >= 2:
                    # Eval datasets and judge batches share a prompt head
                    # (instruction template). Register the batch's common
                    # token prefix once so all-but-the-first admissions
                    # reuse its K/V slab (register_prefix dedupes repeats
                    # and refuses unhelpful/unsafe prefixes itself).
                    common = os.path.commonprefix(ids)
                    if len(common) >= 16:
                        try:
                            eng.register_prefix(list(common))
                        except (RuntimeError, TimeoutError):
                            # Registration is an optimization only: engine
                            # closed mid-flight (RuntimeError) or a
                            # saturated pool timing out the registration
                            # future must not fail the batch itself. Typed
                            # admission errors are NOT RuntimeErrors and
                            # still surface (docs/static-analysis.md,
                            # typed-errors).
                            pass
                with profiling.annotate("llama.generate_batch_online"):
                    futs = [eng.submit(i, max_new_tokens=max_tokens) for i in ids]
                    new_ids = [f.result() for f in futs]
                extra = {"continuous": True}
            except RuntimeError:
                # Engine closed/died between fits() and the results: the
                # solo path below still serves the request.
                new_ids = None
        if new_ids is None:
            with profiling.annotate("llama.generate_batch"):
                new_ids = self._generate_ids_chunked(ids, max_tokens)
        latency_ms = int((time.perf_counter() - started) * 1000)
        label = model or self.model_label
        return [
            GenerateResult(
                text=self.tokenizer.decode(out),
                meta={
                    "provider": "tpu",
                    "model": label,
                    "latency_ms": latency_ms,
                    "tokens_generated": len(out),
                    "batched": len(prompts),
                    **extra,
                },
            )
            for out in new_ids
        ]

    def generate_stream(
        self, prompt: str, *, model: Optional[str] = None, max_tokens: int = 64,
        cancel=None,
    ):
        """Streaming generation: yields text deltas as decode chunks land.

        Engine path: the request joins the shared continuous-batching pool
        and each chunk's accepted tokens surface through the engine's
        ``on_tokens`` callback (token-identical to the blocking path).
        Fallback (engine disabled / request doesn't fit): chunked solo
        decode yielding per device chunk. Deltas join to exactly the text
        ``generate`` would return; incomplete UTF-8 at a chunk boundary is
        withheld until the bytes complete (decode uses errors="replace",
        so an unstable replacement char must never be emitted early).

        Capability beyond the reference: its playground blocks on a full
        Ollama response per request (services/dashboard/app.py:3127-3299);
        here first tokens reach the client after one decode chunk.

        ``cancel`` (optional ``threading.Event``): set by the consumer on
        client disconnect — observed BETWEEN deltas too (a request still
        queued or mid-prefill cancels promptly, not only after its first
        token arrives). Closing the generator has the same effect.
        """
        from kakveda_tpu.core import admission as _admission

        _admission.get_device_health().check()  # degraded: fail fast, never hang
        ids = self.tokenizer.encode(prompt)[-self.cfg.max_seq_len // 2 :]

        def deltas(all_ids: list, done: bool, prev: str) -> tuple:
            text = self.tokenizer.decode(all_ids)
            if not done:
                text = text.rstrip("�")  # partial multi-byte tail
            if text.startswith(prev) and len(text) > len(prev):
                return text[len(prev):], text
            return "", prev

        eng = self.engine()
        if eng is not None and eng.fits(len(ids), max_tokens):
            import queue as _q

            ch: "_q.Queue" = _q.Queue()
            try:
                fut = eng.submit(
                    ids, max_tokens,
                    on_tokens=lambda new, done: ch.put((list(new), done)),
                )
            except RuntimeError:
                fut = None  # engine closed: solo fallback below
            if fut is not None:
                out: list = []
                prev = ""
                try:
                    while True:
                        try:
                            new, done = ch.get(timeout=0.5)
                        except _q.Empty:
                            if cancel is not None and cancel.is_set():
                                break  # finally cancels the engine request
                            if fut.done():  # engine died mid-request
                                fut.result()  # raises the loop's error
                                break
                            continue
                        out.extend(new)
                        d, prev = deltas(out, done, prev)
                        if d:
                            yield d
                        if done:
                            break
                finally:
                    # Abandoned mid-stream (consumer close() → GeneratorExit
                    # lands at the yield): free the engine slot instead of
                    # decoding a result nobody will read.
                    if not fut.done():
                        eng.cancel(fut)
                return

        # Solo fallback: same chunked decode as _generate_ids_chunked, one
        # yield per device chunk.
        import numpy as onp

        plen = len(ids)
        pchunk = int(os.environ.get("KAKVEDA_PREFILL_CHUNK", "0"))
        plen = _prefill_width(plen, pchunk)
        ml = _bucket_len(plen + max_tokens + 1, self.cfg.max_seq_len)
        sess = DecodeSession(
            self.params, self.cfg, [ids], chunk_steps=16, max_len=ml, prefill_chunk=pchunk
        )
        eos = self.tokenizer.EOS
        out = []
        prev = ""
        budget = min(max_tokens, sess.steps_left)
        done = False
        while budget > 0 and not done:
            if cancel is not None and cancel.is_set():
                break  # abandoned: stop dispatching chunks
            chunk = sess.step_chunk(min(16, budget))
            if chunk is None:
                break
            budget -= chunk.shape[1]
            for t in onp.asarray(chunk)[0].tolist():
                if t == eos or len(out) >= max_tokens:
                    done = True
                    break
                out.append(t)
            d, prev = deltas(out, done or budget <= 0, prev)
            if d:
                yield d

    def generate(self, prompt: str, *, model: Optional[str] = None, max_tokens: int = 64) -> GenerateResult:
        started = time.perf_counter()
        from kakveda_tpu.core import admission as _admission

        _admission.get_device_health().check()  # degraded: fail fast, never hang
        ids = self.tokenizer.encode(prompt)[-self.cfg.max_seq_len // 2 :]
        from kakveda_tpu.core import profiling

        meta_extra = {}
        if os.environ.get("KAKVEDA_SPEC", "") == "1":
            # Single-sequence latency mode: draft-free speculative decoding
            # (models/speculative.py) — token-identical to the chunked
            # greedy path, 1..k+1 tokens per weight stream. Trade-off: the
            # whole generation is ONE device program, so concurrent warn
            # batches lose their per-chunk preemption points; leave it off
            # when the chip is shared.
            from kakveda_tpu.models.speculative import generate_tokens_speculative

            with profiling.annotate("llama.generate_spec"):
                new_ids, stats = generate_tokens_speculative(
                    self.params, self.cfg, ids, max_new_tokens=max_tokens,
                    eos_id=self.tokenizer.EOS, return_stats=True,
                )
            meta_extra = {"speculative": True, "tokens_per_round": round(stats["tokens_per_round"], 2)}
        else:
            eng = self.engine()
            new_ids = None
            if eng is not None and eng.fits(len(ids), max_tokens):
                # Online path: join the shared continuous-batching pool —
                # concurrent requests (other chats, eval rows, judge calls)
                # decode in ONE batch. Greedy slot parity keeps the output
                # identical to the solo decode below.
                try:
                    with profiling.annotate("llama.generate_online"):
                        fut = eng.submit(ids, max_tokens)
                        new_ids = fut.result()
                    meta_extra = {"continuous": True}
                    # The engine attaches the request's lifecycle timeline
                    # (queue wait, prefill, TTFT, tokens/s, engine request
                    # id) to the Future — surfaced in meta so HTTP layers
                    # can hang it on the request's OTel span and correlate
                    # traces with /metrics and the flight recorder.
                    tl = getattr(fut, "timeline", None)
                    if tl is not None:
                        meta_extra["serve"] = tl
                except RuntimeError:
                    new_ids = None  # engine closed/died: solo path below
            if new_ids is None:
                with profiling.annotate("llama.generate"):
                    new_ids = self._generate_ids_chunked([ids], max_tokens)[0]
        text = self.tokenizer.decode(new_ids)
        return GenerateResult(
            text=text,
            meta={
                "provider": "tpu",
                "model": model or self.model_label,
                "latency_ms": int((time.perf_counter() - started) * 1000),
                "tokens_generated": len(new_ids),
                **meta_extra,
            },
        )
