"""HF checkpoint → kakveda param pytree (eight model families).

The reference delegates all real-model inference to an external Ollama
daemon (reference: services/dashboard/app.py:1182-1258) — which is also how
it supports many model families. Here real weights load directly onto the
TPU mesh: point ``KAKVEDA_HF_CKPT`` at any local HF-format checkpoint
directory of a supported family — Llama, Mistral, Qwen2, Qwen3, Gemma,
Gemma-2, Phi-3, Mixtral — and ``runtime=tpu`` serves it in-process
(``KAKVEDA_HF_CKPTS`` serves several at once). Every family delta is a
config flag on one runtime (see :func:`hf_config_to_llama`).

Conversion notes (all verified by the logit-parity tests in
tests/test_hf_convert.py against ``transformers.LlamaForCausalLM``):

  * HF ``nn.Linear`` stores ``[out, in]``; our matmuls are ``x @ W`` with
    ``W [in, out]`` — every projection transposes.
  * HF Llama uses the split-half ("NEOX") RoPE convention, identical to
    ``llama.apply_rope``, so q/k need **no** permutation (unlike raw Meta
    weights, which interleave).
  * ``tie_word_embeddings`` (Llama-3.2-1B, Gemma-style) → lm_head is the
    transposed embedding table.
  * ``rope_scaling.rope_type == "llama3"`` maps onto the flat rope_* fields
    of :class:`LlamaConfig`; other scaling types are rejected loudly rather
    than silently mis-positioned.
  * Vocab not divisible by 8 is padded up so the tp axis can shard the
    embed/lm_head tables; ``cfg.effective_vocab`` records the real size and
    sampling masks the pad logits.

Tensors stream one at a time through host RAM (safetensors ``safe_open`` /
lazy torch load) and are cast to ``param_dtype`` (default bfloat16 — what
the MXU wants) before the next loads, so an 8B model converts within
~2×8 GB host memory, not 4×.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kakveda_tpu.models.llama import LlamaConfig, Params

__all__ = ["hf_config_to_llama", "load_hf_checkpoint", "shard_params"]

_VOCAB_MULTIPLE = 8


_SUPPORTED_FAMILIES = (
    "llama", "mistral", "qwen2", "qwen3", "mixtral", "gemma", "gemma2", "phi3",
)
_GEMMA_FAMILIES = ("gemma", "gemma2")


def hf_config_to_llama(hf: Dict[str, Any], *, dtype=jnp.bfloat16) -> LlamaConfig:
    """Map an HF ``config.json`` dict to :class:`LlamaConfig`.

    Eight HF families share the Llama block structure and load onto the one
    runtime: ``llama`` (the baseline), ``mistral`` (adds a sliding attention
    window and sometimes an explicit head_dim), ``qwen2`` (adds q/k/v
    projection biases), ``qwen3`` (per-head q/k RMSNorm), ``mixtral``
    (replaces the dense MLP with a sparse MoE block — models/moe.py),
    ``gemma`` (GeGLU activation, sqrt(d_model) embedding scale, explicit
    head_dim; its (1+w) RMSNorm convention is absorbed at conversion by
    storing the materialized 1+w weights), ``gemma2`` (gemma plus
    alternating per-layer sliding windows, attention/final logit
    softcapping, an explicit query scale, and sandwich post-norms), and
    ``phi3`` (fused qkv / gate_up projections split at conversion, longrope
    per-dim frequency scaling). Anything else is rejected loudly."""
    family = hf.get("model_type") or "llama"
    if family not in _SUPPORTED_FAMILIES:
        raise ValueError(
            f"unsupported model_type={family!r} (supported: {', '.join(_SUPPORTED_FAMILIES)})"
        )
    rope = hf.get("rope_scaling") or {}
    kw: Dict[str, Any] = {}
    if rope:
        rtype = rope.get("rope_type") or rope.get("type")
        if rtype == "llama3":
            kw = dict(
                rope_factor=float(rope["factor"]),
                rope_low_freq_factor=float(rope.get("low_freq_factor", 1.0)),
                rope_high_freq_factor=float(rope.get("high_freq_factor", 4.0)),
                rope_original_max_len=int(rope.get("original_max_position_embeddings", 8192)),
            )
        elif rtype == "longrope" and family == "phi3":
            # Phi-3 longrope: per-dim frequency divisors, selected
            # DYNAMICALLY at runtime (short while the sequence fits the
            # original pretraining context, long beyond it — HF's
            # dynamic_rope_update semantics); the cos/sin attention
            # scaling is static from the config's extension ratio.
            import math as _math

            orig = int(
                hf.get("original_max_position_embeddings")
                or hf.get("max_position_embeddings")
            )
            maxp = int(hf.get("max_position_embeddings", orig))
            scale = maxp / orig
            if rope.get("attention_factor") is not None:
                # HF honors an explicit attention_factor verbatim.
                attn_scale = float(rope["attention_factor"])
            else:
                attn_scale = (
                    _math.sqrt(1.0 + _math.log(scale) / _math.log(orig))
                    if scale > 1.0
                    else 1.0
                )
            hd_half = (
                int(hf.get("head_dim") or int(hf["hidden_size"]) // int(hf["num_attention_heads"]))
                // 2
            )
            short = tuple(float(f) for f in rope["short_factor"])
            long = tuple(float(f) for f in rope["long_factor"])
            if len(short) != hd_half or len(long) != hd_half:
                raise ValueError(
                    f"longrope factor lists must have head_dim//2={hd_half} entries "
                    f"(got {len(short)}/{len(long)})"
                )
            kw = dict(
                rope_dim_factors=short,
                rope_dim_factors_long=long,
                rope_original_max_len=orig,
                rope_attn_scaling=attn_scale,
            )
        else:
            raise ValueError(
                f"unsupported rope_scaling type: {rtype!r} "
                "(llama3; longrope for phi3)"
            )

    # Sliding-window attention: Mistral applies it whenever the config sets
    # one; Qwen2/Qwen3 additionally gate on use_sliding_window and only
    # past max_window_layers — the mixed-layer form has no support here, so
    # it fails loudly rather than serving wrong attention.
    window = int(hf.get("sliding_window") or 0)
    if family in ("qwen2", "qwen3") and window:
        if not hf.get("use_sliding_window", False):
            window = 0
        else:
            # HF semantics: the first max_window_layers layers use FULL
            # attention, the rest slide. Only the uniform cases map here.
            # The missing-key default matches Qwen2Config's (28), so a
            # config without the key resolves the same way HF resolves it.
            mwl = int(hf.get("max_window_layers", 28))
            if mwl >= int(hf["num_hidden_layers"]):
                window = 0  # every layer full attention
            elif mwl != 0:
                raise ValueError(
                    "qwen2 mixed full/sliding layers (0 < max_window_layers < "
                    "num_hidden_layers) is not supported"
                )

    n_heads = int(hf["num_attention_heads"])
    head_dim = int(hf.get("head_dim") or 0)
    if head_dim and head_dim * n_heads == int(hf["hidden_size"]):
        head_dim = 0  # derived value; keep the config canonical

    moe_kw: Dict[str, Any] = {}
    if family == "mixtral":
        moe_kw = dict(
            n_experts=int(hf["num_local_experts"]),
            n_experts_per_tok=int(hf.get("num_experts_per_tok", 2)),
            router_aux_coef=float(hf.get("router_aux_loss_coef", 0.0)),
        )
    if family == "gemma2":
        hd_real = head_dim or int(hf["hidden_size"]) // n_heads
        qpas = float(hf.get("query_pre_attn_scalar") or 0.0)
        qs = qpas**-0.5 if qpas else 0.0
        if qs and abs(qs - hd_real**-0.5) < 1e-12:
            qs = 0.0  # equals the default head_dim scale; keep canonical
        # The runtime assumes gemma2's default alternation (even layers
        # slide, odd full). A config that spells out a DIFFERENT
        # layer_types pattern must fail loudly, not serve wrong masks.
        lt = hf.get("layer_types")
        if lt is not None and window:
            want = [
                "sliding_attention" if i % 2 == 0 else "full_attention"
                for i in range(int(hf["num_hidden_layers"]))
            ]
            if list(lt) != want:
                raise ValueError(
                    "gemma2 layer_types deviates from the even-slide/odd-full "
                    "alternation; this pattern is not supported"
                )
        moe_kw.update(
            alt_window=window > 0,
            attn_softcap=float(hf.get("attn_logit_softcapping") or 0.0),
            final_softcap=float(hf.get("final_logit_softcapping") or 0.0),
            query_scale=qs,
            post_norms=True,
        )

    vocab = int(hf["vocab_size"])
    padded = -(-vocab // _VOCAB_MULTIPLE) * _VOCAB_MULTIPLE
    return LlamaConfig(
        **moe_kw,
        vocab_size=padded,
        effective_vocab=vocab if padded != vocab else None,
        d_model=int(hf["hidden_size"]),
        n_layers=int(hf["num_hidden_layers"]),
        n_heads=n_heads,
        n_kv_heads=int(hf.get("num_key_value_heads", n_heads)),
        d_ff=int(hf["intermediate_size"]),
        max_seq_len=int(hf.get("max_position_embeddings", 2048)),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        dtype=dtype,
        attn_bias=bool(hf.get("attention_bias", family == "qwen2")),
        qk_norm=family == "qwen3",
        sliding_window=window,
        head_dim_opt=head_dim,
        act_fn="gelu_tanh" if family in _GEMMA_FAMILIES else "silu",
        scale_embed=family in _GEMMA_FAMILIES,
        **kw,
    )


# ---------------------------------------------------------------------------
# tensor streaming
# ---------------------------------------------------------------------------


def _iter_weight_files(path: str) -> Iterator[str]:
    """Checkpoint shard files, index-ordered when an index exists."""
    for index_name in ("model.safetensors.index.json", "pytorch_model.bin.index.json"):
        idx = os.path.join(path, index_name)
        if os.path.exists(idx):
            with open(idx) as f:
                files = sorted(set(json.load(f)["weight_map"].values()))
            for fn in files:
                yield os.path.join(path, fn)
            return
    for name in ("model.safetensors", "pytorch_model.bin"):
        p = os.path.join(path, name)
        if os.path.exists(p):
            yield p
            return
    raise FileNotFoundError(f"no model weights (safetensors or bin) under {path}")


def _tensor_reader(path: str) -> Callable[[], Iterator[Tuple[str, np.ndarray]]]:
    """Yield (name, float32 ndarray) one tensor at a time across all shards."""

    def gen() -> Iterator[Tuple[str, np.ndarray]]:
        for fn in _iter_weight_files(path):
            if fn.endswith(".safetensors"):
                from safetensors import safe_open

                # framework="pt": bfloat16 tensors are not representable as
                # numpy dtypes, so route through torch and upcast.
                with safe_open(fn, framework="pt") as f:
                    for name in f.keys():
                        t = f.get_tensor(name)
                        yield name, t.to(dtype=_torch().float32).numpy()
            else:
                sd = _torch().load(fn, map_location="cpu", weights_only=True)
                for name, t in sd.items():
                    yield name, t.to(dtype=_torch().float32).numpy()

    return gen


def _torch():
    import torch

    return torch


# ---------------------------------------------------------------------------
# conversion
# ---------------------------------------------------------------------------


def _empty_tree(cfg: LlamaConfig) -> Params:
    keys = ["attn_norm", "wq", "wk", "wv", "wo", "mlp_norm"]
    if cfg.n_experts:
        keys += ["router", "we_gate", "we_up", "we_down"]
    else:
        keys += ["w_gate", "w_up", "w_down"]
    if cfg.attn_bias:
        keys += ["bq", "bk", "bv"]
    if cfg.post_norms:
        keys += ["post_attn_norm", "post_ffw_norm"]
    if cfg.qk_norm:
        keys += ["q_norm", "k_norm"]
    return {
        "embed": None,
        "layers": [{k: None for k in keys} for _ in range(cfg.n_layers)],
        "final_norm": None,
        "lm_head": None,
    }


def _pad_vocab_rows(arr: np.ndarray, padded: int) -> np.ndarray:
    if arr.shape[0] == padded:
        return arr
    out = np.zeros((padded,) + arr.shape[1:], arr.dtype)
    out[: arr.shape[0]] = arr
    return out


def load_hf_checkpoint(
    path: str,
    *,
    param_dtype=jnp.bfloat16,
    compute_dtype=None,
) -> Tuple[Params, LlamaConfig]:
    """Load + convert an HF Llama checkpoint directory.

    Returns host-resident jnp arrays in ``param_dtype``; use
    :func:`shard_params` to place them on a mesh. ``compute_dtype`` defaults
    to ``param_dtype`` and becomes ``cfg.dtype`` (the activation dtype).
    """
    with open(os.path.join(path, "config.json")) as f:
        hf_cfg = json.load(f)
    cfg = hf_config_to_llama(hf_cfg, dtype=compute_dtype or param_dtype)
    # Gemma applies RMSNorm gain as (1 + w) with zero-init weights; storing
    # the materialized 1+w keeps every forward path convention-free. The
    # materialized gains stay FLOAT32 (norm_dtype) — cast to bf16 their
    # spacing near 1.0 is 2^-8, which would discard the zero-centered
    # parameterization's precision; rms_norm applies f32 gains in f32
    # (HF GemmaRMSNorm's convention).
    is_gemma = hf_cfg.get("model_type") in _GEMMA_FAMILIES
    norm_off = 1.0 if is_gemma else 0.0
    norm_dtype = jnp.float32 if is_gemma else None

    params = _empty_tree(cfg)
    seen = set()
    # Mixtral expert tensors arrive one (layer, expert, projection) at a
    # time; stage them (already cast to param_dtype) and stack per layer
    # at the end into the [E, ...] arrays the MoE block wants.
    staged: Dict[Tuple[int, str], list] = {}

    def put(
        slot: Dict[str, Any] | Params, key: str, arr: np.ndarray, *, transpose: bool, dtype=None
    ) -> None:
        a = arr.T if transpose else arr
        slot[key] = jnp.asarray(a).astype(dtype or param_dtype)

    def stage_expert(li: int, key: str, ei: int, arr: np.ndarray, *, transpose: bool) -> None:
        lst = staged.setdefault((li, key), [None] * cfg.n_experts)
        if not 0 <= ei < cfg.n_experts:
            raise ValueError(f"expert index {ei} out of range (n_experts={cfg.n_experts})")
        lst[ei] = jnp.asarray(arr.T if transpose else arr).astype(param_dtype)

    for name, arr in _tensor_reader(path)():
        seen.add(name)
        base = name.removeprefix("model.")
        if base == "embed_tokens.weight":
            put(params, "embed", _pad_vocab_rows(arr, cfg.vocab_size), transpose=False)
        elif base == "norm.weight":
            put(params, "final_norm", arr + norm_off, transpose=False, dtype=norm_dtype)
        elif name == "lm_head.weight":
            put(params, "lm_head", _pad_vocab_rows(arr, cfg.vocab_size), transpose=True)
        elif base.startswith("layers."):
            _, idx, rest = base.split(".", 2)
            layer = params["layers"][int(idx)]
            match rest:
                case "input_layernorm.weight":
                    put(layer, "attn_norm", arr + norm_off, transpose=False, dtype=norm_dtype)
                case "post_attention_layernorm.weight":
                    # Gemma-2's post_attention_layernorm is a SANDWICH norm
                    # (applied to the attention output); everywhere else it
                    # is the pre-MLP norm.
                    key = "post_attn_norm" if cfg.post_norms else "mlp_norm"
                    put(layer, key, arr + norm_off, transpose=False, dtype=norm_dtype)
                case "pre_feedforward_layernorm.weight":
                    put(layer, "mlp_norm", arr + norm_off, transpose=False, dtype=norm_dtype)
                case "post_feedforward_layernorm.weight":
                    put(layer, "post_ffw_norm", arr + norm_off, transpose=False, dtype=norm_dtype)
                case "self_attn.q_proj.weight":
                    put(layer, "wq", arr, transpose=True)
                case "self_attn.k_proj.weight":
                    put(layer, "wk", arr, transpose=True)
                case "self_attn.v_proj.weight":
                    put(layer, "wv", arr, transpose=True)
                case "self_attn.q_proj.bias" | "self_attn.k_proj.bias" | "self_attn.v_proj.bias":
                    if not cfg.attn_bias:
                        raise ValueError(
                            f"checkpoint carries {name} but the config resolved attn_bias=False"
                        )
                    put(layer, "b" + rest.split(".")[1][0], arr, transpose=False)
                case "self_attn.o_proj.weight":
                    put(layer, "wo", arr, transpose=True)
                case "mlp.gate_proj.weight":
                    put(layer, "w_gate", arr, transpose=True)
                case "mlp.up_proj.weight":
                    put(layer, "w_up", arr, transpose=True)
                case "mlp.down_proj.weight":
                    put(layer, "w_down", arr, transpose=True)
                case "self_attn.q_norm.weight":
                    put(layer, "q_norm", arr, transpose=False)
                case "self_attn.k_norm.weight":
                    put(layer, "k_norm", arr, transpose=False)
                case "self_attn.qkv_proj.weight":
                    # Phi-3 fuses q/k/v into one [nq+2·nkv, d_model] matrix.
                    nq = cfg.n_heads * cfg.head_dim
                    nkv = cfg.n_kv_heads * cfg.head_dim
                    put(layer, "wq", arr[:nq], transpose=True)
                    put(layer, "wk", arr[nq : nq + nkv], transpose=True)
                    put(layer, "wv", arr[nq + nkv :], transpose=True)
                case "mlp.gate_up_proj.weight":
                    # Phi-3 fuses gate/up into one [2·d_ff, d_model] matrix.
                    put(layer, "w_gate", arr[: cfg.d_ff], transpose=True)
                    put(layer, "w_up", arr[cfg.d_ff :], transpose=True)
                case "self_attn.rotary_emb.inv_freq":
                    pass  # derived, not a parameter
                case "block_sparse_moe.gate.weight":
                    put(layer, "router", arr, transpose=True)
                case _ if rest.startswith("block_sparse_moe.experts."):
                    # experts.{i}.w1|w2|w3.weight — w1=gate, w2=down, w3=up
                    parts = rest.split(".")
                    ei, proj = int(parts[2]), parts[3]
                    key = {"w1": "we_gate", "w2": "we_down", "w3": "we_up"}.get(proj)
                    if key is None or parts[4:] != ["weight"]:
                        raise ValueError(f"unrecognized expert tensor: {name}")
                    stage_expert(int(idx), key, ei, arr, transpose=True)
                case _:
                    raise ValueError(f"unrecognized layer tensor: {name}")
        elif name.endswith("rotary_emb.inv_freq"):
            pass
        else:
            raise ValueError(f"unrecognized tensor: {name}")

    for (li, key), lst in staged.items():
        holes = [i for i, a in enumerate(lst) if a is None]
        if holes:
            raise ValueError(f"layer {li} {key}: missing experts {holes[:8]}")
        params["layers"][li][key] = jnp.stack(lst)

    if params["lm_head"] is None:
        # Gemma ties by class default and omits the key from config.json.
        tie_default = hf_cfg.get("model_type") in _GEMMA_FAMILIES
        if not hf_cfg.get("tie_word_embeddings", tie_default):
            raise ValueError("checkpoint has no lm_head and tie_word_embeddings is false")
        params["lm_head"] = params["embed"].T

    missing = [k for k in ("embed", "final_norm") if params[k] is None] + [
        f"layers.{i}.{k}"
        for i, layer in enumerate(params["layers"])
        for k, v in layer.items()
        if v is None
    ]
    if missing:
        raise ValueError(f"checkpoint missing tensors for: {missing[:8]}{'…' if len(missing) > 8 else ''}")
    return params, cfg


def shard_params(params: Params, cfg: LlamaConfig, mesh) -> Params:
    """Place a host param tree onto ``mesh`` per the Megatron TP layout
    (llama.param_specs_like — also places int8 weight-only trees)."""
    from jax.sharding import NamedSharding

    from kakveda_tpu.models.llama import param_specs_like, specs_for_mesh
    from kakveda_tpu.parallel.distributed import put_global

    specs = specs_for_mesh(param_specs_like(params, cfg), mesh)
    return jax.tree.map(
        lambda x, s: put_global(x, NamedSharding(mesh, s)),
        params,
        specs,
    )
