"""In-tree JAX transformer core — the framework's on-pod model runtime.

Replaces the reference's HTTP hop to an external Ollama daemon
(reference: services/dashboard/app.py:1182-1258) with a transformer that
lives on the same TPU mesh as the GFKB index, so the scenario runner,
playground and LLM failure-classifier share the pod. One forward serves
eight HF families — Llama, Mistral, Qwen2/3, Gemma/Gemma-2, Phi-3,
Mixtral — every family delta a flag on :class:`LlamaConfig`
(models/hf_convert.py maps the checkpoints).

Design is TPU-first, pure functional JAX (no framework classes):

  * params are a plain pytree with a parallel tree of ``PartitionSpec``s —
    tensor parallelism shards attention heads and FFN width over the ``tp``
    mesh axis (Megatron layout: column-parallel qkv/gate/up, row-parallel
    o/down; XLA inserts the all-reduces from the sharding constraints);
  * batch is data-parallel over ``dp``; the sequence axis is context-
    parallel over ``cp`` with **ring attention** (shard_map + ppermute with
    an online-softmax accumulator), so long contexts scale across devices
    while weights stay put — see ``ring_attention``;
  * everything jits with static shapes: fixed seq len per call, KV-cache
    decode for generation.

GQA, RoPE, RMSNorm, SwiGLU — Llama-3 architecture; ``LlamaConfig.llama3_8b``
matches the released 8B shapes, tiny configs drive tests and the hermetic
runtime.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kakveda_tpu.parallel.mesh import shard_map as _shard_map

Params = Dict[str, Any]

_NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 264  # ByteTokenizer's 259, padded to a tp-friendly multiple of 8
    d_model: int = 256
    n_layers: int = 2
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 1024
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # Llama-3.1-style NTK rope scaling (HF `rope_scaling.rope_type=llama3`).
    # factor == 1.0 means off. Kept as flat floats so the config stays
    # hashable (it is a static jit argument).
    rope_factor: float = 1.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_len: int = 8192
    # When a checkpoint's vocab is padded up to a TP-friendly multiple,
    # `vocab_size` is the padded table size and `effective_vocab` the real
    # tokenizer vocab; sampling masks logits beyond it. None = no padding.
    effective_vocab: Optional[int] = None
    # Model-family knobs (Qwen2 / Mistral share the Llama block structure):
    # q/k/v projection biases (Qwen2), a sliding attention window in tokens
    # (Mistral; 0 = full causal), and an explicit head_dim for checkpoints
    # where it isn't d_model/n_heads (Mistral-NeMo-style). Flat scalars so
    # the config stays hashable (it is a static jit argument).
    attn_bias: bool = False
    sliding_window: int = 0
    head_dim_opt: int = 0  # 0 = derive from d_model // n_heads
    # Gemma-family deltas: tanh-GELU gate activation (GeGLU) and
    # sqrt(d_model) embedding scaling. Gemma's (1+w) RMSNorm convention
    # needs NO flag — conversion stores the materialized 1+w weights.
    act_fn: str = "silu"  # "silu" | "gelu_tanh"
    scale_embed: bool = False
    # Gemma-2 deltas: alternating per-layer sliding window (even layers
    # slide, odd run full causal), tanh softcapping of attention scores
    # and final logits, an explicit query scale (0 = head_dim**-0.5), and
    # sandwich norms (post-attention / post-feedforward RMSNorms inside
    # each residual branch).
    alt_window: bool = False
    attn_softcap: float = 0.0
    final_softcap: float = 0.0
    query_scale: float = 0.0
    post_norms: bool = False
    # Qwen3-style per-head q/k RMSNorm (over head_dim, applied pre-RoPE).
    qk_norm: bool = False
    # Phi-3 longrope: per-dimension inverse-frequency divisors (length
    # head_dim/2, tuples so the config stays hashable). HF semantics are
    # DYNAMIC: short factors while the running sequence fits the original
    # pretraining context (rope_original_max_len), long factors once it
    # exceeds it; the attention scaling on cos/sin is static.
    rope_dim_factors: tuple = ()  # short factors
    rope_dim_factors_long: tuple = ()
    rope_attn_scaling: float = 1.0
    # KV-cache quantization ("" | "int8"): int8 rows + per-row f32 scales
    # halve the cache — the dominant HBM resident past moderate
    # batch·context — doubling the servable window per chip. Serving-layer
    # knob (KAKVEDA_KV_QUANT=int8 on the runtime), orthogonal to weight
    # quant; parity bounds in tests/test_quant.py.
    kv_quant: str = ""

    def layer_window(self, li: int) -> int:
        """Effective sliding window for layer ``li`` (0 = full causal)."""
        if not self.sliding_window:
            return 0
        if self.alt_window and li % 2 == 1:
            return 0
        return self.sliding_window
    # Sparse Mixture-of-Experts MLP (Mixtral family; models/moe.py).
    # n_experts == 0 means dense. expert_capacity_factor <= 0 means no-drop
    # dispatch (exact; decode + parity tests); positive caps each expert at
    # ceil(T·k/E·factor) tokens per dispatch (training discipline).
    n_experts: int = 0
    n_experts_per_tok: int = 2
    expert_capacity_factor: float = 0.0
    # Load-balancing aux-loss coefficient for MoE fine-tunes (HF Mixtral's
    # router_aux_loss_coef); 0 disables the aux term in lm_loss.
    router_aux_coef: float = 0.0

    @property
    def head_dim(self) -> int:
        return self.head_dim_opt or self.d_model // self.n_heads

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        return cls(**kw)

    @classmethod
    def llama3_8b(cls, vocab_size: int = 128256) -> "LlamaConfig":
        return cls(
            vocab_size=vocab_size,
            d_model=4096,
            n_layers=32,
            n_heads=32,
            n_kv_heads=8,
            d_ff=14336,
            max_seq_len=8192,
            rope_theta=500000.0,
        )


# ---------------------------------------------------------------------------
# parameters
# ---------------------------------------------------------------------------


def init_params(rng: jax.Array, cfg: LlamaConfig) -> Params:
    """He-ish init; params stored in f32, compute in cfg.dtype."""
    keys = jax.random.split(rng, cfg.n_layers + 2)

    def dense(key, fan_in, shape):
        return (jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)).astype(jnp.float32)

    hd = cfg.head_dim
    layers = []
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[i], 7)
        layer = {
            "attn_norm": jnp.ones((cfg.d_model,), jnp.float32),
            "wq": dense(k[0], cfg.d_model, (cfg.d_model, cfg.n_heads * hd)),
            "wk": dense(k[1], cfg.d_model, (cfg.d_model, cfg.n_kv_heads * hd)),
            "wv": dense(k[2], cfg.d_model, (cfg.d_model, cfg.n_kv_heads * hd)),
            "wo": dense(k[3], cfg.n_heads * hd, (cfg.n_heads * hd, cfg.d_model)),
            "mlp_norm": jnp.ones((cfg.d_model,), jnp.float32),
        }
        if cfg.n_experts:
            ke = jax.random.split(k[4], 3)
            layer["router"] = dense(k[5], cfg.d_model, (cfg.d_model, cfg.n_experts))
            layer["we_gate"] = dense(ke[0], cfg.d_model, (cfg.n_experts, cfg.d_model, cfg.d_ff))
            layer["we_up"] = dense(ke[1], cfg.d_model, (cfg.n_experts, cfg.d_model, cfg.d_ff))
            layer["we_down"] = dense(ke[2], cfg.d_ff, (cfg.n_experts, cfg.d_ff, cfg.d_model))
        else:
            layer["w_gate"] = dense(k[4], cfg.d_model, (cfg.d_model, cfg.d_ff))
            layer["w_up"] = dense(k[5], cfg.d_model, (cfg.d_model, cfg.d_ff))
            layer["w_down"] = dense(k[6], cfg.d_ff, (cfg.d_ff, cfg.d_model))
        if cfg.attn_bias:
            layer["bq"] = jnp.zeros((cfg.n_heads * hd,), jnp.float32)
            layer["bk"] = jnp.zeros((cfg.n_kv_heads * hd,), jnp.float32)
            layer["bv"] = jnp.zeros((cfg.n_kv_heads * hd,), jnp.float32)
        if cfg.post_norms:
            layer["post_attn_norm"] = jnp.ones((cfg.d_model,), jnp.float32)
            layer["post_ffw_norm"] = jnp.ones((cfg.d_model,), jnp.float32)
        if cfg.qk_norm:
            layer["q_norm"] = jnp.ones((hd,), jnp.float32)
            layer["k_norm"] = jnp.ones((hd,), jnp.float32)
        layers.append(layer)
    return {
        "embed": dense(keys[-2], cfg.d_model, (cfg.vocab_size, cfg.d_model)),
        "layers": layers,
        "final_norm": jnp.ones((cfg.d_model,), jnp.float32),
        "lm_head": dense(keys[-1], cfg.d_model, (cfg.d_model, cfg.vocab_size)),
    }


def param_specs(cfg: LlamaConfig) -> Params:
    """PartitionSpec tree: Megatron TP layout over the ``tp`` axis."""
    layer = {
        "attn_norm": P(),
        "wq": P(None, "tp"),
        "wk": P(None, "tp"),
        "wv": P(None, "tp"),
        "wo": P("tp", None),
        "mlp_norm": P(),
    }
    if cfg.n_experts:
        # Expert parallelism over ``ep`` on the stacked-expert axis,
        # composing with TP over the ffn width; the router is tiny and
        # replicated.
        layer.update(
            {
                "router": P(),
                "we_gate": P("ep", None, "tp"),
                "we_up": P("ep", None, "tp"),
                "we_down": P("ep", "tp", None),
            }
        )
    else:
        layer.update({"w_gate": P(None, "tp"), "w_up": P(None, "tp"), "w_down": P("tp", None)})
    if cfg.attn_bias:
        # Column-parallel biases follow their projection's out axis.
        layer.update({"bq": P("tp"), "bk": P("tp"), "bv": P("tp")})
    if cfg.post_norms:
        layer.update({"post_attn_norm": P(), "post_ffw_norm": P()})
    if cfg.qk_norm:
        layer.update({"q_norm": P(), "k_norm": P()})
    return {
        "embed": P("tp", None),  # vocab-sharded table
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
        "final_norm": P(),
        "lm_head": P(None, "tp"),
    }


def specs_for_mesh(specs, mesh: Mesh):
    """Drop spec axes the mesh doesn't have (→ replicated on that dim):
    a MoE spec's ``ep`` axis on a dp×tp serving mesh, or ``tp`` on a pure-dp
    mesh, degrades to replication instead of erroring."""
    names = set(mesh.axis_names)

    def fix(s):
        return P(*(a if a in names else None for a in s))

    return jax.tree.map(fix, specs, is_leaf=lambda x: isinstance(x, P))


def _is_quant_leaf(x) -> bool:
    return isinstance(x, dict) and set(x.keys()) == {"q", "s"}


def param_specs_like(params: Params, cfg: LlamaConfig) -> Params:
    """Spec tree matching ``params``' structure — handles int8 weight-only
    leaves (models/quant.py): the int8 matrix shards like the original
    weight and the per-output-channel scale drops the contraction (in) axis
    — sharded for column-parallel projections, replicated for row-parallel,
    and keeping the leading ``ep`` axis for stacked MoE experts."""
    base = param_specs(cfg)

    def expand(w, spec):
        if _is_quant_leaf(w):
            s_spec = P(*spec[:-2], spec[-1]) if len(spec) >= 2 else P(None)
            return {"q": spec, "s": s_spec}
        return spec

    return jax.tree.map(expand, params, base, is_leaf=_is_quant_leaf)


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------



def wmat(w, dt) -> jax.Array:
    """Materialize a dense weight at compute dtype. Accepts a raw array or
    an int8 weight-only pair ``{"q", "s"}`` (models/quant.py) — the dequant
    multiply fuses into the consuming matmul, so quantized weights stream
    from HBM at int8 width. Handles 2-D dense and stacked [E, in, out]
    MoE expert weights alike (scale broadcasts over the in axis)."""
    if isinstance(w, dict):
        return w["q"].astype(dt) * w["s"].astype(dt)[..., None, :]
    return w.astype(dt)

def qkv_proj(
    h: jax.Array, layer: Params, cfg: LlamaConfig, dt
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """q/k/v projections with optional attention biases (Qwen2-style).
    h: [B, S, d_model] -> q [B,S,H,hd], k/v [B,S,KV,hd]."""
    b, s, _ = h.shape
    hd = cfg.head_dim
    q = h @ wmat(layer["wq"], dt)
    k = h @ wmat(layer["wk"], dt)
    v = h @ wmat(layer["wv"], dt)
    if "bq" in layer:
        q = q + layer["bq"].astype(dt)
        k = k + layer["bk"].astype(dt)
        v = v + layer["bv"].astype(dt)
    q = q.reshape(b, s, cfg.n_heads, hd)
    k = k.reshape(b, s, cfg.n_kv_heads, hd)
    v = v.reshape(b, s, cfg.n_kv_heads, hd)
    if "q_norm" in layer:
        # Qwen3 per-head q/k RMSNorm over head_dim, pre-RoPE.
        q = rms_norm(q, layer["q_norm"], cfg.norm_eps)
        k = rms_norm(k, layer["k_norm"], cfg.norm_eps)
    if cfg.query_scale:
        # The kernels scale scores by head_dim**-0.5; fold an explicit
        # query scale (Gemma-2's query_pre_attn_scalar**-0.5) into q so
        # every kernel stays convention-free. Commutes with RoPE
        # (rotations are linear) — but must apply AFTER the optional
        # q_norm: RMSNorm is scale-invariant, so a pre-norm fold would be
        # silently cancelled for any config combining both flags.
        q = q * jnp.asarray(cfg.query_scale * math.sqrt(hd), dt)
    return q, k, v


def mask_pad_vocab(logits: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """−inf the padded vocab columns (converted checkpoints pad the table
    to a TP-friendly multiple; sampling must never emit a pad id). Works
    on [..., V]; identity when the vocab isn't padded."""
    if cfg.effective_vocab is None:
        return logits
    return logits.at[..., cfg.effective_vocab :].set(-jnp.inf)


def softcap_logits(logits: jax.Array, cap: float) -> jax.Array:
    """Gemma-2 tanh logit softcapping: cap·tanh(x/cap); identity at cap=0.
    The ONE definition shared by every decode path."""
    if not cap:
        return logits
    return cap * jnp.tanh(logits / cap)


def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    if w.dtype == jnp.float32 and x.dtype != jnp.float32:
        # f32 gain weights under a low-precision compute dtype apply in
        # f32 BEFORE the downcast — Gemma's convention (its materialized
        # 1+w gains stay f32 at conversion; bf16 spacing near 1.0 is 2^-8,
        # which would swamp the zero-centered parameterization).
        return ((x32 * scale) * w).astype(x.dtype)
    return (x32 * scale).astype(x.dtype) * w.astype(x.dtype)


def _rope_freqs(
    cfg: LlamaConfig,
    positions: jax.Array,
    seq_len: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables [..., head_dim/2] for given positions.

    With ``rope_factor > 1`` applies Llama-3.1's wavelength-dependent NTK
    scaling (matches HF ``_compute_llama3_parameters``): low-frequency
    components are stretched by ``factor``, high-frequency kept, and the
    band between ``low/high_freq_factor`` wavelength thresholds is blended.

    ``seq_len`` ([B] or scalar) overrides the longrope regime-select
    length. Chunked prefill MUST pass the full prompt length here: an
    early chunk's ``max(positions)+1`` is below ``rope_original_max_len``
    even when the whole prompt is past it, and rotating early-chunk K/V
    with short factors would diverge from single-shot prefill of the same
    prompt (whose positions span the full length).
    """
    half = cfg.head_dim // 2
    inv = 1.0 / (cfg.rope_theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    if cfg.rope_dim_factors:
        # Phi-3 longrope: per-dim frequency divisors. HF switches short →
        # long factors once the running sequence exceeds the original
        # pretraining context (seq_len = max position + 1). The regime is
        # selected PER ROW — batch-global selection (what a shared HF
        # inv_freq buffer does) would let one long sequence flip its
        # co-batched neighbors' rotations, breaking batched-vs-solo
        # parity in the continuous batcher. A traced select; no retrace.
        inv_short = inv / jnp.asarray(cfg.rope_dim_factors, jnp.float32)
        if cfg.rope_dim_factors_long:
            inv_long = inv / jnp.asarray(cfg.rope_dim_factors_long, jnp.float32)
            if seq_len is None:
                eff_len = jnp.max(positions, axis=-1, keepdims=True) + 1
            else:
                eff_len = jnp.asarray(seq_len, jnp.int32)[..., None]
            long_row = eff_len > cfg.rope_original_max_len  # [..., 1]
            ang = positions[..., None].astype(jnp.float32)
            ang = jnp.where(long_row[..., None], ang * inv_long, ang * inv_short)
            scale = cfg.rope_attn_scaling
            return jnp.cos(ang) * scale, jnp.sin(ang) * scale
        inv = inv_short
    if cfg.rope_factor != 1.0:
        wavelen = 2.0 * math.pi / inv
        low_wl = cfg.rope_original_max_len / cfg.rope_low_freq_factor
        high_wl = cfg.rope_original_max_len / cfg.rope_high_freq_factor
        smooth = (cfg.rope_original_max_len / wavelen - cfg.rope_low_freq_factor) / (
            cfg.rope_high_freq_factor - cfg.rope_low_freq_factor
        )
        blended = (1.0 - smooth) * inv / cfg.rope_factor + smooth * inv
        inv = jnp.where(wavelen > low_wl, inv / cfg.rope_factor, jnp.where(wavelen < high_wl, inv, blended))
    ang = positions[..., None].astype(jnp.float32) * inv  # [..., half]
    if cfg.rope_attn_scaling != 1.0:
        return (
            jnp.cos(ang) * cfg.rope_attn_scaling,
            jnp.sin(ang) * cfg.rope_attn_scaling,
        )
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [B, S, H, D]; cos/sin: [B?, S, D/2] broadcast over heads."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[..., None, :].astype(x.dtype)  # [B, S, 1, half]
    s = sin[..., None, :].astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


def _repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, KV, D] -> [B, S, KV*n_rep, D] (GQA broadcast).

    Only the reference-oracle `causal_attention` and the ring fallback use
    this — the production paths keep the group axis explicit
    (models/attention.py) so K/V are never materialized ``n_rep``-wide."""
    if n_rep == 1:
        return x
    b, s, kv, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, kv, n_rep, d)).reshape(b, s, kv * n_rep, d)


def causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, q_off: jax.Array | int = 0, window: int = 0
) -> jax.Array:
    """Plain causal attention — the readable O(S²)-memory reference oracle
    that the fused paths are parity-tested against (tests/test_llama.py).
    q: [B,Sq,H,D], k/v: [B,Sk,H,D] (already GQA-repeated). ``q_off`` is the
    global position of q[0] relative to k[0] (for cached decode); ``window``
    > 0 restricts each query to the last ``window`` positions (sliding-window
    attention, Mistral semantics: keep iff q_pos − k_pos < window). Returns
    [B,Sq,H,D]."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    q_pos = jnp.arange(q.shape[1]) + q_off
    k_pos = jnp.arange(k.shape[1])
    mask = q_pos[:, None] >= k_pos[None, :]
    if window:
        mask &= (q_pos[:, None] - k_pos[None, :]) < window
    scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    n_chunks: int,
    key_block: int = 2048,
    window: int = 0,
    softcap: float = 0.0,
) -> jax.Array:
    """Ring attention body — runs *inside* shard_map, sequence sharded over
    ``axis_name``. Each step attends the local queries against the currently
    held K/V chunk with the right global causal mask, folds the result into
    an online-softmax accumulator, then rotates K/V one hop around the ring
    (ppermute over ICI). FLOP-pattern equivalent to blockwise flash
    attention across devices; no device ever holds the full sequence.

    q: [B, S_local, H_local, D]; k/v: [B, S_local, KV_local, D] —
    **un-repeated** GQA heads, so each ring hop moves the raw KV chunk
    (n_rep× less ICI traffic than rotating repeated heads).

    Within each hop the held chunk is processed in ``key_block``-column
    sub-blocks feeding the SAME online-softmax accumulators, so the
    transient score tensor is [B,KV,R,S_l,key_block] f32 — never
    [..., S_l, S_l]. At S_local = 8k that caps the per-hop scratch at
    ~key_block/S_l of the unblocked cost (blockwise/flash structure at
    the second level, after the ring's device level).
    """
    b, s_l, h, d = q.shape
    kv = k.shape[2]
    r = h // kv
    scale = d**-0.5
    me = jax.lax.axis_index(axis_name)

    q5 = q.reshape(b, s_l, kv, r, d)
    q_pos = me * s_l + jnp.arange(s_l)  # global positions of local queries
    m = jnp.full((b, kv, r, s_l), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, kv, r, s_l), jnp.float32)
    acc = jnp.zeros((b, kv, r, s_l, d), jnp.float32)

    kb = min(key_block, s_l)

    perm = [(j, (j + 1) % n_chunks) for j in range(n_chunks)]
    k_cur, v_cur = k, v
    for i in range(n_chunks):  # static unroll: n_chunks is a mesh constant
        src = (me - i) % n_chunks  # whose chunk we hold this step
        for j in range(0, s_l, kb):  # sub-blocks (static ragged tail ok)
            jb = min(kb, s_l - j)
            k_sub = jax.lax.slice_in_dim(k_cur, j, j + jb, axis=1)
            v_sub = jax.lax.slice_in_dim(v_cur, j, j + jb, axis=1)
            k_pos = src * s_l + j + jnp.arange(jb)
            scores = jnp.einsum("bqgrd,bkgd->bgrqk", q5, k_sub).astype(jnp.float32) * scale
            if softcap:
                scores = softcap_logits(scores, softcap)
            keep2d = q_pos[:, None] >= k_pos[None, :]
            if window:
                keep2d &= (q_pos[:, None] - k_pos[None, :]) < window
            mask = keep2d[None, None, None]
            scores = jnp.where(mask, scores, _NEG_INF)

            blk_max = jnp.max(scores, axis=-1)
            m_new = jnp.maximum(m, blk_max)
            # Re-mask after the exp: if every score in this block is masked
            # the subtraction would give exp(0)=1 on the first such step.
            p = jnp.where(mask, jnp.exp(scores - m_new[..., None]), 0.0)
            corr = jnp.exp(m - m_new)
            l = l * corr + jnp.sum(p, axis=-1)
            acc = acc * corr[..., None] + jnp.einsum(
                "bgrqk,bkgd->bgrqd", p.astype(v_sub.dtype), v_sub
            ).astype(jnp.float32)
            m = m_new

        if i < n_chunks - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)

    out = acc / jnp.maximum(l[..., None], 1e-20)
    # [B, KV, R, S, D] -> [B, S, H, D]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, s_l, h, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _attention_block(
    x: jax.Array,
    layer: Params,
    cfg: LlamaConfig,
    cos: jax.Array,
    sin: jax.Array,
    mesh: Optional[Mesh],
    cp_axis: Optional[str],
    li: int = 0,
) -> jax.Array:
    b, s, _ = x.shape
    hd = cfg.head_dim
    dt = x.dtype
    window = cfg.layer_window(li)

    q, k, v = qkv_proj(x, layer, cfg, dt)

    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    if mesh is not None and cp_axis is not None and mesh.shape[cp_axis] > 1:
        n_cp = mesh.shape[cp_axis]
        tp = "tp" if "tp" in mesh.axis_names else None
        tp_size = mesh.shape[tp] if tp else 1
        if cfg.n_kv_heads % tp_size:
            # TP shards the head axis; grouped ring needs whole KV groups
            # per shard, so fall back to rotating repeated heads.
            k = _repeat_kv(k, cfg.n_heads // cfg.n_kv_heads)
            v = _repeat_kv(v, cfg.n_heads // cfg.n_kv_heads)
        spec = P("dp", cp_axis, tp, None)
        attn = _shard_map(
            partial(
                ring_attention_local,
                axis_name=cp_axis,
                n_chunks=n_cp,
                window=window,
                softcap=cfg.attn_softcap,
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v)
    else:
        # Grouped attention over the whole sequence: K/V head-major, no
        # GQA repeat, differentiable XLA path (training runs through here).
        from kakveda_tpu.models.attention import _gqa_xla

        attn = _gqa_xla(
            q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3), 0, None,
            window=window, softcap=cfg.attn_softcap,
        )

    return attn.reshape(b, s, cfg.n_heads * hd) @ wmat(layer["wo"], dt)


def _act(x: jax.Array, act_fn: str) -> jax.Array:
    if act_fn == "gelu_tanh":  # Gemma's GeGLU gate
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _mlp_block(x: jax.Array, layer: Params, act_fn: str = "silu") -> jax.Array:
    dt = x.dtype
    gate = _act(x @ wmat(layer["w_gate"], dt), act_fn)
    up = x @ wmat(layer["w_up"], dt)
    return (gate * up) @ wmat(layer["w_down"], dt)


def embed_tokens(params: Params, cfg: LlamaConfig, tokens: jax.Array) -> jax.Array:
    """Token embedding at compute dtype; Gemma scales by sqrt(d_model)."""
    x = params["embed"].astype(cfg.dtype)[tokens]
    if cfg.scale_embed:
        x = x * jnp.asarray(math.sqrt(cfg.d_model), cfg.dtype)
    return x


def mlp_block(
    x: jax.Array, layer: Params, cfg: LlamaConfig, return_aux: bool = False
):
    """Dense SwiGLU or sparse-MoE MLP, keyed on the layer's params
    (MoE layers carry a ``router``; models/moe.py). With ``return_aux``
    returns ``(out, aux)`` — aux is the layer's load-balancing loss
    (0 for dense layers)."""
    if "router" in layer:
        from kakveda_tpu.models.moe import moe_mlp

        return moe_mlp(x, layer, cfg, return_aux=return_aux)
    out = _mlp_block(x, layer, cfg.act_fn)
    return (out, jnp.zeros((), jnp.float32)) if return_aux else out


def forward(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,
    *,
    mesh: Optional[Mesh] = None,
    cp_axis: Optional[str] = None,
    positions: Optional[jax.Array] = None,
    with_aux: bool = False,
):
    """Full-sequence forward: tokens [B, S] -> logits [B, S, vocab].

    With ``mesh``+``cp_axis`` the sequence axis is context-parallel and
    attention runs as a ring over that axis; RoPE positions are the *global*
    positions, threaded in by the caller via ``positions`` when the local
    shard doesn't start at 0 (handled automatically under jit because the
    whole [B, S] array is logically global). ``with_aux`` returns
    ``(logits, aux)`` where aux is the summed MoE load-balancing loss
    across layers (0 for dense models).
    """
    b, s = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    cos, sin = _rope_freqs(cfg, positions)

    x = embed_tokens(params, cfg, tokens)
    aux = jnp.zeros((), jnp.float32)
    for li, layer in enumerate(params["layers"]):
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        attn = _attention_block(h, layer, cfg, cos, sin, mesh, cp_axis, li)
        if "post_attn_norm" in layer:  # Gemma-2 sandwich norm
            attn = rms_norm(attn, layer["post_attn_norm"], cfg.norm_eps)
        x = x + attn
        h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        m, a = mlp_block(h, layer, cfg, return_aux=True)
        if "post_ffw_norm" in layer:
            m = rms_norm(m, layer["post_ffw_norm"], cfg.norm_eps)
        x = x + m
        aux = aux + a

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ wmat(params["lm_head"], cfg.dtype)).astype(jnp.float32)
    logits = softcap_logits(logits, cfg.final_softcap)
    return (logits, aux) if with_aux else logits


# ---------------------------------------------------------------------------
# KV-cache decode
# ---------------------------------------------------------------------------


def init_cache(cfg: LlamaConfig, batch: int, max_len: Optional[int] = None) -> Params:
    """Per-layer K/V buffer lists, **head-major** [B, KV, max_len, hd]: each
    kv-head's rows are contiguous, so the flash kernel DMA-streams
    [l_blk, hd] tiles without striding over the head axis. Each layer's
    buffer is dynamic-update-sliced independently, which XLA turns into
    in-place row writes — one stacked [L, ...] array (whether rebuilt with
    jnp.stack or updated with a leading-dim DUS) either rewrites the whole
    cache per decode step or compiles pathologically at 1B scale.

    With ``cfg.kv_quant == "int8"`` the K/V buffers are int8 with per-row
    (per position, per kv-head) f32 scales ``ks``/``vs`` [B, KV, max_len]:
    the cache — the dominant HBM resident past moderate batch·context —
    halves, doubling the servable context window per chip. Rows quantize
    on write and dequantize on read (`_kv_quant_rows`/`_kv_dequant`)."""
    ml = max_len or cfg.max_seq_len
    hd = cfg.head_dim
    shape = (batch, cfg.n_kv_heads, ml, hd)
    if cfg.kv_quant == "int8":
        return {
            "pos": jnp.zeros((), jnp.int32),
            "k": [jnp.zeros(shape, jnp.int8) for _ in range(cfg.n_layers)],
            "v": [jnp.zeros(shape, jnp.int8) for _ in range(cfg.n_layers)],
            "ks": [jnp.zeros(shape[:3], jnp.float32) for _ in range(cfg.n_layers)],
            "vs": [jnp.zeros(shape[:3], jnp.float32) for _ in range(cfg.n_layers)],
        }
    return {
        "pos": jnp.zeros((), jnp.int32),
        "k": [jnp.zeros(shape, cfg.dtype) for _ in range(cfg.n_layers)],
        "v": [jnp.zeros(shape, cfg.dtype) for _ in range(cfg.n_layers)],
    }


def _kv_quant_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-row int8 quantization of K/V rows [..., hd]:
    returns (int8 values, f32 scales [...]) with x ≈ q · scale. Per-row
    absmax keeps the error relative to each position's own magnitude —
    a shared tensor scale would crush early-layer K norms."""
    x32 = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(x32), axis=-1) / 127.0
    safe = jnp.maximum(s, 1e-8)[..., None]
    q = jnp.clip(jnp.round(x32 / safe), -127, 127).astype(jnp.int8)
    return q, s


def _kv_dequant(q: jax.Array, s: jax.Array, dtype) -> jax.Array:
    """Inverse of :func:`_kv_quant_rows`; unwritten slots carry scale 0 and
    dequantize to exact zeros (masked by kv_valid/causality anyway)."""
    return q.astype(dtype) * s[..., None].astype(dtype)


def decode_step(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, S] — prompt chunk or single sampled token
    cache: Params,
    kv_valid: Optional[jax.Array] = None,  # [B, max_len] — False masks pad slots
    pos_offset: Optional[jax.Array] = None,  # [B] — logical-position shift (left-pad)
    last_only: bool = False,
    seq_total: Optional[jax.Array] = None,  # [B] — full-sequence length for longrope
) -> Tuple[jax.Array, Params]:
    """Incremental forward with KV cache; returns (logits [B, S, V], cache).

    ``kv_valid``/``pos_offset`` enable exact left-padded batching: sequence
    b's real tokens sit in cache slots [offset_b, …], RoPE positions are
    slot − offset_b (so they match the unpadded sequence), and attention
    never reads a pad slot. Both default to the unpadded single-stream
    behavior.

    ``seq_total`` (per-row full prompt length) overrides the Phi-3
    longrope short/long regime select — REQUIRED for chunked prefill so
    early chunks rotate with the same regime single-shot prefill would
    use (see :func:`_rope_freqs`); decode steps leave it None (the running
    length, HF's dynamic-switch semantics).

    ``last_only=True`` computes final-norm + lm_head for the last position
    only (logits [B, 1, V]) — sampling never reads the others, and at
    serving shapes the full-prefill vocab projection
    (2·B·S·d_model·vocab FLOPs) costs more than the entire rest of the
    prefill.
    """
    from kakveda_tpu.models.attention import gqa_cache_attention

    b, s = tokens.shape
    pos0 = cache["pos"]
    positions = jnp.broadcast_to(jnp.arange(s) + pos0, (b, s))
    if pos_offset is not None:
        positions = positions - pos_offset[:, None]
    cos, sin = _rope_freqs(cfg, positions, seq_total)
    hd = cfg.head_dim

    x = embed_tokens(params, cfg, tokens)
    kq = cfg.kv_quant == "int8"
    new_k: list = []
    new_v: list = []
    new_ks: list = []
    new_vs: list = []
    for li, layer in enumerate(params["layers"]):
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        dt = h.dtype
        q, k, v = qkv_proj(h, layer, cfg, dt)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

        # Head-major cache writes: [B, S, KV, D] -> [B, KV, S, D] slab.
        k_rows = k.transpose(0, 2, 1, 3)
        v_rows = v.transpose(0, 2, 1, 3)
        ks_all = vs_all = None
        if kq:
            k_i8, k_sc = _kv_quant_rows(k_rows)
            v_i8, v_sc = _kv_quant_rows(v_rows)
            k_all = jax.lax.dynamic_update_slice(cache["k"][li], k_i8, (0, 0, pos0, 0))
            v_all = jax.lax.dynamic_update_slice(cache["v"][li], v_i8, (0, 0, pos0, 0))
            ks_all = jax.lax.dynamic_update_slice(cache["ks"][li], k_sc, (0, 0, pos0))
            vs_all = jax.lax.dynamic_update_slice(cache["vs"][li], v_sc, (0, 0, pos0))
            new_ks.append(ks_all)
            new_vs.append(vs_all)
        else:
            k_all = jax.lax.dynamic_update_slice(
                cache["k"][li], k_rows.astype(cfg.dtype), (0, 0, pos0, 0)
            )
            v_all = jax.lax.dynamic_update_slice(
                cache["v"][li], v_rows.astype(cfg.dtype), (0, 0, pos0, 0)
            )
        new_k.append(k_all)
        new_v.append(v_all)

        # Fused cached attention: Pallas flash on TPU, grouped XLA einsum
        # elsewhere — either way K/V are read once, not n_rep times, and
        # the causal mask (q_pos >= slot) also excludes unwritten slots.
        # int8 caches pass raw tiles + scales: the flash kernel streams
        # int8 from HBM and dequantizes in VMEM (the bandwidth win).
        attn = gqa_cache_attention(
            q, k_all, v_all, pos0, kv_valid,
            window=cfg.layer_window(li), softcap=cfg.attn_softcap,
            k_scale=ks_all, v_scale=vs_all,
        )
        attn = attn.reshape(b, s, cfg.n_heads * hd) @ wmat(layer["wo"], dt)
        if "post_attn_norm" in layer:  # Gemma-2 sandwich norm
            attn = rms_norm(attn, layer["post_attn_norm"], cfg.norm_eps)
        x = x + attn

        h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        m = mlp_block(h, layer, cfg)
        if "post_ffw_norm" in layer:
            m = rms_norm(m, layer["post_ffw_norm"], cfg.norm_eps)
        x = x + m

    if last_only:
        x = x[:, -1:, :]
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ wmat(params["lm_head"], cfg.dtype)).astype(jnp.float32)
    logits = softcap_logits(logits, cfg.final_softcap)
    new_cache = {"pos": pos0 + s, "k": new_k, "v": new_v}
    if kq:
        new_cache["ks"] = new_ks
        new_cache["vs"] = new_vs
    return logits, new_cache
