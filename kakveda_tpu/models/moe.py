"""Sparse Mixture-of-Experts MLP for the Llama runtime (Mixtral family).

The reference's model tier is an HTTP client to an Ollama daemon
(reference: services/dashboard/app.py:1182-1258), which is how it "supports"
MoE checkpoints like Mixtral. Here the MoE block is a first-class layer on
the same runtime/mesh as everything else, designed TPU-first:

  * **Routing** matches HF Mixtral semantics exactly: f32 softmax over all
    expert logits, top-k, renormalize the kept weights
    (``transformers`` MixtralSparseMoeBlock) — parity-tested in
    tests/test_hf_convert.py.
  * **Dispatch** is sort-based with a static per-expert capacity: the
    [T·k] (token, choice) assignments are argsorted by expert, each lands
    in slot ``expert·cap + position_in_expert``, and tokens beyond an
    expert's capacity are dropped (GShard discipline, position-priority).
    Everything is static-shaped — no ragged tensors, no data-dependent
    control flow — so the whole block jits and differentiates.
  * **Compute** is one batched einsum per projection over the stacked
    expert weights ``[E, d_model, d_ff]`` — E MXU matmuls batched on the
    leading axis, not a Python loop over experts.
  * **Expert parallelism**: the stacked-E leading axis is the ``ep`` mesh
    axis (llama.param_specs), composing with tensor parallelism over the
    ffn width (``we_gate [E, D, F]`` shards P("ep", None, "tp")). XLA
    partitions the batched einsums over both axes and inserts the
    dispatch/combine collectives from the shardings.

Capacity: ``cfg.expert_capacity_factor <= 0`` means no-drop (capacity = T,
exact — what parity tests and decode steps use; decode T is the batch
size, so the buffer stays small). A positive factor caps each expert at
``ceil(T·k/E · factor)`` tokens, the standard training configuration.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from kakveda_tpu.models.llama import LlamaConfig, Params, wmat


def router_topk(logits: jax.Array, k: int) -> tuple[jax.Array, jax.Array, jax.Array]:
    """HF-Mixtral routing: softmax over ALL experts in f32, take top-k,
    renormalize the kept mass. Returns (weights [T,k], expert_idx [T,k],
    full_probs [T,E] — the latter feeds the load-balancing loss)."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    w, idx = jax.lax.top_k(probs, k)
    w = w / jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1e-20)
    return w, idx, probs


def expert_capacity(n_tokens: int, cfg: LlamaConfig) -> int:
    """Static per-expert token capacity for a T-token dispatch."""
    f = cfg.expert_capacity_factor
    if f <= 0.0:
        return n_tokens
    k, e = cfg.n_experts_per_tok, cfg.n_experts
    return min(n_tokens, max(1, math.ceil(n_tokens * k / e * f)))


def moe_mlp(
    x: jax.Array, layer: Params, cfg: LlamaConfig, return_aux: bool = False
):
    """Sparse-MoE SwiGLU MLP: x [B, S, D] -> [B, S, D] (or ``(out, aux)``
    with ``return_aux`` — aux is this layer's load-balancing loss, which
    the training objective adds at ``cfg.router_aux_coef``).

    Layer params: ``router`` [D, E], stacked ``we_gate``/``we_up``
    [E, D, F], ``we_down`` [E, F, D] (llama.init_params / Mixtral
    conversion in models/hf_convert.py).
    """
    b, s, d = x.shape
    dt = x.dtype
    e, k = cfg.n_experts, cfg.n_experts_per_tok
    t = b * s
    xf = x.reshape(t, d)

    logits = xf.astype(jnp.float32) @ layer["router"].astype(jnp.float32)
    w, idx, probs = router_topk(logits, k)  # [T, k]

    cap = expert_capacity(t, cfg)

    # Flatten (token, choice) assignments and sort by expert. Stable sort
    # keeps token order within an expert => position-priority capacity drop.
    e_flat = idx.reshape(t * k)
    w_flat = w.reshape(t * k)
    tok_flat = jnp.arange(t * k, dtype=jnp.int32) // k
    order = jnp.argsort(e_flat, stable=True)
    e_sorted = e_flat[order]
    tok_sorted = tok_flat[order]

    # Position within the expert's group: running index minus the group's
    # start offset (exclusive cumsum of per-expert counts).
    counts = jnp.zeros((e,), jnp.int32).at[e_sorted].add(1)
    starts = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1]])
    pos = jnp.arange(t * k, dtype=jnp.int32) - starts[e_sorted]

    # Slot in the [E·cap] dispatch buffer; over-capacity rows drop.
    slot = e_sorted * cap + pos
    keep = pos < cap
    slot = jnp.where(keep, slot, e * cap)  # out-of-range => .at[].set drop

    buf = jnp.zeros((e * cap, d), dt).at[slot, :].set(xf[tok_sorted], mode="drop")
    xe = buf.reshape(e, cap, d)

    # Batched expert SwiGLU on the MXU; E axis shards over ``ep``,
    # F over ``tp``.
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, wmat(layer["we_gate"], dt)))
    up = jnp.einsum("ecd,edf->ecf", xe, wmat(layer["we_up"], dt))
    ye = jnp.einsum("ecf,efd->ecd", gate * up, wmat(layer["we_down"], dt))

    # Combine: read each assignment's expert output back from its slot and
    # scatter-add the routing-weighted result into the token rows.
    y_rows = ye.reshape(e * cap, d)[jnp.minimum(slot, e * cap - 1)]
    contrib = y_rows * (w_flat[order] * keep.astype(jnp.float32))[:, None].astype(dt)
    out = jnp.zeros((t, d), dt).at[tok_sorted, :].add(contrib)
    out = out.reshape(b, s, d)
    if return_aux:
        return out, load_balancing_loss(probs, idx, e, k)
    return out


def load_balancing_loss(
    router_probs: jax.Array, expert_idx: jax.Array, n_experts: int, top_k: int = 1
) -> jax.Array:
    """Switch/Mixtral auxiliary load-balancing loss: E · Σ_e f_e · P_e,
    where f_e is the per-TOKEN fraction routed to expert e (assignment
    counts / T — each token contributes ``top_k`` counts, matching HF
    ``load_balancing_loss_func``'s sum of one-hot means over the top-k
    slots; normalizing by T·k instead would shrink the term by 1/k and
    silently under-weight HF-sourced ``router_aux_loss_coef`` values) and
    P_e the mean router probability of e. Minimized (=top_k) by uniform
    routing; add ``coef · loss`` to the LM loss when fine-tuning a MoE
    config (HF ``router_aux_loss_coef``)."""
    probs = router_probs.reshape(-1, n_experts)
    idx = expert_idx.reshape(-1)
    t = jnp.maximum(idx.size // max(top_k, 1), 1)
    f = jnp.zeros((n_experts,), jnp.float32).at[idx].add(1.0) / t
    p = jnp.mean(probs, axis=0)
    return n_experts * jnp.sum(f * p)
