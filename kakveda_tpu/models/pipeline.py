"""Pipeline parallelism (GPipe) for the transformer runtime.

The reference has no model parallelism of any kind (SURVEY §2.9 — its model
tier is an HTTP call). This module completes the framework's parallelism
set — dp (batch), cp (ring attention over sequence), tp (Megatron), ep
(MoE experts) — with **pp**: layers split into contiguous stages placed on
a ``pp`` mesh axis, microbatches streamed through the stages, activations
hopping stage→stage over ICI (``ppermute``).

Design (TPU-first, shard_map-manual):

  * **Stage-stacked params**: the per-layer dicts are re-packed into one
    pytree whose layer arrays carry a leading ``[n_stages, layers_per_stage,
    …]`` axis sharded ``P("pp")`` — each device materializes ONLY its own
    stage's weights (1/S of the model), which is the point of pp: models
    that don't fit one chip.
  * **GPipe schedule**: ``n_micro + n_stages − 1`` ticks. At tick t, stage
    s runs microbatch ``t − s`` (when in range): stage 0 feeds from the
    input queue, later stages from the activation received over the ring
    at the end of the previous tick. The loop is a ``lax.scan`` with static
    length — fully compiled, no host round-trips per tick.
  * **Within a stage**: ``lax.scan`` over the stacked layer axis running
    the same attention/MLP blocks as the dense forward (MoE layers
    included), so pp needs no model-code fork.
  * Embedding / final norm / lm head run replicated outside the shard_map
    region (tiny next to the layer stack).

Composition and trade-offs: pp as implemented composes with the data axes
(microbatching IS batch splitting); it is the *inter-op* alternative to
the *intra-op* tp/ep sharding — shard_map is manual-mode, so stage weights
inside the region don't also auto-shard over tp. Pick pp when the model
doesn't fit (weights 1/S per chip), tp when latency matters. Bubble
fraction is the GPipe ``(S−1)/(M+S−1)``; raise ``n_micro`` to amortize.

Parity: ``pp_forward`` reproduces ``llama.forward`` logits exactly
(tests/test_pipeline_parallel.py), and ``make_pp_train_step`` trains
the same loss as the dense step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kakveda_tpu.parallel.mesh import shard_map as _shard_map
from kakveda_tpu.models.llama import (
    LlamaConfig,
    Params,
    _attention_block,
    _rope_freqs,
    embed_tokens,
    mlp_block,
    param_specs,
    rms_norm,
)


def split_stages(params: Params, cfg: LlamaConfig, n_stages: int) -> Params:
    """Re-pack the flat layer list into stage-stacked arrays
    ``[n_stages, layers_per_stage, …]`` (leading axis shards over ``pp``)."""
    if cfg.n_layers % n_stages:
        raise ValueError(f"{cfg.n_layers} layers do not split into {n_stages} stages")
    per = cfg.n_layers // n_stages
    layers = params["layers"]
    stacked = jax.tree.map(
        lambda *leaves: jnp.stack(leaves).reshape((n_stages, per) + leaves[0].shape),
        *layers,
    )
    return {
        "embed": params["embed"],
        "stages": stacked,
        "final_norm": params["final_norm"],
        "lm_head": params["lm_head"],
    }


def pp_param_specs(cfg: LlamaConfig) -> Params:
    """Spec tree for the stage-stacked FLOAT layout (the training path):
    stage arrays P("pp", …), embed/norm/head replicated (they run outside
    the pipelined region). For serving trees that may carry int8 pairs,
    ``place_stacked`` derives specs from the actual structure instead."""
    layer = param_specs(cfg)["layers"][0]
    stacked = jax.tree.map(lambda s: P("pp"), layer, is_leaf=lambda x: isinstance(x, P))
    return {
        "embed": P(),
        "stages": stacked,
        "final_norm": P(),
        "lm_head": P(),
    }


def _stage_apply(x: jax.Array, stage_layers: Params, cfg: LlamaConfig, cos, sin) -> jax.Array:
    """Run one stage's stacked layers over activations x [mb, S, D]."""

    def layer_step(h, layer):
        a = rms_norm(h, layer["attn_norm"], cfg.norm_eps)
        attn = _attention_block(a, layer, cfg, cos, sin, None, None)
        if "post_attn_norm" in layer:  # Gemma-2 sandwich norm
            attn = rms_norm(attn, layer["post_attn_norm"], cfg.norm_eps)
        h = h + attn
        a = rms_norm(h, layer["mlp_norm"], cfg.norm_eps)
        m = mlp_block(a, layer, cfg)
        if "post_ffw_norm" in layer:
            m = rms_norm(m, layer["post_ffw_norm"], cfg.norm_eps)
        h = h + m
        return h, None

    x, _ = jax.lax.scan(layer_step, x, stage_layers)
    return x


def pp_forward(
    stacked: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, S]
    mesh: Mesh,
    n_micro: int = 4,
    pp_axis: str = "pp",
) -> jax.Array:
    """Pipelined full-sequence forward: tokens [B, S] -> logits [B, S, V].

    ``B`` must divide into ``n_micro`` microbatches; bubble fraction is
    (S−1)/(n_micro+S−1)."""
    n_stages = mesh.shape[pp_axis]
    b, s = tokens.shape
    if b % n_micro:
        raise ValueError(f"batch {b} does not split into {n_micro} microbatches")
    if cfg.alt_window:
        # The stage body scans layers with ONE static attention mask;
        # Gemma-2's per-layer alternating window would need per-iteration
        # masks. Serve those models on the tp/ep paths instead.
        raise ValueError("pipeline parallelism does not support alternating windows")
    mb = b // n_micro

    positions = jnp.broadcast_to(jnp.arange(s), (mb, s))
    cos, sin = _rope_freqs(cfg, positions)

    x = embed_tokens(stacked, cfg, tokens)
    x_mb = x.reshape(n_micro, mb, s, -1)

    n_ticks = n_micro + n_stages - 1

    def pp_body(stages_local, x_all, cos_, sin_):
        # stages_local: stage arrays with local leading dim 1 — this
        # device's stage. x_all: every microbatch (replicated over pp).
        me = jax.lax.axis_index(pp_axis)
        layers_here = jax.tree.map(lambda a: a[0], stages_local)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            recv, outs = carry
            # Stage 0 consumes microbatch t (clamped; out-of-range ticks
            # produce garbage that never reaches outs). Other stages
            # consume what arrived over the ring last tick.
            src = x_all[jnp.clip(t, 0, n_micro - 1)]
            inp = jnp.where(me == 0, src, recv)
            y = _stage_apply(inp, layers_here, cfg, cos_, sin_)
            # Last stage banks microbatch t − (S−1) when in range.
            oi = t - (n_stages - 1)
            oc = jnp.clip(oi, 0, n_micro - 1)
            bank = (me == n_stages - 1) & (oi >= 0)
            prev_row = jax.lax.dynamic_index_in_dim(outs, oc, 0, keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(bank, y, prev_row), oc, 0
            )
            recv = jax.lax.ppermute(y, pp_axis, perm)
            return (recv, outs), None

        outs0 = jnp.zeros_like(x_all)
        (_, outs), _ = jax.lax.scan(
            tick, (jnp.zeros_like(x_all[0]), outs0), jnp.arange(n_ticks)
        )
        # Only the last stage's banked outputs are real; psum with the
        # others zeroed replicates them to every pp member.
        outs = jnp.where(me == n_stages - 1, outs, 0.0)
        return jax.lax.psum(outs, pp_axis)

    stage_spec = jax.tree.map(lambda a: P(pp_axis), stacked["stages"])
    y_mb = _shard_map(
        pp_body,
        mesh=mesh,
        in_specs=(stage_spec, P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )(stacked["stages"], x_mb, cos, sin)

    y = y_mb.reshape(b, s, -1)
    y = rms_norm(y, stacked["final_norm"], cfg.norm_eps)
    from kakveda_tpu.models.llama import softcap_logits, wmat

    logits = (y @ wmat(stacked["lm_head"], cfg.dtype)).astype(jnp.float32)
    return softcap_logits(logits, cfg.final_softcap)


def place_stacked(stacked: Params, cfg: LlamaConfig, mesh: Mesh) -> Params:
    """Place a stage-stacked tree on the mesh (stages over ``pp``). Specs
    derive from the actual tree structure, so int8 weight-only pairs
    ``{"q","s"}`` (models/quant.py) place too — both members carry the
    stage axis."""
    specs = {
        "embed": jax.tree.map(lambda a: P(), stacked["embed"]),
        "stages": jax.tree.map(lambda a: P("pp"), stacked["stages"]),
        "final_norm": P(),
        "lm_head": jax.tree.map(lambda a: P(), stacked["lm_head"]),
    }
    return jax.tree.map(
        lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)), stacked, specs
    )


def make_pp_train_step(
    cfg: LlamaConfig,
    mesh: Mesh,
    n_micro: int = 4,
    lr: float = 3e-4,
):
    """Jitted pipelined training step; returns (step, init_state).

    Same causal-LM loss as models/train.py, gradients flow back through the
    pipeline ticks (ppermute transposes to the reverse rotation)."""
    import optax

    n_stages = mesh.shape["pp"]
    opt = optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=0.01)
    specs = pp_param_specs(cfg)
    shardings = jax.tree.map(
        lambda sp: NamedSharding(mesh, sp), specs, is_leaf=lambda x: isinstance(x, P)
    )
    repl = NamedSharding(mesh, P())

    def loss_fn(stacked, tokens):
        from kakveda_tpu.models.train import lm_loss_from_logits

        logits = pp_forward(stacked, cfg, tokens, mesh, n_micro=n_micro)
        return lm_loss_from_logits(logits, tokens)

    def _init(rng):
        from kakveda_tpu.models.llama import init_params

        stacked = split_stages(init_params(rng, cfg), cfg, n_stages)
        return stacked, opt.init(stacked)

    # Param shardings are pinned; the AdamW state (mu/nu mirror the param
    # tree) is left unspecified — GSPMD derives it from the init
    # computation, which keeps each stage's moments on its stage's devices.
    init_state = jax.jit(_init, out_shardings=(shardings, None))

    def _step(stacked, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(stacked, tokens)
        updates, opt_state = opt.update(grads, opt_state, stacked)
        stacked = optax.apply_updates(stacked, updates)
        return stacked, opt_state, loss

    step = jax.jit(
        _step,
        in_shardings=(shardings, None, repl),
        out_shardings=(shardings, None, repl),
        donate_argnums=(0, 1),
    )
    return step, init_state
