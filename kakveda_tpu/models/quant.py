"""Weight-only int8 quantization for the serving path.

Decode throughput on a single chip is weight-bandwidth-bound: every step
streams every dense matrix out of HBM (2.2 GB at 1.1B bf16). Symmetric
per-output-channel int8 halves that stream — the dequantize (one multiply
by a [out] scale row) fuses into the consuming matmul's weight-operand
read, so HBM traffic is int8-sized while the MXU still accumulates in
f32/bf16.

Representation: a quantized dense weight is the pytree leaf pair
``{"q": int8 [in, out], "s": f32 [out]}`` — ``llama.wmat`` materializes
either form, so forward/decode code is quantization-agnostic. RMSNorm
gains and the embedding table stay unquantized (tiny, and the embedding
is a gather, not a matmul).

Scope: inference only (the quantized tree is not a training target).
Enable with ``LlamaRuntime(..., quant="int8")`` or ``KAKVEDA_QUANT=int8``
for the env-built runtime. The reference has no comparable surface — its
"model runtime" is an HTTP client to an external Ollama daemon
(reference: services/dashboard/app.py:1182-1258).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

_DENSE_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "we_gate", "we_up", "we_down")


def quantize_tensor_int8(w: jax.Array) -> Dict[str, jax.Array]:
    """Symmetric per-output-channel (last axis) int8: q = round(w / s),
    s = absmax / 127 per output column. Works for 2-D dense weights
    ([in, out] → s [out]) and stacked MoE expert weights
    ([E, in, out] → s [E, out]) alike — the reduction is over the
    contraction (in) axis."""
    w32 = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(w32), axis=-2) / 127.0
    s = jnp.where(s == 0.0, 1.0, s)
    q = jnp.clip(jnp.round(w32 / s[..., None, :]), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def quantize_params_int8(params: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize every dense projection (incl. stacked MoE experts — on
    Mixtral the expert FFNs are ~95% of weight bytes) + lm_head; keep
    norms, biases, the MoE router and the embedding."""
    out: Dict[str, Any] = {
        "embed": params["embed"],
        "final_norm": params["final_norm"],
        "lm_head": quantize_tensor_int8(params["lm_head"]),
        "layers": [],
    }
    for layer in params["layers"]:
        ql = {}
        for k, v in layer.items():
            ql[k] = quantize_tensor_int8(v) if k in _DENSE_KEYS else v
        out["layers"].append(ql)
    return out


def quantization_error(params: Dict[str, Any], qparams: Dict[str, Any]) -> float:
    """Max relative per-tensor reconstruction error across dense weights
    (test/diagnostic helper)."""
    worst = 0.0
    for orig, quant in zip(params["layers"], qparams["layers"]):
        for k in _DENSE_KEYS:
            if k not in orig:
                continue
            w = orig[k].astype(jnp.float32)
            wq = quant[k]["q"].astype(jnp.float32) * quant[k]["s"][..., None, :]
            num = jnp.max(jnp.abs(w - wq))
            den = jnp.max(jnp.abs(w))
            worst = max(worst, float(num / den))
    return worst
