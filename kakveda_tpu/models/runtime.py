"""Model runtime abstraction: stub | tpu (JAX Llama) | ollama.

The reference calls Ollama over HTTP and falls back to a deterministic
citation-bearing stub on any error
(reference: services/dashboard/app.py:1182-1258,
scripts/demo_client.py:23-40). That stub *is* the test backend: it always
emits fake citations, so the full failure pipeline is exercisable with no
LLM.

Here the runtime is a first-class interface:

  * ``StubRuntime`` — byte-for-byte the reference's canned response, zero
    dependencies, the hermetic default.
  * ``LlamaRuntime`` (kakveda_tpu.models.llama) — the in-tree JAX Llama,
    TP-sharded on the same mesh as the GFKB index; replaces the Ollama HTTP
    hop with an on-pod forward pass.
  * ``OllamaRuntime`` — HTTP client kept for drop-in compatibility with
    reference deployments; falls back to the stub like the reference does.

Every result carries provider/model/latency metadata in the reference's
meta shape so dashboards and eval scorecards transfer unchanged.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Protocol

# The reference's exact stub text (services/dashboard/app.py:1193-1199) —
# fake citations that trip the rule classifier deterministically.
STUB_RESPONSE = (
    "Here is a summary with references.\n\n"
    "References:\n"
    "[1] Smith et al. (2020) A Study on Things.\n"
    "[2] Doe (2021) Another Paper.\n"
)


@dataclass
class GenerateResult:
    text: str
    meta: Dict[str, Any] = field(default_factory=dict)


class ModelRuntime(Protocol):
    name: str

    def generate(self, prompt: str, *, model: Optional[str] = None, max_tokens: int = 256) -> GenerateResult: ...


def generate_batch(
    runtime: "ModelRuntime", prompts: list, *, model: Optional[str] = None, max_tokens: int = 256
) -> list:
    """Batched generation through whatever the runtime offers: the TPU
    runtime decodes the whole list in one left-padded stream
    (LlamaRuntime.generate_batch); stub/ollama fall back to a per-prompt
    loop. Callers (eval runner, LLM classifier) stay runtime-agnostic."""
    fn = getattr(runtime, "generate_batch", None)
    if callable(fn):
        return fn(prompts, model=model, max_tokens=max_tokens)
    return [runtime.generate(p, model=model, max_tokens=max_tokens) for p in prompts]


def list_models(runtime: "ModelRuntime") -> list:
    """Model names the runtime can serve, for the playground dropdown
    (reference: services/dashboard/app.py:286-306, Ollama /api/tags).
    Runtimes advertise via a ``list_models`` method; anything else falls
    back to a single entry."""
    fn = getattr(runtime, "list_models", None)
    if callable(fn):
        try:
            return list(fn()) or [getattr(runtime, "name", "model")]
        except Exception:  # noqa: BLE001 — listing is best-effort
            pass
    return [getattr(runtime, "name", "model")]


class StubRuntime:
    """Deterministic canned-response backend — the hermetic test model."""

    name = "stub"

    def __init__(self, model_label: str = "stub"):
        self.model_label = model_label

    def list_models(self) -> list:
        return [self.model_label]

    def generate(self, prompt: str, *, model: Optional[str] = None, max_tokens: int = 256) -> GenerateResult:
        started = time.perf_counter()
        text = STUB_RESPONSE
        return GenerateResult(
            text=text,
            meta={
                "provider": "stub",
                "model": model or self.model_label,
                "latency_ms": int((time.perf_counter() - started) * 1000),
            },
        )


class OllamaRuntime:
    """HTTP client for an external Ollama, with stub fallback on any error —
    reference-compatible behavior (services/dashboard/app.py:1182-1199)."""

    name = "ollama"

    def __init__(self, url: Optional[str] = None, model: Optional[str] = None, timeout: float = 8.0):
        self.url = url or os.environ.get("OLLAMA_URL", "http://localhost:11434")
        self.model = model or os.environ.get("OLLAMA_MODEL", "llama3")
        self.timeout = timeout
        self._stub = StubRuntime()

    def list_models(self) -> list:
        """Installed Ollama models via /api/tags (reference:
        services/dashboard/app.py:286-306); configured default on failure."""
        import httpx

        try:
            r = httpx.get(f"{self.url}/api/tags", timeout=3.0)
            r.raise_for_status()
            names = [m.get("name") for m in r.json().get("models", []) if m.get("name")]
            return names or [self.model]
        except Exception:  # noqa: BLE001
            return [self.model]

    def generate(self, prompt: str, *, model: Optional[str] = None, max_tokens: int = 256) -> GenerateResult:
        import httpx

        mdl = model or self.model
        started = time.perf_counter()
        try:
            r = httpx.post(
                f"{self.url}/api/generate",
                json={"model": mdl, "prompt": prompt, "stream": False},
                timeout=self.timeout,
            )
            r.raise_for_status()
            latency_ms = int((time.perf_counter() - started) * 1000)
            return GenerateResult(
                text=r.json().get("response") or "",
                meta={"provider": "ollama", "model": mdl, "url": self.url, "latency_ms": latency_ms},
            )
        except Exception as e:  # noqa: BLE001 — any failure falls back to the stub
            latency_ms = int((time.perf_counter() - started) * 1000)
            res = self._stub.generate(prompt, model=mdl)
            res.meta.update(
                {"latency_ms": latency_ms, "url": self.url, "error": f"{type(e).__name__}: {e}"}
            )
            return res


class MultiModelRuntime:
    """Several HF checkpoints behind one runtime, routed by model label —
    the playground's model dropdown with real choices, like the reference's
    Ollama installed-model list (services/dashboard/app.py:286-306) but
    served in-process on the TPU.

    ``KAKVEDA_HF_CKPTS=/ckpts/llama-1b:/ckpts/qwen3-0.6b`` (os.pathsep-
    separated checkpoint directories; any supported family — see
    models/hf_convert.py). Labels are the directory basenames; the first
    entry is the default model. Checkpoints load LAZILY on first use, so
    only models actually requested occupy HBM — co-residency is the
    operator's budget call (each loaded model holds its full weight set
    on device)."""

    name = "tpu"

    def __init__(self, paths: list, *, quant: Optional[str] = None, mesh=None):
        import threading

        if not paths:
            raise ValueError("MultiModelRuntime needs at least one checkpoint path")
        if quant not in (None, "none", "int8"):
            # Fail at construction (= server startup), not on the first
            # generate request — parity with LlamaRuntime.from_env.
            raise ValueError(f"unknown quant mode {quant!r} (int8|none)")
        self._paths = {os.path.basename(os.path.normpath(p)): p for p in paths}
        if len(self._paths) != len(paths):
            raise ValueError(f"duplicate checkpoint basenames in {paths}")
        self._default = os.path.basename(os.path.normpath(paths[0]))
        self._quant = quant
        self._mesh = mesh
        self._loaded: Dict[str, Any] = {}
        self._load_lock = threading.Lock()

    def _get(self, model: Optional[str]):
        label = model or self._default
        if label not in self._paths:
            raise ValueError(
                f"unknown model {label!r}; available: {sorted(self._paths)}"
            )
        if label not in self._loaded:
            # Serialize checkpoint loads: concurrent first requests for one
            # label would otherwise each convert + upload the full weight
            # set (double HBM for the same model).
            with self._load_lock:
                if label not in self._loaded:
                    from kakveda_tpu.models.generate import LlamaRuntime

                    self._loaded[label] = LlamaRuntime.from_hf(
                        self._paths[label], mesh=self._mesh, quant=self._quant
                    )
        return self._loaded[label]

    def list_models(self) -> list:
        return list(self._paths)

    def generate(self, prompt: str, *, model: Optional[str] = None, max_tokens: int = 256) -> GenerateResult:
        return self._get(model).generate(prompt, model=model, max_tokens=max_tokens)

    def generate_batch(self, prompts: list, *, model: Optional[str] = None, max_tokens: int = 256) -> list:
        return self._get(model).generate_batch(prompts, model=model, max_tokens=max_tokens)


_RUNTIMES: Dict[str, Any] = {}


def get_runtime(name: Optional[str] = None) -> ModelRuntime:
    """Resolve the configured runtime (KAKVEDA_MODEL_RUNTIME: stub|tpu|ollama).
    With ``KAKVEDA_HF_CKPTS`` set, ``tpu`` serves every listed checkpoint
    behind one multi-model router."""
    name = (name or os.environ.get("KAKVEDA_MODEL_RUNTIME", "stub")).lower()
    if name in _RUNTIMES:
        return _RUNTIMES[name]
    if name == "stub":
        rt: ModelRuntime = StubRuntime()
    elif name == "ollama":
        rt = OllamaRuntime()
    elif name == "tpu":
        multi = os.environ.get("KAKVEDA_HF_CKPTS")
        if multi:
            quant = os.environ.get("KAKVEDA_QUANT") or None
            rt = MultiModelRuntime(
                [p for p in multi.split(os.pathsep) if p], quant=quant
            )
        else:
            from kakveda_tpu.models.generate import LlamaRuntime

            rt = LlamaRuntime.from_env()
    else:
        raise ValueError(f"unknown model runtime: {name!r}")
    _RUNTIMES[name] = rt
    return rt
