"""Model runtime abstraction: stub | tpu (JAX Llama) | ollama.

The reference calls Ollama over HTTP and falls back to a deterministic
citation-bearing stub on any error
(reference: services/dashboard/app.py:1182-1258,
scripts/demo_client.py:23-40). That stub *is* the test backend: it always
emits fake citations, so the full failure pipeline is exercisable with no
LLM.

Here the runtime is a first-class interface:

  * ``StubRuntime`` — byte-for-byte the reference's canned response, zero
    dependencies, the hermetic default.
  * ``LlamaRuntime`` (kakveda_tpu.models.llama) — the in-tree JAX Llama,
    TP-sharded on the same mesh as the GFKB index; replaces the Ollama HTTP
    hop with an on-pod forward pass.
  * ``OllamaRuntime`` — HTTP client kept for drop-in compatibility with
    reference deployments; falls back to the stub like the reference does.

Every result carries provider/model/latency metadata in the reference's
meta shape so dashboards and eval scorecards transfer unchanged.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Protocol
from kakveda_tpu.core import sanitize

# The reference's exact stub text (services/dashboard/app.py:1193-1199) —
# fake citations that trip the rule classifier deterministically.
STUB_RESPONSE = (
    "Here is a summary with references.\n\n"
    "References:\n"
    "[1] Smith et al. (2020) A Study on Things.\n"
    "[2] Doe (2021) Another Paper.\n"
)


class UnknownModelError(ValueError):
    """Requested model label isn't among the runtime's checkpoints.

    A distinct type so UI callers can turn ONLY stale-label rejections
    into a friendly chat reply while real serving errors (no decode room,
    prompt too long, …) still surface as server errors."""


class HBMBudgetError(RuntimeError):
    """Loading a checkpoint would exceed the runtime's HBM weight budget
    and nothing (more) can be evicted. Raised BEFORE the upload — the
    alternative is OOMing the chip that also serves the GFKB index."""


def _parse_bytes(s) -> Optional[int]:
    """'8GiB' | '8G' | '512M' | raw int → bytes (None/'' → None)."""
    if s is None or s == "":
        return None
    if isinstance(s, (int, float)):
        return int(s)
    t = str(s).strip().upper().removesuffix("B").removesuffix("I")
    mult = {"K": 2**10, "M": 2**20, "G": 2**30, "T": 2**40}.get(t[-1:], 1)
    if mult != 1:
        t = t[:-1]
    return int(float(t) * mult)


def _tree_bytes(tree) -> int:
    """Exact on-device bytes of a param tree (int8 pairs count both the
    int8 matrix and its scales)."""
    import jax

    return sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves(tree)
        if hasattr(x, "dtype")
    )


@dataclass
class GenerateResult:
    text: str
    meta: Dict[str, Any] = field(default_factory=dict)


class ModelRuntime(Protocol):
    name: str

    def generate(self, prompt: str, *, model: Optional[str] = None, max_tokens: int = 256) -> GenerateResult: ...


def generate_batch(
    runtime: "ModelRuntime", prompts: list, *, model: Optional[str] = None, max_tokens: int = 256
) -> list:
    """Batched generation through whatever the runtime offers: the TPU
    runtime decodes the whole list in one left-padded stream
    (LlamaRuntime.generate_batch); stub/ollama fall back to a per-prompt
    loop. Callers (eval runner, LLM classifier) stay runtime-agnostic."""
    fn = getattr(runtime, "generate_batch", None)
    if callable(fn):
        return fn(prompts, model=model, max_tokens=max_tokens)
    return [runtime.generate(p, model=model, max_tokens=max_tokens) for p in prompts]


def list_models(runtime: "ModelRuntime") -> list:
    """Model names the runtime can serve, for the playground dropdown
    (reference: services/dashboard/app.py:286-306, Ollama /api/tags).
    Runtimes advertise via a ``list_models`` method; anything else falls
    back to a single entry."""
    fn = getattr(runtime, "list_models", None)
    if callable(fn):
        try:
            return list(fn()) or [getattr(runtime, "name", "model")]
        except Exception:  # noqa: BLE001 — listing is best-effort
            pass
    return [getattr(runtime, "name", "model")]


def _serving_stats_unavailable(name: str) -> Dict[str, Any]:
    return {"runtime": name, "engine": None}


class StubRuntime:
    """Deterministic canned-response backend — the hermetic test model."""

    name = "stub"

    def __init__(self, model_label: str = "stub"):
        self.model_label = model_label

    def list_models(self) -> list:
        return [self.model_label]

    def serving_stats(self) -> Dict[str, Any]:
        return _serving_stats_unavailable("stub")

    def generate(self, prompt: str, *, model: Optional[str] = None, max_tokens: int = 256) -> GenerateResult:
        started = time.perf_counter()
        text = STUB_RESPONSE
        return GenerateResult(
            text=text,
            meta={
                "provider": "stub",
                "model": model or self.model_label,
                "latency_ms": int((time.perf_counter() - started) * 1000),
            },
        )

    def generate_stream(self, prompt: str, *, model: Optional[str] = None, max_tokens: int = 256, cancel=None):
        """Deterministic chunked stream so the SSE path is exercisable with
        no hardware: the canned response arrives word by word, joining to
        exactly generate().text."""
        words = STUB_RESPONSE.split(" ")
        for i, w in enumerate(words):
            if cancel is not None and cancel.is_set():
                return
            yield w if i == len(words) - 1 else w + " "


class OllamaRuntime:
    """HTTP client for an external Ollama, with stub fallback on any error —
    reference-compatible behavior (services/dashboard/app.py:1182-1199)."""

    name = "ollama"

    def __init__(self, url: Optional[str] = None, model: Optional[str] = None, timeout: float = 8.0):
        self.url = url or os.environ.get("OLLAMA_URL", "http://localhost:11434")
        self.model = model or os.environ.get("OLLAMA_MODEL", "llama3")
        self.timeout = timeout
        self._stub = StubRuntime()

    def list_models(self) -> list:
        """Installed Ollama models via /api/tags (reference:
        services/dashboard/app.py:286-306); configured default on failure."""
        import httpx

        try:
            r = httpx.get(f"{self.url}/api/tags", timeout=3.0)
            r.raise_for_status()
            names = [m.get("name") for m in r.json().get("models", []) if m.get("name")]
            return names or [self.model]
        except Exception:  # noqa: BLE001
            return [self.model]

    def serving_stats(self) -> Dict[str, Any]:
        return _serving_stats_unavailable("ollama")

    def generate(self, prompt: str, *, model: Optional[str] = None, max_tokens: int = 256) -> GenerateResult:
        import httpx

        mdl = model or self.model
        started = time.perf_counter()
        try:
            r = httpx.post(
                f"{self.url}/api/generate",
                json={"model": mdl, "prompt": prompt, "stream": False},
                timeout=self.timeout,
            )
            r.raise_for_status()
            latency_ms = int((time.perf_counter() - started) * 1000)
            return GenerateResult(
                text=r.json().get("response") or "",
                meta={"provider": "ollama", "model": mdl, "url": self.url, "latency_ms": latency_ms},
            )
        except Exception as e:  # noqa: BLE001 — any failure falls back to the stub
            latency_ms = int((time.perf_counter() - started) * 1000)
            res = self._stub.generate(prompt, model=mdl)
            res.meta.update(
                {"latency_ms": latency_ms, "url": self.url, "error": f"{type(e).__name__}: {e}"}
            )
            return res


class MultiModelRuntime:
    """Several HF checkpoints behind one runtime, routed by model label —
    the playground's model dropdown with real choices, like the reference's
    Ollama installed-model list (services/dashboard/app.py:286-306) but
    served in-process on the TPU.

    ``KAKVEDA_HF_CKPTS=/ckpts/llama-1b:/ckpts/qwen3-0.6b`` (os.pathsep-
    separated checkpoint directories; any supported family — see
    models/hf_convert.py). Labels are the directory basenames; the first
    entry is the default model. Checkpoints load LAZILY on first use, so
    only models actually requested occupy HBM.

    **HBM budget** (``hbm_budget_bytes`` / ``KAKVEDA_HBM_BUDGET=12GiB``):
    the runtime accounts exact weight bytes per loaded model plus the
    serving engine's KV pool, and when a new load would cross the budget
    it LRU-evicts idle models first and raises :class:`HBMBudgetError`
    (before the upload) if eviction can't make room — never an OOM on the
    chip that co-hosts the GFKB index. Set the budget to chip HBM minus
    the index + workspace reserve (docs/performance.md co-residency
    table). No budget → the pre-round-4 behavior (operator's call)."""

    name = "tpu"

    def __init__(
        self,
        paths: list,
        *,
        quant: Optional[str] = None,
        mesh=None,
        hbm_budget_bytes: Optional[int] = None,
    ):
        import threading

        if not paths:
            raise ValueError("MultiModelRuntime needs at least one checkpoint path")
        if quant not in (None, "none", "int8"):
            # Fail at construction (= server startup), not on the first
            # generate request — parity with LlamaRuntime.from_env.
            raise ValueError(f"unknown quant mode {quant!r} (int8|none)")
        self._paths = {os.path.basename(os.path.normpath(p)): p for p in paths}
        if len(self._paths) != len(paths):
            raise ValueError(f"duplicate checkpoint basenames in {paths}")
        self._default = os.path.basename(os.path.normpath(paths[0]))
        self._quant = quant
        self._mesh = mesh
        self._budget = (
            hbm_budget_bytes
            if hbm_budget_bytes is not None
            else _parse_bytes(os.environ.get("KAKVEDA_HBM_BUDGET"))
        )
        self._loaded: Dict[str, Any] = {}  # label -> LlamaRuntime, LRU order
        self._bytes: Dict[str, int] = {}  # label -> exact weight+KV bytes
        self._load_lock = sanitize.named_lock("MultiModelRuntime._load_lock")  # serializes load/evict/budget
        self._lru_lock = sanitize.named_lock("MultiModelRuntime._lru_lock")  # guards _loaded order mutations only
        # HBM headroom on the metrics plane: budget is static, loaded
        # bytes move on every load/evict — headroom is the difference,
        # computed by the dashboard/alert side.
        from kakveda_tpu.core import metrics as _metrics

        reg = _metrics.get_registry()
        self._m_budget = reg.gauge(
            "kakveda_hbm_budget_bytes",
            "Configured HBM weight+KV budget (0 = unbudgeted)",
        )
        self._m_loaded = reg.gauge(
            "kakveda_hbm_loaded_bytes",
            "Resident weight+KV bytes accounted by the model router",
        )
        self._m_budget.set(self._budget or 0)

    def _estimate_bytes(self, path: str) -> int:
        """Pre-load footprint estimate from config.json alone (no weight
        IO): eval_shape of the param tree (+int8 halving) plus the serving
        engine's KV pool. Replaced by exact accounting after the load."""
        import json as _json

        import jax
        import jax.numpy as jnp

        from kakveda_tpu.models.hf_convert import hf_config_to_llama
        from kakveda_tpu.models.llama import init_params

        with open(os.path.join(path, "config.json")) as f:
            cfg = hf_config_to_llama(_json.load(f), dtype=jnp.bfloat16)
        shapes = jax.eval_shape(lambda k: init_params(k, cfg), jax.random.PRNGKey(0))
        w = _tree_bytes(shapes)
        if self._quant == "int8":
            # Dense matrices drop to 1 byte/elt + per-row f32 scales; the
            # (unquantized) norms/embeddings are a small fraction. A ~0.55
            # factor over-estimates slightly — safe direction for a budget.
            w = int(w * 0.55)
        return w + self._engine_pool_bytes(cfg)

    @staticmethod
    def _engine_pool_bytes(cfg) -> int:
        """KV bytes the shared ServingEngine will pin once this model
        serves traffic (slots × window × layers × K+V), from the same env
        knobs LlamaRuntime.engine uses."""
        import numpy as np

        slots = int(os.environ.get("KAKVEDA_SERVE_SLOTS", "8"))
        window = min(
            int(os.environ.get("KAKVEDA_SERVE_WINDOW", min(512, cfg.max_seq_len))),
            cfg.max_seq_len,
        )
        if os.environ.get("KAKVEDA_KV_QUANT", "").lower() == "int8":
            # int8 pool: 1 byte/element + one f32 per-row scale per head_dim
            # elements (models/llama.py:_kv_quant_rows). Charging the dense
            # dtype here over-charges ~2× — safe, but it skews the admin
            # panel's resident-bytes figure and triggers eviction early.
            itemsize = 1.0 + 4.0 / cfg.head_dim
        else:
            itemsize = float(np.dtype(cfg.dtype).itemsize)
        return int(slots * window * cfg.n_layers * 2 * cfg.n_kv_heads * cfg.head_dim * itemsize)

    def _evict_lru(self, keep: str) -> bool:
        """Drop the least-recently-used loaded model (never ``keep``);
        returns False when nothing is evictable. Caller holds _load_lock.

        ``retire()`` closes the engine under ITS lock and bars a rebuild,
        so a thread mid-generate on the evicted runtime can't re-pin a KV
        pool behind the budget's back — it finishes on the solo path and
        the weights free when the last in-flight caller drops them."""
        with self._lru_lock:
            victim = next((lb for lb in self._loaded if lb != keep), None)
            rt = self._loaded.pop(victim) if victim is not None else None
        if rt is None:
            return False
        self._bytes.pop(victim, None)
        rt.retire()
        self._m_loaded.set(self.loaded_bytes())
        return True

    def loaded_bytes(self) -> int:
        # dict() is an atomic C-level copy: the serving panel calls this
        # from a handler thread while loads/evictions mutate _bytes, and
        # iterating the live dict would raise mid-mutation.
        return sum(dict(self._bytes).values())

    def serving_stats(self) -> Dict[str, Any]:
        """Ops snapshot for the admin serving panel: budget accounting
        plus each resident model's engine stats."""
        with self._lru_lock:
            # Snapshot under the order lock: the hot-path LRU touch pops
            # and reinserts entries, and an unguarded items() can see the
            # dict change size mid-iteration.
            resident = list(self._loaded.items())
        return {
            "runtime": "tpu-multi",
            "budget_bytes": self._budget,
            "loaded_bytes": self.loaded_bytes(),
            "models": {
                label: {
                    "bytes": self._bytes.get(label, 0),
                    **rt.serving_stats(),
                }
                for label, rt in resident
            },
            "available": sorted(self._paths),
        }

    def _get(self, model: Optional[str]):
        label = model or self._default
        if label not in self._paths:
            raise UnknownModelError(
                f"unknown model {label!r}; available: {sorted(self._paths)}"
            )
        rt = self._loaded.get(label)
        if rt is not None:
            # Hot path: no load lock (a slow checkpoint load on another
            # label must not stall serving). LRU touch under the cheap
            # order lock; if the label was just evicted, this request
            # still runs on the retired runtime it already holds.
            with self._lru_lock:
                cur = self._loaded.pop(label, None)
                if cur is not None:
                    self._loaded[label] = cur
            return rt
        # Serialize checkpoint loads: concurrent first requests for one
        # label would otherwise each convert + upload the full weight
        # set (double HBM for the same model).
        with self._load_lock:
            rt = self._loaded.get(label)
            if rt is not None:
                return rt
            if self._budget is not None:
                est = self._estimate_bytes(self._paths[label])
                while (
                    self.loaded_bytes() + est > self._budget
                    and self._evict_lru(keep=label)
                ):
                    pass
                if self.loaded_bytes() + est > self._budget:
                    raise HBMBudgetError(
                        f"loading {label!r} needs ~{est / 2**20:.0f} MiB but only "
                        f"{(self._budget - self.loaded_bytes()) / 2**20:.0f} MiB of the "
                        f"{self._budget / 2**20:.0f} MiB HBM budget remains "
                        "(KAKVEDA_HBM_BUDGET) and nothing is left to evict"
                    )
            from kakveda_tpu.models.generate import LlamaRuntime

            rt = LlamaRuntime.from_hf(
                self._paths[label], mesh=self._mesh, quant=self._quant
            )
            self._bytes[label] = _tree_bytes(rt.params) + self._engine_pool_bytes(rt.cfg)
            with self._lru_lock:
                self._loaded[label] = rt
            self._m_loaded.set(self.loaded_bytes())
            return rt

    def list_models(self) -> list:
        return list(self._paths)

    def generate(self, prompt: str, *, model: Optional[str] = None, max_tokens: int = 256) -> GenerateResult:
        return self._get(model).generate(prompt, model=model, max_tokens=max_tokens)

    def generate_batch(self, prompts: list, *, model: Optional[str] = None, max_tokens: int = 256) -> list:
        return self._get(model).generate_batch(prompts, model=model, max_tokens=max_tokens)

    def generate_stream(self, prompt: str, *, model: Optional[str] = None, max_tokens: int = 256, cancel=None):
        """Stream from the resolved model's runtime (SSE playground path).
        Default budget matches generate()/generate_batch here — a streamed
        answer must not silently truncate shorter than the blocking one."""
        return self._get(model).generate_stream(
            prompt, model=model, max_tokens=max_tokens, cancel=cancel
        )


_RUNTIMES: Dict[str, Any] = {}


def get_runtime(name: Optional[str] = None) -> ModelRuntime:
    """Resolve the configured runtime (KAKVEDA_MODEL_RUNTIME: stub|tpu|ollama).
    With ``KAKVEDA_HF_CKPTS`` set, ``tpu`` serves every listed checkpoint
    behind one multi-model router."""
    name = (name or os.environ.get("KAKVEDA_MODEL_RUNTIME", "stub")).lower()
    if name in _RUNTIMES:
        return _RUNTIMES[name]
    if name == "stub":
        rt: ModelRuntime = StubRuntime()
    elif name == "ollama":
        rt = OllamaRuntime()
    elif name == "tpu":
        multi = os.environ.get("KAKVEDA_HF_CKPTS")
        if multi:
            quant = os.environ.get("KAKVEDA_QUANT") or None
            rt = MultiModelRuntime(
                [p for p in multi.split(os.pathsep) if p], quant=quant
            )
        else:
            from kakveda_tpu.models.generate import LlamaRuntime

            rt = LlamaRuntime.from_env()
    else:
        raise ValueError(f"unknown model runtime: {name!r}")
    _RUNTIMES[name] = rt
    return rt
