"""Continuous batching — THE online serving path.

``ServingEngine`` (bottom of this module) is what LlamaRuntime routes
``generate``/``generate_batch`` through by default
(KAKVEDA_SERVE_CONTINUOUS=0 opts out): one daemon loop thread owns a
shared ContinuousBatcher, concurrent callers block on Futures, and every
online request — playground chat, eval row, LLM-judge call — joins one
decode batch. Offline throughput paths (bench, training eval) keep
calling ``generate_tokens_fused`` directly.

The playground, eval runner and LLM-judge tier all call generate. Static
batching (`generate_tokens_batch`/`_fused`) decodes a fixed cohort to the
longest member: every finished (EOS) sequence leaves its batch slot idle
until the whole cohort drains, and new requests wait for the next cohort.
Under mixed-length traffic that wastes both slots and latency.

**Design.** A `ContinuousBatcher` owns a fixed [B, KV, max_len, D] KV-cache
(static shapes — nothing ever retraces) and treats the batch axis as B
independent *slots*:

  * **admit**: a new prompt prefills into one free slot — a [1, P] prefill
    whose cache rows are scattered into the batch cache at that slot
    (`_admit_jit`). Other slots are untouched; admission interleaves with
    decoding chunks.
  * **step_chunk**: ONE bounded decode program advances every active slot
    by up to `chunk_steps` tokens (same chunked-dispatch scheduling that
    lets pre-flight warn batches share the chip — models/generate.py
    `DecodeSession`). Inactive slots decode garbage into their own slot
    positions that admission later overwrites — masked out by per-slot
    `kv_valid`, never visible to active slots.
  * **retire**: EOS/length-exhausted slots free on the host between
    chunks; their results return to callers and the slot re-enters the
    free list.

Throughput model: with static batching a cohort of B requests whose decode
lengths are L_i costs max(L_i) steps of B-wide compute; continuous
batching costs ~mean(L_i) per request at steady state — the delta grows
with length variance (bench: `KAKVEDA_BENCH_METRIC=continuous python
bench.py`, reported in docs/performance.md).

Capability replaced: the reference serves generations through sequential
per-request Ollama HTTP calls (services/dashboard/app.py:1182-1258) — no
batching at all; eval loops run one example at a time
(app.py:2315-2393).

Use the class directly (``ContinuousBatcher(params, cfg, ...)``); it
accepts the same param trees as every other forward path, including int8
weight-only quantized ones (llama.wmat). Decoding is greedy by default;
``admit(..., temperature=t)`` samples that slot only (a [B] temperature
vector threads through the chunk body; greedy slots stay exact).
"""

from __future__ import annotations

import os
import queue
import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kakveda_tpu.models.llama import (
    LlamaConfig,
    Params,
    decode_step,
    init_cache,
    mask_pad_vocab,
)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def _admit_jit(params, cfg: LlamaConfig, cache, last, prompt, slot, kv_valid, pos_offset):
    """Prefill ``prompt`` [1, P] into batch slot ``slot`` of ``cache``.

    The single-sequence prefill runs with its own [1, ...] scratch cache
    (so its attention sees only this prompt), then its K/V rows scatter
    into the batch cache at ``slot``. `last` [B, V] gets the slot's
    next-token logits.
    """
    b = last.shape[0]
    p = prompt.shape[1]
    scratch = init_cache(cfg, batch=1, max_len=cache["k"][0].shape[2])
    logits, scratch = decode_step(
        params, cfg, prompt, scratch,
        kv_valid=kv_valid[slot][None],
        pos_offset=pos_offset[slot][None],
        last_only=True,
    )
    out = {"pos": cache["pos"]}
    for key in ("k", "v") + (("ks", "vs") if cfg.kv_quant == "int8" else ()):
        zeros = (0,) * (cache[key][0].ndim - 1)
        out[key] = [
            jax.lax.dynamic_update_slice(ck, sk, (slot, *zeros))
            for ck, sk in zip(cache[key], scratch[key])
        ]
    nl = mask_pad_vocab(logits[:, -1, :], cfg)
    last = jax.lax.dynamic_update_slice(last, nl, (slot, 0))
    # cache["pos"] is managed per-slot on host (slot positions differ);
    # the batch cache carries pos=0 and step passes explicit positions.
    return out, last


def _forward_wide(params, cfg: LlamaConfig, cache_k, cache_v, cache_ks, cache_vs, tokens, slot_pos, kv_valid, pos_offset):
    """THE serving-chunk forward body, S-wide with PER-SLOT positions:
    token i of slot b writes cache row ``slot_pos[b]+i`` and attends rows
    ``col <= slot_pos[b]+i`` (within kv_valid, and the sliding-window band
    when the layer has one). Shared by the plain decode chunk (S=1 inside
    a scan) and the speculative verify chunk (S=k+1) — ONE body to honor
    model-family flags, not two. Attention goes through
    ``gqa_cache_attention``: S=1 masks are expressible as [B, L] kv_valid
    (keeping the flash / int8-streaming dispatch), S>1 passes the full
    [B, S, L] mask (XLA path; S <= k+1 keeps its scratch tiny).

    Returns (logits [B, S, V] vocab-masked f32, new_k, new_v, new_ks, new_vs).
    """
    from kakveda_tpu.models.attention import gqa_cache_attention
    from kakveda_tpu.models.llama import (
        _kv_quant_rows,
        _rope_freqs,
        apply_rope,
        embed_tokens,
        mlp_block,
        qkv_proj,
        rms_norm,
        softcap_logits,
        wmat,
    )

    b, s = tokens.shape
    hd = cfg.head_dim
    max_len = cache_k[0].shape[2]
    kq = cfg.kv_quant == "int8"

    positions = slot_pos[:, None] + jnp.arange(s)[None, :] - pos_offset[:, None]
    cos, sin = _rope_freqs(cfg, positions)
    x = embed_tokens(params, cfg, tokens)

    col = jnp.arange(max_len)[None, None, :]  # [1, 1, L]
    qpos = (slot_pos[:, None] + jnp.arange(s)[None, :])[:, :, None]  # [B, S, 1]
    base_mask = kv_valid[:, None, :] & (col <= qpos)  # [B, S, L]
    win_mask = base_mask
    if cfg.sliding_window:
        win_mask = base_mask & (col > qpos - cfg.sliding_window)

    rows = jnp.arange(b)[:, None]  # [B, 1]
    wcols = slot_pos[:, None] + jnp.arange(s)[None, :]  # [B, S] write indices
    new_k, new_v, new_ks, new_vs = [], [], [], []
    for li in range(cfg.n_layers):
        mask = win_mask if cfg.layer_window(li) else base_mask
        layer = params["layers"][li]
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        dt = h.dtype
        q, k, v = qkv_proj(h, layer, cfg, dt)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # Per-slot scatter: row i of slot b lands at cache[b, :, slot_pos[b]+i]
        # — a real scatter (in-place row writes), not a whole-cache rewrite;
        # mode="drop" clamps overshoot past the window (discarded host-side).
        k_rows = k.transpose(0, 2, 1, 3)  # [B, KV, S, D]
        v_rows = v.transpose(0, 2, 1, 3)
        ks_all = vs_all = None
        if kq:
            # Same per-row quantizer as decode_step, so a slot's cache
            # bytes are identical to its solo decode — int8 parity is
            # exact, not approximate-squared.
            k_i8, k_sc = _kv_quant_rows(k_rows)
            v_i8, v_sc = _kv_quant_rows(v_rows)
            k_all = cache_k[li].at[rows, :, wcols].set(k_i8.transpose(0, 2, 1, 3), mode="drop")
            v_all = cache_v[li].at[rows, :, wcols].set(v_i8.transpose(0, 2, 1, 3), mode="drop")
            ks_all = cache_ks[li].at[rows, :, wcols].set(k_sc.transpose(0, 2, 1), mode="drop")
            vs_all = cache_vs[li].at[rows, :, wcols].set(v_sc.transpose(0, 2, 1), mode="drop")
            new_ks.append(ks_all)
            new_vs.append(vs_all)
        else:
            k_all = cache_k[li].at[rows, :, wcols].set(
                k_rows.transpose(0, 2, 1, 3).astype(cfg.dtype), mode="drop"
            )
            v_all = cache_v[li].at[rows, :, wcols].set(
                v_rows.transpose(0, 2, 1, 3).astype(cfg.dtype), mode="drop"
            )
        new_k.append(k_all)
        new_v.append(v_all)
        if s == 1:
            # [B, L] mask keeps the flash/int8-streaming dispatch;
            # pos0=max_len makes the kernel's scalar causal mask a no-op.
            attn = gqa_cache_attention(
                q, k_all, v_all, jnp.asarray(max_len), mask[:, 0, :],
                softcap=cfg.attn_softcap, k_scale=ks_all, v_scale=vs_all,
            )
        else:
            attn = gqa_cache_attention(
                q, k_all, v_all, jnp.asarray(max_len), None,
                softcap=cfg.attn_softcap, k_scale=ks_all, v_scale=vs_all,
                full_mask=mask,
            )
        attn = attn.reshape(b, s, cfg.n_heads * hd) @ wmat(layer["wo"], dt)
        if "post_attn_norm" in layer:
            attn = rms_norm(attn, layer["post_attn_norm"], cfg.norm_eps)
        x = x + attn
        h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        m = mlp_block(h, layer, cfg)
        if "post_ffw_norm" in layer:
            m = rms_norm(m, layer["post_ffw_norm"], cfg.norm_eps)
        x = x + m
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ wmat(params["lm_head"], cfg.dtype)).astype(jnp.float32)
    logits = softcap_logits(logits, cfg.final_softcap)
    logits = mask_pad_vocab(logits, cfg)
    return logits, new_k, new_v, new_ks, new_vs


@partial(jax.jit, static_argnames=("cfg", "n_steps"), donate_argnums=(2,))
def _step_chunk_jit(params, cfg: LlamaConfig, cache, last, slot_pos, kv_valid, pos_offset, temps, rng, n_steps: int):
    """Advance every slot by ``n_steps`` tokens in one program.

    ``slot_pos`` [B] — per-slot NEXT cache index (prompt length + tokens
    decoded so far). decode_step's scalar `pos` can't express per-slot
    positions, so the chunk scans :func:`_forward_wide` at S=1 with a
    per-slot write index: token t of slot b lands at cache[b, :, slot_pos[b]+t].
    ``temps`` [B] — per-slot sampling temperature; a slot with temp <= 0
    decodes greedily, others sample categorically (one rng split per step,
    shared across slots — rows are independent draws of the same key).
    """
    kq = cfg.kv_quant == "int8"

    def one_step(carry, _):
        cache_k, cache_v, cache_ks, cache_vs, last, slot_pos, rng = carry
        rng, sub = jax.random.split(rng)
        sampled = jax.random.categorical(
            sub, last / jnp.maximum(temps, 1e-6)[:, None], axis=-1
        )
        nxt = jnp.where(temps > 0.0, sampled, jnp.argmax(last, axis=-1))  # [B]
        logits, new_k, new_v, new_ks, new_vs = _forward_wide(
            params, cfg, cache_k, cache_v, cache_ks, cache_vs,
            nxt[:, None].astype(jnp.int32), slot_pos, kv_valid, pos_offset,
        )
        return (new_k, new_v, new_ks, new_vs, logits[:, -1, :], slot_pos + 1, rng), nxt

    init = (
        cache["k"], cache["v"],
        cache.get("ks", []), cache.get("vs", []),
        last, slot_pos, rng,
    )
    (ck, cv, cks, cvs, last, slot_pos, rng), toks = jax.lax.scan(
        one_step, init, None, length=n_steps
    )
    out = {"pos": cache["pos"], "k": ck, "v": cv}
    if kq:
        out["ks"], out["vs"] = cks, cvs
    return out, last, slot_pos, rng, toks.T  # [B, n_steps]


@partial(jax.jit, static_argnames=("cfg", "k"), donate_argnums=(2,))
def _spec_chunk_jit(params, cfg: LlamaConfig, cache, last, slot_pos, kv_valid, pos_offset, drafts, k: int):
    """Speculative verify chunk: each slot advances 1..k+1 GREEDY tokens in
    ONE :func:`_forward_wide` pass over k+1 positions.

    ``drafts`` [B, k] are host-side prompt-lookup guesses for the tokens
    AFTER the committed next token t0 (= argmax(last), computed in-program
    so every chunk emits >= 1 token). The k+1-wide forward writes all rows
    and produces logits at every position; the accepted prefix is the run
    of drafts matching their own greedy verdicts. Rows written past the
    accepted point hold K/V of rejected tokens — never read (validity is
    bounded by each query's own position) and overwritten as real decoding
    reaches them, the same clamp-and-discard contract as pipelined
    overshoot. Decode is weight-bandwidth-bound, so the k+1-wide forward
    rides the SAME weight stream as a 1-wide step — accepted tokens are
    nearly free (models/speculative.py measures 1.3-1.7 tokens/round on
    judge-shaped traffic).

    Returns (cache, new_last [B,V], new_slot_pos [B], toks [B, k+1],
    counts [B]) — the host emits ``toks[b, :counts[b]]``.
    """
    kq = cfg.kv_quant == "int8"
    t0 = jnp.argmax(last, axis=-1).astype(jnp.int32)  # [B]
    tokens = jnp.concatenate([t0[:, None], drafts.astype(jnp.int32)], axis=1)  # [B, k+1]
    logits, new_k, new_v, new_ks, new_vs = _forward_wide(
        params, cfg, cache["k"], cache["v"],
        cache.get("ks", []), cache.get("vs", []),
        tokens, slot_pos, kv_valid, pos_offset,
    )
    new_cache = {"pos": cache["pos"], "k": new_k, "v": new_v}
    if kq:
        new_cache["ks"], new_cache["vs"] = new_ks, new_vs

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, k+1]; [b, i] follows tokens[b, :i+1]
    match = (drafts.astype(jnp.int32) == greedy[:, :-1]).astype(jnp.int32)  # [B, k]
    m_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # [B] accepted drafts
    counts = m_acc + 1  # emitted = t0 + accepted drafts
    # Next chunk's `last` = logits after the final emitted token.
    new_last = jnp.take_along_axis(logits, m_acc[:, None, None], axis=1)[:, 0, :]
    return new_cache, new_last, slot_pos + counts, tokens, counts


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def _admit_prefix_jit(
    params, cfg: LlamaConfig, cache, last, pfx, suffix, slot, kv_valid, pos_offset, write_pos
):
    """Prefill only ``suffix`` [1, S'] into batch slot ``slot``, reusing the
    precomputed K/V rows of a shared prompt prefix (``pfx``: per-layer
    [1, KV, plen, D] slabs from :meth:`ContinuousBatcher.register_prefix`).

    Prefix K/V rows are position-INDEPENDENT of the slot layout: RoPE
    rotates by logical position (cache index − pos_offset), and a prefix
    token's logical position is its own index regardless of how much left
    pad the admission bucket adds — so one registered slab serves every
    bucket. The slab lands at [off, off+plen); the suffix chunk recomputes
    rows from ``write_pos`` (= off + split point), overwriting the slab's
    tail where the power-of-two suffix chunk overlaps it with identical
    values. Attention over not-yet-written rows is causally masked exactly
    as in chunked prefill.
    """
    b = last.shape[0]
    max_len = cache["k"][0].shape[2]
    off = pos_offset[slot]
    scratch = init_cache(cfg, batch=1, max_len=max_len)
    scratch["pos"] = write_pos
    for key in ("k", "v") + (("ks", "vs") if cfg.kv_quant == "int8" else ()):
        starts = (0, 0, off, 0) if pfx[key][0].ndim == 4 else (0, 0, off)
        scratch[key] = [
            jax.lax.dynamic_update_slice(sk, pk, starts)
            for sk, pk in zip(scratch[key], pfx[key])
        ]
    logits, scratch = decode_step(
        params, cfg, suffix, scratch,
        kv_valid=kv_valid[slot][None],
        pos_offset=pos_offset[slot][None],
        last_only=True,
    )
    out = {"pos": cache["pos"]}
    for key in ("k", "v") + (("ks", "vs") if cfg.kv_quant == "int8" else ()):
        zeros = (0,) * (cache[key][0].ndim - 1)
        out[key] = [
            jax.lax.dynamic_update_slice(ck, sk, (slot, *zeros))
            for ck, sk in zip(cache[key], scratch[key])
        ]
    nl = mask_pad_vocab(logits[:, -1, :], cfg)
    last = jax.lax.dynamic_update_slice(last, nl, (slot, 0))
    return out, last


@partial(jax.jit, static_argnames=("cfg",))
def _prefix_prefill_jit(params, cfg: LlamaConfig, ids):
    """One compiled prefill for prefix registration ([1, plen] exact-length
    cache). Eager decode_step here would pay a per-op dispatch — thousands
    of ~80 ms round trips on a tunneled chip — for what is one program."""
    scratch = init_cache(cfg, batch=1, max_len=ids.shape[1])
    _, scratch = decode_step(params, cfg, ids, scratch, last_only=True)
    return scratch


@dataclass
class _Prefix:
    """One registered shared prompt prefix: token ids + per-layer K/V slabs
    ([1, KV, plen, D], int8 + scales when the cache is quantized)."""

    ids: Tuple[int, ...]
    kv: Dict[str, List[jax.Array]]


@dataclass
class _Slot:
    req_id: int
    prompt_len: int
    max_new: int
    out: List[int] = field(default_factory=list)
    done: bool = False
    # Streaming: called from process_chunk with (new_tokens, done) after
    # each chunk. MUST be fast/non-blocking (queue put) — it runs on the
    # engine loop thread between device dispatches.
    on_tokens: Optional[object] = None
    # Prompt ids retained for host-side speculative drafting (prompt +
    # out = the lookup corpus).
    prompt_ids: List[int] = field(default_factory=list)


class ContinuousBatcher:
    """Admit-as-you-go generation over a fixed slot pool. Greedy by
    default; per-request ``temperature`` samples that slot only."""

    def __init__(
        self,
        params: Params,
        cfg: LlamaConfig,
        *,
        batch_slots: int = 8,
        max_len: int = 512,
        chunk_steps: int = 8,
        eos_id: Optional[int] = None,
        rng: Optional[jax.Array] = None,
        spec_k: int = 0,
    ):
        self.params, self.cfg = params, cfg
        self.B, self.max_len = batch_slots, max_len
        self.chunk_steps = chunk_steps
        self.spec_k = spec_k
        self.spec_stats = {"chunks": 0, "emitted": 0, "slot_chunks": 0}
        self.eos_id = eos_id
        self.cache = init_cache(cfg, batch=batch_slots, max_len=max_len)
        self.last = jnp.full((batch_slots, cfg.vocab_size), -1e30, jnp.float32)
        # Host-side mirrors of the per-slot bookkeeping: step() would
        # otherwise pay per-slot device syncs (int(dev_arr[slot])) and
        # per-slot scatter dispatches between chunks — on remote-attached
        # chips that host bookkeeping can exceed the chunk's compute. The
        # device copies are rebuilt from the mirrors once per call.
        self._kv_np = np.zeros((batch_slots, max_len), bool)
        self._off_np = np.zeros((batch_slots,), np.int32)
        self._pos_np = np.zeros((batch_slots,), np.int32)
        self._temp_np = np.zeros((batch_slots,), np.float32)  # ≤0 = greedy
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.slots: Dict[int, _Slot] = {}
        self.free = list(range(batch_slots))
        self.results: Dict[int, List[int]] = {}
        self._next_id = 0
        self._prefixes: Dict[Tuple[int, ...], _Prefix] = {}
        self.prefix_stats = {"registered": 0, "hits": 0, "hit_tokens_saved": 0}

    @staticmethod
    def bucket_for(prompt_len: int, max_len: int) -> int:
        """Admission pad width: power-of-two ≥ prompt (min 8), capped at
        the slot window. THE definition shared by admit() and
        ServingEngine.fits() — the engine's fallback contract (never admit
        what would truncate) depends on the two staying identical."""
        bucket = 8
        while bucket < prompt_len:
            bucket <<= 1
        return min(bucket, max_len - 1)

    def register_prefix(self, prefix_ids: List[int]) -> bool:
        """Precompute and retain the K/V rows of a shared prompt prefix so
        later admissions prefill only their suffix (``_admit_prefix_jit``).

        The natural users are the fixed instruction templates in front of
        every LLM-judge call and the playground/eval system preamble — the
        reference pays the full prompt on every Ollama hop
        (services/dashboard/app.py:1182-1258); here the shared head of the
        prompt costs its FLOPs once per process instead of once per request.

        Returns False (no-op) when the prefix is too short to matter, too
        long for the slot window, or the model's RoPE regime depends on the
        final sequence length (Phi-3 longrope: a prefix computed at length
        plen would rotate in a different regime than the full prompt —
        reuse would be silently wrong, so it is refused).
        """
        ids = tuple(int(t) for t in prefix_ids)
        if len(ids) < 8 or len(ids) + 9 >= self.max_len:
            return False
        if getattr(self.cfg, "rope_dim_factors_long", None):
            return False
        if ids in self._prefixes:
            return True
        scratch = _prefix_prefill_jit(
            self.params, self.cfg, jnp.asarray([list(ids)], jnp.int32)
        )
        keys = ("k", "v") + (("ks", "vs") if self.cfg.kv_quant == "int8" else ())
        # Bounded store: auto-registration (generate_batch common heads)
        # must not accumulate slabs without limit — each is
        # plen·KV·D·layers·2 resident HBM bytes. Dict order is recency
        # (moved-to-end on hit); evict the least recently used.
        maxp = int(os.environ.get("KAKVEDA_SERVE_PREFIX_MAX", "4"))
        while len(self._prefixes) >= max(1, maxp):
            self._prefixes.pop(next(iter(self._prefixes)))
        self._prefixes[ids] = _Prefix(ids=ids, kv={k: scratch[k] for k in keys})
        self.prefix_stats["registered"] += 1
        return True

    def _match_prefix(self, prompt_ids: List[int]):
        """Longest registered prefix of ``prompt_ids`` plus the suffix-chunk
        split: returns (entry, split, suffix_width) or None. The suffix
        chunk is the power-of-two-wide tail the admission recomputes —
        ``split = len(prompt) − suffix_width`` tokens come from the slab,
        and the chunk re-derives the overlap [split, plen) with identical
        values (keeping compile count logarithmic instead of per-length)."""
        if not self._prefixes:
            return None
        best = None
        for pe in self._prefixes.values():
            pl_ = len(pe.ids)
            if best is not None and pl_ <= len(best.ids):
                continue
            if len(prompt_ids) >= pl_ and tuple(prompt_ids[:pl_]) == pe.ids:
                best = pe
        if best is None:
            return None
        # Recency for the LRU bound: a hit keeps its prefix resident.
        self._prefixes[best.ids] = self._prefixes.pop(best.ids)
        p = len(prompt_ids)
        sw = 8
        while sw < p - len(best.ids):
            sw <<= 1
        split = p - sw
        if split <= 0:
            return None  # suffix chunk covers the whole prompt: no reuse win
        return best, split, sw

    @property
    def has_capacity(self) -> bool:
        return bool(self.free)

    @property
    def active(self) -> int:
        return len(self.slots)

    def admit(
        self,
        prompt_ids: List[int],
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        on_tokens=None,
    ) -> int:
        """Prefill into a free slot; returns a request id.

        Prompts are LEFT-padded to a power-of-two bucket so admission hits
        a handful of compiled prefill programs under mixed-length traffic
        instead of retracing per distinct length; pad slots are masked by
        kv_valid and pos_offset exactly as in generate_tokens_batch."""
        if not self.free:
            raise RuntimeError("no free slot; call step() until one retires")
        p = len(prompt_ids)
        if p + 1 >= self.max_len:
            raise ValueError("prompt too long for the slot window")
        bucket = self.bucket_for(p, self.max_len)
        off = bucket - p
        slot = self.free.pop()
        rid = self._next_id
        self._next_id += 1
        # Slot validity: the real prompt rows [off, bucket), growing per step.
        ar = np.arange(self.max_len)
        self._kv_np[slot] = (ar >= off) & (ar < bucket)
        self._off_np[slot] = off
        self._pos_np[slot] = bucket
        self._temp_np[slot] = temperature
        # .copy(): on the CPU backend jnp.asarray can alias the numpy
        # buffer ZERO-COPY, and these mirrors keep mutating while the
        # async program reads them — observed as flaky garbage logits.
        m = (
            self._match_prefix(list(prompt_ids))
            if os.environ.get("KAKVEDA_SERVE_PREFIX", "1") != "0"
            else None
        )
        if m is not None:
            pe, split, sw = m
            self.prefix_stats["hits"] += 1
            self.prefix_stats["hit_tokens_saved"] += split
            self.cache, self.last = _admit_prefix_jit(
                self.params, self.cfg, self.cache, self.last,
                pe.kv, jnp.asarray([list(prompt_ids[split:])], jnp.int32),
                jnp.asarray(slot),
                jnp.asarray(self._kv_np.copy()), jnp.asarray(self._off_np.copy()),
                jnp.asarray(off + split, jnp.int32),
            )
        else:
            padded = [0] * off + list(prompt_ids)
            self.cache, self.last = _admit_jit(
                self.params, self.cfg, self.cache, self.last,
                jnp.asarray([padded], jnp.int32), jnp.asarray(slot),
                jnp.asarray(self._kv_np.copy()), jnp.asarray(self._off_np.copy()),
            )
        self.slots[slot] = _Slot(
            req_id=rid, prompt_len=bucket, max_new=max_new_tokens, on_tokens=on_tokens,
            prompt_ids=list(prompt_ids),
        )
        return rid

    def step_async(self):
        """Dispatch one decode chunk WITHOUT fetching its tokens; returns a
        handle for :meth:`process_chunk` (or None when no slot is active).

        This is the pipelining half of ``step()``: on remote-attached
        chips the per-chunk token fetch pays a fixed wire RTT that can
        exceed the chunk's compute, so an engine that dispatches chunk
        i+1 before processing chunk i's tokens overlaps that RTT with
        device work. Retirement (EOS / max_new) is then detected one
        chunk late; the overshoot chunk wastes compute but cannot corrupt
        state — cache writes clamp at the window (``mode="drop"``), each
        slot attends only within its own cache row, and the overshoot
        tokens are discarded host-side — so outputs are token-identical
        to the unpipelined path."""
        if not self.slots:
            return None
        self._grow_valid(self.chunk_steps)

        self.cache, self.last, _, self.rng, toks = _step_chunk_jit(
            self.params, self.cfg, self.cache, self.last, jnp.asarray(self._pos_np.copy()),
            jnp.asarray(self._kv_np.copy()), jnp.asarray(self._off_np.copy()),
            jnp.asarray(self._temp_np.copy()), self.rng, self.chunk_steps,
        )
        self._pos_np += self.chunk_steps  # every slot advances in lockstep
        try:
            toks.copy_to_host_async()
        except Exception:  # noqa: BLE001 — backends without async copy
            pass
        # Slot refs (shared, not copied): a slot retired by an EARLIER
        # handle's processing — or by cancel_request between chunks —
        # shows st.done here and its overshoot tokens are skipped. A
        # freed slot re-admitted before this handle is processed gets a
        # NEW _Slot object (the snapshot still holds the done one), and
        # the admit scatter is ordered after the in-flight chunk by the
        # functional cache threading — so a snapshot can never alias or
        # corrupt a newer request.
        return toks, dict(self.slots)

    def process_chunk(self, handle) -> List[int]:
        """Fetch a dispatched chunk's tokens and retire finished slots;
        returns req_ids completed by that chunk."""
        if handle is None:
            return []
        toks, snapshot = handle
        toks_h = np.asarray(toks)
        finished = []
        for slot, st in snapshot.items():
            if st.done:
                continue  # retired by an earlier chunk; these are overshoot tokens
            self._emit(slot, st, toks_h[slot], finished)
        return finished

    def _emit(self, slot: int, st: _Slot, tok_row, finished: List[int]) -> None:
        """Accept a chunk's tokens into a slot (EOS / budget / window stops),
        fire the streaming callback, retire when done. Shared by the plain
        chunk path and the speculative path."""
        n_before = len(st.out)
        for t in tok_row:
            t = int(t)
            if self.eos_id is not None and t == self.eos_id:
                st.done = True
                break
            st.out.append(t)
            if len(st.out) >= st.max_new or st.prompt_len + len(st.out) + 1 >= self.max_len:
                st.done = True
                break
        if st.on_tokens is not None:
            # Streaming: surface this chunk's accepted tokens as they
            # land. Exceptions must not kill the engine loop — a gone
            # stream consumer just stops receiving.
            try:
                st.on_tokens(st.out[n_before:], st.done)
            except Exception:  # noqa: BLE001
                st.on_tokens = None
        if st.done:
            self.results[st.req_id] = st.out
            finished.append(st.req_id)
            del self.slots[slot]
            self.free.append(slot)
            self._kv_np[slot] = False

    def _grow_valid(self, steps: int) -> None:
        """Grow read-validity on the host mirror (vectorized over slots):
        each active slot may read its next ``steps`` rows as it writes
        them (reads stay bounded per-step by ``col <= slot_pos`` inside
        the chunk program). The left-pad region [0, pos_offset) stays
        invalid. One [B, L] upload per chunk replaces per-slot device
        scatters. ONE definition for both chunk flavors — the invariant
        must not fork."""
        ar = np.arange(self.max_len)[None, :]
        active = np.zeros((self.B,), bool)
        active[list(self.slots)] = True
        limit = (self._pos_np + steps)[:, None]
        self._kv_np |= active[:, None] & (ar >= self._off_np[:, None]) & (ar < limit)

    @staticmethod
    def _draft(hist: List[int], k: int) -> List[int]:
        """Prompt-lookup draft (host side): find the most recent earlier
        occurrence of the LONGEST matching history suffix (3→2→1 tokens —
        longer context anchors the copy in the right template region) and
        copy what followed it, SHIFTED by one — the verify chunk's first
        position is the committed token t0 (known only on device), so
        drafts guess t0's continuation. PAD (0) fills when history gives
        nothing; wrong drafts cost nothing extra (the verify forward runs
        k+1 wide either way)."""
        n = len(hist)
        if n < 2:
            return [0] * k
        # One reverse scan over occurrences of the last token, extending
        # each hit leftward to measure suffix-match length (≤3). No slice
        # allocations: this runs on the synchronous spec path, where host
        # time adds directly to every chunk's latency.
        last = hist[-1]
        best_j, best_m = -1, 0
        for j in range(n - 2, -1, -1):
            if hist[j] != last:
                continue
            m = 1
            while m < 3 and j - m >= 0 and hist[j - m] == hist[n - 1 - m]:
                m += 1
            if m > best_m:
                best_j, best_m = j, m
                if m == 3:
                    break
        if best_j < 0:
            return [0] * k
        d = hist[best_j + 2 : best_j + 2 + k]
        return d + [0] * (k - len(d))

    def step_spec(self) -> List[int]:
        """One speculative verify chunk for every active slot (greedy pools
        only — the engine falls back to plain chunks when any active slot
        samples). Synchronous: per-slot acceptance counts must reach the
        host before the next dispatch, so this path trades the pipelining
        RTT overlap for 1..k+1 tokens per weight stream."""
        if not self.slots:
            return []
        k = self.spec_k
        drafts = np.zeros((self.B, k), np.int32)
        for slot, st in self.slots.items():
            drafts[slot] = self._draft(st.prompt_ids + st.out, k)
        self._grow_valid(k + 1)
        self.cache, self.last, _, toks, counts = _spec_chunk_jit(
            self.params, self.cfg, self.cache, self.last,
            jnp.asarray(self._pos_np.copy()), jnp.asarray(self._kv_np.copy()),
            jnp.asarray(self._off_np.copy()), jnp.asarray(drafts), k,
        )
        toks_h = np.asarray(toks)
        counts_h = np.asarray(counts).astype(np.int32)
        # Every slot's mirror advances by ITS emitted count (inactive slots
        # drift harmlessly — admission resets their position, exactly as
        # with the lockstep += chunk_steps of the plain path).
        self._pos_np += counts_h
        finished: List[int] = []
        self.spec_stats["chunks"] += 1
        for slot, st in list(self.slots.items()):
            n = int(counts_h[slot])
            self.spec_stats["emitted"] += n
            self.spec_stats["slot_chunks"] += 1
            self._emit(slot, st, toks_h[slot][:n], finished)
        return finished

    def cancel_request(self, rid: int) -> Optional[List[int]]:
        """Retire a mid-decode request NOW (between chunks): returns its
        partial tokens, frees the slot, and marks the _Slot done so a
        stale pipelined snapshot skips it as overshoot. THE retirement
        bookkeeping for cancellation — one definition, shared with the
        normal retire tail in _emit. Returns None when the rid is not
        active (already finished or never admitted)."""
        for slot, st in list(self.slots.items()):
            if st.req_id == rid:
                st.done = True
                del self.slots[slot]
                self.free.append(slot)
                self._kv_np[slot] = False
                return st.out
        return None

    def spec_ready(self) -> bool:
        """True when the next chunk should be a speculative verify chunk:
        spec enabled and every active slot greedy. THE predicate for both
        step() and the engine loop (which needs it separately to drain its
        pipelined handle before going synchronous)."""
        return bool(
            self.spec_k
            and self.slots
            and all(self._temp_np[s] <= 0.0 for s in self.slots)
        )

    def step(self) -> List[int]:
        """One decode chunk for every active slot; returns req_ids finished
        in this chunk (their token lists land in ``results``). With
        ``spec_k`` set and an all-greedy pool this IS a speculative verify
        chunk — ONE dispatch rule for step()/run_all/engine callers."""
        if self.spec_ready():
            return self.step_spec()
        return self.process_chunk(self.step_async())

    def run_all(self, prompts: List[List[int]], max_new_tokens: int = 64) -> List[List[int]]:
        """Drain a whole request list through the slot pool (admitting as
        slots free up); returns outputs in request order."""
        pending = list(enumerate(prompts))
        order: Dict[int, int] = {}
        while pending or self.slots:
            while pending and self.free:
                idx, p = pending.pop(0)
                order[self.admit(p, max_new_tokens)] = idx
            self.step()
        # Consume only THIS call's request ids: results from an earlier
        # run_all/admit on the same batcher must neither leak in nor crash
        # the index lookup (run_all is reusable for warmup+measure passes).
        outs: List[List[int]] = [[] for _ in prompts]
        for rid, idx in order.items():
            outs[idx] = self.results.pop(rid, [])
        return outs


class ServingEngine:
    """The ONLINE serving path: one shared ContinuousBatcher behind a
    thread-safe submit API, so every concurrent caller — playground chat,
    eval runner, LLM-judge tier — joins ONE decode batch instead of each
    running its own per-request decode stream (the reference's model: one
    sequential Ollama HTTP hop per request, services/dashboard/app.py:
    1226-1258).

    A single daemon loop thread owns the batcher (admission and decode
    chunks never race); callers block on a Future. Requests are admitted
    mid-decode as slots free up, each with its own max_tokens/temperature.
    Greedy outputs are slot-for-slot identical to a solo
    ``generate_tokens`` call (the batcher's parity invariant), so routing
    online traffic here is a throughput decision, not an accuracy one.

    ``fits()`` mirrors the batcher's admission bucketing: a request whose
    padded prompt + budget would overrun the slot window is the CALLER's
    cue to fall back to a solo decode (LlamaRuntime does exactly that) —
    inside the pool it would truncate where the solo path keeps going.
    """

    def __init__(
        self,
        params: Params,
        cfg: LlamaConfig,
        *,
        batch_slots: int = 8,
        max_len: int = 512,
        chunk_steps: int = 8,
        eos_id: Optional[int] = None,
        rng: Optional[jax.Array] = None,
        spec_k: Optional[int] = None,
    ):
        if spec_k is None:
            spec_k = int(os.environ.get("KAKVEDA_SERVE_SPEC", "0"))
        self.cb = ContinuousBatcher(
            params, cfg, batch_slots=batch_slots, max_len=max_len,
            chunk_steps=chunk_steps, eos_id=eos_id, rng=rng, spec_k=spec_k,
        )
        self._q: "queue.Queue[Tuple[List[int], int, float, Future]]" = queue.Queue()
        self._closed = threading.Event()
        self._submit_lock = threading.Lock()  # closes the submit/close race
        self._pend: Dict[int, Future] = {}  # loop-owned; close() fails leftovers
        self._waiting: List = []  # loop-owned: admitted-when-a-slot-frees queue
        self.stats = {"submitted": 0, "completed": 0, "max_active": 0, "chunks": 0}
        self._thread = threading.Thread(target=self._loop, daemon=True, name="serving-engine")
        self._thread.start()

    def fits(self, prompt_len: int, max_new_tokens: int) -> bool:
        """True when the request can run in the pool WITHOUT truncating
        where a solo decode wouldn't: the admission bucket (power-of-two
        left-pad) plus the full token budget must fit the slot window."""
        ml = self.cb.max_len
        if prompt_len + 1 >= ml:
            return False
        bucket = ContinuousBatcher.bucket_for(prompt_len, ml)
        return bucket + max_new_tokens + 1 <= ml

    def submit(
        self,
        prompt_ids: List[int],
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        on_tokens=None,
    ) -> Future:
        """Enqueue a request; the Future resolves to the generated id list.

        ``on_tokens(new_ids, done)`` (optional) streams each decode chunk's
        accepted tokens as they land — called on the engine loop thread, so
        it must be non-blocking (push to a queue and return)."""
        with self._submit_lock:
            # Atomic with close()'s drain: without the lock a put landing
            # between close()'s _closed.set() and its queue drain would
            # enqueue into a dead loop and hang its caller forever.
            if self._closed.is_set():
                raise RuntimeError("ServingEngine is closed")
            fut: Future = Future()
            self._q.put((list(prompt_ids), max_new_tokens, temperature, on_tokens, fut))
            self.stats["submitted"] += 1
            return fut

    def generate_ids(
        self, prompt_ids: List[int], max_new_tokens: int = 64, temperature: float = 0.0
    ) -> List[int]:
        """Blocking submit — what runtime.generate calls from its executor
        thread while the loop thread decodes for everyone at once."""
        return self.submit(prompt_ids, max_new_tokens, temperature).result()

    def cancel(self, fut: Future) -> None:
        """Best-effort cancel of a submitted request: if still queued, the
        Future cancels; if mid-decode, the loop retires its slot at the
        next chunk boundary (the slot frees for other traffic instead of
        decoding a result nobody will read — the disconnect case). The
        Future resolves with the tokens generated so far."""
        if fut.cancel():
            return  # never admitted; set_running_or_notify_cancel skips it
        with self._submit_lock:
            if self._closed.is_set():
                return
            self._q.put(("cancel", fut, fut))

    def register_prefix(self, prefix_ids: List[int], timeout: float = 120.0) -> bool:
        """Precompute a shared prompt prefix's K/V once; later submits whose
        prompts start with it prefill only their suffix. Runs on the loop
        thread (the batcher is loop-owned; a registration prefill must not
        race a decode chunk's donated cache). Blocking; returns whether the
        prefix was accepted (see ContinuousBatcher.register_prefix)."""
        with self._submit_lock:
            if self._closed.is_set():
                raise RuntimeError("ServingEngine is closed")
            fut: Future = Future()
            self._q.put(("prefix", list(prefix_ids), fut))
        return bool(fut.result(timeout=timeout))

    @staticmethod
    def _fail(fut: Future, err: BaseException) -> None:
        """set_exception tolerant of losing the race against the loop's
        set_result (close() can outlive its 5 s join while a chunk compile
        finishes): whichever side lands second is a no-op, never an
        InvalidStateError escaping into restore()/eviction."""
        try:
            if not fut.done():
                fut.set_exception(err)
        except Exception:  # noqa: BLE001 — InvalidStateError: already resolved
            pass

    def close(self) -> None:
        with self._submit_lock:
            self._closed.set()
        self._thread.join(timeout=5.0)
        # Fail anything still queued OR already admitted (mid-decode in
        # _pend) — callers must not hang on a dead loop.
        while True:
            try:
                *_rest, fut = self._q.get_nowait()
            except queue.Empty:
                break
            self._fail(fut, RuntimeError("ServingEngine closed"))
        for item in self._waiting:
            self._fail(item[-1], RuntimeError("ServingEngine closed"))
        self._waiting.clear()
        for fut in list(self._pend.values()):
            self._fail(fut, RuntimeError("ServingEngine closed mid-request"))
        self._pend.clear()

    def _admit_one(self, item) -> None:
        if item[0] == "cancel":
            _, fut, _ = item
            rid = next((r for r, f in self._pend.items() if f is fut), None)
            if rid is None:
                return  # already finished (or was never admitted)
            toks = self.cb.cancel_request(rid)
            self._pend.pop(rid, None)
            if toks is None:
                toks = self.cb.results.pop(rid, [])  # finished between chunks
            if not fut.done():
                try:
                    fut.set_result(toks)
                except Exception:  # noqa: BLE001 — lost the race with completion
                    pass
            return
        if item[0] == "prefix":
            _, ids, fut = item
            if not fut.set_running_or_notify_cancel():
                return
            try:
                fut.set_result(self.cb.register_prefix(ids))
            except Exception as e:  # noqa: BLE001 — registration errors belong to the caller
                self._fail(fut, e)
            return
        ids, max_new, temp, on_tokens, fut = item
        if not fut.set_running_or_notify_cancel():
            return
        try:
            rid = self.cb.admit(
                ids, max_new_tokens=max_new, temperature=temp, on_tokens=on_tokens
            )
        except Exception as e:  # noqa: BLE001 — admission errors belong to the caller
            self._fail(fut, e)
            return
        self._pend[rid] = fut

    def _loop(self) -> None:
        # Chunk pipelining (KAKVEDA_SERVE_PIPELINE=0 opts out): dispatch
        # chunk i+1 BEFORE fetching chunk i's tokens, so the fixed
        # device→host RTT of each token fetch (~70-90 ms on tunneled TPUs,
        # often > the chunk's compute) overlaps the next chunk's device
        # work — per-chunk cost drops from compute+RTT to max(compute,
        # RTT). Outputs are token-identical (see step_async); the cost is
        # retirement lag: a finished slot frees one chunk later, and one
        # overshoot chunk runs at the end of each busy period.
        pipelined = os.environ.get("KAKVEDA_SERVE_PIPELINE", "1") != "0"
        pending_handle = None

        def pump_queue(block: bool) -> None:
            # Control items (cancel, prefix registration) act immediately —
            # a cancel matters MOST when the pool is full, so they must
            # not wait behind the capacity gate. Generation requests wait
            # in _waiting until a slot frees.
            try:
                while True:
                    item = self._q.get(timeout=0.1) if block else self._q.get_nowait()
                    block = False
                    if item[0] in ("cancel", "prefix"):
                        self._admit_one(item)
                    else:
                        self._waiting.append(item)
            except queue.Empty:
                pass
            while self._waiting and self.cb.has_capacity:
                self._admit_one(self._waiting.pop(0))

        try:
            while not self._closed.is_set():
                # Idle: block briefly for the next arrival (bounded so
                # close() is prompt) instead of spinning on an empty pool.
                pump_queue(
                    block=not self.cb.slots and pending_handle is None and not self._waiting
                )
                if self.cb.spec_ready():
                    # Speculative verify chunks are synchronous (per-slot
                    # acceptance must reach the host before the next
                    # dispatch): drain any pipelined handle first, then
                    # advance every greedy slot 1..k+1 tokens in one
                    # weight stream.
                    finished = self.cb.process_chunk(pending_handle)
                    pending_handle = None
                    if self.cb.slots:
                        self.stats["max_active"] = max(
                            self.stats["max_active"], self.cb.active
                        )
                        finished += self.cb.step_spec()
                        self.stats["chunks"] += 1
                elif self.cb.slots:
                    self.stats["max_active"] = max(self.stats["max_active"], self.cb.active)
                    handle = self.cb.step_async()
                    self.stats["chunks"] += 1
                    if not pipelined:
                        finished = self.cb.process_chunk(handle)
                    else:
                        finished = self.cb.process_chunk(pending_handle)
                        pending_handle = handle
                else:
                    finished = self.cb.process_chunk(pending_handle)
                    pending_handle = None
                for rid in finished:
                    self.stats["completed"] += 1
                    fut = self._pend.pop(rid, None)
                    toks = self.cb.results.pop(rid, [])
                    if fut is not None and not fut.done():
                        try:
                            fut.set_result(toks)
                        except Exception:  # noqa: BLE001 — close() won the race
                            pass
        except BaseException as e:  # noqa: BLE001 — a dead loop must not strand callers
            # A device/runtime error escaping cb.step() would otherwise
            # kill this thread silently: every pending Future would hang
            # forever and later submits would enqueue into a dead loop.
            # Mark closed (new submits raise) and fail everything pending.
            with self._submit_lock:
                self._closed.set()
            err = RuntimeError(f"ServingEngine loop died: {type(e).__name__}: {e}")
            for item in self._waiting:
                self._fail(item[-1], err)
            self._waiting.clear()
            for fut in list(self._pend.values()):
                self._fail(fut, err)
            self._pend.clear()
            while True:
                try:
                    *_rest, fut = self._q.get_nowait()
                except queue.Empty:
                    break
                self._fail(fut, err)
