"""Continuous batching — THE online serving path.

``ServingEngine`` (bottom of this module) is what LlamaRuntime routes
``generate``/``generate_batch`` through by default
(KAKVEDA_SERVE_CONTINUOUS=0 opts out): one daemon loop thread owns a
shared ContinuousBatcher, concurrent callers block on Futures, and every
online request — playground chat, eval row, LLM-judge call — joins one
decode batch. Offline throughput paths (bench, training eval) keep
calling ``generate_tokens_fused`` directly.

The playground, eval runner and LLM-judge tier all call generate. Static
batching (`generate_tokens_batch`/`_fused`) decodes a fixed cohort to the
longest member: every finished (EOS) sequence leaves its batch slot idle
until the whole cohort drains, and new requests wait for the next cohort.
Under mixed-length traffic that wastes both slots and latency.

**Design.** A `ContinuousBatcher` owns a fixed [B, KV, max_len, D] KV-cache
(static shapes — nothing ever retraces) and treats the batch axis as B
independent *slots*:

  * **admit**: a new prompt prefills into one free slot — a [1, P] prefill
    whose cache rows are scattered into the batch cache at that slot
    (`_admit_jit`). Other slots are untouched; admission interleaves with
    decoding chunks.
  * **step_chunk**: ONE bounded decode program advances every active slot
    by up to `chunk_steps` tokens (same chunked-dispatch scheduling that
    lets pre-flight warn batches share the chip — models/generate.py
    `DecodeSession`). Inactive slots decode garbage into their own slot
    positions that admission later overwrites — masked out by per-slot
    `kv_valid`, never visible to active slots.
  * **retire**: EOS/length-exhausted slots free on the host between
    chunks; their results return to callers and the slot re-enters the
    free list.

Throughput model: with static batching a cohort of B requests whose decode
lengths are L_i costs max(L_i) steps of B-wide compute; continuous
batching costs ~mean(L_i) per request at steady state — the delta grows
with length variance (bench: `KAKVEDA_BENCH_METRIC=continuous python
bench.py`, reported in docs/performance.md).

Capability replaced: the reference serves generations through sequential
per-request Ollama HTTP calls (services/dashboard/app.py:1182-1258) — no
batching at all; eval loops run one example at a time
(app.py:2315-2393).

Use the class directly (``ContinuousBatcher(params, cfg, ...)``); it
accepts the same param trees as every other forward path, including int8
weight-only quantized ones (llama.wmat). Decoding is greedy by default;
``admit(..., temperature=t)`` samples that slot only (a [B] temperature
vector threads through the chunk body; greedy slots stay exact).
"""

from __future__ import annotations

import copy
import logging
import os
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kakveda_tpu.core import admission as _admission
from kakveda_tpu.core import faults as _faults
from kakveda_tpu.core import metrics as _metrics
from kakveda_tpu.core.admission import DeviceUnavailableError, OverloadError
from kakveda_tpu.core import ledger as _ledger
from kakveda_tpu.core import sanitize
from kakveda_tpu.core import trace as _trace
from kakveda_tpu.models.llama import (
    LlamaConfig,
    Params,
    decode_step,
    init_cache,
    mask_pad_vocab,
)
from kakveda_tpu.models.speculative import NgramIndex, copy_run

log = logging.getLogger("kakveda.serving")

_GATE_STATES = ("disabled", "warmup", "on", "off")


class EngineRetryableError(RuntimeError):
    """An in-flight request was lost to a serving-engine loop death. The
    request's slot state is gone but the supervisor is rebuilding the
    engine — resubmitting is safe (no tokens were delivered to the
    Future). RuntimeError subclass so existing solo-fallback callers
    (LlamaRuntime.generate*) handle it without changes."""


class EngineDeadError(RuntimeError):
    """The serving engine is permanently dead: the supervisor's restart
    budget (KAKVEDA_SERVE_RESTARTS) is exhausted, or the rebuild itself
    failed. submit()/register_prefix() raise this IMMEDIATELY — fail fast
    instead of enqueueing into a queue nobody drains."""


class DeadlineExceededError(RuntimeError):
    """A request's ``deadline_s`` expired before it completed. Carries the
    tokens decoded so far in ``.tokens`` (possibly empty — the request may
    have expired while still queued)."""

    def __init__(self, message: str, tokens: Optional[List[int]] = None):
        super().__init__(message)
        self.tokens: List[int] = list(tokens or [])


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def _admit_jit(params, cfg: LlamaConfig, cache, last, prompt, slot, kv_valid, pos_offset):
    """Prefill ``prompt`` [1, P] into batch slot ``slot`` of ``cache``.

    The single-sequence prefill runs with its own [1, ...] scratch cache
    (so its attention sees only this prompt), then its K/V rows scatter
    into the batch cache at ``slot``. `last` [B, V] gets the slot's
    next-token logits.
    """
    b = last.shape[0]
    p = prompt.shape[1]
    scratch = init_cache(cfg, batch=1, max_len=cache["k"][0].shape[2])
    logits, scratch = decode_step(
        params, cfg, prompt, scratch,
        kv_valid=kv_valid[slot][None],
        pos_offset=pos_offset[slot][None],
        last_only=True,
    )
    out = {"pos": cache["pos"]}
    for key in ("k", "v") + (("ks", "vs") if cfg.kv_quant == "int8" else ()):
        zeros = (0,) * (cache[key][0].ndim - 1)
        out[key] = [
            jax.lax.dynamic_update_slice(ck, sk, (slot, *zeros))
            for ck, sk in zip(cache[key], scratch[key])
        ]
    nl = mask_pad_vocab(logits[:, -1, :], cfg)
    last = jax.lax.dynamic_update_slice(last, nl, (slot, 0))
    # cache["pos"] is managed per-slot on host (slot positions differ);
    # the batch cache carries pos=0 and step passes explicit positions.
    return out, last


def _forward_wide(params, cfg: LlamaConfig, cache_k, cache_v, cache_ks, cache_vs, tokens, slot_pos, kv_valid, pos_offset):
    """THE serving-chunk forward body, S-wide with PER-SLOT positions:
    token i of slot b writes cache row ``slot_pos[b]+i`` and attends rows
    ``col <= slot_pos[b]+i`` (within kv_valid, and the sliding-window band
    when the layer has one). Shared by the plain decode chunk (S=1 inside
    a scan) and the speculative verify chunk (S=k+1) — ONE body to honor
    model-family flags, not two. Attention goes through
    ``gqa_cache_attention``: S=1 masks are expressible as [B, L] kv_valid
    (keeping the flash / int8-streaming dispatch), S>1 passes the full
    [B, S, L] mask (XLA path; S <= k+1 keeps its scratch tiny).

    Returns (logits [B, S, V] vocab-masked f32, new_k, new_v, new_ks, new_vs).
    """
    from kakveda_tpu.models.attention import gqa_cache_attention
    from kakveda_tpu.models.llama import (
        _kv_quant_rows,
        _rope_freqs,
        apply_rope,
        embed_tokens,
        mlp_block,
        qkv_proj,
        rms_norm,
        softcap_logits,
        wmat,
    )

    b, s = tokens.shape
    hd = cfg.head_dim
    max_len = cache_k[0].shape[2]
    kq = cfg.kv_quant == "int8"

    positions = slot_pos[:, None] + jnp.arange(s)[None, :] - pos_offset[:, None]
    cos, sin = _rope_freqs(cfg, positions)
    x = embed_tokens(params, cfg, tokens)

    col = jnp.arange(max_len)[None, None, :]  # [1, 1, L]
    qpos = (slot_pos[:, None] + jnp.arange(s)[None, :])[:, :, None]  # [B, S, 1]
    base_mask = kv_valid[:, None, :] & (col <= qpos)  # [B, S, L]
    win_mask = base_mask
    if cfg.sliding_window:
        win_mask = base_mask & (col > qpos - cfg.sliding_window)

    rows = jnp.arange(b)[:, None]  # [B, 1]
    wcols = slot_pos[:, None] + jnp.arange(s)[None, :]  # [B, S] write indices
    new_k, new_v, new_ks, new_vs = [], [], [], []
    for li in range(cfg.n_layers):
        mask = win_mask if cfg.layer_window(li) else base_mask
        layer = params["layers"][li]
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        dt = h.dtype
        q, k, v = qkv_proj(h, layer, cfg, dt)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # Per-slot scatter: row i of slot b lands at cache[b, :, slot_pos[b]+i]
        # — a real scatter (in-place row writes), not a whole-cache rewrite;
        # mode="drop" clamps overshoot past the window (discarded host-side).
        k_rows = k.transpose(0, 2, 1, 3)  # [B, KV, S, D]
        v_rows = v.transpose(0, 2, 1, 3)
        ks_all = vs_all = None
        if kq:
            # Same per-row quantizer as decode_step, so a slot's cache
            # bytes are identical to its solo decode — int8 parity is
            # exact, not approximate-squared.
            k_i8, k_sc = _kv_quant_rows(k_rows)
            v_i8, v_sc = _kv_quant_rows(v_rows)
            k_all = cache_k[li].at[rows, :, wcols].set(k_i8.transpose(0, 2, 1, 3), mode="drop")
            v_all = cache_v[li].at[rows, :, wcols].set(v_i8.transpose(0, 2, 1, 3), mode="drop")
            ks_all = cache_ks[li].at[rows, :, wcols].set(k_sc.transpose(0, 2, 1), mode="drop")
            vs_all = cache_vs[li].at[rows, :, wcols].set(v_sc.transpose(0, 2, 1), mode="drop")
            new_ks.append(ks_all)
            new_vs.append(vs_all)
        else:
            k_all = cache_k[li].at[rows, :, wcols].set(
                k_rows.transpose(0, 2, 1, 3).astype(cfg.dtype), mode="drop"
            )
            v_all = cache_v[li].at[rows, :, wcols].set(
                v_rows.transpose(0, 2, 1, 3).astype(cfg.dtype), mode="drop"
            )
        new_k.append(k_all)
        new_v.append(v_all)
        if s == 1:
            # [B, L] mask keeps the flash/int8-streaming dispatch;
            # pos0=max_len makes the kernel's scalar causal mask a no-op.
            attn = gqa_cache_attention(
                q, k_all, v_all, jnp.asarray(max_len), mask[:, 0, :],
                softcap=cfg.attn_softcap, k_scale=ks_all, v_scale=vs_all,
            )
        else:
            attn = gqa_cache_attention(
                q, k_all, v_all, jnp.asarray(max_len), None,
                softcap=cfg.attn_softcap, k_scale=ks_all, v_scale=vs_all,
                full_mask=mask,
            )
        attn = attn.reshape(b, s, cfg.n_heads * hd) @ wmat(layer["wo"], dt)
        if "post_attn_norm" in layer:
            attn = rms_norm(attn, layer["post_attn_norm"], cfg.norm_eps)
        x = x + attn
        h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        m = mlp_block(h, layer, cfg)
        if "post_ffw_norm" in layer:
            m = rms_norm(m, layer["post_ffw_norm"], cfg.norm_eps)
        x = x + m
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ wmat(params["lm_head"], cfg.dtype)).astype(jnp.float32)
    logits = softcap_logits(logits, cfg.final_softcap)
    logits = mask_pad_vocab(logits, cfg)
    return logits, new_k, new_v, new_ks, new_vs


@partial(jax.jit, static_argnames=("cfg", "n_steps"), donate_argnums=(2,))
def _step_chunk_jit(params, cfg: LlamaConfig, cache, last, slot_pos, kv_valid, pos_offset, temps, rng, n_steps: int):
    """Advance every slot by ``n_steps`` tokens in one program.

    ``slot_pos`` [B] — per-slot NEXT cache index (prompt length + tokens
    decoded so far). decode_step's scalar `pos` can't express per-slot
    positions, so the chunk scans :func:`_forward_wide` at S=1 with a
    per-slot write index: token t of slot b lands at cache[b, :, slot_pos[b]+t].
    ``temps`` [B] — per-slot sampling temperature; a slot with temp <= 0
    decodes greedily, others sample categorically (one rng split per step,
    shared across slots — rows are independent draws of the same key).
    """
    kq = cfg.kv_quant == "int8"

    def one_step(carry, _):
        cache_k, cache_v, cache_ks, cache_vs, last, slot_pos, rng = carry
        rng, sub = jax.random.split(rng)
        sampled = jax.random.categorical(
            sub, last / jnp.maximum(temps, 1e-6)[:, None], axis=-1
        )
        nxt = jnp.where(temps > 0.0, sampled, jnp.argmax(last, axis=-1))  # [B]
        logits, new_k, new_v, new_ks, new_vs = _forward_wide(
            params, cfg, cache_k, cache_v, cache_ks, cache_vs,
            nxt[:, None].astype(jnp.int32), slot_pos, kv_valid, pos_offset,
        )
        return (new_k, new_v, new_ks, new_vs, logits[:, -1, :], slot_pos + 1, rng), nxt

    init = (
        cache["k"], cache["v"],
        cache.get("ks", []), cache.get("vs", []),
        last, slot_pos, rng,
    )
    (ck, cv, cks, cvs, last, slot_pos, rng), toks = jax.lax.scan(
        one_step, init, None, length=n_steps
    )
    out = {"pos": cache["pos"], "k": ck, "v": cv}
    if kq:
        out["ks"], out["vs"] = cks, cvs
    return out, last, slot_pos, rng, toks.T  # [B, n_steps]


@partial(jax.jit, static_argnames=("cfg", "k"), donate_argnums=(2,))
def _spec_chunk_jit(params, cfg: LlamaConfig, cache, last, slot_pos, kv_valid, pos_offset, drafts, k: int):
    """Speculative verify chunk: each slot advances 1..k+1 GREEDY tokens in
    ONE :func:`_forward_wide` pass over k+1 positions.

    ``drafts`` [B, k] are host-side prompt-lookup guesses for the tokens
    AFTER the committed next token t0 (= argmax(last), computed in-program
    so every chunk emits >= 1 token). The k+1-wide forward writes all rows
    and produces logits at every position; the accepted prefix is the run
    of drafts matching their own greedy verdicts. Rows written past the
    accepted point hold K/V of rejected tokens — never read (validity is
    bounded by each query's own position) and overwritten as real decoding
    reaches them, the same clamp-and-discard contract as pipelined
    overshoot. Decode is weight-bandwidth-bound, so the k+1-wide forward
    rides the SAME weight stream as a 1-wide step — accepted tokens are
    nearly free (models/speculative.py measures 1.3-1.7 tokens/round on
    judge-shaped traffic).

    Returns (cache, new_last [B,V], new_slot_pos [B], toks [B, k+1],
    counts [B]) — the host emits ``toks[b, :counts[b]]``.
    """
    kq = cfg.kv_quant == "int8"
    t0 = jnp.argmax(last, axis=-1).astype(jnp.int32)  # [B]
    tokens = jnp.concatenate([t0[:, None], drafts.astype(jnp.int32)], axis=1)  # [B, k+1]
    logits, new_k, new_v, new_ks, new_vs = _forward_wide(
        params, cfg, cache["k"], cache["v"],
        cache.get("ks", []), cache.get("vs", []),
        tokens, slot_pos, kv_valid, pos_offset,
    )
    new_cache = {"pos": cache["pos"], "k": new_k, "v": new_v}
    if kq:
        new_cache["ks"], new_cache["vs"] = new_ks, new_vs

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, k+1]; [b, i] follows tokens[b, :i+1]
    match = (drafts.astype(jnp.int32) == greedy[:, :-1]).astype(jnp.int32)  # [B, k]
    m_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # [B] accepted drafts
    counts = m_acc + 1  # emitted = t0 + accepted drafts
    # Next chunk's `last` = logits after the final emitted token.
    new_last = jnp.take_along_axis(logits, m_acc[:, None, None], axis=1)[:, 0, :]
    return new_cache, new_last, slot_pos + counts, tokens, counts


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def _admit_prefix_jit(
    params, cfg: LlamaConfig, cache, last, pfx, suffix, slot, kv_valid, pos_offset, write_pos
):
    """Prefill only ``suffix`` [1, S'] into batch slot ``slot``, reusing the
    precomputed K/V rows of a shared prompt prefix (``pfx``: per-layer
    [1, KV, plen, D] slabs from :meth:`ContinuousBatcher.register_prefix`).

    Prefix K/V rows are position-INDEPENDENT of the slot layout: RoPE
    rotates by logical position (cache index − pos_offset), and a prefix
    token's logical position is its own index regardless of how much left
    pad the admission bucket adds — so one registered slab serves every
    bucket. The slab lands at [off, off+plen); the suffix chunk recomputes
    rows from ``write_pos`` (= off + split point), overwriting the slab's
    tail where the power-of-two suffix chunk overlaps it with identical
    values. Attention over not-yet-written rows is causally masked exactly
    as in chunked prefill.
    """
    b = last.shape[0]
    max_len = cache["k"][0].shape[2]
    off = pos_offset[slot]
    scratch = init_cache(cfg, batch=1, max_len=max_len)
    scratch["pos"] = write_pos
    for key in ("k", "v") + (("ks", "vs") if cfg.kv_quant == "int8" else ()):
        starts = (0, 0, off, 0) if pfx[key][0].ndim == 4 else (0, 0, off)
        scratch[key] = [
            jax.lax.dynamic_update_slice(sk, pk, starts)
            for sk, pk in zip(scratch[key], pfx[key])
        ]
    logits, scratch = decode_step(
        params, cfg, suffix, scratch,
        kv_valid=kv_valid[slot][None],
        pos_offset=pos_offset[slot][None],
        last_only=True,
    )
    out = {"pos": cache["pos"]}
    for key in ("k", "v") + (("ks", "vs") if cfg.kv_quant == "int8" else ()):
        zeros = (0,) * (cache[key][0].ndim - 1)
        out[key] = [
            jax.lax.dynamic_update_slice(ck, sk, (slot, *zeros))
            for ck, sk in zip(cache[key], scratch[key])
        ]
    nl = mask_pad_vocab(logits[:, -1, :], cfg)
    last = jax.lax.dynamic_update_slice(last, nl, (slot, 0))
    return out, last


@partial(jax.jit, static_argnames=("cfg",))
def _prefix_prefill_jit(params, cfg: LlamaConfig, ids):
    """One compiled prefill for prefix registration ([1, plen] exact-length
    cache). Eager decode_step here would pay a per-op dispatch — thousands
    of ~80 ms round trips on a tunneled chip — for what is one program."""
    scratch = init_cache(cfg, batch=1, max_len=ids.shape[1])
    _, scratch = decode_step(params, cfg, ids, scratch, last_only=True)
    return scratch


@dataclass
class _Prefix:
    """One registered shared prompt prefix: token ids + per-layer K/V slabs
    ([1, KV, plen, D], int8 + scales when the cache is quantized), plus an
    n-gram index over the ids so speculative drafting can copy template
    continuations even before a slot's own history contains them."""

    ids: Tuple[int, ...]
    kv: Dict[str, List[jax.Array]]
    index: Optional[NgramIndex] = None


@dataclass
class _Slot:
    req_id: int
    prompt_len: int
    max_new: int
    out: List[int] = field(default_factory=list)
    done: bool = False
    # Streaming: called from process_chunk with (new_tokens, done) after
    # each chunk. MUST be fast/non-blocking (queue put) — it runs on the
    # engine loop thread between device dispatches.
    on_tokens: Optional[object] = None
    # Prompt ids retained for host-side speculative drafting (prompt +
    # out = the lookup corpus).
    prompt_ids: List[int] = field(default_factory=list)
    # Speculative state (spec pools only): incremental suffix index over
    # prompt+emitted history; per-slot adaptive draft length in
    # [1, spec_k]; acceptance EMA driving it; and the pipelined copy
    # cursor — (corpus, next idx, period, frozen len), the head of the
    # predicted-continuation chain. The chain survives only while every
    # processed chunk fully matches its own prediction (which travels in
    # the HANDLE, not here — by processing time a newer dispatch has
    # already moved this cursor); any mismatch clears it and the next
    # dispatch re-anchors.
    index: Optional[NgramIndex] = None
    k: int = 0
    accept_ema: float = 0.0
    spec_cursor: Optional[Tuple] = None


class ContinuousBatcher:
    """Admit-as-you-go generation over a fixed slot pool. Greedy by
    default; per-request ``temperature`` samples that slot only."""

    def __init__(
        self,
        params: Params,
        cfg: LlamaConfig,
        *,
        batch_slots: int = 8,
        max_len: int = 512,
        chunk_steps: int = 8,
        eos_id: Optional[int] = None,
        rng: Optional[jax.Array] = None,
        spec_k: int = 0,
        name: str = "default",
        recorder: Optional[_metrics.FlightRecorder] = None,
    ):
        self.params, self.cfg = params, cfg
        self.B, self.max_len = batch_slots, max_len
        self.chunk_steps = chunk_steps
        self.spec_k = spec_k
        self.name = name
        self.recorder = recorder
        # Observability + the acceptance auto-gate's decision state, one
        # dict so serving_stats/bench surface everything at once.
        # gate_state: disabled (spec_k=0) | warmup (measuring) | on | off.
        # The loop thread mutates this concurrently with readers — every
        # mutation holds ``stats_lock`` (RLock: the gate helper nests
        # inside locked sections) and readers go through
        # :meth:`stats_snapshot` / ``ServingEngine.stats()``.
        self.stats_lock = sanitize.named_lock("ContinuousBatcher.stats_lock", kind="rlock")
        self.spec_stats = {
            "chunks": 0, "emitted": 0, "slot_chunks": 0,
            "drafted": 0, "accepted": 0,
            "gate_state": "warmup" if spec_k else "disabled",
            "tokens_per_verify": 0.0,
            "break_even": 0.0,
            "k_trace": [],  # pool verify width per chunk, last 64
        }
        # Metrics-plane children, resolved ONCE here: a per-chunk update is
        # a lock + an add, nothing label-shaped on the hot path.
        reg = _metrics.get_registry()
        self._gate_gauge = reg.gauge(
            "kakveda_serving_spec_gate_state",
            "1 for the pool's current speculation gate state "
            "(disabled|warmup|on|off)", ("engine", "state"),
        )
        self._gate_transitions = reg.counter(
            "kakveda_serving_gate_transitions_total",
            "Speculation auto-gate state transitions", ("engine", "from", "to"),
        )
        for gs in _GATE_STATES:
            self._gate_gauge.labels(engine=name, state=gs).set(
                1.0 if gs == self.spec_stats["gate_state"] else 0.0
            )
        chunk_hist = reg.histogram(
            "kakveda_serving_chunk_seconds",
            "Effective decode-chunk wall (dispatch to process, overlapped "
            "under pipelining)", ("engine", "flavor"),
        )
        prefix_ctr = reg.counter(
            "kakveda_serving_prefix_requests_total",
            "Admissions by prefix-cache result", ("engine", "result"),
        )
        self._mx = {
            "chunk_plain": chunk_hist.labels(engine=name, flavor="plain"),
            "chunk_spec": chunk_hist.labels(engine=name, flavor="spec"),
            "tokens": reg.counter(
                "kakveda_serving_tokens_total",
                "Decode tokens emitted to callers", ("engine",),
            ).labels(engine=name),
            "drafted": reg.counter(
                "kakveda_serving_spec_drafted_total",
                "Speculative draft tokens sent to verify chunks", ("engine",),
            ).labels(engine=name),
            "accepted": reg.counter(
                "kakveda_serving_spec_accepted_total",
                "Speculative draft tokens accepted by verify chunks",
                ("engine",),
            ).labels(engine=name),
            "prefix_hit": prefix_ctr.labels(engine=name, result="hit"),
            "prefix_miss": prefix_ctr.labels(engine=name, result="miss"),
            "active": reg.gauge(
                "kakveda_serving_active_slots",
                "Occupied slots in the continuous-batching pool", ("engine",),
            ).labels(engine=name),
            "spec_k": reg.gauge(
                "kakveda_serving_spec_k",
                "Pool verify width of the most recent speculative chunk",
                ("engine",),
            ).labels(engine=name),
        }
        reg.gauge(
            "kakveda_serving_slots",
            "Total slots in the continuous-batching pool", ("engine",),
        ).labels(engine=name).set(batch_slots)
        self._last_k_rec = 0
        # Gate inputs: recent per-chunk wall times for each arm (median —
        # robust to one-off compile spikes), recent per-slot emitted
        # counts, and the knobs. Walls are recorded where the chunk's
        # effective cost is visible: handles carry their dispatch
        # timestamp and process_*_chunk computes dispatch→process, which
        # under pipelining is the overlapped (real) per-chunk cost.
        self._spec_walls: deque = deque(maxlen=16)
        self._plain_walls: deque = deque(maxlen=16)
        # kakveda: owned-by[serving-loop] — gate decision state, loop thread only
        self._tpv_recent: deque = deque(maxlen=32)
        self._gate_warmup = int(os.environ.get("KAKVEDA_SERVE_SPEC_WARMUP", "8"))
        self._gate_calib = int(os.environ.get("KAKVEDA_SERVE_SPEC_CALIB", "2"))
        self._gate_reprobe = int(os.environ.get("KAKVEDA_SERVE_SPEC_REPROBE", "256"))
        self._gate_prior = float(os.environ.get("KAKVEDA_SERVE_SPEC_BREAKEVEN", "1.35"))
        # kakveda: owned-by[serving-loop] — spec chunks since (re)entering warmup
        self._gate_spec_chunks = 0
        self._gate_plain_since_off = 0
        self._gate_reprobes = 0
        # Pipelined speculation: the device slot_pos returned by the last
        # verify chunk (threaded into the next dispatch WITHOUT a host
        # sync) and the un-processed in-flight chunk count/width (the
        # read-validity growth budget). Valid only while no admission or
        # plain chunk interleaves — both reset/guard it.
        self._spec_pos_dev = None
        self._spec_pending = 0
        self._spec_pending_width = 0
        # First dispatch of each program shape pays its compile; those
        # walls would poison the gate's medians (a 1000× break-even from
        # one trace), so the first sample per shape is dropped.
        self._spec_widths_warm: set = set()
        self._plain_warm = False
        # Chaos-harness sites, resolved once (core/faults.py): a bare
        # attribute check per chunk when unarmed. Dispatch fires before
        # the device program is launched, fetch before a handle's results
        # are consumed — both escape to the engine loop, whose supervisor
        # rebuilds this batcher wholesale (mid-flight state is discarded,
        # so a fault can never leave it half-mutated in service).
        self._fault_dispatch = _faults.site("engine.dispatch")
        self._fault_fetch = _faults.site("engine.fetch")
        self.eos_id = eos_id
        self.cache = init_cache(cfg, batch=batch_slots, max_len=max_len)
        self.last = jnp.full((batch_slots, cfg.vocab_size), -1e30, jnp.float32)
        # Host-side mirrors of the per-slot bookkeeping: step() would
        # otherwise pay per-slot device syncs (int(dev_arr[slot])) and
        # per-slot scatter dispatches between chunks — on remote-attached
        # chips that host bookkeeping can exceed the chunk's compute. The
        # device copies are rebuilt from the mirrors once per call.
        self._kv_np = np.zeros((batch_slots, max_len), bool)
        self._off_np = np.zeros((batch_slots,), np.int32)
        self._pos_np = np.zeros((batch_slots,), np.int32)
        self._temp_np = np.zeros((batch_slots,), np.float32)  # ≤0 = greedy
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.slots: Dict[int, _Slot] = {}
        self.free = list(range(batch_slots))
        self.results: Dict[int, List[int]] = {}
        self._next_id = 0
        self._prefixes: Dict[Tuple[int, ...], _Prefix] = {}
        self.prefix_stats = {"registered": 0, "hits": 0, "hit_tokens_saved": 0}

    @staticmethod
    def bucket_for(prompt_len: int, max_len: int) -> int:
        """Admission pad width: power-of-two ≥ prompt (min 8), capped at
        the slot window. THE definition shared by admit() and
        ServingEngine.fits() — the engine's fallback contract (never admit
        what would truncate) depends on the two staying identical. Thin
        wrapper over the ONE blessed bucket seam (``ops/knn.pow2_bucket``)
        with the admission floor/clamp semantics."""
        from kakveda_tpu.ops.knn import pow2_bucket

        return pow2_bucket(prompt_len, floor=8, cap=max_len - 1)

    def register_prefix(self, prefix_ids: List[int]) -> bool:
        """Precompute and retain the K/V rows of a shared prompt prefix so
        later admissions prefill only their suffix (``_admit_prefix_jit``).

        The natural users are the fixed instruction templates in front of
        every LLM-judge call and the playground/eval system preamble — the
        reference pays the full prompt on every Ollama hop
        (services/dashboard/app.py:1182-1258); here the shared head of the
        prompt costs its FLOPs once per process instead of once per request.

        Returns False (no-op) when the prefix is too short to matter, too
        long for the slot window, or the model's RoPE regime depends on the
        final sequence length (Phi-3 longrope: a prefix computed at length
        plen would rotate in a different regime than the full prompt —
        reuse would be silently wrong, so it is refused).
        """
        ids = tuple(int(t) for t in prefix_ids)
        if len(ids) < 8 or len(ids) + 9 >= self.max_len:
            return False
        if getattr(self.cfg, "rope_dim_factors_long", None):
            return False
        if ids in self._prefixes:
            return True
        scratch = _prefix_prefill_jit(
            self.params, self.cfg, jnp.asarray([list(ids)], jnp.int32)
        )
        keys = ("k", "v") + (("ks", "vs") if self.cfg.kv_quant == "int8" else ())
        # Bounded store: auto-registration (generate_batch common heads)
        # must not accumulate slabs without limit — each is
        # plen·KV·D·layers·2 resident HBM bytes. Dict order is recency
        # (moved-to-end on hit); evict the least recently used.
        maxp = int(os.environ.get("KAKVEDA_SERVE_PREFIX_MAX", "4"))
        while len(self._prefixes) >= max(1, maxp):
            self._prefixes.pop(next(iter(self._prefixes)))
        self._prefixes[ids] = _Prefix(
            ids=ids, kv={k: scratch[k] for k in keys},
            index=NgramIndex(ids) if self.spec_k else None,
        )
        with self.stats_lock:
            self.prefix_stats["registered"] += 1
        return True

    def stats_snapshot(self) -> dict:
        """Deep-copied spec/prefix stats under the stats lock — THE read
        API. The loop thread mutates the live dicts between chunks
        (``k_trace`` append vs list copy is the observable race), so
        readers never touch them directly."""
        with self.stats_lock:
            return {
                "spec": copy.deepcopy(self.spec_stats),
                "prefix": dict(self.prefix_stats),
            }

    def _set_gate_state(self, new: str) -> None:
        """ONE definition of a gate transition: spec_stats, the state
        gauge vector, the transition counter and the flight recorder move
        together. Takes ``stats_lock`` itself (RLock — callers already
        inside a locked section just re-enter), so the transition is
        atomic even from a caller that forgot the lock."""
        with self.stats_lock:
            old = self.spec_stats["gate_state"]
            if new == old:
                return
            self.spec_stats["gate_state"] = new
            self._gate_gauge.labels(engine=self.name, state=old).set(0.0)
            self._gate_gauge.labels(engine=self.name, state=new).set(1.0)
            self._gate_transitions.labels(
                **{"engine": self.name, "from": old, "to": new}
            ).inc()
            if self.recorder is not None:
                self.recorder.record(
                    "gate", **{
                        "from": old, "to": new,
                        "tokens_per_verify": self.spec_stats["tokens_per_verify"],
                        "break_even": self.spec_stats["break_even"],
                    }
                )

    def _match_prefix(self, prompt_ids: List[int]):
        """Longest registered prefix of ``prompt_ids`` plus the suffix-chunk
        split: returns (entry, split, suffix_width) or None. The suffix
        chunk is the power-of-two-wide tail the admission recomputes —
        ``split = len(prompt) − suffix_width`` tokens come from the slab,
        and the chunk re-derives the overlap [split, plen) with identical
        values (keeping compile count logarithmic instead of per-length)."""
        if not self._prefixes:
            return None
        best = None
        for pe in self._prefixes.values():
            pl_ = len(pe.ids)
            if best is not None and pl_ <= len(best.ids):
                continue
            if len(prompt_ids) >= pl_ and tuple(prompt_ids[:pl_]) == pe.ids:
                best = pe
        if best is None:
            return None
        # Recency for the LRU bound: a hit keeps its prefix resident.
        self._prefixes[best.ids] = self._prefixes.pop(best.ids)
        p = len(prompt_ids)
        sw = 8
        while sw < p - len(best.ids):
            sw <<= 1
        split = p - sw
        if split <= 0:
            return None  # suffix chunk covers the whole prompt: no reuse win
        return best, split, sw

    @property
    def has_capacity(self) -> bool:
        return bool(self.free)

    @property
    def active(self) -> int:
        return len(self.slots)

    def admit(
        self,
        prompt_ids: List[int],
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        on_tokens=None,
    ) -> int:
        """Prefill into a free slot; returns a request id.

        Prompts are LEFT-padded to a power-of-two bucket so admission hits
        a handful of compiled prefill programs under mixed-length traffic
        instead of retracing per distinct length; pad slots are masked by
        kv_valid and pos_offset exactly as in generate_tokens_batch."""
        if not self.free:
            raise RuntimeError("no free slot; call step() until one retires")
        if self._spec_pending:
            # Admission rewrites a slot's host mirrors, but an in-flight
            # verify chunk's successor would still read the THREADED
            # device slot_pos for that slot — process the pending chunk
            # first so host state is authoritative again.
            raise RuntimeError(
                "admit() with a speculative chunk in flight; process_spec_chunk first"
            )
        self._spec_pos_dev = None
        p = len(prompt_ids)
        if p + 1 >= self.max_len:
            raise ValueError("prompt too long for the slot window")
        bucket = self.bucket_for(p, self.max_len)
        off = bucket - p
        slot = self.free.pop()
        rid = self._next_id
        self._next_id += 1
        # Slot validity: the real prompt rows [off, bucket), growing per step.
        ar = np.arange(self.max_len)
        self._kv_np[slot] = (ar >= off) & (ar < bucket)
        self._off_np[slot] = off
        self._pos_np[slot] = bucket
        self._temp_np[slot] = temperature
        # .copy(): on the CPU backend jnp.asarray can alias the numpy
        # buffer ZERO-COPY, and these mirrors keep mutating while the
        # async program reads them — observed as flaky garbage logits.
        m = (
            self._match_prefix(list(prompt_ids))
            if os.environ.get("KAKVEDA_SERVE_PREFIX", "1") != "0"
            else None
        )
        if m is not None:
            pe, split, sw = m
            with self.stats_lock:
                self.prefix_stats["hits"] += 1
                self.prefix_stats["hit_tokens_saved"] += split
            self._mx["prefix_hit"].inc()
            self.cache, self.last = _admit_prefix_jit(
                self.params, self.cfg, self.cache, self.last,
                pe.kv, jnp.asarray([list(prompt_ids[split:])], jnp.int32),
                jnp.asarray(slot),
                jnp.asarray(self._kv_np.copy()), jnp.asarray(self._off_np.copy()),
                jnp.asarray(off + split, jnp.int32),
            )
        else:
            self._mx["prefix_miss"].inc()
            padded = [0] * off + list(prompt_ids)
            self.cache, self.last = _admit_jit(
                self.params, self.cfg, self.cache, self.last,
                jnp.asarray([padded], jnp.int32), jnp.asarray(slot),
                jnp.asarray(self._kv_np.copy()), jnp.asarray(self._off_np.copy()),
            )
        # st.index stays None until the first draft actually needs it
        # (_anchor builds it lazily): a pool whose gate is OFF — or that
        # never goes speculative — pays zero index maintenance.
        self.slots[slot] = _Slot(
            req_id=rid, prompt_len=bucket, max_new=max_new_tokens, on_tokens=on_tokens,
            prompt_ids=list(prompt_ids),
            k=self.spec_k,
        )
        self._mx["active"].set(len(self.slots))
        return rid

    def step_async(self):
        """Dispatch one decode chunk WITHOUT fetching its tokens; returns a
        handle for :meth:`process_chunk` (or None when no slot is active).

        This is the pipelining half of ``step()``: on remote-attached
        chips the per-chunk token fetch pays a fixed wire RTT that can
        exceed the chunk's compute, so an engine that dispatches chunk
        i+1 before processing chunk i's tokens overlaps that RTT with
        device work. Retirement (EOS / max_new) is then detected one
        chunk late; the overshoot chunk wastes compute but cannot corrupt
        state — cache writes clamp at the window (``mode="drop"``), each
        slot attends only within its own cache row, and the overshoot
        tokens are discarded host-side — so outputs are token-identical
        to the unpipelined path."""
        if not self.slots:
            return None
        if self._spec_pending:
            raise RuntimeError(
                "step_async() with a speculative chunk in flight; process_spec_chunk first"
            )
        self._fault_dispatch.fire()
        # A plain chunk moves the frontier through the host mirrors; any
        # previously threaded device slot_pos is stale from here on.
        self._spec_pos_dev = None
        t_dispatch = time.perf_counter()
        self._grow_valid(self.chunk_steps)

        _ledger.note_transfer(
            "h2d",
            self._pos_np.nbytes + self._kv_np.nbytes + self._off_np.nbytes
            + self._temp_np.nbytes,
        )
        self.cache, self.last, _, self.rng, toks = _step_chunk_jit(
            self.params, self.cfg, self.cache, self.last, jnp.asarray(self._pos_np.copy()),
            jnp.asarray(self._kv_np.copy()), jnp.asarray(self._off_np.copy()),
            jnp.asarray(self._temp_np.copy()), self.rng, self.chunk_steps,
        )
        self._pos_np += self.chunk_steps  # every slot advances in lockstep
        try:
            toks.copy_to_host_async()
        except Exception:  # noqa: BLE001 — backends without async copy
            pass
        # Slot refs (shared, not copied): a slot retired by an EARLIER
        # handle's processing — or by cancel_request between chunks —
        # shows st.done here and its overshoot tokens are skipped. A
        # freed slot re-admitted before this handle is processed gets a
        # NEW _Slot object (the snapshot still holds the done one), and
        # the admit scatter is ordered after the in-flight chunk by the
        # functional cache threading — so a snapshot can never alias or
        # corrupt a newer request.
        return toks, dict(self.slots), t_dispatch

    def process_chunk(self, handle) -> List[int]:
        """Fetch a dispatched chunk's tokens and retire finished slots;
        returns req_ids completed by that chunk."""
        if handle is None:
            return []
        self._fault_fetch.fire()
        toks, snapshot, t_dispatch = handle
        toks_h = np.asarray(toks)
        _ledger.note_transfer("d2h", toks_h.nbytes)
        # Gate denominator: dispatch→process is the chunk's EFFECTIVE
        # wall — under pipelining the fetch overlapped the next chunk's
        # device work, so this interval is the overlapped cost the spec
        # arm has to beat, not the synchronous one.
        wall = time.perf_counter() - t_dispatch
        self._mx["chunk_plain"].observe(wall)
        if self.spec_k and any(not st.done for st in snapshot.values()):
            self.note_plain_wall(wall)
        finished = []
        for slot, st in snapshot.items():
            if st.done:
                continue  # retired by an earlier chunk; these are overshoot tokens
            self._emit(slot, st, toks_h[slot], finished)
        return finished

    def _emit(self, slot: int, st: _Slot, tok_row, finished: List[int]) -> None:
        """Accept a chunk's tokens into a slot (EOS / budget / window stops),
        fire the streaming callback, retire when done. Shared by the plain
        chunk path and the speculative path."""
        n_before = len(st.out)
        for t in tok_row:
            t = int(t)
            if self.eos_id is not None and t == self.eos_id:
                st.done = True
                break
            st.out.append(t)
            if st.index is not None:
                st.index.append(t)  # keep the draft corpus current
            if len(st.out) >= st.max_new or st.prompt_len + len(st.out) + 1 >= self.max_len:
                st.done = True
                break
        if len(st.out) > n_before:
            self._mx["tokens"].inc(len(st.out) - n_before)
        if st.on_tokens is not None:
            # Streaming: surface this chunk's accepted tokens as they
            # land. Exceptions must not kill the engine loop — a gone
            # stream consumer just stops receiving.
            try:
                st.on_tokens(st.out[n_before:], st.done)
            except Exception:  # noqa: BLE001
                st.on_tokens = None
        if st.done:
            self.results[st.req_id] = st.out
            finished.append(st.req_id)
            del self.slots[slot]
            self.free.append(slot)
            self._kv_np[slot] = False
            self._mx["active"].set(len(self.slots))

    def _grow_valid(self, steps: int) -> None:
        """Grow read-validity on the host mirror (vectorized over slots):
        each active slot may read its next ``steps`` rows as it writes
        them (reads stay bounded per-step by ``col <= slot_pos`` inside
        the chunk program). The left-pad region [0, pos_offset) stays
        invalid. One [B, L] upload per chunk replaces per-slot device
        scatters. ONE definition for both chunk flavors — the invariant
        must not fork."""
        ar = np.arange(self.max_len)[None, :]
        active = np.zeros((self.B,), bool)
        active[list(self.slots)] = True
        limit = (self._pos_np + steps)[:, None]
        self._kv_np |= active[:, None] & (ar >= self._off_np[:, None]) & (ar < limit)

    @staticmethod
    def _draft(hist: List[int], k: int) -> List[int]:
        """Prompt-lookup draft (host side), THE reference semantics the
        per-slot incremental index implements: most recent earlier
        occurrence of the LONGEST matching history suffix (3→2→1 tokens —
        longer context anchors the copy in the right template region),
        copy what followed it SHIFTED by one — the verify chunk's first
        position is the committed token t0 (known only on device), so
        drafts guess t0's continuation. A copy region that runs off the
        end of history extrapolates PERIODICALLY (period = distance from
        anchor to tail), so constant and short-period loops — exactly the
        most repetitive traffic — draft their own continuation instead of
        degenerating to PAD. PAD (0) fills only when history gives no
        anchor at all; wrong drafts cost nothing extra (the verify
        forward runs k+1 wide either way)."""
        idx = NgramIndex(hist)
        j, _ = idx.anchor
        if j < 0:
            return [0] * k
        n = len(hist)
        d, _ = copy_run(hist, j + 2, k, n - 1 - j, n=n)
        return d + [0] * (k - len(d))

    def _anchor(self, st: _Slot):
        """Anchor selection for one slot: its live suffix index first,
        the registered-prefix corpora as a fallback source — template
        traffic (LLM-judge calls, system preambles) reproduces spans of
        the registered head whose continuation the slot's own short
        history may not contain yet, so a weak self-anchor (< 3-gram)
        defers to a deeper match inside a registered prefix. Returns
        ``(corpus, j, period)`` — period 0 for cross-corpus hits (the
        hit may be the corpus tail itself, and periodicity of someone
        else's text means nothing: copy literally, no wrap)."""
        if st.index is None:
            st.index = NgramIndex(st.prompt_ids + st.out)
        j, m = st.index.anchor
        corpus, period = st.index.toks, (len(st.index.toks) - 1 - j if j >= 0 else 0)
        if m < 3 and self._prefixes:
            tail = st.index.toks[-3:]
            for pe in self._prefixes.values():
                if pe.index is None:
                    continue
                pj, pm = pe.index.lookup(tail)
                if pm > m and pj + 2 < len(pe.index.toks):
                    j, m, corpus, period = pj, pm, pe.index.toks, 0
        return corpus, j, period

    def _draft_slot(self, st: _Slot, k: int):
        """Drafts for one slot with host-authoritative history. Returns
        ``(drafts[k], cursor, predicted_emission)`` — cursor/prediction
        feed the pipelined continuation (:meth:`step_spec_async`)."""
        corpus, j, period = self._anchor(st)
        if j < 0:
            return [0] * k, None, None
        n = len(corpus)
        seq, nxt = copy_run(corpus, j + 1, k + 1, period, n=n)
        drafts = seq[1:] + [0] * (k + 1 - len(seq))
        cursor = (corpus, nxt, period, n) if len(seq) == k + 1 else None
        return drafts, cursor, seq

    @staticmethod
    def _draft_cursor(st: _Slot, k: int):
        """Drafts for a slot whose previous verify chunk is still in
        flight AND whose prediction chain is alive: continue the SAME
        copy run past the predicted emission. The host hasn't seen the
        in-flight chunk's tokens, so anchoring on the stale suffix would
        guess a continuation of the WRONG tail; continuing the cursor
        instead bets the in-flight chunk fully accepts — exactly the
        traffic where speculation pays — and process_spec_chunk drops
        the cursor the moment a chunk doesn't."""
        corpus, idx, period, n = st.spec_cursor
        seq, nxt = copy_run(corpus, idx, k + 1, period, n=n)
        drafts = seq[1:] + [0] * (k + 1 - len(seq))
        cursor = (corpus, nxt, period, n) if len(seq) == k + 1 else None
        return drafts, cursor, seq

    def _draft_slot_stale(self, st: _Slot, k: int):
        """Drafts for a slot whose chain broke while a chunk is in
        flight: re-anchor on the HOST-known (stale) history. The broken
        chain means the in-flight chunk carries PAD/garbage drafts, so it
        will (almost always) commit exactly ONE unseen token — the
        continuation of the stale tail, i.e. the anchor's own first
        prediction. Predict k+2 ahead and skip BOTH that token (p0) and
        this chunk's own t0 (p1): drafts are p2.. — the pipeline
        re-enters the accepting regime one chunk after a miss instead of
        never. If the in-flight chunk surprises with >1 tokens the
        prediction just misses and the next dispatch re-anchors again
        (acceptance heuristics never touch parity)."""
        corpus, j, period = self._anchor(st)
        if j < 0:
            return [0] * k, None, None
        n = len(corpus)
        seq, nxt = copy_run(corpus, j + 1, k + 2, period, n=n)
        drafts = seq[2:] + [0] * (k + 2 - len(seq))
        ok = len(seq) == k + 2
        cursor = (corpus, nxt, period, n) if ok else None
        return drafts, cursor, seq[1:] if ok else None

    def _pool_k(self) -> int:
        """Verify width for the next chunk: the max of the active slots'
        adaptive k, rounded up to a power of two so the compile count
        stays logarithmic in spec_k, capped at the configured ceiling."""
        top = max(st.k for st in self.slots.values())
        k = 1
        while k < top:
            k <<= 1
        return max(1, min(k, self.spec_k))

    def step_spec_async(self):
        """Dispatch one speculative verify chunk WITHOUT fetching its
        acceptance; returns a handle for :meth:`process_spec_chunk`.

        This is what makes engine speculation compatible with the chunk
        pipelining win: the verify program RETURNS the post-acceptance
        slot_pos, which threads into the next dispatch as a device array
        — no host sync between verify chunks. The host drafts chunk i+1
        from each slot's copy CURSOR (the predicted continuation of the
        in-flight chunk), read-validity grows by the whole in-flight
        width from the last host-known position, and overshoot obeys the
        same clamp-and-discard contract as plain pipelining (writes clamp
        via mode="drop" in the slot's own cache row; stale snapshots skip
        done slots; rejected-draft rows are overwritten before any query
        can attend that far). Admissions require host-authoritative state:
        callers drain in-flight handles before admitting (admit raises
        otherwise)."""
        if not self.slots:
            return None
        self._fault_dispatch.fire()
        t_dispatch = time.perf_counter()  # drafting is part of the chunk's cost
        k = self._pool_k()
        pipelined = self._spec_pending > 0
        drafts = np.zeros((self.B, k), np.int32)
        kmap: Dict[int, int] = {}
        pmap: Dict[int, Optional[List[int]]] = {}
        for slot, st in self.slots.items():
            kd = min(max(st.k, 1), k)
            kmap[slot] = kd
            if not pipelined:
                row, cursor, pred = self._draft_slot(st, kd)
            elif st.spec_cursor is not None:
                row, cursor, pred = self._draft_cursor(st, kd)
            else:
                row, cursor, pred = self._draft_slot_stale(st, kd)
            drafts[slot, : len(row)] = row  # columns past kd stay PAD
            st.spec_cursor = cursor
            pmap[slot] = pred
        # Validity must cover every in-flight chunk's reads from the last
        # host-known position; rows past the true frontier are garbage-
        # but-valid and excluded by each query's own causal bound
        # (col <= qpos), the same argument that makes rejected-draft rows
        # safe.
        self._grow_valid(self._spec_pending_width + k + 1)
        slot_pos = (
            self._spec_pos_dev
            if self._spec_pos_dev is not None
            else jnp.asarray(self._pos_np.copy())
        )
        _ledger.note_transfer(
            "h2d",
            self._kv_np.nbytes + self._off_np.nbytes
            + getattr(drafts, "nbytes", 0),
        )
        self.cache, self.last, self._spec_pos_dev, toks, counts = _spec_chunk_jit(
            self.params, self.cfg, self.cache, self.last, slot_pos,
            jnp.asarray(self._kv_np.copy()), jnp.asarray(self._off_np.copy()),
            jnp.asarray(drafts), k,
        )
        self._spec_pending += 1
        self._spec_pending_width += k + 1
        for arr in (toks, counts):
            try:
                arr.copy_to_host_async()
            except Exception:  # noqa: BLE001 — backends without async copy
                pass
        return toks, counts, dict(self.slots), k, kmap, pmap, t_dispatch

    def process_spec_chunk(self, handle) -> List[int]:
        """Fetch a dispatched verify chunk's tokens/acceptance, emit the
        accepted prefixes, adapt each slot's draft length, and feed the
        auto-gate; returns req_ids completed by that chunk."""
        if handle is None:
            return []
        self._fault_fetch.fire()
        toks, counts, snapshot, k, kmap, pmap, t_dispatch = handle
        toks_h = np.asarray(toks)
        counts_h = np.asarray(counts).astype(np.int32)
        _ledger.note_transfer("d2h", toks_h.nbytes + counts_h.nbytes)
        self._spec_pending -= 1
        self._spec_pending_width -= k + 1
        wall = time.perf_counter() - t_dispatch
        self._mx["chunk_spec"].observe(wall)
        if k in self._spec_widths_warm:
            self._spec_walls.append(wall)
        else:
            self._spec_widths_warm.add(k)  # compile run — not a cost sample
        # Every slot's mirror advances by ITS emitted count (inactive slots
        # drift harmlessly — admission resets their position, exactly as
        # with the lockstep += chunk_steps of the plain path).
        self._pos_np += counts_h
        finished: List[int] = []
        self._gate_spec_chunks += 1
        # Per-chunk stats accumulate locally and land in spec_stats under
        # ONE lock acquire — the lock must not be held across _emit (its
        # streaming callbacks are caller code).
        em = sc = dr = ac = 0
        for slot, st in snapshot.items():
            if st.done:
                st.spec_cursor = None
                continue  # retired earlier; overshoot tokens, skip
            n = int(counts_h[slot])
            kd = kmap.get(slot, k)
            a = max(0, min(n - 1, kd))  # accepted drafts (t0 is free)
            em += n
            sc += 1
            dr += kd
            ac += a
            self._tpv_recent.append(n)
            # Per-slot adaptive k: a fully-accepted chunk DOUBLES the
            # draft width (rejected drafts ride the same weight stream,
            # so recovering fast when traffic turns repetitive is nearly
            # free); a fully-rejected one halves toward 1, so a slot
            # whose traffic stopped repeating stops paying host drafting
            # and verify width for nothing. Partial accepts hold.
            frac = a / kd if kd else 0.0
            st.accept_ema = 0.7 * st.accept_ema + 0.3 * frac
            if a >= kd:
                st.k = min(self.spec_k, max(st.k, kd) * 2)
            elif a == 0:
                st.k = max(1, st.k // 2)
            # The prediction chain survives ONLY a fully-accepted chunk
            # whose tokens match ITS OWN prediction (from the handle — a
            # newer dispatch has already moved the slot's cursor past
            # this chunk, and that continuation is garbage if this chunk
            # deviated).
            pred = pmap.get(slot)
            emitted = [int(t) for t in toks_h[slot][:n]]
            if pred is None or n != kd + 1 or emitted != pred[:n]:
                st.spec_cursor = None
            self._emit(slot, st, toks_h[slot][:n], finished)
        with self.stats_lock:
            s = self.spec_stats
            s["chunks"] += 1
            s["emitted"] += em
            s["slot_chunks"] += sc
            s["drafted"] += dr
            s["accepted"] += ac
            kt = s["k_trace"]
            kt.append(k)
            if len(kt) > 64:
                del kt[0]
        self._mx["drafted"].inc(dr)
        self._mx["accepted"].inc(ac)
        self._mx["spec_k"].set(k)
        if self.recorder is not None and k != self._last_k_rec:
            self.recorder.record("pool_k", k=k)
            self._last_k_rec = k
        self._gate_eval()
        return finished

    def step_spec(self) -> List[int]:
        """One synchronous speculative verify chunk for every active slot
        (greedy pools only — the engine falls back to plain chunks when
        any active slot samples). The engine loop pipelines instead
        (step_spec_async / process_spec_chunk one chunk apart) whenever
        :meth:`spec_pipeline_ready` says the overlap is acceptance-safe."""
        return self.process_spec_chunk(self.step_spec_async())

    def spec_pipeline_ready(self) -> bool:
        """True when dispatching the NEXT verify chunk before fetching the
        in-flight one is acceptance-safe: every active slot sits on a
        live prediction chain AND has been accepting (EMA ≥ 0.5). A
        cursor continuation bets on FULL acceptance of the un-fetched
        chunk — on traffic that accepts halfway, that bet loses most
        chunks and would trade real acceptance for overlap; the sync
        order (fetch, re-anchor, dispatch) keeps acceptance there, and
        the gate decides whether sync verify chunks pay at all."""
        return all(
            st.spec_cursor is not None and st.accept_ema >= 0.5
            for st in self.slots.values()
        )

    def note_plain_wall(self, wall: float) -> None:
        """Record one plain chunk's effective wall (chunk_steps tokens per
        slot) — the cost the auto-gate compares verify chunks against.
        process_chunk self-reports; while the gate is OFF each plain
        chunk also counts toward the re-probe window that sends the gate
        back to warmup (traffic may turn repetitive again)."""
        if self._plain_warm:
            self._plain_walls.append(wall)
        else:
            self._plain_warm = True  # compile run — not a cost sample
        with self.stats_lock:
            if self.spec_stats["gate_state"] == "off":
                self._gate_plain_since_off += 1
                if self._gate_reprobe and self._gate_plain_since_off >= self._gate_reprobe:
                    self._set_gate_state("warmup")
                    self._gate_spec_chunks = 0
                    self._gate_plain_since_off = 0
                    self._gate_reprobes += 1
                    self._tpv_recent.clear()

    def _gate_eval(self) -> None:
        """The acceptance auto-gate: speculation pays iff observed
        tokens/verify clears the measured break-even — the verify chunk's
        effective wall divided by the plain path's effective per-token
        wall (both medians of recent chunks, so one compile spike can't
        flip the gate). Below it, the pool turns speculation OFF and
        decodes plain — spec can never again be a configured slowdown; a
        re-probe window (KAKVEDA_SERVE_SPEC_REPROBE plain chunks) sends
        it back to warmup with a hysteresis margin so a borderline pool
        doesn't flap."""
        if not self.spec_k:
            return
        tpv = float(np.mean(self._tpv_recent)) if self._tpv_recent else 0.0
        if self._spec_walls and self._plain_walls:
            spec_w = float(np.median(self._spec_walls))
            plain_w = float(np.median(self._plain_walls)) / max(self.chunk_steps, 1)
            be = spec_w / max(plain_w, 1e-9)
        else:
            be = self._gate_prior  # no plain measurement yet: conservative prior
        with self.stats_lock:
            g = self.spec_stats
            g["tokens_per_verify"] = round(tpv, 3)
            g["break_even"] = round(be, 3)
            if g["gate_state"] in ("warmup", "on") and self._gate_spec_chunks >= self._gate_warmup:
                need = be * (1.1 if self._gate_reprobes else 1.0)
                if tpv < need:
                    self._set_gate_state("off")
                    self._gate_plain_since_off = 0
                else:
                    self._set_gate_state("on")

    def cancel_request(self, rid: int) -> Optional[List[int]]:
        """Retire a mid-decode request NOW (between chunks): returns its
        partial tokens, frees the slot, and marks the _Slot done so a
        stale pipelined snapshot skips it as overshoot. THE retirement
        bookkeeping for cancellation — one definition, shared with the
        normal retire tail in _emit. Returns None when the rid is not
        active (already finished or never admitted)."""
        for slot, st in list(self.slots.items()):
            if st.req_id == rid:
                st.done = True
                del self.slots[slot]
                self.free.append(slot)
                self._kv_np[slot] = False
                self._mx["active"].set(len(self.slots))
                return st.out
        return None

    def spec_ready(self) -> bool:
        """True when the next chunk should be a speculative verify chunk:
        spec enabled, the auto-gate not OFF, the gate's plain-cost
        calibration done (the first KAKVEDA_SERVE_SPEC_CALIB chunks of a
        pool run plain so break-even is measured, not assumed), and every
        active slot greedy. THE predicate for both step() and the engine
        loop (which needs it separately to drain its pipelined handle
        before switching chunk flavors). The brownout ladder's FIRST step
        (core/admission.py) vetoes speculation here — under pressure the
        verify-width FLOPs go back to plain decode; the gate's own state
        machine is untouched, so stepping back down resumes where the
        gate left off."""
        return bool(
            self.spec_k
            and self.slots
            and self.spec_stats["gate_state"] != "off"
            and len(self._plain_walls) >= self._gate_calib
            and all(self._temp_np[s] <= 0.0 for s in self.slots)
            and _admission.get_admission().brownout.spec_allowed()
        )

    def step(self) -> List[int]:
        """One decode chunk for every active slot; returns req_ids finished
        in this chunk (their token lists land in ``results``). With
        ``spec_k`` set, an all-greedy pool and the auto-gate open this IS
        a speculative verify chunk — ONE dispatch rule for
        step()/run_all/engine callers."""
        if self.spec_ready():
            return self.step_spec()
        return self.process_chunk(self.step_async())

    def run_all(self, prompts: List[List[int]], max_new_tokens: int = 64) -> List[List[int]]:
        """Drain a whole request list through the slot pool (admitting as
        slots free up); returns outputs in request order."""
        pending = list(enumerate(prompts))
        order: Dict[int, int] = {}
        while pending or self.slots:
            while pending and self.free:
                idx, p = pending.pop(0)
                order[self.admit(p, max_new_tokens)] = idx
            self.step()
        # Consume only THIS call's request ids: results from an earlier
        # run_all/admit on the same batcher must neither leak in nor crash
        # the index lookup (run_all is reusable for warmup+measure passes).
        outs: List[List[int]] = [[] for _ in prompts]
        for rid, idx in order.items():
            outs[idx] = self.results.pop(rid, [])
        return outs


class ServingEngine:
    """The ONLINE serving path: one shared ContinuousBatcher behind a
    thread-safe submit API, so every concurrent caller — playground chat,
    eval runner, LLM-judge tier — joins ONE decode batch instead of each
    running its own per-request decode stream (the reference's model: one
    sequential Ollama HTTP hop per request, services/dashboard/app.py:
    1226-1258).

    A single daemon loop thread owns the batcher (admission and decode
    chunks never race); callers block on a Future. Requests are admitted
    mid-decode as slots free up, each with its own max_tokens/temperature.
    Greedy outputs are slot-for-slot identical to a solo
    ``generate_tokens`` call (the batcher's parity invariant), so routing
    online traffic here is a throughput decision, not an accuracy one.

    ``fits()`` mirrors the batcher's admission bucketing: a request whose
    padded prompt + budget would overrun the slot window is the CALLER's
    cue to fall back to a solo decode (LlamaRuntime does exactly that) —
    inside the pool it would truncate where the solo path keeps going.
    """

    def __init__(
        self,
        params: Params,
        cfg: LlamaConfig,
        *,
        batch_slots: int = 8,
        max_len: int = 512,
        chunk_steps: int = 8,
        eos_id: Optional[int] = None,
        rng: Optional[jax.Array] = None,
        spec_k: Optional[int] = None,
        name: Optional[str] = None,
    ):
        if spec_k is None:
            spec_k = int(os.environ.get("KAKVEDA_SERVE_SPEC", "0"))
        self.name = name or "default"
        # The flight recorder: request timelines + gate/k transitions,
        # dumped via GET /flightrecorder and automatically on loop death.
        self.recorder = _metrics.FlightRecorder(f"serving/{self.name}")
        # Everything the supervisor needs to rebuild the batcher after a
        # loop death — the rebuild constructs a FRESH ContinuousBatcher
        # (cache slabs re-zeroed by init_cache) from these.
        self._params, self._cfg = params, cfg
        self._cb_kw = dict(
            batch_slots=batch_slots, max_len=max_len, chunk_steps=chunk_steps,
            eos_id=eos_id, rng=rng, spec_k=spec_k,
        )
        self.cb = ContinuousBatcher(
            params, cfg, name=self.name, recorder=self.recorder, **self._cb_kw
        )
        # Supervisor state: restart budget (read once — the supervisor must
        # not change behavior mid-life because the env moved), restarts
        # consumed, and the terminal-death latch (submit fails fast on it).
        self._restart_budget = int(os.environ.get("KAKVEDA_SERVE_RESTARTS", "2"))
        self._restarts = 0  # kakveda: owned-by[serving-loop] (supervisor writes)
        self._dead = threading.Event()
        # Prefixes successfully registered on the live batcher, in order —
        # the supervisor re-registers them on the rebuilt batcher so a
        # restart doesn't silently lose the prefix-cache hit rate.
        self._prefix_ids: List[Tuple[int, ...]] = []
        reg = _metrics.get_registry()
        el = {"engine": self.name}
        self._m_requests = reg.counter(
            "kakveda_serving_requests_total",
            "Serving requests by outcome", ("engine", "outcome"),
        )
        self._mx = {
            "queue_wait": reg.histogram(
                "kakveda_serving_queue_wait_seconds",
                "Submit-to-admission wait in the serving engine queue",
                ("engine",),
            ).labels(**el),
            "prefill": reg.histogram(
                "kakveda_serving_prefill_seconds",
                "Admission prefill dispatch wall per request", ("engine",),
            ).labels(**el),
            "ttft": reg.histogram(
                "kakveda_serving_ttft_seconds",
                "Submit-to-first-token latency per request", ("engine",),
            ).labels(**el),
            "request": reg.histogram(
                "kakveda_serving_request_seconds",
                "Submit-to-completion wall per request", ("engine",),
            ).labels(**el),
            "rate": reg.histogram(
                "kakveda_serving_tokens_per_second",
                "Per-request decode rate (tokens / request wall)",
                ("engine",), buckets=_metrics.RATE_BUCKETS,
            ).labels(**el),
            "errors": reg.counter(
                "kakveda_serving_engine_errors_total",
                "Serving-engine loop deaths (flight recorder dumped on "
                "each)", ("engine",),
            ).labels(**el),
            "restarts": reg.counter(
                "kakveda_serving_engine_restarts_total",
                "Supervisor restarts of a serving-engine loop after a "
                "crash (bounded by KAKVEDA_SERVE_RESTARTS)", ("engine",),
            ).labels(**el),
        }
        # Overload protection (core/admission.py): the submit-side backlog
        # bound. Past it, submit() SHEDS with a typed OverloadError instead
        # of growing a queue nobody will drain before callers time out —
        # the HTTP tier surfaces it as 429 + Retry-After.
        self._admit_queue = int(os.environ.get("KAKVEDA_ADMIT_QUEUE", "64"))
        # Per-tenant weighted-fair slot admission (docs/robustness.md
        # § multi-tenancy): when enabled and submits carry a tenant, a
        # freed slot goes to the waiting head of the LEAST-served tenant
        # (deficit pick, per-tenant FIFO), with a starvation bound — any
        # item passed over KAKVEDA_TENANT_PROMOTE_ROUNDS times is admitted
        # next regardless of deficit (max-wait promotion). All tenant-blind
        # or KAKVEDA_TENANT_FAIR=0 traffic degenerates to exact FIFO.
        self._tenant_fair = _admission.tenant_fair_enabled()
        self._promote_rounds = max(
            1, int(os.environ.get("KAKVEDA_TENANT_PROMOTE_ROUNDS", "8")))
        self._fair_table_max = max(
            2, int(os.environ.get("KAKVEDA_TENANT_TABLE", "512")))
        # Loop-owned under _submit_lock (picks happen inside the lock):
        # recent slot admissions per tenant — the deficit input. Bounded +
        # halved periodically so share means RECENT share.
        self._fair_served: Dict[str, int] = {}
        self._fair_picks = 0
        self._fair_promotions = 0
        # Generation items: (ids, max_new, temp, on_tokens, t_submit,
        # deadline_abs_or_None, fut); control items: ("cancel"|"prefix", …, fut).
        self._q: "queue.Queue[tuple]" = queue.Queue()
        self._closed = threading.Event()
        self._submit_lock = sanitize.named_lock("ServingEngine._submit_lock")  # closes the submit/close race
        # submit inserts pre-handoff under _submit_lock (the close race);
        # kakveda: owned-by[serving-loop] — the loop owns every later mutation.
        self._pend: Dict[int, Future] = {}  # loop-owned; close() fails leftovers
        self._waiting: List = []  # loop-owned: admitted-when-a-slot-frees queue
        # kakveda: owned-by[serving-loop] — per-request timeline state
        self._track: Dict[int, dict] = {}
        self._stats = {"submitted": 0, "completed": 0, "max_active": 0, "chunks": 0}
        self._thread = threading.Thread(target=self._loop, daemon=True, name="serving-engine")
        self._thread.start()

    def stats(self) -> dict:
        """Lock-guarded deep-copy snapshot of the engine counters plus the
        batcher's spec/prefix stats. The loop thread mutates all of these
        concurrently with readers (``k_trace`` append vs list copy), so
        THE read API is this snapshot — never the live dicts."""
        with self.cb.stats_lock:
            snap = dict(self._stats)
            snap["spec"] = copy.deepcopy(self.cb.spec_stats)
            snap["prefix"] = dict(self.cb.prefix_stats)
        snap["restarts"] = self._restarts
        snap["dead"] = self._dead.is_set()
        with self._submit_lock:
            snap["tenant_fair"] = {
                "enabled": self._tenant_fair,
                "served": dict(self._fair_served),
                "promotions": self._fair_promotions,
            }
        return snap

    def _bump(self, key: str, v: int = 1) -> None:
        with self.cb.stats_lock:
            self._stats[key] += v

    def _note_active(self) -> None:
        with self.cb.stats_lock:
            self._stats["max_active"] = max(self._stats["max_active"], self.cb.active)

    def _finish_telemetry(self, rid: int, n_tokens: int) -> Optional[dict]:
        """Close a request's timeline: observe the lifecycle histograms,
        record the flight-recorder event, and return the timeline dict
        (attached to the caller's Future so generate() can surface it in
        meta / as OTel span events)."""
        tr = self._track.pop(rid, None)
        if tr is None:
            return None
        wall = time.perf_counter() - tr["submit"]
        rate = n_tokens / wall if wall > 0 else 0.0
        tp = _trace.parse_traceparent(tr.get("traceparent") or "")
        self._mx["request"].observe(wall, exemplar=tp[0] if tp else None)
        if n_tokens:
            self._mx["rate"].observe(rate)
        self._m_requests.labels(engine=self.name, outcome="completed").inc()
        tl = {
            "request_id": rid,
            "queue_wait_ms": round((tr["admit"] - tr["submit"]) * 1000, 3),
            "prefill_ms": round(tr.get("prefill_s", 0.0) * 1000, 3),
            "ttft_ms": (
                round((tr["first"] - tr["submit"]) * 1000, 3)
                if tr["first"] is not None else None
            ),
            "wall_ms": round(wall * 1000, 3),
            "tokens": n_tokens,
            "tokens_per_s": round(rate, 2),
        }
        if self.recorder is not None:
            self.recorder.record("request", **tl)
        # Timeline -> span: recorded after the fact (the loop thread has
        # no ambient context), parented on the submitter's traceparent so
        # a /warn or /generate trace shows queue-wait/prefill/ttft inline.
        rec = _trace.get_tracer().record_completed(
            "serving.request",
            traceparent=tr.get("traceparent") or None,
            ts=time.time() - wall, dur_ms=tl["wall_ms"], outcome="ok",
            engine=self.name, queue_wait_ms=tl["queue_wait_ms"],
            prefill_ms=tl["prefill_ms"], ttft_ms=tl["ttft_ms"] or 0.0,
            tokens=n_tokens,
        )
        if rec:
            tl["trace_id"] = rec["trace_id"]
        return tl

    def fits(self, prompt_len: int, max_new_tokens: int) -> bool:
        """True when the request can run in the pool WITHOUT truncating
        where a solo decode wouldn't: the admission bucket (power-of-two
        left-pad) plus the full token budget must fit the slot window."""
        ml = self.cb.max_len
        if prompt_len + 1 >= ml:
            return False
        bucket = ContinuousBatcher.bucket_for(prompt_len, ml)
        return bucket + max_new_tokens + 1 <= ml

    def submit(
        self,
        prompt_ids: List[int],
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        on_tokens=None,
        deadline_s: Optional[float] = None,
        klass: str = "interactive",
        tenant: str = "",
    ) -> Future:
        """Enqueue a request; the Future resolves to the generated id list.

        ``on_tokens(new_ids, done)`` (optional) streams each decode chunk's
        accepted tokens as they land — called on the engine loop thread, so
        it must be non-blocking (push to a queue and return).

        ``deadline_s`` (optional) bounds submit-to-completion wall time:
        past it, the request retires at the next chunk boundary through
        the cancel_request done-flag path (safe under pipelining) and its
        Future fails with :class:`DeadlineExceededError` carrying the
        partial tokens.

        ``klass`` is the admission class (``interactive`` default,
        ``background`` for batch/eval work). Overload protection runs
        BEFORE anything enqueues: a degraded backend fails fast with
        :class:`DeviceUnavailableError`; the brownout ladder may shed the
        class outright or clamp ``max_new_tokens``; a backlog past
        ``KAKVEDA_ADMIT_QUEUE`` sheds with :class:`OverloadError`; and a
        ``deadline_s`` the live queue-wait history says cannot be met is
        rejected NOW instead of burning a slot and expiring anyway.
        ``tenant`` (optional, the app key) enters the request into the
        weighted-fair slot scheduler and stamps shed provenance; empty
        keeps the request tenant-blind (exact seed behavior).

        Neither error is a RuntimeError — shed work must surface as 429,
        never silently take the solo-decode fallback path."""
        _admission.get_device_health().check()
        adm = _admission.get_admission()
        if adm.enabled:
            if adm.brownout.class_shed(klass):
                self._m_requests.labels(engine=self.name, outcome="shed").inc()
                adm.shed(klass, "brownout", tenant=tenant)
            with self._submit_lock:
                backlog = self._q.qsize() + len(self._waiting)
            if backlog >= self._admit_queue:
                self._m_requests.labels(engine=self.name, outcome="shed").inc()
                adm.shed(
                    klass, "queue_full",
                    detail=f"engine backlog {backlog} >= {self._admit_queue}",
                    tenant=tenant,
                )
            if deadline_s is not None and backlog > 0:
                # Deadline-aware shed: only with a LIVE backlog — an empty
                # queue means the wait history describes some past storm,
                # not this request's fate.
                predicted = adm.predicted_wait(klass)
                if predicted > deadline_s:
                    self._m_requests.labels(engine=self.name, outcome="shed").inc()
                    adm.shed(
                        klass, "deadline",
                        detail=f"predicted queue wait {predicted:.2f}s exceeds "
                               f"deadline {deadline_s:.2f}s",
                        tenant=tenant,
                    )
            cap = adm.brownout.token_cap()
            if cap is not None:
                max_new_tokens = min(max_new_tokens, cap)
        with self._submit_lock:
            # Atomic with close()'s drain: without the lock a put landing
            # between close()'s _closed.set() and its queue drain would
            # enqueue into a dead loop and hang its caller forever.
            if self._dead.is_set():
                raise EngineDeadError(
                    f"ServingEngine {self.name!r} is dead (restart budget "
                    f"exhausted after {self._restarts} restart(s))"
                )
            if self._closed.is_set():
                raise RuntimeError("ServingEngine is closed")
            t0 = time.perf_counter()
            deadline = t0 + deadline_s if deadline_s is not None else None
            fut: Future = Future()
            # Trace context is captured HERE (the caller's contextvar) and
            # rides the Future — the loop thread has no ambient context, so
            # the serialized traceparent is the only bridge to the
            # serving.request span recorded at _finish_telemetry.
            fut.traceparent = _trace.current_traceparent()
            # Tenant identity + fairness counters ride the Future too (the
            # traceparent precedent): the 7-field waiting-item layout and
            # every item[5]/item[-1] access stay untouched.
            fut.tenant = tenant
            fut.fair_rounds = 0
            self._q.put(
                (list(prompt_ids), max_new_tokens, temperature, on_tokens,
                 t0, deadline, fut)
            )
            self._bump("submitted")
            return fut

    def generate_ids(
        self, prompt_ids: List[int], max_new_tokens: int = 64, temperature: float = 0.0
    ) -> List[int]:
        """Blocking submit — what runtime.generate calls from its executor
        thread while the loop thread decodes for everyone at once."""
        return self.submit(prompt_ids, max_new_tokens, temperature).result()

    def cancel(self, fut: Future) -> None:
        """Best-effort cancel of a submitted request: if still queued, the
        Future cancels; if mid-decode, the loop retires its slot at the
        next chunk boundary (the slot frees for other traffic instead of
        decoding a result nobody will read — the disconnect case). The
        Future resolves with the tokens generated so far."""
        if fut.cancel():
            return  # never admitted; set_running_or_notify_cancel skips it
        with self._submit_lock:
            if self._closed.is_set():
                return
            self._q.put(("cancel", fut, fut))

    def register_prefix(self, prefix_ids: List[int], timeout: float = 120.0) -> bool:
        """Precompute a shared prompt prefix's K/V once; later submits whose
        prompts start with it prefill only their suffix. Runs on the loop
        thread (the batcher is loop-owned; a registration prefill must not
        race a decode chunk's donated cache). Blocking; returns whether the
        prefix was accepted (see ContinuousBatcher.register_prefix)."""
        with self._submit_lock:
            if self._dead.is_set():
                raise EngineDeadError(
                    f"ServingEngine {self.name!r} is dead (restart budget "
                    f"exhausted after {self._restarts} restart(s))"
                )
            if self._closed.is_set():
                raise RuntimeError("ServingEngine is closed")
            fut: Future = Future()
            self._q.put(("prefix", list(prefix_ids), fut))
        return bool(fut.result(timeout=timeout))

    @staticmethod
    def _fail(fut: Future, err: BaseException) -> None:
        """set_exception tolerant of losing the race against the loop's
        set_result (close() can outlive its 5 s join while a chunk compile
        finishes): whichever side lands second is a no-op, never an
        InvalidStateError escaping into restore()/eviction."""
        try:
            if not fut.done():
                fut.set_exception(err)
        except Exception:  # noqa: BLE001 — InvalidStateError: already resolved
            pass

    def _fail_all(self, err: BaseException) -> None:
        """Fail everything queued, waiting-for-a-slot, or mid-decode —
        shared by close() and the loop's own exit/death paths. The submit
        lock guards the _waiting handoff (the loop mutates it under the
        same lock), so close() racing a loop thread that outlives its
        join can't corrupt the list or strand an item both sides miss:
        whichever side runs LAST sees the leftovers, and _fail tolerates
        double resolution."""
        with self._submit_lock:
            while True:
                try:
                    *_rest, fut = self._q.get_nowait()
                except queue.Empty:
                    break
                self._fail(fut, err)
            for item in self._waiting:
                self._fail(item[-1], err)
            self._waiting.clear()
            for fut in list(self._pend.values()):
                self._fail(fut, err)
            self._pend.clear()
            self._track.clear()

    def _fail_inflight(self, err: BaseException) -> None:
        """Fail ONLY requests already admitted into the (now dead) batcher —
        their slot state is unrecoverable. Queued/waiting items are left in
        place: the supervisor's rebuilt loop re-admits them."""
        with self._submit_lock:
            for fut in list(self._pend.values()):
                self._fail(fut, err)
            self._pend.clear()
            self._track.clear()

    def _pick_waiting_locked(self):
        """Pop the next waiting generation item for a freed slot. Caller
        holds ``_submit_lock`` and guarantees ``_waiting`` is non-empty.

        Tenant-fair path (KAKVEDA_TENANT_FAIR=1, docs/robustness.md
        § multi-tenancy):

        1. Max-wait promotion — the earliest-queued item passed over
           ``_promote_rounds`` times is taken regardless of deficit. This
           is the starvation BOUND: every pick increments the skip count
           of every item left behind, so any waiting item is admitted
           within K scheduling rounds of reaching the front of its
           tenant's subqueue, flood or no flood.
        2. Deficit pick — among each tenant's FIFO head, take the tenant
           with the fewest recent slot admissions. A light tenant beats a
           flooder for every freed slot; per-tenant order stays FIFO.

        Tenant-blind traffic (all tenants ``""``) reduces to index 0 both
        ways — exact FIFO — and ``KAKVEDA_TENANT_FAIR=0`` short-circuits
        to ``pop(0)`` before any of this runs (bit-for-bit seed)."""
        if not self._tenant_fair or len(self._waiting) <= 1:
            return self._waiting.pop(0)
        pick = None
        for i, item in enumerate(self._waiting):
            if getattr(item[-1], "fair_rounds", 0) >= self._promote_rounds:
                pick = i
                self._fair_promotions += 1
                _admission.note_tenant_promotion("serving")
                break
        if pick is None:
            seen = set()
            best = None
            pick = 0
            for i, item in enumerate(self._waiting):
                t = getattr(item[-1], "tenant", "")
                if t in seen:
                    continue  # only each tenant's FIFO head competes
                seen.add(t)
                s = self._fair_served.get(t, 0)
                if best is None or s < best:
                    best, pick = s, i
        item = self._waiting.pop(pick)
        t = getattr(item[-1], "tenant", "")
        if t not in self._fair_served and len(self._fair_served) >= self._fair_table_max:
            # Bounded table: drop the heaviest-served key — it re-enters
            # at zero (brief priority boost, the safe failure direction).
            del self._fair_served[max(self._fair_served,
                                      key=self._fair_served.get)]
        self._fair_served[t] = self._fair_served.get(t, 0) + 1
        self._fair_picks += 1
        if self._fair_picks % 1024 == 0:
            # Decay: fair share means RECENT share, and zeros drop.
            self._fair_served = {
                k: v // 2 for k, v in self._fair_served.items() if v // 2 > 0
            }
        for other in self._waiting:
            fut = other[-1]
            fut.fair_rounds = getattr(fut, "fair_rounds", 0) + 1
        return item

    def _rebuild(self) -> None:
        """Rebuild the batcher after a loop death: a FRESH ContinuousBatcher
        (cache slabs re-zeroed by init_cache; gate/k/pipeline/adaptive state
        back to construction defaults — the constructor publishes the full
        gate-gauge vector, the same single-definition family
        ``_set_gate_state`` moves), then re-register every previously
        accepted prefix so a restart doesn't silently lose the prefix-cache
        hit rate. Supervisor-thread only."""
        self.cb = ContinuousBatcher(
            self._params, self._cfg, name=self.name, recorder=self.recorder,
            **self._cb_kw,
        )
        # Fairness state is RE-DERIVED from the surviving queue, never
        # trusted from the crashed loop: served deficits reset and every
        # waiting item's skip count restarts, so the rebuilt scheduler
        # starts from what is actually still queued (ISSUE contract — a
        # crash must not let stale counters starve or favor anyone).
        with self._submit_lock:
            self._fair_served.clear()
            self._fair_picks = 0
            for item in self._waiting:
                item[-1].fair_rounds = 0
        for ids in list(self._prefix_ids):
            try:
                self.cb.register_prefix(list(ids))
            # Prefix reuse is an optimization: a rebuild must come up even
            # if a registration prefill fails (compile error on the fresh
            # batcher, OOM, …). The batcher's register_prefix raises no
            # typed admission errors, so nothing shed-shaped is swallowed.
            except Exception as e:  # noqa: BLE001  # kakveda: allow[typed-errors]
                log.warning(
                    "prefix re-registration failed after engine restart: %s", e
                )

    def _finish_rids(self, rids: List[int]) -> None:
        """Resolve completed requests' Futures (telemetry rides along) —
        THE completion path, shared by the serve loop and deadline sweep."""
        for rid in rids:
            self._bump("completed")
            fut = self._pend.pop(rid, None)
            toks = self.cb.results.pop(rid, [])
            tl = self._finish_telemetry(rid, len(toks))
            if fut is not None:
                if tl is not None:
                    fut.timeline = tl  # read back by LlamaRuntime.generate
                if not fut.done():
                    try:
                        fut.set_result(toks)
                    except Exception:  # noqa: BLE001 — close() won the race
                        pass

    def _expire_item(self, fut: Future, tokens: List[int], where: str) -> None:
        """Fail one request's Future with the typed deadline error (outcome
        counter + flight-recorder event ride along). Loop-thread only."""
        self._m_requests.labels(engine=self.name, outcome="deadline").inc()
        if self.recorder is not None:
            self.recorder.record("deadline", tokens=len(tokens), where=where)
        self._fail(
            fut,
            DeadlineExceededError(
                f"deadline exceeded {where} ({len(tokens)} tokens decoded)",
                tokens,
            ),
        )

    def _expire_deadlines(self) -> None:
        """Retire every request whose deadline passed. Admitted requests go
        through ``ContinuousBatcher.cancel_request`` — the done-flag-first
        retirement path, so a stale pipelined (plain OR verify) snapshot
        skips the freed slot as overshoot; requests still waiting for a
        slot fail without occupying one. Loop-thread only."""
        now = time.perf_counter()
        for rid, tr in list(self._track.items()):
            dl = tr.get("deadline")
            if dl is None or now < dl:
                continue
            toks = self.cb.cancel_request(rid)
            if toks is None:
                if rid in self.cb.results:
                    # Finished between chunks before the sweep saw it:
                    # deliver the completed result, not a deadline error.
                    self._finish_rids([rid])
                continue
            fut = self._pend.pop(rid, None)
            self._track.pop(rid, None)
            if fut is not None:
                self._expire_item(fut, toks, "mid-decode")
        with self._submit_lock:
            still = []
            for item in self._waiting:
                dl = item[5]
                if dl is not None and now >= dl:
                    self._expire_item(item[-1], [], "while queued")
                else:
                    still.append(item)
            self._waiting[:] = still

    def close(self) -> None:
        with self._submit_lock:
            self._closed.set()
        self._thread.join(timeout=5.0)
        # Callers must not hang on a dead loop. Idempotent with the
        # loop's own exit cleanup — this call covers a loop thread stuck
        # past the join inside a long chunk compile; the loop's finally
        # covers items it moved after this drain.
        self._fail_all(RuntimeError("ServingEngine closed"))

    def _admit_one(self, item) -> None:
        if item[0] == "cancel":
            _, fut, _ = item
            rid = next((r for r, f in self._pend.items() if f is fut), None)
            if rid is None:
                return  # already finished (or was never admitted)
            toks = self.cb.cancel_request(rid)
            self._pend.pop(rid, None)
            self._track.pop(rid, None)
            self._m_requests.labels(engine=self.name, outcome="cancelled").inc()
            if self.recorder is not None:
                self.recorder.record("cancel", request_id=rid, tokens=len(toks or []))
            if toks is None:
                toks = self.cb.results.pop(rid, [])  # finished between chunks
            if not fut.done():
                try:
                    fut.set_result(toks)
                except Exception:  # noqa: BLE001 — lost the race with completion
                    pass
            return
        if item[0] == "prefix":
            _, ids, fut = item
            if not fut.set_running_or_notify_cancel():
                return
            try:
                ok = self.cb.register_prefix(ids)
                if ok:
                    # Remember accepted prefixes so a supervisor rebuild
                    # re-registers them on the fresh batcher.
                    key = tuple(int(t) for t in ids)
                    if key not in self._prefix_ids:
                        self._prefix_ids.append(key)
                fut.set_result(ok)
            except Exception as e:  # noqa: BLE001 — registration errors belong to the caller
                self._fail(fut, e)
            return
        ids, max_new, temp, on_tokens, t_submit, deadline, fut = item
        if deadline is not None and time.perf_counter() >= deadline:
            self._expire_item(fut, [], "expired before admission")
            return
        if not fut.set_running_or_notify_cancel():
            return
        t_admit = time.perf_counter()
        self._mx["queue_wait"].observe(t_admit - t_submit)
        # Feed the admission controller's live queue-wait history — the
        # input deadline-aware shedding reads (submit rejects a deadline
        # the observed waits say cannot be met).
        _admission.get_admission().note_wait("interactive", t_admit - t_submit)
        # Lifecycle tracking rides the slot's own streaming callback: the
        # wrapper sees each chunk's accepted tokens on the loop thread
        # (TTFT + token counts with no extra bookkeeping in the batcher),
        # then forwards to the caller's callback if any.
        track = {
            "submit": t_submit, "admit": t_admit, "first": None, "tokens": 0,
            "deadline": deadline,
            "traceparent": getattr(fut, "traceparent", None),
        }
        mx_ttft = self._mx["ttft"]

        def _on_tokens(new, done, _orig=on_tokens, _tr=track):
            if _tr["first"] is None and new:
                _tr["first"] = time.perf_counter()
                mx_ttft.observe(_tr["first"] - _tr["submit"])
            _tr["tokens"] += len(new)
            if _orig is not None:
                _orig(new, done)

        try:
            rid = self.cb.admit(
                ids, max_new_tokens=max_new, temperature=temp, on_tokens=_on_tokens
            )
        except Exception as e:  # noqa: BLE001 — admission errors belong to the caller
            self._m_requests.labels(engine=self.name, outcome="rejected").inc()
            self._fail(fut, e)
            return
        track["prefill_s"] = time.perf_counter() - t_admit
        self._mx["prefill"].observe(track["prefill_s"])
        self._track[rid] = track
        self._pend[rid] = fut

    def _loop(self) -> None:
        """Supervise the serve loop: on a crash, fail the in-flight futures
        with a typed RETRYABLE error, rebuild the batcher (cache slabs
        re-zeroed, prefixes re-registered, gate/k state reset), and restart
        under a bounded exponential-backoff budget (KAKVEDA_SERVE_RESTARTS).
        Past the budget the engine is terminally dead: everything pending
        fails with EngineDeadError and submit() fails fast from then on.
        Queued / waiting-for-a-slot requests survive a restart — the rebuilt
        loop re-admits them."""
        backoff = 0.1
        while True:
            try:
                # Ledger attribution: compiles/uploads from the loop thread
                # land on the serve entry / decode phase (module-level jits
                # self-label with their fn names when created post-install).
                with _ledger.entry("serve.loop"), _ledger.phase("decode"):
                    self._serve()
                break  # clean close() exit
            except BaseException as e:  # noqa: BLE001 — a dead loop must not strand callers
                # A device/runtime error escaping a chunk would otherwise
                # kill this thread silently: every pending Future would
                # hang forever. The flight recorder dumps here — the "why"
                # of a stochastic 500 is one log line / one /flightrecorder
                # fetch, not log archaeology.
                self._mx["errors"].inc()
                # Real backend-error detection: a loop death whose cause
                # looks like the chip going away (vs a software bug or an
                # injected engine.* fault) latches device-loss DEGRADED
                # mode — generation fails fast from then on and the probe
                # owns recovery (core/admission.py).
                _admission.get_device_health().note_failure(e, where="engine.loop")
                if self.recorder is not None:
                    self.recorder.record(
                        "engine_error", error=f"{type(e).__name__}: {e}"
                    )
                    try:
                        log.error(
                            "serving engine %s loop died (%s: %s); flight recorder dump: %s",
                            self.name, type(e).__name__, e, self.recorder.dump_json(),
                        )
                    except Exception:  # noqa: BLE001 — telemetry must not mask the death
                        pass
                if self._closed.is_set():
                    # Crash racing close(): plain shutdown semantics.
                    self._fail_all(RuntimeError(
                        f"ServingEngine closed (loop died during shutdown: {e})"
                    ))
                    return
                if self._restarts >= self._restart_budget:
                    self._die(e)
                    return
                self._restarts += 1
                self._mx["restarts"].inc()
                self._fail_inflight(EngineRetryableError(
                    f"ServingEngine loop died mid-decode "
                    f"({type(e).__name__}: {e}); restarting — safe to resubmit"
                ))
                try:
                    self._rebuild()
                except BaseException as rebuild_err:  # noqa: BLE001
                    log.error(
                        "serving engine %s rebuild failed: %s", self.name, rebuild_err
                    )
                    self._die(rebuild_err)
                    return
                if self.recorder is not None:
                    self.recorder.record(
                        "engine_restart", attempt=self._restarts,
                        budget=self._restart_budget, backoff_s=round(backoff, 3),
                    )
                log.warning(
                    "serving engine %s restarted (%d/%d) after %s: %s; "
                    "re-admitting queued requests",
                    self.name, self._restarts, self._restart_budget,
                    type(e).__name__, e,
                )
                if self._closed.wait(backoff):
                    break  # closed during backoff: fall through to the drain
                backoff = min(backoff * 2.0, 5.0)
        # Normal shutdown: anything still queued/waiting/mid-decode at this
        # point — including items this thread moved AFTER close()'s own
        # drain — must fail rather than hang its caller.
        self._fail_all(RuntimeError("ServingEngine closed"))

    def _die(self, cause: BaseException) -> None:
        """Terminal death: latch ``_dead`` (submit/register_prefix fail
        fast with EngineDeadError) and fail everything pending."""
        with self._submit_lock:
            self._dead.set()
            self._closed.set()
        self._fail_all(EngineDeadError(
            f"ServingEngine loop died terminally after {self._restarts} "
            f"restart(s): {type(cause).__name__}: {cause}"
        ))

    def _serve(self) -> None:
        # Chunk pipelining (KAKVEDA_SERVE_PIPELINE=0 opts out): dispatch
        # chunk i+1 BEFORE fetching chunk i's tokens, so the fixed
        # device→host RTT of each token fetch (~70-90 ms on tunneled TPUs,
        # often > the chunk's compute) overlaps the next chunk's device
        # work — per-chunk cost drops from compute+RTT to max(compute,
        # RTT). Outputs are token-identical (see step_async); the cost is
        # retirement lag: a finished slot frees one chunk later, and one
        # overshoot chunk runs at the end of each busy period.
        #
        # Speculative verify chunks pipeline the SAME way since the chunk
        # program threads its post-acceptance slot_pos on device
        # (step_spec_async): chunk i's host draft/accept work overlaps
        # chunk i+1's device time, drafting from each slot's copy cursor.
        # The one ordering rule is that admission needs host-authoritative
        # slot state, so the in-flight verify handle drains before the
        # pump may admit.
        pipelined = os.environ.get("KAKVEDA_SERVE_PIPELINE", "1") != "0"
        pending_handle = None  # plain chunk in flight
        pending_spec = None  # speculative verify chunk in flight

        def pump_queue(block: bool) -> None:
            # Control items (cancel, prefix registration) act immediately —
            # a cancel matters MOST when the pool is full, so they must
            # not wait behind the capacity gate. Generation requests wait
            # in _waiting until a slot frees. _waiting handoff happens
            # under the submit lock (close() drains the same list from
            # its thread); admission itself runs unlocked — it can hide a
            # prefill compile and must not block submitters that long.
            nonlocal pending_spec
            try:
                while True:
                    item = self._q.get(timeout=0.1) if block else self._q.get_nowait()
                    block = False
                    if item[0] in ("cancel", "prefix"):
                        self._admit_one(item)
                    else:
                        with self._submit_lock:
                            self._waiting.append(item)
            except queue.Empty:
                pass
            while self.cb.has_capacity:
                with self._submit_lock:
                    if not self._waiting:
                        break
                    item = self._pick_waiting_locked()
                if pending_spec is not None:
                    drain_spec()
                self._admit_one(item)

        def drain_spec() -> None:
            nonlocal pending_spec
            finish(self.cb.process_spec_chunk(pending_spec))
            pending_spec = None

        finish = self._finish_rids

        while not self._closed.is_set():
            # Idle: block briefly for the next arrival (bounded so
            # close() is prompt) instead of spinning on an empty pool.
            pump_queue(
                block=not self.cb.slots
                and pending_handle is None
                and pending_spec is None
                and not self._waiting
            )
            # Deadline sweep between chunks: expired requests retire via
            # the cancel_request done-flag path (safe while a pipelined
            # plain or verify handle is still in flight).
            self._expire_deadlines()
            if self.cb.spec_ready():
                # Flavor switch plain→spec: drain the plain handle so
                # the verify dispatch sees authoritative positions.
                finish(self.cb.process_chunk(pending_handle))
                pending_handle = None
                if self.cb.slots:
                    self._note_active()
                    if (
                        pipelined
                        and pending_spec is not None
                        and self.cb.spec_pipeline_ready()
                    ):
                        # Full-accept regime: dispatch verify chunk
                        # i+1 (cursor drafts), THEN fetch chunk i —
                        # the draft/accept host work and the fetch
                        # RTT ride under the device's verify time.
                        nxt = self.cb.step_spec_async()
                        drain_spec()
                        pending_spec = nxt
                        self._bump("chunks")
                    else:
                        # Acceptance-preserving sync order: fetch and
                        # re-anchor on real history before drafting.
                        if pending_spec is not None:
                            drain_spec()
                        if self.cb.slots and self.cb.spec_ready():
                            h = self.cb.step_spec_async()
                            if pipelined:
                                pending_spec = h
                            else:
                                finish(self.cb.process_spec_chunk(h))
                            self._bump("chunks")
                elif pending_spec is not None:
                    drain_spec()
            elif self.cb.slots:
                # Flavor switch spec→plain (gate closed, or a sampled
                # request joined): drain the verify handle first.
                if pending_spec is not None:
                    drain_spec()
                if not self.cb.slots:
                    continue  # the drain retired the whole pool
                self._note_active()
                handle = self.cb.step_async()
                self._bump("chunks")
                if not pipelined:
                    finish(self.cb.process_chunk(handle))
                else:
                    finish(self.cb.process_chunk(pending_handle))
                    pending_handle = handle
            else:
                finish(self.cb.process_chunk(pending_handle))
                pending_handle = None
                if pending_spec is not None:
                    drain_spec()
