"""Draft-free speculative decoding (prompt lookup), fused on device.

The reference serves tokens one Ollama HTTP call at a time
(reference: services/dashboard/app.py:1182-1258); this module is a serving
lever it has no equivalent for. Greedy decode emits one token per weight
stream — and at 1B+ scale decode is HBM-bandwidth-bound: every step reads
every dense weight. Speculative decoding amortizes that stream: guess the
next ``k`` tokens, verify all of them in ONE cached forward (k+1 query
positions), keep the longest correct prefix. Each round emits between 1
and k+1 tokens for one weight stream; by greedy-parity construction the
output is IDENTICAL to plain greedy decode, rounds only change how many
tokens each weight stream yields.

No draft model: drafts come from **prompt lookup** (n-gram continuation —
the same family as vLLM's prompt-lookup decoding and "lookahead" schemes).
The failure-intelligence workload is exactly where this shines: LLM-judge
prompts over near-duplicate traces, citation-style completions, and
boilerplate-heavy scenario text repeat their own n-grams constantly.

TPU-first design decisions:

  * **The entire loop is one compiled program** — a ``lax.while_loop``
    whose body does draft lookup, the (k+1)-position verify forward, and
    the accept/advance bookkeeping on device. On a remote-attached chip a
    host-side speculation loop would pay the ~70-90 ms dispatch RTT per
    round, erasing the win; here the host pays ONE dispatch per
    generation, same as ``generate_tokens_fused``.
  * **Lookup is a vectorized bigram match** over the token buffer (no
    hashes, no host dict): the most recent slot j with
    ``buf[j-1] == prev and buf[j] == cur`` proposes ``buf[j+1 : j+1+k]``.
  * **Static shapes throughout**: the verify chunk is always [1, k+1];
    acceptance only moves the ``valid_len`` carry. Rejected draft K/V
    slots are never masked — the next round's chunk overwrites them
    before any query can attend that far (q_pos ≥ slot masking).

Scope: single-sequence greedy (the playground / judge path). Batched
serving keeps using ``generate_tokens_fused`` / ``ContinuousBatcher``
(per-row accept rates diverge, which would stall the batch to its worst
row). Parity + speedup characteristics: tests/test_speculative.py and
``KAKVEDA_BENCH_METRIC=spec``.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kakveda_tpu.models.llama import (
    LlamaConfig,
    Params,
    decode_step,
    init_cache,
    mask_pad_vocab,
)


# ---------------------------------------------------------------------------
# Host-side drafting (the continuous-batching engine's side of speculation).
#
# The fused single-sequence loop below drafts ON DEVICE (vectorized bigram
# match — one dispatch per generation amortizes everything). The serving
# engine can't fuse its loop that way: admissions, retirements and
# cancellations interleave with chunks on the host, so its drafts are host
# lookups between dispatches — which puts them on the per-chunk latency
# path. These utilities keep that path O(k): an incremental n-gram suffix
# index (append O(1) via three dicts) replaces the O(history) reverse scan
# per slot per chunk, and a copy cursor lets a pipelined engine extend an
# in-flight chunk's predicted emission without having seen it.
# ---------------------------------------------------------------------------


def copy_run(
    toks: List[int], start: int, count: int, period: int, n: Optional[int] = None
) -> Tuple[List[int], int]:
    """Copy ``count`` tokens from ``toks`` starting at ``start``, wrapping
    cyclically with ``period`` past index ``n`` (default: len at call time).

    The wrap implements periodic extrapolation: a suffix that matches at
    anchor j hypothesizes ``hist[t] == hist[t - p]`` with ``p = n-1-j``, so
    a copy region that runs off the end of history re-enters one period
    back instead of going empty — this is what keeps constant and
    short-period loops drafting (the period-1 degeneracy fix: a trailing
    same-token run anchors at j = n-2 with an empty literal tail, but
    p = 1 tiles the run forward). ``period <= 0`` (cross-corpus copies,
    where periodicity of someone else's text means nothing) stops at the
    end instead. ``n`` freezes the wrap boundary so a cursor stays
    deterministic while the underlying history list grows.

    Returns ``(tokens, next_index)`` — tokens may be shorter than
    ``count`` only when period <= 0; next_index is the continuation
    cursor in the same (possibly wrapped-logical) coordinate.
    """
    n = len(toks) if n is None else n
    out: List[int] = []
    idx = start
    for _ in range(count):
        while idx >= n:
            if period <= 0:
                return out, idx
            idx -= period
        out.append(toks[idx])
        idx += 1
    return out, idx


class NgramIndex:
    """Incremental suffix index over a token stream for prompt-lookup
    drafting: three dicts map every 1/2/3-gram to its most recent end
    position. ``append`` is O(1); the ``anchor`` property — the most
    recent EARLIER occurrence of the longest suffix (3→2→1) ending at the
    stream tail — is maintained as tokens arrive, so drafting never
    rescans history. ``lookup`` answers the same question for a foreign
    tail (cross-corpus drafting from a registered prefix slab)."""

    __slots__ = ("toks", "_maps", "anchor")

    def __init__(self, toks=()):
        self.toks: List[int] = []
        self._maps: Tuple[dict, dict, dict] = ({}, {}, {})
        self.anchor: Tuple[int, int] = (-1, 0)  # (end pos, match len)
        for t in toks:
            self.append(t)

    def append(self, t: int) -> None:
        toks = self.toks
        toks.append(int(t))
        i = len(toks) - 1
        # Anchor BEFORE indexing position i: the maps still hold only
        # earlier occurrences, so the longest-suffix hit can never be the
        # suffix matching itself.
        self.anchor = (-1, 0)
        for m in (3, 2, 1):
            if i + 1 >= m:
                j = self._maps[m - 1].get(tuple(toks[i - m + 1 : i + 1]), -1)
                if j >= 0:
                    self.anchor = (j, m)
                    break
        for m in (1, 2, 3):
            if i + 1 >= m:
                self._maps[m - 1][tuple(toks[i - m + 1 : i + 1])] = i

    def lookup(self, tail: List[int]) -> Tuple[int, int]:
        """(end pos, match len) of the most recent occurrence in THIS
        corpus of the longest suffix of ``tail`` (3→2→1); (-1, 0) on miss.
        Unlike ``anchor`` the hit may be the corpus's own tail — callers
        copying a continuation must check the copy region is non-empty."""
        for m in (3, 2, 1):
            if len(tail) >= m:
                j = self._maps[m - 1].get(tuple(tail[-m:]), -1)
                if j >= 0:
                    return j, m
        return -1, 0


@partial(jax.jit, static_argnames=("cfg", "k", "max_new"))
def _spec_decode_jit(
    params: Params,
    cfg: LlamaConfig,
    buf: jax.Array,  # [1, ml] i32 — prompt in [0, plen), zeros beyond
    cache: Params,
    last: jax.Array,  # [1, V] logits at position plen-1 (post-prefill)
    plen: jax.Array,  # scalar i32
    k: int,
    max_new: int,
):
    """Speculative greedy decode: returns (buf, n_decided) where
    ``buf[0, plen : n_decided]`` are the generated tokens (≥ max_new of
    them decided; caller truncates)."""
    ml = buf.shape[1]

    def cond(carry):
        _, _, _, vl, _ = carry
        return vl < plen + max_new

    def body(carry):
        buf, cache, last, vl, rounds = carry
        t0 = jnp.argmax(mask_pad_vocab(last, cfg), axis=-1)[0]  # token for slot vl
        buf = jax.lax.dynamic_update_index_in_dim(buf, t0[None], vl, axis=1)

        # Bigram prompt lookup over decided slots [1, vl]: most recent j
        # with buf[j-1] == buf[vl-1] and buf[j] == t0 proposes the k slots
        # that followed it. j == 0 (no match) proposes garbage — harmless,
        # verification rejects it.
        prev = buf[0, jnp.clip(vl - 1, 0, ml - 1)]
        sl = jnp.arange(ml)
        hit = (
            (jnp.roll(buf[0], 1) == prev)
            & (buf[0] == t0)
            & (sl >= 1)
            & (sl <= vl - 1)  # strictly before the slot being drafted
        )
        j = jnp.max(jnp.where(hit, sl, 0))
        draft = jax.lax.dynamic_slice(buf, (0, jnp.clip(j + 1, 0, ml - k - 1)), (1, k))

        # Verify chunk [t0, d1..dk] in one cached forward at pos = vl.
        chunk = jnp.concatenate([t0[None][None], draft], axis=1)  # [1, k+1]
        cache = dict(cache, pos=vl)
        logits, cache = decode_step(params, cfg, chunk, cache)
        preds = jnp.argmax(mask_pad_vocab(logits.reshape(k + 1, -1), cfg), axis=-1)  # [k+1]

        # Longest accepted draft prefix: d_{i+1} must equal the model's
        # greedy continuation p_i given everything before it.
        match = draft[0] == preds[:k]
        a = jnp.sum(jnp.cumprod(match.astype(jnp.int32)))

        # Write the accepted drafts d1..da into slots vl+1..vl+a (the draft
        # may have come from anywhere in the buffer; the decided region
        # must hold it explicitly). The write window never clips: the loop
        # stops at vl = plen+max_new-1 and ml ≥ plen+max_new+k+2.
        keep = (sl > vl) & (sl <= vl + a)
        upd = jnp.zeros((ml,), buf.dtype)
        upd = jax.lax.dynamic_update_slice(upd, draft[0], (vl + 1,))
        buf = jnp.where(keep[None, :], upd[None, :], buf)

        # Next round's logits: the model's output after the accepted
        # prefix — its argmax is the bonus/correction token.
        last = jax.lax.dynamic_index_in_dim(logits.reshape(k + 1, -1), a, 0, keepdims=False)[None]
        return (buf, cache, last, vl + a + 1, rounds + 1)

    buf, _, _, vl, rounds = jax.lax.while_loop(
        cond, body, (buf, cache, last, plen, jnp.asarray(0))
    )
    return buf, vl, rounds


def generate_tokens_speculative(
    params: Params,
    cfg: LlamaConfig,
    prompt_ids: list[int],
    *,
    max_new_tokens: int = 64,
    k: int = 4,
    eos_id: Optional[int] = None,
    max_len: Optional[int] = None,
    return_stats: bool = False,
):
    """Greedy decode with on-device prompt-lookup speculation; output is
    token-identical to ``generate_tokens(temperature=0)`` (when the cache
    window truncates the generation, the speculative window reserves k+1
    extra verify slots, so it may emit up to k+1 fewer trailing tokens —
    the emitted prefix is always identical). ``k`` is the draft length per
    round (each round = one weight stream, emits 1..k+1 tokens). With
    ``return_stats`` returns ``(tokens, {"rounds", "tokens_per_round"})``
    — rounds is the number of weight streams the generation cost."""
    from kakveda_tpu.models.generate import _bucket_len, _prefill_jit

    plen = len(prompt_ids)
    need = plen + max_new_tokens + k + 2
    ml = max_len or _bucket_len(need, cfg.max_seq_len)
    # The verify chunk writes k+1 cache slots per round, so the window must
    # leave k+2 slots of headroom; clamp the generation budget to it (the
    # plain path truncates at its window the same way) and refuse prompts
    # that leave no room at all rather than silently clamping scatter
    # indices into garbage output.
    max_new = min(max_new_tokens, ml - plen - k - 2)
    if max_new <= 0:
        raise ValueError(
            f"prompt ({plen} tokens) leaves no speculative decode room in the "
            f"cache window (max_len={ml}, k={k}); truncate the prompt or raise max_len"
        )
    cache = init_cache(cfg, batch=1, max_len=ml)
    buf = np.zeros((1, ml), np.int32)
    buf[0, :plen] = prompt_ids

    last, cache = _prefill_jit(
        params,
        cfg,
        jnp.asarray([prompt_ids], jnp.int32),
        cache,
        jnp.ones((1, ml), bool),
        jnp.zeros((1,), jnp.int32),
    )
    out_buf, vl, rounds = _spec_decode_jit(
        params, cfg, jnp.asarray(buf), cache, last, jnp.asarray(plen), k, max_new
    )
    n = min(int(vl) - plen, max_new)
    toks = np.asarray(out_buf)[0, plen : plen + n].tolist()
    if eos_id is not None and eos_id in toks:
        toks = toks[: toks.index(eos_id)]
    if return_stats:
        r = int(rounds)
        return toks, {"rounds": r, "tokens_per_round": (int(vl) - plen) / max(r, 1)}
    return toks
