"""Byte-level tokenizer — the hermetic default for the in-tree Llama.

Zero-egress environments can't download a vocab, so the default tokenizer is
bytes: token = byte value + offset, plus BOS/EOS/PAD specials. Any utf-8
string round-trips exactly. A HF tokenizer can be plugged in where one is
available on disk (transformers is in the image); both expose the same
encode/decode surface.
"""

from __future__ import annotations

from typing import List


class ByteTokenizer:
    PAD = 0
    BOS = 1
    EOS = 2
    _OFFSET = 3

    vocab_size = 256 + _OFFSET

    def encode(self, text: str, *, bos: bool = True, eos: bool = False) -> List[int]:
        ids = [b + self._OFFSET for b in text.encode("utf-8")]
        if bos:
            ids = [self.BOS] + ids
        if eos:
            ids = ids + [self.EOS]
        return ids

    def decode(self, ids: List[int]) -> str:
        data = bytes(i - self._OFFSET for i in ids if i >= self._OFFSET)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Real-vocab tokenizer loaded from a local HF checkpoint/tokenizer dir.

    Same encode/decode surface as :class:`ByteTokenizer`, so the runtime and
    the LLM-classifier tier swap tokenizers without caring which is active.
    Zero egress: ``path`` must already hold tokenizer files on disk (it is
    normally the same directory as the converted checkpoint).
    """

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path)
        self.BOS = self._tok.bos_token_id
        self.EOS = self._tok.eos_token_id
        pad = self._tok.pad_token_id
        self.PAD = pad if pad is not None else (self.EOS if self.EOS is not None else 0)
        self.vocab_size = len(self._tok)

    def encode(self, text: str, *, bos: bool = True, eos: bool = False) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if bos and self.BOS is not None:
            ids = [self.BOS] + ids
        if eos and self.EOS is not None:
            ids = ids + [self.EOS]
        return ids

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)
