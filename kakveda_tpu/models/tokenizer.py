"""Byte-level tokenizer — the hermetic default for the in-tree Llama.

Zero-egress environments can't download a vocab, so the default tokenizer is
bytes: token = byte value + offset, plus BOS/EOS/PAD specials. Any utf-8
string round-trips exactly. A HF tokenizer can be plugged in where one is
available on disk (transformers is in the image); both expose the same
encode/decode surface.
"""

from __future__ import annotations

from typing import List


class ByteTokenizer:
    PAD = 0
    BOS = 1
    EOS = 2
    _OFFSET = 3

    vocab_size = 256 + _OFFSET

    def encode(self, text: str, *, bos: bool = True, eos: bool = False) -> List[int]:
        ids = [b + self._OFFSET for b in text.encode("utf-8")]
        if bos:
            ids = [self.BOS] + ids
        if eos:
            ids = ids + [self.EOS]
        return ids

    def decode(self, ids: List[int]) -> str:
        data = bytes(i - self._OFFSET for i in ids if i >= self._OFFSET)
        return data.decode("utf-8", errors="replace")
