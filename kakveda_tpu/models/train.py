"""Training step for the in-tree Llama, sharded over a dp×cp×tp mesh.

The reference never trains anything — its "model" is an HTTP call. Here the
framework owns the model, so it also owns the fine-tuning loop (the LLM
failure-classifier is a fine-tune target): causal-LM loss, AdamW, and a
``make_sharded_train_step`` that jits the whole update over a
``jax.sharding.Mesh`` with

  * params/opt-state sharded per ``param_specs`` (TP over ``tp``,
    replicated over ``dp``/``cp``),
  * batch sharded P('dp', 'cp') — data parallel over batch, context
    parallel over sequence (ring attention inside the forward),
  * donated params/opt-state so the update is in-place in HBM.

XLA inserts the gradient all-reduces from the shardings; there is no
hand-written NCCL/MPI anywhere — the collectives ride ICI.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kakveda_tpu.models.llama import (
    LlamaConfig,
    Params,
    forward,
    init_params,
    param_specs,
    specs_for_mesh,
)


def lm_loss_from_logits(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Causal-LM loss given logits [B, S, V]: next-token targets are the
    tokens shifted left, the wrapped last position masked out. The ONE
    definition of the training objective — shared by the dense step here
    and the pipeline-parallel step (models/pipeline.py)."""
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones_like(tokens, jnp.float32).at[:, -1].set(0.0)
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    return jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def lm_loss(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B, S] — next-token targets are tokens shifted left
    mesh: Optional[Mesh] = None,
    cp_axis: Optional[str] = None,
) -> jax.Array:
    """CE loss; MoE configs with ``router_aux_coef > 0`` add the summed
    load-balancing aux loss (models/moe.py, HF router_aux_loss_coef)."""
    if cfg.n_experts and cfg.router_aux_coef > 0.0:
        logits, aux = forward(params, cfg, tokens, mesh=mesh, cp_axis=cp_axis, with_aux=True)
        return lm_loss_from_logits(logits, tokens) + cfg.router_aux_coef * aux
    logits = forward(params, cfg, tokens, mesh=mesh, cp_axis=cp_axis)
    return lm_loss_from_logits(logits, tokens)


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.01) -> optax.GradientTransformation:
    return optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=weight_decay)


def make_train_step(cfg: LlamaConfig, opt: Optional[optax.GradientTransformation] = None):
    """Single-device (or pure-DP) jitted train step."""
    opt = opt or make_optimizer()

    @jax.jit
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(lm_loss)(params, cfg, tokens)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step, opt


def shard_params(params: Params, cfg: LlamaConfig, mesh: Mesh) -> Params:
    from kakveda_tpu.parallel.distributed import put_global

    specs = specs_for_mesh(param_specs(cfg), mesh)
    return jax.tree.map(
        lambda x, s: put_global(x, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, jax.Array) or hasattr(x, "shape"),
    )


def make_sharded_train_step(
    cfg: LlamaConfig,
    mesh: Mesh,
    opt: Optional[optax.GradientTransformation] = None,
    cp_axis: Optional[str] = "cp",
):
    """Jitted full training step over the mesh; returns (step, init_state).

    ``init_state(rng)`` materializes sharded params + opt state directly on
    the mesh (init is itself jitted with output shardings, so the f32 master
    weights never exist unsharded on one device).
    """
    opt = opt or make_optimizer()
    specs = specs_for_mesh(param_specs(cfg), mesh)
    param_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                   is_leaf=lambda x: isinstance(x, P))
    batch_sharding = NamedSharding(mesh, P("dp", cp_axis if cp_axis in mesh.axis_names else None))
    repl = NamedSharding(mesh, P())

    use_cp = cp_axis if (cp_axis and cp_axis in mesh.axis_names and mesh.shape[cp_axis] > 1) else None

    def _init(rng):
        params = init_params(rng, cfg)
        opt_state = opt.init(params)
        return params, opt_state

    # Opt-state sharding mirrors the param tree inside adamw's mu/nu. Match
    # by pytree-path suffix, not leaf shape: wq [d, d] and wo [d, d] share a
    # shape but carry transposed PartitionSpecs, so a shape-keyed map would
    # silently reshard one of them every step.
    from jax.tree_util import keystr, tree_flatten_with_path, tree_map_with_path

    params_shape = jax.eval_shape(lambda r: init_params(r, cfg), jax.random.PRNGKey(0))
    param_paths = [keystr(path) for path, _ in tree_flatten_with_path(params_shape)[0]]
    path_to_sharding = dict(zip(param_paths, jax.tree.leaves(param_shardings)))

    def _sharding_for(path, leaf):
        if leaf.ndim == 0:
            return repl
        ps = keystr(path)
        for param_path, sharding in path_to_sharding.items():
            if ps.endswith(param_path):
                return sharding
        return repl

    opt_state_shape = jax.eval_shape(lambda r: opt.init(init_params(r, cfg)), jax.random.PRNGKey(0))
    opt_shardings = tree_map_with_path(_sharding_for, opt_state_shape)

    init_state = jax.jit(_init, out_shardings=(param_shardings, opt_shardings))

    def _step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(lm_loss)(params, cfg, tokens, mesh, use_cp)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step = jax.jit(
        _step,
        in_shardings=(param_shardings, opt_shardings, batch_sharding),
        out_shardings=(param_shardings, opt_shardings, repl),
        donate_argnums=(0, 1),
    )
    return step, init_state


# ---------------------------------------------------------------------------
# training loop + checkpointing (train → save → serve on the platform)
# ---------------------------------------------------------------------------


def save_checkpoint(params: Params, path: str) -> None:
    """Orbax checkpoint of the param pytree; LlamaRuntime.load_checkpoint
    (and KAKVEDA_LLAMA_CKPT) restore it for serving."""
    import os

    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(str(path)), params)
    ckptr.wait_until_finished()


def corpus_to_batches(text: str, batch: int, seq_len: int):
    """Tokenize a text corpus into as many [batch, seq_len] blocks as it
    yields (wrapping), for the demo fine-tune loop."""
    import numpy as np

    from kakveda_tpu.models.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    ids = tok.encode(text)
    need = batch * seq_len
    n_blocks = max(1, len(ids) // need)
    flat = np.resize(np.asarray(ids, np.int32), n_blocks * need)
    return [
        jnp.asarray(flat[i * need : (i + 1) * need].reshape(batch, seq_len))
        for i in range(n_blocks)
    ]


def fit(
    cfg: LlamaConfig,
    corpus: str,
    *,
    steps: int = 50,
    batch: int = 4,
    seq_len: int = 128,
    lr: float = 3e-4,
    seed: int = 0,
    checkpoint_path: Optional[str] = None,
    log_every: int = 10,
    log_fn=print,
) -> tuple[Params, list[float]]:
    """Small-scale causal-LM fit over a text corpus; returns (params,
    per-step losses) and optionally saves an orbax checkpoint that
    ``runtime=tpu`` serves directly."""
    params = init_params(jax.random.PRNGKey(seed), cfg)
    step, opt = make_train_step(cfg, make_optimizer(lr))
    opt_state = opt.init(params)
    batches = corpus_to_batches(corpus, batch, seq_len)
    losses: list[float] = []
    for i in range(steps):
        tokens = batches[i % len(batches)]
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
        if log_every and (i + 1) % log_every == 0:
            log_fn(f"step {i + 1}/{steps} loss {losses[-1]:.4f}")
    if checkpoint_path:
        save_checkpoint(params, checkpoint_path)
        log_fn(f"checkpoint saved to {checkpoint_path}")
    return params, losses
