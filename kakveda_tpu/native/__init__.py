"""ctypes bindings for the C++ native host tier (src/native.cc).

The native library accelerates the two host-side hot loops around the TPU
core: signature-text featurization (the per-trace CPU cost of the
10k traces/sec ingest path) and the GFKB's append-only persistence
(group-commit writer vs the reference's open+write+close per record,
reference: services/gfkb/app.py:49-51).

Everything here is optional: ``load()`` returns None when the library is
absent and cannot be built, and every consumer falls back to the pure
Python implementation. Set ``KAKVEDA_NATIVE=0`` to force the fallback,
``KAKVEDA_NATIVE=require`` to fail loudly instead of falling back.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from pathlib import Path
from typing import Optional

log = logging.getLogger("kakveda.native")

_DIR = Path(__file__).parent
_LIB_PATH = _DIR / "build" / "libkakveda_native.so"

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _build() -> bool:
    """Compile the library in-tree (g++ is part of the supported toolchain)."""
    try:
        subprocess.run(
            ["make", "-s"],
            cwd=_DIR,
            check=True,
            capture_output=True,
            timeout=120,
        )
        return _LIB_PATH.exists()
    except (subprocess.SubprocessError, OSError) as e:  # noqa: PERF203
        log.debug("native build failed: %s", e)
        return False


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it on first use; None if unavailable."""
    global _lib, _load_attempted
    if _lib is not None:
        return _lib
    if _load_attempted:
        return None
    _load_attempted = True
    env = os.environ.get("KAKVEDA_NATIVE", "auto").lower()
    if env in ("0", "false", "off"):
        return None
    # Rebuild when the source is newer than the .so (a stale library would
    # be missing newly added symbols); a source-less artifact deployment
    # (built .so, no src/) is simply never stale.
    src = _DIR / "src" / "native.cc"
    stale = not _LIB_PATH.exists() or (
        src.exists() and src.stat().st_mtime > _LIB_PATH.stat().st_mtime
    )
    if stale and not _build() and not _LIB_PATH.exists():
        if env == "require":
            raise RuntimeError("KAKVEDA_NATIVE=require but the native library cannot be built")
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError as e:
        if env == "require":
            raise
        log.debug("native load failed: %s", e)
        return None

    try:
        _bind(lib)
    except AttributeError as e:
        # A stale prebuilt .so (rebuild unavailable) lacking newly added
        # symbols must degrade to the Python fallback, not crash load().
        if env == "require":
            raise
        log.warning("native library is stale and cannot be rebuilt (%s); using Python fallback", e)
        return None
    _lib = lib
    return _lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.kkv_crc32.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.kkv_crc32.restype = ctypes.c_uint32
    lib.kkv_encode_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_char_p,
    ]
    lib.kkv_encode_batch.restype = ctypes.c_int
    lib.kkv_encode_sparse_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_char_p,
    ]
    lib.kkv_encode_sparse_batch.restype = ctypes.c_int
    lib.kkv_log_open.argtypes = [ctypes.c_char_p, ctypes.c_long]
    lib.kkv_log_open.restype = ctypes.c_void_p
    lib.kkv_log_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long]
    lib.kkv_log_append.restype = ctypes.c_int
    lib.kkv_log_flush.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.kkv_log_flush.restype = ctypes.c_int
    lib.kkv_log_close.argtypes = [ctypes.c_void_p]
    lib.kkv_log_close.restype = None


def available() -> bool:
    return load() is not None


class AppendLog:
    """Buffered append-only log with explicit group-commit flush.

    Pure-Python fallback when the native library is absent — same API, one
    ``open`` file object with Python-side buffering.
    """

    def __init__(self, path: str | os.PathLike, flush_bytes: int = 1 << 20):
        self._path = str(path)
        self._lib = load()
        self._h = None
        self._f = None
        if self._lib is not None:
            self._h = self._lib.kkv_log_open(self._path.encode(), flush_bytes)
        if self._h is None:
            self._lib = None
            self._f = open(self._path, "ab", buffering=flush_bytes)

    @property
    def native(self) -> bool:
        return self._h is not None

    def append(self, record: bytes) -> None:
        """Append one record (caller includes the trailing newline)."""
        if self._h is not None:
            if self._lib.kkv_log_append(self._h, record, len(record)) != 0:
                raise OSError(f"native append failed: {self._path}")
        else:
            self._f.write(record)

    def flush(self, fsync: bool = False) -> None:
        if self._h is not None:
            if self._lib.kkv_log_flush(self._h, 1 if fsync else 0) != 0:
                raise OSError(f"native flush failed: {self._path}")
        else:
            self._f.flush()
            if fsync:
                os.fsync(self._f.fileno())

    def close(self) -> None:
        if self._h is not None:
            self._lib.kkv_log_close(self._h)
            self._h = None
        elif self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
