"""ctypes bindings for the C++ native host tier (src/native.cc).

The native library accelerates the host-side hot loops around the TPU
core: signature-text featurization (the per-trace CPU cost of the
10k traces/sec ingest path), the GFKB's append-only persistence
(group-commit writer vs the reference's open+write+close per record,
reference: services/gfkb/app.py:49-51), and host-tier scoring
(:func:`score_block` / :func:`score_candidates` / :func:`score_gather` —
the sparse-dot cosine under every degraded-window warn and routed
overflow match, index/tiers.py; the gather form scores candidate row ids
in place from warm arrays or cold memmap shards, no materialization). ctypes releases the GIL for the duration of each
foreign call, so a long scoring scan never blocks the event loop.

Everything here is optional: ``load()`` returns None when the library is
absent and cannot be built, and every consumer falls back to the pure
Python implementation. Set ``KAKVEDA_NATIVE=0`` to force the fallback,
``KAKVEDA_NATIVE=require`` to fail loudly instead of falling back.
Scoring knobs (docs/observability.md registry): ``KAKVEDA_NATIVE_THREADS``
(0 = one per CPU, capped at 16) and ``KAKVEDA_NATIVE_MIN_ROWS`` (row floor
below which the numpy path wins — thread/ctypes overhead dominates tiny
scans).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from pathlib import Path
from typing import Optional

import numpy as np

log = logging.getLogger("kakveda.native")

_DIR = Path(__file__).parent
_LIB_PATH = _DIR / "build" / "libkakveda_native.so"

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _build() -> bool:
    """Compile the library in-tree (g++ is part of the supported toolchain)."""
    try:
        subprocess.run(
            ["make", "-s"],
            cwd=_DIR,
            check=True,
            capture_output=True,
            timeout=120,
        )
        return _LIB_PATH.exists()
    except (subprocess.SubprocessError, OSError) as e:  # noqa: PERF203
        log.debug("native build failed: %s", e)
        return False


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it on first use; None if unavailable."""
    global _lib, _load_attempted
    if _lib is not None:
        return _lib
    if _load_attempted:
        return None
    _load_attempted = True
    env = os.environ.get("KAKVEDA_NATIVE", "auto").lower()
    if env in ("0", "false", "off"):
        return None
    # Rebuild when the source is newer than the .so (a stale library would
    # be missing newly added symbols); a source-less artifact deployment
    # (built .so, no src/) is simply never stale.
    src = _DIR / "src" / "native.cc"
    stale = not _LIB_PATH.exists() or (
        src.exists() and src.stat().st_mtime > _LIB_PATH.stat().st_mtime
    )
    if stale and not _build() and not _LIB_PATH.exists():
        if env == "require":
            raise RuntimeError("KAKVEDA_NATIVE=require but the native library cannot be built")
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError as e:
        if env == "require":
            raise
        log.debug("native load failed: %s", e)
        return None

    try:
        _bind(lib)
    except AttributeError as e:
        # A stale prebuilt .so (rebuild unavailable) lacking newly added
        # symbols must degrade to the Python fallback, not crash load().
        if env == "require":
            raise
        log.warning("native library is stale and cannot be rebuilt (%s); using Python fallback", e)
        return None
    _lib = lib
    return _lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.kkv_crc32.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.kkv_crc32.restype = ctypes.c_uint32
    lib.kkv_encode_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_char_p,
    ]
    lib.kkv_encode_batch.restype = ctypes.c_int
    lib.kkv_encode_sparse_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_char_p,
    ]
    lib.kkv_encode_sparse_batch.restype = ctypes.c_int
    lib.kkv_log_open.argtypes = [ctypes.c_char_p, ctypes.c_long]
    lib.kkv_log_open.restype = ctypes.c_void_p
    lib.kkv_log_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long]
    lib.kkv_log_append.restype = ctypes.c_int
    lib.kkv_log_flush.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.kkv_log_flush.restype = ctypes.c_int
    lib.kkv_log_close.argtypes = [ctypes.c_void_p]
    lib.kkv_log_close.restype = None
    lib.kkv_score_block.argtypes = [
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_long,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_long,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int,
    ]
    lib.kkv_score_block.restype = ctypes.c_int
    lib.kkv_score_candidates.argtypes = [
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_long,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int,
    ]
    lib.kkv_score_candidates.restype = ctypes.c_int
    lib.kkv_score_gather.argtypes = [
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_long,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int,
    ]
    lib.kkv_score_gather.restype = ctypes.c_int


def available() -> bool:
    return load() is not None


def status() -> dict:
    """Load/build status for /readyz: did the library load, from where,
    and under which policy. Never triggers a build by itself beyond the
    normal first-use ``load()``."""
    try:
        lib = load()
    except RuntimeError:  # KAKVEDA_NATIVE=require and unbuildable
        lib = None
    return {
        "available": lib is not None,
        "mode": os.environ.get("KAKVEDA_NATIVE", "auto").lower(),
        "lib": str(_LIB_PATH) if _LIB_PATH.exists() else None,
        "threads": score_threads(),
    }


# ---------------------------------------------------------------------------
# host-tier scoring
# ---------------------------------------------------------------------------


def score_threads() -> int:
    """KAKVEDA_NATIVE_THREADS, resolved: 0/unset = one per CPU, capped at
    16 (scoring is memory-bound well before that)."""
    try:
        t = int(os.environ.get("KAKVEDA_NATIVE_THREADS", "0"))
    except ValueError:
        t = 0
    if t <= 0:
        t = os.cpu_count() or 1
    return max(1, min(t, 16))


def score_min_rows() -> int:
    """KAKVEDA_NATIVE_MIN_ROWS: total-row floor below which callers keep
    the numpy path (ctypes marshalling beats the win on tiny scans)."""
    try:
        return max(0, int(os.environ.get("KAKVEDA_NATIVE_MIN_ROWS", "256")))
    except ValueError:
        return 256


def _f32c(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, np.float32)


_PF = ctypes.POINTER(ctypes.c_float)
_PI32 = ctypes.POINTER(ctypes.c_int32)
_PI64 = ctypes.POINTER(ctypes.c_int64)


def score_block(
    qdense: np.ndarray,
    idx: np.ndarray,
    val: np.ndarray,
    dim: int,
    *,
    threads: Optional[int] = None,
) -> Optional[np.ndarray]:
    """Scores ``[B, n]`` for B dense queries (``[B, dim+1]``, pad column
    zero) over the same n fixed-width sparse rows, or None when the native
    library is unavailable or the call fails (caller falls back to numpy).
    """
    lib = load()
    if lib is None:
        return None
    q = _f32c(qdense if qdense.ndim == 2 else qdense[None, :])
    b, n = q.shape[0], idx.shape[0]
    if q.shape[1] != dim + 1:
        return None
    idx_c = np.ascontiguousarray(idx, np.int32)
    val_c = _f32c(val)
    out = np.empty((b, n), np.float32)
    rc = lib.kkv_score_block(
        q.ctypes.data_as(_PF), b, dim,
        idx_c.ctypes.data_as(_PI32), val_c.ctypes.data_as(_PF),
        n, idx_c.shape[1] if idx_c.ndim == 2 else 0,
        out.ctypes.data_as(_PF),
        score_threads() if threads is None else threads,
    )
    if rc != 0:
        log.warning("kkv_score_block failed (rc=%d); numpy fallback", rc)
        return None
    return out[0] if qdense.ndim == 1 else out


def score_candidates(
    qdense: np.ndarray,
    idx: np.ndarray,
    val: np.ndarray,
    offsets: np.ndarray,
    dim: int,
    *,
    threads: Optional[int] = None,
) -> Optional[np.ndarray]:
    """Flat scores ``[offsets[-1]]`` where query q covers candidate rows
    ``[offsets[q], offsets[q+1])`` — the one thread-pooled entry point
    behind degraded warn, overflow routed matching and the mining attach
    path. None on unavailability/failure (caller falls back to numpy)."""
    lib = load()
    if lib is None:
        return None
    q = _f32c(qdense)
    if q.ndim != 2 or q.shape[1] != dim + 1:
        return None
    off = np.ascontiguousarray(offsets, np.int64)
    total = int(off[-1])
    idx_c = np.ascontiguousarray(idx, np.int32)
    val_c = _f32c(val)
    out = np.empty(total, np.float32)
    rc = lib.kkv_score_candidates(
        q.ctypes.data_as(_PF), q.shape[0], dim,
        idx_c.ctypes.data_as(_PI32), val_c.ctypes.data_as(_PF),
        off.ctypes.data_as(_PI64),
        idx_c.shape[1] if idx_c.ndim == 2 else 0,
        out.ctypes.data_as(_PF),
        score_threads() if threads is None else threads,
    )
    if rc != 0:
        log.warning("kkv_score_candidates failed (rc=%d); numpy fallback", rc)
        return None
    return out


def score_gather(
    qdense: np.ndarray,
    idx: np.ndarray,
    val: np.ndarray,
    rows: np.ndarray,
    dim: int,
    *,
    threads: Optional[int] = None,
) -> Optional[np.ndarray]:
    """Scores ``[len(rows)]`` for one dense query over row ids gathered
    straight from a base array — the warm tier's resident ``[cap, K]``
    arrays or a cold shard's memmap (pages fault in inside the C call,
    GIL released). STRICTLY zero-copy on idx/val: a dtype or layout
    mismatch returns None rather than silently copying a multi-GB shard.
    Row ids must be in range — the kernel does not bounds-check them."""
    lib = load()
    if lib is None:
        return None
    q = _f32c(qdense)
    if q.ndim != 1 or q.shape[0] != dim + 1:
        return None
    if (
        idx.ndim != 2 or val.ndim != 2
        or idx.dtype != np.int32 or val.dtype != np.float32
        or not idx.flags["C_CONTIGUOUS"] or not val.flags["C_CONTIGUOUS"]
    ):
        return None
    r = np.ascontiguousarray(rows, np.int64)
    if len(r) and (int(r.min()) < 0 or int(r.max()) >= idx.shape[0]):
        return None
    out = np.empty(len(r), np.float32)
    rc = lib.kkv_score_gather(
        q.ctypes.data_as(_PF), dim,
        idx.ctypes.data_as(_PI32), val.ctypes.data_as(_PF),
        idx.shape[1], r.ctypes.data_as(_PI64), len(r),
        out.ctypes.data_as(_PF),
        score_threads() if threads is None else threads,
    )
    if rc != 0:
        log.warning("kkv_score_gather failed (rc=%d); numpy fallback", rc)
        return None
    return out


class AppendLog:
    """Buffered append-only log with explicit group-commit flush.

    Pure-Python fallback when the native library is absent — same API, one
    ``open`` file object with Python-side buffering.
    """

    def __init__(self, path: str | os.PathLike, flush_bytes: int = 1 << 20):
        self._path = str(path)
        self._lib = load()
        self._h = None
        self._f = None
        if self._lib is not None:
            self._h = self._lib.kkv_log_open(self._path.encode(), flush_bytes)
        if self._h is None:
            self._lib = None
            self._f = open(self._path, "ab", buffering=flush_bytes)

    @property
    def native(self) -> bool:
        return self._h is not None

    def append(self, record: bytes) -> None:
        """Append one record (caller includes the trailing newline)."""
        if self._h is not None:
            if self._lib.kkv_log_append(self._h, record, len(record)) != 0:
                raise OSError(f"native append failed: {self._path}")
        else:
            self._f.write(record)

    def flush(self, fsync: bool = False) -> None:
        if self._h is not None:
            if self._lib.kkv_log_flush(self._h, 1 if fsync else 0) != 0:
                raise OSError(f"native flush failed: {self._path}")
        else:
            self._f.flush()
            if fsync:
                os.fsync(self._f.fileno())

    def close(self) -> None:
        if self._h is not None:
            self._lib.kkv_log_close(self._h)
            self._h = None
        elif self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
