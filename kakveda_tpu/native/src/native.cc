// kakveda-tpu native host tier.
//
// The TPU owns the math (matmul kNN, clustering, Llama); this library owns
// the two host-side hot loops that feed it:
//
//   1. hashed n-gram featurization of signature texts — the per-trace CPU
//      cost of the 10k traces/sec ingest path (replaces, with
//      ops/featurizer.py, the reference's per-query TF-IDF refit,
//      reference: services/shared/similarity.py:14-20);
//   2. an append-only log writer with buffered group-commit — the
//      persistence layer under the GFKB's versioned-append store
//      (reference: services/gfkb/app.py:49-51 does one open+write+close
//      per record);
//   3. host-tier scoring (kkv_score_block / kkv_score_candidates /
//      kkv_score_gather) — the
//      sparse-dot cosine over the warm/cold tiers' fixed-width (idx, val)
//      row arrays. This is every degraded-window warn and every routed
//      overflow match; the loops are written so the compiler can keep the
//      dense query resident and vectorize the gather-multiply (-O3 on an
//      AVX host). The GIL is released for the duration of the call by
//      ctypes itself, so concurrent /warn load keeps the event loop live.
//
// Semantics mirror ops/featurizer.py exactly for ASCII text (the Python
// wrapper routes non-ASCII strings to the Python implementation, where
// unicode lowercasing can differ). Hashing is the standard zlib crc32
// polynomial, table-generated here so the library has zero dependencies.
//
// Build: make (g++ -O3 -shared -fPIC). Bound via ctypes from
// kakveda_tpu/native/__init__.py.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#if defined(_WIN32)
#error "posix only"
#endif
#include <fcntl.h>
#include <unistd.h>

namespace {

// --- crc32 (zlib polynomial 0xEDB88320, identical to Python zlib.crc32) ---

uint32_t g_crc_table[256];

struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      g_crc_table[i] = c;
    }
  }
} g_crc_init;

uint32_t crc32_buf(const char* buf, size_t len) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = g_crc_table[(c ^ static_cast<uint8_t>(buf[i])) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// --- featurizer -----------------------------------------------------------

struct FieldSpec {
  std::string name;  // lowercased field label
  float weight;
  bool atomic;
};

// spec string: "name,weight,atomic;name,weight,atomic;..."
std::vector<FieldSpec> parse_spec(const char* spec) {
  std::vector<FieldSpec> out;
  if (!spec) return out;
  const char* p = spec;
  while (*p) {
    const char* end = strchr(p, ';');
    std::string item = end ? std::string(p, end - p) : std::string(p);
    size_t c1 = item.find(',');
    size_t c2 = item.find(',', c1 + 1);
    if (c1 != std::string::npos && c2 != std::string::npos) {
      FieldSpec fs;
      fs.name = item.substr(0, c1);
      fs.weight = strtof(item.c_str() + c1 + 1, nullptr);
      fs.atomic = item[c2 + 1] == '1';
      out.push_back(fs);
    }
    if (!end) break;
    p = end + 1;
  }
  return out;
}

inline bool is_token_char(char c) {
  return (c >= 'a' && c <= 'z') || (c >= '0' && c <= '9') || c == '_';
}

inline char ascii_lower(char c) {
  return (c >= 'A' && c <= 'Z') ? static_cast<char>(c + 32) : c;
}

// Accumulate one hashed term: bucket = crc & 0x7FFFFFFF & (dim-1),
// sign from bit 31 — mirrors featurizer._hash_term / _bucket.
inline void add_term(const char* term, size_t len, float weight, float* row,
                     uint32_t dim_mask) {
  uint32_t h = crc32_buf(term, len);
  float sign = ((h >> 31) & 1u) ? -1.0f : 1.0f;
  row[(h & 0x7FFFFFFFu) & dim_mask] += sign * weight;
}

// Word uni+bigrams of `text` (lowercased, [a-z0-9_]+ tokens), each hashed
// at `weight` — mirrors featurizer._terms. Token emission order matches the
// Python list (all unigrams, then bigrams), which matters for f32
// accumulation order only when buckets collide; we replicate it anyway.
void add_ngrams(const char* text, size_t len, float weight, float* row,
                uint32_t dim_mask, std::string& scratch,
                std::vector<std::pair<size_t, size_t>>& words) {
  scratch.clear();
  scratch.reserve(len);
  for (size_t i = 0; i < len; i++) scratch.push_back(ascii_lower(text[i]));
  words.clear();
  size_t i = 0;
  while (i < scratch.size()) {
    while (i < scratch.size() && !is_token_char(scratch[i])) i++;
    size_t start = i;
    while (i < scratch.size() && is_token_char(scratch[i])) i++;
    if (i > start) words.emplace_back(start, i - start);
  }
  for (auto& w : words) add_term(scratch.data() + w.first, w.second, weight, row, dim_mask);
  std::string gram;
  for (size_t j = 0; j + 1 < words.size(); j++) {
    gram.assign(scratch.data() + words[j].first, words[j].second);
    gram.push_back(' ');
    gram.append(scratch.data() + words[j + 1].first, words[j + 1].second);
    add_term(gram.data(), gram.size(), weight, row, dim_mask);
  }
}

void trim_lower(std::string& s) {
  size_t b = 0, e = s.size();
  while (b < e && (s[b] == ' ' || s[b] == '\t' || s[b] == '\n' || s[b] == '\r' ||
                   s[b] == '\f' || s[b] == '\v'))
    b++;
  while (e > b && (s[e - 1] == ' ' || s[e - 1] == '\t' || s[e - 1] == '\n' ||
                   s[e - 1] == '\r' || s[e - 1] == '\f' || s[e - 1] == '\v'))
    e--;
  s = s.substr(b, e - b);
  for (auto& c : s) c = ascii_lower(c);
}

void encode_one(const char* text, int dim, float* row,
                const std::vector<FieldSpec>& specs) {
  const uint32_t dim_mask = static_cast<uint32_t>(dim - 1);
  std::string scratch;
  std::vector<std::pair<size_t, size_t>> words;
  const char* seg = text;
  const char* text_end = text + strlen(text);
  while (seg <= text_end) {
    const char* sep = strstr(seg, " | ");
    const char* seg_end = sep ? sep : text_end;
    // partition on ':'
    const char* colon = static_cast<const char*>(memchr(seg, ':', seg_end - seg));
    const FieldSpec* spec = nullptr;
    if (colon) {
      std::string name(seg, colon - seg);
      std::string key = name;
      trim_lower(key);
      for (auto& fs : specs)
        if (fs.name == key) { spec = &fs; break; }
      if (spec) {
        if (spec->atomic) {
          // each comma item -> single feature "rawname=item"
          const char* p = colon + 1;
          while (p <= seg_end) {
            const char* comma = static_cast<const char*>(memchr(p, ',', seg_end - p));
            const char* item_end = comma ? comma : seg_end;
            std::string item(p, item_end - p);
            trim_lower(item);
            if (!item.empty()) {
              std::string feat = name;  // raw (unstripped) name, as in Python
              feat.push_back('=');
              feat.append(item);
              add_term(feat.data(), feat.size(), spec->weight, row, dim_mask);
            }
            if (!comma) break;
            p = comma + 1;
          }
        } else {
          add_ngrams(colon + 1, seg_end - (colon + 1), spec->weight, row, dim_mask,
                     scratch, words);
        }
      }
    }
    if (!spec) add_ngrams(seg, seg_end - seg, 1.0f, row, dim_mask, scratch, words);
    if (!sep) break;
    seg = sep + 3;
  }
  // L2 normalize (double accumulator; Python's float32 np.linalg.norm agrees
  // to ~1e-7 relative, covered by the parity tests).
  double ss = 0.0;
  for (int j = 0; j < dim; j++) ss += static_cast<double>(row[j]) * row[j];
  if (ss > 0.0) {
    float inv = static_cast<float>(1.0 / std::sqrt(ss));
    for (int j = 0; j < dim; j++) row[j] *= inv;
  }
}

// --- host-tier scoring ----------------------------------------------------

// Score fixed-width sparse rows [r0, r1) against one dense query.
// `qd` has dim+1 floats with qd[dim] == 0.0 (the pad sentinel scores 0);
// any idx outside [0, dim] clamps to the sentinel, so a corrupt row can
// mis-score but never read out of bounds.
inline void score_range(const float* qd, int dim, const int32_t* idx,
                        const float* val, long r0, long r1, int k,
                        float* out) {
  const uint32_t udim = static_cast<uint32_t>(dim);
  for (long r = r0; r < r1; r++) {
    const int32_t* ir = idx + static_cast<size_t>(r) * k;
    const float* vr = val + static_cast<size_t>(r) * k;
    float s = 0.0f;
    for (int j = 0; j < k; j++) {
      uint32_t ix = static_cast<uint32_t>(ir[j]);
      if (ix > udim) ix = udim;  // negatives wrap huge and clamp too
      s += qd[ix] * vr[j];
    }
    out[r] = s;
  }
}

// Split [0, total) into n_threads contiguous chunks and run fn(lo, hi) on
// each; below the spawn floor (or single-threaded) everything runs inline —
// thread startup would dominate small scans.
template <typename Fn>
void parallel_ranges(long total, int n_threads, long spawn_floor, Fn fn) {
  if (n_threads > 64) n_threads = 64;
  if (n_threads <= 1 || total < spawn_floor) {
    fn(0, total);
    return;
  }
  long chunk = (total + n_threads - 1) / n_threads;
  std::vector<std::thread> pool;
  pool.reserve(n_threads);
  for (int t = 0; t < n_threads; t++) {
    long lo = static_cast<long>(t) * chunk;
    long hi = lo + chunk < total ? lo + chunk : total;
    if (lo >= hi) break;
    pool.emplace_back([=] { fn(lo, hi); });
  }
  for (auto& th : pool) th.join();
}

// --- append log -----------------------------------------------------------

struct AppendLog {
  int fd = -1;
  std::mutex mu;
  std::string buf;
  size_t flush_bytes = 1 << 20;
};

}  // namespace

extern "C" {

uint32_t kkv_crc32(const char* buf, int len) { return crc32_buf(buf, len); }

// texts: n NUL-terminated strings; out: [n, dim] float32, caller-zeroed.
// dim must be a power of two. Returns 0 on success.
int kkv_encode_batch(const char** texts, int n, int dim, float* out,
                     const char* spec_str) {
  if (dim <= 0 || (dim & (dim - 1)) != 0) return -1;
  std::vector<FieldSpec> specs = parse_spec(spec_str);
  for (int i = 0; i < n; i++)
    encode_one(texts[i], dim, out + static_cast<size_t>(i) * dim, specs);
  return 0;
}

// Sparse encode: same features as kkv_encode_batch, emitted as (idx, val)
// pairs for the device-side scatter-add (hashed rows are ~98% zeros, so the
// dense [n, dim] form wastes host→device bandwidth). idx: [n, k] caller-
// filled with `dim` (the scatter drop sentinel); val: [n, k] caller-zeroed.
// Returns 0 on success, or the required k when some row has more than k
// nonzeros (caller re-allocs and retries), or -1 on bad dim.
int kkv_encode_sparse_batch(const char** texts, int n, int dim, int k,
                            int32_t* idx, float* val, const char* spec_str) {
  if (dim <= 0 || (dim & (dim - 1)) != 0) return -1;
  std::vector<FieldSpec> specs = parse_spec(spec_str);
  std::vector<float> row(static_cast<size_t>(dim));
  int need = 0;
  for (int i = 0; i < n; i++) {
    std::memset(row.data(), 0, sizeof(float) * dim);
    encode_one(texts[i], dim, row.data(), specs);
    int m = 0;
    int32_t* irow = idx + static_cast<size_t>(i) * k;
    float* vrow = val + static_cast<size_t>(i) * k;
    for (int j = 0; j < dim; j++) {
      if (row[j] != 0.0f) {
        if (m < k) {
          irow[m] = j;
          vrow[m] = row[j];
        }
        m++;
      }
    }
    if (m > need) need = m;
  }
  return need > k ? need : 0;
}

// Host-tier block scorer: b dense queries ([b, dim+1] f32, qd[dim] == 0)
// against the SAME n fixed-width sparse rows (idx [n, k] int32 with pad ==
// dim, val [n, k] f32). out: [b, n] f32. The one-query case (b == 1) is the
// warm/cold exact scan; b > 1 is the degraded-window warn batch — every
// query streams the row block once. Returns 0 on success, -1 on bad args.
int kkv_score_block(const float* qdense, long b, int dim, const int32_t* idx,
                    const float* val, long n, int k, float* out,
                    int n_threads) {
  if (!qdense || !out || b < 0 || n < 0 || dim <= 0 || k < 0) return -1;
  if (n > 0 && k > 0 && (!idx || !val)) return -1;
  if (k == 0 || n == 0) {
    std::memset(out, 0, sizeof(float) * static_cast<size_t>(b) * n);
    return 0;
  }
  parallel_ranges(n, n_threads, 1 << 14, [=](long lo, long hi) {
    for (long q = 0; q < b; q++)
      score_range(qdense + static_cast<size_t>(q) * (dim + 1), dim, idx, val,
                  lo, hi, k, out + static_cast<size_t>(q) * n);
  });
  return 0;
}

// Thread-pooled IVF candidate scorer: query q scores the concatenated
// candidate rows [offsets[q], offsets[q+1]) — ONE call per match batch for
// degraded warn, overflow routed matching and the mining attach path.
// qdense: [b, dim+1]; idx/val: [offsets[b], k]; out: [offsets[b]] f32.
// Returns 0 on success, -1 on bad args (incl. non-monotonic offsets).
int kkv_score_candidates(const float* qdense, long b, int dim,
                         const int32_t* idx, const float* val,
                         const int64_t* offsets, int k, float* out,
                         int n_threads) {
  if (!qdense || !offsets || b < 0 || dim <= 0 || k < 0) return -1;
  long total = static_cast<long>(offsets[b]);
  if (offsets[0] != 0 || total < 0) return -1;
  for (long q = 0; q < b; q++)
    if (offsets[q + 1] < offsets[q]) return -1;
  if (total == 0) return 0;
  if (!out || (k > 0 && (!idx || !val))) return -1;
  if (k == 0) {
    std::memset(out, 0, sizeof(float) * static_cast<size_t>(total));
    return 0;
  }
  // Chunk the FLAT row range so one giant candidate list still splits
  // across threads; each chunk walks the queries overlapping it.
  parallel_ranges(total, n_threads, 1 << 14, [=](long lo, long hi) {
    long q = 0;
    while (q < b && static_cast<long>(offsets[q + 1]) <= lo) q++;
    for (; q < b && static_cast<long>(offsets[q]) < hi; q++) {
      long r0 = static_cast<long>(offsets[q]) > lo
                    ? static_cast<long>(offsets[q]) : lo;
      long r1 = static_cast<long>(offsets[q + 1]) < hi
                    ? static_cast<long>(offsets[q + 1]) : hi;
      score_range(qdense + static_cast<size_t>(q) * (dim + 1), dim, idx, val,
                  r0, r1, k, out);
    }
  });
  return 0;
}

// Gather-scorer: score row ids straight out of a resident base array
// (warm tier) or an mmap'd cold shard — no [m, k] materialization, no
// Python-side fancy-index copy; cold pages fault in during the scan with
// the GIL released. qdense: [dim+1] (one query); idx/val: the base
// arrays, row stride k; rows: [m] int64 row ids into the base. out: [m].
// The CALLER guarantees row ids are in range — this is the hot path and
// it does no bounds checking beyond the per-entry feature clamp.
int kkv_score_gather(const float* qdense, int dim, const int32_t* idx,
                     const float* val, int k, const int64_t* rows, long m,
                     float* out, int n_threads) {
  if (!qdense || !out || m < 0 || dim <= 0 || k < 0) return -1;
  if (m == 0) return 0;
  if (!rows) return -1;
  if (k == 0 || !idx || !val) {
    std::memset(out, 0, sizeof(float) * static_cast<size_t>(m));
    return 0;
  }
  const uint32_t udim = static_cast<uint32_t>(dim);
  parallel_ranges(m, n_threads, 1 << 14, [=](long lo, long hi) {
    // The row indirection defeats the hardware prefetcher (each row is a
    // ~128 B island in a multi-GB mmap) — software-prefetch a few rows
    // ahead so the memory latency overlaps the current row's math.
    constexpr long kPrefetch = 8;
    for (long i = lo; i < hi; i++) {
      if (i + kPrefetch < hi) {
        const size_t pr = static_cast<size_t>(rows[i + kPrefetch]);
        __builtin_prefetch(idx + pr * k, 0, 1);
        __builtin_prefetch(val + pr * k, 0, 1);
      }
      const size_t row = static_cast<size_t>(rows[i]);
      const int32_t* ir = idx + row * k;
      const float* vr = val + row * k;
      float s = 0.0f;
      for (int j = 0; j < k; j++) {
        uint32_t ix = static_cast<uint32_t>(ir[j]);
        if (ix > udim) ix = udim;  // pad and negatives clamp to the zero slot
        s += qdense[ix] * vr[j];
      }
      out[i] = s;
    }
  });
  return 0;
}

// Append-only log: open(append mode) -> handle.
void* kkv_log_open(const char* path, long flush_bytes) {
  int fd = open(path, O_WRONLY | O_APPEND | O_CREAT, 0644);
  if (fd < 0) return nullptr;
  auto* log = new AppendLog();
  log->fd = fd;
  if (flush_bytes > 0) log->flush_bytes = static_cast<size_t>(flush_bytes);
  return log;
}

// Buffered append; flushes to the kernel when the buffer tops flush_bytes.
// One record = caller's bytes (caller includes the trailing newline).
int kkv_log_append(void* h, const char* data, long len) {
  auto* log = static_cast<AppendLog*>(h);
  if (!log || log->fd < 0 || len < 0) return -1;
  std::lock_guard<std::mutex> lk(log->mu);
  log->buf.append(data, static_cast<size_t>(len));
  if (log->buf.size() >= log->flush_bytes) {
    ssize_t n = write(log->fd, log->buf.data(), log->buf.size());
    if (n != static_cast<ssize_t>(log->buf.size())) return -1;
    log->buf.clear();
  }
  return 0;
}

// Drain the buffer to the kernel; fsync when do_fsync != 0 (group commit:
// many appends, one durability point).
int kkv_log_flush(void* h, int do_fsync) {
  auto* log = static_cast<AppendLog*>(h);
  if (!log || log->fd < 0) return -1;
  std::lock_guard<std::mutex> lk(log->mu);
  if (!log->buf.empty()) {
    ssize_t n = write(log->fd, log->buf.data(), log->buf.size());
    if (n != static_cast<ssize_t>(log->buf.size())) return -1;
    log->buf.clear();
  }
  if (do_fsync && fsync(log->fd) != 0) return -1;
  return 0;
}

void kkv_log_close(void* h) {
  auto* log = static_cast<AppendLog*>(h);
  if (!log) return;
  kkv_log_flush(h, 0);
  close(log->fd);
  delete log;
}

}  // extern "C"
