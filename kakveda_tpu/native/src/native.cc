// kakveda-tpu native host tier.
//
// The TPU owns the math (matmul kNN, clustering, Llama); this library owns
// the two host-side hot loops that feed it:
//
//   1. hashed n-gram featurization of signature texts — the per-trace CPU
//      cost of the 10k traces/sec ingest path (replaces, with
//      ops/featurizer.py, the reference's per-query TF-IDF refit,
//      reference: services/shared/similarity.py:14-20);
//   2. an append-only log writer with buffered group-commit — the
//      persistence layer under the GFKB's versioned-append store
//      (reference: services/gfkb/app.py:49-51 does one open+write+close
//      per record).
//
// Semantics mirror ops/featurizer.py exactly for ASCII text (the Python
// wrapper routes non-ASCII strings to the Python implementation, where
// unicode lowercasing can differ). Hashing is the standard zlib crc32
// polynomial, table-generated here so the library has zero dependencies.
//
// Build: make (g++ -O3 -shared -fPIC). Bound via ctypes from
// kakveda_tpu/native/__init__.py.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <mutex>
#include <string>
#include <vector>

#if defined(_WIN32)
#error "posix only"
#endif
#include <fcntl.h>
#include <unistd.h>

namespace {

// --- crc32 (zlib polynomial 0xEDB88320, identical to Python zlib.crc32) ---

uint32_t g_crc_table[256];

struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      g_crc_table[i] = c;
    }
  }
} g_crc_init;

uint32_t crc32_buf(const char* buf, size_t len) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = g_crc_table[(c ^ static_cast<uint8_t>(buf[i])) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// --- featurizer -----------------------------------------------------------

struct FieldSpec {
  std::string name;  // lowercased field label
  float weight;
  bool atomic;
};

// spec string: "name,weight,atomic;name,weight,atomic;..."
std::vector<FieldSpec> parse_spec(const char* spec) {
  std::vector<FieldSpec> out;
  if (!spec) return out;
  const char* p = spec;
  while (*p) {
    const char* end = strchr(p, ';');
    std::string item = end ? std::string(p, end - p) : std::string(p);
    size_t c1 = item.find(',');
    size_t c2 = item.find(',', c1 + 1);
    if (c1 != std::string::npos && c2 != std::string::npos) {
      FieldSpec fs;
      fs.name = item.substr(0, c1);
      fs.weight = strtof(item.c_str() + c1 + 1, nullptr);
      fs.atomic = item[c2 + 1] == '1';
      out.push_back(fs);
    }
    if (!end) break;
    p = end + 1;
  }
  return out;
}

inline bool is_token_char(char c) {
  return (c >= 'a' && c <= 'z') || (c >= '0' && c <= '9') || c == '_';
}

inline char ascii_lower(char c) {
  return (c >= 'A' && c <= 'Z') ? static_cast<char>(c + 32) : c;
}

// Accumulate one hashed term: bucket = crc & 0x7FFFFFFF & (dim-1),
// sign from bit 31 — mirrors featurizer._hash_term / _bucket.
inline void add_term(const char* term, size_t len, float weight, float* row,
                     uint32_t dim_mask) {
  uint32_t h = crc32_buf(term, len);
  float sign = ((h >> 31) & 1u) ? -1.0f : 1.0f;
  row[(h & 0x7FFFFFFFu) & dim_mask] += sign * weight;
}

// Word uni+bigrams of `text` (lowercased, [a-z0-9_]+ tokens), each hashed
// at `weight` — mirrors featurizer._terms. Token emission order matches the
// Python list (all unigrams, then bigrams), which matters for f32
// accumulation order only when buckets collide; we replicate it anyway.
void add_ngrams(const char* text, size_t len, float weight, float* row,
                uint32_t dim_mask, std::string& scratch,
                std::vector<std::pair<size_t, size_t>>& words) {
  scratch.clear();
  scratch.reserve(len);
  for (size_t i = 0; i < len; i++) scratch.push_back(ascii_lower(text[i]));
  words.clear();
  size_t i = 0;
  while (i < scratch.size()) {
    while (i < scratch.size() && !is_token_char(scratch[i])) i++;
    size_t start = i;
    while (i < scratch.size() && is_token_char(scratch[i])) i++;
    if (i > start) words.emplace_back(start, i - start);
  }
  for (auto& w : words) add_term(scratch.data() + w.first, w.second, weight, row, dim_mask);
  std::string gram;
  for (size_t j = 0; j + 1 < words.size(); j++) {
    gram.assign(scratch.data() + words[j].first, words[j].second);
    gram.push_back(' ');
    gram.append(scratch.data() + words[j + 1].first, words[j + 1].second);
    add_term(gram.data(), gram.size(), weight, row, dim_mask);
  }
}

void trim_lower(std::string& s) {
  size_t b = 0, e = s.size();
  while (b < e && (s[b] == ' ' || s[b] == '\t' || s[b] == '\n' || s[b] == '\r' ||
                   s[b] == '\f' || s[b] == '\v'))
    b++;
  while (e > b && (s[e - 1] == ' ' || s[e - 1] == '\t' || s[e - 1] == '\n' ||
                   s[e - 1] == '\r' || s[e - 1] == '\f' || s[e - 1] == '\v'))
    e--;
  s = s.substr(b, e - b);
  for (auto& c : s) c = ascii_lower(c);
}

void encode_one(const char* text, int dim, float* row,
                const std::vector<FieldSpec>& specs) {
  const uint32_t dim_mask = static_cast<uint32_t>(dim - 1);
  std::string scratch;
  std::vector<std::pair<size_t, size_t>> words;
  const char* seg = text;
  const char* text_end = text + strlen(text);
  while (seg <= text_end) {
    const char* sep = strstr(seg, " | ");
    const char* seg_end = sep ? sep : text_end;
    // partition on ':'
    const char* colon = static_cast<const char*>(memchr(seg, ':', seg_end - seg));
    const FieldSpec* spec = nullptr;
    if (colon) {
      std::string name(seg, colon - seg);
      std::string key = name;
      trim_lower(key);
      for (auto& fs : specs)
        if (fs.name == key) { spec = &fs; break; }
      if (spec) {
        if (spec->atomic) {
          // each comma item -> single feature "rawname=item"
          const char* p = colon + 1;
          while (p <= seg_end) {
            const char* comma = static_cast<const char*>(memchr(p, ',', seg_end - p));
            const char* item_end = comma ? comma : seg_end;
            std::string item(p, item_end - p);
            trim_lower(item);
            if (!item.empty()) {
              std::string feat = name;  // raw (unstripped) name, as in Python
              feat.push_back('=');
              feat.append(item);
              add_term(feat.data(), feat.size(), spec->weight, row, dim_mask);
            }
            if (!comma) break;
            p = comma + 1;
          }
        } else {
          add_ngrams(colon + 1, seg_end - (colon + 1), spec->weight, row, dim_mask,
                     scratch, words);
        }
      }
    }
    if (!spec) add_ngrams(seg, seg_end - seg, 1.0f, row, dim_mask, scratch, words);
    if (!sep) break;
    seg = sep + 3;
  }
  // L2 normalize (double accumulator; Python's float32 np.linalg.norm agrees
  // to ~1e-7 relative, covered by the parity tests).
  double ss = 0.0;
  for (int j = 0; j < dim; j++) ss += static_cast<double>(row[j]) * row[j];
  if (ss > 0.0) {
    float inv = static_cast<float>(1.0 / std::sqrt(ss));
    for (int j = 0; j < dim; j++) row[j] *= inv;
  }
}

// --- append log -----------------------------------------------------------

struct AppendLog {
  int fd = -1;
  std::mutex mu;
  std::string buf;
  size_t flush_bytes = 1 << 20;
};

}  // namespace

extern "C" {

uint32_t kkv_crc32(const char* buf, int len) { return crc32_buf(buf, len); }

// texts: n NUL-terminated strings; out: [n, dim] float32, caller-zeroed.
// dim must be a power of two. Returns 0 on success.
int kkv_encode_batch(const char** texts, int n, int dim, float* out,
                     const char* spec_str) {
  if (dim <= 0 || (dim & (dim - 1)) != 0) return -1;
  std::vector<FieldSpec> specs = parse_spec(spec_str);
  for (int i = 0; i < n; i++)
    encode_one(texts[i], dim, out + static_cast<size_t>(i) * dim, specs);
  return 0;
}

// Sparse encode: same features as kkv_encode_batch, emitted as (idx, val)
// pairs for the device-side scatter-add (hashed rows are ~98% zeros, so the
// dense [n, dim] form wastes host→device bandwidth). idx: [n, k] caller-
// filled with `dim` (the scatter drop sentinel); val: [n, k] caller-zeroed.
// Returns 0 on success, or the required k when some row has more than k
// nonzeros (caller re-allocs and retries), or -1 on bad dim.
int kkv_encode_sparse_batch(const char** texts, int n, int dim, int k,
                            int32_t* idx, float* val, const char* spec_str) {
  if (dim <= 0 || (dim & (dim - 1)) != 0) return -1;
  std::vector<FieldSpec> specs = parse_spec(spec_str);
  std::vector<float> row(static_cast<size_t>(dim));
  int need = 0;
  for (int i = 0; i < n; i++) {
    std::memset(row.data(), 0, sizeof(float) * dim);
    encode_one(texts[i], dim, row.data(), specs);
    int m = 0;
    int32_t* irow = idx + static_cast<size_t>(i) * k;
    float* vrow = val + static_cast<size_t>(i) * k;
    for (int j = 0; j < dim; j++) {
      if (row[j] != 0.0f) {
        if (m < k) {
          irow[m] = j;
          vrow[m] = row[j];
        }
        m++;
      }
    }
    if (m > need) need = m;
  }
  return need > k ? need : 0;
}

// Append-only log: open(append mode) -> handle.
void* kkv_log_open(const char* path, long flush_bytes) {
  int fd = open(path, O_WRONLY | O_APPEND | O_CREAT, 0644);
  if (fd < 0) return nullptr;
  auto* log = new AppendLog();
  log->fd = fd;
  if (flush_bytes > 0) log->flush_bytes = static_cast<size_t>(flush_bytes);
  return log;
}

// Buffered append; flushes to the kernel when the buffer tops flush_bytes.
// One record = caller's bytes (caller includes the trailing newline).
int kkv_log_append(void* h, const char* data, long len) {
  auto* log = static_cast<AppendLog*>(h);
  if (!log || log->fd < 0 || len < 0) return -1;
  std::lock_guard<std::mutex> lk(log->mu);
  log->buf.append(data, static_cast<size_t>(len));
  if (log->buf.size() >= log->flush_bytes) {
    ssize_t n = write(log->fd, log->buf.data(), log->buf.size());
    if (n != static_cast<ssize_t>(log->buf.size())) return -1;
    log->buf.clear();
  }
  return 0;
}

// Drain the buffer to the kernel; fsync when do_fsync != 0 (group commit:
// many appends, one durability point).
int kkv_log_flush(void* h, int do_fsync) {
  auto* log = static_cast<AppendLog*>(h);
  if (!log || log->fd < 0) return -1;
  std::lock_guard<std::mutex> lk(log->mu);
  if (!log->buf.empty()) {
    ssize_t n = write(log->fd, log->buf.data(), log->buf.size());
    if (n != static_cast<ssize_t>(log->buf.size())) return -1;
    log->buf.clear();
  }
  if (do_fsync && fsync(log->fd) != 0) return -1;
  return 0;
}

void kkv_log_close(void* h) {
  auto* log = static_cast<AppendLog*>(h);
  if (!log) return;
  kkv_log_flush(h, 0);
  close(log->fd);
  delete log;
}

}  // extern "C"
