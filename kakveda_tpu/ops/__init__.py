"""Device kernels: featurization, kNN, clustering, health scoring.

This package is the native-performance tier of the framework — the JAX/XLA
replacement for the reference's sklearn TF-IDF + cosine path
(reference: services/shared/similarity.py:14-20) and its O(N)-per-query
match loop (reference: services/gfkb/app.py:79-102).
"""

from kakveda_tpu.ops.featurizer import HashedNGramFeaturizer  # noqa: F401
