"""Device-side clustering of failure embeddings.

Connected components of the threshold cosine-similarity graph, computed by
iterative min-label propagation — every step is a masked matmul-shaped op
that XLA maps onto the MXU/VPU, with a ``lax.while_loop`` until fixpoint:

    A      = (E @ E^T) >= threshold          # adjacency, [N, N]
    l_i    <- min_j { l_j : A[i, j] }        # propagate smallest label
    repeat until no label changes (≤ graph diameter iterations)

This replaces "pattern detection" as a group-by on failure_type
(reference: services/pattern_detector/app.py:40-47) with actual similarity
clustering over the index embeddings. Intended as a periodic batch job over
up to ~100k canonical failures (N² adjacency); larger indexes should mine
patterns over a recent window.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _propagate_labels(adj: jax.Array) -> jax.Array:
    n = adj.shape[0]
    init = jnp.arange(n, dtype=jnp.int32)

    def cond(state):
        labels, changed, it = state
        return jnp.logical_and(changed, it < n)

    def body(state):
        labels, _, it = state
        # min over neighbors' labels (self-edge keeps own label).
        big = jnp.iinfo(jnp.int32).max
        neigh = jnp.where(adj, labels[None, :], big)
        new = jnp.minimum(labels, jnp.min(neigh, axis=1))
        return new, jnp.any(new != labels), it + 1

    labels, _, _ = jax.lax.while_loop(cond, body, (init, jnp.bool_(True), jnp.int32(0)))
    return labels


def cluster_embeddings(vecs: np.ndarray, threshold: float = 0.6) -> np.ndarray:
    """Connected-component labels for L2-normalized embeddings [N, d].

    Returns int32 labels [N]; rows in the same component share a label
    (the smallest member index).
    """
    v = jnp.asarray(vecs, dtype=jnp.float32)
    sims = v @ v.T
    adj = sims >= threshold
    # Ensure self-edges so isolated rows keep their own label.
    adj = jnp.logical_or(adj, jnp.eye(v.shape[0], dtype=bool))
    return np.asarray(_propagate_labels(adj))
