"""Device-side clustering of failure embeddings.

Connected components of the threshold cosine-similarity graph, computed by
iterative min-label propagation — every step is matmul-shaped work that XLA
maps onto the MXU, with a ``lax.while_loop`` until fixpoint:

    l_i <- min over j with cos(v_i, v_j) >= t of l_j
    repeat until no label changes (≤ graph diameter iterations)

Two tiers sharing the same math:

- dense (N ≤ _DENSE_MAX): one [N, N] adjacency in memory;
- blocked (any N): the similarity matrix is never materialized — each
  iteration scans column blocks, computing ``v @ v_blockᵀ`` [N, B] tiles
  and folding a running per-row min of neighbor labels. Memory is O(N·B)
  instead of O(N²), so mining runs over the full GFKB at 1M rows (the
  reference's pattern detector is a group-by on failure_type,
  services/pattern_detector/app.py:40-47 — no similarity clustering at
  all).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_DENSE_MAX = 8192
_BLOCK = 1024
_BIG = jnp.iinfo(jnp.int32).max


@jax.jit
def _propagate_labels(adj: jax.Array) -> jax.Array:
    n = adj.shape[0]
    init = jnp.arange(n, dtype=jnp.int32)

    def cond(state):
        labels, changed, it = state
        return jnp.logical_and(changed, it < n)

    def body(state):
        labels, _, it = state
        # min over neighbors' labels (self-edge keeps own label).
        neigh = jnp.where(adj, labels[None, :], _BIG)
        new = jnp.minimum(labels, jnp.min(neigh, axis=1))
        return new, jnp.any(new != labels), it + 1

    labels, _, _ = jax.lax.while_loop(cond, body, (init, jnp.bool_(True), jnp.int32(0)))
    return labels


@jax.jit
def _propagate_labels_blocked(v: jax.Array, threshold: jax.Array, valid: jax.Array) -> jax.Array:
    """Blocked fixpoint: v is [Np, d] with Np a multiple of _BLOCK; ``valid``
    masks padding rows out of neighbor propagation (a traced array, so the
    compile cache keys only on the padded shape, not the exact row count)."""
    np_rows = v.shape[0]
    init = jnp.arange(np_rows, dtype=jnp.int32)
    vb = v.reshape(np_rows // _BLOCK, _BLOCK, v.shape[1])
    valid_b = valid.reshape(np_rows // _BLOCK, _BLOCK)

    def one_iteration(labels):
        lb = labels.reshape(np_rows // _BLOCK, _BLOCK)

        def scan_block(running_min, block):
            vj, lj, okj = block
            sims = jax.lax.dot_general(
                v, vj, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )  # [Np, B]
            neigh = jnp.where((sims >= threshold) & okj[None, :], lj[None, :], _BIG)
            return jnp.minimum(running_min, jnp.min(neigh, axis=1)), None

        mins, _ = jax.lax.scan(
            scan_block, jnp.full((np_rows,), _BIG, jnp.int32), (vb, lb, valid_b)
        )
        return jnp.minimum(labels, mins)

    def cond(state):
        labels, changed, it = state
        return jnp.logical_and(changed, it < np_rows)

    def body(state):
        labels, _, it = state
        new = one_iteration(labels)
        return new, jnp.any(new != labels), it + 1

    labels, _, _ = jax.lax.while_loop(cond, body, (init, jnp.bool_(True), jnp.int32(0)))
    return labels


def cluster_embeddings(vecs: np.ndarray, threshold: float = 0.6) -> np.ndarray:
    """Connected-component labels for L2-normalized embeddings [N, d].

    Returns int32 labels [N]; rows in the same component share a label
    (the smallest member index).
    """
    v = jnp.asarray(vecs, dtype=jnp.float32)
    n = v.shape[0]
    if n <= _DENSE_MAX:
        sims = v @ v.T
        adj = sims >= threshold
        # Ensure self-edges so isolated rows keep their own label.
        adj = jnp.logical_or(adj, jnp.eye(n, dtype=bool))
        return np.asarray(_propagate_labels(adj))

    pad = (-n) % _BLOCK
    if pad:
        v = jnp.concatenate([v, jnp.zeros((pad, v.shape[1]), v.dtype)], axis=0)
    valid = jnp.arange(v.shape[0]) < n  # pad rows never propagate labels
    labels = _propagate_labels_blocked(v, jnp.float32(threshold), valid)
    return np.asarray(labels[:n])
