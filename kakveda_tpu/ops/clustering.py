"""Device-side clustering of failure embeddings.

Connected components of the threshold cosine-similarity graph. Two tiers:

- **dense** (N ≤ _DENSE_MAX): one [N, N] adjacency + on-device min-label
  propagation to fixpoint — the small-N oracle.
- **kNN graph** (any N): ONE blocked top-k sweep builds a symmetric-union
  k-nearest-neighbor candidate graph (each row keeps its k best neighbors;
  an edge exists when either endpoint keeps the other), edges below the
  threshold are dropped, and connected components run on that sparse graph
  on host. Total device work is O(N²·d_c) for the single sweep — not per
  fixpoint iteration like a dense propagation — with d_c the candidate
  dim: full dim up to _EXACT_SWEEP_MAX rows, a random projection above it
  (candidates from the projection, every surviving edge re-scored at full
  dim, so edge *weights* are always exact; projection only affects which
  candidates are seen).

Graph-equivalence note: the union-kNN graph preserves the dense partition
whenever every row has ≤ k neighbors above threshold (then it IS the
threshold graph). Rows with more neighbors keep their k nearest, and
mutual-kNN chains keep real clusters connected; pathological merges that
hinge on a single pair ranked > k from both sides can split — the
documented approximation that buys 1M-row mining
(the reference's pattern detector is a group-by on failure_type,
services/pattern_detector/app.py:40-47 — no similarity clustering at all).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_DENSE_MAX = 8192
_BLOCK = 1024
# Query rows per device dispatch: each dispatch costs one device→host fetch
# (a fixed wire RTT on remote-attached TPUs), so bigger blocks amortize it.
_QBLOCK = 4096
_EXACT_SWEEP_MAX = 1 << 17  # full-dim candidate sweep up to 131k rows
_MINE_DIM = 256  # projection dim for the candidate sweep beyond that
_KNN_K = 32
_BIG = jnp.iinfo(jnp.int32).max


@jax.jit
def _propagate_labels(adj: jax.Array) -> jax.Array:
    n = adj.shape[0]
    init = jnp.arange(n, dtype=jnp.int32)

    def cond(state):
        labels, changed, it = state
        return jnp.logical_and(changed, it < n)

    def body(state):
        labels, _, it = state
        # min over neighbors' labels (self-edge keeps own label).
        neigh = jnp.where(adj, labels[None, :], _BIG)
        new = jnp.minimum(labels, jnp.min(neigh, axis=1))
        return new, jnp.any(new != labels), it + 1

    labels, _, _ = jax.lax.while_loop(cond, body, (init, jnp.bool_(True), jnp.int32(0)))
    return labels


@partial(jax.jit, static_argnames=("k",))
def _block_topk(q: jax.Array, v: jax.Array, valid: jax.Array, k: int):
    """Streaming top-k of ``q @ v.T`` without materializing [Q, N]: scan
    over column blocks collecting per-block candidates, then one exact
    merge. The per-block select uses ``approx_max_k`` — the TPU-native
    partial-reduce (an exact top-k on other backends); its <1 recall is
    candidate-level only and every surviving edge is exact-rescored by the
    caller. q [Q, d], v [Np, d] (Np multiple of _BLOCK), valid [Np]."""
    nb = v.shape[0] // _BLOCK
    vb = v.reshape(nb, _BLOCK, v.shape[1])
    validb = valid.reshape(nb, _BLOCK)
    bases = (jnp.arange(nb) * _BLOCK).astype(jnp.int32)
    kb = min(k, _BLOCK)

    def scan_fn(_, block):
        vj, okj, base = block
        sims = jax.lax.dot_general(
            q, vj, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [Q, B]
        sims = jnp.where(okj[None, :], sims, -jnp.inf)
        vals, idx = jax.lax.approx_max_k(sims, kb, recall_target=0.98)
        return None, (vals, (idx + base).astype(jnp.int32))

    _, (ys_v, ys_i) = jax.lax.scan(scan_fn, None, (vb, validb, bases))
    # [nb, Q, kb] -> [Q, nb*kb], exact merge down to k.
    q_rows = q.shape[0]
    flat_v = jnp.transpose(ys_v, (1, 0, 2)).reshape(q_rows, nb * kb)
    flat_i = jnp.transpose(ys_i, (1, 0, 2)).reshape(q_rows, nb * kb)
    bv, sel = jax.lax.top_k(flat_v, min(k, nb * kb))
    bi = jnp.take_along_axis(flat_i, sel, axis=1)
    # Pack (values, indices) into ONE output buffer => one host fetch per
    # dispatch (indices are exact in f32 up to 2^24 rows).
    return jnp.concatenate([bv, bi.astype(jnp.float32)], axis=1)


@partial(jax.jit, static_argnames=())
def _rescore_pairs(v: jax.Array, rows: jax.Array, cols: jax.Array) -> jax.Array:
    """Exact full-dim cosine for candidate pairs (embeddings are unit-norm)."""
    return jnp.sum(v[rows] * v[cols], axis=1)


def _project(v: jax.Array, out_dim: int) -> jax.Array:
    """Fixed-seed Gaussian random projection, re-normalized — preserves
    cosine ranking well enough for CANDIDATE generation (edges are
    re-scored exactly afterwards)."""
    r = jax.random.normal(jax.random.PRNGKey(7), (v.shape[1], out_dim), jnp.float32)
    p = v @ (r / np.sqrt(out_dim))
    return p / jnp.maximum(jnp.linalg.norm(p, axis=1, keepdims=True), 1e-12)


def _corpus_pad(n: int) -> int:
    """Padded corpus length for the blocked sweep: the next power of two
    (≥ _BLOCK). Padding only to the next _BLOCK multiple re-specializes
    ``_block_topk`` on every 1024-row boundary the GFKB crosses — O(N)
    compiles over a growing corpus; pow2 buckets make it O(log N), and the
    pad rows are valid-masked so results are identical. Thin wrapper over
    the ONE blessed bucket seam (``ops/knn.pow2_bucket``)."""
    from kakveda_tpu.ops.knn import pow2_bucket

    return pow2_bucket(n, floor=_BLOCK)


def build_knn_edges(
    vecs: np.ndarray, *, k: int = _KNN_K, threshold: float = 0.6,
    force_projection: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """(rows, cols) of the symmetric-union kNN graph restricted to exact
    cosine ≥ threshold. One blocked sweep; O(N·k) edges out.

    ``force_projection`` activates the random-projection candidate tier
    below its natural _EXACT_SWEEP_MAX switch-over — the recall tests use
    it to observe projection-tier behavior at CI-tractable sizes."""
    v = jnp.asarray(vecs, jnp.float32)
    n, d = v.shape
    kk = min(k + 1, n)  # +1: each row's own top-1 is itself

    exact = (n <= _EXACT_SWEEP_MAX or d <= _MINE_DIM) and not (
        force_projection and d > _MINE_DIM
    )
    vc = v if exact else _project(v, _MINE_DIM)

    total = _corpus_pad(n)  # bucketed corpus length — never size by raw n
    if total != n:
        vc_p = jnp.zeros((total, vc.shape[1]), vc.dtype).at[:n].set(vc)
    else:
        vc_p = vc
    valid = jnp.arange(total) < n

    # Dispatch every query block up front (async), then drain fetches — the
    # device computes block i+1 while the host pulls block i's packed
    # results, so the per-fetch wire RTT overlaps compute.
    pending = []
    for start in range(0, n, _QBLOCK):
        stop = min(start + _QBLOCK, n)
        q = vc[start:stop]
        if q.shape[0] < _QBLOCK:  # pad the last block to keep one compile
            q = jnp.concatenate([q, jnp.zeros((_QBLOCK - q.shape[0], q.shape[1]), q.dtype)])
        packed = _block_topk(q, vc_p, valid, kk)
        packed.copy_to_host_async()
        pending.append((start, stop, packed))

    rows_out, cols_out, sims_out = [], [], []
    for start, stop, dev in pending:
        packed = np.asarray(dev)[: stop - start]
        kk_eff = packed.shape[1] // 2  # ≤ kk when the padded index is tiny
        bv_h = packed[:, :kk_eff]
        bi_h = packed[:, kk_eff:].astype(np.int64)
        qi = np.repeat(np.arange(start, stop), kk_eff)
        ci = bi_h.reshape(-1)
        sv = bv_h.reshape(-1)
        keep = (ci != qi) & np.isfinite(sv)
        rows_out.append(qi[keep])
        cols_out.append(ci[keep])
        sims_out.append(sv[keep])

    rows = np.concatenate(rows_out) if rows_out else np.zeros(0, np.int64)
    cols = np.concatenate(cols_out) if cols_out else np.zeros(0, np.int64)
    sims = np.concatenate(sims_out) if sims_out else np.zeros(0, np.float32)

    if not exact:
        # Candidates came from the projection; re-score exactly, in chunks
        # that bound the gather memory (two [chunk, d] f32 gathers live per
        # dispatch — 128k × 2048 ≈ 1 GB each; 1M-pair chunks OOMed a 16 GB
        # chip).
        chunk = 1 << 17
        exact_sims = np.empty_like(sims)
        for s in range(0, len(rows), chunk):
            e = min(s + chunk, len(rows))
            exact_sims[s:e] = np.asarray(
                _rescore_pairs(v, jnp.asarray(rows[s:e]), jnp.asarray(cols[s:e]))
            )
        sims = exact_sims

    keep = sims >= threshold
    return rows[keep], cols[keep]


def _sparse_components(n: int, rows: np.ndarray, cols: np.ndarray) -> np.ndarray:
    """Connected components over an edge list; labels = min member index
    (the dense path's convention)."""
    try:
        from scipy.sparse import coo_matrix
        from scipy.sparse.csgraph import connected_components

        g = coo_matrix((np.ones(len(rows), np.int8), (rows, cols)), shape=(n, n))
        _, comp = connected_components(g, directed=False)
    except ImportError:  # vectorized host label propagation fallback
        comp = np.arange(n, dtype=np.int64)
        # undirected: propagate both ways each sweep
        r = np.concatenate([rows, cols])
        c = np.concatenate([cols, rows])
        while True:
            new = comp.copy()
            np.minimum.at(new, r, comp[c])
            if np.array_equal(new, comp):
                break
            comp = new
        return comp.astype(np.int32)

    mins = np.full(comp.max() + 1 if len(comp) else 0, np.iinfo(np.int64).max)
    np.minimum.at(mins, comp, np.arange(n))
    return mins[comp].astype(np.int32)


def cluster_embeddings(
    vecs: np.ndarray, threshold: float = 0.6, *, knn_k: int = _KNN_K,
    force_projection: bool = False,
) -> np.ndarray:
    """Connected-component labels for L2-normalized embeddings [N, d].

    Returns int32 labels [N]; rows in the same component share a label
    (the smallest member index).
    """
    v = jnp.asarray(vecs, dtype=jnp.float32)
    n = v.shape[0]
    if n == 0:
        return np.zeros(0, np.int32)
    if n <= _DENSE_MAX and not force_projection:
        sims = v @ v.T
        # SAME graph family as the large-N tier: union-top-k edges above
        # the threshold, not the raw threshold graph. The raw graph
        # transitively chains boilerplate-heavy corpora into one giant
        # component (observed: 120 templates → 2 clusters, purity 0.02 at
        # 5k rows, while the degree-capped tier is pure at every larger
        # scale) — so the degree cap is part of the clustering SEMANTICS,
        # scale-invariant across tiers, not an approximation artifact.
        k = min(knn_k + 1, n)  # +1: top-k includes the self-match
        vals, idx = jax.lax.top_k(sims, k)
        r = jnp.broadcast_to(jnp.arange(n)[:, None], (n, k))
        adj = jnp.zeros((n, n), bool).at[r, idx].set(vals >= threshold)
        adj = jnp.logical_or(adj, adj.T)  # symmetric union
        # Ensure self-edges so isolated rows keep their own label.
        adj = jnp.logical_or(adj, jnp.eye(n, dtype=bool))
        return np.asarray(_propagate_labels(adj))

    rows, cols = build_knn_edges(
        vecs, k=knn_k, threshold=threshold, force_projection=force_projection
    )
    return _sparse_components(n, rows, cols)
