"""Backend identity helpers.

The tunneled TPU registers as platform name ``"axon"`` (canonical
platform ``"tpu"`` — its MLIR lowerings and Pallas rules alias to tpu),
so ``jax.default_backend()`` may report either name depending on the
client. Every "are we on TPU hardware?" gate must accept both — a bare
``== "tpu"`` comparison silently disables the Pallas kernels and bf16
stores on the real chip.
"""

from __future__ import annotations

import jax

_TPU_NAMES = ("tpu", "axon")


def is_tpu_backend() -> bool:
    """True when the default backend is real TPU hardware (incl. the
    tunneled 'axon' platform)."""
    try:
        return jax.default_backend() in _TPU_NAMES
    except Exception:  # noqa: BLE001 — backend init failure means "no"
        return False
