"""Hashed n-gram text featurizer — fixed-dimension failure embeddings.

The reference scores similarity by re-fitting a TF-IDF vectorizer on
(query + full corpus) for every single match request
(reference: services/shared/similarity.py:14-20, called from
services/gfkb/app.py:81-89) — O(N·d) work per pre-flight check and
impossible to keep device-resident because the feature space changes with
every insert.

Here each signature text maps to a *fixed* d-dimensional vector via signed
feature hashing of word uni+bigrams (Weinberger et al., 2009 — "hashing
trick"), so:

  * embeddings are computed once at insert time and live in HBM;
  * a pre-flight match is one matmul + top-k on device;
  * the feature space never changes — no refit, no retrace.

Field-aware weighting replaces TF-IDF's idf as the discriminative mechanism:
signature texts lead with stable intent tags
(reference: services/shared/fingerprint.py:51-66), and tokens inside the
``intent_tags:`` field get a configurable weight boost so that prompts with
the same failure *shape* score high even when their wording differs — the
same determinism the reference gets from keeping tags as the primary TF-IDF
signal.

Hashing is zlib.crc32-based: stable across processes, platforms and
restarts, so an index snapshot is valid forever.
"""

from __future__ import annotations

import re
import zlib
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

_TOKEN_RE = re.compile(r"[a-z0-9_]+")

# Signature fields, in `signature_text` order. ``weight`` is the term-weight
# boost; ``atomic`` fields contribute whole comma-separated items as single
# features instead of word n-grams. Intent tags dominate (they are the stable
# cross-app signal, reference: services/shared/fingerprint.py:54-58); the
# prompt hint contributes wording detail; env keys are near-boilerplate and
# get muted so unrelated prompts sharing an environment don't look similar.
_FIELD_SPECS: Dict[str, Tuple[float, bool]] = {
    "intent_tags": (3.0, True),
    "prompt_hint": (1.0, False),
    "tools": (1.0, True),
    "env_keys": (0.25, True),
}

_FIELD_SPLIT = " | "


def _terms(text: str) -> List[str]:
    """Word unigrams + adjacent bigrams of the lowercased text."""
    words = _TOKEN_RE.findall(text.lower())
    grams = list(words)
    grams.extend(f"{a} {b}" for a, b in zip(words, words[1:]))
    return grams


def _hash_term(term: str) -> Tuple[int, float]:
    """Stable (bucket, sign) for a term via crc32."""
    h = zlib.crc32(term.encode("utf-8"))
    sign = 1.0 if (h >> 31) & 1 == 0 else -1.0
    return h & 0x7FFFFFFF, sign


class HashedNGramFeaturizer:
    """Signed feature hashing of word 1-2 grams into a fixed dim.

    ``dim`` must be a power of two (bucket = hash & (dim-1)). Stateless and
    thread-safe: terms hash directly (crc32 is cheaper than a memo dict, and
    a memo over arbitrary user prompts would grow without bound).
    """

    def __init__(
        self,
        dim: int = 2048,
        field_specs: Dict[str, Tuple[float, bool]] | None = None,
    ):
        if dim & (dim - 1) != 0:
            raise ValueError(f"dim must be a power of two, got {dim}")
        self.dim = dim
        self.field_specs = dict(field_specs or _FIELD_SPECS)

    def _bucket(self, term: str) -> Tuple[int, float]:
        h, sign = _hash_term(term)
        return h & (self.dim - 1), sign

    def _weighted_terms(self, text: str) -> List[Tuple[str, float]]:
        """(term, weight) features for one text.

        Segments of a signature text are recognized by their field prefix
        (``intent_tags:...``); the label itself is stripped so structural
        boilerplate never contributes similarity. Atomic fields emit each
        comma-separated item as a single feature (an intent tag is one
        indivisible signal, not a bag of words). Free-form text falls back to
        plain word n-grams at weight 1.0, so arbitrary strings embed too.
        """
        feats: List[Tuple[str, float]] = []
        for seg in text.split(_FIELD_SPLIT):
            name, sep, rest = seg.partition(":")
            spec = self.field_specs.get(name.strip().lower()) if sep else None
            if spec is None:
                feats.extend((t, 1.0) for t in _terms(seg))
                continue
            weight, atomic = spec
            if atomic:
                for item in rest.split(","):
                    item = item.strip().lower()
                    if item:
                        feats.append((f"{name}={item}", weight))
            else:
                feats.extend((t, weight) for t in _terms(rest))
        return feats

    def _native_spec(self) -> str:
        """Field specs serialized for the C++ encoder ("name,weight,atomic;…")."""
        return ";".join(
            f"{name},{weight!r},{1 if atomic else 0}"
            for name, (weight, atomic) in self.field_specs.items()
        )

    def encode(self, text: str) -> np.ndarray:
        """One L2-normalized float32 vector of shape [dim]."""
        return self.encode_batch([text])[0]

    def encode_batch(self, texts: Sequence[str]) -> np.ndarray:
        """[B, dim] float32, rows L2-normalized (zero row for empty text).

        ASCII batches take the C++ path (kakveda_tpu/native) when the
        library is available — same features, same crc32 buckets; non-ASCII
        strings fall back here because unicode lowercasing is
        Python-defined.
        """
        from kakveda_tpu import native

        lib = native.load()
        if lib is not None and all(isinstance(t, str) and t.isascii() for t in texts):
            return self._encode_batch_native(lib, texts)
        return self._encode_batch_py(texts)

    def _encode_batch_native(self, lib, texts: Sequence[str]) -> np.ndarray:
        import ctypes

        out = np.zeros((len(texts), self.dim), dtype=np.float32)
        arr = (ctypes.c_char_p * len(texts))(*[t.encode("ascii") for t in texts])
        rc = lib.kkv_encode_batch(
            arr,
            len(texts),
            self.dim,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._native_spec().encode("ascii"),
        )
        if rc != 0:
            return self._encode_batch_py(texts)
        return out

    def _encode_batch_py(self, texts: Sequence[str]) -> np.ndarray:
        out = np.zeros((len(texts), self.dim), dtype=np.float32)
        for i, text in enumerate(texts):
            row = out[i]
            for term, w in self._weighted_terms(text):
                b, sign = self._bucket(term)
                row[b] += sign * w
            n = float(np.linalg.norm(row))
            if n > 0.0:
                row /= n
        return out

    def encode_signatures(self, sigs: Iterable[str]) -> np.ndarray:
        return self.encode_batch(list(sigs))

    def encode_batch_sparse(
        self, texts: Sequence[str]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Sparse form of :meth:`encode_batch`: ``(idx [B,K] int32, val
        [B,K] f32)`` with rows padded to a power-of-two K (pad idx=dim → the
        device scatter drops it).

        A signature text touches ~30 of the ``dim`` buckets, so the dense
        [B, dim] form is ~98% zeros — shipping it host→device made insert
        transfer-bound (4 MB per 512-batch at dim=2048). The sparse pair is
        ~60× smaller; the index rows are densified *on device* by a
        scatter-add (ShardedKnn.insert_sparse). The C++ encoder emits the
        pairs directly; the Python fallback densifies then np.nonzero's.
        """
        from kakveda_tpu import native

        lib = native.load()
        if lib is not None and all(isinstance(t, str) and t.isascii() for t in texts):
            out = self._encode_sparse_native(lib, texts)
            if out is not None:
                return out
        return dense_rows_to_sparse(self.encode_batch(texts), self.dim)

    def _encode_sparse_native(
        self, lib, texts: Sequence[str]
    ) -> Tuple[np.ndarray, np.ndarray] | None:
        import ctypes

        b = len(texts)
        arr = (ctypes.c_char_p * b)(*[t.encode("ascii") for t in texts])
        k = 64
        while True:
            idx = np.full((b, k), self.dim, dtype=np.int32)
            val = np.zeros((b, k), dtype=np.float32)
            rc = lib.kkv_encode_sparse_batch(
                arr,
                b,
                self.dim,
                k,
                idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                val.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                self._native_spec().encode("ascii"),
            )
            if rc == 0:
                return idx, val
            if rc < 0:
                return None  # bad layout; fall back to Python
            while k < rc:  # rc = required K; re-encode with room
                k <<= 1


def dense_rows_to_sparse(dense: np.ndarray, dim: int) -> Tuple[np.ndarray, np.ndarray]:
    """Sparsify dense embedding rows into the (idx [B,K], val [B,K]) pair
    the device scatter consumes (pad idx = dim, the drop sentinel; K = a
    power of two ≥ the max row nnz). Shared by the Python sparse-encode
    fallback and the bulk restore/growth paths — hashed-ngram rows are
    ~98% zeros, so shipping them sparse cuts host→device bytes ~30×."""
    b = dense.shape[0]
    rows, cols = np.nonzero(dense)
    counts = np.bincount(rows, minlength=b)
    kmax = int(counts.max()) if b else 0
    # K floor of 64 matches the native encoder's starting width, so typical
    # multi-chunk restores stay on ONE compiled insert program instead of
    # retracing per distinct chunk-max-nnz.
    k = 64
    while k < kmax:
        k <<= 1
    idx = np.full((b, k), dim, dtype=np.int32)  # dim == drop sentinel
    val = np.zeros((b, k), dtype=np.float32)
    # Positions within each row: nonzero() walks row-major, so the
    # running offset of each (row, col) pair within its row is its rank.
    offs = np.arange(len(rows)) - np.concatenate(([0], np.cumsum(counts)))[rows]
    idx[rows, offs] = cols
    val[rows, offs] = dense[rows, cols]
    return idx, val
