"""Incremental streaming cluster state for pattern mining.

``ops/clustering.py`` answers "what are the clusters?" with one O(N²·d)
blocked sweep over the whole corpus — correct, but every
``mine_patterns()`` call re-pays the full corpus even though the GFKB is
append-only and the device already streams every new row through a top-k
for the warn path. This module makes clustering pay for the *delta*:

* :func:`delta_topk_sparse` / :func:`delta_topk_dense` — ONE device
  dispatch of a new batch against the resident index, reusing
  ``ops.clustering._block_topk`` with the batch as queries: O(ΔN·N·d)
  per batch instead of O(N²·d) per mine. The packed result is host-copied
  asynchronously; attachment drains later, so ingest never waits on a
  device→host fetch.
* :class:`ClusterState` — the host-side streaming mirror of the sweep's
  union-kNN graph: per-row top-k above-threshold neighbor lists,
  maintained under insertion (a new row stores its candidates AND is
  offered to each neighbor's list, evicting that list's worst entry).
  Unions are LAZY: ``refresh()`` runs connected components over the
  maintained edge set (+ the seeded base partition), so an early
  candidate that later rows crowd out never merges anything — eager
  unions would freeze prefix-view mistakes into the partition forever.
  Labels follow ``cluster_embeddings``' convention (smallest member
  index), so a refresh is directly comparable to a full sweep.

Graph equivalence: whenever every row's above-threshold degree is ≤ k,
no list ever evicts, every above-threshold pair (i, j) is recorded when
the later row arrives (i is necessarily in j's prefix top-k), and the
maintained graph IS the threshold graph — the incremental partition
equals the full sweep's exactly (property-tested in
tests/test_mine_incremental.py; bench.py asserts the same parity on its
20k-template corpus). Rows with more neighbors keep their k best — the
same degree-cap semantics ``cluster_embeddings`` applies in both of its
tiers. One monotonicity caveat: after a :meth:`seed`, the base partition
is carried as edges, so components never split until the next full sweep
(``mode="full"`` — the periodic audit) re-derives them; the pattern
store's union-merge semantics are monotone in the same way.

The class is dependency-free (numpy only) and thread-safe via one RLock;
metrics and fault sites live in the caller (index/gfkb.py) so this stays
importable from bench.py without the platform stack.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kakveda_tpu.ops.clustering import _BLOCK, _block_topk, _sparse_components
from kakveda_tpu.core import sanitize

__all__ = [
    "ClusterState",
    "delta_topk_sparse",
    "delta_topk_dense",
    "unpack_topk",
    "centroids_from_sparse",
    "collapse_groups",
]


def collapse_groups(
    labels, min_size: int, exclude: Iterable[int] = ()
) -> List[Tuple[int, List[int]]]:
    """Turn a label partition into duplicate-collapse work items.

    Groups slots by cluster label, drops excluded members (already
    tombstoned), and returns ``(exemplar, victims)`` pairs for every
    cluster with at least ``min_size`` LIVE members — the GFKB keeps the
    exemplar, folds the victims' occurrence counts into it and tombstones
    them (index/gfkb.py ``collapse_duplicates``). The exemplar is the
    smallest live slot, matching the min-member label convention (and the
    oldest record — stable across repeated collapse rounds). Pure numpy
    grouping; deterministic in label order."""
    excluded = set(int(s) for s in exclude)
    groups: Dict[int, List[int]] = {}
    for slot, lab in enumerate(np.asarray(labels).tolist()):
        if slot in excluded:
            continue
        groups.setdefault(int(lab), []).append(slot)
    out: List[Tuple[int, List[int]]] = []
    for lab in sorted(groups):
        members = groups[lab]  # appended in slot order → members[0] is min
        if len(members) < max(2, min_size):
            continue
        out.append((members[0], members[1:]))
    return out


def centroids_from_sparse(labels, rows_fn, dim: int, chunk: int = 1 << 14):
    """Export a label partition as coarse-quantizer state: one
    L2-normalized centroid per cluster, built from sparse member rows.

    This is the bridge between the incremental mining state (its
    :meth:`ClusterState.labels` partition — per-row cluster structure the
    platform already maintains) and the tiered index's IVF router
    (``index/tiers.py``): the router re-seeds its coarse partition from
    these exact member means instead of its online running estimates.

    ``rows_fn(slots) -> (idx [B, K] int32, val [B, K] f32)`` supplies the
    sparse rows (pad idx == ``dim``). Returns ``(centroids [C, dim] f32,
    counts [C] int64, lists, assign [n] int32)`` where ``assign`` maps
    each row to its dense centroid id and ``lists[c]`` are the member
    slots. Pure numpy, chunked so no dense [n, dim] ever materializes.
    """
    labels = np.asarray(labels)
    n = len(labels)
    uniq, assign = np.unique(labels, return_inverse=True)
    c = len(uniq)
    sums = np.zeros((c, dim), np.float32)
    counts = np.bincount(assign, minlength=c).astype(np.int64)
    for s in range(0, n, chunk):
        e = min(n, s + chunk)
        idx, val = rows_fn(np.arange(s, e, dtype=np.int64))
        keep = idx < dim
        rows_lab = np.broadcast_to(assign[s:e, None], idx.shape)[keep]
        np.add.at(sums, (rows_lab, idx[keep]), val[keep])
    norms = np.linalg.norm(sums, axis=1, keepdims=True)
    cents = np.divide(sums, norms, out=np.zeros_like(sums), where=norms > 0)
    lists: list = [[] for _ in range(c)]
    for slot, a in enumerate(assign.tolist()):
        lists[a].append(slot)
    return cents, counts, lists, assign.astype(np.int32)


# ---------------------------------------------------------------------------
# delta top-k dispatch (device)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("k",))
def _delta_topk_sparse_jit(emb, valid, idx, val, k):
    """Densify sparse (idx, val) queries on device and run the blocked
    top-k against the resident index buffer. The corpus side is padded to
    a _BLOCK multiple inside the program (compile-time shapes), so the
    index capacity never has to be block-aligned."""
    b = idx.shape[0]
    dim = emb.shape[1]
    q = jnp.zeros((b, dim), jnp.float32).at[jnp.arange(b)[:, None], idx].add(
        val, mode="drop"
    )
    q = q.astype(emb.dtype)
    pad = (-emb.shape[0]) % _BLOCK
    if pad:
        emb = jnp.concatenate([emb, jnp.zeros((pad, dim), emb.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), jnp.bool_)])
    return _block_topk(q, emb, valid, k)


@partial(jax.jit, static_argnames=("k",))
def _delta_topk_dense_jit(q, v, n_valid, k):
    """Dense-query variant for pre-resident corpora (bench streaming arm):
    rows [0, n_valid) are live, the rest are padding. ``n_valid`` is a
    traced scalar so the growing stream reuses ONE compiled program."""
    valid = jnp.arange(v.shape[0]) < n_valid
    return _block_topk(q.astype(v.dtype), v, valid, k)


def _bucket(b: int) -> int:
    from kakveda_tpu.ops.knn import batch_bucket

    return batch_bucket(max(b, 1))


def delta_topk_sparse(
    emb: jax.Array, valid: jax.Array, idx: np.ndarray, val: np.ndarray, k: int
) -> jax.Array:
    """Dispatch one delta top-k of a sparse-encoded batch against the
    index; returns the packed [B, 2k'] device buffer with the host copy
    already started (fetch with :func:`unpack_topk`). Batch pads to a
    power-of-two bucket so ragged ingest batches never retrace."""
    b = idx.shape[0]
    bb = _bucket(b)
    if b != bb:
        pad_i = np.full((bb, idx.shape[1]), emb.shape[1], np.int32)
        pad_v = np.zeros((bb, val.shape[1]), np.float32)
        pad_i[:b] = idx
        pad_v[:b] = val
        idx, val = pad_i, pad_v
    packed = _delta_topk_sparse_jit(
        emb, valid, jnp.asarray(np.ascontiguousarray(idx)),
        jnp.asarray(np.ascontiguousarray(val)), k
    )
    packed.copy_to_host_async()
    return packed


def delta_topk_dense(q: jax.Array, v: jax.Array, n_valid: int, k: int) -> jax.Array:
    """Dense-query delta dispatch (bench streaming arm). ``v`` must be
    pre-padded to a _BLOCK multiple; ``q`` to a constant batch shape."""
    packed = _delta_topk_dense_jit(q, v, jnp.asarray(n_valid, jnp.int32), k)
    packed.copy_to_host_async()
    return packed


def unpack_topk(packed, b: int) -> Tuple[np.ndarray, np.ndarray]:
    """(scores [b, k'], row-indices [b, k'] int64) from a packed buffer.
    Indices are raw rows of the queried buffer (physical rows for a
    sharded index — the caller maps them to logical slots)."""
    host = np.asarray(packed)[:b]
    kk = host.shape[1] // 2
    return host[:, :kk], host[:, kk:].astype(np.int64)


# ---------------------------------------------------------------------------
# streaming cluster state (host)
# ---------------------------------------------------------------------------


class ClusterState:
    """Streaming mirror of the union-kNN clustering graph.

    Per-row state: the k best above-threshold neighbors seen so far
    (ids + sims, evict-worst on overflow) plus optional pattern metadata
    (failure type / id / apps). ``refresh()`` materializes labels by
    running connected components over every stored edge plus the seeded
    base partition, caches them, and tracks which clusters changed since
    the last :meth:`pop_dirty` — the set ``mine_patterns`` re-emits.

    ``stale`` latches when the state can no longer be trusted (failed
    restore, attach fault, replay tail with unseen rows) — the owner
    falls back to ONE full sweep and re-seeds via :meth:`seed`. Never
    serve labels from a stale state.
    """

    _GROW = 1024

    def __init__(self, threshold: float = 0.6, k: int = 32):
        self.threshold = float(threshold)
        self.k = int(k)
        self._lock = sanitize.named_lock("ClusterState._lock", kind="rlock")
        self._n = 0
        self._ids = np.full((0, self.k), -1, np.int64)
        self._sims = np.full((0, self.k), -np.inf, np.float32)
        # Base partition from the last full sweep / restore: rows
        # [0, len) carry an implicit edge to their base label.
        self._base = np.zeros(0, np.int32)
        # Optional per-row pattern metadata (None for bench-style rows).
        self._ftype: List[Optional[str]] = []
        self._fid: List[Optional[str]] = []
        self._apps: List[set] = []
        self._touched: set = set()
        self._dirty_labels: set = set()
        self._cached_labels: Optional[np.ndarray] = None
        self._prev_labels = np.zeros(0, np.int32)
        self.stale = False
        self.stale_reason: Optional[str] = None
        self.attached = 0
        self.evictions = 0
        self.merges = 0

    # --- mutation --------------------------------------------------------

    @property
    def n_rows(self) -> int:
        return self._n

    @property
    def n_clusters(self) -> int:
        with self._lock:
            return int(len(np.unique(self._labels_locked())))

    def mark_stale(self, reason: str) -> None:
        with self._lock:
            self.stale = True
            self.stale_reason = reason

    def _grow_to(self, n: int) -> None:
        if n <= len(self._ids):
            return
        cap = max(n, len(self._ids) + self._GROW, 2 * len(self._ids))
        ids = np.full((cap, self.k), -1, np.int64)
        sims = np.full((cap, self.k), -np.inf, np.float32)
        ids[: len(self._ids)] = self._ids
        sims[: len(self._sims)] = self._sims
        self._ids, self._sims = ids, sims

    def add_row(
        self,
        slot: int,
        failure_type: Optional[str] = None,
        failure_id: Optional[str] = None,
        apps: Iterable[str] = (),
    ) -> None:
        """Register a new slot. Slots must arrive in order (GFKB appends
        them densely); a gap means the caller missed rows and the state
        is no longer trustworthy."""
        with self._lock:
            if slot < self._n:
                return  # idempotent re-add
            if slot != self._n:
                self.mark_stale(f"non-contiguous slot {slot} (have {self._n})")
                return
            self._grow_to(slot + 1)
            self._n = slot + 1
            self._ftype.append(failure_type)
            self._fid.append(failure_id)
            self._apps.append(set(apps))
            self._touched.add(slot)
            self._cached_labels = None

    def note_apps(self, slot: int, apps: Iterable[str]) -> None:
        """A version update widened a record's affected apps — membership
        is unchanged, the cluster aggregate isn't."""
        with self._lock:
            if slot >= self._n:
                return
            new = set(apps) - self._apps[slot]
            if new:
                self._apps[slot] |= new
                self._touched.add(slot)

    def attach(self, slot: int, neigh: Sequence[int], sims: Sequence[float]) -> int:
        """Record ``slot``'s above-threshold candidates (its delta top-k,
        best-first): they become its neighbor list, and ``slot`` is
        offered to each neighbor's list (replacing that list's worst
        entry when better — the streaming analogue of the full sweep's
        per-row degree cap). Returns edges stored."""
        stored = 0
        with self._lock:
            if slot >= self._n:
                return 0
            ids, sims_a = self._ids, self._sims
            row_i, row_s = ids[slot], sims_a[slot]
            # Vectorized prefilter: attach batches arrive straight from the
            # (native) host-tier scorer with most candidates below the
            # threshold — drop them in one pass instead of per-candidate
            # Python float checks. Survivor order is preserved, so the
            # evict-worst walk below behaves exactly as before.
            neigh_a = np.asarray(neigh, np.int64)
            sims_f = np.asarray(sims, np.float32)
            keep = (
                np.isfinite(sims_f)
                & (sims_f >= self.threshold)
                & (neigh_a != slot)
                & (neigh_a >= 0)
                & (neigh_a < self._n)
            )
            for j, s in zip(neigh_a[keep].tolist(), sims_f[keep].tolist()):
                # slot's own list (candidates arrive best-first)
                w = int(np.argmin(row_s))
                if s > row_s[w]:
                    if row_i[w] >= 0:
                        self.evictions += 1
                    row_i[w], row_s[w] = j, s
                    stored += 1
                # reverse offer into j's list
                nb_i, nb_s = ids[j], sims_a[j]
                w = int(np.argmin(nb_s))
                if s > nb_s[w]:
                    if nb_i[w] >= 0:
                        self.evictions += 1
                        self._touched.add(j)
                    nb_i[w], nb_s[w] = slot, s
            self.attached += 1
            self._touched.add(slot)
            self._cached_labels = None
        return stored

    def seed(
        self,
        labels: np.ndarray,
        meta: Optional[Sequence[Tuple[str, str, Iterable[str]]]] = None,
        threshold: Optional[float] = None,
    ) -> None:
        """Reset the state from a full-sweep result: the labels become the
        base partition (carried as edges), neighbor lists clear, dirty
        clears — a full sweep just emitted everything."""
        labels = np.asarray(labels, np.int32)
        with self._lock:
            n = len(labels)
            self._n = n
            self._ids = np.full((n, self.k), -1, np.int64)
            self._sims = np.full((n, self.k), -np.inf, np.float32)
            self._base = labels.copy()
            self._ftype = [None] * n
            self._fid = [None] * n
            self._apps = [set() for _ in range(n)]
            if meta is not None:
                for i, (ftype, fid, apps) in enumerate(meta):
                    self._ftype[i] = ftype
                    self._fid[i] = fid
                    self._apps[i] = set(apps)
            self._touched = set()
            self._dirty_labels = set()
            self._prev_labels = labels.copy()
            self._cached_labels = labels.copy()
            if threshold is not None:
                self.threshold = float(threshold)
            self.stale = False
            self.stale_reason = None

    # --- refresh / read --------------------------------------------------

    def _labels_locked(self) -> np.ndarray:
        if self._cached_labels is not None:
            return self._cached_labels
        n = self._n
        live = self._ids[:n]
        mask = live >= 0
        rows = np.broadcast_to(np.arange(n, dtype=np.int64)[:, None], live.shape)[mask]
        cols = live[mask]
        nb = len(self._base)
        if nb:
            rows = np.concatenate([rows, np.arange(nb, dtype=np.int64)])
            cols = np.concatenate([cols, self._base.astype(np.int64)])
        labels = _sparse_components(n, rows, cols)
        # dirty = clusters holding any touched row, under BOTH the old and
        # the new labeling (a merge dirties the surviving cluster; rows
        # whose label flipped dirty their new home)
        m = min(len(self._prev_labels), n)
        changed = set(int(r) for r in self._touched if r < n)
        if m:
            changed.update(int(r) for r in np.flatnonzero(labels[:m] != self._prev_labels[:m]))
            # clusters whose old root lost its identity merged into another
            prev_roots = np.unique(self._prev_labels[:m])
            self.merges += int(np.count_nonzero(labels[prev_roots] != prev_roots))
        self._dirty_labels.update(int(labels[r]) for r in changed)
        self._prev_labels = labels
        self._touched = set()
        self._cached_labels = labels
        return labels

    def labels(self) -> np.ndarray:
        """Materialized int32 labels [n_rows], min-member convention —
        byte-comparable with ``cluster_embeddings`` output. Cached until
        the next mutation; the refresh is one vectorized
        connected-components pass over O(N·k) edges, never a device
        sweep."""
        with self._lock:
            return self._labels_locked().copy()

    def pop_dirty(self) -> List[dict]:
        """Aggregate snapshots (apps / type counts / failure ids / member
        count) of every cluster touched since the last call; clears the
        dirty set. Aggregates are built only for dirty clusters — O(dirty
        members), not O(N)."""
        with self._lock:
            labels = self._labels_locked()
            dirty = sorted(
                d for d in self._dirty_labels if d < self._n and labels[d] == d
            )
            self._dirty_labels = set()
            if not dirty:
                return []
            sel = np.flatnonzero(np.isin(labels, np.asarray(dirty, labels.dtype)))
            groups: Dict[int, List[int]] = {}
            for r in sel:
                groups.setdefault(int(labels[r]), []).append(int(r))
            out = []
            for lbl in dirty:
                members = groups.get(lbl)
                if not members:
                    continue
                apps: set = set()
                types: Dict[str, int] = {}
                fids: set = set()
                for r in members:
                    apps |= self._apps[r]
                    ft = self._ftype[r]
                    if ft is not None:
                        types[ft] = types.get(ft, 0) + 1
                    if self._fid[r]:
                        fids.add(self._fid[r])
                out.append(
                    {
                        "label": lbl,
                        "apps": sorted(apps),
                        "types": types,
                        "fids": sorted(fids),
                        "n": len(members),
                    }
                )
            return out

    def n_clusters_cached(self) -> Optional[int]:
        """Cluster count without forcing a refresh (None when labels are
        not currently cached) — for cheap gauge updates on hot paths."""
        with self._lock:
            if self._cached_labels is None:
                return None
            return int(len(np.unique(self._cached_labels)))

    def info(self) -> dict:
        with self._lock:
            return {
                "rows": self._n,
                "clusters": self.n_clusters_cached(),
                "dirty": len(self._dirty_labels) + len(self._touched),
                "attached": self.attached,
                "evictions": self.evictions,
                "merges": self.merges,
                "stale": self.stale,
                "stale_reason": self.stale_reason,
                "threshold": self.threshold,
                "k": self.k,
            }
