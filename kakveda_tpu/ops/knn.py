"""Sharded cosine top-k over a device-resident embedding matrix.

This is the kernel that replaces the reference's entire match path —
load-all-JSONL + pydantic validate + TF-IDF refit + sklearn cosine per query
(reference: services/gfkb/app.py:79-102, services/shared/similarity.py:14-20)
— with one compiled device program:

    scores = Q @ E^T          (MXU matmul, f32 accumulation)
    local top-k per shard     (lax.top_k)
    all_gather(k·n candidates) over ICI, merge with a second top-k

The embedding matrix is row-sharded over the mesh's ``data`` axis with
*round-robin* slot placement (slot ``s`` lives on shard ``s % n``), so every
shard does equal matmul work regardless of how full the index is. All shapes
are static: capacity is fixed at allocation, queries are padded to bucketed
batch sizes by the caller, so the hot path never retraces.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kakveda_tpu.core import ledger
from kakveda_tpu.ops import pallas_knn
from kakveda_tpu.parallel.mesh import shard_map as _shard_map

# Sentinel below any reachable cosine score (valid range [-1, 1]).
_NEG = -2.0


def slot_to_physical(slots: np.ndarray, n_shards: int, rows_per_shard: int) -> np.ndarray:
    """Logical insert slot -> physical row in the [capacity, d] array.

    Round-robin: slot s -> shard s % n, row-in-shard s // n. Keeps shard load
    balanced while the index fills.
    """
    return (slots % n_shards) * rows_per_shard + slots // n_shards


def physical_to_slot(phys: np.ndarray, n_shards: int, rows_per_shard: int) -> np.ndarray:
    shard = phys // rows_per_shard
    row = phys % rows_per_shard
    return row * n_shards + shard


class ShardedKnn:
    """Compiled insert + cosine-top-k over a sharded [capacity, dim] matrix.

    Owns no state: callers (kakveda_tpu.index.gfkb.DeviceIndex) hold the
    (embeddings, valid) device arrays and thread them through ``insert`` /
    ``topk``. ``insert`` donates its buffers, so updates are in-place in HBM.
    """

    def __init__(
        self,
        mesh: Mesh,
        capacity: int,
        dim: int,
        k: int = 5,
        store_dtype: jnp.dtype | None = None,
        shard_axis: str = "data",
        use_pallas: bool | None = None,
    ):
        if shard_axis not in mesh.axis_names:
            raise ValueError(f"mesh has no axis {shard_axis!r}: {mesh.axis_names}")
        self.mesh = mesh
        self.axis = shard_axis
        self.n_shards = mesh.shape[shard_axis]
        if capacity % self.n_shards != 0:
            capacity += self.n_shards - capacity % self.n_shards
        self.dim = dim
        self.k = k

        # Fused Pallas match kernel (ops/pallas_knn.py): on by default on TPU
        # when the layout qualifies; KAKVEDA_PALLAS=0|1|interpret overrides
        # ("interpret" runs the kernel through the Pallas interpreter so the
        # CPU test suite exercises the exact kernel logic).
        env = os.environ.get("KAKVEDA_PALLAS", "auto").lower()
        self._pallas_interpret = env == "interpret"
        if use_pallas is None:
            if env == "auto":
                from kakveda_tpu.ops.device import is_tpu_backend

                use_pallas = is_tpu_backend()
            else:
                use_pallas = env not in ("0", "false", "off")
        rows = capacity // self.n_shards
        tile = pallas_knn.DEFAULT_ROW_TILE
        if (
            use_pallas
            and dim % 128 == 0
            and capacity >= tile * self.n_shards
            and k <= pallas_knn._KPAD
        ):
            rows = -(-rows // tile) * tile  # per-shard rows to a tile multiple
            capacity = rows * self.n_shards
            self.use_pallas = True
            self._pallas_tile = tile
        else:
            self.use_pallas = False
            self._pallas_tile = tile
        self.capacity = capacity
        self.rows_per_shard = rows
        if store_dtype is None:
            from kakveda_tpu.ops.device import is_tpu_backend

            store_dtype = jnp.bfloat16 if is_tpu_backend() else jnp.float32
        self.store_dtype = store_dtype

        # Single-device meshes take a plain-jit path: identical math, no
        # shard_map / NamedSharding. Besides being the natural degenerate
        # case, this sidesteps a pathology of the remote-TPU (axon) runtime
        # where dispatches of mesh-sharded programs degrade to ~70 ms after
        # the first host fetch of a NamedSharding-backed output.
        if capacity > (1 << 24):
            raise ValueError(
                f"capacity {capacity} exceeds 2^24: packed f32 row indices "
                "would lose precision (widen _pack before raising this limit)"
            )
        self.single_device = mesh.devices.size == 1
        if self.single_device:
            self._device = mesh.devices.flat[0]
            sharding = jax.sharding.SingleDeviceSharding(self._device)
            self._emb_sharding = sharding
            self._valid_sharding = sharding
            self._repl = sharding
            self._topk = jax.jit(self._topk_single_impl)
            self._topk_sparse = jax.jit(
                lambda e, v, i, x: self._topk_single_impl(e, v, self._densify_q(i, x))
            )
        else:
            self._emb_sharding = NamedSharding(mesh, P(shard_axis, None))
            self._valid_sharding = NamedSharding(mesh, P(shard_axis))
            self._repl = NamedSharding(mesh, P())
            self._topk = jax.jit(self._topk_impl)
            self._topk_sparse = jax.jit(
                lambda e, v, i, x: self._topk_impl(e, v, self._densify_q(i, x))
            )
        self._insert = jax.jit(self._insert_impl, donate_argnums=(0, 1))
        self._insert_sparse = jax.jit(self._insert_sparse_impl, donate_argnums=(0, 1, 2))
        # Int32 side-table (per-slot failure-type ids) sharded like `valid`:
        # scattered on insert, AND-ed into the valid mask for device-side
        # type-filtered matches.
        self._scatter_i32_jit = jax.jit(
            lambda a, rows, vals: a.at[rows].set(vals, mode="drop"), donate_argnums=(0,)
        )
        self._mask_jit = jax.jit(lambda valid, types, tid: valid & (types == tid))
        # Allocation happens INSIDE jit with explicit output shardings: under
        # multi-controller JAX (process_count > 1) no single host could
        # device_put a full [capacity, dim] host array onto the global mesh —
        # and even single-host this skips a host→device transfer of zeros.
        cap = self.capacity
        sd = self.store_dtype
        self._alloc_jit = jax.jit(
            lambda: (jnp.zeros((cap, dim), sd), jnp.zeros((cap,), jnp.bool_)),
            out_shardings=(self._emb_sharding, self._valid_sharding),
        )
        self._alloc_i32_jit = jax.jit(
            lambda: jnp.full((cap,), -1, jnp.int32), out_shardings=self._valid_sharding
        )
        # Persistent jit (shape-keyed cache) for the snapshot gather — a
        # fresh wrapper per call would recompile every snapshot. Replicated
        # output so every process can read the gathered rows to host.
        self._gather = jax.jit(lambda e, p: e[p].astype(jnp.float32), out_shardings=self._repl)
        self._copy = jax.jit(jnp.copy)

    def device_copy(self, emb: jax.Array) -> jax.Array:
        """Device-side copy of the embedding buffer (fast HBM copy) so
        callers can release their lock before the slow host transfer."""
        return self._copy(emb)

    # --- allocation ------------------------------------------------------

    def alloc(self) -> Tuple[jax.Array, jax.Array]:
        """Fresh (embeddings, valid) buffers on the mesh, zeroed."""
        return self._alloc_jit()

    def alloc_i32(self) -> jax.Array:
        """Fresh per-slot int32 side-table (-1 = unset), sharded like valid."""
        return self._alloc_i32_jit()

    def _replicate(self, x: np.ndarray) -> jax.Array:
        """Host array → replicated device array. Every process passes the
        same value (the SPMD contract: all hosts see the same log/queries),
        which is exactly what device_put-to-replicated supports under
        multi-controller JAX."""
        ledger.note_transfer("h2d", getattr(x, "nbytes", 0))
        return jax.device_put(x, self._repl)

    def scatter_i32(self, arr: jax.Array, slots: np.ndarray, values: np.ndarray) -> jax.Array:
        """Write int32 values at logical slots (donates ``arr``)."""
        phys = slot_to_physical(np.asarray(slots, dtype=np.int32), self.n_shards, self.rows_per_shard)
        return self._scatter_i32_jit(
            arr, self._replicate(phys), self._replicate(np.asarray(values, np.int32))
        )

    def mask_valid(self, valid: jax.Array, types: jax.Array, type_id: int) -> jax.Array:
        """valid AND (types == type_id) — the device-side pre-selection mask
        for type-filtered matches. ``type_id`` stays a Python scalar so it
        replicates implicitly on any mesh."""
        return self._mask_jit(valid, types, type_id)

    # --- insert ----------------------------------------------------------

    def _insert_impl(self, emb, valid, vecs, phys_rows):
        emb = emb.at[phys_rows].set(vecs.astype(emb.dtype), mode="drop")
        valid = valid.at[phys_rows].set(True, mode="drop")
        return emb, valid

    def insert(
        self,
        emb: jax.Array,
        valid: jax.Array,
        vecs: np.ndarray,
        slots: np.ndarray,
    ) -> Tuple[jax.Array, jax.Array]:
        """Write rows for logical ``slots`` (new inserts or version updates)."""
        phys = slot_to_physical(np.asarray(slots, dtype=np.int32), self.n_shards, self.rows_per_shard)
        vecs_d = self._replicate(np.asarray(vecs, dtype=np.float32))
        return self._insert(emb, valid, vecs_d, self._replicate(phys))

    def _insert_sparse_impl(self, emb, valid, types, idx, val, phys_rows, tids):
        # Pad entries carry idx == dim → dropped by the densify scatter;
        # pad rows carry phys == capacity → dropped by the row scatter.
        rows = self._densify_q(idx, val)
        emb = emb.at[phys_rows].set(rows.astype(emb.dtype), mode="drop")
        valid = valid.at[phys_rows].set(True, mode="drop")
        types = types.at[phys_rows].set(tids, mode="drop")
        return emb, valid, types

    def insert_sparse(
        self,
        emb: jax.Array,
        valid: jax.Array,
        types: jax.Array,
        idx: np.ndarray,  # [B, K] int32 bucket ids (pad = dim)
        val: np.ndarray,  # [B, K] f32 weights (pad = 0)
        slots: np.ndarray,
        tids: np.ndarray,
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Sparse-row insert: ships (idx, val) pairs instead of dense [B, dim]
        rows — hashed n-gram embeddings are ~98% zeros, so this cuts the
        host→device transfer of the streaming-ingest path ~60×. Rows are
        densified on device by a scatter-add, and the per-slot type-id
        side-table is scattered in the same program (one dispatch per batch,
        not three). Batch is padded to a power-of-two bucket so the jit
        never retraces on ragged tail batches."""
        b = len(slots)
        bb = batch_bucket(max(b, 1))
        phys = np.full((bb,), self.capacity, dtype=np.int32)  # pad = drop
        phys[:b] = slot_to_physical(
            np.asarray(slots, dtype=np.int32), self.n_shards, self.rows_per_shard
        )
        tids_p = np.full((bb,), -1, dtype=np.int32)
        tids_p[:b] = np.asarray(tids, np.int32)
        if idx.shape[0] != bb:
            pad_i = np.full((bb, idx.shape[1]), self.dim, dtype=np.int32)
            pad_v = np.zeros((bb, idx.shape[1]), dtype=np.float32)
            pad_i[:b] = idx
            pad_v[:b] = val
            idx, val = pad_i, pad_v
        return self._insert_sparse(
            emb,
            valid,
            types,
            self._replicate(np.ascontiguousarray(idx)),
            self._replicate(np.ascontiguousarray(val)),
            self._replicate(phys),
            self._replicate(tids_p),
        )

    def gather_slots(self, emb: jax.Array, slots: np.ndarray) -> np.ndarray:
        """Host copy of the embedding rows for logical ``slots`` (snapshot
        path). Chunked so a 1M-row gather never materializes a second
        full-size host buffer at once."""
        phys = slot_to_physical(np.asarray(slots, dtype=np.int32), self.n_shards, self.rows_per_shard)
        out = np.empty((len(phys), self.dim), dtype=np.float32)
        chunk = 1 << 16
        for i in range(0, len(phys), chunk):
            out[i : i + chunk] = np.asarray(self._gather(emb, self._replicate(phys[i : i + chunk])))
        return out

    # --- match -----------------------------------------------------------

    @staticmethod
    def _pack(vals: jax.Array, phys: jax.Array) -> jax.Array:
        """Fuse (scores, rows) into one [B, 2k] f32 buffer.

        One output buffer means one device→host fetch per match call — on
        remote-attached TPUs each fetch pays a fixed wire RTT, so halving
        fetches halves the latency floor. Row indices are exact in f32 up to
        2^24 (capacities beyond 16M rows would need a wider packing).
        """
        return jnp.concatenate([vals, phys.astype(jnp.float32)], axis=1)

    def _local_topk(self, emb, valid, q):
        """Per-shard (scores, rows): fused Pallas kernel when enabled, else
        matmul + lax.top_k. Identical results either way (same tie-break)."""
        if self.use_pallas:
            return pallas_knn.fused_topk(
                emb, valid, q, k=self.k,
                row_tile=self._pallas_tile, interpret=self._pallas_interpret,
            )
        scores = jax.lax.dot_general(
            q.astype(emb.dtype),
            emb,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        scores = jnp.where(valid[None, :], scores, _NEG)
        return jax.lax.top_k(scores, min(self.k, emb.shape[0]))

    def _topk_single_impl(self, emb, valid, q):
        """Degenerate one-shard path: one local top-k, plain jit."""
        vals, idx = self._local_topk(emb, valid, q)
        return self._pack(vals, idx)

    def _topk_impl(self, emb, valid, q):
        k = self.k

        def local(emb_l, valid_l, q_l):
            # [B, kk] local candidates from this shard's rows.
            vals, idx = self._local_topk(emb_l, valid_l, q_l)
            kk = vals.shape[1]
            shard = jax.lax.axis_index(self.axis)
            phys = idx + shard * emb_l.shape[0]
            # Gather every shard's candidates, merge with a second top-k.
            all_vals = jax.lax.all_gather(vals, self.axis, axis=0)  # [n, B, kk]
            all_phys = jax.lax.all_gather(phys, self.axis, axis=0)
            n = all_vals.shape[0]
            B = all_vals.shape[1]
            flat_vals = jnp.transpose(all_vals, (1, 0, 2)).reshape(B, n * kk)
            flat_phys = jnp.transpose(all_phys, (1, 0, 2)).reshape(B, n * kk)
            mvals, midx = jax.lax.top_k(flat_vals, min(k, n * kk))
            mphys = jnp.take_along_axis(flat_phys, midx, axis=1)
            return self._pack(mvals, mphys)

        # check_vma=False: after the all_gather every shard computes the
        # identical merged top-k, so the outputs are replicated by
        # construction, but the static analysis can't prove it.
        return _shard_map(
            local,
            mesh=self.mesh,
            in_specs=(P(self.axis, None), P(self.axis), P()),
            out_specs=P(),
            check_vma=False,
        )(emb, valid, q)

    def topk_async(self, emb: jax.Array, valid: jax.Array, q: np.ndarray) -> jax.Array:
        """Dispatch a match and start the host copy; returns the packed
        [B, 2k] device buffer. Pair with ``topk_result`` — lets a serving
        loop pipeline batch i's compute with batch i-1's fetch."""
        qd = jax.device_put(jnp.asarray(q, dtype=jnp.float32), self._repl)
        packed = self._topk(emb, valid, qd)
        packed.copy_to_host_async()
        return packed

    def _densify_q(self, idx: jax.Array, val: jax.Array) -> jax.Array:
        b = idx.shape[0]
        q = jnp.zeros((b, self.dim), jnp.float32)
        return q.at[jnp.arange(b)[:, None], idx].add(val, mode="drop")

    def topk_async_sparse(
        self, emb: jax.Array, valid: jax.Array, idx: np.ndarray, val: np.ndarray
    ) -> jax.Array:
        """Sparse-query dispatch: ships (idx, val) pairs — ~60× smaller
        than dense hashed-ngram rows — and densifies on device before the
        same top-k (identical results to ``topk_async``). The query upload
        is part of every pre-flight check's wire cost, so this matters on
        remote-attached chips the same way insert_sparse does for ingest.
        The batch pads to a power-of-two bucket internally (pad rows carry
        idx == dim, the densify drop sentinel) so ragged batches never
        retrace — same contract as insert_sparse. Result rows beyond the
        caller's batch belong to pad rows: an all-zero query scores 0.0
        against every valid index row, so callers must SLICE results to
        their batch size (a score threshold cannot identify pad rows)."""
        b = idx.shape[0]
        bb = batch_bucket(max(b, 1))
        if b != bb:
            pad_i = np.full((bb, idx.shape[1]), self.dim, np.int32)
            pad_v = np.zeros((bb, val.shape[1]), np.float32)
            pad_i[:b] = idx
            pad_v[:b] = val
            idx, val = pad_i, pad_v
        packed = self._topk_sparse(
            emb,
            valid,
            self._replicate(np.ascontiguousarray(idx)),
            self._replicate(np.ascontiguousarray(val)),
        )
        packed.copy_to_host_async()
        return packed

    def topk_result(self, packed: jax.Array) -> Tuple[np.ndarray, np.ndarray]:
        """(scores, logical slots) from a ``topk_async`` buffer."""
        host = np.asarray(packed)
        ledger.note_transfer("d2h", host.nbytes)
        kk = host.shape[1] // 2
        vals = host[:, :kk]
        phys = host[:, kk:].astype(np.int64)
        if self.single_device:
            return vals, phys  # physical row == logical slot on one shard
        return vals, physical_to_slot(phys, self.n_shards, self.rows_per_shard)

    def topk(self, emb: jax.Array, valid: jax.Array, q: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Top-k (scores, logical slots) for a [B, dim] query batch."""
        return self.topk_result(self.topk_async(emb, valid, q))


def pow2_bucket(n: int, *, floor: int = 1, cap: int | None = None) -> int:
    """THE blessed pow2-bucket seam: smallest power-of-two ≥ ``n`` starting
    from ``floor`` (itself a power of two), optionally clamped to ``cap``.

    Every data-dependent Python size that becomes a jit argument shape must
    round through here (directly or via the thin wrappers ``batch_bucket``,
    ``generate._bucket_len``, ``ContinuousBatcher.bucket_for``) — bucketed
    shapes bound distinct lowerings to O(log N) while exact-fit shapes
    retrace per distinct size, and on the tunneled TPU one retrace costs
    more than the kernel it wraps. The static ``retrace-hazard`` rule
    (kakveda_tpu/analysis/device.py) recognizes exactly this seam; the
    runtime ledger (core/ledger.py) cross-checks the compile counts.
    """
    b = floor
    while b < n:
        b <<= 1
    return b if cap is None else min(b, cap)


@functools.lru_cache(maxsize=8)
def batch_bucket(b: int) -> int:
    """Pad query batches to power-of-two buckets so jit never retraces."""
    return pow2_bucket(b)
