"""Pallas TPU kernel: fused cosine-score + streaming top-k for GFKB match.

The XLA path (ops/knn.py) computes ``scores = Q @ E^T`` then
``lax.top_k(scores)`` — correct, but it materializes the full ``[B, N]``
f32 score matrix in HBM (256 MB at B=64, N=1M) and pays a second full pass
over it for the top-k. This kernel fuses the two: the index streams through
VMEM in row tiles, each tile's scores live only in VMEM, and a small
per-tile top-k (k ≤ 8 candidates per tile) is all that ever reaches HBM —
``[n_tiles, B, 8]`` instead of ``[B, N]``, ~250× less score traffic. The
candidate merge is one cheap ``lax.top_k`` over ``[B, n_tiles·8]``.

Replaces (with ops/knn.py) the reference's whole match path: load-all-JSONL
+ TF-IDF refit + sklearn cosine per query (reference:
services/gfkb/app.py:79-102, services/shared/similarity.py:14-20).

Layout requirements (callers fall back to the XLA path otherwise):
rows % row_tile == 0, dim % 128 == 0, and on hardware row_tile % 1024 == 0
(XLA tiles 1-D f32 arrays at T(1024), and the occupancy-mask block must
align with it; the interpreter has no such constraint, so CPU tests may use
small tiles). Query batch is padded to a multiple of 8 (f32 sublane)
internally. Tie-breaking matches ``lax.top_k``: equal scores resolve to the
lowest row index.

Measured on v5e-1 at 999k×2048 bf16, B=64: 9.0 ms/batch vs 10.7 ms for the
XLA matmul+top_k — ~1.2× faster and without the [B, N] f32 score
materialization (256 MB of HBM scratch the Llama serving path would
otherwise contend with).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Sentinel below any reachable cosine score (valid range [-1, 1]).
_NEG = -2.0
# Per-tile candidate lanes: k ≤ _KPAD, padded so the output's last dim is
# a fixed small constant (Mosaic pads lanes to 128 internally either way).
_KPAD = 8
DEFAULT_ROW_TILE = 1024


def _tile_kernel(q_ref, emb_ref, valid_ref, vals_ref, idx_ref, *, k: int):
    """One grid step: score this row tile and emit its top-k candidates.

    q_ref:    [B, D]   queries (f32, replicated across steps)
    emb_ref:  [T, D]   this tile's index rows (store dtype)
    valid_ref:[T]      occupancy mask for the tile (f32 0/1; narrow dtypes hit
                       Mosaic bitwidth-change limits on 1-D vectors)
    vals_ref: [1, B, _KPAD] out: candidate scores (pad lanes = _NEG)
    idx_ref:  [1, B, _KPAD] out: candidate row ids *within the shard*
    """
    t = pl.program_id(0)
    rows = emb_ref.shape[0]

    # [B, T] cosine scores on the MXU, f32 accumulation.
    scores = jax.lax.dot_general(
        q_ref[:].astype(emb_ref.dtype),
        emb_ref[:],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # Arithmetic mask (no dtype change): v==1 keeps the score, v==0 -> _NEG.
    v = valid_ref[:][None, :]
    scores = scores * v + (1.0 - v) * _NEG

    b = scores.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, (b, rows), 1)
    base = t * rows

    # Iterative top-k (k is small and static): extract the max, mask it,
    # repeat. First-occurrence tie-break == lax.top_k semantics.
    vcols = []
    icols = []
    for _ in range(k):
        m = jnp.max(scores, axis=1, keepdims=True)  # [B, 1]
        first = jnp.min(
            jnp.where(scores >= m, col, rows), axis=1, keepdims=True
        )  # [B, 1] lowest argmax
        vcols.append(m)
        icols.append(first + base)
        scores = jnp.where(col == first, _NEG, scores)

    if k < _KPAD:
        vcols.append(jnp.full((b, _KPAD - k), _NEG, jnp.float32))
        icols.append(jnp.zeros((b, _KPAD - k), jnp.int32))
    vals_ref[0] = jnp.concatenate(vcols, axis=1)
    idx_ref[0] = jnp.concatenate(icols, axis=1)


@functools.partial(
    jax.jit, static_argnames=("k", "row_tile", "interpret")
)
def fused_topk(
    emb: jax.Array,
    valid: jax.Array,
    q: jax.Array,
    *,
    k: int,
    row_tile: int = DEFAULT_ROW_TILE,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k (scores [B,k] f32, row ids [B,k] i32) of ``q @ emb^T``.

    ``emb`` [rows, dim] (rows % row_tile == 0, dim % 128 == 0), ``valid``
    [rows] bool/int occupancy, ``q`` [B, dim] f32. Also usable inside
    shard_map on a per-shard basis (row ids are shard-local).
    """
    rows, dim = emb.shape
    if rows % row_tile or dim % 128:
        raise ValueError(f"bad layout for pallas knn: rows={rows} tile={row_tile} dim={dim}")
    if not 1 <= k <= _KPAD:
        raise ValueError(f"k={k} not in [1, {_KPAD}]")
    n_tiles = rows // row_tile

    b = q.shape[0]
    bpad = max(8, -(-b // 8) * 8)
    if bpad != b:
        q = jnp.pad(q, ((0, bpad - b), (0, 0)))

    valid_f = valid.astype(jnp.float32)

    vals, idx = pl.pallas_call(
        functools.partial(_tile_kernel, k=k),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((bpad, dim), lambda t: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((row_tile, dim), lambda t: (t, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((row_tile,), lambda t: (t,), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bpad, _KPAD), lambda t: (t, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bpad, _KPAD), lambda t: (t, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_tiles, bpad, _KPAD), jnp.float32),
            jax.ShapeDtypeStruct((n_tiles, bpad, _KPAD), jnp.int32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * bpad * rows * dim,
            bytes_accessed=rows * dim * emb.dtype.itemsize
            + bpad * dim * 4
            + n_tiles * bpad * _KPAD * 8,
            transcendentals=0,
        ),
        interpret=interpret,
    )(q, emb, valid_f)

    # Merge the per-tile candidates: [n_tiles, B, KPAD] -> [B, n_tiles*KPAD].
    flat_vals = jnp.transpose(vals, (1, 0, 2)).reshape(bpad, n_tiles * _KPAD)
    flat_idx = jnp.transpose(idx, (1, 0, 2)).reshape(bpad, n_tiles * _KPAD)
    kk = min(k, n_tiles * _KPAD)
    mvals, margs = jax.lax.top_k(flat_vals, kk)
    midx = jnp.take_along_axis(flat_idx, margs, axis=1)
    return mvals[:b], midx[:b]


def supports(rows: int, dim: int, row_tile: int = DEFAULT_ROW_TILE) -> bool:
    """Whether the fused kernel's layout constraints hold."""
    return rows % row_tile == 0 and dim % 128 == 0 and rows >= row_tile
