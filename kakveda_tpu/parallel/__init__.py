"""Device-mesh + collective utilities.

The reference's "distributed backend" is an HTTP pub/sub bus with best-effort
fan-out (reference: services/event_bus/app.py:25-54). Here, device-side state
(the GFKB embedding index, pattern labels) is sharded over a
``jax.sharding.Mesh`` and kept coherent with XLA collectives over ICI —
all_gather for cross-shard top-k merge, psum for global statistics — while a
host-side asyncio bus (kakveda_tpu.events) keeps the external integration
contract.
"""

from kakveda_tpu.parallel.mesh import create_mesh, local_device_count, parse_mesh_shape  # noqa: F401
