"""Multi-host initialization: one logical mesh spanning TPU slices.

The reference's "distributed backend" is an HTTP pub/sub bus on one node
(reference: services/event_bus/app.py:25-54; SURVEY §2.9/§5.8). Here the
scaling backend is JAX's runtime: on a multi-host slice (or multiple
slices over DCN), every host calls :func:`initialize_multihost` before
touching devices, after which ``jax.devices()`` spans the whole pod and
the platform's `Mesh` (row-sharded GFKB index, TP/DP/CP Llama) extends
across hosts with XLA inserting ICI/DCN collectives — no NCCL/MPI code
anywhere in this tree.

Configuration (all three required to opt in, matching
``jax.distributed.initialize``):

- ``KAKVEDA_COORDINATOR``   — host:port of process 0
- ``KAKVEDA_NUM_PROCESSES`` — world size
- ``KAKVEDA_PROCESS_ID``    — this host's rank

On TPU pods with standard metadata (GKE/QueuedResources), the variables
may all be omitted AND ``KAKVEDA_MULTIHOST=auto`` set: jax.distributed
then self-configures from the TPU environment.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger("kakveda.distributed")


def multihost_config() -> Optional[dict]:
    """Parse env into initialize() kwargs; None when not configured.
    Raises ValueError on a partial configuration — silently running
    single-host when the operator set 2 of 3 variables would strand the
    other hosts at a barrier."""
    mh = os.environ.get("KAKVEDA_MULTIHOST", "").strip().lower()
    coord = os.environ.get("KAKVEDA_COORDINATOR")
    nproc = os.environ.get("KAKVEDA_NUM_PROCESSES")
    pid = os.environ.get("KAKVEDA_PROCESS_ID")
    if mh in ("0", "false", "off", "no"):
        return None  # explicit kill switch, even with coordinator vars set
    if mh not in ("", "auto", "1", "true", "yes"):
        # A typo'd opt-in must fail loudly — silently booting single-host
        # strands every other pod host at the collective barrier.
        raise ValueError(f"KAKVEDA_MULTIHOST={mh!r} not understood (use 'auto' or 0)")
    enabled = mh != ""
    present = [v is not None for v in (coord, nproc, pid)]
    if all(present):
        # Explicit coordinator config always wins over metadata autodetect.
        return {
            "coordinator_address": coord,
            "num_processes": int(nproc),
            "process_id": int(pid),
        }
    if enabled:
        # Autodetect was requested: a stray partial var (orchestrators often
        # export one of them) must not block boot — metadata wins.
        return {}
    if any(present):
        raise ValueError(
            "partial multi-host config: set all of KAKVEDA_COORDINATOR, "
            "KAKVEDA_NUM_PROCESSES, KAKVEDA_PROCESS_ID (or KAKVEDA_MULTIHOST=auto)"
        )
    return None


def put_global(x, sharding):
    """Place a host-global array onto a (possibly multi-process) sharding.

    Single-process shardings take the fast device_put path; on a
    multi-controller mesh each process materializes only its addressable
    shards via ``make_array_from_callback`` (every process holds the same
    host-global ``x`` — checkpoint loads and log replays are replicated
    host work in this architecture)."""
    import jax

    if sharding.is_fully_addressable:
        return jax.device_put(x, sharding)
    return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])


def initialize_multihost() -> bool:
    """Initialize jax.distributed when configured; returns True when the
    process joined a multi-host world. Must run before the first device
    touch (mesh creation, jax.devices())."""
    cfg = multihost_config()
    if cfg is None:
        return False
    import jax

    jax.distributed.initialize(**cfg)
    log.info(
        "multi-host initialized: process %d/%d, %d global devices",
        jax.process_index(),
        jax.process_count(),
        len(jax.devices()),
    )
    return True
