"""Mesh construction for the intelligence core and the model runtime.

Axis conventions used across the framework:

  * ``data``  — GFKB index row shards / batch parallelism for trace
    classification (the intelligence-core mesh).
  * ``dp`` / ``cp`` / ``tp`` — data, context (sequence) and tensor
    parallelism for the in-tree Llama model runtime
    (kakveda_tpu.models.llama).

Mesh shape strings look like ``"data:-1"`` or ``"dp:2,cp:2,tp:2"``; a ``-1``
size absorbs all remaining devices (like a reshape wildcard).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def local_device_count() -> int:
    return len(jax.devices())


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions — ONE compat seam for every
    manual-collective in the tree (ops/knn.py, models/llama.py,
    models/pipeline.py).

    jax < 0.5 only ships it as ``jax.experimental.shard_map.shard_map``
    with the replication check named ``check_rep`` (same semantics as the
    promoted API's ``check_vma``). Without this seam the whole warn path
    — and everything downstream of a sharded top-k — dies at dispatch
    time on such versions with ``AttributeError: module 'jax' has no
    attribute 'shard_map'``."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def parse_mesh_shape(spec: str, n_devices: int | None = None) -> Dict[str, int]:
    """Parse ``"dp:2,tp:-1"`` into an ordered {axis: size} dict.

    At most one axis may be -1; it is resolved so the product equals
    ``n_devices``.
    """
    n = n_devices if n_devices is not None else local_device_count()
    axes: Dict[str, int] = {}
    for part in spec.split(","):
        name, _, size = part.strip().partition(":")
        if not name or not size:
            raise ValueError(f"bad mesh axis spec: {part!r}")
        axes[name] = int(size)

    wild = [k for k, v in axes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one -1 axis allowed: {spec!r}")
    fixed = int(np.prod([v for v in axes.values() if v != -1])) if axes else 1
    if wild:
        if n % fixed != 0:
            raise ValueError(f"{n} devices not divisible by fixed axes {fixed}")
        axes[wild[0]] = n // fixed
    elif fixed != n:
        raise ValueError(f"mesh {spec!r} wants {fixed} devices, have {n}")
    return axes


def create_mesh(
    spec: str = "data:-1",
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh from a shape spec.

    A fully-fixed spec smaller than the device count uses a prefix of the
    devices (handy for single-device paths and tests); a ``-1`` wildcard
    absorbs all of them.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if "-1" not in spec:
        fixed = int(np.prod([int(p.split(":")[1]) for p in spec.split(",")]))
        if fixed < len(devs):
            devs = devs[:fixed]
    axes = parse_mesh_shape(spec, len(devs))
    names: Tuple[str, ...] = tuple(axes.keys())
    shape: List[int] = [axes[k] for k in names]
    arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, axis_names=names)
