"""Intelligence pipeline: classification, patterns, warnings, health.

TPU-first re-design of the reference's L3 reactor services
(reference: services/failure_classifier/, pattern_detector/,
warning_policy/, health_scoring/) — batched ops over the device-resident
GFKB instead of per-event HTTP hops.
"""

from kakveda_tpu.pipeline.classifier import RuleClassifier, classify_trace  # noqa: F401
from kakveda_tpu.pipeline.warning import WarningPolicy  # noqa: F401
from kakveda_tpu.pipeline.patterns import PatternDetector  # noqa: F401
from kakveda_tpu.pipeline.health_score import HealthScorer  # noqa: F401
