"""Failure classification over ingested traces.

Rule tier mirrors the reference's demo classifier
(reference: services/failure_classifier/app.py:30-91): a trace whose prompt
asks for citations and whose response contains citation markers is a
``HALLUCINATION_CITATION`` (medium severity) — deterministic, hermetic, and
the backbone of the e2e tests. Designed batch-first: ``classify_batch``
processes whole trace batches for the 10k traces/sec streaming path, and an
optional LLM classifier tier (kakveda_tpu.models) can re-judge ambiguous
traces on-device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

from kakveda_tpu.core.fingerprint import detect_citation_markers, prompt_intent_tags
from kakveda_tpu.core.schemas import FailureSignal, Severity, TracePayload

HALLUCINATION_CITATION = "HALLUCINATION_CITATION"

_ROOT_CAUSE = "Model produced citations without provided sources"
_MITIGATION = "Ask model to explicitly say 'no sources available' when none are provided"


def _wants_citations(prompt: str) -> bool:
    # Keyword list matches the reference classifier exactly
    # (reference: services/failure_classifier/app.py:35-46); the intent
    # tagger uses the same vocabulary, so reuse it.
    return "intent:citations_required" in prompt_intent_tags(prompt)


def classify_trace(trace: TracePayload) -> Optional[FailureSignal]:
    """Single-trace rule classification; None when the trace looks healthy."""
    if not (_wants_citations(trace.prompt) and detect_citation_markers(trace.response).has_citation_markers):
        return None
    return FailureSignal(
        trace_id=trace.trace_id,
        ts=trace.ts,
        app_id=trace.app_id,
        failure_type=HALLUCINATION_CITATION,
        severity=Severity.medium,
        root_cause=_ROOT_CAUSE,
        mitigation=_MITIGATION,
        context_signature={
            "prompt_shape": trace.prompt[:200],
            "model": trace.model,
            "tools": trace.tools,
            "env": trace.env,
        },
    )


@dataclass
class RuleClassifier:
    """Batch rule classifier for the streaming ingest path."""

    def classify_batch(self, traces: Sequence[TracePayload]) -> List[Optional[FailureSignal]]:
        return [classify_trace(t) for t in traces]
