"""Failure classification over ingested traces.

Rule tier mirrors the reference's demo classifier
(reference: services/failure_classifier/app.py:30-91): a trace whose prompt
asks for citations and whose response contains citation markers is a
``HALLUCINATION_CITATION`` (medium severity) — deterministic, hermetic, and
the backbone of the e2e tests. Designed batch-first: ``classify_batch``
processes whole trace batches for the 10k traces/sec streaming path, and an
optional LLM classifier tier (kakveda_tpu.models) can re-judge ambiguous
traces on-device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

from kakveda_tpu.core.fingerprint import detect_citation_markers, prompt_intent_tags
from kakveda_tpu.core.schemas import FailureSignal, Severity, TracePayload

HALLUCINATION_CITATION = "HALLUCINATION_CITATION"

_ROOT_CAUSE = "Model produced citations without provided sources"
_MITIGATION = "Ask model to explicitly say 'no sources available' when none are provided"


def _wants_citations(prompt: str) -> bool:
    # Keyword list matches the reference classifier exactly
    # (reference: services/failure_classifier/app.py:35-46); the intent
    # tagger uses the same vocabulary, so reuse it.
    return "intent:citations_required" in prompt_intent_tags(prompt)


def classify_trace(trace: TracePayload) -> Optional[FailureSignal]:
    """Single-trace rule classification; None when the trace looks healthy."""
    if not (_wants_citations(trace.prompt) and detect_citation_markers(trace.response).has_citation_markers):
        return None
    # model_construct: every field comes straight off an already-validated
    # TracePayload; skipping re-validation matters at streaming rates.
    return FailureSignal.model_construct(
        trace_id=trace.trace_id,
        ts=trace.ts,
        app_id=trace.app_id,
        failure_type=HALLUCINATION_CITATION,
        severity=Severity.medium,
        root_cause=_ROOT_CAUSE,
        mitigation=_MITIGATION,
        context_signature={
            "prompt_shape": trace.prompt[:200],
            "model": trace.model,
            "tools": trace.tools,
            "env": trace.env,
        },
    )


@dataclass
class RuleClassifier:
    """Batch rule classifier for the streaming ingest path."""

    def classify_batch(self, traces: Sequence[TracePayload]) -> List[Optional[FailureSignal]]:
        return [classify_trace(t) for t in traces]


_JUDGE_PROMPT = (
    "You are a failure auditor for LLM applications. The user asked:\n"
    "---\n{prompt}\n---\n"
    "The model answered:\n---\n{response}\n---\n"
    "The user's request called for citations. Did the model FABRICATE "
    "citations or references that were not provided to it? Answer with "
    "exactly one word, YES or NO."
)


def parse_judge_verdict(text: str) -> Optional[bool]:
    """First YES/NO token of a judge reply; None when unparseable.

    The deterministic stub's canned response parses to None, so under the
    hermetic runtime the LLM tier never overrides the rule verdict — the
    same no-LLM degradation the reference gets from its stub fallback
    (reference: services/dashboard/app.py:1193-1199).
    """
    for tok in text.upper().replace(".", " ").replace(",", " ").split():
        if tok == "YES":
            return True
        if tok == "NO":
            return False
    return None


@dataclass
class TieredClassifier:
    """Rule tier first; an LLM judge re-checks the ambiguous remainder.

    "Ambiguous" = the prompt demanded citations but the marker regex found
    none — the case the reference's rule classifier silently passes
    (reference: services/failure_classifier/app.py:34-50) even though the
    response may fabricate sources in an unmarked format. Rule verdicts are
    never overridden: the LLM only *adds* failures, so the deterministic
    e2e outcomes are preserved under any runtime.

    ``runtime`` is any ModelRuntime — on TPU the in-tree Llama shares the
    mesh with the GFKB index, so judging is an on-pod forward pass, not an
    HTTP hop.
    """

    runtime: "object"  # ModelRuntime protocol (generate())
    max_judge_chars: int = 2000
    _prefix_registered: bool = False

    def _register_judge_prefix(self) -> None:
        """Register the fixed head of the judge template as a serving
        prefix (once): every judge call shares it, so the serving engine
        prefills only the per-trace remainder. Best-effort — runtimes
        without prefix support (stub, Ollama) just skip."""
        if self._prefix_registered:
            return
        reg = getattr(self.runtime, "register_prefix", None)
        if callable(reg):
            try:
                reg(_JUDGE_PROMPT.split("{prompt}")[0])
            except Exception:  # noqa: BLE001 — registration is an optimization only
                pass
        self._prefix_registered = True

    def classify_batch(self, traces: Sequence[TracePayload]) -> List[Optional[FailureSignal]]:
        out = RuleClassifier().classify_batch(traces)
        ambiguous = [
            i
            for i, (trace, sig) in enumerate(zip(traces, out))
            if sig is None and _wants_citations(trace.prompt)
        ]
        if not ambiguous:
            return out
        judge_prompts = [
            _JUDGE_PROMPT.format(
                prompt=traces[i].prompt[: self.max_judge_chars],
                response=traces[i].response[: self.max_judge_chars],
            )
            for i in ambiguous
        ]
        self._register_judge_prefix()
        # One decode stream for the whole ambiguous set when the runtime
        # supports batching (the TPU Llama does); per-prompt otherwise.
        batch_fn = getattr(self.runtime, "generate_batch", None)
        if callable(batch_fn):
            verdicts = batch_fn(judge_prompts, max_tokens=4)
        else:
            verdicts = [self.runtime.generate(p, max_tokens=4) for p in judge_prompts]
        for i, judge in zip(ambiguous, verdicts):
            if not parse_judge_verdict(judge.text):
                continue
            trace = traces[i]
            out[i] = FailureSignal(
                trace_id=trace.trace_id,
                ts=trace.ts,
                app_id=trace.app_id,
                failure_type=HALLUCINATION_CITATION,
                severity=Severity.medium,
                root_cause=_ROOT_CAUSE + " (LLM-judged, unmarked format)",
                mitigation=_MITIGATION,
                context_signature={
                    "prompt_shape": trace.prompt[:200],
                    "model": trace.model,
                    "tools": trace.tools,
                    "env": trace.env,
                    "judge": {"provider": judge.meta.get("provider"), "verdict": "YES"},
                },
            )
        return out
