"""Per-app health scoring over a rolling failure window.

Scoring math matches the reference exactly
(reference: services/health_scoring/app.py:58-108):

    weighted          = Σ severity_weight over the window (≤50 events)
    failure_rate      = min(1, n / 10)
    recurrent_penalty = Σ_type max(0, count-1) * 2.5
    avg_recovery      = 30 + 10 * recurrent_penalty   (placeholder metric)
    score             = max(0, base − 5·weighted − recurrent_penalty)

Severity weights and base come from hot-reloaded config
(reference: config/config.yaml:8-13). Points append to ``health.jsonl`` —
durable-by-append like every other store. ``on_failures_batch`` is the
streaming entry: one config read and one file append per batch.
"""

from __future__ import annotations

import json
import os
import threading
from collections import defaultdict, deque
from pathlib import Path
from typing import Deque, Dict, List, Optional

from kakveda_tpu.core.config import ConfigStore
from kakveda_tpu.core.schemas import FailureSignal, HealthPoint, utcnow
from kakveda_tpu.core import sanitize

WINDOW = 50
EXECUTIONS_PER_WINDOW = 10.0
RECURRENCE_UNIT = 2.5
WEIGHT_SCALE = 5.0


class _AppWindow:
    """Rolling window with incrementally-maintained aggregates: the score
    math needs Σweight and per-type counts over the last ≤50 events, and
    recomputing those per event is the streaming path's hottest host loop."""

    __slots__ = ("events", "weighted", "counts")

    def __init__(self) -> None:
        self.events: Deque[dict] = deque()
        self.weighted: float = 0.0
        self.counts: Dict[str, int] = defaultdict(int)

    def push(self, event: dict) -> None:
        self.events.append(event)
        self.weighted += event["weight"]
        self.counts[str(event["failure_type"])] += 1
        if len(self.events) > WINDOW:
            old = self.events.popleft()
            self.weighted -= old["weight"]
            ft = str(old["failure_type"])
            self.counts[ft] -= 1
            if self.counts[ft] == 0:
                del self.counts[ft]


class HealthScorer:
    def __init__(
        self,
        data_dir: str | Path = "data",
        config: Optional[ConfigStore] = None,
        persist: bool = True,
    ):
        self.config = config or ConfigStore()
        self.persist = persist
        self.data_dir = Path(data_dir)
        if persist:
            self.data_dir.mkdir(parents=True, exist_ok=True)
        self.health_path = self.data_dir / "health.jsonl"
        self._windows: Dict[str, _AppWindow] = defaultdict(_AppWindow)
        self._lock = sanitize.named_lock("HealthScorer._lock")

    def _append_all(self, points: List[HealthPoint]) -> None:
        if not self.persist or not points:
            return
        with self.health_path.open("a", encoding="utf-8") as f:
            # pydantic's C serializer straight to JSON — no intermediate dict
            # or Python json encoder on the streaming path.
            f.write("".join(p.model_dump_json() + "\n" for p in points))

    def _score_one(self, failure: FailureSignal, weights: Dict[str, float], base: float) -> HealthPoint:
        """Window update + score math; caller holds the lock and owns I/O."""
        w = float(weights.get(failure.severity.value, 1.0))
        window = self._windows[failure.app_id]
        window.push(
            {
                "severity": failure.severity.value,
                "weight": w,
                "failure_type": failure.failure_type,
            }
        )
        n = len(window.events)
        counts = window.counts
        # Σ_type max(0, count-1) over counts where every count ≥ 1 reduces
        # to (total events − distinct types).
        recurrent_penalty = (n - len(counts)) * RECURRENCE_UNIT
        score = max(0.0, base - window.weighted * WEIGHT_SCALE - recurrent_penalty)
        last = window.events[-1]

        # model_construct: fields are built here with correct types; skipping
        # validation keeps the streaming path off the pydantic hot loop.
        return HealthPoint.model_construct(
            ts=utcnow(),
            app_id=failure.app_id,
            score=score,
            failure_rate=min(1.0, n / EXECUTIONS_PER_WINDOW),
            recurrent_penalty=recurrent_penalty,
            avg_recovery_time_sec=30.0 + 10.0 * recurrent_penalty,
            notes={
                "window_failures": n,
                "weighted": window.weighted,
                "top_failure": max(counts, key=counts.get) if counts else None,
                "last_failure": last["failure_type"],
                "last_severity": last["severity"],
            },
        )

    def on_failure(self, failure: FailureSignal) -> HealthPoint:
        return self.on_failures_batch([failure])[0]

    def on_failures_batch(self, failures: List[FailureSignal]) -> List[HealthPoint]:
        """Streaming-path batch entry: one config read and one JSONL append
        for the whole batch, in order."""
        weights = self.config.severity_weights()
        base = self.config.base_score()
        with self._lock:
            points = [self._score_one(f, weights, base) for f in failures]
        self._append_all(points)
        return points

    def history(self, app_id: str, limit: int = 50) -> List[dict]:
        """Tail of the persisted health timeline for one app
        (reference: services/health_scoring/app.py:116-130).

        Reads the log BACKWARDS in fixed-size chunks and stops as soon as
        ``limit`` matching points are found — at streaming-ingest rates the
        file grows without bound, and the reference's read-everything
        approach makes every dashboard health view O(all points ever). Cost
        here is O(tail) for any app actively emitting points (worst case
        one full pass for an app absent from the log)."""
        if not self.health_path.exists():
            return []
        pts: List[dict] = []
        chunk_size = 1 << 16
        with self.health_path.open("rb") as f:
            f.seek(0, os.SEEK_END)
            pos = f.tell()
            carry = b""
            while pos > 0 and len(pts) < limit:
                step = min(chunk_size, pos)
                pos -= step
                f.seek(pos)
                block = f.read(step) + carry
                lines = block.split(b"\n")
                # The first piece may be a partial line continued in the
                # previous (earlier) chunk — carry it into the next read.
                carry = lines[0] if pos > 0 else b""
                start = 1 if pos > 0 else 0
                for line in reversed(lines[start:]):
                    if len(pts) >= limit:
                        break
                    if not line.strip():
                        continue
                    try:
                        obj = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if obj.get("app_id") == app_id:
                        pts.append(obj)
        pts.reverse()  # back to chronological order
        return pts
