"""Pattern detection: recurring failures spanning multiple apps.

Parity tier mirrors the reference's reactor
(reference: services/pattern_detector/app.py:28-60): on a citation-
hallucination failure, group GFKB failures by type and upsert the named
pattern once ≥2 apps are affected.

Beyond parity, ``mine_patterns`` surfaces clusters of similar failures
spanning multiple apps as discovered patterns — the batch job the reference
never had. It is INCREMENTAL by default: the GFKB streams every inserted
row into a persistent union-find cluster state (ops/incremental.py), so a
mine call drains pending deltas and re-emits only dirty clusters in
milliseconds; the O(N²·d) device sweep (kakveda_tpu.ops.clustering) remains
as ``mode="full"`` — the compaction/audit path, and the automatic fallback
whenever the streaming state can't serve a call (threshold change, stale
state, KAKVEDA_MINE_INCREMENTAL=0).
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import numpy as np

from kakveda_tpu.core import metrics as _metrics
from kakveda_tpu.core.schemas import FailureSignal, PatternEntity
from kakveda_tpu.index.gfkb import GFKB
from kakveda_tpu.pipeline.classifier import HALLUCINATION_CITATION

_CITATION_PATTERN_NAME = "Citation hallucination without sources"
_CITATION_PATTERN_DESC = "Same prompt pattern causes hallucinated citations across apps"
MAX_PATTERN_FAILURE_IDS = 1000


class PatternDetector:
    def __init__(self, gfkb: GFKB, min_apps: int = 2):
        self.gfkb = gfkb
        self.min_apps = min_apps
        self._m_sweeps = _metrics.get_registry().counter(
            "kakveda_mine_sweeps_total", "Pattern-mining sweeps by mode", ("mode",)
        )

    def on_failure(self, failure: FailureSignal) -> Optional[PatternEntity]:
        """Reactor invoked on every failure.detected event."""
        out = self.on_failures_batch([failure])
        return out[0] if out else None

    def on_failures_batch(self, failures: List[FailureSignal]) -> List[PatternEntity]:
        """Batch reactor for the streaming-ingest path: one GFKB scan and at
        most one pattern upsert per distinct failure type in the batch —
        per-event reaction would be O(N) scans per batch (O(N²) over a
        stream) plus a pattern-version append per failure."""
        types = {f.failure_type for f in failures if f.failure_type == HALLUCINATION_CITATION}
        if not types:
            return []
        out: List[PatternEntity] = []
        for ftype in sorted(types):
            # O(1) read of incrementally-maintained aggregates — rescanning
            # the GFKB per batch is O(N²) over a failure stream.
            ids, affected = self.gfkb.type_aggregate(ftype)
            if len(affected) < self.min_apps:
                continue
            # Cap the stored id list: each upsert re-appends the pattern to
            # the JSONL log, so unbounded failure_ids makes the log O(N²)
            # over a failure stream. The full membership is recoverable from
            # the failures log by type.
            pattern, _ = self.gfkb.upsert_pattern(
                name=_CITATION_PATTERN_NAME,
                failure_ids=ids[-MAX_PATTERN_FAILURE_IDS:],
                affected_apps=affected,
                description=_CITATION_PATTERN_DESC,
            )
            out.append(pattern)
        return out

    @staticmethod
    def _pattern_fields(types_count: Dict[str, int], n_members: int):
        """(name, description) from a cluster's failure-type counts —
        shared by the full-sweep and incremental emission paths so both
        produce byte-identical pattern records."""
        types = sorted(types_count)
        dominant = max(types, key=lambda t: types_count[t])
        name = (
            _CITATION_PATTERN_NAME
            if dominant == HALLUCINATION_CITATION
            else f"Recurring {dominant.lower().replace('_', ' ')}"
        )
        desc = f"Cluster of {n_members} similar failures ({', '.join(types)})"
        return name, desc

    def mine_patterns(
        self, threshold: float = 0.6, mode: str = "auto"
    ) -> List[PatternEntity]:
        return self.mine_patterns_ex(threshold, mode)[0]

    def mine_patterns_ex(
        self, threshold: float = 0.6, mode: str = "auto"
    ) -> Tuple[List[PatternEntity], dict]:
        """Pattern mining over the GFKB; returns (patterns, freshness info).

        ``mode``:
          * ``"auto"`` (default) — incremental when the streaming cluster
            state can serve this call (enabled, non-stale, covers every
            record, same threshold): drain pending delta top-ks and
            re-emit patterns ONLY for dirty clusters — milliseconds,
            independent of corpus size. Otherwise one full sweep which
            also re-seeds the incremental baseline.
          * ``"full"`` — force the O(N²·d) device sweep (periodic audit /
            threshold changes). Re-seeds the incremental state.
          * ``"incremental"`` — like auto but reports (rather than hides)
            the fallback reason when a full sweep was required.

        Clusters whose members span ≥min_apps apps become (or refresh) a
        pattern named after the dominant failure type; member count is NOT
        a criterion (identical signatures canonicalize into one record, so
        a singleton cluster can represent a cross-app recurrence).
        """
        if mode not in ("auto", "full", "incremental"):
            raise ValueError(f"unknown mine mode {mode!r} (auto|full|incremental)")
        t0 = time.perf_counter()
        if mode != "full" and self.gfkb.mine_usable(threshold):
            out, info = self._mine_incremental()
        else:
            out, info = self._mine_full(threshold)
            if mode == "incremental":
                st = self.gfkb.mine_state_info()
                info["fallback"] = (
                    "disabled" if not st.get("enabled")
                    else st.get("stale_reason") or "state not usable at this threshold"
                )
        self._m_sweeps.labels(mode=info["mode"]).inc()
        info["wall_ms"] = round((time.perf_counter() - t0) * 1000.0, 3)
        info.update(self.gfkb.mine_state_info())
        return out, info

    def _mine_incremental(self) -> Tuple[List[PatternEntity], dict]:
        """Drain pending deltas, re-emit only dirty clusters — the
        pattern log's delta-append semantics make this equivalent to a
        full emission (clean clusters would no-op their upsert)."""
        drained = self.gfkb.mine_drain()
        dirty = self.gfkb.mine_pop_dirty()
        out: List[PatternEntity] = []
        for cl in dirty:
            if len(cl["apps"]) < self.min_apps:
                continue
            name, desc = self._pattern_fields(cl["types"], cl["n"])
            pattern, _ = self.gfkb.upsert_pattern(
                name=name,
                failure_ids=cl["fids"],
                affected_apps=cl["apps"],
                description=desc,
            )
            out.append(pattern)
        return out, {"mode": "incremental", "drained": drained, "dirty_clusters": len(dirty)}

    def _mine_full(self, threshold: float) -> Tuple[List[PatternEntity], dict]:
        """The original whole-corpus device sweep; also re-seeds the
        incremental baseline so later calls pay only for their deltas."""
        from kakveda_tpu.ops.clustering import cluster_embeddings

        # Reuse the device-resident index rows (one gather) instead of
        # re-embedding every signature on host — at 1M records the re-embed
        # costs minutes, the gather costs a device copy. Captured atomically
        # with the record list so a concurrent purge/reload can't misalign
        # rows with records.
        records, vecs = self.gfkb.records_and_embeddings()
        if not records:
            self.gfkb.mine_reseed(np.zeros(0, np.int32), threshold, 0)
            return [], {"mode": "full", "dirty_clusters": 0}
        labels = cluster_embeddings(vecs, threshold=threshold)

        groups: Dict[int, List[int]] = defaultdict(list)
        for i, lbl in enumerate(labels):
            groups[int(lbl)].append(i)

        out: List[PatternEntity] = []
        for members in groups.values():
            recs = [records[i] for i in members]
            apps = sorted({a for r in recs for a in r.affected_apps})
            # App span is the criterion, not member count: identical
            # signatures canonicalize into ONE record whose affected_apps
            # grows, so a singleton cluster spanning ≥min_apps apps is
            # exactly the recurring cross-app failure a pattern describes.
            if len(apps) < self.min_apps:
                continue
            types_count: Dict[str, int] = {}
            for r in recs:
                types_count[r.failure_type] = types_count.get(r.failure_type, 0) + 1
            name, desc = self._pattern_fields(types_count, len(recs))
            pattern, _ = self.gfkb.upsert_pattern(
                name=name,
                failure_ids=sorted({r.failure_id for r in recs}),
                affected_apps=apps,
                description=desc,
            )
            out.append(pattern)
        self.gfkb.mine_reseed(labels, threshold, len(records))
        return out, {"mode": "full", "dirty_clusters": len(groups)}
