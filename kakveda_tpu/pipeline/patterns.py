"""Pattern detection: recurring failures spanning multiple apps.

Parity tier mirrors the reference's reactor
(reference: services/pattern_detector/app.py:28-60): on a citation-
hallucination failure, group GFKB failures by type and upsert the named
pattern once ≥2 apps are affected.

Beyond parity, ``mine_patterns`` runs device-side clustering over the full
GFKB embedding matrix (threshold cosine graph → connected components via
iterative label propagation, kakveda_tpu.ops.clustering) and surfaces
clusters that span multiple apps as discovered patterns — the batch job the
reference never had.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional

from kakveda_tpu.core.schemas import FailureSignal, PatternEntity
from kakveda_tpu.index.gfkb import GFKB
from kakveda_tpu.pipeline.classifier import HALLUCINATION_CITATION

_CITATION_PATTERN_NAME = "Citation hallucination without sources"
_CITATION_PATTERN_DESC = "Same prompt pattern causes hallucinated citations across apps"
MAX_PATTERN_FAILURE_IDS = 1000


class PatternDetector:
    def __init__(self, gfkb: GFKB, min_apps: int = 2):
        self.gfkb = gfkb
        self.min_apps = min_apps

    def on_failure(self, failure: FailureSignal) -> Optional[PatternEntity]:
        """Reactor invoked on every failure.detected event."""
        out = self.on_failures_batch([failure])
        return out[0] if out else None

    def on_failures_batch(self, failures: List[FailureSignal]) -> List[PatternEntity]:
        """Batch reactor for the streaming-ingest path: one GFKB scan and at
        most one pattern upsert per distinct failure type in the batch —
        per-event reaction would be O(N) scans per batch (O(N²) over a
        stream) plus a pattern-version append per failure."""
        types = {f.failure_type for f in failures if f.failure_type == HALLUCINATION_CITATION}
        if not types:
            return []
        out: List[PatternEntity] = []
        for ftype in sorted(types):
            # O(1) read of incrementally-maintained aggregates — rescanning
            # the GFKB per batch is O(N²) over a failure stream.
            ids, affected = self.gfkb.type_aggregate(ftype)
            if len(affected) < self.min_apps:
                continue
            # Cap the stored id list: each upsert re-appends the pattern to
            # the JSONL log, so unbounded failure_ids makes the log O(N²)
            # over a failure stream. The full membership is recoverable from
            # the failures log by type.
            pattern, _ = self.gfkb.upsert_pattern(
                name=_CITATION_PATTERN_NAME,
                failure_ids=ids[-MAX_PATTERN_FAILURE_IDS:],
                affected_apps=affected,
                description=_CITATION_PATTERN_DESC,
            )
            out.append(pattern)
        return out

    def mine_patterns(self, threshold: float = 0.6) -> List[PatternEntity]:
        """Batch pattern mining over the whole GFKB via device clustering.

        Clusters canonical failures by embedding similarity; any cluster
        whose members span ≥min_apps apps becomes (or refreshes) a pattern
        named after its dominant failure type. (Member count is NOT a
        criterion: identical signatures canonicalize into one record, so a
        singleton cluster can represent a failure recurring across apps.)
        """
        from kakveda_tpu.ops.clustering import cluster_embeddings

        # Reuse the device-resident index rows (one gather) instead of
        # re-embedding every signature on host — at 1M records the re-embed
        # costs minutes, the gather costs a device copy. Captured atomically
        # with the record list so a concurrent purge/reload can't misalign
        # rows with records.
        records, vecs = self.gfkb.records_and_embeddings()
        if not records:
            return []
        labels = cluster_embeddings(vecs, threshold=threshold)

        groups: Dict[int, List[int]] = defaultdict(list)
        for i, lbl in enumerate(labels):
            groups[int(lbl)].append(i)

        out: List[PatternEntity] = []
        for members in groups.values():
            recs = [records[i] for i in members]
            apps = sorted({a for r in recs for a in r.affected_apps})
            # App span is the criterion, not member count: identical
            # signatures canonicalize into ONE record whose affected_apps
            # grows, so a singleton cluster spanning ≥min_apps apps is
            # exactly the recurring cross-app failure a pattern describes.
            if len(apps) < self.min_apps:
                continue
            types = sorted({r.failure_type for r in recs})
            dominant = max(types, key=lambda t: sum(1 for r in recs if r.failure_type == t))
            name = (
                _CITATION_PATTERN_NAME
                if dominant == HALLUCINATION_CITATION
                else f"Recurring {dominant.lower().replace('_', ' ')}"
            )
            pattern, _ = self.gfkb.upsert_pattern(
                name=name,
                failure_ids=sorted({r.failure_id for r in recs}),
                affected_apps=apps,
                description=f"Cluster of {len(recs)} similar failures ({', '.join(types)})",
            )
            out.append(pattern)
        return out
