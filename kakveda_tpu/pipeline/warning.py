"""Pre-flight warning policy — 'has something like this failed before?'

Parity with the reference's warning service
(reference: services/warning_policy/app.py:19-72): build the signature text,
match against the GFKB, compare the best score to the config threshold
(default 0.8), attach a pattern id when a known pattern covers the matched
failure type, and answer block|warn|silent with a confidence score.

Unlike the reference — which pays an HTTP hop to GFKB plus a full TF-IDF
refit per request — this policy calls the device index in-process; the match
is a warm compiled matmul+top-k, and ``warn_batch`` amortizes many
concurrent pre-flight checks into one device call (the <10 ms p50 path).
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

from kakveda_tpu.core import metrics as _metrics
from kakveda_tpu.core.config import ConfigStore
from kakveda_tpu.core.fingerprint import signature_text
from kakveda_tpu.core.schemas import WarningRequest, WarningResponse
from kakveda_tpu.index.gfkb import GFKB
from kakveda_tpu.pipeline.classifier import HALLUCINATION_CITATION

# The demo pattern the reference's policy knows how to attach
# (reference: services/warning_policy/app.py:40-48).
_CITATION_PATTERN_NAME = "Citation hallucination without sources"


class WarningPolicy:
    def __init__(self, gfkb: GFKB, config: Optional[ConfigStore] = None):
        self.gfkb = gfkb
        self.config = config or ConfigStore()
        reg = _metrics.get_registry()
        self._m_batch = reg.histogram(
            "kakveda_warn_batch_seconds",
            "Device kNN match wall per warn batch",
        )
        self._m_verdicts = reg.counter(
            "kakveda_warn_requests_total",
            "Pre-flight warn verdicts by action", ("action",),
        )

    def warn(self, req: WarningRequest) -> WarningResponse:
        return self.warn_batch([req])[0]

    def warn_batch(self, reqs: Sequence[WarningRequest]) -> List[WarningResponse]:
        t0 = time.perf_counter()
        threshold = self.config.similarity_threshold()
        default_action = self.config.default_action()

        sigs = [signature_text(r.prompt, r.tools, r.env) for r in reqs]
        # Device-loss degraded mode (core/admission.py): while the backend
        # is latched DEGRADED we never even dispatch (a wedged chip hangs,
        # it doesn't error) — the GFKB's host-warm/disk-cold tiers answer
        # instead (index/tiers.py, `match_batch_fallback`), flagged
        # `degraded=true`. A fresh backend failure here latches the mode
        # and takes the same fallback, so the request that DISCOVERS the
        # outage still gets a verdict. The pre-flight check is the
        # product; it must not die with the chip.
        from kakveda_tpu.core import admission as _admission

        health = _admission.get_device_health()
        degraded = False
        if health.degraded:
            all_matches, tier_info = self.gfkb.match_batch_fallback(sigs)
            degraded = True
        else:
            try:
                all_matches, tier_info = self.gfkb.match_batch_info(sigs)
            except Exception as e:  # noqa: BLE001 — classify, maybe degrade
                if not health.note_failure(e, where="gfkb.match"):
                    raise  # a real software bug, not a device loss
                all_matches, tier_info = self.gfkb.match_batch_fallback(sigs)
                degraded = True
        self._m_batch.observe(time.perf_counter() - t0)
        patterns = self.gfkb.list_patterns()

        out: List[WarningResponse] = []
        for matches in all_matches:
            best = matches[0] if matches else None
            score = best.score if best else 0.0

            pattern_id = None
            if best and best.failure_type == HALLUCINATION_CITATION:
                for p in patterns:
                    if p.name == _CITATION_PATTERN_NAME:
                        pattern_id = p.pattern_id
                        break

            if best and score >= threshold:
                out.append(
                    WarningResponse(
                        action=default_action,
                        confidence=score,
                        pattern_id=pattern_id,
                        references=[best],
                        message=(
                            f"This execution matches past failure type {best.failure_type} "
                            f"(failure_id={best.failure_id}, similarity={score:.2f}). "
                            f"Suggested mitigation: {best.suggested_mitigation or 'n/a'}"
                        ),
                        degraded=degraded,
                        tier=tier_info.get("tier"),
                        nprobe=tier_info.get("nprobe"),
                    )
                )
            else:
                out.append(
                    WarningResponse(
                        action="silent" if default_action == "silent" else "warn",
                        confidence=score,
                        pattern_id=pattern_id,
                        references=[],
                        message="No high-similarity match found in GFKB.",
                        degraded=degraded,
                        tier=tier_info.get("tier"),
                        nprobe=tier_info.get("nprobe"),
                    )
                )
        for r in out:
            self._m_verdicts.labels(action=r.action).inc()
        return out
