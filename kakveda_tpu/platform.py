"""The assembled intelligence core — one object, the whole platform.

The reference spreads this across seven containers talking JSON-over-HTTP
(event-bus, ingestion, gfkb, failure-classifier, pattern-detector,
warning-policy, health-scoring; reference: docker-compose.yml). Here the
same pipeline is one in-process object holding the device-resident GFKB:

    ingest(trace)  → publish trace.ingested
                   → rule classifier → GFKB upsert (device embed + insert)
                   → publish failure.detected
                   → pattern detector → pattern upsert
                   → health scorer    → health point append
    warn(request)  → device kNN match → policy decision

``ingest_batch`` is the streaming path: classify, embed and insert whole
batches in single device calls (the 10k traces/sec target). The HTTP
service layer (kakveda_tpu.service) and dashboard mount this core; external
subscribers can still attach callback URLs to the bus for the reference's
pub/sub contract.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import List, Optional, Sequence

from jax.sharding import Mesh

from kakveda_tpu.core import metrics as _metrics
from kakveda_tpu.core import trace as _trace
from kakveda_tpu.core.config import ConfigStore
from kakveda_tpu.core.fingerprint import signature_text
from kakveda_tpu.core.schemas import (
    FailureSignal,
    HealthPoint,
    PatternEntity,
    TracePayload,
    WarningRequest,
    WarningResponse,
)
from kakveda_tpu.events.bus import (
    TOPIC_FAILURE_DETECTED,
    TOPIC_GFKB_REPLICATE,
    TOPIC_TRACE_INGESTED,
    EventBus,
    new_event_id,
)
from kakveda_tpu.index.gfkb import GFKB
from kakveda_tpu.pipeline.classifier import RuleClassifier
from kakveda_tpu.pipeline.health_score import HealthScorer
from kakveda_tpu.pipeline.patterns import PatternDetector
from kakveda_tpu.pipeline.warning import WarningPolicy


class Platform:
    """Wires bus + GFKB + classifier + patterns + warnings + health."""

    def __init__(
        self,
        data_dir: str | Path = "data",
        config: Optional[ConfigStore] = None,
        mesh: Optional[Mesh] = None,
        capacity: int = 1 << 14,
        dim: Optional[int] = None,
        persist: bool = True,
        classifier=None,
    ):
        self.config = config or ConfigStore()
        self.data_dir = Path(data_dir)
        d = dim or self.config.embedding_dim()

        # HTTP-URL subscriptions survive restarts (replayed from the append
        # log) — fixes the reference's lost-on-restart hazard (SURVEY §3.5).
        self.bus = EventBus(
            persist_path=(self.data_dir / "subscriptions.jsonl") if persist else None
        )
        self.gfkb = GFKB(
            data_dir=self.data_dir,
            mesh=mesh,
            capacity=capacity,
            dim=d,
            top_k=self.config.match_top_k(),
            persist=persist,
        )
        # Classifier tier: rule-only by default (deterministic, hermetic);
        # KAKVEDA_CLASSIFIER=tiered adds the LLM judge over the configured
        # model runtime for citation prompts the marker regex passes.
        if classifier is None:
            if os.environ.get("KAKVEDA_CLASSIFIER", "rule") == "tiered":
                from kakveda_tpu.models.runtime import get_runtime
                from kakveda_tpu.pipeline.classifier import TieredClassifier

                classifier = TieredClassifier(runtime=get_runtime())
            else:
                classifier = RuleClassifier()
        self.classifier = classifier
        self.patterns = PatternDetector(self.gfkb)
        self.warning_policy = WarningPolicy(self.gfkb, self.config)
        self.health = HealthScorer(self.data_dir, self.config, persist=persist)

        # Internal pipeline reactors ride the same bus external subscribers use.
        self.bus.subscribe(TOPIC_TRACE_INGESTED, self._on_trace_event)
        self.bus.subscribe(TOPIC_FAILURE_DETECTED, self._on_failure_event)

        # Fleet identity (docs/scale-out.md): set per-replica by the fleet
        # supervisor; stamps replication events with their origin.
        self.replica_id = os.environ.get("KAKVEDA_REPLICA_ID", "")
        # Sharded ownership (fleet/ownership.py): the service app installs
        # an OwnershipState here when KAKVEDA_FLEET_OWNERSHIP=1; replication
        # then publishes range-scoped per-peer events instead of the
        # full-fleet broadcast. None = legacy full replication, untouched.
        self.ownership = None

        # Pipeline counters on the process-global metrics plane (scraped
        # at GET /metrics; children resolved once, not per batch).
        reg = _metrics.get_registry()
        self._m_traces = reg.counter(
            "kakveda_ingest_traces_total",
            "Traces classified by the intelligence pipeline",
        )
        self._m_failures = reg.counter(
            "kakveda_ingest_failures_total",
            "Failure signals detected by the classifier tier",
        )
        self._m_batch_wall = reg.histogram(
            "kakveda_ingest_batch_seconds",
            "Classify+embed+insert wall per ingest batch",
        )

    # ------------------------------------------------------------------
    # event reactors (dict payloads — the bus speaks JSON shapes)
    # ------------------------------------------------------------------

    async def _on_trace_event(self, event: dict) -> None:
        trace = TracePayload.model_validate(event)
        await self._classify_and_record([trace])

    async def _on_failure_event(self, event: dict) -> None:
        failure = FailureSignal.model_validate(event)
        self.patterns.on_failure(failure)
        self.health.on_failure(failure)

    # ------------------------------------------------------------------
    # core flows
    # ------------------------------------------------------------------

    async def _classify_and_record(self, traces: Sequence[TracePayload]) -> List[FailureSignal]:
        t0 = time.perf_counter()
        self._m_traces.inc(len(traces))
        # The heavy sync work — rule/LLM classification and the GFKB's
        # embed+insert — runs OFF the event loop. Inline it blocked the
        # loop for the whole batch, so one ingest flood serialized every
        # concurrent /warn behind it (measured: warn p95 43× worse under
        # saturation) AND kept the admission controller blind — handlers
        # never overlapped, so in-flight counts never reached the bound
        # and nothing shed. Off-loop, floods stack up against the bound
        # and get 429s while warn keeps answering. GFKB upserts are
        # lock-protected by design, so executor threads are safe here.
        import asyncio

        loop = asyncio.get_running_loop()
        signals = await loop.run_in_executor(
            None, self.classifier.classify_batch, traces
        )
        found = [(t, s) for t, s in zip(traces, signals) if s is not None]
        if not found:
            self._m_batch_wall.observe(time.perf_counter() - t0)
            return []
        rows = [
            {
                "failure_type": s.failure_type,
                "root_cause": s.root_cause,
                "context_signature": s.context_signature,
                "impact_severity": s.severity.value,
                "resolution": s.mitigation,
                "signature_text": signature_text(t.prompt, t.tools, t.env),
                "app_id": t.app_id,
            }
            for t, s in found
        ]
        await loop.run_in_executor(None, self.gfkb.upsert_failures_batch, rows)
        signals_found = [s for _, s in found]
        # Fleet ingest fan-in: the rows this replica just accepted ARE the
        # replication log entry — published at-least-once to every peer's
        # /replicate (retry → breaker → DLQ; `dlq replay` converges
        # stragglers). The event id makes peer application idempotent
        # (GFKB.apply_replication). publish() never raises — a peer outage
        # dead-letters the event, it never fails THIS ingest.
        await self.replicate_rows(rows)
        # Batch-aware reactors run once per batch (one GFKB scan for pattern
        # detection, one health append) — the O(N²) trap of reacting per
        # event is what keeps the reference from streaming throughput. The
        # bus still delivers every failure.detected to external subscribers;
        # the internal reactor is excluded because it just ran here.
        self.patterns.on_failures_batch(signals_found)
        self.health.on_failures_batch(signals_found)
        exclude = (self._on_failure_event,)
        if self.bus.has_subscribers(TOPIC_FAILURE_DETECTED, exclude=exclude):
            await self.bus.publish_many(
                TOPIC_FAILURE_DETECTED,
                [s.model_dump(mode="json") for s in signals_found],
                exclude=exclude,
            )
        self._m_failures.inc(len(signals_found))
        self._m_batch_wall.observe(time.perf_counter() - t0)
        return signals_found

    async def replicate_rows(self, rows: List[dict]) -> None:
        """Publish accepted rows to peers — ingest-classified and manual
        upserts replicate through this ONE path so the fleet's shards
        never diverge by entry point.

        Legacy (ownership None): one broadcast event on gfkb.replicate to
        every subscribed peer. Sharded ownership (KAKVEDA_FLEET_OWNERSHIP
        =1, fleet/ownership.py): each row goes only to the holders of its
        shard key, on that peer's own per-destination topic — same
        at-least-once retry/breaker/DLQ machinery per peer, write
        amplification R instead of N. Scoped events carry the publisher's
        ownership epoch so a receiver with a NEWER view fences rows it no
        longer holds (service/app.py /replicate)."""
        if not rows:
            return
        if self.ownership is not None:
            from kakveda_tpu.events.bus import replicate_topic
            from kakveda_tpu.fleet.ownership import shard_key_of_row

            view = self.ownership.view
            by_target: dict = {}
            for row in rows:
                for rid in view.holders(shard_key_of_row(row)):
                    if rid != self.replica_id:
                        by_target.setdefault(rid, []).append(row)
            tp = _trace.current_traceparent()
            for rid in sorted(by_target):
                topic = replicate_topic(rid)
                if self.bus.has_subscribers(topic):
                    event = {
                        "id": new_event_id(),
                        "origin": self.replica_id,
                        "ts": time.time(),
                        "epoch": view.epoch,
                        "rows": by_target[rid],
                    }
                    # The envelope carries the causal context, so a peer's
                    # apply — or this event's DLQ record and its eventual
                    # `dlq replay` redelivery — continues the ingest's
                    # trace instead of starting an uncorrelated one.
                    if tp:
                        event["trace"] = tp
                    await self.bus.publish(topic, event)
        elif self.bus.has_subscribers(TOPIC_GFKB_REPLICATE):
            event = {
                "id": new_event_id(),
                "origin": self.replica_id,
                "ts": time.time(),
                "rows": rows,
            }
            tp = _trace.current_traceparent()
            if tp:
                event["trace"] = tp
            await self.bus.publish(TOPIC_GFKB_REPLICATE, event)

    async def ingest(self, trace: TracePayload) -> None:
        """The reference's POST /ingest → publish trace.ingested
        (reference: services/ingestion/app.py:15-21)."""
        await self.bus.publish(TOPIC_TRACE_INGESTED, trace.model_dump(mode="json"))

    async def ingest_batch(self, traces: Sequence[TracePayload]) -> List[FailureSignal]:
        """Streaming ingest: classify + embed + insert whole batches in single
        device calls. Bypasses the internal per-trace reactor (classification
        runs here, batched) but still fans trace.ingested out to every OTHER
        subscriber — durable URL subscribers and the dashboard's runs-explorer
        handler see batched traces exactly as they see single ones."""
        exclude = (self._on_trace_event,)
        if self.bus.has_subscribers(TOPIC_TRACE_INGESTED, exclude=exclude):
            await self.bus.publish_many(
                TOPIC_TRACE_INGESTED,
                [t.model_dump(mode="json") for t in traces],
                exclude=exclude,
            )
        return await self._classify_and_record(traces)

    def warn(self, req: WarningRequest) -> WarningResponse:
        return self.warning_policy.warn(req)

    def warn_batch(self, reqs: Sequence[WarningRequest]) -> List[WarningResponse]:
        return self.warning_policy.warn_batch(reqs)

    # ------------------------------------------------------------------
    # views
    # ------------------------------------------------------------------

    def failures(self):
        return self.gfkb.list_failures()

    def failures_page(self, offset: int = 0, limit: int = 50):
        """Newest-first page — dashboard views must stay O(page), not
        O(records), as the GFKB grows."""
        return self.gfkb.list_failures_page(offset, limit)

    def get_failure(self, failure_id: str):
        return self.gfkb.get_failure(failure_id)

    def apps(self) -> List[str]:
        return self.gfkb.all_apps()

    def patterns_list(self) -> List[PatternEntity]:
        return self.gfkb.list_patterns()

    def mine(self, threshold: float = 0.6, mode: str = "auto"):
        """Pattern mining with freshness info: incremental (drain the
        streaming cluster state, re-emit dirty clusters) when possible,
        full device sweep otherwise or on ``mode="full"``. Returns
        (patterns, info) — see PatternDetector.mine_patterns_ex."""
        return self.patterns.mine_patterns_ex(threshold, mode)

    def health_history(self, app_id: str, limit: int = 50) -> List[dict]:
        return self.health.history(app_id, limit)

    def health_points(self, app_id: str) -> List[HealthPoint]:
        return [HealthPoint.model_validate(p) for p in self.health.history(app_id)]
