"""Host HTTP service layer (aiohttp) over the in-process platform.

Keeps the reference's external REST contracts — ingest, warn, GFKB
failures/patterns, health, event-bus pub/sub, agent echo — on one port
instead of nine containers (reference: docker-compose.yml port map in
SURVEY.md §1). The TPU intelligence core stays in-process; HTTP exists for
operators, dashboards and external agents, not for the pipeline's own hops.
"""

from kakveda_tpu.service.app import make_app  # noqa: F401
