from kakveda_tpu.service.main import run_server

run_server()
