"""`python -m kakveda_tpu.service` — start the platform API + dashboard."""

import argparse

from kakveda_tpu.service.main import run_server

ap = argparse.ArgumentParser(prog="kakveda_tpu.service")
ap.add_argument("--host", default="127.0.0.1")
ap.add_argument("--port", type=int, default=8100)
ap.add_argument("--dashboard-port", type=int, default=8110)
ap.add_argument("--no-dashboard", action="store_true")
ap.add_argument("--data-dir", default=None)
args = ap.parse_args()

raise SystemExit(
    run_server(
        host=args.host,
        port=args.port,
        data_dir=args.data_dir,
        dashboard_port=None if args.no_dashboard else args.dashboard_port,
    )
)
