"""Standalone agent-echo server: `python -m kakveda_tpu.service.agent_echo`.

Runs the reference external-agent contract (/health, /capabilities,
/invoke — reference: services/agent_echo/app.py:13-47) as its own process,
for exercising the agent registry and event plane over real HTTP.
"""

from __future__ import annotations

import argparse
import asyncio

from aiohttp import web

from kakveda_tpu.core.runtime import setup_logging
from kakveda_tpu.service.app import make_agent_echo_app


async def _serve(host: str, port: int) -> None:
    runner = web.AppRunner(make_agent_echo_app())
    await runner.setup()
    await web.TCPSite(runner, host, port).start()
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await runner.cleanup()


def main() -> int:
    ap = argparse.ArgumentParser(prog="kakveda_tpu.service.agent_echo")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8120)
    args = ap.parse_args()
    setup_logging(service_name="agent-echo")
    try:
        asyncio.run(_serve(args.host, args.port))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
