"""The platform's HTTP surface — reference REST contracts on one port.

Route map (reference originals in parentheses):

  POST /ingest                  (ingestion:8102, services/ingestion/app.py:15)
  POST /warn                    (warning-policy:8105, services/warning_policy/app.py:19)
  GET  /failures                (gfkb:8101, services/gfkb/app.py:74)
  POST /failures/match          (gfkb, services/gfkb/app.py:79)
  POST /failures/upsert         (gfkb, services/gfkb/app.py:105)
  GET  /patterns                (gfkb, services/gfkb/app.py:150)
  POST /patterns/upsert         (gfkb, services/gfkb/app.py:168)
  GET  /health/{app_id}         (health-scoring:8106, services/health_scoring/app.py:116)
  POST /subscribe /publish, GET /topics
                                (event-bus:8100, services/event_bus/app.py:28-59)
  GET  /healthz /readyz         (liveness/readiness)
  GET  /metrics /flightrecorder (metrics plane — Prometheus exposition +
                                 serving flight-recorder dump; also mounted
                                 on the dashboard. docs/observability.md)

The warn route drains through a MicroBatcher so concurrent pre-flight
checks share one device call. External subscribers registered via
/subscribe get HTTP callbacks exactly like the reference bus delivered.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

from aiohttp import web
from pydantic import ValidationError

from kakveda_tpu.core import admission as _admission
from kakveda_tpu.core import faults as _faults
from kakveda_tpu.core.admission import DeviceUnavailableError, OverloadError
from kakveda_tpu.core import sanitize
from kakveda_tpu.core import trace as _trace
from kakveda_tpu.core.runtime import ensure_request_id, get_runtime_config
from kakveda_tpu.core.schemas import (
    FailureMatchRequest,
    IngestBatchRequest,
    IngestRequest,
    Severity,
    WarningRequest,
)
from kakveda_tpu.platform import Platform
from kakveda_tpu.service.batcher import MicroBatcher

log = logging.getLogger("kakveda.service")


def _native_status() -> dict:
    """Native library load/build status for /readyz (ISSUE 11): operators
    see at a glance whether the host-tier scoring engine is live or the
    process is running on the numpy fallbacks."""
    from kakveda_tpu import native as _native

    return _native.status()

PLATFORM_KEY: web.AppKey[Platform] = web.AppKey("platform", Platform)
WARN_BATCHER_KEY: web.AppKey[MicroBatcher] = web.AppKey("warn_batcher", MicroBatcher)
_GOSSIP_TASK_KEY: web.AppKey[object] = web.AppKey("fleet_gossip_task", object)
_STALL_WATCHDOG_KEY: web.AppKey[object] = web.AppKey("sanitize_stall_watchdog", object)

# Chaos site for the HTTP tier, resolved once at import: an armed
# service.handler fault turns a request into a clean 500 before its
# handler runs — proving callers survive the platform's own API failing.
_FAULT_HANDLER = _faults.site("service.handler")
# Fleet replication apply (docs/robustness.md): armed, a peer's
# /replicate apply dies with a clean 500 — the publishing bus retries,
# breaks, dead-letters, and `dlq replay` converges the gap later. Never
# a lost row, never a failed ingest at the origin.
_FAULT_REPLICATE = _faults.site("fleet.replicate_apply")


def _json_error(status: int, message: str) -> web.Response:
    return web.json_response({"ok": False, "error": message}, status=status)


def overload_response(e: OverloadError) -> web.Response:
    """THE 429 shape — admission sheds, brownout rejections and the
    per-client token bucket all answer identically: a ``Retry-After``
    header plus the hint repeated in the JSON body for clients that
    only read bodies."""
    return web.json_response(
        {
            "ok": False,
            "error": str(e),
            "retry_after": round(e.retry_after, 2),
            "reason": e.reason or "overload",
        },
        status=429,
        headers={"Retry-After": str(max(1, int(round(e.retry_after))))},
    )


def degraded_response(e: DeviceUnavailableError) -> web.Response:
    """503 for device-loss degraded mode: retryable by contract — the
    background probe un-latches when the chip answers again."""
    return web.json_response(
        {
            "ok": False,
            "error": str(e),
            "retry_after": round(e.retry_after, 2),
            "degraded": True,
        },
        status=503,
        headers={"Retry-After": str(max(1, int(round(e.retry_after))))},
    )


def metrics_routes() -> list:
    """The metrics-plane routes, shared by the service app AND the
    dashboard (one registry per process — scraping either port sees the
    whole picture):

      GET /metrics         Prometheus text exposition of the process-global
                           registry (serving lifecycle, spec gate, pipeline,
                           bus — see docs/observability.md for the catalog).
      GET /flightrecorder  JSON dump of every live flight recorder's ring
                           (recent request timelines + gate/k transitions
                           per serving engine).
    """
    from kakveda_tpu.core import metrics as _metrics

    async def metrics_ep(request):
        return web.Response(
            body=_metrics.get_registry().render().encode("utf-8"),
            headers={"Content-Type": _metrics.PROMETHEUS_CONTENT_TYPE},
        )

    async def flightrecorder_ep(request):
        return web.json_response({"recorders": _metrics.dump_recorders()})

    async def trace_ring_ep(request):
        tr = _trace.get_tracer()
        try:
            limit = int(request.query["n"]) if "n" in request.query else None
        except ValueError:
            limit = None
        return web.json_response(
            {"plane": tr.plane(), "spans": tr.dump(limit=limit)}
        )

    async def trace_one_ep(request):
        tid = request.match_info["trace_id"]
        return web.json_response(
            {"trace_id": tid, "spans": _trace.get_tracer().dump(tid)}
        )

    return [
        web.get("/metrics", metrics_ep),
        web.get("/flightrecorder", flightrecorder_ep),
        web.get("/trace", trace_ring_ep),
        web.get("/trace/{trace_id}", trace_one_ep),
    ]


@web.middleware
async def request_context_middleware(request: web.Request, handler):
    """Request id + duration logging (reference: dashboard app.py:590-611).

    When the otel middleware runs outside this one it already resolved the
    request id (and put it on the span); reuse it so logs, the echoed
    header and the trace all carry ONE id."""
    cfg = get_runtime_config(service_name="kakveda-tpu")
    rid = request.get("request_id") or ensure_request_id(
        request.headers.get(cfg.request_id_header)
    )
    # Causal trace (core/trace.py): extract the incoming W3C context or
    # start a new root that FOLDS the request id (ensure_request_id mints
    # 32 lowercase hex — a valid trace id), so logs, the echoed header and
    # the cross-process span tree all join on one key. Handlers reach the
    # span via request["trace_span"] to attach provenance.
    span = _trace.get_tracer().start_span(
        "service.request",
        traceparent=request.headers.get(_trace.TRACEPARENT_HEADER),
        trace_id=rid,
        path=request.path,
        method=request.method,
        rid=rid,
    )
    request["trace_span"] = span
    span.activate()
    started = time.perf_counter()
    try:
        _FAULT_HANDLER.fire()
        response = await handler(request)
    except _faults.FaultInjected as e:
        response = _json_error(500, str(e))
    except OverloadError as e:
        # Shed by admission control / brownout / rate limit anywhere under
        # the handler: ONE conversion point to 429 + Retry-After.
        response = overload_response(e)
    except DeviceUnavailableError as e:
        response = degraded_response(e)
    except web.HTTPException as e:
        e.headers[cfg.request_id_header] = rid
        span.deactivate()
        span.end(
            "error" if e.status >= 500
            else "shed" if e.status == 429
            else span.outcome,
            status=e.status,
        )
        raise
    except BaseException:
        span.deactivate()
        span.end("error")
        raise
    duration_ms = int((time.perf_counter() - started) * 1000)
    response.headers[cfg.request_id_header] = rid
    span.deactivate()
    span.end(
        "shed" if response.status == 429
        else "degraded" if response.status == 503
        else "error" if response.status >= 500
        else span.outcome,  # a 200 degraded-warn handler may have marked it
        status=response.status,
    )
    log.info(
        "request",
        extra={
            "request_id": rid,
            "path": request.path,
            "method": request.method,
            "status_code": response.status,
            "duration_ms": duration_ms,
        },
    )
    return response


def make_app(
    platform: Optional[Platform] = None,
    admission: Optional[_admission.AdmissionController] = None,
    **platform_kw,
) -> web.Application:
    plat = platform or Platform(**platform_kw)
    from kakveda_tpu.core import otel

    # Overload protection (core/admission.py): bounded per-class admission
    # ahead of every queue, with 429 + Retry-After shedding (converted by
    # the middleware above). Process-global by default so the serving
    # engine and this app see ONE pressure picture; tests inject private
    # controllers.
    adm = admission if admission is not None else _admission.get_admission()
    health = _admission.get_device_health()

    # Traffic capture (kakveda_tpu/traffic/capture.py): every warn/ingest
    # arrival lands in this bounded ring so `traffic record` can pull GET
    # /flightrecorder and convert the timeline into a replayable traffic
    # log. One deque append per request when enabled; KAKVEDA_TRAFFIC_
    # CAPTURE=0 makes record() a no-op (capacity 0).
    from kakveda_tpu.core.metrics import FlightRecorder

    _cap_on = os.environ.get("KAKVEDA_TRAFFIC_CAPTURE", "1") != "0"
    traffic_rec = FlightRecorder(
        "traffic",
        capacity=int(os.environ.get("KAKVEDA_TRAFFIC_CAPTURE_N", "2048"))
        if _cap_on else 0,
    )

    # Optional per-client token bucket (KAKVEDA_RATELIMIT_RPS) on the
    # unauthenticated write path — same 429 shape as admission sheds.
    rl_rps = float(os.environ.get("KAKVEDA_RATELIMIT_RPS", "0") or 0)
    bucket = None
    if rl_rps > 0:
        from kakveda_tpu.core.ratelimit import TokenBucket

        burst = os.environ.get("KAKVEDA_RATELIMIT_BURST")
        bucket = TokenBucket(rl_rps, float(burst) if burst else None)

    def _ratelimit(request) -> None:
        if bucket is None:
            return
        ok, ra = bucket.allow(request.remote or "anon")
        if not ok:
            adm.note_shed("ingest", "ratelimit", retry_after=ra)
            raise OverloadError(
                f"per-client rate limit exceeded ({rl_rps:g} rps)",
                retry_after=ra, klass="ingest", reason="ratelimit",
            )

    middlewares = [request_context_middleware]
    if otel.setup_otel("platform"):
        middlewares.insert(0, otel.otel_middleware())
    app = web.Application(middlewares=middlewares)
    app[PLATFORM_KEY] = plat

    # Trace provenance resolved ONCE at construction (hot paths must not
    # re-derive it per request): recorded spans carry the replica id, and
    # warn spans note whether the native scorer could have served them.
    _trace.get_tracer().service = plat.replica_id or ""
    _native_avail = bool(_native_status().get("available"))
    from kakveda_tpu.core import metrics as _metrics_reg

    _h_warn = _metrics_reg.get_registry().histogram(
        "kakveda_warn_request_seconds",
        "End-to-end /warn wall inside the service handler "
        "(exemplar-linked to its trace id)",
    )

    # Micro-batcher shape is operator surface now that fleets tune it per
    # replica (docs/scale-out.md): KAKVEDA_WARN_MAX_BATCH coalesced
    # requests per device call, KAKVEDA_WARN_DEADLINE_MS straggler wait.
    warn_max_batch = int(os.environ.get("KAKVEDA_WARN_MAX_BATCH", "64") or 64)
    warn_deadline_s = float(os.environ.get("KAKVEDA_WARN_DEADLINE_MS", "2") or 2) / 1e3
    run_warn_batch = plat.warn_batch
    rtt_emu_ms = float(os.environ.get("KAKVEDA_WARN_RTT_EMU_MS", "0") or 0)
    if rtt_emu_ms > 0:
        # Dev/bench emulation of the tunneled-accelerator dispatch RTT
        # (CLAUDE.md: ~70-90 ms wire RTT per dispatch/fetch on the remote
        # TPU). On a local CPU backend the warn batch returns in
        # microseconds, which hides the production bottleneck the fleet
        # exists to parallelize; this adds one blocking RTT per BATCHED
        # device call (it runs in the batcher's executor thread and
        # releases the GIL, exactly like a real wire wait). Never set in
        # production — the real wire provides it.
        def run_warn_batch(reqs, _inner=plat.warn_batch, _rtt=rtt_emu_ms / 1e3):
            time.sleep(_rtt)
            return _inner(reqs)

    warn_batcher: MicroBatcher = MicroBatcher(
        run_warn_batch, max_batch=warn_max_batch, deadline_s=warn_deadline_s,
        max_queue=adm.limits["warn"], admission=adm,
        # Tenant identity for weighted-fair batch composition + the
        # tenant-aware queue bound (docs/robustness.md § multi-tenancy).
        # The warn body is parsed BEFORE submit, so — unlike the ingest
        # slots, which shed pre-parse by contract and stay tenant-blind —
        # the app key is free here.
        tenant_key=lambda r: r.app_id,
    )
    app[WARN_BATCHER_KEY] = warn_batcher

    # Fleet wiring (docs/scale-out.md): a replica spawned by
    # `cli up --replicas N` carries its identity in env. Peers are
    # subscribed on the local bus so accepted ingest replicates out
    # (gfkb.replicate, at-least-once) and control state gossips out
    # (fleet.control, ephemeral); stale fleet subscriptions from a
    # previous topology are pruned so dead URLs don't burn the breaker.
    from kakveda_tpu.events.bus import TOPIC_FLEET_CONTROL, TOPIC_GFKB_REPLICATE
    from kakveda_tpu.fleet.gossip import FleetView, GossipPublisher

    replica_id = os.environ.get("KAKVEDA_REPLICA_ID", "")
    fleet_peers = [
        u.strip().rstrip("/")
        for u in (os.environ.get("KAKVEDA_FLEET_PEERS", "") or "").split(",")
        if u.strip()
    ]
    gossip_ttl = float(os.environ.get("KAKVEDA_FLEET_GOSSIP_TTL_S", "5") or 5)
    fleet_view = FleetView(ttl_s=gossip_ttl)

    # Sharded ownership (KAKVEDA_FLEET_OWNERSHIP=1, fleet/ownership.py):
    # this replica holds only its owned + standby key ranges; replication
    # is range-scoped on per-peer topics and /replicate fences stale-epoch
    # events. The acknowledged view persists (data_dir/ownership.json) so
    # a restart mid-topology-change resumes at the epoch it had — the
    # spawn env only seeds epoch 1. Off (default): legacy full
    # replication, bit-for-bit.
    own_state = None
    own_path = plat.data_dir / "ownership.json"
    if os.environ.get("KAKVEDA_FLEET_OWNERSHIP", "0") == "1":
        from kakveda_tpu.fleet.ownership import (
            OwnershipState,
            OwnershipView,
            parse_members,
        )

        members = parse_members(os.environ.get("KAKVEDA_FLEET_MEMBERS", ""))
        if not members:  # solo dev run: self owns everything
            members = {replica_id or "r?": ""}
        env_view = OwnershipView(
            members,
            replication=int(os.environ.get("KAKVEDA_FLEET_REPLICATION", "2") or 2),
            vnodes=int(os.environ.get("KAKVEDA_FLEET_VNODES", "64") or 64),
        )
        persisted = OwnershipView.load(own_path)
        own_state = OwnershipState(
            persisted
            if persisted is not None and persisted.epoch > env_view.epoch
            else env_view,
            replica_id or "r?",
        )
        plat.ownership = own_state

    def _sync_fleet_subscriptions() -> None:
        """Ownership-mode bus wiring, re-run on every acknowledged view
        swap: gossip goes to every current member, replication rides ONE
        per-destination topic per peer (own retry/breaker/DLQ lane each),
        and topics of departed members — plus any legacy broadcast
        subscription — are pruned so dead URLs don't burn breakers."""
        from kakveda_tpu.events.bus import (
            TOPIC_GFKB_REPLICATE_PREFIX,
            replicate_topic,
        )

        view = own_state.view
        self_id = own_state.self_id
        want = {
            TOPIC_FLEET_CONTROL: {
                url + "/fleet/gossip"
                for rid, url in view.members.items()
                if rid != self_id and url
            },
            TOPIC_GFKB_REPLICATE: set(),  # never broadcast under ownership
        }
        for rid, url in view.members.items():
            if rid != self_id and url:
                want[replicate_topic(rid)] = {url + "/replicate"}
        for topic in list(plat.bus.topics()):
            if topic.startswith(TOPIC_GFKB_REPLICATE_PREFIX) and topic not in want:
                want[topic] = set()  # departed member
        for topic, urls in want.items():
            for url in plat.bus.url_subscribers(topic):
                if url not in urls:
                    plat.bus.unsubscribe(topic, url)
            for url in sorted(urls):
                plat.bus.subscribe(topic, url)

    gossip: Optional[GossipPublisher] = None
    if own_state is not None and (fleet_peers or len(own_state.view.members) > 1):
        plat.bus.mark_ephemeral(TOPIC_FLEET_CONTROL)
        _sync_fleet_subscriptions()
        gossip = GossipPublisher(
            plat.bus, adm, health, replica_id or "r?", fleet_view,
            interval_s=float(os.environ.get("KAKVEDA_FLEET_GOSSIP_S", "1") or 1),
            ownership=own_state,
        )
    elif fleet_peers:
        plat.bus.mark_ephemeral(TOPIC_FLEET_CONTROL)
        for topic, suffix in (
            (TOPIC_FLEET_CONTROL, "/fleet/gossip"),
            (TOPIC_GFKB_REPLICATE, "/replicate"),
        ):
            want = {p + suffix for p in fleet_peers}
            for url in plat.bus.url_subscribers(topic):
                if url not in want:
                    plat.bus.unsubscribe(topic, url)
            for url in sorted(want):
                plat.bus.subscribe(topic, url)
        gossip = GossipPublisher(
            plat.bus, adm, health, replica_id or "r?", fleet_view,
            interval_s=float(os.environ.get("KAKVEDA_FLEET_GOSSIP_S", "1") or 1),
        )

    async def _on_startup(app):
        warn_batcher.start()
        if gossip is not None:
            import asyncio as _asyncio

            app[_GOSSIP_TASK_KEY] = _asyncio.get_running_loop().create_task(
                gossip.run()
            )
        if sanitize.enabled():
            # Loop-stall watchdog: the runtime half of the static
            # event-loop-blocking rule. Stalls past
            # KAKVEDA_SANITIZE_STALL_MS dump the loop thread's stack to
            # the sanitizer flight recorder (docs/robustness.md).
            wd = sanitize.LoopStallWatchdog()
            await wd.start()
            app[_STALL_WATCHDOG_KEY] = wd

    async def _on_cleanup(app):
        wd = app.get(_STALL_WATCHDOG_KEY)
        if wd is not None:
            await wd.stop()
        t = app.get(_GOSSIP_TASK_KEY)
        if t is not None:
            import asyncio as _asyncio

            t.cancel()
            try:
                await t
            except _asyncio.CancelledError:
                pass
        await warn_batcher.stop()
        plat.bus.close()  # cancel a pending DLQ auto-replay timer

    app.on_startup.append(_on_startup)
    app.on_cleanup.append(_on_cleanup)

    # --- liveness -------------------------------------------------------

    async def healthz(request):
        return web.json_response({"ok": True})

    async def readyz(request):
        """Readiness WITH mode report: degraded (device loss) and the
        brownout ladder are operating states a balancer/operator must see
        — a degraded platform still answers warns (host fallback), so
        ok stays true; routing decisions read the mode fields."""
        body = {
            "ok": True,
            "gfkb_count": plat.gfkb.count,
            "device": health.info(),
            "admission": adm.info(),
            "tiers": plat.gfkb.tiers_info(),
            "native": _native_status(),
        }
        body["fleet"] = {
            "replica_id": replica_id,
            "peers": len(fleet_peers),
            "view": fleet_view.peers(),
            "degraded_any": fleet_view.any_degraded(),
            "worst_brownout": fleet_view.worst_brownout(),
        }
        if own_state is not None:
            view = own_state.view
            owned_arcs, standby_arcs = view.arc_counts(own_state.self_id)
            rows = {"owned": 0, "standby": 0, "foreign": 0}
            # O(distinct shard keys) — app counts, not row scans, per probe.
            for key, n in plat.gfkb.shard_key_counts().items():
                role = view.role(own_state.self_id, key)
                bucket = role if role in ("owner", "standby") else "foreign"
                rows["owned" if bucket == "owner" else bucket] += n
            body["ownership"] = {
                "enabled": True,
                "epoch": view.epoch,
                "replication": view.replication,
                "members": list(view.members),
                "owned_arcs": owned_arcs,
                "standby_arcs": standby_arcs,
                "rows": rows,
            }
        return web.json_response(body)

    # --- ingest ---------------------------------------------------------

    async def ingest(request):
        # Admission runs BEFORE the body is parsed: a shed must cost
        # microseconds, and pydantic-validating a payload we are about to
        # 429 would burn the event-loop time the shed exists to protect.
        _ratelimit(request)
        with adm.slot("ingest"):
            try:
                req = IngestRequest.model_validate(await request.json())
            except (ValidationError, ValueError) as e:
                return _json_error(422, str(e))
            traffic_rec.record("ingest", app_id=req.trace.app_id, n=1)
            with _trace.get_tracer().start_span(
                "gfkb.ingest", app_id=req.trace.app_id, n=1
            ):
                await plat.ingest(req.trace)
        return web.json_response({"ok": True, "trace_id": req.trace.trace_id})

    async def ingest_batch(request):
        """Batched ingest — one validate + one device scatter per batch
        (kakveda_tpu.platform.Platform.ingest_batch), the rate the
        streaming pipeline actually sustains. Returns per-batch failure
        count so callers can track detection rates without a second call.
        Admission gates BEFORE the body parse (shed-while-cheap): under a
        flood, a 429 costs no JSON decode and no pydantic pass — measured
        in the overload bench, validating shed batches was most of the
        event-loop damage."""
        _ratelimit(request)
        with adm.slot("ingest"):
            try:
                req = IngestBatchRequest.model_validate(await request.json())
            except (ValidationError, ValueError) as e:
                return _json_error(422, str(e))
            if not req.traces:
                return web.json_response({"ok": True, "n": 0, "failures": 0})
            traffic_rec.record(
                "ingest", app_id=req.traces[0].app_id, n=len(req.traces)
            )
            with _trace.get_tracer().start_span(
                "gfkb.ingest", app_id=req.traces[0].app_id, n=len(req.traces)
            ):
                signals = await plat.ingest_batch(req.traces)
        return web.json_response(
            {"ok": True, "n": len(req.traces), "failures": len(signals)}
        )

    # --- fleet (replication fan-in + control gossip) --------------------

    _m_fence = None
    _m_stale_view = None
    if own_state is not None:
        from kakveda_tpu.core import metrics as _metrics_mod

        _own_reg = _metrics_mod.get_registry()
        _m_fence = _own_reg.counter(
            "kakveda_fleet_fenced_rows_total",
            "Replicated rows dropped by the ownership-epoch fence (stale "
            "events for ranges this replica no longer holds)",
        )
        _m_stale_view = _own_reg.counter(
            "kakveda_fleet_stale_view_total",
            "Gossip samples revealing a peer at a newer ownership epoch "
            "than the locally acknowledged view",
        )

    async def replicate(request):
        """Apply one bus-replicated ingest event from a peer replica —
        idempotent by event id (GFKB dedup set), through the tiered
        insert path. A failure here (chaos: fleet.replicate_apply) is a
        clean 500 back to the peer's bus, whose retry/breaker/DLQ policy
        owns redelivery; a 429 shed behaves the same way. Either way the
        event converges later — it is never silently dropped here.

        Ownership-epoch fence: a scoped event stamped with an OLDER epoch
        than the acknowledged view (a DLQ replay or straggler retry from
        before a migration) keeps only the rows this replica still holds;
        an event left with none is acknowledged as a clean drop — 2xx, so
        the origin's at-least-once machinery retires it instead of
        retrying a range that migrated away. Rows this replica DOES still
        hold apply idempotently as ever — never a double insert, never an
        un-migrate."""
        try:
            body = await request.json()
        except ValueError as e:
            return _json_error(422, str(e))
        event_id, rows = body.get("id"), body.get("rows")
        if not isinstance(event_id, str) or not isinstance(rows, list):
            return _json_error(422, "id (str) and rows (list) required")
        # Continue the ORIGIN's trace (envelope "trace" stamp, set by
        # Platform.replicate_rows) — replication, DLQ dead-letter and
        # `dlq replay` redelivery all correlate back to the ingest that
        # produced the rows. No stamp → parent under the local request.
        with _trace.get_tracer().start_span(
            "gfkb.replicate_apply",
            traceparent=body.get("trace") or None,
            origin=body.get("origin"), event_id=event_id, n=len(rows),
        ) as rspan:
            dropped = 0
            epoch = body.get("epoch")
            if isinstance(epoch, int):
                rspan.set(epoch=epoch)
            if (
                own_state is not None
                and isinstance(epoch, int)
                and epoch < own_state.view.epoch
            ):
                from kakveda_tpu.fleet.ownership import shard_key_of_row

                view = own_state.view
                kept = [
                    r for r in rows
                    if isinstance(r, dict)
                    and view.is_holder(own_state.self_id, shard_key_of_row(r))
                ]
                dropped = len(rows) - len(kept)
                if dropped:
                    _m_fence.inc(dropped)
                if not kept:
                    rspan.set(dropped=dropped, reason="stale_epoch")
                    return web.json_response(
                        {"ok": True, "applied": 0, "deduped": False,
                         "dropped": dropped, "reason": "stale_epoch"}
                    )
                rows = kept
            _FAULT_REPLICATE.fire()
            import asyncio as _asyncio

            loop = _asyncio.get_running_loop()
            with adm.slot("ingest"):
                try:
                    applied = await loop.run_in_executor(
                        None, plat.gfkb.apply_replication, rows, event_id
                    )
                except (KeyError, ValueError) as e:  # malformed row payload
                    rspan.set(error=type(e).__name__)
                    rspan.end("error")
                    return _json_error(422, f"bad replication rows: {e}")
            rspan.set(applied=applied, deduped=applied == 0)
            out = {"ok": True, "applied": applied, "deduped": applied == 0}
            if dropped:
                out["dropped"] = dropped
            return web.json_response(out)

    async def fleet_ownership_get(request):
        if own_state is None:
            return web.json_response({"enabled": False})
        return web.json_response({"enabled": True, **own_state.view.to_dict()})

    async def fleet_ownership_post(request):
        """Acknowledge a new epoch'd ownership view (the router's
        promotion push, or the rebalance flip). Monotonic: an epoch at or
        below the acknowledged one is a no-op ``stale`` ack — pushes may
        arrive out of order and replays must not regress the view. A real
        swap persists atomically and rewires the per-peer replication
        topics before returning."""
        if own_state is None:
            return _json_error(409, "ownership disabled on this replica")
        from kakveda_tpu.fleet.ownership import OwnershipView

        try:
            new_view = OwnershipView.from_dict(await request.json())
        except (ValueError, KeyError, TypeError) as e:
            return _json_error(422, f"bad ownership view: {e}")
        cur = own_state.view
        if new_view.epoch <= cur.epoch:
            return web.json_response(
                {"ok": True, "stale": True, "epoch": cur.epoch}
            )
        own_state.view = new_view  # one reference write — readers swap whole
        try:
            new_view.save(own_path)
        except OSError as e:
            log.warning("ownership view persist failed: %s", e)
        _sync_fleet_subscriptions()
        log.info(
            "ownership epoch %d -> %d (%d members)",
            cur.epoch, new_view.epoch, len(new_view.members),
        )
        return web.json_response(
            {"ok": True, "stale": False, "epoch": new_view.epoch}
        )

    # Migration export is CONTROL PLANE, not tenant background work: the
    # flood that trips the autoscaler is the same flood a background
    # admission slot would shed this ship behind, and a fleet that cannot
    # migrate while saturated can never scale OUT of saturation
    # (metastable). Bounded by its own tiny in-flight counter instead —
    # shed-never-hang still holds: past the bound it 429s immediately and
    # the router's next rebalance attempt retries.
    export_inflight = 0

    async def fleet_export(request):
        """Migration export (fleet/ownership.py run_rebalance): the rows
        past ``since`` that THIS replica is the responsible source for,
        grouped by gaining target. Pure read — rows ship as replication
        dicts and re-embed deterministically at the target (hashed n-gram
        featurizer), so no vector payloads cross the wire. Runs off the
        event loop under its own control-plane bound (never the
        background class — tenant floods must not starve a migration)."""
        if own_state is None:
            return _json_error(409, "ownership disabled on this replica")
        from kakveda_tpu.fleet.ownership import (
            OwnershipView,
            plan_targets,
            responsible_source,
            shard_key_of_row,
        )

        try:
            body = await request.json()
            old_v = OwnershipView.from_dict(body["old"])
            new_v = OwnershipView.from_dict(body["new"])
            sources = [str(s) for s in body.get("sources") or []]
            since = int(body.get("since", 0))
        except (ValueError, KeyError, TypeError) as e:
            return _json_error(422, f"bad export request: {e}")
        import asyncio as _asyncio

        nonlocal export_inflight
        if export_inflight >= 2:
            return _json_error(429, "export concurrency bound")
        loop = _asyncio.get_running_loop()
        export_inflight += 1
        try:
            rows, count = await loop.run_in_executor(
                None, plat.gfkb.export_rows, since
            )
        finally:
            export_inflight -= 1
        grouped: dict = {}
        for row in rows:
            key = shard_key_of_row(row)
            if responsible_source(key, old_v, sources) != own_state.self_id:
                continue
            for tgt in plan_targets(key, old_v, new_v):
                grouped.setdefault(tgt, []).append(row)
        return web.json_response({"rows": grouped, "count": count})

    async def fleet_gossip(request):
        """Fold one peer control sample into the fleet view and re-feed
        the folded pressure into the local admission controller (an input
        — gate state only ever moves through the controller's own
        single-writer helpers)."""
        try:
            body = await request.json()
        except ValueError as e:
            return _json_error(422, str(e))
        fresh = fleet_view.fold(body) if isinstance(body, dict) else False
        if fresh:
            adm.note_fleet_pressure(
                fleet_view.fleet_pressure(), ttl_s=fleet_view.ttl_s
            )
            if own_state is not None:
                # Stale-ring-view detection: a peer gossiping a newer
                # epoch means this replica missed an ownership push (the
                # router retries it next probe tick; doctor surfaces the
                # disagreement meanwhile).
                peer_epoch = body.get("ownership_epoch")
                if (
                    isinstance(peer_epoch, int)
                    and peer_epoch > own_state.view.epoch
                ):
                    _m_stale_view.inc()
                    log.warning(
                        "stale ownership view: peer %s at epoch %d, local %d",
                        body.get("replica"), peer_epoch, own_state.view.epoch,
                    )
        return web.json_response({"ok": True, "fresh": fresh})

    # --- warn (micro-batched) -------------------------------------------

    async def warn(request):
        try:
            req = WarningRequest.model_validate(await request.json())
        except (ValidationError, ValueError) as e:
            return _json_error(422, str(e))
        traffic_rec.record("warn", app_id=req.app_id, prompt=req.prompt)
        # The batcher's bounded queue is the warn class's shed point (its
        # limit IS the admission bound); a degraded backend still answers
        # here through the GFKB host fallback — warn is the last class to
        # go dark, by design.
        t0 = time.perf_counter()
        with _trace.get_tracer().start_span(
            "gfkb.warn", app_id=req.app_id
        ) as gspan:
            res = await warn_batcher.submit(req)
            gspan.set(
                tier=res.tier, nprobe=res.nprobe, degraded=res.degraded,
                native=_native_avail, action=res.action,
            )
            if res.degraded:
                gspan.outcome = "degraded"
                parent = request.get("trace_span")
                if parent is not None:
                    parent.outcome = "degraded"
        _h_warn.observe(
            time.perf_counter() - t0, exemplar=gspan.trace_id or None
        )
        return web.json_response(res.model_dump())

    # --- GFKB -----------------------------------------------------------

    async def list_failures(request):
        return web.json_response(
            {"failures": [f.model_dump(mode="json") for f in plat.failures()]}
        )

    async def match(request):
        try:
            req = FailureMatchRequest.model_validate(await request.json())
        except (ValidationError, ValueError) as e:
            return _json_error(422, str(e))
        matches = plat.gfkb.match(req.signature_text, failure_type=req.failure_type)
        return web.json_response({"matches": [m.model_dump() for m in matches]})

    async def upsert_failure(request):
        try:
            body = await request.json()
            rec, created = plat.gfkb.upsert_failure(
                failure_type=body["failure_type"],
                signature_text=body["signature_text"],
                app_id=body["app_id"],
                impact_severity=Severity(body["impact_severity"]),
                context_signature=body.get("context_signature"),
                root_cause=body.get("root_cause"),
                resolution=body.get("resolution"),
            )
        except (KeyError, ValueError, ValidationError) as e:
            return _json_error(422, str(e))
        # Manual upserts replicate like ingest-classified rows do — an
        # operator correction must not diverge the fleet's shards. One
        # publish path (Platform.replicate_rows) covers both the legacy
        # broadcast and range-scoped ownership fan-out.
        await plat.replicate_rows(
            [
                {
                    "failure_type": body["failure_type"],
                    "signature_text": body["signature_text"],
                    "app_id": body["app_id"],
                    "impact_severity": body["impact_severity"],
                    "context_signature": body.get("context_signature"),
                    "root_cause": body.get("root_cause"),
                    "resolution": body.get("resolution"),
                }
            ]
        )
        return web.json_response(
            {"ok": True, "created": created, "failure": rec.model_dump(mode="json")}
        )

    async def list_patterns(request):
        return web.json_response(
            {"patterns": [p.model_dump(mode="json") for p in plat.patterns_list()]}
        )

    async def upsert_pattern(request):
        try:
            body = await request.json()
            p, created = plat.gfkb.upsert_pattern(
                name=body["name"],
                failure_ids=body.get("failure_ids", []),
                affected_apps=body.get("affected_apps", []),
                description=body.get("description"),
            )
        except (KeyError, ValueError, ValidationError) as e:
            return _json_error(422, str(e))
        return web.json_response(
            {"ok": True, "created": created, "pattern": p.model_dump(mode="json")}
        )

    # --- health timeline ------------------------------------------------

    async def app_health(request):
        app_id = request.match_info["app_id"]
        limit = min(max(int(request.query.get("limit", 50)), 1), 500)
        return web.json_response({"app_id": app_id, "points": plat.health_history(app_id, limit)})

    # --- event bus (external pub/sub contract) --------------------------

    async def subscribe(request):
        body = await request.json()
        topic, cb = body.get("topic"), body.get("callback_url")
        if not topic or not cb:
            return _json_error(422, "topic and callback_url required")
        n = plat.bus.subscribe(topic, cb)
        return web.json_response({"ok": True, "topic": topic, "subscribers": n})

    async def snapshot(request):
        """Point-in-time GFKB snapshot: restart restores it and replays only
        the log tail (startup at 1M rows drops from minutes to seconds)."""
        import asyncio as _asyncio

        loop = _asyncio.get_running_loop()
        from kakveda_tpu.index.gfkb import SnapshotError

        try:
            with adm.slot("background"):
                path = await loop.run_in_executor(None, plat.gfkb.snapshot)
        except SnapshotError as e:  # persist=False, or aborted by a reload
            return _json_error(409, str(e))
        return web.json_response({"ok": True, "path": str(path), "entries": plat.gfkb.count})

    async def mine_patterns(request):
        """Pattern mining over the GFKB. Body (all optional):
        {"threshold": 0.6, "mode": "auto"|"full"|"incremental"}.
        ``auto`` serves from the streaming cluster state when possible
        (drain deltas, re-emit dirty clusters — milliseconds); ``full``
        forces the whole-corpus device sweep (compaction/audit). The
        response carries freshness fields: the mode actually used, rows
        drained, dirty/total cluster counts, staleness and wall time."""
        try:
            body = await request.json()
        except Exception:  # noqa: BLE001 — empty body is fine
            body = {}
        try:
            threshold = float(body.get("threshold", 0.6))
        except (TypeError, ValueError, AttributeError):
            return _json_error(422, "threshold must be a number")
        mode = body.get("mode", "auto") if isinstance(body, dict) else "auto"
        if mode not in ("auto", "full", "incremental"):
            return _json_error(422, "mode must be auto|full|incremental")
        import asyncio as _asyncio

        loop = _asyncio.get_running_loop()
        with adm.slot("background"):
            found, info = await loop.run_in_executor(None, plat.mine, threshold, mode)
        return web.json_response(
            {
                "ok": True,
                "patterns": [p.model_dump(mode="json") for p in found],
                "mining": info,
            }
        )

    async def unsubscribe(request):
        body = await request.json()
        topic, cb = body.get("topic"), body.get("callback_url")
        if not topic or not cb:
            return _json_error(422, "topic and callback_url required")
        plat.bus.unsubscribe(topic, cb)
        return web.json_response({"ok": True, "topic": topic})

    async def publish(request):
        body = await request.json()
        topic, event = body.get("topic"), body.get("event")
        if not topic or event is None:
            return _json_error(422, "topic and event required")
        delivered = await plat.bus.publish(topic, event)
        return web.json_response({"ok": True, "delivered": delivered})

    async def topics(request):
        return web.json_response({"topics": plat.bus.topics()})

    app.add_routes(
        [
            web.get("/healthz", healthz),
            web.get("/readyz", readyz),
            web.post("/ingest", ingest),
            web.post("/ingest/batch", ingest_batch),
            web.post("/warn", warn),
            web.get("/failures", list_failures),
            web.post("/failures/match", match),
            web.post("/failures/upsert", upsert_failure),
            web.get("/patterns", list_patterns),
            web.post("/patterns/upsert", upsert_pattern),
            web.post("/patterns/mine", mine_patterns),
            web.post("/snapshot", snapshot),
            web.get("/health/{app_id}", app_health),
            web.post("/subscribe", subscribe),
            web.post("/unsubscribe", unsubscribe),
            web.post("/publish", publish),
            web.get("/topics", topics),
            web.post("/replicate", replicate),
            web.post("/fleet/gossip", fleet_gossip),
            web.get("/fleet/ownership", fleet_ownership_get),
            web.post("/fleet/ownership", fleet_ownership_post),
            web.post("/fleet/export", fleet_export),
        ]
    )
    app.add_routes(metrics_routes())
    return app


def make_agent_echo_app(agent_name: str = "agent-echo") -> web.Application:
    """Reference external-agent contract (reference: services/agent_echo/app.py):
    /health, /capabilities, /invoke echoing events back."""
    app = web.Application()

    async def health(request):
        return web.json_response({"ok": True, "service": agent_name, "status": "healthy"})

    async def capabilities(request):
        return web.json_response(
            {
                "name": agent_name,
                "capabilities": ["echo"],
                "events_in": ["*"],
                "events_out": ["echo"],
            }
        )

    async def invoke(request):
        body = await request.json()
        out = {
            "event_type": "echo",
            "payload": {
                "received_event_type": str(body.get("event_type") or "unknown"),
                "received_payload": body.get("payload"),
                "agent": agent_name,
            },
        }
        return web.json_response({"status": "ok", "events": [out]})

    app.add_routes(
        [
            web.get("/health", health),
            web.get("/capabilities", capabilities),
            web.post("/invoke", invoke),
        ]
    )
    return app
