"""Micro-batching request queue for the pre-flight warn hot path.

The reference answers each /warn with its own full TF-IDF pass
(reference: services/warning_policy/app.py:19-72). Here concurrent warn
requests coalesce into one device call: requests enqueue, a drain loop
collects up to ``max_batch`` of them (waiting at most ``deadline_s`` for
stragglers once the first arrives), runs the batch through
``WarningPolicy.warn_batch`` — one compiled matmul+top-k — and resolves
every waiter. Under load the batch fills instantly and per-request cost is
batch_time/B (see bench.py); when idle a lone request pays only the
deadline (default 2 ms) on top of its own match.

Overload protection (core/admission.py): the queue is BOUNDED. Past
``max_queue`` waiting requests, ``submit`` sheds immediately with a typed
``OverloadError`` (HTTP tier: 429 + Retry-After) instead of queueing into
a timeout — under saturation the batcher's drain rate is the ceiling, and
work beyond it must be rejected while it is still cheap to reject.
Observed queue waits feed the admission controller's wait history.

Per-tenant fairness (docs/robustness.md § multi-tenancy): with a
``tenant_key`` extractor and ``KAKVEDA_TENANT_FAIR=1`` (default), batch
COMPOSITION is deficit round-robin over per-tenant subqueues instead of
global FIFO — no tenant takes more than ``KAKVEDA_TENANT_MAX_SHARE`` of a
batch while others have queued work (work-conserving: spare seats go to
whoever has work), and per-tenant order stays FIFO. The submit-side bound
becomes tenant-aware the same way: at ``max_queue`` depth a tenant whose
own queued share is at cap sheds with ``reason="tenant_quota"`` (the
flooder absorbs the shed) while an under-share tenant may ride bounded
slack up to 2x ``max_queue`` (the hard bound nobody crosses). Items a
composition pass defers carry over to the next batch ahead of new queue
pulls, so deferral never reorders a tenant against itself. Per-tenant
counters are bounded and decayed — a key-churn flood cannot grow state.
``KAKVEDA_TENANT_FAIR=0`` or no ``tenant_key`` keeps global FIFO
bit-for-bit.
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict, deque
from typing import (
    Awaitable, Callable, Generic, List, Optional, Sequence, Tuple, TypeVar,
)

from kakveda_tpu.core import metrics as _metrics
from kakveda_tpu.core.admission import (
    AdmissionController,
    _env_float,
    _env_int,
    tenant_fair_enabled,
)

TReq = TypeVar("TReq")
TRes = TypeVar("TRes")

# One queue entry: (request, waiter, enqueue time, tenant key).
_Item = Tuple[TReq, asyncio.Future, float, str]

# Decay cadence for the per-tenant served counters: every N drains the
# counts halve, so "fair share" means RECENT share — a tenant that was
# heavy an hour ago isn't deprioritized forever — and zeros drop, which
# (with the eviction in _bump_served) bounds the table under key churn.
_SERVED_DECAY_EVERY = 256


class MicroBatcher(Generic[TReq, TRes]):
    def __init__(
        self,
        run_batch: Callable[[Sequence[TReq]], List[TRes]],
        *,
        max_batch: int = 64,
        deadline_s: float = 0.002,
        name: str = "warn",
        max_queue: int = 0,
        admission: Optional[AdmissionController] = None,
        klass: str = "warn",
        tenant_key: Optional[Callable[[TReq], str]] = None,
    ):
        self._run_batch = run_batch
        self.max_batch = max_batch
        self.deadline_s = deadline_s
        # 0 = unbounded (library users); the service app passes its
        # admission class bound so the queue can never outgrow what the
        # drain loop retires before callers give up.
        self.max_queue = max_queue
        self._admission = admission
        self._klass = klass
        # Tenant plane — resolved at construction like every knob.
        self._tenant_key = tenant_key
        self._fair = tenant_key is not None and tenant_fair_enabled()
        self._tenant_share = min(1.0, max(
            0.01, _env_float("KAKVEDA_TENANT_MAX_SHARE", 0.5)))
        self._tenant_table_max = max(2, _env_int("KAKVEDA_TENANT_TABLE", 512))
        # Items deferred by a composition pass: drained BEFORE new queue
        # pulls so per-tenant FIFO survives deferral. Bounded ≤ max_batch
        # (a pass considers ≤ 2x max_batch candidates and runs max_batch).
        self._carry: List[_Item] = []
        # served: recent batch seats per tenant (deficit input, decayed).
        # queued: live per-tenant depth for the submit-side quota; keys
        # drop at zero, so it's bounded by the queue depth itself.
        self._served: dict = {}
        self._queued: dict = {}
        self._drains = 0
        self._queue: asyncio.Queue[_Item] = asyncio.Queue()
        self._task: asyncio.Task | None = None
        reg = _metrics.get_registry()
        self._m_depth = reg.gauge(
            "kakveda_microbatch_queue_depth",
            "Requests waiting in a micro-batcher queue", ("batcher",),
        ).labels(batcher=name)
        self._m_size = reg.histogram(
            "kakveda_microbatch_batch_size",
            "Coalesced batch size per micro-batcher drain", ("batcher",),
            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0),
        ).labels(batcher=name)

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._drain())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        # Carried items would otherwise dangle with no drain loop; queued
        # items keep seed behavior (they die with the queue on shutdown).
        for item in self._carry:
            if not item[1].done():
                item[1].cancel()
        self._carry.clear()

    def _depth(self) -> int:
        return self._queue.qsize() + len(self._carry)

    async def submit(self, req: TReq) -> TRes:
        tenant = self._tenant_key(req) if self._fair else ""
        depth = self._depth()
        if self.max_queue and depth >= self.max_queue:
            if not (self._fair and tenant):
                # Seed behavior: global bound, global shed.
                self._shed("queue_full",
                           f"micro-batcher backlog {depth} >= {self.max_queue}")
            cap = max(1, int(self.max_queue * self._tenant_share))
            held = self._queued.get(tenant, 0)
            if held >= cap:
                # The shed lands on whoever owns the backlog — under a
                # noisy-neighbor flood that is the flooder, not a victim
                # arriving into a queue someone else filled.
                self._shed(
                    "tenant_quota",
                    f"tenant {tenant!r} holds {held}/{cap} queued warn slots",
                    tenant=tenant,
                )
            if depth >= 2 * self.max_queue:
                # Hard bound nobody rides past — the slack exists so an
                # under-share tenant survives a full queue, not so total
                # depth grows without limit.
                self._shed(
                    "queue_full",
                    f"micro-batcher backlog {depth} >= {2 * self.max_queue}",
                    tenant=tenant,
                )
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        if self._fair and tenant:
            self._queued[tenant] = self._queued.get(tenant, 0) + 1
        await self._queue.put((req, fut, time.monotonic(), tenant))
        return await fut

    def _shed(self, reason: str, detail: str, tenant: str = "") -> None:
        # Shed while it's still cheap: the typed error carries the
        # drain-rate-derived retry hint when an admission controller
        # is attached (the service app's case).
        if self._admission is not None:
            self._admission.shed(self._klass, reason, detail=detail,
                                 tenant=tenant)
        from kakveda_tpu.core.admission import OverloadError

        raise OverloadError(
            f"micro-batcher shed ({reason}): {detail}",
            klass=self._klass, reason=reason, tenant=tenant,
        )

    # -- batch collection -------------------------------------------------

    async def _collect(self) -> List[_Item]:
        if not self._fair:
            return await self._collect_fifo(self.max_batch)
        if self._carry:
            # Deferred items go first; top up with whatever is already
            # waiting (no deadline wait — the carry proves oversubscription
            # and the queue is being fed faster than it drains).
            cands = self._carry
            self._carry = []
            while len(cands) < 2 * self.max_batch:
                try:
                    cands.append(self._queue.get_nowait())
                except asyncio.QueueEmpty:
                    break
        else:
            # Pull up to 2x max_batch so composition sees the cross-tenant
            # mix the cap is supposed to act on; the overflow carries.
            cands = await self._collect_fifo(2 * self.max_batch)
        return self._compose(cands)

    async def _collect_fifo(self, limit: int) -> List[_Item]:
        first = await self._queue.get()
        batch = [first]
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.deadline_s
        while len(batch) < limit:
            timeout = deadline - loop.time()
            if timeout <= 0:
                break
            try:
                batch.append(await asyncio.wait_for(self._queue.get(), timeout))
            except asyncio.TimeoutError:
                break
        return batch

    def _compose(self, cands: List[_Item]) -> List[_Item]:
        """Deficit round-robin batch composition over per-tenant subqueues.
        Per-tenant FIFO is preserved (each subqueue is a deque in arrival
        order); the per-tenant cap binds only while other tenants have
        queued work; leftovers carry in original arrival order."""
        groups: "OrderedDict[str, deque]" = OrderedDict()
        for item in cands:
            groups.setdefault(item[3], deque()).append(item)
        if len(groups) <= 1:
            batch, leftover = cands[: self.max_batch], cands[self.max_batch:]
        else:
            cap = max(1, int(self.max_batch * self._tenant_share))
            taken = {t: 0 for t in groups}
            batch = []
            while len(batch) < self.max_batch:
                elig = [t for t in groups if groups[t] and taken[t] < cap]
                if not elig:
                    # Everyone with work is capped: relax the cap rather
                    # than run a short batch (work-conserving).
                    elig = [t for t in groups if groups[t]]
                    if not elig:
                        break
                t = min(elig, key=lambda x: (
                    self._served.get(x, 0) + taken[x], x))
                batch.append(groups[t].popleft())
                taken[t] += 1
            picked = set(map(id, batch))
            leftover = [it for it in cands if id(it) not in picked]
            for t, n in taken.items():
                if n:
                    self._bump_served(t, n)
        self._carry = leftover
        for item in batch:
            t = item[3]
            if t:
                left = self._queued.get(t, 0) - 1
                if left > 0:
                    self._queued[t] = left
                else:
                    self._queued.pop(t, None)
        self._drains += 1
        if self._drains % _SERVED_DECAY_EVERY == 0:
            self._served = {
                t: n // 2 for t, n in self._served.items() if n // 2 > 0
            }
        return batch

    def _bump_served(self, tenant: str, n: int) -> None:
        if tenant not in self._served and len(self._served) >= self._tenant_table_max:
            # Evict the heaviest-served key: it re-enters at zero (a brief
            # priority boost), which is the safe failure direction — a
            # bounded table must never deprioritize an unknown tenant.
            heaviest = max(self._served, key=self._served.get)
            del self._served[heaviest]
        self._served[tenant] = self._served.get(tenant, 0) + n

    async def _drain(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            batch = await self._collect()
            self._m_size.observe(len(batch))
            self._m_depth.set(self._depth())
            if self._admission is not None:
                # Oldest item's wait = the batch's worst queue delay; one
                # sample per drain keeps the wait history cheap and honest.
                self._admission.note_wait(
                    self._klass, time.monotonic() - batch[0][2]
                )
            reqs = [b[0] for b in batch]
            try:
                # The device call is sync; run it off-loop so new requests
                # keep enqueueing while the match executes.
                results = await loop.run_in_executor(None, self._run_batch, reqs)
                for (_, fut, _, _), res in zip(batch, results):
                    if not fut.done():
                        fut.set_result(res)
            except Exception as e:  # noqa: BLE001 — propagate to all waiters
                for _, fut, _, _ in batch:
                    if not fut.done():
                        fut.set_exception(e)
