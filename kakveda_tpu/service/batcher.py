"""Micro-batching request queue for the pre-flight warn hot path.

The reference answers each /warn with its own full TF-IDF pass
(reference: services/warning_policy/app.py:19-72). Here concurrent warn
requests coalesce into one device call: requests enqueue, a drain loop
collects up to ``max_batch`` of them (waiting at most ``deadline_s`` for
stragglers once the first arrives), runs the batch through
``WarningPolicy.warn_batch`` — one compiled matmul+top-k — and resolves
every waiter. Under load the batch fills instantly and per-request cost is
batch_time/B (see bench.py); when idle a lone request pays only the
deadline (default 2 ms) on top of its own match.

Overload protection (core/admission.py): the queue is BOUNDED. Past
``max_queue`` waiting requests, ``submit`` sheds immediately with a typed
``OverloadError`` (HTTP tier: 429 + Retry-After) instead of queueing into
a timeout — under saturation the batcher's drain rate is the ceiling, and
work beyond it must be rejected while it is still cheap to reject.
Observed queue waits feed the admission controller's wait history.
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable, Generic, List, Optional, Sequence, Tuple, TypeVar

from kakveda_tpu.core import metrics as _metrics
from kakveda_tpu.core.admission import AdmissionController

TReq = TypeVar("TReq")
TRes = TypeVar("TRes")


class MicroBatcher(Generic[TReq, TRes]):
    def __init__(
        self,
        run_batch: Callable[[Sequence[TReq]], List[TRes]],
        *,
        max_batch: int = 64,
        deadline_s: float = 0.002,
        name: str = "warn",
        max_queue: int = 0,
        admission: Optional[AdmissionController] = None,
        klass: str = "warn",
    ):
        self._run_batch = run_batch
        self.max_batch = max_batch
        self.deadline_s = deadline_s
        # 0 = unbounded (library users); the service app passes its
        # admission class bound so the queue can never outgrow what the
        # drain loop retires before callers give up.
        self.max_queue = max_queue
        self._admission = admission
        self._klass = klass
        self._queue: asyncio.Queue[Tuple[TReq, asyncio.Future, float]] = asyncio.Queue()
        self._task: asyncio.Task | None = None
        reg = _metrics.get_registry()
        self._m_depth = reg.gauge(
            "kakveda_microbatch_queue_depth",
            "Requests waiting in a micro-batcher queue", ("batcher",),
        ).labels(batcher=name)
        self._m_size = reg.histogram(
            "kakveda_microbatch_batch_size",
            "Coalesced batch size per micro-batcher drain", ("batcher",),
            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0),
        ).labels(batcher=name)

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._drain())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def submit(self, req: TReq) -> TRes:
        if self.max_queue and self._queue.qsize() >= self.max_queue:
            # Shed while it's still cheap: the typed error carries the
            # drain-rate-derived retry hint when an admission controller
            # is attached (the service app's case).
            if self._admission is not None:
                self._admission.shed(
                    self._klass, "queue_full",
                    detail=f"micro-batcher backlog {self._queue.qsize()} "
                           f">= {self.max_queue}",
                )
            from kakveda_tpu.core.admission import OverloadError

            raise OverloadError(
                f"micro-batcher queue full ({self._queue.qsize()})",
                klass=self._klass, reason="queue_full",
            )
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put((req, fut, time.monotonic()))
        return await fut

    async def _collect(self) -> List[Tuple[TReq, asyncio.Future, float]]:
        first = await self._queue.get()
        batch = [first]
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.deadline_s
        while len(batch) < self.max_batch:
            timeout = deadline - loop.time()
            if timeout <= 0:
                break
            try:
                batch.append(await asyncio.wait_for(self._queue.get(), timeout))
            except asyncio.TimeoutError:
                break
        return batch

    async def _drain(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            batch = await self._collect()
            self._m_size.observe(len(batch))
            self._m_depth.set(self._queue.qsize())
            if self._admission is not None:
                # Oldest item's wait = the batch's worst queue delay; one
                # sample per drain keeps the wait history cheap and honest.
                self._admission.note_wait(
                    self._klass, time.monotonic() - batch[0][2]
                )
            reqs = [r for r, _, _ in batch]
            try:
                # The device call is sync; run it off-loop so new requests
                # keep enqueueing while the match executes.
                results = await loop.run_in_executor(None, self._run_batch, reqs)
                for (_, fut, _), res in zip(batch, results):
                    if not fut.done():
                        fut.set_result(res)
            except Exception as e:  # noqa: BLE001 — propagate to all waiters
                for _, fut, _ in batch:
                    if not fut.done():
                        fut.set_exception(e)
