"""Server entry point: `kakveda-tpu up` / `python -m kakveda_tpu.service`.

Runs the platform API (reference port 8100-8106 contracts) and the
dashboard (reference port 8110) from one process over one shared
intelligence core — two listeners, zero HTTP hops between pipeline stages.
"""

from __future__ import annotations

import asyncio
import logging

from aiohttp import web

from kakveda_tpu.core.runtime import get_runtime_config, setup_logging
from kakveda_tpu.platform import Platform
from kakveda_tpu.service.app import make_app

log = logging.getLogger("kakveda.service")


async def _serve(
    plat: Platform, host: str, port: int, dashboard_port: int | None
) -> None:
    api_app = make_app(plat)
    api_runner = web.AppRunner(api_app)
    await api_runner.setup()
    await web.TCPSite(api_runner, host, port).start()
    log.info("platform API on http://%s:%d (gfkb entries: %d)", host, port, plat.gfkb.count)

    if dashboard_port:
        from kakveda_tpu.dashboard.app import make_dashboard_app

        dash_app = make_dashboard_app(platform=plat)
        dash_runner = web.AppRunner(dash_app)
        await dash_runner.setup()
        await web.TCPSite(dash_runner, host, dashboard_port).start()
        log.info("dashboard on http://%s:%d", host, dashboard_port)

    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await api_runner.cleanup()
        # Graceful shutdown snapshot: the next start restores it and replays
        # only the log tail instead of re-embedding the whole GFKB.
        try:
            plat.gfkb.snapshot()
            log.info("gfkb snapshot written (%d entries)", plat.gfkb.count)
        except Exception as e:  # noqa: BLE001 — shutdown must not fail on this
            log.warning("shutdown snapshot failed: %s", e)


def run_server(
    host: str = "127.0.0.1",
    port: int = 8100,
    data_dir: str | None = None,
    dashboard_port: int | None = 8110,
) -> int:
    setup_logging(service_name="kakveda-tpu")
    cfg = get_runtime_config(service_name="kakveda-tpu")

    # Honor JAX_PLATFORMS even on images whose sitecustomize pins the
    # platform through jax.config (where the env var alone is ignored) —
    # operators use it to run the service on CPU for dev/tests.
    import os

    plat_env = os.environ.get("JAX_PLATFORMS")
    if plat_env:
        import jax

        try:
            jax.config.update("jax_platforms", plat_env)
        except Exception as e:  # noqa: BLE001 — best effort, never fatal
            log.warning("could not apply JAX_PLATFORMS=%s: %s", plat_env, e)

    # Join the multi-host world (if configured) BEFORE the Platform builds
    # its mesh — jax.devices() must already span the pod.
    from kakveda_tpu.parallel.distributed import initialize_multihost

    initialize_multihost()

    # Compile-and-transfer ledger (KAKVEDA_LEDGER=1) installs BEFORE the
    # Platform so its jit wrapping covers the match/ingest programs built
    # at construction; /metrics then carries kakveda_compile_total and
    # kakveda_transfer_bytes (docs/observability.md).
    from kakveda_tpu.core import ledger

    if ledger.maybe_install():
        log.info("compile-and-transfer ledger installed (KAKVEDA_LEDGER=1)")
    plat = Platform(data_dir=data_dir or cfg.data_dir, capacity=cfg.index_capacity)

    # Generational-GC tuning for the streaming path: ingest allocates ~2k
    # short-lived objects per 512-batch (pydantic records + dicts), which
    # trips gen-2 collections every ~13 batches — observed as periodic
    # ~100 ms pauses in an otherwise ~30 ms/batch stream. Freezing the
    # startup object graph takes the permanent majority of the heap out of
    # every collection; raised thresholds amortize the rest.
    # KAKVEDA_GC_TUNE=0 restores CPython defaults.
    if os.environ.get("KAKVEDA_GC_TUNE", "1") != "0":
        import gc

        gc.collect()
        gc.freeze()
        gc.set_threshold(50_000, 20, 20)

    # Zero-code operator profiling: KAKVEDA_PROFILE_DIR=/path captures an
    # XPlane trace of one warm pre-flight match at startup.
    from kakveda_tpu.core import profiling
    from kakveda_tpu.core.schemas import WarningRequest

    logdir = profiling.startup_profile_dir()
    if logdir:
        probe = WarningRequest(app_id="_profile", prompt="startup profile probe", tools=[], env={})
        plat.warn(probe)  # warm/compile outside the trace
        with profiling.profile(logdir):
            plat.warn(probe)
    try:
        asyncio.run(_serve(plat, host, port, dashboard_port))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    run_server()
