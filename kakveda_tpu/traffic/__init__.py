"""Record-replay traffic harness — scenario storms, chaos timelines,
SLO-gated graceful degradation.

The robustness layers below this package (admission shedding, the brownout
ladder, device-loss degraded mode, bus DLQ, fleet gossip — PRs 5 and 8)
were only ever validated against synthetic saturation floods. This package
is the load *generator* that turns "we degrade gracefully" into a
regression-gated claim: seeded scenario generators produce the load shapes
that actually break systems (hot-key skew, diurnal waves, failure storms,
adversarial near-duplicate floods), an open-loop replayer drives them
through the real HTTP stack at a controllable speed factor, a chaos
timeline arms `core/faults.py` sites and kills fleet replicas at scheduled
offsets mid-run, and declarative SLO gates assert the degradation contract
(bounded warn p95, sheds confined to sheddable classes, zero hung
requests, zero lost warns, ladder recovery after the storm).

Modules — docs/robustness.md § traffic harness has the operator view:

* :mod:`capture`   — flight-recorder request timelines ⇄ replayable JSONL
  traffic logs (`kakveda-tpu traffic record`).
* :mod:`scenarios` — seeded generators; same seed → identical arrival
  schedule and app-key sequence (the determinism tier-1 asserts).
* :mod:`replay`    — open-loop replay + chaos-timeline executor
  (`traffic replay`, `traffic storm`).
* :mod:`slo`       — per-scenario declarative gates and their evaluation,
  folded into the `storm` bench row.
"""

from kakveda_tpu.traffic.capture import (  # noqa: F401
    from_flightrecorder,
    read_log,
    write_log,
)
from kakveda_tpu.traffic.replay import (  # noqa: F401
    ReplayResult,
    replay,
    run_chaos,
    run_scenario,
)
from kakveda_tpu.traffic.scenarios import SCENARIOS, Scenario, make_scenario  # noqa: F401
from kakveda_tpu.traffic.slo import SLO, SLOReport, evaluate  # noqa: F401
