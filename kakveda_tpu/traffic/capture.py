"""Traffic capture: flight-recorder request timelines → replayable JSONL.

The service app's middleware-adjacent handlers record every warn/ingest
arrival into a bounded ``FlightRecorder("traffic")`` ring (service/app.py;
``KAKVEDA_TRAFFIC_CAPTURE=0`` disables, ``KAKVEDA_TRAFFIC_CAPTURE_N``
sizes the ring). ``kakveda-tpu traffic record`` pulls ``GET
/flightrecorder`` from a live server and this module converts that dump
into a traffic log the replayer can re-drive:

    {"kakveda_traffic_log": 1, "meta": {…}}          ← header line
    {"t": 0.0,  "method": "POST", "path": "/warn", "klass": "warn",
     "app_id": "app-3", "body": {…}, "phase": "capture"}
    {"t": 0.42, …}

Offsets are relative to the first captured event — a traffic log carries
the SHAPE of traffic (arrival schedule, class mix, app-key sequence,
payload skeletons), which is what the robustness layers react to. Ingest
bodies are re-synthesized deterministically at conversion time (the ring
records counts and keys, not multi-KB trace batches).

Reading is skip-with-warning per line (the bus subscription-replay
contract, docs/robustness.md): a torn or hand-edited log replays what it
can instead of refusing the whole run.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Iterable, List, Optional, Tuple

log = logging.getLogger("kakveda.traffic")

TRAFFIC_LOG_VERSION = 1


def write_log(path: str | Path, events: Iterable[dict],
              meta: Optional[dict] = None) -> int:
    """Write a traffic log (header + one event per line, offset-sorted).
    Returns the number of events written."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    evs = sorted(events, key=lambda e: float(e.get("t", 0.0)))
    tmp = path.with_suffix(path.suffix + ".tmp")
    with tmp.open("w", encoding="utf-8") as f:
        f.write(json.dumps(
            {"kakveda_traffic_log": TRAFFIC_LOG_VERSION, "meta": meta or {}},
            ensure_ascii=False,
        ) + "\n")
        for e in evs:
            f.write(json.dumps(e, ensure_ascii=False) + "\n")
    tmp.replace(path)
    return len(evs)


def read_log(path: str | Path) -> Tuple[dict, List[dict]]:
    """Read a traffic log → ``(meta, events)``. Malformed lines are
    skipped with a warning; a missing header is tolerated (every line is
    then an event)."""
    meta: dict = {}
    events: List[dict] = []
    with Path(path).open("r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                log.warning("traffic log %s:%d unparseable, skipped: %s",
                            path, lineno, e)
                continue
            if not isinstance(rec, dict):
                log.warning("traffic log %s:%d not an object, skipped", path, lineno)
                continue
            if "kakveda_traffic_log" in rec:
                meta = dict(rec.get("meta") or {})
                meta["version"] = rec["kakveda_traffic_log"]
                continue
            if "t" not in rec:
                log.warning("traffic log %s:%d has no offset, skipped", path, lineno)
                continue
            events.append(rec)
    events.sort(key=lambda e: float(e.get("t", 0.0)))
    return meta, events


def from_flightrecorder(payload: dict, *, seed: int = 0,
                        recorder: str = "traffic") -> List[dict]:
    """Convert a ``GET /flightrecorder`` dump into replayable events.

    Only the named recorder's ring is read (default the service tier's
    ``traffic`` ring). ``warn`` records replay byte-faithfully (app_id +
    prompt were captured); ``ingest`` records replay shape-faithfully —
    the batch is re-synthesized with the captured size and app key, seeded
    so the same dump always converts to the same log."""
    from kakveda_tpu.traffic.scenarios import synth_traces

    # A real server has exactly one ring per name, but several service
    # apps can share one process (in-process fleet drills, tests) and
    # dump_recorders() reports every LIVE ring — pick the most recently
    # active one (events carry epoch t), never first-match: a stale
    # empty ring from a torn-down app must not shadow the live capture.
    ring: list = []
    ring_t = float("-inf")
    for rec in payload.get("recorders", []):
        if rec.get("name") != recorder:
            continue
        events = rec.get("events", [])
        t = max((float(e.get("t", 0.0)) for e in events), default=float("-inf"))
        if t > ring_t:
            ring, ring_t = events, t
    evs: List[dict] = []
    if not ring:
        return evs
    t0 = min(float(e.get("t", 0.0)) for e in ring)
    for i, e in enumerate(sorted(ring, key=lambda r: float(r.get("t", 0.0)))):
        kind = e.get("kind")
        t = round(float(e.get("t", t0)) - t0, 6)
        if kind == "warn":
            app = str(e.get("app_id", "app-0"))
            evs.append({
                "t": t, "method": "POST", "path": "/warn", "klass": "warn",
                "app_id": app, "phase": "capture",
                "body": {"app_id": app, "prompt": str(e.get("prompt", ""))},
            })
        elif kind == "ingest":
            app = str(e.get("app_id", "app-0"))
            n = max(1, int(e.get("n", 1)))
            evs.append({
                "t": t, "method": "POST", "path": "/ingest/batch",
                "klass": "ingest", "app_id": app, "phase": "capture",
                "body": {"traces": synth_traces(seed + i, app, n)},
            })
    return evs
