"""Open-loop replay + chaos-timeline executor.

Open-loop is the point: arrivals fire on the SCHEDULE (``t0 + t/speed``),
not when the previous response lands — a closed-loop client self-throttles
against a degrading server and hides exactly the metastable failure modes
this harness exists to catch. Concurrency is still bounded (the semaphore
is acquired INSIDE the spawned task, so admission sheds and slow responses
delay sends without deforming the arrival schedule; the resulting lateness
is measured and reported rather than hidden).

Every dispatch terminates in exactly one bucket:

* ``ok``        — 2xx.
* ``shed``      — 429 (typed OverloadError surfaced by the service tier).
* ``degraded``  — 503 (device-loss fail-fast path).
* ``error``     — any other status, connection error, or an armed
  ``traffic.dispatch`` fault (a replay client losing the request).
* ``hung``      — no terminal outcome within ``timeout_s``. The zero-hung
  SLO gate is the end-to-end SHED-NEVER-HANG check.

``run_chaos`` applies timeline actions at offsets (same clock + speed
factor as the replay): ``faults`` re-arms `core/faults.py` (empty spec
ends the outage window — disarm IS recovery), ``kill_replica`` /
``restart_replica`` drive a FleetSupervisor, ``crash_replica`` hard-kills
one (SIGKILL — the dead-owner drill; skipped with a warning when the
replica may hold the TPU lease, per the never-kill-the-lease-holder
gotcha), ``fleet_pressure`` feeds
``AdmissionController.note_fleet_pressure`` exactly as a peer's gossip
sample would, and ``scale_events`` snapshots the threaded autoscaler's
decision counters into the chaos log (a measurement, not a mutation).
Actions needing a handle the caller didn't provide are skipped with a
warning, never fatal — a single-process storm simply has no replicas to
kill.
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, List, Optional

from kakveda_tpu.core import faults
from kakveda_tpu.core import trace as _trace
from kakveda_tpu.core.faults import FaultInjected

log = logging.getLogger("kakveda.traffic")

__all__ = ["ReplayResult", "replay", "run_chaos", "run_scenario"]

# Replay client losing a request before the send — the harness's own
# failure mode, threaded like every other failure path (docs/robustness.md
# catalog). Resolved once at import per the fault-site-once rule.
_SITE_DISPATCH = faults.site("traffic.dispatch")

_DEF_CONC = int(os.environ.get("KAKVEDA_TRAFFIC_MAX_CONC", "64"))
_DEF_TIMEOUT = float(os.environ.get("KAKVEDA_TRAFFIC_TIMEOUT_S", "15"))

PostFn = Callable[[str, dict], Awaitable[int]]
LocalFn = Callable[[dict], Awaitable[float]]


@dataclass
class ReplayResult:
    """Terminal accounting for one replay. ``records`` is one dict per
    dispatched event: klass/phase/status/latency_ms/late_ms."""

    records: List[dict] = field(default_factory=list)
    generated_counts: Dict[str, int] = field(default_factory=dict)
    skipped: Dict[str, int] = field(default_factory=dict)
    ttfts_ms: List[float] = field(default_factory=list)
    ladder_recovery_s: Optional[float] = None
    wall_s: float = 0.0
    # Caller-stuffed side facts (e.g. scatter-gather partial counts from
    # a custom post fn) — gates like SLO.max_partial_rate read these.
    notes: Dict[str, float] = field(default_factory=dict)

    def latencies_ms(self, klass: str, phase: Optional[str] = None) -> List[float]:
        return [r["latency_ms"] for r in self.records
                if r["klass"] == klass and r["status"] == "ok"
                and (phase is None or r["phase"] == phase)]

    def ttft_ms(self) -> List[float]:
        return list(self.ttfts_ms)

    def class_counts(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for r in self.records:
            out.setdefault(r["klass"], {})
            out[r["klass"]][r["status"]] = out[r["klass"]].get(r["status"], 0) + 1
        return out

    def tenant_counts(self, klass: Optional[str] = None) -> Dict[str, Dict[str, int]]:
        """Per-tenant terminal buckets: {app: {status: n}} over records that
        carry an app tag (events without one aggregate under ``""``). The
        input to the noisy-neighbor gates — who absorbed the shed."""
        out: Dict[str, Dict[str, int]] = {}
        for r in self.records:
            if klass is not None and r["klass"] != klass:
                continue
            app = r.get("app", "")
            out.setdefault(app, {})
            out[app][r["status"]] = out[app].get(r["status"], 0) + 1
        return out

    def tenant_latencies_ms(self, app: str, klass: str = "warn",
                            phase: Optional[str] = None) -> List[float]:
        """One tenant's ok-latency series (the victim-p95 gate input)."""
        return [r["latency_ms"] for r in self.records
                if r.get("app", "") == app and r["klass"] == klass
                and r["status"] == "ok"
                and (phase is None or r["phase"] == phase)]

    def generated(self, klass: str) -> int:
        # Skipped LOCAL events (no dispatcher provided) were never
        # generated INTO the system — they don't count as lost.
        return (self.generated_counts.get(klass, 0)
                - self.skipped.get(klass, 0))

    def late_p95_ms(self) -> float:
        from kakveda_tpu.traffic.slo import percentile
        return round(percentile([r["late_ms"] for r in self.records], 95), 3)

    def to_dict(self) -> dict:
        return {
            "dispatched": len(self.records),
            "generated": dict(self.generated_counts),
            "skipped": dict(self.skipped),
            "class_counts": self.class_counts(),
            "late_p95_ms": self.late_p95_ms(),
            "ladder_recovery_s": self.ladder_recovery_s,
            "wall_s": round(self.wall_s, 3),
            **({"notes": dict(self.notes)} if self.notes else {}),
        }


async def _dispatch(e: dict, sched_t: float, sem: asyncio.Semaphore,
                    post: PostFn, extra: Dict[str, LocalFn],
                    timeout_s: float, result: ReplayResult) -> None:
    # "app" (tenant identity) + "t" (scheduled offset) feed the per-tenant
    # SLO gates (max_victim_shed_rate / victim_p95_x_baseline /
    # max_tenant_starvation_s) — untagged events simply leave them vacuous.
    rec = {"klass": e.get("klass", "warn"), "phase": e.get("phase", ""),
           "app": e.get("app_id", ""), "t": float(e.get("t", 0.0)),
           "status": "error", "latency_ms": 0.0, "late_ms": 0.0}
    loop = asyncio.get_running_loop()
    # One span per dispatch, ended in the SAME finally that buckets the
    # record — a dispatch span terminates in exactly one bucket, so the
    # storm bench's zero-orphan certification mirrors the zero-lost
    # accounting. The span is client-side only: the request body stays
    # byte-faithful for warn replay.
    span = _trace.get_tracer().start_span(
        "traffic.dispatch", klass=rec["klass"], path=e.get("path", ""),
        phase=rec["phase"])
    if span.trace_id:
        rec["trace"] = span.trace_id
    span.activate()
    try:
        async with sem:
            send_t = loop.time()
            rec["late_ms"] = round(max(0.0, send_t - sched_t) * 1e3, 3)
            if _SITE_DISPATCH.armed:
                _SITE_DISPATCH.fire()
            if e.get("method") == "LOCAL":
                fn = extra.get(e.get("path", ""))
                if fn is None:
                    rec["status"] = "skipped"
                    result.skipped[rec["klass"]] = (
                        result.skipped.get(rec["klass"], 0) + 1)
                    return
                ttft = await asyncio.wait_for(fn(e), timeout_s)
                rec["status"] = "ok"
                if ttft is not None:
                    result.ttfts_ms.append(round(float(ttft) * 1e3, 3))
            else:
                status = await asyncio.wait_for(
                    post(e["path"], e.get("body", {})), timeout_s)
                rec["status"] = ("ok" if 200 <= status < 300
                                 else "shed" if status == 429
                                 else "degraded" if status == 503
                                 else "error")
            rec["latency_ms"] = round((loop.time() - send_t) * 1e3, 3)
    except asyncio.TimeoutError:
        rec["status"] = "hung"
        rec["latency_ms"] = round(timeout_s * 1e3, 3)
    except FaultInjected as f:
        rec["status"] = "error"
        log.warning("traffic.dispatch fault dropped a request: %s", f)
    except asyncio.CancelledError:
        rec["status"] = "hung"
        raise
    except Exception as ex:
        rec["status"] = "error"
        log.warning("dispatch %s failed: %s: %s",
                    e.get("path"), type(ex).__name__, ex)
    finally:
        span.deactivate()
        span.end(rec["status"], late_ms=rec["late_ms"],
                 latency_ms=rec["latency_ms"])
        result.records.append(rec)


async def replay(events: List[dict], *, post: PostFn, speed: float = 1.0,
                 max_concurrency: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 extra_dispatch: Optional[Dict[str, LocalFn]] = None,
                 result: Optional[ReplayResult] = None) -> ReplayResult:
    """Drive ``events`` open-loop through ``post``. ``speed=2`` replays a
    10 s log in 5 s. Returns after every spawned dispatch terminated."""
    speed = max(1e-6, float(speed))
    sem = asyncio.Semaphore(max_concurrency or _DEF_CONC)
    timeout_s = _DEF_TIMEOUT if timeout_s is None else float(timeout_s)
    extra = extra_dispatch or {}
    res = result if result is not None else ReplayResult()
    for e in events:
        k = e.get("klass", "warn")
        res.generated_counts[k] = res.generated_counts.get(k, 0) + 1

    loop = asyncio.get_running_loop()
    t0 = loop.time()
    tasks: List[asyncio.Task] = []
    for e in sorted(events, key=lambda x: float(x.get("t", 0.0))):
        sched_t = t0 + float(e.get("t", 0.0)) / speed
        delay = sched_t - loop.time()
        if delay > 0:
            await asyncio.sleep(delay)
        tasks.append(loop.create_task(
            _dispatch(e, sched_t, sem, post, extra, timeout_s, res)))
    if tasks:
        await asyncio.gather(*tasks, return_exceptions=True)
    res.wall_s = loop.time() - t0
    return res


async def run_chaos(timeline: List[dict], *, speed: float = 1.0,
                    supervisor=None, admission=None, autoscaler=None,
                    callbacks: Optional[Dict[str, Callable]] = None,
                    t0: Optional[float] = None) -> List[dict]:
    """Apply chaos actions at their offsets (``t0`` lets the caller share
    the replay's clock). Returns a log of applied/skipped actions.

    ``callbacks`` maps extra action kinds to handles the caller owns
    (e.g. ``{"rebalance": fn}`` for the rebalance-under-storm drill) —
    a coroutine function is awaited, a plain callable runs off the event
    loop. Still only existing seams: a missing handle skips-with-warning
    like any other unknown action."""
    speed = max(1e-6, float(speed))
    loop = asyncio.get_running_loop()
    base = loop.time() if t0 is None else t0
    applied: List[dict] = []
    for act in sorted(timeline, key=lambda a: float(a.get("t", 0.0))):
        delay = base + float(act.get("t", 0.0)) / speed - loop.time()
        if delay > 0:
            await asyncio.sleep(delay)
        kind = act.get("action")
        entry = {"t": act.get("t"), "action": kind, "applied": True}
        try:
            if kind == "faults":
                spec = str(act.get("spec", ""))
                if spec:
                    faults.arm(spec, seed=int(act.get("seed", 0)))
                else:
                    faults.disarm()
            elif kind in ("kill_replica", "restart_replica"):
                if supervisor is None:
                    entry.update(applied=False, reason="no supervisor")
                else:
                    i = int(act.get("replica", 0))
                    # stop() waits out SIGTERM (never SIGKILL — TPU
                    # lease); keep that wait off the event loop.
                    fn = supervisor.stop if kind == "kill_replica" else supervisor.start
                    await loop.run_in_executor(None, fn, i)
            elif kind == "crash_replica":
                # Hard owner death (the replacement drill): SIGKILL with
                # zero grace so the replica cannot drain — UNLESS it may
                # hold the TPU lease (CLAUDE.md gotcha: a killed lease
                # holder wedges every later backend init for hours).
                if supervisor is None:
                    entry.update(applied=False, reason="no supervisor")
                else:
                    i = int(act.get("replica", 0))
                    if supervisor.may_hold_device_lease(i):
                        entry.update(applied=False,
                                     reason="replica may hold TPU lease")
                    else:
                        await loop.run_in_executor(
                            None,
                            lambda: supervisor.stop(
                                i, timeout_s=0.5, sig=signal.SIGKILL),
                        )
            elif kind == "scale_events":
                # Measurement-only: snapshot the autoscaler's decision
                # ledger into the chaos log at this offset.
                if autoscaler is None:
                    entry.update(applied=False, reason="no autoscaler")
                else:
                    entry["scale"] = {
                        "counts": autoscaler.decision_counts(),
                        "flaps": autoscaler.flap_count(),
                        "state": autoscaler.info().get("state"),
                    }
            elif kind == "fleet_pressure":
                if admission is None:
                    entry.update(applied=False, reason="no admission")
                else:
                    admission.note_fleet_pressure(
                        float(act.get("pressure", 0.0)),
                        ttl_s=float(act.get("ttl_s", 5.0)))
            elif callbacks and kind in callbacks:
                fn = callbacks[kind]
                if asyncio.iscoroutinefunction(fn):
                    await fn(act)
                else:
                    await loop.run_in_executor(None, fn, act)
            else:
                entry.update(applied=False, reason=f"unknown action {kind!r}")
        except Exception as ex:
            entry.update(applied=False, reason=f"{type(ex).__name__}: {ex}")
            log.warning("chaos action %r failed: %s", kind, ex)
        if not entry["applied"]:
            log.warning("chaos action skipped: %s", entry)
        applied.append(entry)
    return applied


async def _watch_recovery(result: ReplayResult, admission, storm_end_s: float,
                          speed: float, t0: float, horizon_s: float) -> None:
    """Poll the ladder after the storm window closes; record how long it
    takes to get back to ``normal`` (transitions themselves still move
    only through _set_brownout_state — this only READS the state)."""
    loop = asyncio.get_running_loop()
    end_t = t0 + storm_end_s / speed
    delay = end_t - loop.time()
    if delay > 0:
        await asyncio.sleep(delay)
    deadline = loop.time() + horizon_s / speed
    while loop.time() < deadline:
        if admission.brownout.state == "normal":
            result.ladder_recovery_s = (loop.time() - end_t) * speed
            return
        await asyncio.sleep(0.05)


async def run_scenario(scenario, *, post: PostFn, speed: float = 1.0,
                       max_concurrency: Optional[int] = None,
                       timeout_s: Optional[float] = None,
                       supervisor=None, admission=None, autoscaler=None,
                       callbacks: Optional[Dict[str, Callable]] = None,
                       extra_dispatch: Optional[Dict[str, LocalFn]] = None,
                       recovery_horizon_s: float = 30.0) -> ReplayResult:
    """Replay a Scenario with its chaos timeline on the same clock, then
    (when the scenario declares storm phases and an admission handle is
    given) measure ladder recovery after the storm window closes.

    An ``autoscaler`` handle enables the ``scale_events`` chaos action and
    stuffs ``notes["scale_flaps"]`` / ``notes["scale_decisions"]`` into
    the result for the ``max_scale_flaps`` SLO gate."""
    res = ReplayResult()
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    jobs = [replay(scenario.events, post=post, speed=speed,
                   max_concurrency=max_concurrency, timeout_s=timeout_s,
                   extra_dispatch=extra_dispatch, result=res)]
    if scenario.chaos:
        jobs.append(run_chaos(scenario.chaos, speed=speed, t0=t0,
                              supervisor=supervisor, admission=admission,
                              autoscaler=autoscaler, callbacks=callbacks))
    storm_end = scenario.notes.get("storm_end_s")
    if storm_end is not None and admission is not None:
        jobs.append(_watch_recovery(res, admission, float(storm_end),
                                    speed, t0, recovery_horizon_s))
    await asyncio.gather(*jobs)
    if autoscaler is not None:
        res.notes["scale_flaps"] = float(autoscaler.flap_count())
        res.notes["scale_decisions"] = float(
            sum(autoscaler.decision_counts().values()))
    return res
