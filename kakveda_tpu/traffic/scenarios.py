"""Seeded scenario generators — the load shapes that actually break systems.

Every generator is a pure function of ``(seed, knobs)``: arrivals come from
one ``random.Random(seed)`` drawing inter-arrival gaps, so the same seed
produces the identical arrival schedule and app-key sequence on every run
(tier-1 asserts this — tests/test_traffic.py). Events are plain dicts the
replayer posts open-loop:

    {"t": offset_s, "method": "POST", "path": "/warn", "klass": "warn",
     "app_id": "app-3", "body": {…}, "phase": "baseline|storm|recovery"}

``method: "LOCAL"`` events (mixed contention's generate arm) dispatch
through a caller-provided callable instead of HTTP — the core service tier
has no generation route (that lives behind the serving engine), and the
harness must not pretend otherwise.

A scenario optionally carries a **chaos timeline**: actions applied at
offsets while the replay runs —

    {"t": 4.0, "action": "faults", "spec": "device.unavailable:1.0:-1"}
    {"t": 6.0, "action": "faults", "spec": ""}            ← outage ends
    {"t": 5.0, "action": "kill_replica", "replica": 1}
    {"t": 5.5, "action": "restart_replica", "replica": 1}
    {"t": 5.2, "action": "crash_replica", "replica": 1}   ← SIGKILL (dead-owner
                                                            drill; skipped when
                                                            the replica may hold
                                                            the TPU lease)
    {"t": 4.5, "action": "fleet_pressure", "pressure": 0.95, "ttl_s": 5.0}
    {"t": 7.0, "action": "scale_events"}                  ← snapshot autoscaler
                                                            counters (measurement)

``faults`` entries are full :func:`kakveda_tpu.core.faults.arm` specs
(each REPLACES the arming — an empty spec closes the outage window, the
same disarm-ends-the-outage shape as a real recovery). ``fleet_pressure``
feeds :meth:`AdmissionController.note_fleet_pressure` — exactly what a
saturated peer's gossip sample does, so a single-process storm still
exercises the fleet pressure floor. Replica actions need a
FleetSupervisor handle at replay time.

Catalog + per-scenario SLO table: docs/robustness.md § traffic harness.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from kakveda_tpu.traffic.slo import SLO

__all__ = ["Scenario", "SCENARIOS", "make_scenario", "synth_traces"]

# Fixed epoch for synthesized trace timestamps: generation must be a pure
# function of the seed (same seed → byte-identical events), so wall clock
# is banned here. Replay stamps real time where it matters.
_TRACE_EPOCH = 1_700_000_000.0

_PROMPTS = (
    "Cite sources for claim {i} even if unavailable.",
    "Summarize document {i} and include references for every claim.",
    "Explain incident {i} adding citations even when none exist.",
    "Review change {i} and list supporting sources.",
)


def synth_traces(seed: int, app_id: str, n: int, *, near_dup: bool = False) -> List[dict]:
    """Deterministic ingest trace batch. ``near_dup=True`` emits variants
    of ONE template differing by a token — the adversarial shape for the
    incremental mining path (near-ties in similarity, cluster churn)."""
    rng = random.Random(seed)
    base = rng.randrange(1 << 30)
    traces = []
    for k in range(n):
        i = base if near_dup else base + k * 97
        prompt = _PROMPTS[0 if near_dup else (base + k) % len(_PROMPTS)].format(i=i)
        if near_dup:
            prompt += f" variant {k % 7}"
        traces.append({
            "trace_id": f"tr-{seed}-{app_id}-{k}",
            "ts": _TRACE_EPOCH + (seed % 100_000) + k,
            "app_id": app_id,
            "prompt": prompt,
            "response": "According to [Smith 2020] (fabricated).",
            "tools": [],
            "env": {"os": "linux"},
        })
    return traces


@dataclass
class Scenario:
    """One generated traffic run: events + chaos timeline + SLO + phase
    boundaries (``notes``: storm_start_s / storm_end_s / gossip_ttl_s)."""

    name: str
    seed: int
    duration_s: float
    events: List[dict]
    chaos: List[dict] = field(default_factory=list)
    slo: SLO = field(default_factory=SLO)
    notes: Dict[str, float] = field(default_factory=dict)

    def app_key_sequence(self) -> List[str]:
        return [e.get("app_id", "") for e in self.events]

    def arrival_schedule(self) -> List[float]:
        return [float(e["t"]) for e in self.events]


def _arrivals(rng: random.Random, duration_s: float,
              rate_fn: Callable[[float], float]) -> List[float]:
    """Seeded non-homogeneous arrivals by thinning: draw at the peak rate,
    keep each with p = rate(t)/peak. Deterministic given the rng."""
    peak = max(rate_fn(duration_s * i / 64.0) for i in range(65))
    peak = max(peak, 1e-6)
    out, t = [], 0.0
    while True:
        t += rng.expovariate(peak)
        if t >= duration_s:
            return out
        if rng.random() < rate_fn(t) / peak:
            out.append(round(t, 6))


def _pick_app(rng: random.Random, apps: int, hot_share: float) -> str:
    """App-key draw: ``hot_share`` of traffic lands on app-0."""
    if hot_share > 0.0 and rng.random() < hot_share:
        return "app-0"
    return f"app-{rng.randrange(1, max(2, apps))}"


def _warn_event(t: float, app: str, i: int, phase: str) -> dict:
    prompt = _PROMPTS[i % len(_PROMPTS)].format(i=i)
    return {
        "t": t, "method": "POST", "path": "/warn", "klass": "warn",
        "app_id": app, "phase": phase,
        "body": {"app_id": app, "prompt": prompt},
    }


# -- generators ----------------------------------------------------------


def diurnal_wave(seed: int = 0, *, duration_s: float = 10.0,
                 warn_rps: float = 40.0, depth: float = 0.7,
                 apps: int = 8) -> Scenario:
    """One compressed diurnal cycle: warn arrivals swell to
    ``(1+depth)×`` the mean mid-window and trough to ``(1-depth)×`` at the
    edges. The shape that catches drain-rate estimators calibrated on the
    trough being hit by the crest."""
    rng = random.Random(seed)
    rate = lambda t: warn_rps * (1.0 - depth * math.cos(2 * math.pi * t / duration_s))  # noqa: E731
    events = [
        _warn_event(t, _pick_app(rng, apps, 0.0), i, "wave")
        for i, t in enumerate(_arrivals(rng, duration_s, rate))
    ]
    return Scenario(
        name="diurnal", seed=seed, duration_s=duration_s, events=events,
        slo=SLO(shed_only=("interactive", "background"), zero_lost=("warn",)),
    )


def hot_key_skew(seed: int = 0, *, duration_s: float = 8.0,
                 warn_rps: float = 50.0, hot_share: float = 0.9,
                 apps: int = 8) -> Scenario:
    """One app produces ``hot_share`` (default 90%) of the warn traffic —
    the shard-imbalance shape the fleet router's hash ring must absorb and
    the per-app failure-rate trackers must not let starve the cold keys."""
    rng = random.Random(seed)
    events = [
        _warn_event(t, _pick_app(rng, apps, hot_share), i, "skew")
        for i, t in enumerate(_arrivals(rng, duration_s, lambda _t: warn_rps))
    ]
    return Scenario(
        name="hot_key", seed=seed, duration_s=duration_s, events=events,
        slo=SLO(shed_only=("interactive", "background"), zero_lost=("warn",)),
    )


def failure_storm(seed: int = 0, *, duration_s: float = 12.0,
                  warn_rps: float = 40.0, ingest_rps: float = 6.0,
                  storm_start_frac: float = 0.3, storm_len_frac: float = 0.4,
                  device_loss: bool = True) -> Scenario:
    """A failure wave: steady warn traffic, plus an ingest burst (apps
    suddenly reporting failures en masse) through a mid-run window that
    also opens a device-loss chaos window — warn must ride it out on the
    host tiers (degraded verdicts, never errors)."""
    rng = random.Random(seed)
    b = duration_s * storm_start_frac
    s = b + duration_s * storm_len_frac
    phase = lambda t: "baseline" if t < b else ("storm" if t < s else "recovery")  # noqa: E731
    events = [
        _warn_event(t, _pick_app(rng, 8, 0.0), i, phase(t))
        for i, t in enumerate(_arrivals(rng, duration_s, lambda _t: warn_rps))
    ]
    for j, t in enumerate(_arrivals(rng, duration_s,
                                    lambda t: ingest_rps if b <= t < s else ingest_rps / 8)):
        app = f"app-{j % 4}"
        events.append({
            "t": t, "method": "POST", "path": "/ingest/batch", "klass": "ingest",
            "app_id": app, "phase": phase(t),
            "body": {"traces": synth_traces(seed * 1009 + j, app, 8)},
        })
    events.sort(key=lambda e: e["t"])
    chaos = []
    if device_loss:
        storm_len = s - b
        chaos = [
            {"t": round(b + 0.2 * storm_len, 3), "action": "faults",
             "spec": "device.unavailable:1.0:-1"},
            {"t": round(b + 0.7 * storm_len, 3), "action": "faults", "spec": ""},
        ]
    return Scenario(
        name="failure_storm", seed=seed, duration_s=duration_s, events=events,
        chaos=chaos,
        slo=SLO(shed_only=("interactive", "background"),
                zero_lost=("warn",), warn_p95_x_baseline=50.0),
        notes={"storm_start_s": b, "storm_end_s": s},
    )


def adversarial_near_dup(seed: int = 0, *, duration_s: float = 8.0,
                         ingest_rps: float = 8.0, batch: int = 16,
                         warn_rps: float = 10.0) -> Scenario:
    """Near-duplicate ingest flood against the incremental mining path:
    every batch is variants of one template (near-tied similarities,
    maximal cluster churn per row), with background mine calls
    interleaved so the streaming state is being read WHILE it churns."""
    rng = random.Random(seed)
    events = [
        _warn_event(t, "app-dup", i, "flood")
        for i, t in enumerate(_arrivals(rng, duration_s, lambda _t: warn_rps))
    ]
    for j, t in enumerate(_arrivals(rng, duration_s, lambda _t: ingest_rps)):
        events.append({
            "t": t, "method": "POST", "path": "/ingest/batch", "klass": "ingest",
            "app_id": "app-dup", "phase": "flood",
            "body": {"traces": synth_traces(seed * 31 + j, "app-dup", batch,
                                            near_dup=True)},
        })
    for t in _arrivals(rng, duration_s, lambda _t: 0.5):
        events.append({
            "t": t, "method": "POST", "path": "/patterns/mine",
            "klass": "background", "app_id": "miner", "phase": "flood",
            "body": {"mode": "auto"},
        })
    events.sort(key=lambda e: e["t"])
    return Scenario(
        name="near_dup", seed=seed, duration_s=duration_s, events=events,
        slo=SLO(shed_only=("interactive", "background"), zero_lost=("warn",)),
    )


def mixed_contention(seed: int = 0, *, duration_s: float = 8.0,
                     warn_rps: float = 30.0, gen_rps: float = 4.0,
                     mine_rps: float = 1.0) -> Scenario:
    """Warn + generation contention: interactive generate events dispatch
    through a caller-provided callable (``method: "LOCAL"`` — the serving
    engine lives behind the dashboard, not this HTTP tier) while
    background mines burn executor/GIL time. The pre-flight class must
    hold its latency against both."""
    rng = random.Random(seed)
    events = [
        _warn_event(t, _pick_app(rng, 8, 0.0), i, "mixed")
        for i, t in enumerate(_arrivals(rng, duration_s, lambda _t: warn_rps))
    ]
    for j, t in enumerate(_arrivals(rng, duration_s, lambda _t: gen_rps)):
        events.append({
            "t": t, "method": "LOCAL", "path": "generate",
            "klass": "interactive", "app_id": f"gen-{j % 4}", "phase": "mixed",
            "body": {"prompt": f"Summarize incident {j}.", "max_new_tokens": 16},
        })
    for t in _arrivals(rng, duration_s, lambda _t: mine_rps):
        events.append({
            "t": t, "method": "POST", "path": "/patterns/mine",
            "klass": "background", "app_id": "miner", "phase": "mixed",
            "body": {"mode": "auto"},
        })
    events.sort(key=lambda e: e["t"])
    return Scenario(
        name="mixed", seed=seed, duration_s=duration_s, events=events,
        slo=SLO(shed_only=("interactive", "background"), zero_lost=("warn",),
                ttft_p95_ms=None),
    )


def storm(seed: int = 0, *, duration_s: float = 12.0, warn_rps: float = 40.0,
          hot_share: float = 0.9, apps: int = 8, bg_rps: float = 20.0,
          baseline_frac: float = 0.3, storm_frac: float = 0.4,
          device_loss: bool = True, kill_replica: Optional[int] = None,
          fleet_pressure: bool = True, gossip_ttl_s: float = 5.0,
          warn_p95_x: float = 50.0) -> Scenario:
    """THE bench/tier-1 composition — hot-key skew + failure storm:

    * phase ``baseline`` ``[0, b)``: hot-key-skewed warn at capacity.
    * phase ``storm`` ``[b, s)``: same warn stream + a background flood
      (mine calls past the background bound — the SHEDDABLE excess) + the
      chaos timeline: a device-loss window (warn must degrade to host
      tiers, not fail), gossiped fleet pressure pinning the ladder up,
      and optionally one replica kill (fleet mode).
    * phase ``recovery`` ``[s, end)``: warn only; the pressure floor is
      refreshed at 0 by the next gossip tick (a live fleet's samples
      REPLACE, only a dead peer waits out the TTL) and the ladder must
      walk back to ``normal`` within ``gossip_ttl_s`` of storm end.

    ``warn_p95_x`` bounds the storm-phase warn p95 at a multiple of the
    same run's baseline p95. The default (50x) covers the device-loss
    window, where warn deliberately pays warm-tier host matching instead
    of failing — bounded degradation, against an unprotected stack whose
    warns time out (effectively unbounded). Size the warn class bound for
    DEGRADED throughput when driving this scenario: warn must never shed,
    so the queue has to absorb the warm-tier window's slower drain. The
    attached SLO is the acceptance contract the `storm` bench row
    self-certifies (docs/robustness.md § traffic harness)."""
    rng = random.Random(seed)
    b = round(duration_s * baseline_frac, 3)
    s = round(b + duration_s * storm_frac, 3)
    phase = lambda t: "baseline" if t < b else ("storm" if t < s else "recovery")  # noqa: E731
    events = [
        _warn_event(t, _pick_app(rng, apps, hot_share), i, phase(t))
        for i, t in enumerate(_arrivals(rng, duration_s, lambda _t: warn_rps))
    ]
    for t in _arrivals(rng, duration_s, lambda t: bg_rps if b <= t < s else 0.0):
        events.append({
            "t": t, "method": "POST", "path": "/patterns/mine",
            "klass": "background", "app_id": "miner", "phase": "storm",
            "body": {"mode": "auto"},
        })
    events.sort(key=lambda e: e["t"])

    storm_len = s - b
    chaos: List[dict] = []
    if device_loss:
        chaos += [
            {"t": round(b + 0.15 * storm_len, 3), "action": "faults",
             "spec": "device.unavailable:1.0:-1"},
            {"t": round(b + 0.65 * storm_len, 3), "action": "faults", "spec": ""},
        ]
    if kill_replica is not None:
        chaos.append({"t": round(b + 0.5 * storm_len, 3),
                      "action": "kill_replica", "replica": int(kill_replica)})
    if fleet_pressure:
        # A peer's gossip, tick by tick: pressure 0.95 samples through the
        # storm, then drained (0.0) samples through recovery — a live
        # peer's fresh sample REPLACES the floor (only a dead peer waits
        # out the TTL), and each recovery tick re-evaluates the ladder
        # exactly as GossipPublisher.tick_inputs does on an idle replica.
        t = b
        while t < s:
            chaos.append({"t": round(t, 3), "action": "fleet_pressure",
                          "pressure": 0.95, "ttl_s": gossip_ttl_s})
            t += 1.0
        t = s + 0.1
        while t < duration_s:
            chaos.append({"t": round(t, 3), "action": "fleet_pressure",
                          "pressure": 0.0, "ttl_s": gossip_ttl_s})
            t += 1.0
    chaos.sort(key=lambda c: c["t"])
    return Scenario(
        name="storm", seed=seed, duration_s=duration_s, events=events,
        chaos=chaos,
        slo=SLO(
            warn_p95_x_baseline=warn_p95_x,
            shed_only=("interactive", "background"),
            zero_hung=True,
            zero_lost=("warn",),
            recovery_s=gossip_ttl_s,
        ),
        notes={"storm_start_s": b, "storm_end_s": s,
               "gossip_ttl_s": gossip_ttl_s},
    )


def rebalance_storm(seed: int = 0, *, duration_s: float = 10.0,
                    warn_rps: float = 30.0, apps: int = 12,
                    hot_share: float = 0.5, rebalance_frac: float = 0.35,
                    kill_replica: Optional[int] = None,
                    kill_frac: float = 0.7, gossip_ttl_s: float = 5.0,
                    max_partial_rate: float = 0.1) -> Scenario:
    """Sharded-ownership drill (fleet/ownership.py): steady warn traffic
    while the fleet rebalances — and, optionally, an OWNER dies.

    * phase ``baseline`` ``[0, rb)``: warn across ``apps`` keys.
    * at ``rb`` the ``rebalance`` action fires — the driving test/bench
      supplies the handle via run_chaos ``callbacks`` (add a replica +
      run the range migration through the router's /fleet/rebalance);
      warn keeps flowing open-loop through the migration.
    * phase ``storm`` until ``kill``; at ``kill`` the named replica — an
      owner — gets SIGTERM'd (supervisor.stop, never SIGKILL). Scatter-
      gather must keep answering from standbys; the epoch push re-fences.
    * phase ``recovery`` to the end: the ladder must be back to normal
      within ``gossip_ttl_s``.

    Zero lost warns + zero hung + sheds confined to interactive/
    background + bounded partial-verdict rate IS the acceptance contract
    (ISSUE 13); the ``ownership`` bench arm self-certifies it."""
    rng = random.Random(seed)
    rb = round(duration_s * rebalance_frac, 3)
    kl = round(duration_s * kill_frac, 3)
    phase = lambda t: "baseline" if t < rb else ("storm" if t < kl else "recovery")  # noqa: E731
    events = [
        _warn_event(t, _pick_app(rng, apps, hot_share), i, phase(t))
        for i, t in enumerate(_arrivals(rng, duration_s, lambda _t: warn_rps))
    ]
    events.sort(key=lambda e: e["t"])
    chaos: List[dict] = [{"t": rb, "action": "rebalance"}]
    if kill_replica is not None:
        chaos.append({"t": kl, "action": "kill_replica",
                      "replica": int(kill_replica)})
    return Scenario(
        name="rebalance_storm", seed=seed, duration_s=duration_s,
        events=events, chaos=chaos,
        slo=SLO(
            shed_only=("interactive", "background"),
            zero_hung=True,
            zero_lost=("warn",),
            recovery_s=gossip_ttl_s,
            max_partial_rate=max_partial_rate,
        ),
        notes={"storm_start_s": rb, "storm_end_s": kl,
               "gossip_ttl_s": gossip_ttl_s},
    )


def flash_crowd(seed: int = 0, *, baseline_s: float = 8.0,
                surge_s: float = 30.0, decay_s: float = 40.0,
                warn_rps: float = 10.0, surge_x: float = 5.0,
                bg_rps: float = 15.0, apps: int = 12,
                hot_share: float = 0.3,
                crash_replica: Optional[int] = None,
                gossip_ttl_s: float = 5.0, max_scale_flaps: int = 1,
                recovery_s: Optional[float] = None,
                mine_mode: str = "full") -> Scenario:
    """Elastic-fleet drill (fleet/autoscaler.py): flash crowd → dead owner.

    * phase ``baseline`` ``[0, b)``: warn at ``warn_rps`` — the fleet
      holds at ``KAKVEDA_SCALE_MIN`` replicas, occupancy well under the
      scale-up threshold.
    * phase ``storm`` ``[b, s)``: warn ramps to ``surge_x ×`` over the
      first fifth of the window and holds, plus a background mine flood
      (``bg_rps`` past the background class bound — the sheddable excess
      that pins occupancy at 1.0; ``mine_mode="full"`` by default so each
      admitted mine is a real O(N²) burn, not an empty-delta no-op the
      probe would sample as idle). Sustained pressure must carry the
      autoscaler through its dwell and spawn fresh replicas — size
      ``surge_s`` to cover dwell + replica cold-start (a jax import is
      tens of seconds on CPU).
    * at ``s`` (surge end) the optional ``crash_replica`` fires: one
      OWNER dies by SIGKILL — no drain, no goodbye gossip. The autoscaler
      must declare it dead past ``KAKVEDA_SCALE_REPLACE_S``, give a fresh
      replica its ring position, and heal its rows (snapshot-ship +
      DLQ replay) — replacement outranks elastic actions in the policy.
    * phase ``recovery`` ``[s, end)``: warn back at baseline rate long
      enough for the replacement AND the lossless scale-down drains
      (migrate-then-SIGTERM, never stop-then-migrate) to complete.

    The attached SLO is the elastic acceptance contract the ``elastic``
    bench row self-certifies: zero lost warns, zero hung, sheds confined
    to interactive/background, and at most ``max_scale_flaps`` direction
    reversals (a clean 2→4→2 cycle is exactly one flap — anything more is
    ring flapping). ``scale_events`` entries snapshot the autoscaler's
    decision ledger at each phase boundary for the chaos log."""
    rng = random.Random(seed)
    b = round(baseline_s, 3)
    s = round(baseline_s + surge_s, 3)
    duration_s = round(baseline_s + surge_s + decay_s, 3)
    phase = lambda t: "baseline" if t < b else ("storm" if t < s else "recovery")  # noqa: E731
    ramp = max(1e-6, 0.2 * surge_s)

    def warn_rate(t: float) -> float:
        if t < b or t >= s:
            return warn_rps
        return warn_rps * min(surge_x, 1.0 + (surge_x - 1.0) * (t - b) / ramp)

    events = [
        _warn_event(t, _pick_app(rng, apps, hot_share), i, phase(t))
        for i, t in enumerate(_arrivals(rng, duration_s, warn_rate))
    ]
    for t in _arrivals(rng, duration_s, lambda t: bg_rps if b <= t < s else 0.0):
        events.append({
            "t": t, "method": "POST", "path": "/patterns/mine",
            "klass": "background", "app_id": "miner", "phase": "storm",
            "body": {"mode": mine_mode},
        })
    events.sort(key=lambda e: e["t"])

    chaos: List[dict] = [
        {"t": b, "action": "scale_events"},
        {"t": round(b + 0.5 * surge_s, 3), "action": "scale_events"},
        {"t": s, "action": "scale_events"},
        {"t": round(duration_s - 0.5, 3), "action": "scale_events"},
    ]
    if crash_replica is not None:
        chaos.append({"t": s, "action": "crash_replica",
                      "replica": int(crash_replica)})
    chaos.sort(key=lambda c: c["t"])
    return Scenario(
        name="flash_crowd", seed=seed, duration_s=duration_s, events=events,
        chaos=chaos,
        slo=SLO(
            shed_only=("interactive", "background"),
            zero_hung=True,
            zero_lost=("warn",),
            recovery_s=recovery_s,
            max_scale_flaps=max_scale_flaps,
        ),
        notes={"storm_start_s": b, "storm_end_s": s,
               "gossip_ttl_s": gossip_ttl_s},
    )


def noisy_neighbor(seed: int = 0, *, duration_s: float = 10.0,
                   victims: int = 3, victim_rps: float = 15.0,
                   flood_rps: float = 150.0, flood_start_frac: float = 0.3,
                   flood_app: str = "app-flood",
                   max_victim_shed_rate: float = 0.05,
                   victim_p95_x: float = 3.0,
                   min_flood_shed_share: float = 0.9,
                   starvation_s: float = 2.0) -> Scenario:
    """THE tenant-isolation drill (docs/robustness.md § multi-tenancy):
    well-behaved victim apps warm up alone, then ONE flooder opens up at
    many multiples of the warn drain rate and keeps firing to the end.

    * phase ``baseline`` ``[0, b)``: ``victims`` apps share ``victim_rps``
      of warn traffic — comfortably under capacity; this phase is the
      self-normalizing latency reference.
    * phase ``flood`` ``[b, end)``: the same victim stream continues
      unchanged while ``flood_app`` adds ``flood_rps`` on top — far past
      the drain rate, so the warn queue saturates and SOMEONE must shed.

    The SLO is the isolation contract: the shed lands on the flooder
    (``min_flood_shed_share``), victims keep their admission rate
    (``max_victim_shed_rate``) and near-baseline latency
    (``victim_p95_x_baseline``), and no victim starves longer than
    ``starvation_s`` of scheduled time without a success — the observed
    end-to-end counterpart of the weighted-fair promotion bound
    (``KAKVEDA_TENANT_PROMOTE_ROUNDS``). ``shed_only`` is cleared because
    warn sheds are EXPECTED here — the whole point is who absorbs them.
    The ``tenants`` bench row self-certifies this SLO in-run; without
    tenant fairness (``KAKVEDA_TENANT_FAIR=0``) the flooder's backlog
    sheds victims indiscriminately and the gates fail."""
    rng = random.Random(seed)
    b = round(duration_s * flood_start_frac, 3)
    phase = lambda t: "baseline" if t < b else "flood"  # noqa: E731
    events = [
        _warn_event(t, f"app-v{rng.randrange(max(1, victims))}", i, phase(t))
        for i, t in enumerate(_arrivals(rng, duration_s, lambda _t: victim_rps))
    ]
    for j, t in enumerate(_arrivals(rng, duration_s,
                                    lambda t: flood_rps if t >= b else 0.0)):
        events.append(_warn_event(t, flood_app, j, "flood"))
    events.sort(key=lambda e: e["t"])
    return Scenario(
        name="noisy_neighbor", seed=seed, duration_s=duration_s,
        events=events,
        slo=SLO(
            shed_only=(),  # warn sheds are the scenario's point
            zero_hung=True,
            zero_lost=("warn",),
            flood_app=flood_app,
            max_victim_shed_rate=max_victim_shed_rate,
            victim_p95_x_baseline=victim_p95_x,
            max_tenant_starvation_s=starvation_s,
            min_flood_shed_share=min_flood_shed_share,
        ),
        notes={"flood_start_s": b},
    )


def aging(seed: int = 0, *, duration_s: float = 8.0,
          virtual_days: float = 28.0, cohorts: int = 4,
          warn_rps: float = 20.0, ingest_rps: float = 4.0,
          age_ttl_virtual_days: float = 14.0) -> Scenario:
    """A month of failure memory compressed into ``duration_s``: app
    cohorts arrive in weekly waves — cohort k ingests (and warns) only
    during its own week, then goes quiet forever. By the end of the run
    the oldest cohorts are past any ``age_ttl_virtual_days`` TTL while the
    young ones are fresh, which is exactly the differential the lifecycle
    tier must honor: aged cohorts tombstone, live cohorts keep answering,
    and resident/log bytes stay bound instead of growing with history.

    Pure in (seed, knobs) like every scenario — virtual time derives from
    the scheduled arrival offset (``t / compression``), never wall clock.
    ``notes`` carry the compression factor and TTL so a consumer (the
    recovery bench row, a replay harness) can convert run time to virtual
    seconds and drive ``GFKB.age_rows(ttl_s=…, now=…)`` with an injected
    clock instead of waiting out real weeks."""
    rng = random.Random(seed)
    cohorts = max(1, cohorts)
    compression = (virtual_days * 86400.0) / duration_s
    week = duration_s / cohorts
    events = []
    for c in range(cohorts):
        lo, hi = c * week, (c + 1) * week
        in_week = lambda t: warn_rps / cohorts if lo <= t < hi else 0.0  # noqa: E731
        for i, t in enumerate(_arrivals(rng, duration_s, in_week)):
            events.append(_warn_event(t, f"app-c{c}-{i % 3}", i, f"week{c}"))
        for j, t in enumerate(_arrivals(rng, duration_s,
                                        lambda t: ingest_rps / cohorts
                                        if lo <= t < hi else 0.0)):
            app = f"app-c{c}-{j % 3}"
            events.append({
                "t": t, "method": "POST", "path": "/ingest/batch",
                "klass": "ingest", "app_id": app, "phase": f"week{c}",
                "body": {"traces": synth_traces(seed * 7919 + c * 97 + j,
                                                app, 6)},
            })
    events.sort(key=lambda e: e["t"])
    return Scenario(
        name="aging", seed=seed, duration_s=duration_s, events=events,
        slo=SLO(shed_only=("interactive", "background"), zero_lost=("warn",)),
        notes={"compression": compression,
               "virtual_days": virtual_days,
               "cohorts": float(cohorts),
               "age_ttl_virtual_s": age_ttl_virtual_days * 86400.0},
    )


SCENARIOS: Dict[str, Callable[..., Scenario]] = {
    "diurnal": diurnal_wave,
    "hot_key": hot_key_skew,
    "failure_storm": failure_storm,
    "near_dup": adversarial_near_dup,
    "mixed": mixed_contention,
    "storm": storm,
    "rebalance_storm": rebalance_storm,
    "flash_crowd": flash_crowd,
    "noisy_neighbor": noisy_neighbor,
    "aging": aging,
}


def make_scenario(name: str, seed: int = 0, **kw) -> Scenario:
    try:
        factory = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; have {sorted(SCENARIOS)}"
        ) from None
    return factory(seed, **kw)
