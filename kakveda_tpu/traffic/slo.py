"""Declarative SLO gates over a replay — the degradation CONTRACT.

An :class:`SLO` is a per-scenario set of bounds; :func:`evaluate` checks a
finished :class:`~kakveda_tpu.traffic.replay.ReplayResult` against it and
returns a typed :class:`SLOReport` (one row per gate: observed value,
bound, pass/fail). ``None`` bounds are not evaluated — a scenario only
pays for the gates it declares.

Gates (the storm bench row self-certifies all of them in-run):

* ``warn_p50_ms`` / ``warn_p95_ms`` — absolute warn latency bounds.
* ``warn_p95_x_baseline`` — warn p95 during/after the storm bounded at a
  multiple of the SAME run's baseline-phase p95 (self-normalizing: no
  machine-speed constant to rot).
* ``ttft_p95_ms`` — interactive time-to-first-token (LOCAL dispatch arm).
* ``max_shed_rate`` — per-class shed-rate ceilings, e.g. ``{"warn": 0.0}``.
* ``shed_only`` — sheds confined to these classes; a shed observed for
  any OTHER class (warn! ingest!) fails the gate outright.
* ``zero_hung`` — no request may still be in flight / timed out at the
  end: SHED-NEVER-HANG, end to end.
* ``zero_lost`` — for each named class, every event generated was
  terminally accounted (ok/shed/degraded/error — never silently dropped).
* ``recovery_s`` — the brownout ladder must be back at ``normal`` within
  this many seconds of ``storm_end_s`` (measured by the replayer).
* ``max_partial_rate`` — sharded ownership: ceiling on the share of ok
  warn verdicts whose scatter-gather merge was ``partial=true`` (a range
  had no answering holder). Fed from ``ReplayResult.notes["partial"]``.
* ``max_scale_flaps`` — elastic fleet: ceiling on autoscaler direction
  reversals (executed scale-up↔scale-down flips) during the run. Fed
  from ``ReplayResult.notes["scale_flaps"]`` (the replayer stuffs it
  when an autoscaler handle was threaded through ``run_scenario``).
* Tenant-isolation arm (``flood_app`` names the flooder; every gate is
  vacuous unless records carry app tags AND ``flood_app`` is set):
  ``max_victim_shed_rate`` — shed-rate ceiling over NON-flooder traffic;
  ``victim_p95_x_baseline`` — victim ok-p95 during the ``flood`` phase
  bounded at a multiple of the same victims' ``baseline``-phase p95;
  ``max_tenant_starvation_s`` — longest per-victim span of consecutive
  non-ok dispatches (the weighted-fair promotion bound, observed
  end-to-end); ``min_flood_shed_share`` — FLOOR on the fraction of all
  sheds that landed on the flooder (quotas must aim the pain at whoever
  owns the backlog).

Table of which scenario declares what: docs/robustness.md § traffic
harness.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = ["SLO", "SLOReport", "evaluate", "percentile"]


def percentile(xs, q: float) -> float:
    """Nearest-rank percentile; 0.0 on empty input (dependency-free —
    this module must import without jax/numpy, the metrics-plane rule)."""
    xs = sorted(xs)
    if not xs:
        return 0.0
    i = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return float(xs[i])


@dataclass(frozen=True)
class SLO:
    name: str = "default"
    warn_p50_ms: Optional[float] = None
    warn_p95_ms: Optional[float] = None
    warn_p95_x_baseline: Optional[float] = None
    ttft_p95_ms: Optional[float] = None
    max_shed_rate: Dict[str, float] = field(default_factory=dict)
    shed_only: Tuple[str, ...] = ("interactive", "background")
    zero_hung: bool = True
    zero_lost: Tuple[str, ...] = ("warn",)
    recovery_s: Optional[float] = None
    # Sharded-ownership arm: ceiling on the fraction of ok warn verdicts
    # the scatter-gather merge flagged partial=true (missing range
    # coverage). Reads result.notes["partial"] — the caller's post fn
    # counts partials there; no notes at all leaves the gate vacuous.
    max_partial_rate: Optional[float] = None
    # Elastic-fleet arm: ceiling on executed scale-direction reversals
    # (a 2→4→2 flash-crowd cycle is exactly one flap). Reads
    # result.notes["scale_flaps"]; vacuous when no autoscaler ran.
    max_scale_flaps: Optional[int] = None
    # Tenant-isolation arm (noisy-neighbor drill). flood_app names the
    # flooder tenant; victims = records with a non-empty app tag that
    # isn't the flooder. All four gates are vacuous without app-tagged
    # records and a flood_app.
    flood_app: str = ""
    max_victim_shed_rate: Optional[float] = None
    victim_p95_x_baseline: Optional[float] = None
    max_tenant_starvation_s: Optional[float] = None
    min_flood_shed_share: Optional[float] = None


@dataclass
class Gate:
    gate: str
    ok: bool
    observed: object
    bound: object
    # Trace ids of the dispatches that broke the gate (slowest / hung /
    # shed offenders) — a failing gate is one `cli trace <id>` away from
    # its cross-process cause. Populated only on failure, only when the
    # replay tagged records with trace ids.
    exemplars: Optional[List[str]] = None

    def to_dict(self) -> dict:
        d = {"gate": self.gate, "ok": self.ok,
             "observed": self.observed, "bound": self.bound}
        if self.exemplars:
            d["exemplars"] = list(self.exemplars)
        return d


@dataclass
class SLOReport:
    slo: str
    ok: bool
    gates: List[Gate]

    def to_dict(self) -> dict:
        return {"slo": self.slo, "ok": self.ok,
                "gates": [g.to_dict() for g in self.gates]}

    def failures(self) -> List[Gate]:
        return [g for g in self.gates if not g.ok]

    def summary(self) -> str:
        if self.ok:
            return f"SLO {self.slo}: all {len(self.gates)} gates pass"
        bad = ", ".join(
            f"{g.gate} (observed {g.observed!r}, bound {g.bound!r})"
            for g in self.failures()
        )
        return f"SLO {self.slo}: FAILED — {bad}"


def _exemplar_traces(records, status=None, klass=None, n=3) -> List[str]:
    """Worst-offender trace ids for a failing gate: matching records,
    slowest first. Records without a trace tag (sampling off) drop out —
    exemplars are best-effort, never a gate input."""
    cand = [r for r in records if r.get("trace")
            and (status is None or r.get("status") == status)
            and (klass is None or r.get("klass") == klass)]
    cand.sort(key=lambda r: r.get("latency_ms", 0.0), reverse=True)
    return [r["trace"] for r in cand[:n]]


def _tenant_gates(slo: SLO, result, add) -> None:
    """The noisy-neighbor isolation gates. Victims are app-tagged records
    that aren't the flooder's; every gate passes vacuously when the replay
    carried no tenant accounting (untagged captures, flood_app unset)."""
    wants = (slo.max_victim_shed_rate is not None
             or slo.victim_p95_x_baseline is not None
             or slo.max_tenant_starvation_s is not None
             or slo.min_flood_shed_share is not None)
    if not wants:
        return
    records = getattr(result, "records", None) or []
    tagged = [r for r in records if r.get("app")]
    if not slo.flood_app or not tagged:
        reason = "no tenant accounting"
        if slo.max_victim_shed_rate is not None:
            add("max_victim_shed_rate", True, reason, slo.max_victim_shed_rate)
        if slo.victim_p95_x_baseline is not None:
            add("victim_p95_x_baseline", True, reason, slo.victim_p95_x_baseline)
        if slo.max_tenant_starvation_s is not None:
            add("max_tenant_starvation_s", True, reason,
                slo.max_tenant_starvation_s)
        if slo.min_flood_shed_share is not None:
            add("min_flood_shed_share", True, reason, slo.min_flood_shed_share)
        return
    victims = [r for r in tagged if r["app"] != slo.flood_app]

    if slo.max_victim_shed_rate is not None:
        shed = sum(1 for r in victims if r["status"] == "shed")
        rate = round(shed / len(victims), 4) if victims else 0.0
        add("max_victim_shed_rate", rate <= slo.max_victim_shed_rate,
            rate, slo.max_victim_shed_rate)

    if slo.victim_p95_x_baseline is not None:
        base = [r["latency_ms"] for r in victims
                if r["status"] == "ok" and r.get("phase") == "baseline"]
        flood = [r["latency_ms"] for r in victims
                 if r["status"] == "ok" and r.get("phase") == "flood"]
        if base and flood:
            ratio = round(percentile(flood, 95)
                          / max(percentile(base, 95), 1e-9), 3)
            add("victim_p95_x_baseline", ratio <= slo.victim_p95_x_baseline,
                ratio, slo.victim_p95_x_baseline)
        else:
            add("victim_p95_x_baseline", True, "no baseline/flood phases",
                slo.victim_p95_x_baseline)

    if slo.max_tenant_starvation_s is not None:
        # Longest per-victim stretch of consecutive non-ok dispatches,
        # measured in scheduled time ("t"): how long one tenant went
        # without a single success. The observed counterpart of the
        # KAKVEDA_TENANT_PROMOTE_ROUNDS starvation bound.
        worst = 0.0
        by_app: Dict[str, List[dict]] = {}
        for r in victims:
            by_app.setdefault(r["app"], []).append(r)
        for rows in by_app.values():
            rows.sort(key=lambda r: r.get("t", 0.0))
            run_start = None
            for r in rows:
                if r["status"] == "ok":
                    run_start = None
                    continue
                t = float(r.get("t", 0.0))
                if run_start is None:
                    run_start = t
                worst = max(worst, t - run_start)
        add("max_tenant_starvation_s", worst <= slo.max_tenant_starvation_s,
            round(worst, 3), slo.max_tenant_starvation_s)

    if slo.min_flood_shed_share is not None:
        sheds = [r for r in tagged if r["status"] == "shed"]
        if sheds:
            share = round(sum(1 for r in sheds
                              if r["app"] == slo.flood_app) / len(sheds), 4)
            add("min_flood_shed_share", share >= slo.min_flood_shed_share,
                share, slo.min_flood_shed_share)
        else:
            # Nothing shed at all: isolation is trivially intact.
            add("min_flood_shed_share", True, "no sheds",
                slo.min_flood_shed_share)


def evaluate(slo: SLO, result) -> SLOReport:
    """Check a finished ReplayResult against an SLO. Pure function of the
    result snapshot — safe to re-run, never mutates the replay state."""
    gates: List[Gate] = []

    def add(name, ok, observed, bound):
        gates.append(Gate(name, bool(ok), observed, bound))

    warn_all = result.latencies_ms("warn")
    if slo.warn_p50_ms is not None:
        p50 = round(percentile(warn_all, 50), 3)
        add("warn_p50_ms", p50 <= slo.warn_p50_ms, p50, slo.warn_p50_ms)
    if slo.warn_p95_ms is not None:
        p95 = round(percentile(warn_all, 95), 3)
        add("warn_p95_ms", p95 <= slo.warn_p95_ms, p95, slo.warn_p95_ms)

    if slo.warn_p95_x_baseline is not None:
        base = result.latencies_ms("warn", phase="baseline")
        rest = [x for ph in ("storm", "recovery")
                for x in result.latencies_ms("warn", phase=ph)]
        if base and rest:
            bp = percentile(base, 95)
            rp = percentile(rest, 95)
            ratio = round(rp / max(bp, 1e-9), 3)
            add("warn_p95_x_baseline", ratio <= slo.warn_p95_x_baseline,
                ratio, slo.warn_p95_x_baseline)
        else:
            # No phased traffic to compare — the gate is vacuous, not
            # failed (capture replays have a single "capture" phase).
            add("warn_p95_x_baseline", True,
                "no baseline/storm phases", slo.warn_p95_x_baseline)

    if slo.ttft_p95_ms is not None:
        ttft = result.ttft_ms()
        p95 = round(percentile(ttft, 95), 3)
        add("ttft_p95_ms", (not ttft) or p95 <= slo.ttft_p95_ms,
            p95, slo.ttft_p95_ms)

    counts = result.class_counts()
    for klass, ceil in sorted(slo.max_shed_rate.items()):
        c = counts.get(klass, {})
        total = sum(c.values())
        rate = round(c.get("shed", 0) / total, 4) if total else 0.0
        add(f"max_shed_rate[{klass}]", rate <= ceil, rate, ceil)

    if slo.shed_only:
        offenders = {k: c.get("shed", 0) for k, c in counts.items()
                     if c.get("shed", 0) and k not in slo.shed_only}
        add("shed_only", not offenders, offenders or "none",
            list(slo.shed_only))

    if slo.zero_hung:
        hung = sum(c.get("hung", 0) for c in counts.values())
        add("zero_hung", hung == 0, hung, 0)

    for klass in slo.zero_lost:
        c = counts.get(klass, {})
        lost = result.generated(klass) - sum(c.values())
        add(f"zero_lost[{klass}]", lost <= 0, lost, 0)

    if slo.max_partial_rate is not None:
        notes = getattr(result, "notes", {}) or {}
        if "partial" in notes:
            ok_warns = counts.get("warn", {}).get("ok", 0)
            rate = (round(float(notes["partial"]) / ok_warns, 4)
                    if ok_warns else 0.0)
            add("max_partial_rate", rate <= slo.max_partial_rate,
                rate, slo.max_partial_rate)
        else:
            add("max_partial_rate", True, "no partial accounting",
                slo.max_partial_rate)

    if slo.max_scale_flaps is not None:
        notes = getattr(result, "notes", {}) or {}
        if "scale_flaps" in notes:
            flaps = int(notes["scale_flaps"])
            add("max_scale_flaps", flaps <= slo.max_scale_flaps,
                flaps, slo.max_scale_flaps)
        else:
            add("max_scale_flaps", True, "no autoscaler accounting",
                slo.max_scale_flaps)

    _tenant_gates(slo, result, add)

    if slo.recovery_s is not None:
        rec = result.ladder_recovery_s
        if rec is None:
            add("recovery_s", False, "never recovered", slo.recovery_s)
        else:
            add("recovery_s", rec <= slo.recovery_s,
                round(rec, 3), slo.recovery_s)

    records = getattr(result, "records", None) or []
    for g in gates:
        if g.ok or not records:
            continue
        if g.gate.startswith(("warn_p", "ttft_")):
            g.exemplars = _exemplar_traces(records, status="ok") or None
        elif g.gate == "zero_hung":
            g.exemplars = _exemplar_traces(records, status="hung") or None
        elif g.gate.startswith("max_shed_rate["):
            klass = g.gate[len("max_shed_rate["):-1]
            g.exemplars = _exemplar_traces(
                records, status="shed", klass=klass) or None
        elif g.gate in ("shed_only", "max_victim_shed_rate",
                        "min_flood_shed_share"):
            g.exemplars = _exemplar_traces(records, status="shed") or None

    return SLOReport(slo=slo.name, ok=all(g.ok for g in gates), gates=gates)
