#!/usr/bin/env python
"""Render a bench result (one-line JSON from bench.py, or a driver
BENCH_r{N}.json) as a readable table, with the BASELINE.md north stars
called out.

    python scripts/bench_report.py BENCH_r04.json
    python bench.py | python scripts/bench_report.py -

No deps beyond stdlib; safe to run anywhere — it never initializes an
accelerator backend (this image's sitecustomize imports jax into every
interpreter, but importing alone claims no device lease)."""

from __future__ import annotations

import json
import sys

NORTH_STARS = {
    # metric-name prefix -> (target, comparator, unit)
    "preflight_warn_p50_ms": (10.0, "<", "ms"),
    "ingest_throughput_traces_per_sec": (10_000.0, ">=", "traces/s"),
}


def _flatten(doc: dict) -> list:
    """A bench line is {headline..., extra_metrics: [...]}; a driver
    BENCH_r{N}.json wraps it ({"rc": ..., "tail": "...stderr+stdout..."},
    the JSON line being the last {-prefixed line of the tail)."""
    for key in ("result", "stdout", "tail"):
        v = doc.get(key)
        if isinstance(v, str):
            lines = [ln for ln in v.splitlines() if ln.lstrip().startswith("{")]
            if lines:
                try:
                    doc = json.loads(lines[-1])
                    break
                except ValueError:
                    continue
        elif isinstance(v, dict):
            doc = v
            break
    if "metric" not in doc:
        rc = doc.get("rc")
        raise SystemExit(
            f"no metric JSON found (rc={rc}); keys: {sorted(doc)[:8]}"
        )
    return [doc] + list(doc.get("extra_metrics", []))


def _star(name: str, value: float) -> str:
    for prefix, (target, op, unit) in NORTH_STARS.items():
        if name.startswith(prefix):
            ok = value < target if op == "<" else value >= target
            verdict = "MET" if ok else "MISSED"
            return f"  <- north star {op} {target:g} {unit}: {verdict}"
    return ""


def main() -> int:
    path = sys.argv[1] if len(sys.argv) > 1 else "-"
    raw = sys.stdin.read() if path == "-" else open(path).read()
    try:
        doc = json.loads(raw)  # whole file (driver files are pretty-printed)
    except ValueError:
        # bench stdout piped with stderr noise: find the JSON line
        line = next(
            (ln for ln in raw.splitlines() if ln.lstrip().startswith("{")), raw
        )
        doc = json.loads(line)
    metrics = _flatten(doc)
    width = max(len(m["metric"]) for m in metrics)
    for m in metrics:
        extras = {
            k: v
            for k, v in m.items()
            if k not in ("metric", "value", "unit", "vs_baseline", "extra_metrics")
        }
        extra_s = " ".join(f"{k}={v}" for k, v in sorted(extras.items()))
        print(
            f"{m['metric']:<{width}}  {m['value']:>12,.3f} {m.get('unit', ''):<11}"
            f"(vs_baseline {m.get('vs_baseline', '—')})"
            f"{_star(m['metric'], float(m['value']))}"
        )
        if extra_s:
            print(f"{'':<{width}}  {extra_s}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
