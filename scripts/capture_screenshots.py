#!/usr/bin/env python3
"""Capture dashboard screenshots into docs/screenshots/.

Parity target: the reference ships docs/screenshots/{login,dashboard,
warnings,scenarios,run,playground,prompts,experiments,datasets,
admin_rbac,...}.png. This script drives a LIVE kakveda-tpu dashboard
(started via ``python -m kakveda_tpu.cli up``) through headless Chrome's
DevTools protocol and saves the same page set.

Usage:
    python -m kakveda_tpu.cli up --detach --dir /tmp/shots --dashboard-port 8110
    python scripts/demo_client.py --base http://127.0.0.1:8100   # seed data
    python scripts/capture_screenshots.py --base http://127.0.0.1:8110

Requires a Chrome/Chromium binary (``--chrome`` or $CHROME). The CI image
this repo is developed in has no browser — run this wherever Chrome
exists; the capture itself is fully automated (login + cookie handling
included).

Text-mode fallback (``--html``, VERDICT item 9): when no browser is
reachable, render the same page set as SERVED HTML through the running
server (login + session cookie over plain urllib) into
``docs/screenshots/*.html`` — the dashboard's parity surface stays
inspectable without Chrome. The PNG path remains the preferred artifact
wherever a browser exists.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

PAGES = [
    ("login", "/login", False),
    ("dashboard", "/", True),
    ("warnings", "/warnings", True),
    ("scenarios", "/scenarios", True),
    ("runs", "/runs", True),
    ("playground", "/playground", True),
    ("prompts", "/prompts", True),
    ("experiments", "/experiments", True),
    ("datasets", "/datasets", True),
    ("health", "/health-page", True),
    ("admin_rbac", "/admin/users", True),
    ("admin_serving", "/admin/serving", True),
]


def find_chrome(explicit: str | None) -> str:
    cands = [explicit, os.environ.get("CHROME")] + [
        shutil.which(n)
        for n in ("chromium", "chromium-browser", "google-chrome", "chrome")
    ]
    for c in cands:
        if c and Path(c).exists():
            return c
    sys.exit(
        "no Chrome/Chromium binary found — pass --chrome or set $CHROME "
        "(this image has no browser; run where one exists)"
    )


def cdp(port: int, ws, method: str, params: dict, _id=[0]):
    _id[0] += 1
    ws.send(json.dumps({"id": _id[0], "method": method, "params": params}))
    while True:
        msg = json.loads(ws.recv())
        if msg.get("id") == _id[0]:
            if "error" in msg:
                raise RuntimeError(f"{method}: {msg['error']}")
            return msg.get("result", {})


def capture_html(args) -> int:
    """Browser-free capture: log in with plain urllib (cookie jar), GET
    each page and commit the served HTML. Pages that need a login are
    fetched with the session cookie, exactly like the CDP path."""
    import http.cookiejar
    import urllib.parse

    jar = http.cookiejar.CookieJar()
    opener = urllib.request.build_opener(
        urllib.request.HTTPCookieProcessor(jar)
    )
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)

    def fetch(path: str) -> str:
        with opener.open(args.base + path, timeout=30) as r:
            return r.read().decode("utf-8", errors="replace")

    # anonymous login page first, then authenticate (302 sets the cookie)
    (out / "login.html").write_text(fetch("/login"), encoding="utf-8")
    print("captured login.html")
    form = urllib.parse.urlencode(
        {"email": args.email, "password": args.password, "next": "/"}
    ).encode()
    opener.open(args.base + "/login", data=form, timeout=30)
    if not any(c for c in jar):
        sys.exit("login did not set a session cookie — wrong credentials?")

    for name, path, needs_login in PAGES:
        if name == "login":
            continue
        try:
            (out / f"{name}.html").write_text(fetch(path), encoding="utf-8")
            print(f"captured {name}.html")
        except Exception as e:  # noqa: BLE001 — capture the rest regardless
            print(f"FAILED {name} ({path}): {e}", file=sys.stderr)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--base", default="http://127.0.0.1:8110")
    ap.add_argument("--out", default=str(Path(__file__).resolve().parent.parent / "docs" / "screenshots"))
    ap.add_argument("--chrome", default=None)
    ap.add_argument("--email", default="admin@local")
    ap.add_argument("--password", default="admin123")
    ap.add_argument(
        "--html", action="store_true",
        help="no-browser fallback: save served HTML instead of PNGs",
    )
    args = ap.parse_args()

    if args.html:
        return capture_html(args)

    try:
        from websocket import create_connection  # websocket-client
    except ImportError:
        sys.exit("pip install websocket-client (CDP transport)")

    chrome = find_chrome(args.chrome)
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    port = 9222
    prof = tempfile.mkdtemp(prefix="kakveda-shots-")
    proc = subprocess.Popen(
        [
            chrome, "--headless=new", f"--remote-debugging-port={port}",
            f"--user-data-dir={prof}", "--no-sandbox", "--window-size=1280,860",
            "about:blank",
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        for _ in range(50):
            try:
                tabs = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/json"))
                break
            except Exception:
                time.sleep(0.2)
        ws = create_connection(tabs[0]["webSocketDebuggerUrl"])
        cdp(port, ws, "Page.enable", {})
        cdp(port, ws, "Runtime.enable", {})

        def goto(path):
            cdp(port, ws, "Page.navigate", {"url": args.base + path})
            time.sleep(1.2)  # charts render client-side

        def shot(name):
            r = cdp(port, ws, "Page.captureScreenshot", {"format": "png"})
            (out / f"{name}.png").write_bytes(base64.b64decode(r["data"]))
            print(f"captured {name}.png")

        # login via the real form (sets the session cookie in-browser)
        goto("/login")
        shot("login")
        cdp(port, ws, "Runtime.evaluate", {
            "expression": (
                f"document.querySelector('[name=email]').value={args.email!r};"
                f"document.querySelector('[name=password]').value={args.password!r};"
                "document.querySelector('form').submit();"
            )
        })
        time.sleep(1.5)
        for name, path, needs_login in PAGES:
            if name == "login":
                continue
            goto(path)
            shot(name)
    finally:
        proc.terminate()
    return 0


if __name__ == "__main__":
    sys.exit(main())
