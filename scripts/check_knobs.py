#!/usr/bin/env python
"""Static check: every ``KAKVEDA_*`` env knob the code reads must be
documented.

An undocumented knob is an outage waiting for an operator: the serving
levers (KAKVEDA_SERVE_*), the bench sweep controls and the metrics-plane
sizing all change production behavior, and the only discoverable surface
is the docs. This script greps the *code* tree for knob references and the
*docs* corpus (CLAUDE.md, README.md, TROUBLESHOOTING.md, BASELINE.md,
docs/**/*.md) for mentions; anything referenced but never documented fails
the check. Runs in tier-1 via tests/test_knobs.py.

Usage: ``python scripts/check_knobs.py [repo_root]`` — exits nonzero and
lists the undocumented knobs on stdout.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

KNOB_RE = re.compile(r"KAKVEDA_[A-Z0-9_]+")

# Code that can introduce operator-facing knobs. Tests are deliberately
# excluded: KAKVEDA_TEST_* style fixtures are not operator surface.
CODE_PATHS = ("kakveda_tpu", "scripts", "bench.py", "__graft_entry__.py")
DOC_PATHS = ("CLAUDE.md", "README.md", "TROUBLESHOOTING.md", "BASELINE.md", "docs")

# Internal/cross-process plumbing set by our own launchers, not operators.
ALLOWLIST = frozenset({
    "KAKVEDA_PROCESS_ID",  # set per-process by the multihost launcher
})


def _md_files(root: Path):
    for rel in DOC_PATHS:
        p = root / rel
        if p.is_file():
            yield p
        elif p.is_dir():
            yield from sorted(p.rglob("*.md"))


def _code_files(root: Path):
    for rel in CODE_PATHS:
        p = root / rel
        if p.is_file():
            yield p
        elif p.is_dir():
            yield from sorted(p.rglob("*.py"))


def referenced_knobs(root: Path) -> dict:
    """knob -> sorted list of repo-relative files referencing it."""
    refs: dict = {}
    for f in _code_files(root):
        try:
            text = f.read_text(errors="replace")
        except OSError:
            continue
        for m in set(KNOB_RE.findall(text)):
            if m.rstrip("_") != m or m == "KAKVEDA_":
                continue
            refs.setdefault(m, []).append(str(f.relative_to(root)))
    for files in refs.values():
        files.sort()
    return refs


def documented_knobs(root: Path) -> set:
    docs: set = set()
    for f in _md_files(root):
        try:
            docs.update(KNOB_RE.findall(f.read_text(errors="replace")))
        except OSError:
            continue
    return docs


def undocumented_knobs(root: Path) -> dict:
    """knob -> referencing files, for every knob the docs never mention."""
    refs = referenced_knobs(root)
    docs = documented_knobs(root)
    return {
        k: v for k, v in sorted(refs.items())
        if k not in docs and k not in ALLOWLIST
    }


def main(argv: list) -> int:
    root = Path(argv[1]).resolve() if len(argv) > 1 else Path(__file__).resolve().parents[1]
    missing = undocumented_knobs(root)
    if not missing:
        print(f"check_knobs: all {len(referenced_knobs(root))} KAKVEDA_* knobs documented")
        return 0
    print(f"check_knobs: {len(missing)} undocumented KAKVEDA_* knob(s):")
    for knob, files in missing.items():
        print(f"  {knob}  (referenced by {', '.join(files[:3])}"
              f"{', …' if len(files) > 3 else ''})")
    print("document them in CLAUDE.md or docs/ (see docs/observability.md knob registry)")
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
