#!/usr/bin/env python
"""Static check: every ``KAKVEDA_*`` env knob the code reads must be
documented — and every documented knob must still be read (dead-knob
drift). Same contract for chaos fault sites: every ``faults.site("…")``
registered in the code tree must appear in docs/robustness.md's catalog.

An undocumented knob is an outage waiting for an operator: the serving
levers (KAKVEDA_SERVE_*), the bench sweep controls and the metrics-plane
sizing all change production behavior, and the only discoverable surface
is the docs. The converse rots just as fast: a knob the docs still teach
but the code no longer reads sends an operator tuning a no-op mid-
incident. Fault sites get the same treatment because an operator can only
arm (``KAKVEDA_FAULTS``) what the catalog names.

The scanning logic lives in :mod:`kakveda_tpu.analysis.knobs` — shared
with the invariant linter's ``knob-docs`` and ``fault-site-catalog``
rules (scripts/lint_invariants.py, docs/static-analysis.md) so both
entry points walk ONE tree discovery helper
(:mod:`kakveda_tpu.analysis.discovery`) instead of two divergent walkers.
This CLI is kept for muscle memory and tier-1 (tests/test_knobs.py).

Usage: ``python scripts/check_knobs.py [repo_root]`` — exits nonzero and
lists the offending knobs/sites on stdout.
"""

from __future__ import annotations

import sys
from pathlib import Path

# Script-mode bootstrap: `python scripts/check_knobs.py` puts scripts/ on
# sys.path, not the repo root the package import needs.
_REPO = Path(__file__).resolve().parents[1]
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from kakveda_tpu.analysis.knobs import (  # noqa: E402,F401 — re-exported API
    ALLOWLIST,
    DOC_ONLY_ALLOWLIST,
    KNOB_RE,
    SITE_RE,
    dead_knobs,
    documented_knobs,
    referenced_knobs,
    registered_fault_sites,
    undocumented_fault_sites,
    undocumented_knobs,
)


def main(argv: list) -> int:
    root = Path(argv[1]).resolve() if len(argv) > 1 else _REPO
    missing = undocumented_knobs(root)
    dead = dead_knobs(root)
    missing_sites = undocumented_fault_sites(root)
    if not missing and not dead and not missing_sites:
        print(f"check_knobs: all {len(referenced_knobs(root))} KAKVEDA_* knobs "
              f"documented, none dead; all {len(registered_fault_sites(root))} "
              "fault sites cataloged")
        return 0
    if missing:
        print(f"check_knobs: {len(missing)} undocumented KAKVEDA_* knob(s):")
        for knob, files in missing.items():
            print(f"  {knob}  (referenced by {', '.join(files[:3])}"
                  f"{', …' if len(files) > 3 else ''})")
        print("document them in CLAUDE.md or docs/ (see docs/observability.md knob registry)")
    if dead:
        print(f"check_knobs: {len(dead)} dead KAKVEDA_* knob(s) (documented but "
              "no longer read by any code):")
        for knob in dead:
            print(f"  {knob}")
        print("remove them from the docs, or add to DOC_ONLY_ALLOWLIST if "
              "deliberately doc-only")
    if missing_sites:
        print(f"check_knobs: {len(missing_sites)} fault site(s) registered in "
              "code but missing from the docs/robustness.md catalog:")
        for site, files in missing_sites.items():
            print(f"  {site}  (registered by {', '.join(files[:3])}"
                  f"{', …' if len(files) > 3 else ''})")
        print("add them to the fault-site catalog table in docs/robustness.md")
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
