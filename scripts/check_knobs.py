#!/usr/bin/env python
"""Static check: every ``KAKVEDA_*`` env knob the code reads must be
documented — and every documented knob must still be read (dead-knob
drift). Same contract for chaos fault sites: every ``faults.site("…")``
registered in the code tree must appear in docs/robustness.md's catalog.

An undocumented knob is an outage waiting for an operator: the serving
levers (KAKVEDA_SERVE_*), the bench sweep controls and the metrics-plane
sizing all change production behavior, and the only discoverable surface
is the docs. The converse rots just as fast: a knob the docs still teach
but the code no longer reads sends an operator tuning a no-op mid-
incident. This script greps the *code* tree for knob references and the
*docs* corpus (CLAUDE.md, README.md, TROUBLESHOOTING.md, BASELINE.md,
docs/**/*.md) for mentions; anything referenced-but-undocumented OR
documented-but-unreferenced fails the check. Fault sites get the same
treatment because an operator can only arm (``KAKVEDA_FAULTS``) what the
catalog names — the site list grew three PRs straight with nothing
guarding the docs. Runs in tier-1 via tests/test_knobs.py.

Usage: ``python scripts/check_knobs.py [repo_root]`` — exits nonzero and
lists the offending knobs/sites on stdout.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

KNOB_RE = re.compile(r"KAKVEDA_[A-Z0-9_]+")
# A fault-site registration in code: faults.site("engine.dispatch") /
# _faults.site("gfkb.append"). Dotted lowercase names only — the call in
# core/faults.py's own site() definition has no literal and never matches.
SITE_RE = re.compile(r"""\bsite\(\s*["']([a-z0-9_]+(?:\.[a-z0-9_]+)+)["']\s*\)""")

# Code that can introduce operator-facing knobs. Tests are deliberately
# excluded: KAKVEDA_TEST_* style fixtures are not operator surface.
CODE_PATHS = ("kakveda_tpu", "scripts", "bench.py", "__graft_entry__.py")
DOC_PATHS = ("CLAUDE.md", "README.md", "TROUBLESHOOTING.md", "BASELINE.md", "docs")

# Internal/cross-process plumbing set by our own launchers, not operators.
ALLOWLIST = frozenset({
    "KAKVEDA_PROCESS_ID",  # set per-process by the multihost launcher
    "KAKVEDA_TEST_PLATFORM",  # test-suite lever (tests/conftest.py), named here
})

# Knobs the docs legitimately mention without the scanned code tree reading
# them — test-surface levers (tests/ is excluded from CODE_PATHS on
# purpose) and docs-about-the-docs. Anything else documented-but-unread is
# dead-knob drift and fails.
DOC_ONLY_ALLOWLIST = frozenset({
    "KAKVEDA_TEST_PLATFORM",  # tests/conftest.py: run the suite on real TPU
    # tests/test_hf_integration.py: prompt/expectation for the real-weight
    # integration test (tests/ is outside the code scan)
    "KAKVEDA_HF_PROMPT",
    "KAKVEDA_HF_EXPECT",
})


def _md_files(root: Path):
    for rel in DOC_PATHS:
        p = root / rel
        if p.is_file():
            yield p
        elif p.is_dir():
            yield from sorted(p.rglob("*.md"))


def _code_files(root: Path):
    for rel in CODE_PATHS:
        p = root / rel
        if p.is_file():
            yield p
        elif p.is_dir():
            yield from sorted(p.rglob("*.py"))


def referenced_knobs(root: Path) -> dict:
    """knob -> sorted list of repo-relative files referencing it."""
    refs: dict = {}
    for f in _code_files(root):
        try:
            text = f.read_text(errors="replace")
        except OSError:
            continue
        for m in set(KNOB_RE.findall(text)):
            if m.rstrip("_") != m or m == "KAKVEDA_":
                continue
            refs.setdefault(m, []).append(str(f.relative_to(root)))
    for files in refs.values():
        files.sort()
    return refs


def documented_knobs(root: Path) -> set:
    docs: set = set()
    for f in _md_files(root):
        try:
            docs.update(KNOB_RE.findall(f.read_text(errors="replace")))
        except OSError:
            continue
    return docs


def undocumented_knobs(root: Path) -> dict:
    """knob -> referencing files, for every knob the docs never mention."""
    refs = referenced_knobs(root)
    docs = documented_knobs(root)
    return {
        k: v for k, v in sorted(refs.items())
        if k not in docs and k not in ALLOWLIST
    }


def registered_fault_sites(root: Path) -> dict:
    """site name -> sorted list of repo-relative files registering it."""
    refs: dict = {}
    for f in _code_files(root):
        try:
            text = f.read_text(errors="replace")
        except OSError:
            continue
        for m in set(SITE_RE.findall(text)):
            refs.setdefault(m, []).append(str(f.relative_to(root)))
    for files in refs.values():
        files.sort()
    return refs


def undocumented_fault_sites(root: Path) -> dict:
    """Registered sites docs/robustness.md never mentions — the catalog is
    the only surface an operator can discover KAKVEDA_FAULTS arms from."""
    doc = root / "docs" / "robustness.md"
    try:
        text = doc.read_text(errors="replace")
    except OSError:
        text = ""
    return {k: v for k, v in sorted(registered_fault_sites(root).items())
            if k not in text}


def dead_knobs(root: Path) -> list:
    """Documented knobs the code no longer references — dead-knob drift."""
    refs = referenced_knobs(root)
    docs = documented_knobs(root)
    return sorted(
        k for k in docs
        if k not in refs
        and k not in DOC_ONLY_ALLOWLIST
        and k.rstrip("_") == k and k != "KAKVEDA_"
    )


def main(argv: list) -> int:
    root = Path(argv[1]).resolve() if len(argv) > 1 else Path(__file__).resolve().parents[1]
    missing = undocumented_knobs(root)
    dead = dead_knobs(root)
    missing_sites = undocumented_fault_sites(root)
    if not missing and not dead and not missing_sites:
        print(f"check_knobs: all {len(referenced_knobs(root))} KAKVEDA_* knobs "
              f"documented, none dead; all {len(registered_fault_sites(root))} "
              "fault sites cataloged")
        return 0
    if missing:
        print(f"check_knobs: {len(missing)} undocumented KAKVEDA_* knob(s):")
        for knob, files in missing.items():
            print(f"  {knob}  (referenced by {', '.join(files[:3])}"
                  f"{', …' if len(files) > 3 else ''})")
        print("document them in CLAUDE.md or docs/ (see docs/observability.md knob registry)")
    if dead:
        print(f"check_knobs: {len(dead)} dead KAKVEDA_* knob(s) (documented but "
              "no longer read by any code):")
        for knob in dead:
            print(f"  {knob}")
        print("remove them from the docs, or add to DOC_ONLY_ALLOWLIST if "
              "deliberately doc-only")
    if missing_sites:
        print(f"check_knobs: {len(missing_sites)} fault site(s) registered in "
              "code but missing from the docs/robustness.md catalog:")
        for site, files in missing_sites.items():
            print(f"  {site}  (registered by {', '.join(files[:3])}"
                  f"{', …' if len(files) > 3 else ''})")
        print("add them to the fault-site catalog table in docs/robustness.md")
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
