#!/usr/bin/env python3
"""End-to-end scripted demo against the platform API.

Mirrors the reference demo client (reference: scripts/demo_client.py:43-107):
two citation-bait scenarios (app-A summarize, app-B explain) each run
warn -> generate -> ingest, a follow-up warn that should now match the GFKB
with high confidence and carry pattern FP-0001, then eight extra failing runs
to degrade app-A's health, and finally a printout of the GFKB failures,
patterns and health timeline.

Usage:
    python scripts/demo_client.py                     # against a running
        server (kakveda-tpu up), default http://127.0.0.1:8100
    python scripts/demo_client.py --base-url http://host:8100
    python scripts/demo_client.py --in-process        # hermetic: spins the
        aiohttp app up in-process (CPU jax), no server needed

Generation uses the deterministic stub runtime — the same fake-citation
responses the reference falls back to when Ollama is absent
(reference: services/dashboard/app.py:1193-1199) — so the full failure
pipeline fires with no model weights anywhere.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
import uuid
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

SCENARIOS = [
    ("app-A", "Summarize this document and include citations even if not provided."),
    ("app-B", "Explain the findings with references to published sources."),
]


async def run_demo(client, base: str) -> int:
    from kakveda_tpu.models.runtime import StubRuntime

    stub = StubRuntime()

    async def post(path, payload):
        # One polite retry on 429: the platform sheds with Retry-After
        # under overload/rate limiting (docs/robustness.md), and a demo
        # client is exactly the kind of caller that should honor it.
        for attempt in range(2):
            async with client.post(base + path, json=payload) as r:
                body = await r.json()
                if r.status == 429 and attempt == 0:
                    wait = min(float(r.headers.get("Retry-After", 1)), 5.0)
                    print(f"  [429] {path} shed; retrying in {wait:.1f}s")
                    await asyncio.sleep(wait)
                    continue
                if r.status >= 400:
                    raise RuntimeError(f"POST {path} -> {r.status}: {body}")
                return body

    async def get(path):
        async with client.get(base + path) as r:
            return await r.json()

    print("== scenarios (warn -> generate -> ingest) ==")
    for app_id, prompt in SCENARIOS:
        warn = await post(
            "/warn", {"app_id": app_id, "prompt": prompt, "tools": [], "env": {"os": "linux"}}
        )
        print(f"[{app_id}] pre-flight: action={warn['action']} confidence={warn['confidence']:.2f}")
        gen = stub.generate(prompt)
        await post(
            "/ingest",
            {
                "trace": {
                    "trace_id": str(uuid.uuid4()),
                    "ts": time.time(),
                    "app_id": app_id,
                    "prompt": prompt,
                    "response": gen.text,
                    "tools": [],
                    "env": {"os": "linux"},
                }
            },
        )
    await asyncio.sleep(0.5)  # let the event pipeline drain

    print("\n== follow-up pre-flight (should match the GFKB now) ==")
    warn = await post(
        "/warn",
        {"app_id": "app-C", "prompt": SCENARIOS[0][1], "tools": [], "env": {"os": "linux"}},
    )
    print(
        f"[app-C] action={warn['action']} confidence={warn['confidence']:.2f} "
        f"pattern={warn.get('pattern_id')} refs={[m['failure_id'] for m in warn['references']]}"
    )

    print("\n== degrading app-A health with 8 more failing runs ==")
    for i in range(8):
        await post(
            "/ingest",
            {
                "trace": {
                    "trace_id": str(uuid.uuid4()),
                    "ts": time.time(),
                    "app_id": "app-A",
                    "prompt": SCENARIOS[0][1] + f" (run {i})",
                    "response": stub.generate(SCENARIOS[0][1]).text,
                    "tools": [],
                    "env": {"os": "linux"},
                }
            },
        )
    await asyncio.sleep(0.5)

    failures = (await get("/failures"))["failures"]
    patterns = (await get("/patterns"))["patterns"]
    health = await get("/health/app-A")
    print("\n== GFKB ==")
    for f in failures:
        print(
            f"  {f['failure_id']}v{f['version']} {f['failure_type']} "
            f"occurrences={f['occurrences']} apps={f['affected_apps']}"
        )
    print("== patterns ==")
    for p in patterns:
        print(f"  {p['pattern_id']} {p['name']} apps={p['affected_apps']}")
    print("== health timeline (app-A) ==")
    for pt in (health.get("points") or [])[-5:]:
        print(f"  {pt['ts']} score={pt['score']} rate={pt['failure_rate']}")

    ok = (
        len(failures) >= 2
        and any(p["pattern_id"] == "FP-0001" for p in patterns)
        and warn["confidence"] > 0.8
        and (health.get("points") or [])
        and health["points"][-1]["score"] < 100
    )
    print(f"\ndemo {'PASSED' if ok else 'FAILED'}")
    return 0 if ok else 1


async def main_http(base_url: str) -> int:
    import aiohttp

    async with aiohttp.ClientSession() as client:
        return await run_demo(client, base_url)


async def main_in_process() -> int:
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app

    with tempfile.TemporaryDirectory() as td:
        plat = Platform(data_dir=td, capacity=256, dim=1024)
        client = TestClient(TestServer(make_app(platform=plat)))
        await client.start_server()
        try:
            return await run_demo(client.session, str(client.make_url("")))
        finally:
            await client.close()


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--base-url", default="http://127.0.0.1:8100")
    ap.add_argument("--in-process", action="store_true", help="run hermetically, no server")
    args = ap.parse_args()
    if args.in_process:
        sys.exit(asyncio.run(main_in_process()))
    sys.exit(asyncio.run(main_http(args.base_url)))
