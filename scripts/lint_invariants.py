#!/usr/bin/env python
"""Invariant lint: machine-enforce the CLAUDE.md design contracts.

AST static analysis over the code tree (no imports, no jax — sub-second):
forward-flag parity across the four forward paths, single-writer
transition helpers, stats-lock discipline, host-sync hazards in jit
bodies, typed-error discipline on service paths, fault-site resolve-once,
plus the knob-docs / fault-site-catalog parity checks shared with
``scripts/check_knobs.py``. Rule catalog: docs/static-analysis.md.

Usage::

    python scripts/lint_invariants.py [root] [--json] [--rule ID ...]
                                      [--list-rules] [--update-baseline]
                                      [--changed]

``--changed`` scans only the files git reports as modified/staged/
untracked (filtered to the lint's code tree) — a sub-100 ms pre-commit
loop. Whole-tree rules (knob docs, forward-flag parity, lock-order …)
need the full corpus and are skipped in that mode: the full-tree run
stays the tier-1 gate.

Exit codes (stable; tier-1 asserts them via tests/test_lint_invariants.py):
0 = clean (suppressed/baselined findings allowed), 1 = live findings,
2 = usage or internal error.

Suppress a deliberate exception inline with ``# kakveda: allow[rule-id]``
(same line or the line above) and a comment saying why. The committed
baseline (kakveda_tpu/analysis/baseline.json) grandfathers findings
without suppressing new ones — it ships empty; keep it that way.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

# Script-mode bootstrap: `python scripts/lint_invariants.py` puts scripts/
# on sys.path, not the repo root the package imports need.
_REPO = Path(__file__).resolve().parents[1]
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from kakveda_tpu.analysis import discovery  # noqa: E402
from kakveda_tpu.analysis.framework import (  # noqa: E402
    BASELINE_REL,
    all_rules,
    run_lint,
)


def _changed_files(root: Path) -> list:
    """Modified + staged + untracked .py files inside the lint's code
    tree, as absolute paths. Empty list = nothing relevant changed."""
    import subprocess

    out = subprocess.run(
        ["git", "-C", str(root), "status", "--porcelain", "--untracked-files=all"],
        capture_output=True, text=True, timeout=10, check=True,
    ).stdout
    rels = set()
    for line in out.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].strip()
        if " -> " in path:  # rename: scan the new side
            path = path.split(" -> ", 1)[1]
        path = path.strip('"')
        if path.endswith(".py"):
            rels.add(path)
    picked = []
    for rel in sorted(rels):
        p = root / rel
        if not p.is_file() or discovery._skipped(root, p):
            continue
        if any(rel == c or rel.startswith(c + "/") for c in discovery.CODE_PATHS):
            picked.append(p)
    return picked


def main(argv: list) -> int:
    ap = argparse.ArgumentParser(
        prog="lint_invariants.py",
        description="AST invariant lint (docs/static-analysis.md)",
    )
    ap.add_argument("root", nargs="?", default=str(_REPO))
    ap.add_argument("--json", action="store_true", help="machine output")
    ap.add_argument("--rule", action="append", help="run only these rule ids")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to grandfather current findings",
    )
    ap.add_argument(
        "--changed", action="store_true",
        help="scan only git-modified files; per-file rules only (pre-commit)",
    )
    try:
        args = ap.parse_args(argv[1:])
    except SystemExit as e:
        return 2 if e.code else 0

    if args.list_rules:
        for rid, rule in all_rules().items():
            print(f"{rid}: {rule.invariant}")
        return 0

    root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"lint_invariants: not a directory: {root}", file=sys.stderr)
        return 2
    files = None
    if args.changed:
        if args.update_baseline:
            print("lint_invariants: --changed and --update-baseline are "
                  "incompatible (baseline needs the full tree)", file=sys.stderr)
            return 2
        try:
            files = _changed_files(root)
        except Exception as e:  # noqa: BLE001 — not-a-git-checkout etc.
            print(f"lint_invariants: --changed needs git: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2
        if not files:
            print("lint_invariants: ok — no changed code files")
            return 0
    try:
        res = run_lint(root, rule_ids=args.rule, files=files)
    except KeyError as e:
        print(f"lint_invariants: unknown rule {e.args[0]!r} "
              "(see --list-rules)", file=sys.stderr)
        return 2
    except Exception as e:  # noqa: BLE001 — internal error is exit 2, not a traceback-as-failure
        print(f"lint_invariants: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.update_baseline:
        path = root / BASELINE_REL
        keys = sorted(f.baseline_key for f in res.findings + res.baselined)
        path.write_text(json.dumps(keys, indent=2) + "\n")
        print(f"lint_invariants: baseline rewritten with {len(keys)} key(s)")
        return 0

    if args.json:
        print(json.dumps({
            "findings": [f.as_dict() for f in res.findings],
            "suppressed": [f.as_dict() for f in res.suppressed],
            "baselined": [f.as_dict() for f in res.baselined],
            "rules": res.rules_run,
        }))
        return 1 if res.findings else 0

    for f in res.findings:
        print(f.human())
    for f in res.baselined:
        print(f"{f.human()}  [baselined]")
    status = "FAIL" if res.findings else "ok"
    print(
        f"lint_invariants: {status} — {len(res.findings)} finding(s), "
        f"{len(res.suppressed)} suppressed, {len(res.baselined)} baselined "
        f"({len(res.rules_run)} rule(s))"
    )
    return 1 if res.findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
