#!/usr/bin/env python3
"""North-star-scale rehearsal: snapshot/restore (+ optional mining) at N rows.

VERDICT r4 #7: mining was verified at 500k rows and snapshot/restore at
100k; the north-star index size is 1M. This script builds an N-row GFKB
through the REAL ingest path (distinct signature texts, batched
embed+insert), snapshots it, and times:

  * restore-from-snapshot  (fresh GFKB on the same data_dir)
  * full log replay        (same failures.jsonl, snapshot hidden)

then verifies the two agree: identical record count and identical
match_batch results for probe queries. Optionally (--mine, TPU
recommended) runs pattern mining over the restored index with the purity
gate on.

Emits ONE JSON line with all timings. CPU at 1M takes tens of minutes
(single-threaded host featurize dominates); run detached:

    JAX_PLATFORMS=cpu python scripts/rehearsal_scale.py --n 1000000 \
        --dir /tmp/rehearsal_1m > /tmp/rehearsal_1m.json 2>/tmp/rehearsal_1m.log
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

VERBS = ["Summarize", "Explain", "Describe", "Review", "Audit", "Outline"]
TAILS = [
    "and include citations even if not provided",
    "adding references for every claim",
    "with sources listed for each point",
    "without inventing sources",
    "while citing the original documents",
]
TYPES = ["HALLUCINATION_CITATION", "TOOL_MISUSE", "REFUSAL_LOOP", "FORMAT_DRIFT"]


def sig(i: int) -> str:
    return (
        f"{VERBS[i % len(VERBS)]} document {i} "
        f"{TAILS[i % len(TAILS)]} (case {i % 97})"
    )


def build(gfkb, n: int, chunk: int) -> float:
    t0 = time.time()
    for start in range(0, n, chunk):
        m = min(chunk, n - start)
        items = [
            {
                "failure_type": TYPES[(start + i) % len(TYPES)],
                "signature_text": sig(start + i),
                "app_id": f"app-{(start + i) % 11}",
                "impact_severity": "medium",
            }
            for i in range(m)
        ]
        gfkb.upsert_failures_batch(items)
        if (start // chunk) % 16 == 0:
            el = time.time() - t0
            print(
                f"rehearsal: inserted {start + m:,}/{n:,} ({(start + m) / max(el, 1e-9):,.0f}/s)",
                file=sys.stderr,
                flush=True,
            )
    return time.time() - t0


def probe_match(gfkb, n: int):
    qs = [sig(i) for i in range(0, n, max(1, n // 8))][:8]
    res = gfkb.match_batch(qs)
    return [
        [(m.failure_id, round(m.score, 4)) for m in row] for row in res
    ]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--dim", type=int, default=2048)
    ap.add_argument("--chunk", type=int, default=8192)
    ap.add_argument("--dir", default="/tmp/kakveda-rehearsal")
    ap.add_argument("--mine", action="store_true", help="also run pattern mining (slow off-TPU)")
    args = ap.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from kakveda_tpu.index.gfkb import GFKB

    root = Path(args.dir)
    root.mkdir(parents=True, exist_ok=True)
    data = root / "data"
    out: dict = {"n": args.n, "dim": args.dim, "backend": jax.default_backend()}

    # --- build + snapshot ------------------------------------------------
    g = GFKB(data_dir=data, capacity=args.n + args.chunk, dim=args.dim)
    if g.count < args.n:
        out["ingest_s"] = round(build(g, args.n, args.chunk), 1)
        print(f"rehearsal: built {g.count:,} rows in {out['ingest_s']}s", file=sys.stderr)
    t0 = time.time()
    g.snapshot()
    out["snapshot_s"] = round(time.time() - t0, 1)
    baseline = probe_match(g, args.n)
    n_built = g.count
    g.close()
    del g

    # --- restore from snapshot ------------------------------------------
    t0 = time.time()
    g_restored = GFKB(data_dir=data, capacity=args.n + args.chunk, dim=args.dim)
    out["restore_s"] = round(time.time() - t0, 1)
    assert g_restored.count == n_built, (g_restored.count, n_built)
    restored = probe_match(g_restored, args.n)

    # --- full replay (snapshot hidden: same log, no vectors) -------------
    snap = data / "snapshot"
    hidden = data / ".snapshot-hidden"
    if snap.exists():
        snap.rename(hidden)
    try:
        t0 = time.time()
        g_replayed = GFKB(data_dir=data, capacity=args.n + args.chunk, dim=args.dim)
        out["replay_s"] = round(time.time() - t0, 1)
        assert g_replayed.count == n_built
        replayed = probe_match(g_replayed, args.n)
    finally:
        if hidden.exists():
            hidden.rename(snap)

    # --- parity: restore == replay == pre-snapshot ------------------------
    ids = lambda res: [[fid for fid, _ in row] for row in res]  # noqa: E731
    out["parity_ids"] = ids(restored) == ids(replayed) == ids(baseline)
    # Scores: restored vectors round-trip through f32 disk + device store;
    # replayed re-embed from text. Same featurizer ⇒ tight agreement.
    score_gap = max(
        (abs(a - b) for ra, rb in zip(restored, replayed) for (_, a), (_, b) in zip(ra, rb)),
        default=0.0,
    )
    out["max_score_gap"] = round(score_gap, 6)
    out["restore_vs_replay_speedup"] = (
        round(out["replay_s"] / out["restore_s"], 2) if out["restore_s"] else 0.0
    )

    if args.mine:
        from kakveda_tpu.pipeline.patterns import PatternDetector

        t0 = time.time()
        pats = PatternDetector(g_restored).mine_patterns()
        out["mine_s"] = round(time.time() - t0, 1)
        out["mine_patterns"] = len(pats)

    g_restored.close()
    print(json.dumps(out))
    return 0 if out["parity_ids"] else 1


if __name__ == "__main__":
    sys.exit(main())
