#!/usr/bin/env python3
"""Render docs/figures/*.svg to PNG.

Mirrors the reference's figure renderer (reference:
scripts/render_figures.py:22-49). cairosvg is not part of this image's
baked dependency set, so the script degrades to a no-op with a clear
message when it is absent (the SVGs render natively on GitHub either way).

Usage: python scripts/render_figures.py [--scale 2.0]
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

FIGURES_DIR = Path(__file__).parent.parent / "docs" / "figures"


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--scale", type=float, default=2.0, help="raster scale factor")
    args = ap.parse_args()

    try:
        import cairosvg
    except ImportError:
        print("cairosvg not installed; SVG sources are the canonical figures — skipping")
        return 0

    svgs = sorted(FIGURES_DIR.glob("*.svg"))
    if not svgs:
        print(f"no figures under {FIGURES_DIR}")
        return 1
    for svg in svgs:
        png = svg.with_suffix(".png")
        cairosvg.svg2png(url=str(svg), write_to=str(png), scale=args.scale)
        print(f"rendered {png} ({png.stat().st_size} bytes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
