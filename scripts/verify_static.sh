#!/usr/bin/env bash
# One-shot static verification: everything that must be green before a
# commit, without touching a backend or waiting on the full test suite.
#
#   bash scripts/verify_static.sh            # whole tree (~5 s)
#   bash scripts/verify_static.sh --changed  # git-dirty files only
#
# Runs, in order:
#   1. the invariant lint (all 16 rules incl. the device-plane pass;
#      --changed narrows to per-file rules over dirty files)
#   2. the knob/fault-site parity check (legacy check_knobs CLI)
#   3. a ledger smoke: KAKVEDA_LEDGER=1 install/attribute/uninstall on a
#      throwaway jit — proves the runtime half of the device pass wires
#      up on this interpreter (jax import, monitoring listener, metrics
#      families) without a TPU.
#   4. a trace smoke: a private core/trace.py Tracer builds a 3-span
#      tree, round-trips the W3C traceparent wire format, asserts the
#      ring dump + orphan accounting, and proves an armed trace.record
#      fault drops the span without raising (~1 s, no backend).
#   5. the autoscaler policy selftest: the canned decision table over the
#      PURE decide/commit functions (fleet/autoscaler.py) — no processes,
#      no router, ~1 s; a hysteresis/backoff regression fails pre-commit.
#   6. a compaction smoke: a tiny GFKB takes rows + occurrence bumps,
#      compacts (checkpoint+delta fence), reopens, and must serve the
#      identical top-1 match with the manifest generation advanced —
#      the failure-memory lifecycle's restart contract in ~1 s on CPU.
#
# Exit: non-zero on the first failing stage. Tier-1 runs this via
# tests/test_verify_static.py, so CI and the pre-commit habit share one
# entry point.

set -euo pipefail
cd "$(dirname "$0")/.."

CHANGED=""
if [[ "${1:-}" == "--changed" ]]; then
    CHANGED="--changed"
fi

echo "== invariant lint =="
python scripts/lint_invariants.py ${CHANGED}

echo "== knob / fault-site parity =="
python scripts/check_knobs.py

echo "== ledger smoke =="
KAKVEDA_LEDGER=1 python - <<'EOF'
import jax

jax.config.update("jax_platforms", "cpu")  # never touch the remote TPU
import jax.numpy as jnp

from kakveda_tpu.core import ledger

assert ledger.maybe_install(), "KAKVEDA_LEDGER=1 set but install refused"
try:
    @jax.jit
    def _smoke(x):
        return x * 2.0

    with ledger.phase("smoke"):
        _smoke(jnp.zeros((4,), jnp.float32)).block_until_ready()
        ledger.note_transfer("h2d", 16)
    rep = ledger.ledger_report()
    assert rep["compiles"].get("_smoke") == 1, rep["compiles"]
    assert rep["transfer_by_phase"]["h2d"]["smoke"] == 16, rep
    from kakveda_tpu.core import metrics

    text = metrics.get_registry().render()
    assert 'kakveda_compile_total{fn="_smoke"}' in text
    print("ledger smoke: ok — 1 compile attributed, 16 bytes phased")
finally:
    ledger.uninstall()
    ledger.reset()
EOF

echo "== trace smoke =="
python - <<'EOF'
from kakveda_tpu.core import faults
from kakveda_tpu.core.trace import (
    Tracer, assemble_tree, format_traceparent, parse_traceparent, render_trace,
)

tr = Tracer(capacity=64, sample=1.0)
with tr.start_span("router.request", path="/warn") as root:
    root.activate()
    try:
        with tr.start_span("router.scatter", replica="r0") as hop:
            # wire round-trip: serialize, parse, continue on "the peer"
            tp = hop.traceparent()
            parsed = parse_traceparent(tp)
            assert parsed is not None and parsed[0] == root.trace_id, tp
            assert format_traceparent(*parsed) == tp
            child = tr.start_span("service.request", traceparent=tp)
            child.end("ok")
    finally:
        root.deactivate()
spans = tr.dump(root.trace_id)
assert len(spans) == 3, spans
tree = assemble_tree(spans)
assert len(tree) == 1 and tree[0]["name"] == "router.request"
assert render_trace(spans).startswith(f"trace {root.trace_id}")
p = tr.plane()
assert p["started"] == p["ended"] == 3 and p["orphaned"] == 0, p
# failure contract: an armed trace.record site drops the span, never raises
faults.arm("trace.record:1.0:1")
try:
    with tr.start_span("chaos.victim"):
        pass
finally:
    faults.disarm()
p = tr.plane()
assert p["orphaned"] == 0 and p["dropped"] == 1, p
print("trace smoke: ok — 3-span tree assembled, wire round-trip, "
      "armed recorder dropped 1 span without raising")
EOF

echo "== autoscaler policy selftest =="
python - <<'EOF'
from kakveda_tpu.fleet.autoscaler import policy_selftest

n = policy_selftest()
print(f"policy selftest: ok — {n} checks")
EOF

echo "== compaction smoke =="
python - <<'EOF'
import jax

jax.config.update("jax_platforms", "cpu")  # never touch the remote TPU
import json
import tempfile
from pathlib import Path

from kakveda_tpu.index.gfkb import GFKB

data = Path(tempfile.mkdtemp(prefix="kakveda-compact-smoke-"))
kb = GFKB(data_dir=data, capacity=64, dim=256)
rows = [
    {"failure_type": "oom", "signature_text": f"compact smoke sig {i}",
     "app_id": f"a{i % 3}", "impact_severity": "high"}
    for i in range(24)
]
kb.upsert_failures_batch(rows)
kb.upsert_failures_batch(rows[:12])  # occurrence bumps = delta history
before = kb.match_batch(["compact smoke sig 7"])[0]
assert before, "no match before compaction"
out = kb.compact()
assert out["compacted"], out
kb.close()

kb2 = GFKB(data_dir=data, capacity=64, dim=256)
after = kb2.match_batch(["compact smoke sig 7"])[0]
assert after and after[0].failure_id == before[0].failure_id, (before, after)
assert abs(after[0].score - before[0].score) < 1e-5, (before, after)
man = json.loads((data / "snapshot" / "manifest.json").read_text())
assert man["compact"]["generation"] == out["generation"], man
assert man["log_offset"] == 0, man
kb2.close()
print(f"compaction smoke: ok — gen {out['generation']}, "
      f"{out['checkpoint_rows']} rows checkpointed, top-1 parity held")
EOF

echo "verify_static: all stages green"
