#!/usr/bin/env bash
# One-shot static verification: everything that must be green before a
# commit, without touching a backend or waiting on the full test suite.
#
#   bash scripts/verify_static.sh            # whole tree (~5 s)
#   bash scripts/verify_static.sh --changed  # git-dirty files only
#
# Runs, in order:
#   1. the invariant lint (all 16 rules incl. the device-plane pass;
#      --changed narrows to per-file rules over dirty files)
#   2. the knob/fault-site parity check (legacy check_knobs CLI)
#   3. a ledger smoke: KAKVEDA_LEDGER=1 install/attribute/uninstall on a
#      throwaway jit — proves the runtime half of the device pass wires
#      up on this interpreter (jax import, monitoring listener, metrics
#      families) without a TPU.
#   4. the autoscaler policy selftest: the canned decision table over the
#      PURE decide/commit functions (fleet/autoscaler.py) — no processes,
#      no router, ~1 s; a hysteresis/backoff regression fails pre-commit.
#
# Exit: non-zero on the first failing stage. Tier-1 runs this via
# tests/test_verify_static.py, so CI and the pre-commit habit share one
# entry point.

set -euo pipefail
cd "$(dirname "$0")/.."

CHANGED=""
if [[ "${1:-}" == "--changed" ]]; then
    CHANGED="--changed"
fi

echo "== invariant lint =="
python scripts/lint_invariants.py ${CHANGED}

echo "== knob / fault-site parity =="
python scripts/check_knobs.py

echo "== ledger smoke =="
KAKVEDA_LEDGER=1 python - <<'EOF'
import jax

jax.config.update("jax_platforms", "cpu")  # never touch the remote TPU
import jax.numpy as jnp

from kakveda_tpu.core import ledger

assert ledger.maybe_install(), "KAKVEDA_LEDGER=1 set but install refused"
try:
    @jax.jit
    def _smoke(x):
        return x * 2.0

    with ledger.phase("smoke"):
        _smoke(jnp.zeros((4,), jnp.float32)).block_until_ready()
        ledger.note_transfer("h2d", 16)
    rep = ledger.ledger_report()
    assert rep["compiles"].get("_smoke") == 1, rep["compiles"]
    assert rep["transfer_by_phase"]["h2d"]["smoke"] == 16, rep
    from kakveda_tpu.core import metrics

    text = metrics.get_registry().render()
    assert 'kakveda_compile_total{fn="_smoke"}' in text
    print("ledger smoke: ok — 1 compile attributed, 16 bytes phased")
finally:
    ledger.uninstall()
    ledger.reset()
EOF

echo "== autoscaler policy selftest =="
python - <<'EOF'
from kakveda_tpu.fleet.autoscaler import policy_selftest

n = policy_selftest()
print(f"policy selftest: ok — {n} checks")
EOF

echo "verify_static: all stages green"
