"""Test bootstrap: run everything on a simulated 8-device CPU mesh.

The XLA host-device-count flag must be set before jax initializes its
backends. This image's sitecustomize pre-registers a TPU ('axon') platform
and pins ``jax_platforms`` via jax.config, so an env var alone is not enough
— we override through jax.config here, before any test touches a device.
Set KAKVEDA_TEST_PLATFORM=tpu to run the suite on real hardware instead.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if os.environ.get("KAKVEDA_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_data_dir(tmp_path):
    return tmp_path / "data"


import pytest  # noqa: E402


@pytest.fixture
def decode_parity():
    """Cached greedy decode must reproduce the full forward's argmax chain —
    the serving-path invariant every model family asserts. A fixture (not a
    conftest import) so it works under any pytest import mode."""
    import jax.numpy as jnp

    from kakveda_tpu.models.generate import generate_tokens
    from kakveda_tpu.models.llama import forward, mask_pad_vocab

    def check(params, cfg, prompt, n=8):
        greedy_cached = generate_tokens(params, cfg, prompt, max_new_tokens=n)
        toks = list(prompt)
        for _ in range(n):
            logits = forward(params, cfg, jnp.asarray([toks]))
            # Same padded-vocab masking as the decode path — without it a
            # checkpoint with effective_vocab set could argmax a pad column
            # here and spuriously fail (or hide a masking bug).
            toks.append(int(jnp.argmax(mask_pad_vocab(logits[0, -1], cfg))))
        assert greedy_cached == toks[len(prompt) :]

    return check
