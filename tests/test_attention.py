"""Fused-attention parity: the Pallas flash kernel (interpret mode on CPU)
and the grouped XLA path must both reproduce the plain O(S²) oracle
(`causal_attention`) bit-for-bit up to f32 tolerance, across GQA group
sizes, cache offsets (decode), and left-pad validity masks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kakveda_tpu.models.attention import _gqa_xla, flash_gqa_cache, gqa_cache_attention
from kakveda_tpu.models.llama import _repeat_kv, causal_attention


def _mk(b, s, h, kv, l, d, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, kv, l, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kv, l, d)), jnp.float32)
    return q, k, v


def _oracle(q, k, v, pos0, kv_valid):
    """causal_attention over the repeated, seq-major cache + explicit
    validity masking (mirrors the pre-fusion decode_step math)."""
    b, s, h, d = q.shape
    kv = k.shape[1]
    ks = k.transpose(0, 2, 1, 3)  # [B, L, KV, D]
    vs = v.transpose(0, 2, 1, 3)
    kr = _repeat_kv(ks, h // kv)
    vr = _repeat_kv(vs, h // kv)
    l = kr.shape[1]
    scale = d**-0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, kr).astype(jnp.float32) * scale
    q_pos = pos0 + jnp.arange(s)
    mask = q_pos[:, None] >= jnp.arange(l)[None, :]
    if kv_valid is not None:
        full = mask[None, :, :] & kv_valid[:, None, :]
        scores = jnp.where(full[:, None], scores, -1e30)
    else:
        scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, vr)


CASES = [
    # (B, S, H, KV, L, D, pos0, with_valid)   — prefill, decode, MQA, MHA
    (2, 8, 4, 2, 32, 16, 0, False),
    (2, 1, 4, 2, 32, 16, 7, False),     # single-token decode mid-cache
    (1, 4, 8, 1, 16, 8, 3, False),      # MQA (kv=1)
    (2, 8, 4, 4, 32, 16, 0, False),     # MHA (no grouping)
    (2, 8, 4, 2, 32, 16, 0, True),      # left-pad validity mask
    (3, 1, 8, 2, 64, 32, 20, True),     # batched decode with pads
]


@pytest.mark.parametrize("b,s,h,kv,l,d,pos0,with_valid", CASES)
def test_grouped_xla_matches_oracle(b, s, h, kv, l, d, pos0, with_valid):
    q, k, v = _mk(b, s, h, kv, l, d, seed=b + s)
    valid = None
    if with_valid:
        rng = np.random.default_rng(99)
        off = rng.integers(0, 4, size=(b,))
        valid = jnp.asarray(np.arange(l)[None, :] >= off[:, None])
    want = np.asarray(_oracle(q, k, v, pos0, valid))
    got = np.asarray(_gqa_xla(q, k, v, jnp.asarray(pos0), valid))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("b,s,h,kv,l,d,pos0,with_valid", CASES)
def test_flash_kernel_matches_oracle(b, s, h, kv, l, d, pos0, with_valid):
    q, k, v = _mk(b, s, h, kv, l, d, seed=b * 7 + s)
    valid = None
    if with_valid:
        rng = np.random.default_rng(7)
        off = rng.integers(0, 4, size=(b,))
        valid = jnp.asarray(np.arange(l)[None, :] >= off[:, None])
    want = np.asarray(_oracle(q, k, v, pos0, valid))
    got = np.asarray(
        flash_gqa_cache(
            q, k, v, jnp.asarray(pos0), valid, q_blk=8, l_blk=16, interpret=True
        )
    )
    # Fully-masked query rows (pad positions before any valid slot) are
    # don't-care: softmax gives a uniform average, flash gives zeros.
    if valid is not None:
        q_pos = pos0 + np.arange(s)
        visible = (q_pos[None, :, None] >= np.arange(l)[None, None, :]) & np.asarray(
            valid
        )[:, None, :]
        live = visible.any(-1)  # [B, S]
        got = got[live]
        want = want[live]
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("b,s,h,kv,l,d,pos0,with_valid", CASES)
def test_flash_kernel_int8_cache_matches_dequant_path(b, s, h, kv, l, d, pos0, with_valid):
    """int8-KV flash: streaming int8 tiles + per-row scales and
    dequantizing IN the kernel must equal dequantize-then-attend (the XLA
    fallback's math) exactly — the kernel casts back to the q dtype, so
    the two paths see identical K/V values."""
    from kakveda_tpu.models.llama import _kv_dequant, _kv_quant_rows

    q, k, v = _mk(b, s, h, kv, l, d, seed=b * 11 + s)
    k_i8, k_sc = _kv_quant_rows(k)
    v_i8, v_sc = _kv_quant_rows(v)
    valid = None
    if with_valid:
        rng = np.random.default_rng(7)
        off = rng.integers(0, 4, size=(b,))
        valid = jnp.asarray(np.arange(l)[None, :] >= off[:, None])
    want = np.asarray(
        _gqa_xla(
            q, _kv_dequant(k_i8, k_sc, q.dtype), _kv_dequant(v_i8, v_sc, q.dtype),
            jnp.asarray(pos0), valid,
        )
    )
    got = np.asarray(
        flash_gqa_cache(
            q, k_i8, v_i8, jnp.asarray(pos0), valid,
            k_scale=k_sc, v_scale=v_sc, q_blk=8, l_blk=16, interpret=True,
        )
    )
    if valid is not None:
        q_pos = pos0 + np.arange(s)
        visible = (q_pos[None, :, None] >= np.arange(l)[None, None, :]) & np.asarray(
            valid
        )[:, None, :]
        live = visible.any(-1)
        got, want = got[live], want[live]
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_flash_int8_bf16_bitwise_matches_dequant_path():
    """Under bf16 compute the kernel must replicate _kv_dequant's exact
    op order (round the scale to bf16 FIRST, multiply in bf16):
    multiply-in-f32-then-round differs in the last bit and would make
    flash vs XLA-fallback logits diverge per element."""
    from kakveda_tpu.models.llama import _kv_dequant, _kv_quant_rows

    rng = np.random.default_rng(3)
    b, s, h, kv, l, d = 1, 8, 4, 2, 32, 16
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, kv, l, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, kv, l, d)), jnp.bfloat16)
    k_i8, k_sc = _kv_quant_rows(k)
    v_i8, v_sc = _kv_quant_rows(v)
    want = _gqa_xla(
        q, _kv_dequant(k_i8, k_sc, jnp.bfloat16), _kv_dequant(v_i8, v_sc, jnp.bfloat16),
        jnp.asarray(0), None,
    )
    got = flash_gqa_cache(
        q, k_i8, v_i8, jnp.asarray(0), None,
        k_scale=k_sc, v_scale=v_sc, q_blk=8, l_blk=16, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=1e-2, rtol=1e-2
    )
    # the dequantized K/V the two paths see must be IDENTICAL bits —
    # that's the invariant the kernel's op ordering exists for
    kd_kernel = k_i8.astype(jnp.bfloat16) * k_sc.astype(jnp.bfloat16)[..., None]
    assert jnp.array_equal(kd_kernel, _kv_dequant(k_i8, k_sc, jnp.bfloat16))


def test_flash_decode_shape_pads_q_rows():
    """Single-token decode with a small GQA ratio folds to s*r < 8 query
    rows; the kernel pads them to the sublane multiple and slices the
    output — parity with the XLA path on the same int8 cache."""
    from kakveda_tpu.models.llama import _kv_dequant, _kv_quant_rows

    rng = np.random.default_rng(4)
    b, s, h, kv, l, d = 3, 1, 8, 2, 128, 64  # sr = 4 -> pads to 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, kv, l, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kv, l, d)), jnp.float32)
    k_i8, k_sc = _kv_quant_rows(k)
    v_i8, v_sc = _kv_quant_rows(v)
    pos0 = 40
    want = np.asarray(
        _gqa_xla(
            q, _kv_dequant(k_i8, k_sc, q.dtype), _kv_dequant(v_i8, v_sc, q.dtype),
            jnp.asarray(pos0), None,
        )
    )
    got = np.asarray(
        flash_gqa_cache(
            q, k_i8, v_i8, jnp.asarray(pos0), None,
            k_scale=k_sc, v_scale=v_sc, q_blk=8, l_blk=128, interpret=True,
        )
    )
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_dispatch_int8_cache_xla_fallback_matches_oracle():
    """gqa_cache_attention with k_scale/v_scale on CPU (XLA path) equals
    the oracle over the dequantized cache."""
    from kakveda_tpu.models.llama import _kv_dequant, _kv_quant_rows

    q, k, v = _mk(2, 4, 4, 2, 32, 16, seed=5)
    k_i8, k_sc = _kv_quant_rows(k)
    v_i8, v_sc = _kv_quant_rows(v)
    want = np.asarray(
        _oracle(q, _kv_dequant(k_i8, k_sc, q.dtype), _kv_dequant(v_i8, v_sc, q.dtype), 3, None)
    )
    got = np.asarray(
        gqa_cache_attention(q, k_i8, v_i8, jnp.asarray(3), None, k_scale=k_sc, v_scale=v_sc)
    )
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_flash_kernel_multiblock_streaming():
    """Cache longer than one l-block: online-softmax accumulation across
    tiles must agree with the oracle, including a fully-masked leading tile
    (pos0 far into the cache) and an empty trailing tile."""
    b, s, h, kv, l, d = 2, 4, 4, 2, 64, 16
    q, k, v = _mk(b, s, h, kv, l, d, seed=5)
    for pos0 in (0, 17, 59):
        want = np.asarray(_oracle(q, k, v, pos0, None))
        got = np.asarray(
            flash_gqa_cache(q, k, v, jnp.asarray(pos0), None, q_blk=8, l_blk=16, interpret=True)
        )
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5, err_msg=f"pos0={pos0}")


def _windowed_oracle(q, k, v, pos0, window):
    """Banded oracle = llama.causal_attention(window=) over the repeated,
    seq-major cache (one oracle for the semantics, shared with llama.py)."""
    h, kv = q.shape[2], k.shape[1]
    kr = _repeat_kv(k.transpose(0, 2, 1, 3), h // kv)
    vr = _repeat_kv(v.transpose(0, 2, 1, 3), h // kv)
    return causal_attention(q, kr, vr, q_off=pos0, window=window)


@pytest.mark.parametrize("pos0,window", [(0, 4), (20, 8), (31, 5)])
def test_sliding_window_xla_and_flash_match_oracle(pos0, window):
    """Mistral-style sliding window in both fused paths vs the banded oracle
    — including a decode position deep enough that the window excludes
    early cache slots."""
    b, s, h, kv, l, d = 2, 8 if pos0 == 0 else 1, 4, 2, 32, 16
    q, k, v = _mk(b, s, h, kv, l, d, seed=pos0 + window)
    want = np.asarray(_windowed_oracle(q, k, v, pos0, window))
    got_xla = np.asarray(_gqa_xla(q, k, v, jnp.asarray(pos0), None, window=window))
    np.testing.assert_allclose(got_xla, want, atol=1e-5, rtol=1e-5)
    got_flash = np.asarray(
        flash_gqa_cache(
            q, k, v, jnp.asarray(pos0), None, q_blk=8, l_blk=16, window=window, interpret=True
        )
    )
    np.testing.assert_allclose(got_flash, want, atol=1e-5, rtol=1e-5)
    # The band must actually bite: full-causal on the same inputs differs.
    full = np.asarray(_gqa_xla(q, k, v, jnp.asarray(pos0), None))
    assert np.abs(full - want).max() > 1e-4


def test_dispatch_uses_xla_on_cpu():
    """On a CPU backend the dispatcher must take the XLA path (flash is
    TPU-only outside interpret mode) and still match the oracle."""
    q, k, v = _mk(2, 4, 4, 2, 32, 16, seed=11)
    got = np.asarray(gqa_cache_attention(q, k, v, jnp.asarray(2), None))
    want = np.asarray(_oracle(q, k, v, 2, None))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_flash_bf16_close_to_f32_oracle():
    """bf16 inputs (the production dtype): flash kernel accumulates in f32,
    so it should sit within bf16 rounding of the f32 oracle."""
    b, s, h, kv, l, d = 2, 8, 8, 2, 32, 64
    q, k, v = _mk(b, s, h, kv, l, d, seed=3)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    want = np.asarray(_oracle(q, k, v, 0, None))
    got = np.asarray(
        flash_gqa_cache(qb, kb, vb, jnp.asarray(0), None, q_blk=16, l_blk=16, interpret=True)
    ).astype(np.float32)
    np.testing.assert_allclose(got, want, atol=0.04, rtol=0.04)
