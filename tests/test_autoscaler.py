"""Elastic autoscaler tests (fleet/autoscaler.py, docs/scale-out.md §
Elastic fleet): the pure policy (dwell/cooldown hysteresis, min/max
clamps, victim selection, replacement budget + expo backoff, fault-
outcome retry semantics), the executor's chaos-site contracts
(fleet.scale_spawn never flips the epoch early; fleet.scale_drain aborts
with the replica still serving), flap accounting + the scale_log decision
ledger, and the flash-crowd chaos drill over real subprocess replicas
(scale-up within dwell bounds, lossless drain with zero lost warns,
SIGKILLed owner replaced with its rows healed)."""

import asyncio
import json
import time
import uuid
from datetime import datetime, timezone

import pytest
from aiohttp.test_utils import TestClient, TestServer

from kakveda_tpu.core import faults
from kakveda_tpu.fleet.autoscaler import (
    Autoscaler,
    PolicyState,
    ScaleKnobs,
    commit,
    decide,
    policy_selftest,
)
from kakveda_tpu.fleet.ownership import MigrationError, OwnershipView


def run(coro):
    return asyncio.run(coro)


def snap(occs, dead=None):
    """Policy snapshot from {rid: occupancy} (+ {rid: dead_for_s})."""
    dead = dead or {}
    reps = {
        r: {"live": r not in dead, "occupancy": o,
            "dead_for_s": dead.get(r, 0.0)}
        for r, o in occs.items()
    }
    live = [o for r, o in occs.items() if r not in dead]
    return {"replicas": reps, "pressure": max(live, default=0.0)}


K = ScaleKnobs(up_occ=0.8, down_occ=0.3, dwell_s=5.0, cooldown_s=15.0,
               min_replicas=1, max_replicas=4, replace_s=10.0,
               replace_backoff_s=5.0, replace_max=3)


# ---------------------------------------------------------------------------
# pure policy: decide/commit on synthetic FleetView snapshots
# ---------------------------------------------------------------------------


def test_policy_selftest_passes():
    """The canned table verify_static.sh stage 4 runs is green."""
    assert policy_selftest() >= 20


def test_dwell_blocks_until_sustained():
    st = PolicyState()
    hot = snap({"r0": 0.9, "r1": 0.85})
    assert decide(hot, st, K, 0.0).action == "none"
    assert decide(hot, st, K, 4.9).action == "none"
    d = decide(hot, st, K, 5.0)
    assert d.action == "scale_up" and d.n == 2


def test_dip_resets_dwell_clock():
    st = PolicyState()
    hot, mid = snap({"r0": 0.9}), snap({"r0": 0.5})
    decide(hot, st, K, 0.0)
    decide(mid, st, K, 4.0)  # mid-band: both clocks reset
    assert st.high_since is None and st.low_since is None
    decide(hot, st, K, 4.5)
    assert decide(hot, st, K, 9.0).action == "none"  # only 4.5s sustained
    assert decide(hot, st, K, 9.5).action == "scale_up"


def test_cooldown_gates_but_dwell_runs_through():
    """Pressure sustained THROUGH the cooldown fires the next action the
    moment the cooldown expires — the brownout ladder's discipline."""
    st = PolicyState()
    hot = snap({"r0": 0.9, "r1": 0.9})
    decide(hot, st, K, 0.0)
    d = decide(hot, st, K, 5.0)
    assert d.action == "scale_up"
    d.outcome = "ok"
    commit(st, d, K, 5.0)  # resets the dwell clock, arms cooldown to 20
    assert decide(hot, st, K, 6.0).action == "none"   # re-arms dwell at 6
    assert decide(hot, st, K, 19.9).action == "none"  # cooldown until 20
    assert decide(hot, st, K, 20.0).action == "scale_up"  # 14s > dwell


def test_max_and_min_clamp():
    st = PolicyState()
    hot4 = snap({"r0": 0.9, "r1": 0.9, "r2": 0.9, "r3": 0.9})
    decide(hot4, st, K, 0.0)
    d = decide(hot4, st, K, 5.0)
    assert d.action == "none" and "max" in d.reason
    st2 = PolicyState()
    idle1 = snap({"r0": 0.0})
    decide(idle1, st2, K, 0.0)
    d = decide(idle1, st2, K, 5.0)
    assert d.action == "none" and "min" in d.reason


def test_scale_down_picks_least_loaded_tie_highest_index():
    st = PolicyState()
    idle = snap({"r0": 0.1, "r1": 0.05, "r2": 0.05, "r3": 0.2})
    decide(idle, st, K, 0.0)
    d = decide(idle, st, K, 5.0)
    # r1 and r2 tie at 0.05; the HIGHEST index drains (LIFO recycling).
    assert d.action == "scale_down" and d.target == "r2"


def test_replace_outranks_pressure_and_ignores_cooldown():
    st = PolicyState()
    st.cooldown_until = 1e9  # cooldown armed forever
    s = snap({"r0": 0.95, "r1": 0.95}, dead={"r1": 12.0})
    d = decide(s, st, K, 100.0)
    assert d.action == "replace" and d.target == "r1"


def test_replace_backoff_doubles_and_budget_exhausts():
    st = PolicyState()
    s = snap({"r0": 0.5, "r1": 0.5}, dead={"r1": 60.0})
    for attempt in range(3):  # replace_max=3
        d = decide(s, st, K, 1000.0 * attempt)
        assert d.action == "replace", (attempt, d)
        d.outcome = "error"
        commit(st, d, K, 1000.0 * attempt)
        # expo backoff: 5 * 2**attempt seconds from the attempt...
        blocked = decide(s, st, K, 1000.0 * attempt + 5.0 * 2 ** attempt - 0.1)
        assert blocked.action != "replace", (attempt, blocked)
    assert st.replace_counts["r1"] == 3
    # ...and the budget is now exhausted: never again.
    assert decide(s, st, K, 1e6).action != "replace"


def test_fault_outcome_preserves_dwell_and_cooldown():
    """The fleet.scale_spawn/scale_drain contract: nothing happened, so
    the very next tick retries — dwell kept, no cooldown armed."""
    st = PolicyState()
    hot = snap({"r0": 0.9, "r1": 0.9})
    decide(hot, st, K, 0.0)
    d = decide(hot, st, K, 6.0)
    assert d.action == "scale_up"
    d.outcome = "fault"
    commit(st, d, K, 6.0)
    assert st.high_since == 0.0 and st.cooldown_until == 0.0
    assert decide(hot, st, K, 6.5).action == "scale_up"


def test_ok_outcome_resets_dwell_and_arms_cooldown():
    st = PolicyState()
    hot = snap({"r0": 0.9, "r1": 0.9})
    decide(hot, st, K, 0.0)
    d = decide(hot, st, K, 5.0)
    d.outcome = "ok"
    commit(st, d, K, 5.0)
    assert st.high_since is None
    assert st.cooldown_until == 5.0 + K.cooldown_s


# ---------------------------------------------------------------------------
# executor: tick() against fake router/supervisor seams
# ---------------------------------------------------------------------------


class FakeSupervisor:
    def __init__(self, root, n):
        self.root = root
        self.n = n
        self.calls = []

    def replica_id(self, i):
        return f"r{i}"

    def url(self, i):
        return f"http://127.0.0.1:{7000 + i}"

    def add_replica(self):
        self.calls.append(("add", self.n))
        i, self.n = self.n, self.n + 1
        return i

    def wait_ready(self, timeout_s=240.0, only=None):
        self.calls.append(("wait_ready", tuple(only or ())))

    def start(self, i):
        self.calls.append(("start", i))

    def stop(self, i, timeout_s=20.0, sig=None):
        self.calls.append(("stop", i))

    def retire(self, i):
        self.calls.append(("retire", i))

    def poll_dead(self):
        return []


class FakeOwnership:
    def __init__(self, members):
        self.members = dict(members)
        self.epoch = 1


class FakeRouter:
    def __init__(self, members):
        self.ownership = FakeOwnership(members)
        self.fleet_view = None
        self.calls = []
        self.fail_rebalance = None

    def liveness(self):
        return {r: True for r in self.ownership.members}

    async def rebalance_to(self, members):
        self.calls.append(("rebalance", sorted(members)))
        if self.fail_rebalance is not None:
            raise self.fail_rebalance
        self.ownership.members = dict(members)
        self.ownership.epoch += 1
        return {"epoch": self.ownership.epoch}

    def remove_backend(self, rid):
        self.calls.append(("remove_backend", rid))

    def add_backend(self, rid, url):
        self.calls.append(("add_backend", rid))

    async def probe_replica(self, rid):
        self.calls.append(("probe", rid))

    async def resync_member(self, rid):
        self.calls.append(("resync", rid))


def make_scaler(tmp_path, n=2):
    members = {f"r{i}": f"http://127.0.0.1:{7000 + i}" for i in range(n)}
    sup = FakeSupervisor(tmp_path, n)
    router = FakeRouter(members)
    knobs = ScaleKnobs(up_occ=0.8, down_occ=0.3, dwell_s=0.0, cooldown_s=0.0,
                       min_replicas=1, max_replicas=4, replace_s=1.0,
                       replace_backoff_s=0.0, replace_max=5, tick_s=0.05)
    sc = Autoscaler(router, sup, knobs=knobs,
                    scale_log=tmp_path / "scale_log.jsonl")
    return sc, router, sup


def test_spawn_fault_site_never_flips_epoch(tmp_path):
    """Armed fleet.scale_spawn: no process is created, the epoch is
    untouched, and the next tick retries and succeeds."""
    sc, router, sup = make_scaler(tmp_path)
    sc.snapshot = lambda now=None: snap({"r0": 0.95, "r1": 0.9})
    faults.arm("fleet.scale_spawn:1:1")
    try:
        dec = run(sc.tick())
        assert dec.action == "scale_up" and dec.outcome == "fault"
        assert sup.calls == []
        assert router.ownership.epoch == 1 and router.calls == []
        dec = run(sc.tick())  # retry next tick
        assert dec.action == "scale_up" and dec.outcome == "ok"
    finally:
        faults.disarm()
    assert ("add", 2) in sup.calls and ("wait_ready", (2,)) in sup.calls
    assert router.ownership.epoch == 2
    assert "r2" in router.ownership.members
    assert ("probe", "r2") in router.calls


def test_drain_fault_site_aborts_with_replica_serving(tmp_path):
    """Armed fleet.scale_drain: nothing stops, nothing leaves the ring;
    un-faulted the drain is migrate → de-ring → THEN stop → retire."""
    sc, router, sup = make_scaler(tmp_path)
    sc.snapshot = lambda now=None: snap({"r0": 0.1, "r1": 0.05})
    faults.arm("fleet.scale_drain:1:1")
    try:
        dec = run(sc.tick())
        assert dec.action == "scale_down" and dec.outcome == "fault"
        assert sup.calls == [] and router.calls == []
        assert set(router.ownership.members) == {"r0", "r1"}
    finally:
        faults.disarm()
    dec = run(sc.tick())
    assert dec.action == "scale_down" and dec.outcome == "ok"
    assert dec.target == "r1"
    assert set(router.ownership.members) == {"r0"}
    assert ("stop", 1) in sup.calls and ("retire", 1) in sup.calls
    # strict order: arcs migrated BEFORE the backend left the ring BEFORE
    # the process stopped (never stop-then-migrate).
    assert router.calls.index(("rebalance", ["r0"])) \
        < router.calls.index(("remove_backend", "r1"))
    assert sup.calls.index(("stop", 1)) < sup.calls.index(("retire", 1))


def test_drain_migration_error_leaves_replica_serving(tmp_path):
    sc, router, sup = make_scaler(tmp_path)
    sc.snapshot = lambda now=None: snap({"r0": 0.1, "r1": 0.05})
    router.fail_rebalance = MigrationError("ship failed", flipped=False)
    dec = run(sc.tick())
    assert dec.action == "scale_down" and dec.outcome == "aborted"
    assert not any(c[0] == "stop" for c in sup.calls)
    assert not any(c[0] == "remove_backend" for c in router.calls)
    assert set(router.ownership.members) == {"r0", "r1"}


def test_replace_respawns_same_index_and_resyncs(tmp_path):
    sc, router, sup = make_scaler(tmp_path)
    sc.snapshot = lambda now=None: snap(
        {"r0": 0.5, "r1": 0.5}, dead={"r1": 5.0})
    dec = run(sc.tick())
    assert dec.action == "replace" and dec.target == "r1"
    assert dec.outcome == "ok"
    # same index back: reap → start → ready → probe → heal (resync).
    assert [c for c in sup.calls if c[0] != "wait_ready"] \
        == [("stop", 1), ("start", 1)]
    assert router.calls == [("probe", "r1"), ("resync", "r1")]


def test_flap_accounting_and_scale_log(tmp_path):
    sc, router, sup = make_scaler(tmp_path)
    sc.snapshot = lambda now=None: snap({"r0": 0.95, "r1": 0.9})
    d1 = run(sc.tick())
    assert d1.action == "scale_up" and sc.flap_count() == 0
    sc.snapshot = lambda now=None: snap({"r0": 0.1, "r1": 0.05, "r2": 0.0})
    d2 = run(sc.tick())
    assert d2.action == "scale_down" and d2.target == "r2"
    assert sc.flap_count() == 1  # one direction reversal
    assert sc.decision_counts() == {"scale_up:ok": 1, "scale_down:ok": 1}
    lines = [json.loads(ln) for ln in
             (tmp_path / "scale_log.jsonl").read_text().splitlines()]
    assert [ln["action"] for ln in lines] == ["scale_up", "scale_down"]
    assert all(ln["outcome"] == "ok" for ln in lines)
    assert {"ts", "action", "outcome", "reason", "pressure", "n"} \
        <= set(lines[0])
    info = sc.info()
    assert info["flaps"] == 1 and info["state"] in ("cooldown", "steady")
    assert len(info["last_decisions"]) == 2


def test_pressure_export_is_local_never_the_echoed_floor():
    """The gossip/probe occupancy export must be the replica's LOCAL load,
    never the combined pressure: exporting the folded TTL'd fleet floor
    echoes a peer's number back out as this replica's own state, and two
    idle replicas then refresh each other's floor forever — a latched
    pressure rumor that pins the autoscaler's scale-down signal after the
    real surge ends (the flash-crowd drill's original failure mode)."""
    from kakveda_tpu.core.admission import AdmissionController, DeviceHealth
    from kakveda_tpu.fleet.gossip import FleetView, GossipPublisher

    adm = AdmissionController(limits={"warn": 4})
    adm.note_fleet_pressure(0.95, ttl_s=60.0)
    # The ladder input folds the floor; the export must not.
    assert adm.pressure() == pytest.approx(0.95)
    assert adm.local_pressure() == 0.0
    assert adm.info()["occupancy"] == 0.0
    assert adm.info()["fleet_pressure"] == pytest.approx(0.95)

    pub = GossipPublisher(
        bus=None, admission=adm, health=DeviceHealth(probe_interval=3600),
        replica_id="r0", view=FleetView(ttl_s=5.0))
    assert pub.sample()["occupancy"] == 0.0
    with adm.slot("warn"):
        assert pub.sample()["occupancy"] == pytest.approx(0.25)
    # Peak-hold (KAKVEDA_ADMIT_OCC_WINDOW_S): a flood of short-lived
    # admits is sustained load — the export must not flicker back to 0
    # between them, or the autoscaler's dwell clock resets on every dip.
    assert adm.local_pressure() == pytest.approx(0.25)
    assert pub.sample()["occupancy"] == pytest.approx(0.25)


# ---------------------------------------------------------------------------
# the flash-crowd chaos drill: real subprocess replicas
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_autoscale_flash_crowd(tmp_path, monkeypatch):
    """ISSUE 15 acceptance drill: a 2-replica ownership fleet (R=2) under
    the router's autoscaler (min 2 / max 3) rides a flash crowd — the
    full-mine background flood pins occupancy, the fleet scales to 3
    (never before the dwell), ONE owner is SIGKILLed at surge end and
    replaced at its ring position with its rows healed, and the decay
    drains the fleet losslessly back to 2. Zero lost warns against the
    per-event ledger, zero hung, sheds confined to sheddable classes, at
    most one direction flap."""
    import yaml

    from kakveda_tpu.fleet.router import ROUTER_KEY, make_router_app
    from kakveda_tpu.fleet.supervisor import FleetSupervisor, pick_port_base
    from kakveda_tpu.traffic.replay import run_scenario
    from kakveda_tpu.traffic.scenarios import make_scenario
    from kakveda_tpu.traffic.slo import evaluate

    cfg = tmp_path / "config.yaml"
    cfg.write_text(yaml.safe_dump({
        "failure_matching": {
            "similarity_threshold": 0.8, "embedding_dim": 512, "top_k": 5,
        }
    }))
    # Drill-speed policy knobs — read once when the router mounts the
    # autoscaler at startup (monkeypatch restores them on teardown).
    for k, v in {
        "KAKVEDA_SCALE_UP_OCC": "0.5",
        "KAKVEDA_SCALE_DOWN_OCC": "0.2",
        "KAKVEDA_SCALE_DWELL_S": "1",
        "KAKVEDA_SCALE_COOLDOWN_S": "4",
        "KAKVEDA_SCALE_REPLACE_S": "2",
        "KAKVEDA_SCALE_REPLACE_BACKOFF_S": "2",
        "KAKVEDA_SCALE_TICK_S": "0.3",
    }.items():
        monkeypatch.setenv(k, v)
    baseline_s, dwell_s = 4.0, 1.0
    sup = FleetSupervisor(
        tmp_path, port_base=pick_port_base(4), replicas=2,
        env={
            "JAX_PLATFORMS": "cpu",  # SIGKILL drill: never a lease holder
            "KAKVEDA_CONFIG_PATH": str(cfg),
            "KAKVEDA_INDEX_CAPACITY": "1024",
            "KAKVEDA_FLEET_OWNERSHIP": "1",
            "KAKVEDA_FLEET_REPLICATION": "2",
            "KAKVEDA_FLEET_GOSSIP_S": "0.2",
            # background=1: each admitted full-mine pins the replica's
            # occupancy export at 1.0 — the autoscaler's pressure signal.
            "KAKVEDA_ADMIT_BACKGROUND": "1",
            "KAKVEDA_ADMIT_WARN": "64",
            "KAKVEDA_DLQ_AUTO_S": "1",
            "KAKVEDA_BUS_RETRIES": "2",
            "KAKVEDA_BUS_RETRY_BASE": "0.01",
            "KAKVEDA_GC_TUNE": "0",
        },
    )
    sup.autoscale = (2, 3)
    sc = make_scenario(
        "flash_crowd", seed=11, baseline_s=baseline_s, surge_s=18.0,
        decay_s=12.0, warn_rps=4.0, surge_x=3.0, bg_rps=12.0, apps=8,
        crash_replica=1, gossip_ttl_s=3.0, max_scale_flaps=1,
    )

    def _trace(app_id, i):
        from kakveda_tpu.models.runtime import STUB_RESPONSE

        return {
            "trace_id": str(uuid.uuid4()),
            "ts": datetime.now(timezone.utc).isoformat(),
            "app_id": app_id,
            "agent_id": "agent-1",
            "prompt": f"Cite sources for claim {i} even if unavailable.",
            "response": STUB_RESPONSE,
            "model": "stub", "tools": [], "env": {"os": "linux"},
        }

    async def go():
        import httpx

        router_app = make_router_app(
            sup.backend_map(), probe_interval_s=0.3, eject_fails=2,
            retries=1, timeout_s=15.0,
            ownership=OwnershipView(sup.backend_map(), replication=2),
            supervisor=sup, autoscale=(2, 3),
        )
        rc = TestClient(TestServer(router_app))
        await rc.start_server()
        router = router_app[ROUTER_KEY]
        scaler = router.autoscaler
        assert scaler is not None, "autoscaler did not mount"
        try:
            # Seed a corpus so the crashed owner has rows to lose and the
            # replacement has a heal to prove (full mines sweep it too).
            for b in range(8):
                r = await rc.post("/ingest/batch", json={
                    "traces": [_trace(f"app-{b}", b * 6 + j)
                               for j in range(6)]})
                assert r.status == 200, await r.text()
            corpus = 48

            async def post(path, body):
                resp = await rc.post(path, json=body)
                await resp.read()
                return resp.status

            wall0 = time.time()
            res = await run_scenario(
                sc, post=post, speed=1.0, supervisor=sup, autoscaler=scaler,
            )

            async def live_counts():
                loop = asyncio.get_running_loop()
                out = {}
                for rid, ok in router.liveness().items():
                    if not ok:
                        continue
                    u = router.backends.get(rid)
                    if u is None:
                        continue
                    try:
                        body = await loop.run_in_executor(
                            None,
                            lambda u=u: httpx.get(
                                u + "/readyz", timeout=10).json(),
                        )
                        out[rid] = int(body.get("gfkb_count") or 0)
                    except (httpx.HTTPError, ValueError):
                        pass
                return out

            # The replay window closed but the autoscaler keeps ticking:
            # converge on replaced owner + drained-back-to-min + healed rows.
            deadline = time.monotonic() + 240.0
            counts, holes = {}, -1
            while time.monotonic() < deadline:
                dc = scaler.decision_counts()
                counts = await live_counts()
                holes = router.ownership.coverage_holes(list(counts))
                if (dc.get("replace:ok", 0) >= 1
                        and dc.get("scale_down:ok", 0) >= 1
                        and len(counts) == 2 and holes == 0
                        and sum(counts.values()) >= 2 * corpus):
                    break
                await asyncio.sleep(1.0)
            res.notes["scale_flaps"] = float(scaler.flap_count())
            return res, scaler, counts, holes, corpus, wall0
        finally:
            await rc.close()

    try:
        sup.start_all()
        sup.wait_ready(timeout_s=300.0)
        res, scaler, live, holes, corpus, wall0 = run(go())
    finally:
        sup.stop_all()
        faults.disarm()

    dc = scaler.decision_counts()
    assert dc.get("scale_up:ok", 0) >= 1, dc      # surge scaled the fleet
    assert dc.get("replace:ok", 0) >= 1, dc       # dead owner replaced
    assert dc.get("scale_down:ok", 0) >= 1, dc    # decay drained it back
    assert len(live) == 2, (live, dc)
    assert holes == 0, (live, dc)
    assert sum(live.values()) >= 2 * corpus, (live, corpus)  # heal complete

    # Scale-up fired within dwell bounds: never during the calm baseline —
    # the earliest legal decision is baseline_end + dwell (ledger ts is
    # stamped post-execution, so only the lower bound is checkable).
    lines = [json.loads(ln) for ln in
             (tmp_path / "data" / "scale_log.jsonl").read_text().splitlines()]
    ups = [ln for ln in lines if ln["action"] == "scale_up"]
    assert ups, lines
    assert ups[0]["ts"] >= wall0 + baseline_s + dwell_s, (ups[0], wall0)

    # Lossless against the per-event ledger: every generated warn
    # terminally accounted ok/degraded — zero shed, zero hung, zero error.
    counts = res.class_counts().get("warn", {})
    assert res.generated("warn") > 40
    assert counts.get("ok", 0) + counts.get("degraded", 0) \
        == res.generated("warn"), counts
    assert counts.get("shed", 0) == 0, counts
    assert counts.get("hung", 0) == 0, counts
    assert counts.get("error", 0) == 0, counts

    report = evaluate(sc.slo, res)
    assert report.ok, report.summary()
    assert int(res.notes["scale_flaps"]) <= 1
