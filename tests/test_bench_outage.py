"""Bench outage contract: a down chip must still yield ONE parseable JSON
line carrying the outage flag plus any previously measured partial metrics
(VERDICT r4 weak-4 — BENCH_r03/r04 recorded parsed=null on rc=1).

Runs bench.py in a subprocess with JAX_PLATFORMS=nonexistent so backend
init raises immediately instead of entering the remote claim loop.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(tmp_path, extra_env):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update(
        {
            "PYTHONPATH": "/root/.axon_site:" + REPO,
            "KAKVEDA_BENCH_INIT_RETRIES": "0",
            "KAKVEDA_BENCH_INIT_TIMEOUT": "60",
            "KAKVEDA_BENCH_PARTIAL": str(tmp_path / "partial.json"),
        }
    )
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True,
        text=True,
        env=env,
        timeout=180,
    )


def test_outage_emits_machine_readable_json(tmp_path):
    partial = tmp_path / "partial.json"
    prior = {
        "backend": "axon",
        "ts": time.time(),
        "done": {"_bench_warn": {"metric": "warn_p50_ms", "value": 0.2}},
    }
    partial.write_text(json.dumps(prior))
    proc = _run_bench(tmp_path, {"JAX_PLATFORMS": "nonexistent"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["chip_unavailable"] is True
    assert out["metric"] == "chip_unavailable"
    assert "nonexistent" in out["error"]
    # Previously measured metrics ride along so the driver artifact keeps them.
    assert out["partial"]["done"]["_bench_warn"]["value"] == 0.2


def test_outage_rc_env_override(tmp_path):
    proc = _run_bench(
        tmp_path,
        {"JAX_PLATFORMS": "nonexistent", "KAKVEDA_BENCH_OUTAGE_RC": "1"},
    )
    assert proc.returncode == 1
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["chip_unavailable"] is True


def test_resume_partial_policy(tmp_path, monkeypatch):
    """Resume defaults ON but refuses stale or cross-backend partials, so a
    long-dead partial can't masquerade as a fresh sweep (ADVICE r4 low-4)."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    p = tmp_path / "partial.json"
    fresh = {
        "backend": "cpu",
        "ts": time.time() - 60,
        "done": {"_bench_warn": {"value": 0.2}},
    }
    p.write_text(json.dumps(fresh))
    assert bench.load_resumable_partial(str(p), "cpu") == fresh["done"]
    # Wrong backend: ignored.
    assert bench.load_resumable_partial(str(p), "tpu") == {}
    # Too old: ignored.
    stale = dict(fresh, ts=time.time() - 7 * 3600)
    p.write_text(json.dumps(stale))
    assert bench.load_resumable_partial(str(p), "cpu") == {}
    # Resume disabled: ignored even when fresh.
    p.write_text(json.dumps(fresh))
    monkeypatch.setenv("KAKVEDA_BENCH_RESUME", "0")
    assert bench.load_resumable_partial(str(p), "cpu") == {}
    # Missing file: empty, no error.
    monkeypatch.delenv("KAKVEDA_BENCH_RESUME")
    assert bench.load_resumable_partial(str(tmp_path / "nope.json"), "cpu") == {}
    # Complete partial (a finished sweep): never resumed from — a live-chip
    # run must re-measure fresh — but it stays on disk as outage evidence.
    done_sweep = dict(fresh, complete=True)
    p.write_text(json.dumps(done_sweep))
    assert bench.load_resumable_partial(str(p), "cpu") == {}


def test_outage_carries_complete_sweep_evidence(tmp_path):
    """A chip-down run that follows a fully successful sweep must surface the
    finished sweep's numbers in its chip_unavailable line (the round-4
    failure mode: success → partial deleted → later outage had nothing)."""
    partial = tmp_path / "partial.json"
    partial.write_text(
        json.dumps(
            {
                "backend": "axon",
                "ts": time.time(),
                "done": {"_bench_warn": {"metric": "warn_p50_ms", "value": 0.21}},
                "complete": True,
            }
        )
    )
    proc = _run_bench(tmp_path, {"JAX_PLATFORMS": "nonexistent"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["chip_unavailable"] is True
    assert out["partial"]["complete"] is True
    assert out["partial"]["done"]["_bench_warn"]["value"] == 0.21
