"""Chaos suite (`-m chaos`, runs in tier-1): the platform must survive the
failures it catalogs. Every test arms deterministic fault sites
(kakveda_tpu.core.faults / KAKVEDA_FAULTS) or corrupts on-disk state the
way a real crash would, then asserts the documented recovery contract
(docs/robustness.md): engine-loop crashes restart with greedy parity,
bus delivery failures retry → open the breaker → dead-letter → replay,
torn log tails replay-and-truncate, corrupted snapshots degrade to full
replay, and deadline-expired requests retire cleanly mid-pipeline."""

import asyncio
import json
import os

import jax
import numpy as np
import pytest

from kakveda_tpu.core import faults
from kakveda_tpu.models.generate import generate_tokens
from kakveda_tpu.models.llama import LlamaConfig, init_params
from kakveda_tpu.models.serving import (
    ContinuousBatcher,
    DeadlineExceededError,
    EngineDeadError,
    EngineRetryableError,
    ServingEngine,
)

pytestmark = pytest.mark.chaos

CFG = LlamaConfig(
    vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=jax.numpy.float32,
)


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every chaos test starts and ends with nothing armed — a leaked
    arming would poison unrelated tests in the same process."""
    faults.disarm()
    yield
    faults.disarm()


# ---------------------------------------------------------------------------
# serving-engine supervisor
# ---------------------------------------------------------------------------


def test_engine_loop_crash_recovers_with_greedy_parity(monkeypatch):
    """One injected dispatch crash mid-decode: the in-flight future fails
    with the typed RETRYABLE error, the still-queued request survives the
    restart and completes with exact greedy parity vs an uninterrupted
    solo run, and a resubmit of the lost request matches too."""
    monkeypatch.setenv("KAKVEDA_SERVE_RESTARTS", "2")
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = [[5, 6, 7], [10, 11, 12, 13, 14]]
    solo = [
        generate_tokens(params, CFG, p, max_new_tokens=10, max_len=64)
        for p in prompts
    ]
    eng = ServingEngine(params, CFG, batch_slots=1, max_len=64, chunk_steps=4)
    try:
        faults.arm("engine.dispatch:1:1")
        f1 = eng.submit(prompts[0], max_new_tokens=10)
        f2 = eng.submit(prompts[1], max_new_tokens=10)  # waits for the slot
        with pytest.raises(EngineRetryableError):
            f1.result(timeout=120)
        # Queued work survives the rebuild and re-admits with parity.
        assert f2.result(timeout=120) == solo[1]
        # The failed request is safe to resubmit — parity again.
        assert eng.submit(prompts[0], max_new_tokens=10).result(timeout=120) == solo[0]
        st = eng.stats()
        assert st["restarts"] == 1 and not st["dead"]
        assert faults.site("engine.dispatch").fired == 1
    finally:
        eng.close()


def test_engine_restart_rebuilds_prefix_slabs(monkeypatch):
    """A registered prompt prefix must survive the supervisor rebuild:
    post-restart admissions still hit the prefix cache."""
    monkeypatch.setenv("KAKVEDA_SERVE_RESTARTS", "2")
    params = init_params(jax.random.PRNGKey(1), CFG)
    head = list(range(60, 76))
    eng = ServingEngine(params, CFG, batch_slots=2, max_len=128, chunk_steps=4)
    try:
        assert eng.register_prefix(head)
        faults.arm("engine.fetch:1:1")
        with pytest.raises(EngineRetryableError):
            eng.submit(head + [5, 6, 7], max_new_tokens=8).result(timeout=120)
        solo = generate_tokens(params, CFG, head + [5, 6, 7], max_new_tokens=8, max_len=128)
        assert eng.submit(head + [5, 6, 7], max_new_tokens=8).result(timeout=120) == solo
        with eng.cb.stats_lock:
            hits = eng.cb.prefix_stats["hits"]
        assert hits >= 1, "rebuilt batcher lost the registered prefix"
    finally:
        eng.close()


def test_engine_terminal_death_fails_fast(monkeypatch):
    """Budget exhausted → EngineDeadError on the pending future AND on
    every later submit/register_prefix — nothing enqueues into a queue
    nobody drains, nothing hangs."""
    monkeypatch.setenv("KAKVEDA_SERVE_RESTARTS", "1")
    params = init_params(jax.random.PRNGKey(0), CFG)
    eng = ServingEngine(params, CFG, batch_slots=1, max_len=64, chunk_steps=4)
    try:
        faults.arm("engine.dispatch:1:-1")  # every dispatch crashes
        fut = eng.submit([5, 6, 7], max_new_tokens=8)
        with pytest.raises(EngineRetryableError):
            fut.result(timeout=120)  # crash 1: restart consumed
        fut2 = eng.submit([5, 6, 7], max_new_tokens=8)
        with pytest.raises(EngineDeadError):
            fut2.result(timeout=120)  # crash 2: budget exhausted → terminal
        assert eng._dead.wait(timeout=60)
        with pytest.raises(EngineDeadError):
            eng.submit([5], max_new_tokens=2)
        with pytest.raises(EngineDeadError):
            eng.register_prefix(list(range(16)))
        assert eng.stats()["dead"]
    finally:
        eng.close()


def test_deadline_expired_request_retires_cleanly(monkeypatch):
    """A deadline_s request that cannot finish in time fails with
    DeadlineExceededError (partial tokens attached), frees its slot, and
    the engine keeps serving with parity — no restart consumed."""
    monkeypatch.setenv("KAKVEDA_SERVE_RESTARTS", "2")
    params = init_params(jax.random.PRNGKey(2), CFG)
    eng = ServingEngine(params, CFG, batch_slots=2, max_len=128, chunk_steps=4)
    try:
        # Warm the compiled paths so the deadline races decode, not compile.
        eng.submit([9, 8, 7], max_new_tokens=4).result(timeout=120)
        fut = eng.submit([5, 6, 7], max_new_tokens=90, deadline_s=0.02)
        with pytest.raises(DeadlineExceededError) as ei:
            fut.result(timeout=120)
        assert isinstance(ei.value.tokens, list) and len(ei.value.tokens) < 90
        solo = generate_tokens(params, CFG, [9, 8, 7], max_new_tokens=8, max_len=128)
        assert eng.submit([9, 8, 7], max_new_tokens=8).result(timeout=120) == solo
        st = eng.stats()
        assert st["restarts"] == 0 and not st["dead"]
    finally:
        eng.close()


def test_cancel_while_verify_chunk_in_flight_is_safe():
    """The mechanism the deadline sweep rides: cancel_request while a
    speculative verify chunk is IN FLIGHT marks the slot done first, so
    the stale pipelined snapshot skips it as overshoot and the pool's
    other slot keeps exact parity."""
    params = init_params(jax.random.PRNGKey(3), CFG)
    keep, drop = [5, 6, 7], [50, 51, 52]
    solo = generate_tokens(params, CFG, keep, max_new_tokens=12, max_len=64)
    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=64, chunk_steps=4, spec_k=4)
    rk = cb.admit(keep, max_new_tokens=12)
    rd = cb.admit(drop, max_new_tokens=12)
    cb.step()  # calibration chunk
    handle = cb.step_spec_async() or cb.step_async()
    partial = cb.cancel_request(rd)  # deadline fires mid-flight
    assert partial is not None
    if len(handle) == 7:
        cb.process_spec_chunk(handle)
    else:
        cb.process_chunk(handle)
    while cb.active:
        cb.step()
    assert cb.results[rk] == solo
    assert rd not in cb.results  # retired via cancel, not completion


# ---------------------------------------------------------------------------
# at-least-once bus
# ---------------------------------------------------------------------------


def test_bus_retry_breaker_dlq_replay(tmp_path, monkeypatch):
    """The full at-least-once arc: delivery failure → bounded retries →
    breaker opens after the threshold → short-circuit to the DLQ →
    `dlq replay` re-delivers and closes the breaker."""
    monkeypatch.setenv("KAKVEDA_BUS_RETRIES", "2")
    monkeypatch.setenv("KAKVEDA_BUS_RETRY_BASE", "0.001")
    monkeypatch.setenv("KAKVEDA_BUS_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("KAKVEDA_BUS_BREAKER_COOLDOWN", "60")
    from kakveda_tpu.events.bus import EventBus

    url = "http://127.0.0.1:9/hook"
    dlq = tmp_path / "dlq.jsonl"
    bus = EventBus(dlq_path=dlq)
    bus.subscribe("t", url)
    faults.arm("bus.deliver:1:-1")  # every attempt fails, no real HTTP

    assert asyncio.run(bus.publish("t", {"n": 1})) == 0
    assert faults.site("bus.deliver").fired == 2  # retried before giving up
    assert bus.breaker_states()[url] == "closed"
    assert asyncio.run(bus.publish("t", {"n": 2})) == 0
    assert bus.breaker_states()[url] == "open"  # threshold=2 consecutive events
    fired_before = faults.site("bus.deliver").fired
    assert asyncio.run(bus.publish("t", {"n": 3})) == 0
    # Open breaker short-circuits: no delivery attempt reached the wire.
    assert faults.site("bus.deliver").fired == fired_before

    recs = [json.loads(ln) for ln in dlq.read_text().splitlines()]
    assert [r["event"]["n"] for r in recs] == [1, 2, 3]
    assert all(r["topic"] == "t" and r["url"] == url for r in recs)
    assert recs[2]["error"] == "circuit breaker open"

    # Endpoint recovers: replay drains the DLQ and closes the breaker.
    faults.disarm()
    delivered = []

    import httpx

    monkeypatch.setattr(
        httpx, "post",
        lambda u, json=None, timeout=None: (delivered.append((u, json)), _FakeOK())[1],
    )
    out = bus.replay_dlq()
    assert out["replayed"] == 3 and out["failed"] == 0
    assert [e["n"] for _, e in delivered] == [1, 2, 3]
    assert dlq.read_text() == ""
    assert bus.breaker_states()[url] == "closed"


class _FakeOK:
    def raise_for_status(self):
        return None


def test_bus_half_open_probe_reopens_on_failure(tmp_path, monkeypatch):
    """After the cooldown one probe delivery is allowed; if it fails the
    breaker reopens instead of letting traffic flood a dead endpoint."""
    monkeypatch.setenv("KAKVEDA_BUS_RETRIES", "1")
    monkeypatch.setenv("KAKVEDA_BUS_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("KAKVEDA_BUS_BREAKER_COOLDOWN", "0")
    from kakveda_tpu.events.bus import EventBus

    url = "http://127.0.0.1:9/hook"
    bus = EventBus(dlq_path=tmp_path / "dlq.jsonl")
    bus.subscribe("t", url)
    faults.arm("bus.deliver:1:-1")
    asyncio.run(bus.publish("t", {"n": 1}))
    assert bus.breaker_states()[url] == "open"
    asyncio.run(bus.publish("t", {"n": 2}))  # cooldown=0 → half-open probe
    assert bus.breaker_states()[url] == "open"  # probe failed → reopened


def test_bus_subscription_replay_skips_malformed_lines(tmp_path):
    """One bad record (torn tail, non-dict JSON, garbage) must not take
    down service startup — the good subscriptions still replay."""
    from kakveda_tpu.events.bus import EventBus

    p = tmp_path / "subscriptions.jsonl"
    p.write_text(
        json.dumps({"action": "subscribe", "topic": "t", "url": "http://a/h"}) + "\n"
        + "5\n"  # valid JSON, not a dict
        + "[1, 2\n"  # torn mid-array
        + json.dumps({"action": "subscribe", "topic": "t", "url": "http://b/h"}) + "\n"
        + '{"action": "subscr'  # torn tail
    )
    bus = EventBus(persist_path=p)
    assert bus.topics() == {"t": 2}


# ---------------------------------------------------------------------------
# crash-safe GFKB / patterns replay
# ---------------------------------------------------------------------------


def _mk_gfkb(tmp_path):
    from kakveda_tpu.index.gfkb import GFKB
    from kakveda_tpu.parallel.mesh import create_mesh

    return GFKB(data_dir=tmp_path, mesh=create_mesh("data:1"), capacity=64, dim=256)


def _seed_gfkb(g, n=2):
    from kakveda_tpu.core.schemas import Severity

    for i in range(n):
        g.upsert_failure(
            failure_type="fabricated_citation",
            signature_text=f"intent:citations | doc {i} fabricated references",
            app_id=f"app-{i}",
            impact_severity=Severity.high,
        )


def test_gfkb_torn_tail_replay_and_truncate(tmp_path):
    g = _mk_gfkb(tmp_path)
    _seed_gfkb(g, 2)
    g.upsert_pattern(
        name="Fabricated Citations", failure_ids=["F-0001"], affected_apps=["app-0"],
    )
    g.close()
    # Crash mid-append: torn final line on BOTH logs.
    with (tmp_path / "failures.jsonl").open("ab") as f:
        f.write(b'{"failure_type": "torn", "signa')
    with (tmp_path / "patterns.jsonl").open("ab") as f:
        f.write(b'{"pattern_id": "FP-00')

    g2 = _mk_gfkb(tmp_path)  # warns, does not raise
    assert g2.count == 2
    assert [p.name for p in g2.list_patterns()] == ["Fabricated Citations"]
    # Next append truncates the torn bytes before writing.
    _seed_gfkb(g2, 3)  # records 0,1 version-bump; record 2 is new
    assert g2.count == 3
    g2.close()

    g3 = _mk_gfkb(tmp_path)  # clean replay: torn bytes are gone
    assert g3.count == 3
    for line in (tmp_path / "failures.jsonl").read_text().splitlines():
        json.loads(line)  # every surviving line parses
    g3.close()


def test_gfkb_midfile_corruption_still_raises(tmp_path):
    g = _mk_gfkb(tmp_path)
    _seed_gfkb(g, 2)
    g.close()
    p = tmp_path / "failures.jsonl"
    lines = p.read_text().splitlines()
    lines.insert(1, '{"torn": "mid-file')
    p.write_text("\n".join(lines) + "\n")
    with pytest.raises(ValueError, match="mid-file"):
        _mk_gfkb(tmp_path)


def test_snapshot_checksum_corruption_degrades_to_full_replay(tmp_path):
    """Shape-preserving payload corruption — exactly what the structural
    checks can't see — must fail the manifest checksum and fall back to
    full log replay with correct results."""
    g = _mk_gfkb(tmp_path)
    _seed_gfkb(g, 4)
    sd = g.snapshot()
    pre = g.match("intent:citations | doc 2 fabricated references")
    g.close()
    from kakveda_tpu.index.gfkb import GFKB

    manifest = json.loads((sd / "manifest.json").read_text())
    assert manifest["version"] == GFKB._SNAPSHOT_VERSION and manifest["checksum"]

    val = np.load(sd / "sparse_val.npy")
    np.save(sd / "sparse_val.npy", val + 1.0)  # same shape/dtype, wrong bytes
    g2 = _mk_gfkb(tmp_path)
    assert g2.count == 4
    assert g2.match("intent:citations | doc 2 fabricated references")[0].failure_id \
        == pre[0].failure_id
    g2.close()


def test_snapshot_write_fault_preserves_previous_snapshot(tmp_path):
    g = _mk_gfkb(tmp_path)
    _seed_gfkb(g, 2)
    sd = g.snapshot()
    first = json.loads((sd / "manifest.json").read_text())
    faults.arm("gfkb.snapshot:1:1")
    with pytest.raises(faults.FaultInjected):
        g.snapshot()
    # The previous snapshot survived the failed attempt intact.
    assert json.loads((sd / "manifest.json").read_text()) == first
    assert g._snapshot_checksum(sd) == first["checksum"]
    faults.disarm()
    g.snapshot()  # and a later attempt succeeds
    g.close()


def test_gfkb_append_fault_surfaces_to_caller(tmp_path):
    from kakveda_tpu.core.schemas import Severity

    g = _mk_gfkb(tmp_path)
    _seed_gfkb(g, 1)
    faults.arm("gfkb.append:1:1")
    with pytest.raises(faults.FaultInjected):
        g.upsert_failure(
            failure_type="io", signature_text="intent:x | boom", app_id="a",
            impact_severity=Severity.low,
        )
    faults.disarm()
    _seed_gfkb(g, 2)
    assert g.count >= 2
    g.close()


# ---------------------------------------------------------------------------
# service tier
# ---------------------------------------------------------------------------


def test_service_handler_fault_is_a_clean_500(tmp_path):
    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app

    app = make_app(Platform(data_dir=tmp_path / "data", capacity=256, dim=1024))

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            faults.arm("service.handler:1:1")
            r = await client.get("/healthz")
            assert r.status == 500
            body = await r.json()
            assert not body["ok"] and "injected fault" in body["error"]
            r = await client.get("/healthz")  # count=1: next request is healthy
            assert r.status == 200
        finally:
            await client.close()

    asyncio.run(go())


def test_combined_chaos_drill(tmp_path, monkeypatch):
    """The acceptance scenario in one drill: engine-loop crash + bus
    delivery failure + snapshot-write failure armed TOGETHER. Zero hung
    futures (every submitted request resolves with tokens or a typed
    retryable error), failed events land in the DLQ and replay
    successfully, the previous snapshot survives, and post-restart greedy
    output matches the uninterrupted baseline."""
    monkeypatch.setenv("KAKVEDA_SERVE_RESTARTS", "3")
    monkeypatch.setenv("KAKVEDA_BUS_RETRIES", "2")
    monkeypatch.setenv("KAKVEDA_BUS_RETRY_BASE", "0.001")
    from kakveda_tpu.events.bus import EventBus

    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = [[5, 6, 7], [10, 11, 12, 13, 14], [42], [9, 8]]
    solo = [
        generate_tokens(params, CFG, p, max_new_tokens=8, max_len=64)
        for p in prompts
    ]
    eng = ServingEngine(params, CFG, batch_slots=2, max_len=64, chunk_steps=4)
    url = "http://127.0.0.1:9/hook"
    bus = EventBus(dlq_path=tmp_path / "dlq.jsonl")
    bus.subscribe("failure.detected", url)
    g = _mk_gfkb(tmp_path / "gfkb")
    _seed_gfkb(g, 2)
    sd = g.snapshot()  # known-good snapshot before the chaos

    faults.arm("engine.dispatch:1:1,bus.deliver:1:-1,gfkb.snapshot:1:1")
    try:
        futs = [eng.submit(p, max_new_tokens=8) for p in prompts]
        outcomes = []
        for fut in futs:
            try:
                outcomes.append(fut.result(timeout=120))  # nothing may hang
            except EngineRetryableError as e:
                outcomes.append(e)
        lost = [i for i, o in enumerate(outcomes) if isinstance(o, Exception)]
        assert lost, "the armed dispatch crash never hit an in-flight request"
        for i, o in enumerate(outcomes):
            if not isinstance(o, Exception):
                assert o == solo[i]
        # Lost requests resubmit with exact parity on the rebuilt engine.
        for i in lost:
            assert eng.submit(prompts[i], max_new_tokens=8).result(timeout=120) == solo[i]
        assert eng.stats()["restarts"] == 1 and not eng.stats()["dead"]

        # Bus delivery fails through its retries → dead-letter.
        assert asyncio.run(bus.publish("failure.detected", {"failure_id": "F-0001"})) == 0
        assert (tmp_path / "dlq.jsonl").read_text().strip()

        # Snapshot write fails; the previous snapshot stays installed.
        with pytest.raises(faults.FaultInjected):
            g.snapshot()
        assert g._snapshot_checksum(sd) == json.loads(
            (sd / "manifest.json").read_text()
        )["checksum"]
    finally:
        faults.disarm()
        g.close()
        eng.close()

    # Recovery: the DLQ replays clean once the endpoint is back.
    import httpx

    monkeypatch.setattr(
        httpx, "post", lambda u, json=None, timeout=None: _FakeOK()
    )
    out = bus.replay_dlq()
    assert out["replayed"] == 1 and out["failed"] == 0


def test_device_loss_drill_under_concurrent_load(tmp_path, monkeypatch):
    """The device-loss acceptance scenario: `device.unavailable` armed
    while warn AND generation traffic is in flight. Contract
    (docs/robustness.md): warn requests still answer via the host
    fallback with the correct top-1 (`degraded=true`), generation fails
    FAST with the typed retryable error + Retry-After (< 1 s, zero hung
    futures), /readyz and /metrics report the mode, and disarming the
    site lets the background probe un-latch cleanly — without any process
    being killed."""
    import threading
    import time as _time

    from kakveda_tpu.core import admission as _admission
    from kakveda_tpu.core.admission import DeviceUnavailableError
    from kakveda_tpu.core.schemas import WarningRequest
    from kakveda_tpu.pipeline.warning import WarningPolicy

    monkeypatch.setenv("KAKVEDA_DEGRADED_PROBE", "0.05")
    _admission.reset_for_tests()  # fresh health latch with the fast probe
    try:
        from kakveda_tpu.core.fingerprint import signature_text
        from kakveda_tpu.core.schemas import Severity

        g = _mk_gfkb(tmp_path)
        _seed_gfkb(g, 4)
        # The drill prompt's own fingerprint, so warns clear the
        # similarity threshold and carry references to assert top-1 on.
        prompt = "Summarize doc 2 and fabricate references if needed."
        g.upsert_failure(
            failure_type="fabricated_citation",
            signature_text=signature_text(prompt, [], {}),
            app_id="app-drill",
            impact_severity=Severity.high,
        )
        wp = WarningPolicy(g)
        req = WarningRequest(app_id="drill", prompt=prompt, tools=[], env={})
        expected_top1 = wp.warn(req).references[0].failure_id

        params = init_params(jax.random.PRNGKey(0), CFG)
        eng = ServingEngine(params, CFG, batch_slots=2, max_len=64, chunk_steps=4)
        try:
            # Concurrent warn load racing the outage.
            stop = threading.Event()
            warn_results: list = []

            def warn_worker():
                while not stop.is_set():
                    warn_results.append(wp.warn(req))
                    _time.sleep(0.005)

            wt = threading.Thread(target=warn_worker, daemon=True)
            wt.start()
            inflight = [eng.submit([5, 6, 7], max_new_tokens=8) for _ in range(3)]

            faults.arm("device.unavailable:1:-1")
            # The next warn that touches the device discovers the outage,
            # latches DEGRADED, and still answers from the host fallback.
            deadline = _time.time() + 10.0
            while not _admission.get_device_health().degraded and _time.time() < deadline:
                _time.sleep(0.01)
            assert _admission.get_device_health().degraded

            # ZERO hung futures: everything submitted before the latch
            # resolves (the device still works in-test — only new device
            # paths are fenced), and new generation fails fast + typed.
            for f in inflight:
                f.result(timeout=120)
            t0 = _time.perf_counter()
            with pytest.raises(DeviceUnavailableError) as ei:
                eng.submit([9, 8, 7], max_new_tokens=8)
            assert _time.perf_counter() - t0 < 1.0
            assert ei.value.retry_after > 0

            # Warn keeps answering DURING the outage, correct top-1.
            degraded_verdict = wp.warn(req)
            assert degraded_verdict.degraded
            assert degraded_verdict.references[0].failure_id == expected_top1
            stop.set()
            wt.join(timeout=10)
            assert all(
                r.references[0].failure_id == expected_top1
                for r in warn_results if r.references
            )

            # /metrics reports the mode.
            from kakveda_tpu.core import metrics as _metrics

            snap = _metrics.get_registry().snapshot()
            assert snap["kakveda_device_degraded"]["series"][""] == 1
            assert snap["kakveda_warn_fallback_total"]["series"][""] >= 1

            # Recovery: disarm (the outage ends) → the probe un-latches —
            # nothing was killed or restarted to get here.
            faults.disarm()
            deadline = _time.time() + 10.0
            while _admission.get_device_health().degraded and _time.time() < deadline:
                _time.sleep(0.05)
            assert not _admission.get_device_health().degraded
            post = wp.warn(req)
            assert not post.degraded and post.references[0].failure_id == expected_top1
            assert eng.submit([5, 6, 7], max_new_tokens=4).result(timeout=120)
        finally:
            eng.close()
            g.close()
    finally:
        faults.disarm()
        _admission.reset_for_tests()


def test_faults_env_spec_parsing():
    faults.arm("a.b:0.5:3, c.d, e.f::-1", seed=7)
    armed = faults.armed_sites()
    assert armed["a.b"].prob == 0.5 and armed["a.b"].remaining == 3
    assert armed["c.d"].prob == 1.0 and armed["c.d"].remaining == 1
    assert armed["e.f"].remaining == -1
    s = faults.site("c.d")
    with pytest.raises(faults.FaultInjected):
        s.fire()
    assert not s.armed  # count exhausted → self-disarmed
    faults.disarm()
    assert faults.armed_sites() == {}
    s.fire()  # disarmed: a no-op, not an exception
