"""CLI tests (reference analogue: tests/test_cli.py — help/exit-code checks)."""

import json

import pytest

from kakveda_tpu.cli.main import build_parser, main


def test_help_lists_verbs(capsys):
    with pytest.raises(SystemExit) as ei:
        build_parser().parse_args(["--help"])
    assert ei.value.code == 0
    out = capsys.readouterr().out
    for verb in ("init", "up", "down", "status", "reset", "logs", "doctor", "version"):
        assert verb in out


def test_no_command_errors():
    with pytest.raises(SystemExit) as ei:
        build_parser().parse_args([])
    assert ei.value.code == 2


def test_version(capsys):
    assert main(["version"]) == 0
    assert "kakveda-tpu" in capsys.readouterr().out


def test_init_and_status_and_reset(tmp_path, capsys):
    assert main(["init", "--dir", str(tmp_path)]) == 0
    assert (tmp_path / "config" / "config.yaml").exists()
    assert (tmp_path / "data").is_dir()

    assert main(["status", "--dir", str(tmp_path)]) == 0
    capsys.readouterr()

    # init twice without --force refuses to overwrite
    assert main(["init", "--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "already exists" in out

    # reset requires --yes
    assert main(["reset", "--dir", str(tmp_path)]) == 1
    assert (tmp_path / "data").exists()
    assert main(["reset", "--dir", str(tmp_path), "--yes"]) == 0
    assert not (tmp_path / "data").exists()


def test_status_counts_rows(tmp_path, capsys):
    data = tmp_path / "data"
    data.mkdir(parents=True)
    (data / "failures.jsonl").write_text('{"a":1}\n{"a":2}\n')
    assert main(["status", "--dir", str(tmp_path)]) == 0
    status = json.loads(capsys.readouterr().out)
    assert status["failures"] == 2
    assert status["patterns"] == 0
