"""CLI tests (reference analogue: tests/test_cli.py — help/exit-code checks)."""

import json

import pytest

from kakveda_tpu.cli.main import build_parser, main


def test_help_lists_verbs(capsys):
    with pytest.raises(SystemExit) as ei:
        build_parser().parse_args(["--help"])
    assert ei.value.code == 0
    out = capsys.readouterr().out
    for verb in ("init", "up", "down", "status", "reset", "logs", "doctor", "version"):
        assert verb in out


def test_no_command_errors():
    with pytest.raises(SystemExit) as ei:
        build_parser().parse_args([])
    assert ei.value.code == 2


def test_version(capsys):
    assert main(["version"]) == 0
    assert "kakveda-tpu" in capsys.readouterr().out


def test_init_and_status_and_reset(tmp_path, capsys):
    assert main(["init", "--dir", str(tmp_path)]) == 0
    assert (tmp_path / "config" / "config.yaml").exists()
    assert (tmp_path / "data").is_dir()

    assert main(["status", "--dir", str(tmp_path)]) == 0
    capsys.readouterr()

    # init twice without --force refuses to overwrite
    assert main(["init", "--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "already exists" in out

    # reset requires --yes
    assert main(["reset", "--dir", str(tmp_path)]) == 1
    assert (tmp_path / "data").exists()
    assert main(["reset", "--dir", str(tmp_path), "--yes"]) == 0
    assert not (tmp_path / "data").exists()


def test_dlq_list_and_replay(tmp_path, capsys, monkeypatch):
    """`dlq list` summarizes per-(topic, url) without event bodies;
    `dlq replay` re-POSTs and rewrites the file with what still fails."""
    dlq = tmp_path / "data" / "dlq.jsonl"
    dlq.parent.mkdir(parents=True)

    # empty: list reports zero events
    assert main(["dlq", "--dir", str(tmp_path)]) == 0
    assert json.loads(capsys.readouterr().out)["events"] == 0

    dlq.write_text(
        json.dumps({"ts": 1.0, "topic": "t", "url": "http://a/h",
                    "event": {"n": 1}, "error": "boom", "attempts": 3}) + "\n"
        + json.dumps({"ts": 2.0, "topic": "t", "url": "http://a/h",
                      "event": {"n": 2}, "error": "later", "attempts": 3}) + "\n"
    )
    assert main(["dlq", "list", "--dir", str(tmp_path)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["events"] == 2
    assert out["entries"][0]["count"] == 2 and out["entries"][0]["last_error"] == "later"

    import httpx

    sent = []

    class _OK:
        def raise_for_status(self):
            return None

    monkeypatch.setattr(
        httpx, "post", lambda u, json=None, timeout=None: (sent.append(json), _OK())[1]
    )
    assert main(["dlq", "replay", "--dir", str(tmp_path)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["replayed"] == 2 and out["failed"] == 0
    assert [e["n"] for e in sent] == [1, 2]
    assert dlq.read_text() == ""


def test_status_counts_rows(tmp_path, capsys):
    data = tmp_path / "data"
    data.mkdir(parents=True)
    (data / "failures.jsonl").write_text('{"a":1}\n{"a":2}\n')
    assert main(["status", "--dir", str(tmp_path)]) == 0
    status = json.loads(capsys.readouterr().out)
    assert status["failures"] == 2
    assert status["patterns"] == 0


def test_init_yes_writes_env(tmp_path):
    from kakveda_tpu.cli.main import main

    assert main(["init", "--dir", str(tmp_path), "--yes"]) == 0
    env = (tmp_path / ".env").read_text()
    assert "DASHBOARD_JWT_SECRET=" in env
    secret = [l for l in env.splitlines() if l.startswith("DASHBOARD_JWT_SECRET=")][0].split("=", 1)[1]
    assert len(secret) == 64  # token_hex(32)
    assert "KAKVEDA_ENV=development" in env
    # re-running keeps the existing secret (sessions survive)
    assert main(["init", "--dir", str(tmp_path), "--yes", "--force"]) == 0
    assert secret in (tmp_path / ".env").read_text()


def test_wizard_interactive_answers(tmp_path):
    from kakveda_tpu.cli.wizard import run_wizard

    answers = iter([
        "production",          # env
        "tpu",                 # model runtime
        "/ckpts/llama3-8b",    # hf checkpoint dir
        "text",                # log format
        "4096",                # index capacity
        "data:4,model:2",      # mesh shape
        "redis://r:6379/0",    # redis url
        "",                    # smtp host (skip)
        "",                    # otel (skip)
    ])
    out = []
    path = run_wizard(tmp_path, input_fn=lambda _: next(answers), print_fn=out.append)
    env = path.read_text()
    assert "KAKVEDA_ENV=production" in env
    assert "KAKVEDA_MODEL_RUNTIME=tpu" in env
    assert "KAKVEDA_HF_CKPT=/ckpts/llama3-8b" in env
    assert "KAKVEDA_MESH_SHAPE=data:4,model:2" in env
    assert "KAKVEDA_REDIS_URL=redis://r:6379/0" in env
    assert "SMTP_HOST" not in env
    assert any("production mode" in line for line in out)


def test_doctor_runs(capsys, tmp_path, monkeypatch):
    from kakveda_tpu.cli.main import main

    # hermetic: ambient redis/env settings or repo-dir writes must not leak
    monkeypatch.delenv("KAKVEDA_REDIS_URL", raising=False)
    monkeypatch.delenv("KAKVEDA_ENV", raising=False)
    monkeypatch.delenv("KAKVEDA_MESH_SHAPE", raising=False)
    monkeypatch.setenv("KAKVEDA_DATA_DIR", str(tmp_path / "data"))
    monkeypatch.setenv("KAKVEDA_CONFIG_PATH", str(tmp_path / "config.yaml"))
    monkeypatch.chdir(tmp_path)
    rc = main(["doctor"])
    outp = capsys.readouterr().out
    assert "jax" in outp and "device mesh" in outp and "native extension" in outp
    assert rc == 0


def test_doctor_redacts_redis_password(capsys, tmp_path, monkeypatch):
    from kakveda_tpu.cli.main import main

    monkeypatch.setenv("KAKVEDA_REDIS_URL", "redis://:s3cretpass@localhost:1/0")
    monkeypatch.setenv("KAKVEDA_DATA_DIR", str(tmp_path / "data"))
    monkeypatch.chdir(tmp_path)
    main(["doctor"])
    outp = capsys.readouterr().out
    assert "s3cretpass" not in outp


def test_load_dotenv_env_wins(tmp_path, monkeypatch):
    from kakveda_tpu.cli.wizard import load_dotenv

    env = tmp_path / ".env"
    env.write_text("KAKVEDA_TEST_A=from_file\nKAKVEDA_TEST_B=file_b\n# comment\nbad line\n")
    monkeypatch.setenv("KAKVEDA_TEST_A", "from_env")
    monkeypatch.delenv("KAKVEDA_TEST_B", raising=False)
    applied = load_dotenv(env)
    try:
        import os
        assert os.environ["KAKVEDA_TEST_A"] == "from_env"  # real env wins
        assert os.environ["KAKVEDA_TEST_B"] == "file_b"
        assert applied == 1
    finally:
        import os
        os.environ.pop("KAKVEDA_TEST_B", None)


def test_env_file_permissions(tmp_path):
    import os, stat
    from kakveda_tpu.cli.main import main

    assert main(["init", "--dir", str(tmp_path), "--yes"]) == 0
    mode = stat.S_IMODE(os.stat(tmp_path / ".env").st_mode)
    assert mode == 0o600


def test_wizard_rejects_invalid_choice(tmp_path):
    from kakveda_tpu.cli.wizard import run_wizard

    answers = iter([
        "prod",            # invalid → re-asked
        "production",      # valid env
        "stub", "", "json", "4096", "data:-1", "", "", "",
    ])
    path = run_wizard(tmp_path, input_fn=lambda _: next(answers), print_fn=lambda s: None)
    assert "KAKVEDA_ENV=production" in path.read_text()


def test_up_detach_status_logs_down(tmp_path):
    """Real process management: up --detach spawns a background server with
    server.pid + server.log, status reports it running, logs tails output,
    down SIGTERMs it and cleans the pid file."""
    import json
    import os
    import socket
    import subprocess
    import sys
    import time
    import urllib.request

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    port = free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", KAKVEDA_LOG_FORMAT="text")

    def cli(*argv, timeout=60):
        return subprocess.run(
            [sys.executable, "-m", "kakveda_tpu.cli", *argv],
            capture_output=True, text=True, env=env, timeout=timeout,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )

    r = cli("up", "--detach", "--dir", str(tmp_path), "--port", str(port),
            "--dashboard-port", "0")
    assert r.returncode == 0, r.stderr
    pid_file = tmp_path / "server.pid"
    assert pid_file.exists()

    try:
        # Wait for the server to come up (first jit compile is slow).
        deadline = time.time() + 120
        up = False
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=2) as resp:
                    up = resp.status == 200
                    break
            except OSError:
                time.sleep(1.0)
        assert up, (tmp_path / "server.log").read_text()[-2000:]

        # Double-up refuses while running.
        r = cli("up", "--dir", str(tmp_path), "--port", str(port))
        assert r.returncode == 1 and "already running" in r.stderr

        r = cli("status", "--dir", str(tmp_path))
        st = json.loads(r.stdout)
        assert st["server"]["running"] is True

        r = cli("logs", "--dir", str(tmp_path))
        assert r.returncode == 0 and "platform API" in r.stdout
    finally:
        r = cli("down", "--dir", str(tmp_path), timeout=60)
    assert r.returncode == 0, r.stderr
    assert not pid_file.exists()
    st = json.loads(cli("status", "--dir", str(tmp_path)).stdout)
    assert st["server"]["running"] is False
