"""Config store + runtime config tests (reference: services/shared/config.py,
runtime.py)."""

import time

import yaml

from kakveda_tpu.core.config import ConfigStore, write_default_config
from kakveda_tpu.core.runtime import ensure_request_id, get_runtime_config


def test_missing_file_returns_empty_and_defaults(tmp_path):
    cs = ConfigStore(tmp_path / "nope.yaml")
    assert cs.get() == {}
    assert cs.similarity_threshold() == 0.8
    assert cs.default_action() == "warn"
    assert cs.severity_weights() == {"low": 1.0, "medium": 3.0, "high": 7.0}


def test_reads_yaml(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text(yaml.safe_dump({"failure_matching": {"similarity_threshold": 0.5}}))
    cs = ConfigStore(p)
    assert cs.similarity_threshold() == 0.5


def test_hot_reload_on_mtime_change(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text(yaml.safe_dump({"failure_matching": {"similarity_threshold": 0.5}}))
    cs = ConfigStore(p)
    assert cs.similarity_threshold() == 0.5
    time.sleep(0.02)
    p.write_text(yaml.safe_dump({"failure_matching": {"similarity_threshold": 0.9}}))
    # mtime change forces reload even inside the poll interval
    assert cs.similarity_threshold() == 0.9


def test_write_default_config_roundtrip(tmp_path):
    p = write_default_config(tmp_path / "cfg" / "config.yaml")
    cs = ConfigStore(p)
    assert cs.similarity_threshold() == 0.8
    assert cs.embedding_dim() == 2048


def test_runtime_config_defaults(monkeypatch):
    monkeypatch.delenv("KAKVEDA_ENV", raising=False)
    cfg = get_runtime_config(service_name="svc")
    assert cfg.env == "dev"
    assert cfg.model_runtime == "stub"
    assert cfg.otel_service_name == "svc"


def test_runtime_config_env_override(monkeypatch):
    monkeypatch.setenv("KAKVEDA_MODEL_RUNTIME", "tpu")
    monkeypatch.setenv("KAKVEDA_INDEX_CAPACITY", "4096")
    cfg = get_runtime_config(service_name="svc")
    assert cfg.model_runtime == "tpu"
    assert cfg.index_capacity == 4096


def test_ensure_request_id():
    assert ensure_request_id("abc") == "abc"
    rid = ensure_request_id(None)
    assert len(rid) == 32
    assert ensure_request_id("x" * 500) == "x" * 128
