"""Config store + runtime config tests (reference: services/shared/config.py,
runtime.py)."""

import time

import yaml

from kakveda_tpu.core.config import ConfigStore, write_default_config
from kakveda_tpu.core.runtime import ensure_request_id, get_runtime_config


def test_missing_file_returns_empty_and_defaults(tmp_path):
    cs = ConfigStore(tmp_path / "nope.yaml")
    assert cs.get() == {}
    assert cs.similarity_threshold() == 0.8
    assert cs.default_action() == "warn"
    assert cs.severity_weights() == {"low": 1.0, "medium": 3.0, "high": 7.0}


def test_reads_yaml(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text(yaml.safe_dump({"failure_matching": {"similarity_threshold": 0.5}}))
    cs = ConfigStore(p)
    assert cs.similarity_threshold() == 0.5


def test_hot_reload_on_mtime_change(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text(yaml.safe_dump({"failure_matching": {"similarity_threshold": 0.5}}))
    cs = ConfigStore(p)
    assert cs.similarity_threshold() == 0.5
    time.sleep(0.02)
    p.write_text(yaml.safe_dump({"failure_matching": {"similarity_threshold": 0.9}}))
    # mtime change forces reload even inside the poll interval
    assert cs.similarity_threshold() == 0.9


def test_write_default_config_roundtrip(tmp_path):
    p = write_default_config(tmp_path / "cfg" / "config.yaml")
    cs = ConfigStore(p)
    assert cs.similarity_threshold() == 0.8
    assert cs.embedding_dim() == 2048


def test_runtime_config_defaults(monkeypatch):
    monkeypatch.delenv("KAKVEDA_ENV", raising=False)
    cfg = get_runtime_config(service_name="svc")
    assert cfg.env == "dev"
    assert cfg.model_runtime == "stub"
    assert cfg.otel_service_name == "svc"


def test_runtime_config_env_override(monkeypatch):
    monkeypatch.setenv("KAKVEDA_MODEL_RUNTIME", "tpu")
    monkeypatch.setenv("KAKVEDA_INDEX_CAPACITY", "4096")
    cfg = get_runtime_config(service_name="svc")
    assert cfg.model_runtime == "tpu"
    assert cfg.index_capacity == 4096


def test_ensure_request_id():
    assert ensure_request_id("abc") == "abc"
    rid = ensure_request_id(None)
    assert len(rid) == 32
    assert ensure_request_id("x" * 500) == "x" * 128


def test_rate_limiter_fixed_window():
    from kakveda_tpu.core.ratelimit import RateLimiter

    rl = RateLimiter(redis_url=None)
    key = "t:1"
    assert all(rl.allow(key, limit=3) for _ in range(3))
    assert not rl.allow(key, limit=3)
    # distinct keys are independent windows
    assert rl.allow("t:2", limit=3)


def test_alias_package_resolves_to_kakveda_tpu():
    import kakveda
    import kakveda_tpu
    import kakveda_tpu.core

    # attribute access and deep imports are identity-preserving: the alias
    # meta-path finder hands back the same module objects, never duplicates
    assert kakveda.core is kakveda_tpu.core
    import kakveda.core.schemas as alias_schemas
    import kakveda_tpu.core.schemas as real_schemas

    assert alias_schemas is real_schemas
    assert alias_schemas.WarningRequest is real_schemas.WarningRequest
    # real module metadata survives the aliasing
    assert real_schemas.__name__ == "kakveda_tpu.core.schemas"
    from kakveda.core.fingerprint import fingerprint as fp_alias
    from kakveda_tpu.core.fingerprint import fingerprint as fp_real

    assert fp_alias is fp_real
    # missing attributes probe cleanly
    assert getattr(kakveda, "does_not_exist", None) is None
