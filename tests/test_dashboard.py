"""Dashboard tests: auth, RBAC, scenario flow, runs/spans, datasets/evals,
prompts/experiments, admin, projects + API-key ingest.

Smoke-level coverage mirroring the reference's dashboard smoke tests
(reference: services/dashboard/tests/test_dashboard_smoke.py) plus flows
the reference never tested.
"""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from kakveda_tpu.dashboard import auth as auth_lib
from kakveda_tpu.dashboard.app import make_dashboard_app
from kakveda_tpu.models.runtime import StubRuntime
from kakveda_tpu.platform import Platform


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _fresh_rate_limiter():
    """Every test gets a fresh login-rate window: the limiter is
    process-GLOBAL (all test apps share one process and one 127.0.0.1
    peer key), so a fast full-suite run crosses the 20-logins/60s
    threshold mid-file and unrelated tests start bouncing off the
    'Too many attempts' page."""
    from kakveda_tpu.dashboard.core import RATE_LIMITER

    RATE_LIMITER._hits.clear()
    yield


def _mk_app(tmp_path):
    plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
    return make_dashboard_app(
        platform=plat, db_path=tmp_path / "dash.db", model=StubRuntime()
    )


async def _client(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


async def _login(client, email="admin@local", password="admin123"):
    r = await client.post(
        "/login", data={"email": email, "password": password, "next": "/"}, allow_redirects=False
    )
    assert r.status == 302, await r.text()
    return client


def test_auth_redirect_and_login(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            r = await client.get("/", allow_redirects=False)
            assert r.status == 302 and "/login" in r.headers["Location"]

            r = await client.get("/login")
            assert r.status == 200 and "Sign in" in await r.text()

            r = await client.post(
                "/login", data={"email": "admin@local", "password": "wrong", "next": "/"}
            )
            assert "Invalid credentials" in await r.text()

            await _login(client)
            r = await client.get("/")
            assert r.status == 200
            assert "Failure intelligence overview" in await r.text()
        finally:
            await client.close()

    run(go())


def test_jwt_roundtrip_and_tamper():
    tok = auth_lib.create_access_token(email="a@local", roles=["admin"], secret="s1")
    claims = auth_lib.decode_token(tok, secret="s1")
    assert claims["sub"] == "a@local" and claims["roles"] == ["admin"]
    assert auth_lib.decode_token(tok, secret="s2") is None
    assert auth_lib.decode_token(tok[:-4] + "AAAA", secret="s1") is None
    assert auth_lib.decode_token("garbage", secret="s1") is None


def test_password_hash_roundtrip():
    h = auth_lib.hash_password("hunter42x")
    assert auth_lib.verify_password("hunter42x", h)
    assert not auth_lib.verify_password("wrong", h)
    assert not auth_lib.verify_password("hunter42x", "malformed")


def test_scenario_run_creates_warning_runs_spans(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            r = await client.post(
                "/scenarios/run",
                data={
                    "app_id": "app-A",
                    "prompt": "Summarize this document and include citations even if not provided.",
                },
                allow_redirects=False,
            )
            assert r.status == 302 and "/warnings" in r.headers["Location"]

            r = await client.get("/warnings")
            body = await r.text()
            assert "app-A" in body

            r = await client.get("/runs")
            assert "stub" in await r.text()

            r = await client.get("/scenarios")
            text = await r.text()
            assert "spans" in text
            # follow the trace link to the span waterfall
            import re

            m = re.search(r'/runs/([0-9a-f-]{36})', text)
            assert m
            r = await client.get(f"/runs/{m.group(1)}")
            detail = await r.text()
            assert "scenario.run" in detail and "warn_policy.call" in detail
        finally:
            await client.close()

    run(go())


def test_rbac_viewer_cannot_run_scenarios(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client, "viewer@local", "viewer123")
            r = await client.post(
                "/scenarios/run", data={"app_id": "a", "prompt": "x"}, allow_redirects=False
            )
            assert r.status == 403
            r = await client.get("/admin/users", allow_redirects=False)
            assert r.status == 403
        finally:
            await client.close()

    run(go())


def test_admin_users_and_impersonation(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            r = await client.get("/admin/users")
            body = await r.text()
            assert "viewer@local" in body

            r = await client.post(
                "/admin/impersonate", data={"email": "viewer@local"}, allow_redirects=False
            )
            assert r.status == 302
            r = await client.get("/")
            assert "as-of admin@local" in await r.text()
        finally:
            await client.close()

    run(go())


def test_datasets_eval_flow(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            await client.post("/datasets/create", data={"name": "ds1", "description": "d"})
            await client.post(
                "/datasets/1/examples",
                data={"app_id": "eval-app", "prompt": "Summarize with citations please"},
            )
            await client.post(
                "/datasets/1/examples", data={"app_id": "eval-app", "prompt": "What is 2+2?"}
            )
            r = await client.post("/datasets/1/eval", allow_redirects=False)
            assert r.status == 302
            r = await client.get(r.headers["Location"])
            body = await r.text()
            # stub always emits citations: citation-demanding example fails,
            # plain example passes => 50%
            assert "pass rate 50%" in body
            assert "p50" in body
        finally:
            await client.close()

    run(go())


def test_prompts_versioning(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            await client.post("/prompts/save", data={"name": "p1", "text": "v1 text"})
            await client.post("/prompts/save", data={"name": "p1", "text": "v2 text"})
            r = await client.get("/prompts/1")
            body = await r.text()
            assert "v2 text" in body and "v1 text" in body
        finally:
            await client.close()

    run(go())


def test_experiments_and_playground(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            await client.post("/experiments/create", data={"name": "exp1"})
            r = await client.post(
                "/playground/run",
                data={"prompt": "hello", "target": "model", "experiment": "exp1"},
            )
            assert "Result" in await r.text()
            r = await client.get("/experiments/1")
            assert "1 runs" in await r.text() or "p50" in await r.text()
        finally:
            await client.close()

    run(go())


def test_warnings_analytics_and_span_waterfall_depth(tmp_path):
    """The computed aggregates must REACH the page: stat tiles, the
    zero-filled daily chart, per-app/per-pattern breakdown scaffolding,
    the raw-rows JSON powering client-side 30d/90d + app filtering, and a
    depth-indented span waterfall with computed offsets (reference
    capability: templates/warnings.html + app.py:1912-2041, 2927-2970)."""
    import json as _json
    import re

    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            for app in ("app-A", "app-A", "app-B"):
                await client.post(
                    "/scenarios/run",
                    data={"app_id": app,
                          "prompt": "Summarize this and include citations even if not provided."},
                    allow_redirects=False,
                )
            body = await (await client.get("/warnings")).text()
            # tiles + chart + filters are rendered
            assert 'id="tile-total"' in body and 'id="day-chart"' in body
            assert 'id="f-window"' in body and 'id="f-app"' in body
            # the day series must INCLUDE today (events land in today's
            # bucket; a range ending yesterday or at a phantom tomorrow
            # drops the newest warnings from the tile/chart)
            import datetime as _dt

            today = _dt.datetime.now(_dt.timezone.utc).strftime("%Y-%m-%d")
            m_tile = re.search(r'id="tile-total">(\d+)<', body)
            assert m_tile and int(m_tile.group(1)) >= 3, body[:500]
            assert today in body
            # zero-filled 31-day series reaches the template context
            assert body.count("<tr") >= 3
            # raw rows JSON is embedded and parseable, with the real events
            m = re.search(r'<script id="rows-data"[^>]*>(.*?)</script>', body, re.S)
            assert m, "rows JSON missing"
            data = _json.loads(m.group(1))
            rows = data["rows"]
            assert data["truncated"] is False
            assert len(rows) >= 2 and {r["app_id"] for r in rows} >= {"app-A", "app-B"}
            assert all("ts" in r and "action" in r for r in rows)
            # server-side app filter narrows the page
            body_a = await (await client.get("/warnings?app_id=app-B")).text()
            rows_a = _json.loads(
                re.search(r'<script id="rows-data"[^>]*>(.*?)</script>', body_a, re.S).group(1)
            )["rows"]
            assert {r["app_id"] for r in rows_a} == {"app-B"}

            # stored-XSS guard: a hostile app_id must not be able to
            # terminate the rows-data <script> block
            evil = '</script><b>pwn</b>'
            await client.post(
                "/scenarios/run",
                data={"app_id": evil, "prompt": "include citations please"},
                allow_redirects=False,
            )
            body_x = await (await client.get("/warnings")).text()
            block = re.search(r'<script id="rows-data"[^>]*>(.*?)</script>', body_x, re.S).group(1)
            assert "</script" not in block and "\\u003c/script" in block
            assert _json.loads(block)  # still valid JSON after escaping

            # span waterfall: depth-indented tree with computed offsets
            runs_page = await (await client.get("/scenarios")).text()
            trace = re.search(r"/runs/([0-9a-f-]{36})", runs_page).group(1)
            detail = await (await client.get(f"/runs/{trace}")).text()
            assert "Span waterfall" in detail and "ms total" in detail
            assert "padding-left:" in detail  # depth indent applied
            assert re.search(r"left:\d", detail) and re.search(r"width:\d", detail)
            assert "+0 ms" in detail  # start offsets rendered
        finally:
            await client.close()

    run(go())


def test_playground_concurrent_requests_share_engine(tmp_path, monkeypatch):
    """Service-level continuous batching: concurrent HTTP playground runs
    against a real (tiny) TPU runtime all decode through ONE shared
    ServingEngine KV pool, and each reply equals the runtime's solo
    (engine-off) output for the same prompt."""
    import jax.numpy as jnp

    from kakveda_tpu.models.generate import LlamaRuntime
    from kakveda_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(
        vocab_size=264, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=48, max_seq_len=256, dtype=jnp.float32,
    )
    monkeypatch.setenv("KAKVEDA_SERVE_CONTINUOUS", "0")
    rt_solo = LlamaRuntime(cfg=cfg, seed=0)
    prompts = ["first failure", "second timeout story", "third"]
    solo = {p: rt_solo.generate(p, max_tokens=8).text for p in prompts}
    monkeypatch.delenv("KAKVEDA_SERVE_CONTINUOUS", raising=False)

    rt = LlamaRuntime(cfg=cfg, seed=0)
    plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
    app = make_dashboard_app(platform=plat, db_path=tmp_path / "dash.db", model=rt)

    async def go():
        client = await _client(app)
        try:
            await _login(client)
            rs = await asyncio.gather(
                *(
                    client.post("/playground/run", data={"prompt": p, "target": "model"})
                    for p in prompts
                )
            )
            pages = [await r.text() for r in rs]
            for p, page in zip(prompts, pages):
                assert solo[p] in page, f"engine output for {p!r} != solo decode"
        finally:
            await client.close()

    run(go())
    assert rt._engine is not None, "playground did not go through the engine"
    assert rt._engine.stats()["completed"] == len(prompts)
    rt._engine.close()


def test_admin_serving_page_reports_engine_and_levers(tmp_path, monkeypatch):
    """The serving admin panel must surface the live pool state: after a
    playground request through a real TPU runtime it shows the engine's
    slots/window and completed count plus the quant levers; under the
    stub runtime it says there is no pool."""
    import re

    import jax.numpy as jnp

    from kakveda_tpu.models.generate import LlamaRuntime
    from kakveda_tpu.models.llama import LlamaConfig

    async def stub_case():
        client = await _client(_mk_app(tmp_path / "stub"))
        try:
            await _login(client)
            body = await (await client.get("/admin/serving")).text()
            assert "no serving pool" in body
        finally:
            await client.close()

    run(stub_case())

    monkeypatch.setenv("KAKVEDA_KV_QUANT", "int8")
    cfg = LlamaConfig(vocab_size=264, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
                      d_ff=48, max_seq_len=256, dtype=jnp.float32)
    rt = LlamaRuntime(cfg=cfg, seed=0)
    plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
    app = make_dashboard_app(platform=plat, db_path=tmp_path / "dash.db", model=rt)

    async def tpu_case():
        client = await _client(app)
        try:
            await _login(client)
            # before any request: lazily-built engine absent, levers shown
            body = await (await client.get("/admin/serving")).text()
            assert "No engine yet" in body and "kv int8" in body
            await client.post("/playground/run", data={"prompt": "hi", "target": "model"})
            body = await (await client.get("/admin/serving")).text()
            assert re.search(r"\d+ slots × \d+-token window", body)
            assert "submitted / completed" in body
        finally:
            await client.close()

    run(tpu_case())
    if rt._engine is not None:
        rt._engine.close()


def test_project_api_key_ingest_and_budget(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            await client.post(
                "/projects/create", data={"name": "proj1", "monthly_budget_micro_usd": "10"}
            )
            r = await client.post("/projects/api-key", data={"project_id": 1, "label": "ci"})
            body = await r.text()
            import re

            m = re.search(r"kk-[A-Za-z0-9_\-]+", body)
            assert m, "API key not shown"
            key = m.group(0)

            # no key -> 401; bad key -> 403
            r = await client.post("/api/ingest/run", json={"prompt": "x"})
            assert r.status == 401
            r = await client.post(
                "/api/ingest/run", json={"prompt": "x"}, headers={"X-API-Key": "bad"}
            )
            assert r.status == 403

            # valid key ingests
            r = await client.post(
                "/api/ingest/run",
                json={"prompt": "Summarize with citations", "response": "See [1]", "app_id": "api-app"},
                headers={"X-API-Key": key},
            )
            assert r.status == 200
            out = await r.json()
            assert out["ok"] and out["cost_micro_usd"] >= 0

            # tiny budget: a big request trips budget enforcement -> 402
            r = await client.post(
                "/api/ingest/run",
                json={"prompt": "word " * 2000, "response": "resp " * 2000},
                headers={"X-API-Key": key},
            )
            assert r.status == 402
            assert (await r.json())["error"] == "budget exceeded"
        finally:
            await client.close()

    run(go())


def test_health_page_and_fault_injection(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            r = await client.post(
                "/health/test",
                data={"app_id": "test-app", "severity": "high", "failure_type": "SYNTH"},
                allow_redirects=False,
            )
            assert r.status == 302
            r = await client.get("/health-page?app_id=test-app")
            body = await r.text()
            assert "test-app" in body and "points" in body
        finally:
            await client.close()

    run(go())


def test_security_headers(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            r = await client.get("/login")
            assert "Content-Security-Policy" in r.headers
            assert r.headers["X-Frame-Options"] == "DENY"
        finally:
            await client.close()

    run(go())


def test_csp_nonce_covers_inline_scripts(tmp_path):
    """Pages with executable inline scripts must carry the SAME nonce in
    the CSP header and the <script> tags — script-src otherwise falls
    back to 'self', which blocks inline execution in real browsers (a
    gap no TestClient assertion on status codes can see). Each response
    must get a FRESH nonce (a static one is as weak as unsafe-inline)."""
    import re

    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            nonces = []
            for _ in range(2):
                r = await client.get("/warnings")
                csp = r.headers["Content-Security-Policy"]
                m = re.search(r"script-src 'self' 'nonce-([^']+)'", csp)
                assert m, csp
                body = await r.text()
                assert f'<script nonce="{m.group(1)}">' in body
                nonces.append(m.group(1))
            assert nonces[0] != nonces[1]
        finally:
            await client.close()

    run(go())


def test_production_requires_secret(tmp_path, monkeypatch):
    monkeypatch.setenv("KAKVEDA_ENV", "production")
    with pytest.raises(RuntimeError, match="JWT secret"):
        _mk_app(tmp_path)


def test_purge_demo_reloads_gfkb(tmp_path):
    async def go():
        plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
        app = make_dashboard_app(platform=plat, db_path=tmp_path / "dash.db", model=StubRuntime())
        client = await _client(app)
        try:
            await _login(client)
            for app_id in ("app-A", "app-B"):
                await client.post(
                    "/scenarios/run",
                    data={"app_id": app_id, "prompt": "Summarize with citations please"},
                    allow_redirects=False,
                )
            assert plat.gfkb.count > 0
            r = await client.post(
                "/admin/purge-demo", data={"confirm": "yes"}, allow_redirects=False
            )
            assert r.status == 302
            # device index + metadata must reflect the rewritten log
            assert plat.gfkb.count == 0
            assert plat.gfkb.match("anything") == []
            # and a fresh upsert mints F-0001 again, consistent with the log
            rec, created = plat.gfkb.upsert_failure(
                failure_type="T", signature_text="s", app_id="x",
                impact_severity=__import__("kakveda_tpu.core.schemas", fromlist=["Severity"]).Severity.low,
            )
            assert created and rec.failure_id == "F-0001"
        finally:
            await client.close()

    run(go())


def test_login_rejects_backslash_redirect(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            r = await client.post(
                "/login",
                data={"email": "admin@local", "password": "admin123", "next": "/\\evil.com"},
                allow_redirects=False,
            )
            assert r.status == 302 and r.headers["Location"] == "/"
        finally:
            await client.close()

    run(go())


def test_security_headers_on_redirects(tmp_path):
    # Most mutating handlers raise HTTPFound; headers must ride those too.
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            r = await client.get("/", allow_redirects=False)
            assert r.status == 302
            assert "Content-Security-Policy" in r.headers
            assert r.headers["X-Frame-Options"] == "DENY"
        finally:
            await client.close()

    run(go())


def test_api_ingest_duplicate_trace_is_idempotent(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            await client.post(
                "/projects/create", data={"name": "proj1", "monthly_budget_micro_usd": "1000000"}
            )
            r = await client.post("/projects/api-key", data={"project_id": 1, "label": "ci"})
            import re

            key = re.search(r"kk-[A-Za-z0-9_\-]+", await r.text()).group(0)
            payload = {"prompt": "hello world", "response": "resp", "trace_id": "t-dup-1"}
            r1 = await client.post("/api/ingest/run", json=payload, headers={"X-API-Key": key})
            out1 = await r1.json()
            assert r1.status == 200 and out1["ok"] and not out1.get("duplicate")
            db = client.server.app[_ctx_key()].db
            spent1 = (db.one(
                "SELECT spent_micro_usd FROM project_budgets WHERE project_id=1"
            ) or {}).get("spent_micro_usd")

            r2 = await client.post("/api/ingest/run", json=payload, headers={"X-API-Key": key})
            out2 = await r2.json()
            assert r2.status == 200 and out2.get("duplicate") is True
            spent2 = (db.one(
                "SELECT spent_micro_usd FROM project_budgets WHERE project_id=1"
            ) or {}).get("spent_micro_usd")
            assert spent1 == spent2, "retry must not double-charge the budget"
        finally:
            await client.close()

    run(go())


def _ctx_key():
    from kakveda_tpu.dashboard.core import CTX_KEY

    return CTX_KEY


def test_production_skips_demo_users(tmp_path, monkeypatch):
    monkeypatch.setenv("KAKVEDA_ENV", "production")
    monkeypatch.setenv("DASHBOARD_JWT_SECRET", "prod-secret-123456")
    app = make_dashboard_app(
        platform=Platform(data_dir=tmp_path / "data", capacity=256, dim=1024),
        db_path=tmp_path / "dash.db",
        model=StubRuntime(),
    )
    db = app[_ctx_key()].db
    assert db.user_by_email("admin@local") is None


def test_forgot_hides_reset_link_in_production(tmp_path, monkeypatch):
    async def go():
        monkeypatch.setenv("KAKVEDA_ENV", "production")
        monkeypatch.setenv("DASHBOARD_JWT_SECRET", "prod-secret-123456")
        monkeypatch.setenv("KAKVEDA_DEMO_USERS", "1")
        client = await _client(_mk_app(tmp_path))
        try:
            r = await client.post("/forgot", data={"email": "admin@local"})
            assert "token=" not in await r.text()
        finally:
            await client.close()

    run(go())


def test_project_clear_cookie(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            await client.post("/projects/create", data={"name": "p1"})
            r = await client.post(
                "/projects/select", data={"project_id": "1"}, allow_redirects=False
            )
            assert r.status == 302
            r = await client.post("/projects/clear", allow_redirects=False)
            assert r.status == 302
            # cleared cookie arrives as an expired Set-Cookie
            sc = r.headers.getall("Set-Cookie", [])
            assert any("kakveda_project" in c or "project" in c for c in sc)
        finally:
            await client.close()

    run(go())


def test_forgot_sends_email_when_smtp_configured(tmp_path, monkeypatch):
    sent = {}

    def fake_send(to, subject, body):
        sent["to"], sent["subject"], sent["body"] = to, subject, body
        return True

    async def go():
        monkeypatch.setenv("SMTP_HOST", "smtp.example.com")
        monkeypatch.setenv("SMTP_USER", "mailer")
        from kakveda_tpu.dashboard import email as email_lib

        monkeypatch.setattr(email_lib, "send_email", fake_send)
        client = await _client(_mk_app(tmp_path))
        try:
            r = await client.post("/forgot", data={"email": "admin@local"})
            body = await r.text()
            # delivered by email: the inline demo link is suppressed
            assert "token=" not in body
            assert sent["to"] == "admin@local"
            assert "/reset?token=" in sent["body"]
        finally:
            await client.close()

    run(go())


def test_evals_list_page(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            # empty state renders
            r = await client.get("/evals")
            assert r.status == 200 and "Evaluation runs" in await r.text()
            # create dataset + example, run an eval, then the run lists
            r = await client.post("/datasets/create", data={"name": "ds1", "description": ""})
            await client.post(
                "/datasets/1/examples",
                data={"prompt": "Summarize with citations", "app_id": "eval-app", "expected": ""},
            )
            await client.post("/datasets/1/eval")
            r = await client.get("/evals")
            body = await r.text()
            assert "/eval/1" in body and "ds1" in body
        finally:
            await client.close()

    run(go())


def test_playground_model_selection(tmp_path):
    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            page = await (await client.get("/playground")).text()
            assert 'value="model:stub"' in page
            r = await client.post(
                "/playground/run", data={"prompt": "hi", "target": "model:stub"}
            )
            assert r.status == 200
            assert "stub" in await r.text()
        finally:
            await client.close()

    run(go())


def test_csrf_cookie_issued_and_enforced(tmp_path, monkeypatch):
    """Reference parity: the csrf_token cookie is set even with enforcement
    disabled (reference: services/dashboard/app.py:655-663); with
    KAKVEDA_CSRF_ENFORCE=1 mutating form posts require the double-submit
    token."""

    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            r = await client.get("/login")
            assert r.status == 200
            cookies = {c.key: c.value for c in client.session.cookie_jar}
            assert cookies.get("csrf_token"), "csrf cookie not issued"
            token = cookies["csrf_token"]

            # enforcement off (default): login works without the token
            await _login(client)

            monkeypatch.setenv("KAKVEDA_CSRF_ENFORCE", "1")
            r = await client.post("/scenarios/run", data={"app_id": "a"}, allow_redirects=False)
            assert r.status == 403, await r.text()
            r = await client.post(
                "/scenarios/run",
                data={"app_id": "app-A", "prompt": "Summarize with citations", "csrf_token": token},
                allow_redirects=False,
            )
            assert r.status in (200, 302), await r.text()
        finally:
            monkeypatch.delenv("KAKVEDA_CSRF_ENFORCE", raising=False)
            await client.close()

    run(go())


def test_pg_dialect_translation():
    """The Postgres shim rewrites exactly the three sqlite-isms the route
    layer uses; everything else passes through byte-identical. (A live
    Postgres round-trip is exercised via docker-compose.prod.yml — see
    docs — since the CI image carries no server.)"""
    from kakveda_tpu.dashboard.db import _IDLESS_TABLES, _SCHEMA, pg_schema, pg_translate

    assert pg_translate("SELECT * FROM users WHERE email=?") == (
        "SELECT * FROM users WHERE email=%s"
    )
    assert pg_translate("INSERT OR IGNORE INTO roles (name) VALUES (?)") == (
        "INSERT INTO roles (name) VALUES (%s) ON CONFLICT DO NOTHING"
    )
    # multi-line INSERT OR IGNORE (the user_roles shape)
    t = pg_translate("INSERT OR IGNORE INTO user_roles (user_id, role_id)\n VALUES (?,?)")
    assert t.startswith("INSERT INTO user_roles") and t.endswith("ON CONFLICT DO NOTHING")
    # non-insert SQL untouched beyond params
    assert pg_translate("UPDATE users SET is_active=? WHERE id=?") == (
        "UPDATE users SET is_active=%s WHERE id=%s"
    )

    stmts = pg_schema(_SCHEMA)
    joined = "\n".join(stmts)
    assert "AUTOINCREMENT" not in joined
    assert "BIGSERIAL PRIMARY KEY" in joined
    # every schema statement survives the split intact
    assert sum(1 for s in stmts if s.upper().startswith("CREATE TABLE")) == 23
    # the idless set matches the schema: tables with no "id" column
    for tbl in _IDLESS_TABLES:
        ddl = next(s for s in stmts if f"EXISTS {tbl} " in s or f"EXISTS {tbl}\n" in s)
        assert "BIGSERIAL" not in ddl, tbl


def test_make_database_respects_env(tmp_path, monkeypatch):
    from kakveda_tpu.dashboard.db import Database, make_database

    monkeypatch.delenv("KAKVEDA_DB_URL", raising=False)
    db = make_database(tmp_path / "x.db")
    assert isinstance(db, Database)
    monkeypatch.setenv("KAKVEDA_DB_URL", "postgresql://u:p@nowhere:5432/d")
    with pytest.raises(RuntimeError, match="psycopg2"):
        make_database(tmp_path / "x.db")


def test_admin_purge_demo_confirm_flow(tmp_path):
    """Purge-demo ships a preview page + explicit confirm: GET shows counts
    and backups, a POST without confirmation refuses, the confirmed POST
    backs up, rewrites stores, and reports via the message banner."""

    async def go():
        app = _mk_app(tmp_path)
        client = await _client(app)
        try:
            await _login(client)
            # Seed demo + non-demo failures through the platform.
            from datetime import datetime, timezone

            from kakveda_tpu.core.schemas import TracePayload
            from kakveda_tpu.dashboard.core import CTX_KEY

            ctx_plat = app[CTX_KEY].platform
            # Distinct prompts → distinct canonical records per app (a
            # shared signature would canonicalize into one record spanning
            # demo + prod apps, which purge rightly keeps).
            for app_id in ("app-A", "app-B", "prod-app"):
                await ctx_plat.ingest_batch(
                    [
                        TracePayload(
                            trace_id=f"t-{app_id}", ts=datetime.now(timezone.utc),
                            app_id=app_id, agent_id="t",
                            prompt=f"Summarize the {app_id} report with citations even if not provided",
                            response="Done [1] (Smith 2021)", tools=[], env={},
                        )
                    ]
                )
            assert len(ctx_plat.failures()) >= 1

            r = await client.get("/admin/purge-demo")
            page = await r.text()
            assert r.status == 200 and "app-A" in page and "failures.jsonl" in page

            # Unconfirmed POST refuses.
            r = await client.post("/admin/purge-demo", data={}, allow_redirects=False)
            assert r.status == 302 and "error" in r.headers["Location"]

            # Confirmed POST purges, backs up, redirects with a message.
            r = await client.post(
                "/admin/purge-demo", data={"confirm": "yes"}, allow_redirects=False
            )
            assert r.status == 302 and "message=" in r.headers["Location"]
            r = await client.get(r.headers["Location"])
            page = await r.text()
            assert "Purged demo apps" in page and ".bak-" in page
            # Non-demo rows survive the purge.
            apps = ctx_plat.apps()
            assert "prod-app" in apps and "app-A" not in apps
        finally:
            await client.close()

    run(go())


def test_admin_agents_page(tmp_path):
    """Dedicated admin agent-management page: register lands back on
    /admin/agents, listing shows the secret-env column, delete removes."""

    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            r = await client.post(
                "/agents/register",
                data={
                    "name": "probe", "base_url": "http://127.0.0.1:9",
                    "auth_kind": "bearer_env", "auth_secret_env": "PROBE_TOKEN",
                    "next": "/admin/agents",
                },
                allow_redirects=False,
            )
            assert r.status == 302 and r.headers["Location"] == "/admin/agents"
            r = await client.get("/admin/agents")
            page = await r.text()
            assert "probe" in page and "PROBE_TOKEN" in page
            r = await client.post(
                "/admin/agents/delete", data={"name": "probe"}, allow_redirects=False
            )
            assert r.status == 302
            page = await (await client.get("/admin/agents")).text()
            assert "probe" not in page
        finally:
            await client.close()

    run(go())


def test_span_waterfall_survives_parent_cycles(tmp_path):
    """Spans whose parent chain never reaches a root — a parent CYCLE or a
    self-parenting row from corrupted ingestion — must still appear in the
    waterfall as depth-0 rows instead of silently vanishing (ADVICE r4:
    the orphan pass only rescued spans whose parent_id was absent)."""
    import time as _time

    from kakveda_tpu.dashboard.core import CTX_KEY

    async def go():
        app = _mk_app(tmp_path)
        db = app[CTX_KEY].db
        now = _time.time()
        tid = "11111111-2222-3333-4444-555555555555"
        db.execute(
            "INSERT INTO trace_runs (trace_id, ts, app_id, status) VALUES (?,?,?,?)",
            (tid, now, "app-C", "ok"),
        )
        root = db.add_span(tid, "root", now, now + 1.0)
        db.add_span(tid, "child", now + 0.1, now + 0.5, parent_id=root)
        # Parent cycle: A's parent is B, B's parent is A (ids exist, but
        # neither is reachable from a root).
        a = db.add_span(tid, "cyc-a", now + 0.2, now + 0.3, parent_id=10**6)
        b = db.add_span(tid, "cyc-b", now + 0.25, now + 0.35, parent_id=a)
        db.execute("UPDATE trace_spans SET parent_id=? WHERE id=?", (b, a))
        # Self-parenting span.
        s = db.add_span(tid, "self-loop", now + 0.4, now + 0.45, parent_id=10**6)
        db.execute("UPDATE trace_spans SET parent_id=? WHERE id=?", (s, s))

        client = await _client(app)
        try:
            await _login(client)
            detail = await (await client.get(f"/runs/{tid}")).text()
            for name in ("root", "child", "cyc-a", "cyc-b", "self-loop"):
                assert name in detail, f"span {name!r} missing from waterfall"
        finally:
            await client.close()

    run(go())


def test_warnings_initial_render_uses_server_aggregates(tmp_path):
    """The first paint must come from the full-window SQL aggregates, not a
    client re-aggregation of the truncated newest-500 rows (ADVICE r4
    medium): the server-agg JSON is embedded and the script renders from it
    without an unconditional refresh()."""
    import json as _json
    import re

    async def go():
        client = await _client(_mk_app(tmp_path))
        try:
            await _login(client)
            await client.post(
                "/scenarios/run",
                data={"app_id": "app-S",
                      "prompt": "Summarize this and include citations even if not provided."},
                allow_redirects=False,
            )
            body = await (await client.get("/warnings")).text()
            m = re.search(r'<script id="server-agg"[^>]*>(.*?)</script>', body, re.S)
            assert m, "server aggregates JSON missing"
            agg = _json.loads(m.group(1))
            assert sum(n for _, n in agg["by_day"]) >= 1
            assert any(a == "app-S" for a, _ in agg["by_app"])
            # Initial render comes from SERVER data; client refresh() only
            # runs on filter events, so the page must not call it on load.
            script = body[body.index("server-agg"):]
            assert "renderChart(new Map(SERVER.by_day" in script
            assert re.search(r"^\s*refresh\(\);", script, re.M) is None
        finally:
            await client.close()

    run(go())


def test_runs_query_language_operators(tmp_path):
    """Full reference operator set (services/dashboard/app.py:173-221):
    latency_ms> and latency_ms<, project:<name>, and REPEATABLE tag:/label:
    (a run matches any of the listed values)."""
    import time as _time
    import uuid as _uuid

    from kakveda_tpu.dashboard.core import CTX_KEY

    async def go():
        app = _mk_app(tmp_path)
        db = app[CTX_KEY].db
        now = _time.time()
        pid = db.execute(
            "INSERT INTO projects (name, created_at) VALUES (?,?)", ("proj-x", now)
        )
        rows = [
            # (app_id, latency, project_id, tags, label)
            ("app-fast", 100, None, ["prod"], "good"),
            ("app-slow", 5000, pid, ["canary"], "bad"),
            ("app-mid", 1500, None, ["staging"], None),
        ]
        tids = {}
        for app_id, lat, proj, tags, label in rows:
            tid = str(_uuid.uuid4())
            tids[app_id] = tid
            db.execute(
                "INSERT INTO trace_runs (trace_id, ts, app_id, latency_ms, project_id,"
                " tags_json, status) VALUES (?,?,?,?,?,?,?)",
                (tid, now, app_id, lat, proj, __import__("json").dumps(tags), "ok"),
            )
            if label:
                db.execute(
                    "INSERT INTO run_feedback (trace_id, user_email, thumb, label, ts)"
                    " VALUES (?,?,?,?,?)", (tid, "t@local", "up", label, now),
                )

        client = await _client(app)
        try:
            await _login(client)

            async def hits(q):
                body = await (await client.get("/runs", params={"q": q})).text()
                return {a for a in ("app-fast", "app-slow", "app-mid") if a in body}

            assert await hits("latency_ms>2000") == {"app-slow"}
            assert await hits("latency_ms<500") == {"app-fast"}
            assert await hits("latency_ms>500 latency_ms<2000") == {"app-mid"}
            assert await hits("project:proj-x") == {"app-slow"}
            assert await hits("project:no-such") == set()
            # repeatable tag: matches ANY listed value
            assert await hits("tag:prod tag:canary") == {"app-fast", "app-slow"}
            assert await hits("label:good label:bad") == {"app-fast", "app-slow"}
            assert await hits("tag:prod label:bad") == set()  # AND across operators
        finally:
            await client.close()

    run(go())
