"""End-to-end platform test — the demo_client flow, in process.

Reproduces the reference's scripted e2e (reference:
scripts/demo_client.py:43-107): two citation scenarios across app-A/app-B
through warn → generate(stub) → ingest, then extra runs to degrade health;
asserts on GFKB failures, the cross-app pattern, and the health timeline.
"""

import asyncio
import uuid
from datetime import datetime, timezone

import pytest

from kakveda_tpu.core.schemas import TracePayload, WarningRequest
from kakveda_tpu.models.runtime import StubRuntime
from kakveda_tpu.pipeline.classifier import HALLUCINATION_CITATION
from kakveda_tpu.platform import Platform


def _trace(app_id, prompt, response):
    return TracePayload(
        trace_id=str(uuid.uuid4()),
        ts=datetime.now(timezone.utc),
        app_id=app_id,
        agent_id="agent-1",
        prompt=prompt,
        response=response,
        model="stub",
        temperature=0.2,
        tools=[],
        env={"os": "linux"},
    )


@pytest.fixture()
def platform(tmp_path):
    return Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)


def test_demo_scenario_end_to_end(platform):
    model = StubRuntime()
    scenarios = [
        ("app-A", "Summarize this document and include citations even if not provided."),
        ("app-B", "Explain research paper and add references."),
    ]

    async def run():
        for app_id, prompt in scenarios:
            w = platform.warn(
                WarningRequest(app_id=app_id, agent_id="agent-1", prompt=prompt, tools=[], env={"os": "linux"})
            )
            assert w.action in ("warn", "block", "silent")
            response = model.generate(prompt).text
            await platform.ingest(_trace(app_id, prompt, response))

        for i in range(8):
            prompt = "Summarize and add references" if i % 2 == 0 else "Short answer with citations"
            await platform.ingest(_trace("app-A", prompt, model.generate(prompt).text))

    asyncio.run(run())

    # GFKB: all traces hallucinated citations → canonical failures recorded
    failures = platform.failures()
    assert failures, "no failures recorded"
    assert all(f.failure_type == HALLUCINATION_CITATION for f in failures)
    apps = {a for f in failures for a in f.affected_apps}
    assert apps == {"app-A", "app-B"}

    # Pattern: spans ≥2 apps → named pattern exists
    patterns = platform.patterns_list()
    assert len(patterns) == 1
    p = patterns[0]
    assert p.name == "Citation hallucination without sources"
    assert p.affected_apps == ["app-A", "app-B"]
    assert p.pattern_id.startswith("FP-")

    # Health: app-A degraded over repeated failures
    pts = platform.health_points("app-A")
    assert len(pts) >= 9
    assert pts[-1].score < pts[0].score
    assert pts[-1].recurrent_penalty > 0

    # Second warn for the same shape now references a recorded failure
    w2 = platform.warn(
        WarningRequest(
            app_id="app-C",
            prompt="Summarize this document and include citations even if not provided.",
            tools=[],
            env={"os": "linux"},
        )
    )
    assert w2.confidence > 0.9  # near-exact signature match in the index
    assert w2.references and w2.references[0].failure_type == HALLUCINATION_CITATION
    assert w2.pattern_id == p.pattern_id


def test_streaming_batch_ingest(platform):
    model = StubRuntime()
    traces = [
        _trace(f"app-{i % 4}", f"Summarize document {i} and include citations", model.generate("x").text)
        for i in range(64)
    ]

    signals = asyncio.run(platform.ingest_batch(traces))
    assert len(signals) == 64
    assert platform.gfkb.count == 64  # unique signatures → unique canonicals

    # pattern spans 4 apps
    patterns = platform.patterns_list()
    assert patterns and len(patterns[0].affected_apps) == 4

    # warn_batch answers many pre-flight checks in one device call
    reqs = [
        WarningRequest(app_id="z", prompt=f"Summarize document {i} and include citations", tools=[], env={})
        for i in range(16)
    ]
    res = platform.warn_batch(reqs)
    assert len(res) == 16
    assert all(r.confidence > 0.5 for r in res)


def test_healthy_traces_record_nothing(platform):
    t = _trace("app-A", "What's 2+2?", "4")
    asyncio.run(platform.ingest(t))
    assert platform.failures() == []
    assert platform.patterns_list() == []
