"""Featurizer tests: determinism, normalization, and the similarity-ordering
invariant the reference tests (reference: tests/test_similarity.py:4-12)."""

import numpy as np

from kakveda_tpu.core.fingerprint import signature_text
from kakveda_tpu.ops.featurizer import HashedNGramFeaturizer


def _sig(prompt):
    return signature_text(prompt, [], {"os": "linux"})


def test_deterministic_across_instances():
    a = HashedNGramFeaturizer(1024).encode("hello world citations")
    b = HashedNGramFeaturizer(1024).encode("hello world citations")
    np.testing.assert_array_equal(a, b)


def test_rows_are_l2_normalized():
    f = HashedNGramFeaturizer(2048)
    v = f.encode_batch([_sig("Summarize with citations"), _sig("explain stuff")])
    norms = np.linalg.norm(v, axis=1)
    np.testing.assert_allclose(norms, 1.0, atol=1e-5)


def test_empty_text_is_zero_vector():
    v = HashedNGramFeaturizer(1024).encode("")
    assert float(np.linalg.norm(v)) == 0.0


def test_ordering_invariant_citation_query():
    """Citation-ish query must score the citation corpus doc above the
    unrelated doc — the reference's core similarity invariant."""
    f = HashedNGramFeaturizer(2048)
    query = _sig("Explain research paper and add references.")
    citation_doc = _sig("Summarize this document and include citations even if not provided.")
    unrelated_doc = _sig("What's the best pasta recipe?")
    q, c, u = f.encode_batch([query, citation_doc, unrelated_doc])
    assert float(q @ c) > float(q @ u)
    assert float(q @ c) > 0.15
    assert float(q @ u) < 0.1


def test_dim_must_be_power_of_two():
    import pytest

    with pytest.raises(ValueError):
        HashedNGramFeaturizer(1000)


def test_free_form_text_embeds():
    f = HashedNGramFeaturizer(1024)
    v1 = f.encode("the quick brown fox")
    v2 = f.encode("the quick brown fox")
    v3 = f.encode("totally different words entirely")
    assert float(v1 @ v2) > 0.99
    assert float(v1 @ v3) < 0.3
