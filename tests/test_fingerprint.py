"""Fingerprint semantics parity tests.

The intent-tag vocabulary and signature layout must be stable — they are the
primary similarity signal (reference: services/shared/fingerprint.py:22-66).
"""

from kakveda_tpu.core.fingerprint import (
    detect_citation_markers,
    fingerprint,
    normalize_prompt,
    prompt_intent_tags,
    signature_text,
)


def test_normalize_prompt():
    assert normalize_prompt("  Hello\t World \n") == "hello world"


def test_intent_tags_citations_summarize():
    tags = prompt_intent_tags("Summarize this document and include citations even if not provided.")
    assert tags == [
        "constraint:no_sources_provided",
        "instruction:include_references",
        "intent:citations_required",
        "task:summarization",
    ]


def test_intent_tags_explanation_references():
    tags = prompt_intent_tags("Explain research paper and add references.")
    assert tags == ["intent:citations_required", "task:explanation"]


def test_intent_tags_empty_for_unrelated():
    assert prompt_intent_tags("What is the weather in Paris?") == []


def test_signature_text_is_app_agnostic_and_stable():
    s1 = signature_text("Summarize with citations", ["search"], {"os": "linux"})
    s2 = signature_text("Summarize  with   CITATIONS", ["search"], {"os": "linux"})
    assert s1 == s2  # normalization collapses case/whitespace
    assert "intent_tags:" in s1 and "prompt_hint:" in s1
    assert "tools:search" in s1 and "env_keys:os" in s1


def test_signature_sorts_tools_and_env_keys():
    a = signature_text("hi", ["b", "a", "a"], {"z": 1, "a": 2})
    assert "tools:a,b" in a
    assert "env_keys:a,z" in a


def test_fingerprint_is_16_hex():
    fp = fingerprint("Summarize with citations", [], {})
    assert len(fp) == 16
    int(fp, 16)  # parses as hex


def test_citation_markers():
    assert detect_citation_markers("See [1] for details").has_citation_markers
    assert detect_citation_markers("(Smith, 2020) argued...").has_citation_markers
    assert detect_citation_markers("doi: 10.1000/xyz").has_citation_markers
    assert detect_citation_markers("References:\n[stuff]").has_citation_markers
    assert detect_citation_markers("A Bibliography section").has_citation_markers
    assert not detect_citation_markers("Just a plain answer").has_citation_markers
    assert not detect_citation_markers("").has_citation_markers
