"""Replica-fleet tests (kakveda_tpu/fleet/, docs/scale-out.md):
consistent-hash properties, router sharding/ejection/retry, control-state
gossip feeding the brownout ladder, idempotent bus-replicated ingest, and
the kill-one-replica chaos drill over real subprocess replicas."""

import asyncio
import json
import time
import uuid
from datetime import datetime, timezone

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from kakveda_tpu.core import admission as _adm
from kakveda_tpu.core import faults
from kakveda_tpu.fleet.hashring import HashRing


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# hash ring properties
# ---------------------------------------------------------------------------

KEYS = [f"app-{i}" for i in range(2000)]


def test_hashring_stable_across_instances():
    """Assignment is a pure function of (key, membership): a restarted
    router (fresh ring object) must route every key identically —
    Python's salted hash() would not."""
    nodes = [f"r{i}" for i in range(4)]
    a, b = HashRing(nodes), HashRing(list(reversed(nodes)))
    for k in KEYS[:500]:
        assert a.assign(k) == b.assign(k)
        assert a.preference(k) == b.preference(k)


def test_hashring_remap_fraction_on_replica_loss():
    """Removing one of N nodes remaps ~1/N of keys — and ONLY keys the
    lost node owned (everyone else keeps their assignment)."""
    nodes = [f"r{i}" for i in range(4)]
    ring = HashRing(nodes)
    smaller = HashRing([n for n in nodes if n != "r2"])
    moved = 0
    for k in KEYS:
        before, after = ring.assign(k), smaller.assign(k)
        if before != "r2":
            assert after == before  # survivors keep their keys
        else:
            moved += 1
    # E[moved] = 1/4; allow generous slack for vnode variance.
    assert 0.10 < moved / len(KEYS) < 0.45, moved / len(KEYS)


def test_hashring_balance_and_exclusion():
    ring = HashRing([f"r{i}" for i in range(4)])
    counts = {}
    for k in KEYS:
        counts[ring.assign(k)] = counts.get(ring.assign(k), 0) + 1
    assert len(counts) == 4
    assert max(counts.values()) / (len(KEYS) / 4) < 2.0, counts
    # Ejection spills to the failover successor, never to nothing.
    k = KEYS[0]
    owner = ring.assign(k)
    spill = ring.assign(k, exclude=(owner,))
    assert spill is not None and spill != owner
    assert ring.assign(k, exclude=tuple(ring.nodes)) is None


# ---------------------------------------------------------------------------
# fleet view + gossip → brownout input
# ---------------------------------------------------------------------------


def _sample(replica="rX", seq=1, occ=0.0, **kw):
    s = {
        "replica": replica, "seq": seq, "ts": time.time(),
        "occupancy": occ, "brownout": "normal", "brownout_step": 0,
        "degraded": False,
    }
    s.update(kw)
    return s


def test_fleet_view_freshness_discipline():
    from kakveda_tpu.fleet.gossip import FleetView

    view = FleetView(ttl_s=0.4)
    assert view.fold(_sample(seq=2, occ=0.5))
    # seq regress = at-least-once redelivery / DLQ replay: dropped.
    assert not view.fold(_sample(seq=2, occ=0.9))
    assert not view.fold(_sample(seq=1, occ=0.9))
    assert view.fleet_pressure() == pytest.approx(0.5)
    # Stale wall-clock ts (a replayed ancient sample): dropped.
    assert not view.fold(_sample(replica="rY", seq=9, ts=time.time() - 60))
    # TTL expiry: a silent peer stops contributing pressure.
    time.sleep(0.5)
    assert view.fleet_pressure() == 0.0
    assert view.peers() == {}
    # Degraded + worst-brownout folds.
    assert view.fold(_sample(replica="rZ", seq=1, occ=0.2, degraded=True,
                             brownout="clamped", brownout_step=2))
    assert view.any_degraded()
    assert view.worst_brownout() == {"state": "clamped", "step": 2}


def test_fleet_pressure_drives_local_ladder():
    """The gossip input steps the LOCAL ladder (fleet-wide brownout)
    through the sanctioned note_pressure path, and expires so a dead
    peer cannot pin the fleet browned-out."""
    brown = _adm.BrownoutController(enabled=True, enter=0.85, exit=0.3, dwell_s=0.0)
    adm = _adm.AdmissionController(
        limits={"warn": 4, "ingest": 2, "interactive": 2, "background": 1},
        enabled=True, brownout=brown,
    )
    adm.note_fleet_pressure(0.95, ttl_s=0.3)
    assert brown.step == 1  # no_spec — fleet-wide degradation
    assert adm.pressure() == pytest.approx(0.95)
    assert adm.info()["fleet_pressure"] == pytest.approx(0.95)
    time.sleep(0.35)
    assert adm.pressure() == 0.0  # floor expired
    adm.note_fleet_pressure(0.0, ttl_s=1.0)
    assert brown.step == 0  # stepped back down


def test_gossip_endpoint_feeds_private_admission(tmp_path):
    """POST /fleet/gossip folds a peer sample and the ladder follows —
    end to end through the service app, with a private controller."""
    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app

    brown = _adm.BrownoutController(enabled=True, enter=0.85, exit=0.3, dwell_s=0.0)
    adm = _adm.AdmissionController(enabled=True, brownout=brown)
    plat = Platform(data_dir=tmp_path / "d", capacity=256, dim=1024)
    app = make_app(platform=plat, admission=adm)

    async def go(client):
        r = await client.post("/fleet/gossip", json=_sample(seq=1, occ=0.97))
        body = await r.json()
        assert r.status == 200 and body["fresh"]
        assert brown.state == "no_spec"
        r = await client.get("/readyz")
        ready = await r.json()
        assert ready["admission"]["fleet_pressure"] == pytest.approx(0.97)
        assert ready["fleet"]["view"]["rX"]["occupancy"] == pytest.approx(0.97)
        # Replayed sample: not fresh, no double effect.
        r = await client.post("/fleet/gossip", json=_sample(seq=1, occ=0.97))
        assert not (await r.json())["fresh"]

    run(_with_client(app, go))


# ---------------------------------------------------------------------------
# replication: idempotent apply + bus fan-in
# ---------------------------------------------------------------------------


def _rows(n, tag):
    return [
        {
            "failure_type": "TIMEOUT",
            "signature_text": f"{tag} timeout calling service {i}",
            "app_id": f"app-{i % 4}",
            "impact_severity": "medium",
            "context_signature": {},
            "root_cause": None,
            "resolution": None,
        }
        for i in range(n)
    ]


def test_gfkb_apply_replication_idempotent_across_restart(tmp_path):
    from kakveda_tpu.index.gfkb import GFKB

    kb = GFKB(data_dir=tmp_path / "d", capacity=128, dim=512)
    assert kb.apply_replication(_rows(4, "ev1"), "evt-1") == 4
    assert kb.count == 4
    # Double delivery: the regression the invariant demands — no double
    # insert, no occurrence inflation.
    assert kb.apply_replication(_rows(4, "ev1"), "evt-1") == 0
    assert kb.count == 4
    assert all(r.occurrences == 1 for r in kb.list_failures())
    kb.close()
    # The dedup set survives restart (applied_events.jsonl replays).
    kb2 = GFKB(data_dir=tmp_path / "d", capacity=128, dim=512)
    assert kb2.count == 4
    assert kb2.apply_replication(_rows(4, "ev1"), "evt-1") == 0
    assert all(r.occurrences == 1 for r in kb2.list_failures())
    # A new event id applies normally.
    assert kb2.apply_replication(_rows(2, "ev2"), "evt-2") == 2
    assert kb2.count == 6
    kb2.close()


def _trace(app_id, prompt):
    from kakveda_tpu.models.runtime import STUB_RESPONSE

    return {
        "trace_id": str(uuid.uuid4()),
        "ts": datetime.now(timezone.utc).isoformat(),
        "app_id": app_id,
        "agent_id": "agent-1",
        "prompt": prompt,
        "response": STUB_RESPONSE,
        "model": "stub",
        "tools": [],
        "env": {"os": "linux"},
    }


async def _with_client(app, fn):
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        return await fn(client)
    finally:
        await client.close()


def test_ingest_replicates_to_peer_and_dedups(tmp_path):
    """Ingest accepted by replica A fans in to replica B over the bus
    topic; a duplicate POST of the same event (redelivery) is a no-op."""
    from kakveda_tpu.events.bus import TOPIC_GFKB_REPLICATE
    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app

    plat_a = Platform(data_dir=tmp_path / "a", capacity=256, dim=1024)
    plat_b = Platform(data_dir=tmp_path / "b", capacity=256, dim=1024)

    async def go():
        app_a, app_b = make_app(platform=plat_a), make_app(platform=plat_b)
        ca, cb = TestClient(TestServer(app_a)), TestClient(TestServer(app_b))
        await ca.start_server()
        await cb.start_server()
        try:
            plat_a.bus.subscribe(
                TOPIC_GFKB_REPLICATE, str(cb.make_url("/replicate"))
            )
            traces = [
                _trace(f"app-{i % 3}", f"Cite sources for claim {i} even if unavailable.")
                for i in range(8)
            ]
            r = await ca.post("/ingest/batch", json={"traces": traces})
            body = await r.json()
            assert r.status == 200 and body["failures"] >= 1
            assert plat_b.gfkb.count == plat_a.gfkb.count > 0
            occ_before = [rec.occurrences for rec in plat_b.gfkb.list_failures()]

            # Redeliver the same event by hand — dedup by event id.
            evt = {"id": "dup-evt", "rows": _rows(3, "dup"), "ts": time.time()}
            r = await cb.post("/replicate", json=evt)
            assert (await r.json())["applied"] == 3
            r = await cb.post("/replicate", json=evt)
            body = await r.json()
            assert body["applied"] == 0 and body["deduped"]
            assert [rec.occurrences for rec in plat_b.gfkb.list_failures()][
                : len(occ_before)
            ] == occ_before
            # Malformed: typed 422, never a 500.
            r = await cb.post("/replicate", json={"rows": "nope"})
            assert r.status == 422
        finally:
            await ca.close()
            await cb.close()

    run(go())


@pytest.mark.chaos
def test_replicate_apply_fault_dead_letters_then_replay(tmp_path, monkeypatch):
    """Armed fleet.replicate_apply: the peer's apply 500s, the origin bus
    exhausts retries and dead-letters the event; disarm + `dlq replay`
    converges the peer — at-least-once, never a lost row."""
    monkeypatch.setenv("KAKVEDA_BUS_RETRIES", "2")
    monkeypatch.setenv("KAKVEDA_BUS_RETRY_BASE", "0.01")
    faults.disarm()
    from kakveda_tpu.events.bus import TOPIC_GFKB_REPLICATE, replay_dlq_file
    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app

    plat_a = Platform(data_dir=tmp_path / "a", capacity=256, dim=1024)
    plat_b = Platform(data_dir=tmp_path / "b", capacity=256, dim=1024)
    dlq = tmp_path / "a" / "dlq.jsonl"

    async def go():
        ca = TestClient(TestServer(make_app(platform=plat_a)))
        cb = TestClient(TestServer(make_app(platform=plat_b)))
        await ca.start_server()
        await cb.start_server()
        try:
            plat_a.bus.subscribe(
                TOPIC_GFKB_REPLICATE, str(cb.make_url("/replicate"))
            )
            faults.arm("fleet.replicate_apply:1.0:-1")
            traces = [
                _trace("app-x", f"Cite sources for claim {i} even if unavailable.")
                for i in range(4)
            ]
            r = await ca.post("/ingest/batch", json={"traces": traces})
            assert r.status == 200  # origin ingest NEVER fails on peer loss
            assert plat_a.gfkb.count > 0
            assert plat_b.gfkb.count == 0  # apply died while armed
            assert dlq.exists() and dlq.read_text().strip()
        finally:
            await ca.close()
            # replay while B is still up but the fault disarmed (off-loop:
            # the replay's sync POSTs target a server on THIS loop)
            faults.disarm()
            out = await asyncio.get_running_loop().run_in_executor(
                None, lambda: replay_dlq_file(dlq, timeout=5.0)
            )
            assert out["failed"] == 0 and out["replayed"] >= 1
            assert plat_b.gfkb.count == plat_a.gfkb.count
            await cb.close()

    run(go())
    _adm.reset_for_tests()


def test_ephemeral_topic_never_dead_letters(tmp_path):
    """fleet.control is gossip: single-attempt delivery, no DLQ — a dead
    peer costs one failed POST per tick, not a dead-letter flood."""
    from kakveda_tpu.events.bus import TOPIC_FLEET_CONTROL, EventBus

    bus = EventBus(
        delivery_timeout=0.5, persist_path=tmp_path / "subs.jsonl",
    )
    bus.mark_ephemeral(TOPIC_FLEET_CONTROL)
    dead = "http://127.0.0.1:9/fleet/gossip"  # port 9: connection refused
    bus.subscribe(TOPIC_FLEET_CONTROL, dead)
    bus.subscribe("real.topic", dead)
    assert bus.url_subscribers(TOPIC_FLEET_CONTROL) == [dead]

    async def go():
        delivered = await bus.publish(TOPIC_FLEET_CONTROL, _sample())
        assert delivered == 0
        assert not (tmp_path / "dlq.jsonl").exists()  # no DLQ for gossip
        # The same endpoint on a NON-ephemeral topic still dead-letters.
        await bus.publish("real.topic", {"x": 1})
        assert (tmp_path / "dlq.jsonl").exists()

    run(go())


# ---------------------------------------------------------------------------
# router: sharding, ejection, retry-on-next
# ---------------------------------------------------------------------------


def _stub_backend(name, seen, *, fail_with=None, gfkb_count=7):
    """A minimal replica double: records warn app_ids, answers the
    /readyz shape the router's probe reads."""
    app = web.Application()

    async def warn(request):
        body = await request.json()
        if fail_with is not None:
            return web.json_response({"ok": False}, status=fail_with)
        seen.setdefault(name, []).append(body.get("app_id"))
        return web.json_response(
            {"action": "silent", "confidence": 0.0, "references": [],
             "served_by": name}
        )

    async def readyz(request):
        return web.json_response(
            {"ok": True, "gfkb_count": gfkb_count,
             "admission": {"brownout": "normal", "brownout_step": 0},
             "device": {"degraded": False}}
        )

    async def shed(request):
        return web.json_response(
            {"ok": False, "error": "shed", "retry_after": 2.0},
            status=429, headers={"Retry-After": "2"},
        )

    app.add_routes([
        web.post("/warn", warn),
        web.get("/readyz", readyz),
        web.post("/ingest", shed),
    ])
    return app


def test_router_shards_by_app_key_with_affinity(tmp_path):
    from kakveda_tpu.fleet.router import make_router_app

    seen: dict = {}

    async def go():
        b0 = TestClient(TestServer(_stub_backend("b0", seen)))
        b1 = TestClient(TestServer(_stub_backend("b1", seen)))
        await b0.start_server()
        await b1.start_server()
        router = make_router_app(
            {"r0": str(b0.make_url("")).rstrip("/"),
             "r1": str(b1.make_url("")).rstrip("/")},
            probe_interval_s=30.0, eject_fails=3, retries=1,
        )
        rc = TestClient(TestServer(router))
        await rc.start_server()
        try:
            owners = {}
            for i in range(32):
                app_id = f"app-{i % 16}"
                r = await rc.post("/warn", json={"app_id": app_id, "prompt": "x"})
                assert r.status == 200
                owners.setdefault(app_id, set()).add(
                    (await r.json())["served_by"]
                )
            # Affinity: every app key always lands on ONE replica…
            assert all(len(v) == 1 for v in owners.values()), owners
            # …and 16 keys spread over both replicas.
            assert len(seen) == 2, seen
            # 429 passes through untouched (a shed is a verdict, not a
            # router failure) with its Retry-After intact.
            r = await rc.post("/ingest", json={"trace": {"app_id": "a"}})
            assert r.status == 429 and r.headers["Retry-After"] == "2"
        finally:
            await rc.close()
            await b0.close()
            await b1.close()

    run(go())


def test_router_retries_next_replica_and_ejects_dead(tmp_path):
    """One backend is a closed port: every request still answers (from
    the survivor), and after eject_fails consecutive failures the dead
    replica is ejected — /readyz reports it."""
    from kakveda_tpu.fleet.router import ROUTER_KEY, make_router_app

    seen: dict = {}

    async def go():
        live = TestClient(TestServer(_stub_backend("live", seen)))
        await live.start_server()
        router_app = make_router_app(
            {"r0": "http://127.0.0.1:9",  # connection refused
             "r1": str(live.make_url("")).rstrip("/")},
            probe_interval_s=30.0, eject_fails=2, retries=1, timeout_s=3.0,
        )
        rc = TestClient(TestServer(router_app))
        await rc.start_server()
        try:
            for i in range(12):
                r = await rc.post(
                    "/warn", json={"app_id": f"app-{i}", "prompt": "x"}
                )
                assert r.status == 200  # zero lost warns
                assert (await r.json())["served_by"] == "live"
            router = router_app[ROUTER_KEY]
            assert "r0" in router.ejected()
            r = await rc.get("/readyz")
            rep = await r.json()
            assert rep["ok"]
            assert rep["replicas"]["r0"]["ejected"] is True
            assert rep["replicas"]["r1"]["healthy"] is True
            assert rep["fleet"]["healthy"] == 1
        finally:
            await rc.close()
            await live.close()

    run(go())


@pytest.mark.chaos
def test_router_forward_fault_reroutes(tmp_path):
    """Armed router.forward (count=1): the first forward attempt dies
    like a transport error and the SAME request answers from the next
    replica — the retry path proven without killing a process."""
    from kakveda_tpu.fleet.router import make_router_app

    faults.disarm()
    seen: dict = {}

    async def go():
        b0 = TestClient(TestServer(_stub_backend("b0", seen)))
        b1 = TestClient(TestServer(_stub_backend("b1", seen)))
        await b0.start_server()
        await b1.start_server()
        router = make_router_app(
            {"r0": str(b0.make_url("")).rstrip("/"),
             "r1": str(b1.make_url("")).rstrip("/")},
            probe_interval_s=30.0, eject_fails=5, retries=1,
        )
        rc = TestClient(TestServer(router))
        await rc.start_server()
        try:
            faults.arm("router.forward:1.0:1")
            r = await rc.post("/warn", json={"app_id": "app-z", "prompt": "x"})
            assert r.status == 200
        finally:
            faults.disarm()
            await rc.close()
            await b0.close()
            await b1.close()

    run(go())


def test_cli_parser_fleet_flags():
    from kakveda_tpu.cli.main import build_parser

    args = build_parser().parse_args(
        ["up", "--replicas", "4", "--port-base", "9000", "--dir", "/tmp/x"]
    )
    assert args.replicas == 4 and args.port_base == 9000
    assert args.replica_index is None


# ---------------------------------------------------------------------------
# the kill-one-replica chaos drill (real subprocess replicas)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_kill_one_replica_drill(tmp_path):
    """SIGTERM one of two replicas mid-load: zero lost warns (the router
    re-routes every request to the survivor), the dead replica's GFKB gap
    is healed by DLQ replay after restart, and the fleet state
    re-converges (router /readyz healthy, ladder normal)."""
    import yaml

    from kakveda_tpu.events.bus import replay_dlq_file
    from kakveda_tpu.fleet.router import ROUTER_KEY, make_router_app
    from kakveda_tpu.fleet.supervisor import FleetSupervisor, pick_port_base

    root = tmp_path / "fleet"
    root.mkdir()
    cfg = root / "config.yaml"
    cfg.write_text(yaml.safe_dump({
        "failure_matching": {
            "similarity_threshold": 0.8, "embedding_dim": 512, "top_k": 5,
        },
    }))
    sup = FleetSupervisor(
        root,
        port_base=pick_port_base(2),
        replicas=2,
        env={
            "JAX_PLATFORMS": "cpu",
            "KAKVEDA_CONFIG_PATH": str(cfg),
            "KAKVEDA_INDEX_CAPACITY": "1024",
            "KAKVEDA_FLEET_GOSSIP_S": "0.2",
            "KAKVEDA_BUS_RETRIES": "2",
            "KAKVEDA_BUS_RETRY_BASE": "0.01",
            "KAKVEDA_GC_TUNE": "0",
        },
    )
    import httpx

    gap_prompt = "Cite sources for the postmortem gap report even if unavailable."

    async def go():
        router_app = make_router_app(
            sup.backend_map(), probe_interval_s=0.3, eject_fails=2,
            retries=1, timeout_s=10.0,
        )
        rc = TestClient(TestServer(router_app))
        await rc.start_server()
        statuses: list = []
        stop = asyncio.Event()
        task = None

        def _reroutes():
            from kakveda_tpu.core import metrics as _metrics

            fam = _metrics.get_registry().snapshot().get(
                "kakveda_fleet_reroutes_total", {}
            )
            return sum(
                v for v in fam.get("series", {}).values()
                if isinstance(v, (int, float))
            )

        async def storm():
            i = 0
            while not stop.is_set():
                r = await rc.post("/warn", json={
                    "app_id": f"app-{i % 16}",
                    "prompt": f"Cite sources for claim {i}.",
                })
                await r.read()
                statuses.append(r.status)
                i += 1
                await asyncio.sleep(0.01)

        try:
            # Seed through the router; replication converges both replicas.
            traces = [
                _trace(f"app-{i % 8}",
                       f"Cite sources for claim {i} even if unavailable.")
                for i in range(16)
            ]
            r = await rc.post("/ingest/batch", json={"traces": traces})
            assert r.status == 200, await r.text()
            counts = []
            for u in sup.urls():
                for _ in range(40):
                    n = httpx.get(u + "/readyz", timeout=5).json()["gfkb_count"]
                    if n > 0:
                        break
                    await asyncio.sleep(0.25)
                counts.append(n)
            assert counts[0] == counts[1] > 0, counts

            reroutes_before = _reroutes()
            task = asyncio.create_task(storm())
            await asyncio.sleep(1.0)
            sup.stop(1)  # SIGTERM replica 1 mid-load
            await asyncio.sleep(2.0)  # router re-routes around the corpse

            # Gap ingest DIRECT to the survivor: its bus delivery to the
            # dead peer exhausts retries and dead-letters.
            r = await rc.post("/ingest/batch", json={
                "traces": [_trace("app-gap", gap_prompt)]
            })
            assert r.status == 200
            dlq = sup.data_dir(0) / "dlq.jsonl"
            for _ in range(60):
                if dlq.exists() and dlq.read_text().strip():
                    break
                await asyncio.sleep(0.25)
            assert dlq.exists() and dlq.read_text().strip(), "no DLQ record"

            stop.set()
            await task
            # ZERO lost warns: every request during the kill answered 200.
            assert statuses and all(s == 200 for s in statuses), (
                len(statuses), [s for s in statuses if s != 200][:5]
            )
            router = router_app[ROUTER_KEY]
            assert _reroutes() > reroutes_before  # reroute path exercised

            # Restart the dead replica: it replays its own log (gap rows
            # missing), then DLQ replay converges it.
            sup.start(1)
            await asyncio.get_running_loop().run_in_executor(
                None, sup.wait_ready, 240.0
            )
            n0 = httpx.get(sup.url(0) + "/readyz", timeout=5).json()["gfkb_count"]
            n1 = httpx.get(sup.url(1) + "/readyz", timeout=5).json()["gfkb_count"]
            assert n1 < n0, (n0, n1)  # the gap is real before replay
            out = replay_dlq_file(dlq, timeout=10.0)
            assert out["failed"] == 0 and out["replayed"] >= 1, out
            n1 = httpx.get(sup.url(1) + "/readyz", timeout=5).json()["gfkb_count"]
            assert n1 == n0, (n0, n1)  # healed

            # The healed replica answers a warn for a gap-row signature.
            r = httpx.post(sup.url(1) + "/warn", json={
                "app_id": "probe", "prompt": gap_prompt,
            }, timeout=30)
            assert r.status_code == 200
            body = r.json()
            assert body["references"], body

            # Fleet state re-converged: probes see both healthy + normal.
            await router.probe_once()
            rep = router.report()
            assert rep["fleet"]["healthy"] == 2, rep
            assert rep["fleet"]["brownout"] == "normal", rep
        finally:
            stop.set()
            if task is not None and not task.done():
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
            await rc.close()

    try:
        sup.start_all()
        sup.wait_ready(timeout_s=300.0)
        run(go())
    finally:
        sup.stop_all()
