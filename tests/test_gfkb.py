"""GFKB behavior tests: versioned upsert, match semantics, patterns,
replay-from-log (reference behaviors: services/gfkb/app.py:79-198)."""

import numpy as np

from kakveda_tpu.core.fingerprint import signature_text
from kakveda_tpu.core.schemas import Severity
from kakveda_tpu.index.gfkb import GFKB


def _sig(prompt):
    return signature_text(prompt, [], {"os": "linux"})


def _mk(tmp_path, **kw):
    kw.setdefault("capacity", 64)
    kw.setdefault("dim", 1024)
    return GFKB(data_dir=tmp_path / "data", **kw)


def test_upsert_creates_then_versions(tmp_path):
    kb = _mk(tmp_path)
    sig = _sig("Summarize with citations")
    rec, created = kb.upsert_failure(
        failure_type="HALLUCINATION_CITATION",
        signature_text=sig,
        app_id="app-A",
        impact_severity=Severity.medium,
        root_cause="rc",
        resolution="fix",
    )
    assert created and rec.failure_id == "F-0001" and rec.version == 1

    rec2, created2 = kb.upsert_failure(
        failure_type="HALLUCINATION_CITATION",
        signature_text=sig,
        app_id="app-B",
        impact_severity=Severity.medium,
    )
    assert not created2
    assert rec2.failure_id == "F-0001"
    assert rec2.version == 2
    assert rec2.occurrences == 2
    assert rec2.affected_apps == ["app-A", "app-B"]
    assert rec2.root_cause == "rc"  # evolving knowledge: old value kept


def test_match_empty_index(tmp_path):
    kb = _mk(tmp_path)
    assert kb.match(_sig("anything")) == []


def test_match_ranks_similar_first(tmp_path):
    kb = _mk(tmp_path)
    kb.upsert_failure(
        failure_type="HALLUCINATION_CITATION",
        signature_text=_sig("Summarize this document and include citations even if not provided."),
        app_id="app-A",
        impact_severity=Severity.medium,
        resolution="say no sources",
    )
    kb.upsert_failure(
        failure_type="TIMEOUT",
        signature_text=_sig("Transcode this video file to mp4 format please"),
        app_id="app-C",
        impact_severity=Severity.low,
    )
    matches = kb.match(_sig("Explain research paper and add references."))
    assert matches
    assert matches[0].failure_type == "HALLUCINATION_CITATION"
    assert matches[0].suggested_mitigation == "say no sources"
    assert matches[0].score > 0.1


def test_match_type_post_filter(tmp_path):
    kb = _mk(tmp_path)
    kb.upsert_failure(
        failure_type="HALLUCINATION_CITATION",
        signature_text=_sig("Summarize with citations"),
        app_id="a",
        impact_severity=Severity.medium,
    )
    assert kb.match(_sig("Summarize with citations"), failure_type="OTHER") == []


def test_match_type_pre_filter_returns_k(tmp_path):
    """VERDICT item 8: with type_filter="pre" a type-filtered match returns
    k hits whenever ≥k failures of that type exist — even when failures of
    OTHER types score higher (where the reference-compatible "post" mode
    returns fewer, reference: services/gfkb/app.py:89-91)."""
    kb = _mk(tmp_path)
    # 6 near-identical OTHER failures that will dominate raw top-5...
    for i in range(6):
        kb.upsert_failure(
            failure_type="OTHER",
            signature_text=_sig(f"Summarize the annual report with citations please v{i}"),
            app_id=f"app-{i}",
            impact_severity=Severity.low,
        )
    # ...and 5 weaker-matching HALLUCINATION_CITATION failures.
    for i in range(5):
        kb.upsert_failure(
            failure_type="HALLUCINATION_CITATION",
            signature_text=_sig(f"Write about topic {i} including citations"),
            app_id=f"app-h{i}",
            impact_severity=Severity.medium,
        )
    query = _sig("Summarize the annual report with citations please v0")
    post = kb.match(query, failure_type="HALLUCINATION_CITATION", type_filter="post")
    pre = kb.match(query, failure_type="HALLUCINATION_CITATION", type_filter="pre")
    assert len(pre) == 5
    assert all(m.failure_type == "HALLUCINATION_CITATION" for m in pre)
    assert len(post) < len(pre)  # the documented reference behavior loses hits
    # unknown type: pre returns empty, not an error
    assert kb.match(query, failure_type="NEVER_SEEN", type_filter="pre") == []


def test_match_during_concurrent_growth(tmp_path):
    """Capacity growth re-embeds off the write lock; matches issued during
    a growth storm must stay correct (never silently empty/wrong)."""
    import threading

    kb = GFKB(data_dir=tmp_path / "g", capacity=8, dim=512)
    sig = _sig("Summarize with citations baseline")
    kb.upsert_failure(
        failure_type="HALLUCINATION_CITATION",
        signature_text=sig,
        app_id="a0",
        impact_severity=Severity.medium,
    )
    errors = []

    def grower():
        try:
            for i in range(200):
                kb.upsert_failure(
                    failure_type="OTHER",
                    signature_text=_sig(f"filler row {i} to force doubling"),
                    app_id="b",
                    impact_severity=Severity.low,
                )
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    t = threading.Thread(target=grower)
    t.start()
    try:
        while t.is_alive():
            hits = kb.match(sig)
            assert hits and hits[0].score > 0.99, hits
    finally:
        t.join()
    assert not errors, errors
    assert kb.count == 201
    # and the index is still exact post-growth
    hits = kb.match(sig)
    assert hits and hits[0].score > 0.99


def test_batch_upsert_and_batch_match(tmp_path):
    kb = _mk(tmp_path)
    items = [
        dict(
            failure_type="HALLUCINATION_CITATION",
            signature_text=_sig(f"Summarize doc {i} with citations"),
            app_id=f"app-{i % 3}",
            impact_severity="medium",
        )
        for i in range(20)
    ]
    out = kb.upsert_failures_batch(items)
    assert sum(1 for _, c in out if c) == 20
    assert kb.count == 20

    results = kb.match_batch([_sig("Summarize doc 5 with citations"), _sig("unrelated pasta recipe")])
    assert len(results) == 2
    assert results[0][0].score > results[1][0].score if results[1] else True


def test_replay_from_jsonl(tmp_path):
    data = tmp_path / "data"
    kb = GFKB(data_dir=data, capacity=64, dim=1024)
    sig = _sig("Summarize with citations")
    kb.upsert_failure(
        failure_type="HALLUCINATION_CITATION",
        signature_text=sig,
        app_id="app-A",
        impact_severity=Severity.medium,
    )
    kb.upsert_failure(
        failure_type="HALLUCINATION_CITATION",
        signature_text=sig,
        app_id="app-B",
        impact_severity=Severity.medium,
    )
    kb.upsert_pattern(name="P", failure_ids=["F-0001"], affected_apps=["app-A", "app-B"])

    kb2 = GFKB(data_dir=data, capacity=64, dim=1024)
    assert kb2.count == 1
    rec = kb2.list_failures()[0]
    assert rec.version == 2 and rec.occurrences == 2
    assert len(kb2.list_patterns()) == 1
    m = kb2.match(sig)
    assert m and m[0].failure_id == "F-0001" and m[0].score > 0.99


def test_capacity_growth(tmp_path):
    kb = GFKB(data_dir=tmp_path / "data", capacity=8, dim=256)
    for i in range(30):
        kb.upsert_failure(
            failure_type="T",
            signature_text=_sig(f"unique prompt number {i} about topic {i * 7}"),
            app_id="a",
            impact_severity=Severity.low,
        )
    assert kb.count == 30
    m = kb.match(_sig("unique prompt number 17 about topic 119"))
    assert m and m[0].score > 0.9


def test_pattern_upsert_merges(tmp_path):
    """Membership is set-union; order is first-seen (insertion), NOT sorted —
    the delta-append pattern store never re-sorts the full id list on the
    streaming path."""
    kb = _mk(tmp_path)
    p1, created = kb.upsert_pattern(name="N", failure_ids=["F-2", "F-1"], affected_apps=["b"])
    assert created and p1.pattern_id == "FP-0001"
    assert p1.failure_ids == ["F-2", "F-1"]
    p2, created2 = kb.upsert_pattern(name="N", failure_ids=["F-3", "F-1"], affected_apps=["a"], description="d")
    assert not created2
    assert p2.failure_ids == ["F-2", "F-1", "F-3"]
    assert p2.affected_apps == ["b", "a"]
    assert p2.description == "d"
    # No-op upsert (nothing new): no growth, not created.
    p3, created3 = kb.upsert_pattern(name="N", failure_ids=["F-1"], affected_apps=["a"], description="d")
    assert not created3 and p3.failure_ids == p2.failure_ids


def test_pattern_delta_log_replays(tmp_path):
    """The patterns log is delta-append; a fresh GFKB over the same dir must
    union the deltas back into the full membership."""
    kb = _mk(tmp_path)
    kb.upsert_pattern(name="N", failure_ids=["F-1"], affected_apps=["a"])
    kb.upsert_pattern(name="N", failure_ids=["F-2"], affected_apps=["b"], description="d")
    kb.upsert_pattern(name="M", failure_ids=["F-9"], affected_apps=["c"])
    kb.close()

    from kakveda_tpu.index.gfkb import GFKB

    kb2 = GFKB(data_dir=kb.data_dir, capacity=64, dim=256)
    by_name = {p.name: p for p in kb2.list_patterns()}
    assert set(by_name) == {"N", "M"}
    assert by_name["N"].failure_ids == ["F-1", "F-2"]
    assert by_name["N"].affected_apps == ["a", "b"]
    assert by_name["N"].description == "d"
    assert by_name["N"].pattern_id == "FP-0001"
    assert by_name["M"].failure_ids == ["F-9"]


def test_concurrent_upserts_and_match(tmp_path):
    """SURVEY §5.2: the reference has unsynchronized shared state; here
    concurrent writers + readers must stay consistent (lock-protected
    metadata, atomic slot assignment, no lost records)."""
    import threading

    from kakveda_tpu.index.gfkb import GFKB

    gfkb = GFKB(data_dir=tmp_path, capacity=512, dim=512)
    n_threads, per_thread = 8, 25
    errors = []

    def writer(tid):
        try:
            for i in range(per_thread):
                gfkb.upsert_failure(
                    failure_type="HALLUCINATION_CITATION",
                    signature_text=f"sig thread {tid} item {i} citations required",
                    app_id=f"app-{tid}",
                    impact_severity=Severity.medium,
                )
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        try:
            for _ in range(40):
                gfkb.match("sig thread citations required")
                gfkb.list_failures()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(n_threads)]
    threads += [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert not errors, errors
    recs = gfkb.list_failures()
    assert len(recs) == n_threads * per_thread
    # slots and ids are unique despite interleaved writers
    assert len({r.failure_id for r in recs}) == len(recs)
    ids, apps = gfkb.type_aggregate("HALLUCINATION_CITATION")
    assert len(ids) == len(recs)
    assert len(apps) == n_threads


def _seed(gfkb, n, tag="s"):
    gfkb.upsert_failures_batch([
        {
            "failure_type": "HALLUCINATION_CITATION",
            "signature_text": f"intent:citations_required | {tag} doc {i} references",
            "app_id": f"app-{i % 5}",
            "impact_severity": "medium",
        }
        for i in range(n)
    ])


def test_snapshot_restore_and_tail_replay(tmp_path, monkeypatch):
    gfkb = GFKB(data_dir=tmp_path, capacity=512, dim=1024)
    _seed(gfkb, 100, "base")
    gfkb.snapshot()
    _seed(gfkb, 20, "tail")  # written after the snapshot
    n_total = gfkb.count
    pre_match = gfkb.match("intent:citations_required | base doc 7 references")
    gfkb.close()

    # restore: snapshot rows must NOT be re-embedded (only the 20-row tail)
    import kakveda_tpu.ops.featurizer as feat_mod

    calls = []
    orig = feat_mod.HashedNGramFeaturizer.encode_batch_sparse

    def counting(self, texts):
        calls.append(len(texts))
        return orig(self, texts)

    # Replay encodes through the sparse path; counting it proves snapshot
    # rows skip re-embedding (they re-sparsify from stored dense vectors).
    monkeypatch.setattr(feat_mod.HashedNGramFeaturizer, "encode_batch_sparse", counting)
    g2 = GFKB(data_dir=tmp_path, capacity=512, dim=1024)
    assert g2.count == n_total
    assert sum(calls) == 20, calls  # tail only
    ids, apps = g2.type_aggregate("HALLUCINATION_CITATION")
    assert len(ids) == n_total and len(apps) == 5
    post_match = g2.match("intent:citations_required | base doc 7 references")
    assert post_match[0].failure_id == pre_match[0].failure_id
    g2.close()


def test_snapshot_invalidated_by_log_rewrite(tmp_path):
    gfkb = GFKB(data_dir=tmp_path, capacity=256, dim=1024)
    _seed(gfkb, 30)
    gfkb.snapshot()
    gfkb.close()
    # rewrite the log in place (what purge-demo does): keep only 10 rows
    lines = (tmp_path / "failures.jsonl").read_text().splitlines()
    (tmp_path / "failures.jsonl").write_text("\n".join(lines[:10]) + "\n")

    g2 = GFKB(data_dir=tmp_path, capacity=256, dim=1024)
    assert g2.count == 10  # stale snapshot rejected, full replay of new log
    g2.close()


def test_snapshot_tail_update_of_snapshotted_record(tmp_path):
    gfkb = GFKB(data_dir=tmp_path, capacity=256, dim=1024)
    gfkb.upsert_failure(
        failure_type="HALLUCINATION_CITATION",
        signature_text="sig one citations",
        app_id="app-A",
        impact_severity=Severity.medium,
    )
    gfkb.snapshot()
    # version bump of the SAME record lands in the tail
    gfkb.upsert_failure(
        failure_type="HALLUCINATION_CITATION",
        signature_text="sig one citations",
        app_id="app-B",
        impact_severity=Severity.medium,
    )
    gfkb.close()

    g2 = GFKB(data_dir=tmp_path, capacity=256, dim=1024)
    assert g2.count == 1
    rec = g2.list_failures()[0]
    assert rec.version == 2 and sorted(rec.affected_apps) == ["app-A", "app-B"]
    g2.close()


def test_reopen_after_log_outgrows_capacity(tmp_path):
    """Reopening a dir whose log has MORE records than the configured
    capacity must replay through init-time growth (regression: _build_index
    read type ids before replay had minted any → KeyError on restart)."""
    kb = GFKB(data_dir=tmp_path / "d", capacity=8, dim=256)
    for i in range(30):
        kb.upsert_failure(
            failure_type=f"T{i % 3}",
            signature_text=_sig(f"grown record {i} topic {i * 11}"),
            app_id=f"a{i % 2}",
            impact_severity=Severity.low,
        )
    kb.close()

    kb2 = GFKB(data_dir=tmp_path / "d", capacity=8, dim=256)
    assert kb2.count == 30
    m = kb2.match(_sig("grown record 17 topic 187"), failure_type="T2", type_filter="pre")
    assert m and m[0].score > 0.9 and m[0].failure_type == "T2"
    kb2.close()


def test_snapshot_v2_sparse_files_and_corruption_fallback(tmp_path):
    """v2 snapshots persist sparse (idx, val) pairs — no dense matrix on
    disk — and ANY corruption of them (truncated array, wrong dtype,
    missing file) falls back to full replay with identical results."""
    import numpy as np

    gfkb = GFKB(data_dir=tmp_path, capacity=256, dim=1024)
    _seed(gfkb, 40)
    sd = gfkb.snapshot()
    pre = gfkb.match("intent:citations_required | doc 7 references")
    gfkb.close()

    assert (sd / "sparse_idx.npy").exists() and (sd / "sparse_val.npy").exists()
    assert not (sd / "vectors.npy").exists()
    idx = np.load(sd / "sparse_idx.npy")
    assert idx.dtype == np.int32 and idx.shape[0] == 40

    def reopen():
        g = GFKB(data_dir=tmp_path, capacity=256, dim=1024)
        try:
            assert g.count == 40
            assert g.match("intent:citations_required | doc 7 references")[0].failure_id \
                == pre[0].failure_id
        finally:
            g.close()

    # healthy restore
    reopen()
    # truncated rows -> shape mismatch -> full replay
    np.save(sd / "sparse_idx.npy", idx[:10])
    reopen()
    np.save(sd / "sparse_idx.npy", idx)
    # wrong dtype -> full replay
    np.save(sd / "sparse_val.npy", np.zeros((40, idx.shape[1]), np.float64))
    reopen()
    # missing file -> full replay
    (sd / "sparse_val.npy").unlink()
    reopen()
